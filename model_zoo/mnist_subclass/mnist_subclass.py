"""MNIST CNN, subclass style — rebuild of the reference zoo module
model_zoo/mnist_subclass/mnist_subclass.py:18-100 (explicit-submodule Keras
subclass Conv32-Conv64-BN-MaxPool-Dropout-Dense10) as a flax.linen module
with `setup()` (the flax analogue of the Keras subclass style). Same spec
surface: custom_model/loss/optimizer/dataset_fn/eval_metrics_fn."""

import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.data.example_codec import decode_example


class CustomModel(nn.Module):
    channel_last: bool = True

    def setup(self):
        self._conv1 = nn.Conv(32, (3, 3), padding="VALID")
        self._conv2 = nn.Conv(64, (3, 3), padding="VALID")
        self._batch_norm = nn.BatchNorm(momentum=0.99)
        self._dropout = nn.Dropout(0.25)
        self._dense = nn.Dense(10)

    def __call__(self, features, training=False):
        x = features["image"]
        x = x.reshape(x.shape[0], 28, 28, 1)
        x = nn.relu(self._conv1(x))
        x = nn.relu(self._conv2(x))
        x = self._batch_norm(x, use_running_average=not training)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = self._dropout(x, deterministic=not training)
        x = x.reshape(x.shape[0], -1)
        return self._dense(x)


def custom_model():
    return CustomModel()


def loss(labels, predictions):
    labels = labels.reshape(-1)
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(predictions, labels)
    )


def optimizer(lr=0.01):
    return optax.sgd(lr)


def dataset_fn(dataset, mode, _):
    def _parse(record):
        ex = decode_example(record)
        features = {"image": ex["image"].astype(np.float32)}
        if mode == Mode.PREDICTION:
            return features
        return features, ex["label"].astype(np.int32)[0]

    dataset = dataset.map(_parse)
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=1024, seed=0)
    return dataset


def eval_metrics_fn():
    return {
        "accuracy": lambda labels, predictions: (
            np.argmax(predictions, axis=1) == np.asarray(labels).reshape(-1)
        ).astype(np.float32)
    }


def feature_shapes():
    return {"image": (28, 28)}
