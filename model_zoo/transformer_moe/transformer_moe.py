"""Mixture-of-experts causal transformer LM: every block's MLP is a
top-k-routed expert bank (router_top_k: 1 = Switch, 2 = GShard)
sharded over the ``ep`` mesh axis (parallel/moe.py) — the family that
makes ``ep`` a true expert axis.

Attention reuses transformer_lm's CausalSelfAttention (flash/ring/TP
annotations in one place). Training-mode outputs are a dict
{"logits", "aux_loss"}: loss() adds the Switch load-balancing aux term;
inference returns bare logits (eval metrics see one array).

The family speaks the KV-cache decode convention (decode/prefill
modes), so every generation strategy — greedy/sampled, beam,
speculative, int8 — works on MoE models. Decode and prefill route
DROP-FREE through the dense per-expert formulation (moe_mlp_infer):
no capacity queues, so a decoded token's routing never depends on
which other tokens share its pass — cached decode is deterministic
and chunk-width-invariant. Training AND eval keep the capacity-
bounded dispatch (fixed per-expert compute); uncached full-forward
generation therefore matches cached decode exactly whenever the
configured capacity admits every routing choice
(capacity_factor >= num_experts / router_top_k guarantees it), and
the cached path is the canonical generation semantics otherwise.
"""

import numpy as np

import jax.numpy as jnp
import optax
from flax import linen as nn

from elasticdl_tpu.common.constants import MeshAxis, Mode
from elasticdl_tpu.data.example_codec import decode_example
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.parallel.moe import (
    moe_mlp_apply,
    moe_mlp_apply_a2a,
    moe_mlp_infer,
    moe_mlp_infer_gather,
)
from model_zoo.transformer_lm.transformer_lm import (
    CausalSelfAttention,
    resolve_dtype,
    setup_decode_positions,
)

AUX_LOSS_WEIGHT = 0.01


def _expert_init(name, shape):
    if name.startswith("b_"):
        return nn.initializers.zeros
    base = nn.initializers.lecun_normal()

    def init(key, full_shape, dtype=jnp.float32):
        import jax

        keys = jax.random.split(key, full_shape[0])
        return jnp.stack([base(k, full_shape[1:], dtype) for k in keys])

    return init


class MoEBlock(nn.Module):
    num_heads: int
    head_dim: int
    num_experts: int = 4
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    router_top_k: int = 1  # 1 = Switch; 2 = GShard top-2
    dtype: object = None
    attn_impl: str = "auto"
    tp_shard: bool = True
    cache_len: int = 0  # KV-cache capacity for decode/prefill
    kv_cache_dtype: str = ""  # "" | "int8" (see CausalSelfAttention)
    # "auto" = sharding-annotated einsums (GSPMD infers collectives);
    # "a2a" = explicit shard_map all-to-all dispatch over ep
    # (parallel/moe.py moe_mlp_apply_a2a; falls back to einsum off-mesh
    # or at ep=1, where there is nothing to exchange)
    moe_impl: str = "auto"
    # decode/prefill formulation: "dense" = every expert over all T
    # (E x FLOPs, the determinism baseline); "gather" = sorted
    # ragged_dot dropless dispatch (k/E of the FLOPs — the prefill
    # path once expert counts grow)
    moe_infer_impl: str = "dense"

    @nn.compact
    def __call__(self, x, training=False, decode=False, decode_pos=None,
                 prefill=False):
        # validate both dispatch knobs up front so a typo fails at
        # trace time on EVERY path, not only when its branch first runs
        if self.moe_infer_impl not in ("dense", "gather"):
            raise ValueError(
                "Unknown moe_infer_impl %r (valid: dense, gather)"
                % (self.moe_infer_impl,)
            )
        if self.moe_impl not in ("auto", "a2a"):
            raise ValueError(
                "Unknown moe_impl %r (valid: auto, a2a)"
                % (self.moe_impl,)
            )
        b, l, e = x.shape
        y = nn.LayerNorm(dtype=self.dtype)(x)
        x = x + CausalSelfAttention(
            self.num_heads, self.head_dim, dtype=self.dtype,
            attn_impl=self.attn_impl, tp_shard=self.tp_shard,
            cache_len=self.cache_len,
            kv_cache_dtype=self.kv_cache_dtype, name="attn",
        )(y, training, decode=decode, decode_pos=decode_pos,
          prefill=prefill)
        y = nn.LayerNorm(dtype=self.dtype)(x)

        h = self.mlp_ratio * e
        n_exp = self.num_experts
        params = {
            "router": self.param(
                "router", nn.initializers.lecun_normal(), (e, n_exp)
            ),
            "w_up": self.param(
                "w_up",
                nn.with_partitioning(
                    _expert_init("w_up", (e, h)),
                    (MeshAxis.EP, None, None),
                ),
                (n_exp, e, h),
            ),
            "b_up": self.param(
                "b_up",
                nn.with_partitioning(
                    _expert_init("b_up", (h,)), (MeshAxis.EP, None)
                ),
                (n_exp, h),
            ),
            "w_down": self.param(
                "w_down",
                nn.with_partitioning(
                    _expert_init("w_down", (h, e)),
                    (MeshAxis.EP, None, None),
                ),
                (n_exp, h, e),
            ),
            "b_down": self.param(
                "b_down",
                nn.with_partitioning(
                    _expert_init("b_down", (e,)), (MeshAxis.EP, None)
                ),
                (n_exp, e),
            ),
        }
        flat = y.reshape(b * l, e)
        if decode or prefill:
            # Generation routes DROP-FREE (moe_infer_impl: "dense" =
            # every expert over all T via parallel/moe.py
            # moe_mlp_infer; "gather" = sorted ragged_dot dispatch,
            # moe_mlp_infer_gather): no capacity queues, so a decoded
            # token's routing never depends on which other tokens
            # share its pass — cached decode is deterministic and
            # chunk-width-invariant. Training and eval keep the
            # capacity-bounded dispatch (fixed compute; drops ride
            # the residual).
            infer = (moe_mlp_infer_gather
                     if self.moe_infer_impl == "gather"
                     else moe_mlp_infer)
            out = infer(
                params, flat, router_top_k=self.router_top_k
            )
            return x + out.reshape(b, l, e), 0.0
        mesh = mesh_lib.current_mesh()
        if (self.moe_impl == "a2a" and mesh is not None
                and mesh.shape.get(MeshAxis.EP, 1) > 1):
            out, aux_loss, _ = moe_mlp_apply_a2a(
                params, flat, mesh,
                capacity_factor=self.capacity_factor,
                router_top_k=self.router_top_k,
            )
        else:
            out, aux_loss, _ = moe_mlp_apply(
                params, flat, capacity_factor=self.capacity_factor,
                router_top_k=self.router_top_k,
            )
        return x + out.reshape(b, l, e), aux_loss


class TransformerMoE(nn.Module):
    vocab_size: int = 256
    seq_len: int = 128
    embed_dim: int = 128
    num_heads: int = 4
    num_layers: int = 2
    num_experts: int = 4
    capacity_factor: float = 1.25
    router_top_k: int = 1  # 1 = Switch; 2 = GShard top-2
    dtype: object = None
    attn_impl: str = "auto"
    tp_shard: bool = True
    kv_cache_dtype: str = ""  # "" | "int8" (see CausalSelfAttention)
    moe_impl: str = "auto"  # "auto" einsum/GSPMD | "a2a" explicit
    moe_infer_impl: str = "dense"  # "dense" | "gather" (ragged_dot)

    @nn.compact
    def __call__(self, features, training=False, decode=False,
                 prefill=False, prompt_len=None):
        tokens = features["tokens"]
        if decode and prefill:
            raise ValueError("decode and prefill are mutually exclusive")
        x = nn.Embed(
            self.vocab_size, self.embed_dim, dtype=self.dtype, name="wte"
        )(tokens)
        # shared decode-counter convention (transformer_lm
        # setup_decode_positions — the one place the generation API's
        # prefill/decode contract is implemented)
        decode_pos, wpe_idx = setup_decode_positions(
            self, tokens, decode, prefill, prompt_len
        )
        x = x + nn.Embed(
            self.seq_len, self.embed_dim, dtype=self.dtype, name="wpe"
        )(wpe_idx)
        head_dim = self.embed_dim // self.num_heads
        aux_total = 0.0
        for i in range(self.num_layers):
            x, aux = MoEBlock(
                self.num_heads, head_dim, num_experts=self.num_experts,
                capacity_factor=self.capacity_factor,
                router_top_k=self.router_top_k, dtype=self.dtype,
                attn_impl=self.attn_impl, tp_shard=self.tp_shard,
                cache_len=self.seq_len,
                kv_cache_dtype=self.kv_cache_dtype,
                moe_impl=self.moe_impl,
                moe_infer_impl=self.moe_infer_impl,
                name="block_%d" % i,
            )(x, training, decode=decode, decode_pos=decode_pos,
              prefill=prefill)
            aux_total = aux_total + aux
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        logits = nn.Dense(
            self.vocab_size, use_bias=False, dtype=self.dtype, name="head"
        )(x).astype(jnp.float32)
        if not training:
            return logits
        return {
            "logits": logits,
            "aux_loss": jnp.asarray(aux_total, jnp.float32),
        }


def custom_model(**kwargs):
    return TransformerMoE(**resolve_dtype(kwargs, "transformer_moe"))


def loss(labels, predictions, sample_weights=None):
    logits = predictions["logits"]
    ce = optax.softmax_cross_entropy_with_integer_labels(
        logits, labels
    ).mean(axis=-1)
    if sample_weights is None:
        task_loss = jnp.mean(ce)
    else:
        task_loss = jnp.sum(ce * sample_weights) / jnp.maximum(
            jnp.sum(sample_weights), 1.0
        )
    return task_loss + AUX_LOSS_WEIGHT * predictions["aux_loss"]


def optimizer(lr=3e-4):
    return optax.adamw(lr, weight_decay=0.01)


def dataset_fn(dataset, mode, metadata):
    def _parse(record):
        ex = decode_example(record)
        tokens = ex["tokens"].astype(np.int32)
        features = {"tokens": tokens[:-1]}
        if mode == Mode.PREDICTION:
            return features
        return features, tokens[1:]

    dataset = dataset.map(_parse)
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=1024, seed=0)
    return dataset


def eval_metrics_fn():
    return {
        "token_accuracy": lambda labels, predictions: (
            np.argmax(predictions, axis=-1)
            == np.asarray(labels)
        ).astype(np.float32).reshape(len(labels), -1).mean(axis=1)
    }


def feature_shapes(seq_len=128):
    return {"tokens": (seq_len,)}
