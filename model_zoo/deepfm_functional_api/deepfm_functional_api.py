"""DeepFM over frappe-style id lists — rebuild of the reference zoo module
model_zoo/deepfm_functional_api/deepfm_functional_api.py:40-186:

* second-order FM term 0.5 * (sum^2 - sum-of-squares) over masked id
  embeddings (mask_zero semantics: id 0 is padding),
* first-order per-id bias embedding,
* deep tower Dense(fc_unit) -> Dense(1) over flattened embeddings,
* dict outputs {"logits", "probs"}, sigmoid-CE loss, nested eval metrics
  ({"logits": accuracy, "probs": AUC} — reference :161-171),
* LearningRateScheduler + MaxStepsStopping callbacks (reference :143-153).
"""

import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from elasticdl_tpu.api.callbacks import (
    LearningRateScheduler,
    MaxStepsStopping,
)
from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.data.example_codec import decode_example
from elasticdl_tpu.training.metrics import AUC

INPUT_DIM = 5383  # frappe vocabulary (reference dataset_fn)


class DeepFMModel(nn.Module):
    input_dim: int = INPUT_DIM
    embedding_dim: int = 64
    input_length: int = 10
    fc_unit: int = 64

    @nn.compact
    def __call__(self, features, training=False):
        ids = features["feature"].astype(jnp.int32)  # [B, L]
        mask = (ids != 0).astype(jnp.float32)[..., None]  # mask_zero

        emb = nn.Embed(self.input_dim, self.embedding_dim,
                       name="embedding")(ids)
        emb = emb * mask  # ApplyMask

        emb_sum = jnp.sum(emb, axis=1)  # [B, D]
        second_order = 0.5 * jnp.sum(
            jnp.square(emb_sum) - jnp.sum(jnp.square(emb), axis=1), axis=1
        )

        id_bias = nn.Embed(self.input_dim, 1, name="id_bias")(ids) * mask
        first_order = jnp.sum(id_bias, axis=(1, 2))
        fm_output = first_order + second_order

        nn_input = emb.reshape(emb.shape[0], -1)
        deep = nn.Dense(1)(nn.Dense(self.fc_unit)(nn_input)).reshape(-1)

        logits = fm_output + deep
        probs = jnp.reshape(nn.sigmoid(logits), (-1, 1))
        return {"logits": logits, "probs": probs}


def custom_model(input_dim=INPUT_DIM, embedding_dim=64, input_length=10,
                 fc_unit=64):
    return DeepFMModel(
        input_dim=input_dim,
        embedding_dim=embedding_dim,
        input_length=input_length,
        fc_unit=fc_unit,
    )


def loss(labels, predictions):
    logits = predictions["logits"].reshape(-1)
    labels = labels.reshape(-1).astype(jnp.float32)
    return jnp.mean(optax.sigmoid_binary_cross_entropy(logits, labels))


def optimizer(lr=0.1):
    return optax.sgd(lr)


def callbacks():
    # traced schedule (compiled into the train step): the reference's
    # python-if absolute-LR schedule (deepfm_functional_api.py:143-147),
    # expressed as multipliers of the base lr=0.1
    def _schedule(model_version):
        return jnp.where(
            model_version < 2000, 1.0,
            jnp.where(model_version < 4000, 0.5, 0.1),
        )

    return [LearningRateScheduler(_schedule), MaxStepsStopping(max_steps=200)]


def dataset_fn(dataset, mode, _):
    def _parse(record):
        ex = decode_example(record)
        features = {"feature": ex["feature"].astype(np.int32)}
        if mode == Mode.PREDICTION:
            return features
        return features, ex["label"].astype(np.int32)[0]

    dataset = dataset.map(_parse)
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=1024, seed=0)
    return dataset


def eval_metrics_fn():
    return {
        "logits": {
            "accuracy": lambda labels, predictions: (
                (np.asarray(predictions).reshape(-1) > 0.0).astype(np.int32)
                == np.asarray(labels).reshape(-1)
            ).astype(np.float32)
        },
        "probs": {"auc": AUC()},
    }


def feature_shapes():
    return {"feature": (10,)}
