"""DeepFM whose embedding tables live in HOST DRAM via the host-spill
bridge — the model a user picks when the tables exceed HBM.

Same math as model_zoo/deepfm_edl_embedding (itself the rebuild of the
reference model_zoo/deepfm_edl_embedding/deepfm_edl_embedding.py:29-120),
but the two tables are declared through the `host_embeddings()` zoo
convention: their rows are stored in the native C++ host store
(native/host_embedding.cc), pulled per batch by HostEmbeddingManager, and
updated by the engine's native row optimizer — the role PS pod memory +
OptimizerWrapper played in the reference (ps/embedding_table.py:23-136,
ps/optimizer_wrapper.py:70-351)."""

import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.data.example_codec import decode_example
from elasticdl_tpu.embedding.host_bridge import HostEmbedding
from elasticdl_tpu.training.metrics import AUC


class DeepFMHostModel(nn.Module):
    input_length: int = 10
    fc_unit: int = 64

    @nn.compact
    def __call__(self, features, training=False):
        ids = features["feature"].astype(jnp.int32)  # [B, L]
        mask = (ids != 0).astype(jnp.float32)[..., None]  # mask_zero

        emb = HostEmbedding(table="edl_embedding")(features)
        emb = emb * mask

        emb_sum = jnp.sum(emb, axis=1)
        second_order = 0.5 * jnp.sum(
            jnp.square(emb_sum) - jnp.sum(jnp.square(emb), axis=1), axis=1
        )

        id_bias = HostEmbedding(table="edl_id_bias")(features) * mask
        first_order = jnp.sum(id_bias, axis=(1, 2))
        fm_output = first_order + second_order

        nn_input = emb.reshape(emb.shape[0], -1)
        deep = nn.Dense(1)(nn.Dense(self.fc_unit)(nn_input)).reshape(-1)

        logits = fm_output + deep
        probs = jnp.reshape(nn.sigmoid(logits), (-1, 1))
        return {"logits": logits, "probs": probs}


def custom_model(input_length=10, fc_unit=64):
    return DeepFMHostModel(input_length=input_length, fc_unit=fc_unit)


def host_embeddings(embedding_dim=64):
    """Host-DRAM table declarations (embedding/host_bridge
    build_manager_from_spec). The engines' SGD matches optimizer()
    below so dense params and embedding rows step identically."""
    return {
        "edl_embedding": dict(
            ids_feature="feature", dim=embedding_dim,
            optimizer="sgd", lr=0.1,
        ),
        "edl_id_bias": dict(
            ids_feature="feature", dim=1, optimizer="sgd", lr=0.1,
        ),
    }


def loss(labels, predictions):
    logits = predictions["logits"].reshape(-1)
    labels = labels.reshape(-1).astype(jnp.float32)
    return jnp.mean(optax.sigmoid_binary_cross_entropy(logits, labels))


def optimizer(lr=0.1):
    return optax.sgd(lr)


def dataset_fn(dataset, mode, _):
    def _parse(record):
        ex = decode_example(record)
        features = {"feature": ex["feature"].astype(np.int32)}
        if mode == Mode.PREDICTION:
            return features
        return features, ex["label"].astype(np.int32)[0]

    dataset = dataset.map(_parse)
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=1024, seed=0)
    return dataset


def eval_metrics_fn():
    return {
        "logits": {
            "accuracy": lambda labels, predictions: (
                (np.asarray(predictions).reshape(-1) > 0.0).astype(np.int32)
                == np.asarray(labels).reshape(-1)
            ).astype(np.float32)
        },
        "probs": {"auc": AUC()},
    }


def feature_shapes():
    return {"feature": (10,)}
