"""DLRM (Deep Learning Recommendation Model, Naumov et al. 2019) — the
BASELINE.json configs[4] "DLRM-1B-embedding" stress family: 26
categorical tables whose combined parameter count reaches the billions,
exercising the sparse PS-replacement tiers at scale (SURVEY.md §7 build
order #8). The reference has no DLRM; this is the net-new config its
north star names.

Architecture (the canonical one):
    dense [b, 13] -> bottom MLP -> [b, d]
    26 categorical ids -> per-table Embedding lookups -> [b, 26, d]
    pairwise dot-product feature interactions over the 27 vectors
    concat(bottom, interactions) -> top MLP -> logit

TPU-first mapping: every table is the framework Embedding layer, so
tables past the 2 MB threshold shard over the (ep, fsdp) mesh axes with
O(touched rows) sparse-row updates (embedding/sparse_update.py) — the
billion-parameter capacity lives in sharded HBM where the reference's
PS pods held it in pod RAM. The interaction is one batched einsum
(MXU-friendly) with a static upper-triangle gather.

Size knobs: `table_size` rows per table x `num_tables` tables x
`embedding_dim` -> 26 x 1.5e6 x 32 ≈ 1.2B embedding parameters at the
stress config (bench.py EDL_BENCH_MODEL=dlrm uses a single-chip-sized
default; scale table_size for the full stress).
"""

import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.data.example_codec import decode_example
from elasticdl_tpu.embedding.layer import Embedding
from elasticdl_tpu.training.metrics import AUC

NUM_DENSE = 13
NUM_SPARSE = 26


def _mlp(x, sizes, name):
    for i, width in enumerate(sizes):
        x = nn.Dense(width, name="%s_%d" % (name, i))(x)
        if i < len(sizes) - 1:
            x = nn.relu(x)
    return x


class DLRM(nn.Module):
    table_size: int = 100_000  # rows per categorical table
    num_tables: int = NUM_SPARSE
    embedding_dim: int = 32
    bottom_mlp: tuple = (64, 32)
    top_mlp: tuple = (64, 1)

    @nn.compact
    def __call__(self, features, training=False):
        dense = features["dense"].astype(jnp.float32)  # [b, 13]
        # fold hashed ids into this model's table range (ids arrive
        # hashed modulo HASH_BUCKETS; a smaller table double-hashes)
        ids = features["sparse"].astype(jnp.int32) % self.table_size
        d = self.embedding_dim

        bottom = _mlp(dense, self.bottom_mlp + (d,), "bottom")  # [b, d]
        embs = [
            Embedding(
                input_dim=self.table_size, output_dim=d,
                name="table_%d" % t,
            )(ids[:, t])
            for t in range(self.num_tables)
        ]
        z = jnp.stack([bottom] + embs, axis=1)  # [b, T+1, d]

        # pairwise dot-product interactions: one batched matmul, then
        # the static upper triangle (i < j)
        inter = jnp.einsum("bmd,bnd->bmn", z, z)
        iu, ju = np.triu_indices(z.shape[1], k=1)
        pairs = inter[:, iu, ju]  # [b, (T+1)T/2]

        top_in = jnp.concatenate([bottom, pairs], axis=1)
        logits = _mlp(
            top_in, self.top_mlp, "top"
        ).reshape(-1)
        return {
            "logits": logits,
            "probs": nn.sigmoid(logits).reshape(-1, 1),
        }


def custom_model(table_size=100_000, num_tables=NUM_SPARSE,
                 embedding_dim=32, bottom_mlp=(64, 32),
                 top_mlp=(64, 1)):
    return DLRM(
        table_size=table_size,
        num_tables=num_tables,
        embedding_dim=embedding_dim,
        bottom_mlp=tuple(bottom_mlp),
        top_mlp=tuple(top_mlp),
    )


def loss(labels, predictions, sample_weights=None):
    logits = predictions["logits"].reshape(-1)
    labels = labels.reshape(-1).astype(jnp.float32)
    ce = optax.sigmoid_binary_cross_entropy(logits, labels)
    if sample_weights is None:
        return jnp.mean(ce)
    w = sample_weights.reshape(-1)
    return jnp.sum(ce * w) / jnp.maximum(jnp.sum(w), 1e-9)


def optimizer(lr=0.01):
    return optax.sgd(lr)


# Hash modulus for categorical strings -> ids (DLRM's standard
# preprocessing). Must be <= the model's table_size; the default model
# uses exactly this value, and larger tables stay valid (ids < modulus).
HASH_BUCKETS = 100_000


def dataset_fn(dataset, mode, _):
    """Criteo/DAC records (data/recordio_gen.gen_criteo_like: numeric
    I1..I13, categorical strings C1..C26, binary label): dense features
    log-normalized, categorical strings hashed into HASH_BUCKETS ids —
    the canonical DLRM preprocessing for Criteo."""
    from elasticdl_tpu.common.hash_utils import string_to_id

    def _parse(record):
        ex = decode_example(record)
        dense = np.array(
            [float(ex["I%d" % i]) for i in range(1, NUM_DENSE + 1)],
            np.float32,
        )
        dense = np.log1p(np.maximum(dense, 0.0))
        sparse = np.array(
            [
                string_to_id(
                    np.asarray(ex["C%d" % i]).item().decode(),
                    HASH_BUCKETS,
                )
                for i in range(1, NUM_SPARSE + 1)
            ],
            np.int32,
        )
        features = {"dense": dense, "sparse": sparse}
        if mode == Mode.PREDICTION:
            return features
        return features, np.int32(ex["label"])

    dataset = dataset.map(_parse)
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=1024, seed=0)
    return dataset


def eval_metrics_fn():
    return {
        "logits": {
            "accuracy": lambda labels, predictions: (
                (np.asarray(predictions).reshape(-1) > 0.0).astype(
                    np.int32)
                == np.asarray(labels).reshape(-1)
            ).astype(np.float32)
        },
        "probs": {"auc": AUC()},
    }


def feature_shapes():
    return {"dense": (NUM_DENSE,), "sparse": (NUM_SPARSE,)}
