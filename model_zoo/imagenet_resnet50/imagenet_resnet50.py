"""ImageNet ResNet-50 zoo entry — rebuild of the reference
model_zoo/imagenet_resnet50/imagenet_resnet50.py (ResNet-50 over 224x224x3
images, 1000 classes). Shares the flax ResNet50 stack with resnet50_subclass;
bfloat16 activations for MXU throughput on real ImageNet shapes."""

import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.data.example_codec import decode_example
from model_zoo.resnet50_subclass.resnet50_model import (
    L2_WEIGHT_DECAY,
    ResNet50,
)


class ImagenetModel(nn.Module):
    num_classes: int = 1000

    @nn.compact
    def __call__(self, features, training=False):
        x = features["image"].astype(jnp.bfloat16)
        logits = ResNet50(num_classes=self.num_classes, name="resnet50")(
            x, training
        )
        return logits.astype(jnp.float32)


def custom_model():
    return ImagenetModel()


def loss(labels, predictions):
    labels = labels.reshape(-1)
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(predictions, labels)
    )


def optimizer(lr=0.1):
    return optax.chain(
        optax.add_decayed_weights(L2_WEIGHT_DECAY),
        optax.sgd(lr, momentum=0.9),
    )


def dataset_fn(dataset, mode, _):
    def _parse(record):
        ex = decode_example(record)
        features = {"image": ex["image"].astype(np.float32) / 255.0}
        if mode == Mode.PREDICTION:
            return features
        return features, ex["label"].astype(np.int32)[0]

    dataset = dataset.map(_parse)
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=1024, seed=0)
    return dataset


def eval_metrics_fn():
    return {
        "accuracy": lambda labels, predictions: (
            np.argmax(predictions, axis=1) == np.asarray(labels).reshape(-1)
        ).astype(np.float32)
    }


def feature_shapes():
    return {"image": (224, 224, 3)}
