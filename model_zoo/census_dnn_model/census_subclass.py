"""Census DNN, subclass style — rebuild of the reference
model_zoo/census_dnn_model/census_subclass.py (same MLP with explicit
submodules via flax setup())."""

from flax import linen as nn

from model_zoo.census_dnn_model.census_functional_api import (  # noqa: F401
    dataset_fn,
    eval_metrics_fn,
    feature_shapes,
    loss,
    optimizer,
)
from model_zoo.census_dnn_model.census_feature_columns import (
    CensusFeatureLayer,
)


class CensusSubclassModel(nn.Module):
    def setup(self):
        self._features = CensusFeatureLayer()
        self._dense1 = nn.Dense(16)
        self._dense2 = nn.Dense(16)
        self._head = nn.Dense(1)

    def __call__(self, features, training=False):
        x = self._features(features)
        x = nn.relu(self._dense1(x))
        x = nn.relu(self._dense2(x))
        return nn.sigmoid(self._head(x))


def custom_model():
    return CensusSubclassModel()
