"""Census DNN, Sequential style — rebuild of the reference
model_zoo/census_dnn_model/census_sequential.py (same MLP as the functional
variant, built with nn.Sequential over the feature layer output)."""

from flax import linen as nn

from model_zoo.census_dnn_model.census_functional_api import (  # noqa: F401
    dataset_fn,
    eval_metrics_fn,
    feature_shapes,
    loss,
    optimizer,
)
from model_zoo.census_dnn_model.census_feature_columns import (
    CensusFeatureLayer,
)


class CensusSequentialModel(nn.Module):
    @nn.compact
    def __call__(self, features, training=False):
        x = CensusFeatureLayer()(features)
        mlp = nn.Sequential(
            [nn.Dense(16), nn.relu, nn.Dense(16), nn.relu, nn.Dense(1),
             nn.sigmoid]
        )
        return mlp(x)


def custom_model():
    return CensusSequentialModel()
