"""Census feature transforms — rebuild of the reference
model_zoo/census_dnn_model/census_feature_columns.py (numeric columns pass
through; each categorical string column is hashed into 64 buckets and embedded
at dim 16 via the framework embedding_column equivalent).

TPU split: string hashing is a host-side transform (strings never enter XLA),
so it runs in ``dataset_fn`` via preprocessing.Hashing; the embedding + concat
half lives in the flax model (CensusFeatureLayer). Same bucket counts and
dimensions as the reference."""

import numpy as np
from flax import linen as nn

from elasticdl_tpu.preprocessing.layers import Hashing

CATEGORICAL_FEATURE_KEYS = [
    "workclass",
    "education",
    "marital-status",
    "occupation",
    "relationship",
    "race",
    "sex",
    "native-country",
]
NUMERIC_FEATURE_KEYS = [
    "age",
    "capital-gain",
    "capital-loss",
    "hours-per-week",
]
LABEL_KEY = "label"

HASH_BUCKET_SIZE = 64
EMBEDDING_DIM = 16


def transform_categoricals(example):
    """Host-side: string categorical features -> hashed int ids."""
    out = {}
    for key in CATEGORICAL_FEATURE_KEYS:
        out[key] = np.asarray(
            Hashing(HASH_BUCKET_SIZE)(example[key]), dtype=np.int32
        )
    return out


class CensusFeatureLayer(nn.Module):
    """In-model half of the feature columns: embeds each hashed categorical
    (64 buckets -> dim 16) and concatenates with the numeric features —
    the DenseFeatures equivalent."""

    @nn.compact
    def __call__(self, features):
        import jax.numpy as jnp

        parts = [
            jnp.reshape(
                features[key].astype(jnp.float32), (-1, 1)
            )
            for key in NUMERIC_FEATURE_KEYS
        ]
        for key in CATEGORICAL_FEATURE_KEYS:
            ids = features[key].astype(jnp.int32).reshape(-1)
            emb = nn.Embed(
                HASH_BUCKET_SIZE, EMBEDDING_DIM,
                name="emb_%s" % key.replace("-", "_"),
            )(ids)
            parts.append(emb)
        return jnp.concatenate(parts, axis=-1)
