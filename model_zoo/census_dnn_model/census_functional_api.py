"""Census-income DNN — rebuild of the reference
model_zoo/census_dnn_model/census_functional_api.py:23-61 (DenseFeatures over
numeric + hashed-embedded categoricals, Dense16-Dense16-Dense1-sigmoid, Adam,
binary crossentropy)."""

import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.data.example_codec import decode_example
from model_zoo.census_dnn_model.census_feature_columns import (
    CATEGORICAL_FEATURE_KEYS,
    LABEL_KEY,
    NUMERIC_FEATURE_KEYS,
    CensusFeatureLayer,
    transform_categoricals,
)


class CensusDnnModel(nn.Module):
    @nn.compact
    def __call__(self, features, training=False):
        x = CensusFeatureLayer()(features)
        x = nn.relu(nn.Dense(16)(x))
        x = nn.relu(nn.Dense(16)(x))
        return nn.sigmoid(nn.Dense(1)(x))


def custom_model():
    return CensusDnnModel()


def loss(labels, predictions):
    labels = labels.reshape(-1, 1).astype(jnp.float32)
    p = jnp.clip(predictions, 1e-7, 1 - 1e-7)
    return -jnp.mean(
        labels * jnp.log(p) + (1.0 - labels) * jnp.log(1.0 - p)
    )


def optimizer():
    return optax.adam(1e-3)


def dataset_fn(dataset, mode, _):
    def _parse(record):
        ex = decode_example(record)
        features = transform_categoricals(ex)
        for key in NUMERIC_FEATURE_KEYS:
            features[key] = np.asarray(ex[key], dtype=np.float32).reshape(())
        if mode == Mode.PREDICTION:
            return features
        return features, np.asarray(ex[LABEL_KEY], dtype=np.int32).reshape(())

    dataset = dataset.map(_parse)
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=1024, seed=0)
    return dataset


def eval_metrics_fn():
    return {
        "accuracy": lambda labels, predictions: (
            np.round(np.asarray(predictions).reshape(-1)).astype(np.int32)
            == np.asarray(labels).reshape(-1)
        ).astype(np.float32)
    }


def feature_shapes():
    shapes = {key: () for key in NUMERIC_FEATURE_KEYS}
    shapes.update({key: () for key in CATEGORICAL_FEATURE_KEYS})
    return shapes
