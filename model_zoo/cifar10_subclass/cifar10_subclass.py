"""CIFAR-10 CNN, subclass style — rebuild of the reference zoo module
model_zoo/cifar10_subclass/cifar10_subclass.py:18-200 (same stack as the
functional variant: conv-BN-relu pairs at 32/64/128 with maxpool+dropout,
Dense10), written with explicit flax `setup()` submodules."""

import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.data.example_codec import decode_example


class _ConvBNRelu(nn.Module):
    channels: int

    @nn.compact
    def __call__(self, x, training=False):
        x = nn.Conv(self.channels, (3, 3), padding="SAME")(x)
        x = nn.BatchNorm(
            use_running_average=not training, momentum=0.9, epsilon=1e-6
        )(x)
        return nn.relu(x)


class CustomModel(nn.Module):
    channel_last: bool = True

    def setup(self):
        self._block1a = _ConvBNRelu(32)
        self._block1b = _ConvBNRelu(32)
        self._drop1 = nn.Dropout(0.2)
        self._block2a = _ConvBNRelu(64)
        self._block2b = _ConvBNRelu(64)
        self._drop2 = nn.Dropout(0.3)
        self._block3a = _ConvBNRelu(128)
        self._block3b = _ConvBNRelu(128)
        self._drop3 = nn.Dropout(0.4)
        self._dense = nn.Dense(10)

    def __call__(self, features, training=False):
        x = features["image"]
        x = x.reshape(x.shape[0], 32, 32, 3)
        for a, b, drop in (
            (self._block1a, self._block1b, self._drop1),
            (self._block2a, self._block2b, self._drop2),
            (self._block3a, self._block3b, self._drop3),
        ):
            x = a(x, training)
            x = b(x, training)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
            x = drop(x, deterministic=not training)
        x = x.reshape(x.shape[0], -1)
        return self._dense(x)


def custom_model():
    return CustomModel()


def loss(labels, predictions):
    labels = labels.reshape(-1)
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(predictions, labels)
    )


def optimizer(lr=0.1):
    return optax.sgd(lr)


def dataset_fn(dataset, mode, _):
    def _parse(record):
        ex = decode_example(record)
        features = {"image": ex["image"].astype(np.float32)}
        if mode == Mode.PREDICTION:
            return features
        return features, ex["label"].astype(np.int32)[0]

    dataset = dataset.map(_parse)
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=1024, seed=0)
    return dataset


def eval_metrics_fn():
    return {
        "accuracy": lambda labels, predictions: (
            np.argmax(predictions, axis=1) == np.asarray(labels).reshape(-1)
        ).astype(np.float32)
    }


def feature_shapes():
    return {"image": (32, 32, 3)}
