"""Iris DNN over table rows — rebuild of the reference
model_zoo/odps_iris_dnn_model/odps_iris_dnn_model.py:18-56 (flatten 4 floats
-> Dense(3) softmax classifier; the reference reads MaxCompute/ODPS rows of
strings, parsed to floats in dataset_fn). Here the debug path consumes CSV
rows (lists of strings) from the CSV reader, matching the reference's
string-row parsing."""

import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from elasticdl_tpu.common.constants import Mode


class IrisDnnModel(nn.Module):
    @nn.compact
    def __call__(self, features, training=False):
        x = features["input"].reshape(features["input"].shape[0], -1)
        return nn.Dense(3, name="output")(x)


def custom_model():
    return IrisDnnModel()


def loss(labels, predictions):
    labels = labels.reshape(-1).astype(jnp.int32)
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(predictions, labels)
    )


def optimizer(lr=0.1):
    return optax.sgd(lr)


def dataset_fn(dataset, mode, metadata):
    def _parse(record):
        # record: list/array of string fields (ODPS row / CSV row)
        values = [float(v) for v in record]
        features = {"input": np.asarray(values[0:-1], np.float32)}
        if mode == Mode.PREDICTION:
            return features
        return features, np.asarray(values[-1], np.int32).reshape(())

    return dataset.map(_parse)


def eval_metrics_fn():
    return {
        "accuracy": lambda labels, predictions: (
            np.argmax(predictions, axis=1) == np.asarray(labels).reshape(-1)
        ).astype(np.float32)
    }


def feature_shapes():
    return {"input": (4,)}
