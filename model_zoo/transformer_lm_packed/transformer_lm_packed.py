"""Packed transformer LM: VARIABLE-length documents packed into fixed
rows inside the worker's task stream.

Same model as model_zoo/transformer_lm (reused outright); the
difference is the data path: records are whole documents
(data/recordio_gen.gen_docs_like), and dataset_fn streams them through
data/packing.pack_dataset — every training row carries `segment_ids`,
so attention stays inside each document (the flash kernels' segment
masks), positions restart per document, and cross-document next-token
targets are label-masked. ROW_LEN is the packing row length AND the
model's seq_len; dataset_fn cannot see model_params (it receives
reader metadata by convention), so custom_model REJECTS a divergent
seq_len instead of silently desynchronizing the packing width from
the positional table — change ROW_LEN (or copy the family) for other
lengths.

The reference zoo has no sequence families at all (SURVEY.md §2.10);
this packs on top of the net-new LM surface.
"""

import numpy as np

from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.data.example_codec import decode_example
from elasticdl_tpu.data.packing import pack_dataset
from model_zoo.transformer_lm.transformer_lm import (  # noqa: F401
    TransformerLM,
    loss,
    optimizer,
    resolve_dtype,
)

ROW_LEN = 128


def custom_model(**kwargs):
    seq_len = kwargs.setdefault("seq_len", ROW_LEN)
    if seq_len != ROW_LEN:
        raise ValueError(
            "transformer_lm_packed packs %d-token rows; seq_len=%r "
            "would desynchronize the positional table from the packed "
            "width (edit ROW_LEN or copy the family for other lengths)"
            % (ROW_LEN, seq_len)
        )
    return TransformerLM(
        **resolve_dtype(kwargs, "transformer_lm_packed")
    )


def dataset_fn(dataset, mode, metadata):
    if mode == Mode.PREDICTION:
        raise ValueError(
            "the packed family trains/evaluates; use transformer_lm "
            "for prediction/decoding"
        )
    dataset = dataset.map(
        lambda record: decode_example(record)["tokens"].astype(np.int32)
    )
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=512, seed=0)
    return pack_dataset(dataset, ROW_LEN)


def eval_metrics_fn():
    def token_accuracy(labels, predictions):
        labels = np.asarray(labels)
        preds = np.argmax(np.asarray(predictions), axis=-1)
        valid = labels >= 0
        return (
            ((preds == labels) & valid).sum(axis=1)
            / np.maximum(valid.sum(axis=1), 1)
        ).astype(np.float32)

    return {"token_accuracy": token_accuracy}


def feature_shapes(seq_len=ROW_LEN):
    return {"tokens": (seq_len,), "segment_ids": (seq_len,)}
