"""DeepFM using the framework's distributed Embedding layer — rebuild of the
reference model_zoo/deepfm_edl_embedding/deepfm_edl_embedding.py:29-120
(identical math to deepfm_functional_api, but the embedding tables are
`elasticdl.layers.Embedding` instances whose storage is framework-managed —
here elasticdl_tpu.embedding.Embedding, whose table shards across the mesh's
HBM and is picked up by the sparse-update engine via is_embedding_path)."""

import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.data.example_codec import decode_example
from elasticdl_tpu.embedding.layer import Embedding
from elasticdl_tpu.training.metrics import AUC


class DeepFMEdlModel(nn.Module):
    input_dim: int = 5383
    embedding_dim: int = 64
    input_length: int = 10
    fc_unit: int = 64

    @nn.compact
    def __call__(self, features, training=False):
        ids = features["feature"].astype(jnp.int32)  # [B, L]
        mask = (ids != 0).astype(jnp.float32)[..., None]  # mask_zero

        emb = Embedding(
            input_dim=self.input_dim,
            output_dim=self.embedding_dim,
            name="edl_embedding",
        )(ids)
        emb = emb * mask

        emb_sum = jnp.sum(emb, axis=1)
        second_order = 0.5 * jnp.sum(
            jnp.square(emb_sum) - jnp.sum(jnp.square(emb), axis=1), axis=1
        )

        id_bias = Embedding(
            input_dim=self.input_dim, output_dim=1, name="edl_id_bias"
        )(ids) * mask
        first_order = jnp.sum(id_bias, axis=(1, 2))
        fm_output = first_order + second_order

        nn_input = emb.reshape(emb.shape[0], -1)
        deep = nn.Dense(1)(nn.Dense(self.fc_unit)(nn_input)).reshape(-1)

        logits = fm_output + deep
        probs = jnp.reshape(nn.sigmoid(logits), (-1, 1))
        return {"logits": logits, "probs": probs}


def custom_model(input_dim=5383, embedding_dim=64, input_length=10,
                 fc_unit=64):
    return DeepFMEdlModel(
        input_dim=input_dim,
        embedding_dim=embedding_dim,
        input_length=input_length,
        fc_unit=fc_unit,
    )


def loss(labels, predictions):
    logits = predictions["logits"].reshape(-1)
    labels = labels.reshape(-1).astype(jnp.float32)
    return jnp.mean(optax.sigmoid_binary_cross_entropy(logits, labels))


def optimizer(lr=0.1):
    return optax.sgd(lr)


def dataset_fn(dataset, mode, _):
    def _parse(record):
        ex = decode_example(record)
        features = {"feature": ex["feature"].astype(np.int32)}
        if mode == Mode.PREDICTION:
            return features
        return features, ex["label"].astype(np.int32)[0]

    dataset = dataset.map(_parse)
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=1024, seed=0)
    return dataset


def eval_metrics_fn():
    return {
        "logits": {
            "accuracy": lambda labels, predictions: (
                (np.asarray(predictions).reshape(-1) > 0.0).astype(np.int32)
                == np.asarray(labels).reshape(-1)
            ).astype(np.float32)
        },
        "probs": {"auc": AUC()},
    }


def feature_shapes():
    return {"feature": (10,)}
