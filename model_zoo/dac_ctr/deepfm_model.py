"""Criteo DeepFM — rebuild of the reference model_zoo/dac_ctr/deepfm_model.py
(linear logits + DNN[16,4] logit + FM pairwise term over the stacked dim-8
group embeddings; reduce_sum -> logits)."""

import jax.numpy as jnp
from flax import linen as nn

from model_zoo.dac_ctr.utils import DNN, FM, GroupEmbeddings


class DeepFMCTR(nn.Module):
    max_ids: dict
    deep_embedding_dim: int = 8

    @nn.compact
    def __call__(self, dense_tensor, id_tensors, training=False):
        linear_logits = GroupEmbeddings(self.max_ids, 1)(id_tensors)
        deep_embeddings = GroupEmbeddings(
            self.max_ids, self.deep_embedding_dim
        )(id_tensors)

        dnn_input = jnp.concatenate(deep_embeddings, axis=-1)
        if dense_tensor is not None:
            dnn_input = jnp.concatenate([dense_tensor, dnn_input], axis=-1)
            linear_logits.append(nn.Dense(1, use_bias=False)(dense_tensor))

        linear_logit = jnp.concatenate(linear_logits, axis=-1)
        dnn_logit = nn.Dense(1, use_bias=False)(
            DNN((16, 4), "relu")(dnn_input)
        )

        parts = [linear_logit, dnn_logit]
        if len(deep_embeddings) > 1:
            stacked = jnp.stack(deep_embeddings, axis=1)  # [B, F, D]
            parts.append(FM()(stacked))

        concat = jnp.concatenate(parts, axis=1)
        logits = jnp.sum(concat, axis=1, keepdims=True)
        probs = jnp.reshape(nn.sigmoid(logits), (-1,))
        return {"logits": logits, "probs": probs}


def deepfm_model(max_ids, deep_embedding_dim=8):
    return DeepFMCTR(max_ids=max_ids, deep_embedding_dim=deep_embedding_dim)
