"""Criteo CTR training entry — rebuild of the reference
model_zoo/dac_ctr/elasticdl_train.py (spec module: transform_feature over
FEATURE_GROUPS feeding a selectable CTR model — the reference hardwires
xdeepfm; here ``custom_model(ctr_model=...)`` selects
wide_deep/deepfm/dcn/xdeepfm via --model_params, and
``max_hashing_bucket_size`` scales the hash spaces for small runs)."""

import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from elasticdl_tpu.api.callbacks import MaxStepsStopping
from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.data.example_codec import decode_example
from elasticdl_tpu.training.metrics import AUC
from model_zoo.dac_ctr.dcn_model import dcn_model
from model_zoo.dac_ctr.deepfm_model import deepfm_model
from model_zoo.dac_ctr.feature_config import (
    FEATURE_GROUPS,
    LABEL_KEY,
    MAX_HASHING_BUCKET_SIZE,
)
from model_zoo.dac_ctr.feature_transform import (
    group_max_ids,
    transform_feature,
)
from model_zoo.dac_ctr.wide_deep_model import wide_deep_model
from model_zoo.dac_ctr.xdeepfm_model import xdeepfm_model

_MODELS = {
    "wide_deep": wide_deep_model,
    "deepfm": deepfm_model,
    "dcn": dcn_model,
    "xdeepfm": xdeepfm_model,
}

# module-level so dataset_fn (which has no model handle) matches the model's
# id spaces; custom_model(max_hashing_bucket_size=...) updates it
_max_bucket = [MAX_HASHING_BUCKET_SIZE]


class _CTRWrapper(nn.Module):
    """Adapts (features dict) -> (dense_tensor, id_tensors) call form."""

    inner: nn.Module

    @nn.compact
    def __call__(self, features, training=False):
        dense = features["dense"].astype(jnp.float32)
        id_tensors = {
            k: v for k, v in features.items() if k.startswith("group_")
        }
        return self.inner(dense, id_tensors, training=training)


def custom_model(ctr_model="xdeepfm",
                 max_hashing_bucket_size=MAX_HASHING_BUCKET_SIZE):
    _max_bucket[0] = int(max_hashing_bucket_size)
    max_ids = group_max_ids(FEATURE_GROUPS, _max_bucket[0])
    return _CTRWrapper(inner=_MODELS[ctr_model](max_ids))


def loss(labels, predictions):
    logits = predictions["logits"].reshape(-1)
    labels = labels.reshape(-1).astype(jnp.float32)
    return jnp.mean(optax.sigmoid_binary_cross_entropy(logits, labels))


def optimizer(lr=0.001):
    return optax.adam(lr)


def callbacks():
    return [MaxStepsStopping(max_steps=150000)]


def dataset_fn(dataset, mode, _):
    def _parse(record):
        ex = decode_example(record)
        dense, id_tensors = transform_feature(
            ex, FEATURE_GROUPS, _max_bucket[0]
        )
        features = {"dense": dense}
        features.update(id_tensors)
        if mode == Mode.PREDICTION:
            return features
        return features, np.asarray(ex[LABEL_KEY], np.int32).reshape(())

    dataset = dataset.map(_parse)
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=10000, seed=0)
    return dataset


def eval_metrics_fn():
    return {
        "logits": {
            "accuracy": lambda labels, predictions: (
                (np.asarray(predictions).reshape(-1) > 0.5).astype(np.int32)
                == np.asarray(labels).reshape(-1)
            ).astype(np.float32)
        },
        "probs": {"auc": AUC()},
    }


def feature_shapes():
    shapes = {"dense": (13,)}
    shapes.update({"group_%d" % i: (1,) for i in range(len(FEATURE_GROUPS))})
    return shapes
