"""Criteo/DAC CTR feature configuration — rebuild of the reference
model_zoo/dac_ctr/feature_config.py: 13 numeric features (standardized with
published avg/stddev, and bucketized with published boundaries) + 26 hashed
categorical features with published distinct counts, grouped one feature per
embedding group."""

STANDARDIZED_FEATURES = ["I%d" % i for i in range(1, 14)]
BUCKET_FEATURES = ["I%d" % i for i in range(1, 14)]
HASH_FEATURES = ["C%d" % i for i in range(1, 27)]

FEATURES_AVGS = {
    "I1": 1.913844818114358, "I2": 105.85781137082337,
    "I3": 21.179428578076866, "I4": 5.735273873448716,
    "I5": 18067.71807784242, "I6": 90.08603360120591,
    "I7": 15.626512199091756, "I8": 12.509966404126569,
    "I9": 101.53250047174322, "I10": 0.3374528968790535,
    "I11": 2.614521353031052, "I12": 0.23277149534177055,
    "I13": 6.436560081179827,
}

FEATURES_STDDEVS = {
    "I1": 7.203044443387521, "I2": 391.73147156506417,
    "I3": 354.59360229869503, "I4": 8.351369642571008,
    "I5": 68611.11705989522, "I6": 340.20415627271075,
    "I7": 64.82617180501207, "I8": 16.71389239615237,
    "I9": 216.67850042198575, "I10": 0.5918310609867024,
    "I11": 5.115695237395591, "I12": 2.7609291491203973,
    "I13": 14.799688705863462,
}

FEATURE_BOUNDARIES = {
    "I1": [0.0, 1.0, 2.0, 5.0],
    "I2": [0.0, 1.0, 4.0, 16.0, 64.0],
    "I3": [1.0, 4.0, 16.0, 64.0],
    "I4": [1.0, 4.0, 8.0, 16.0],
    "I5": [64.0, 1024.0, 4096.0, 16384.0],
    "I6": [1.0, 8.0, 32.0, 128.0],
    "I7": [0.0, 1.0, 4.0, 16.0],
    "I8": [1.0, 4.0, 8.0, 16.0],
    "I9": [4.0, 16.0, 64.0, 256.0],
    "I10": [0.0, 1.0],
    "I11": [0.0, 1.0, 2.0, 4.0],
    "I12": [0.0, 1.0],
    "I13": [0.0, 1.0, 4.0, 8.0],
}

FEATURE_DISTINCT_COUNT = {
    "C1": 1460, "C2": 582, "C3": 9264260, "C4": 2046299, "C5": 305,
    "C6": 24, "C7": 12506, "C8": 633, "C9": 3, "C10": 91211,
    "C11": 5670, "C12": 7659856, "C13": 3194, "C14": 27, "C15": 14876,
    "C16": 5031503, "C17": 10, "C18": 5624, "C19": 2171, "C20": 4,
    "C21": 6477624, "C22": 18, "C23": 15, "C24": 272811, "C25": 101,
    "C26": 92253,
}

FEATURE_NAMES = STANDARDIZED_FEATURES + HASH_FEATURES

LABEL_KEY = "label"

# one feature per embedding group (I4 intentionally absent upstream)
FEATURE_GROUPS = [
    [f] for f in BUCKET_FEATURES if f != "I4"
] + [[f] for f in HASH_FEATURES]

MAX_HASHING_BUCKET_SIZE = 1000000
