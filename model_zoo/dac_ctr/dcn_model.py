"""Criteo DCN — rebuild of the reference model_zoo/dac_ctr/dcn_model.py
(linear logits + parallel DNN[16,4] and 2-layer CrossNet over the deep
input, Dense(1) over their concat, reduce_sum with linear -> logits)."""

import jax.numpy as jnp
from flax import linen as nn

from model_zoo.dac_ctr.utils import DNN, CrossNet, GroupEmbeddings


class DCNCTR(nn.Module):
    max_ids: dict
    deep_embedding_dim: int = 8

    @nn.compact
    def __call__(self, dense_tensor, id_tensors, training=False):
        linear_logits = GroupEmbeddings(self.max_ids, 1)(id_tensors)
        deep_embeddings = GroupEmbeddings(
            self.max_ids, self.deep_embedding_dim
        )(id_tensors)

        dnn_input = jnp.concatenate(deep_embeddings, axis=-1)
        if dense_tensor is not None:
            dnn_input = jnp.concatenate([dense_tensor, dnn_input], axis=-1)
            linear_logits.append(nn.Dense(1, use_bias=False)(dense_tensor))

        linear_logit = jnp.concatenate(linear_logits, axis=-1)

        dnn_output = DNN((16, 4), "relu")(dnn_input)
        cross_out = CrossNet(2)(dnn_input)
        deep_cross_logit = nn.Dense(1, use_bias=False)(
            jnp.concatenate([dnn_output, cross_out], axis=1)
        )

        concat = jnp.concatenate([linear_logit, deep_cross_logit], axis=1)
        logits = jnp.sum(concat, axis=1, keepdims=True)
        probs = jnp.reshape(nn.sigmoid(logits), (-1,))
        return {"logits": logits, "probs": probs}


def dcn_model(max_ids, deep_embedding_dim=8):
    return DCNCTR(max_ids=max_ids, deep_embedding_dim=deep_embedding_dim)
