"""Shared CTR building blocks — rebuild of the reference
model_zoo/dac_ctr/utils.py (DNN, lookup_embedding_func) plus flax
implementations of the interaction layers the reference imports from the
external `deepctr` package (FM, CrossNet, CIN)."""

import jax.numpy as jnp
from flax import linen as nn


class DNN(nn.Module):
    """Stack of Dense layers (reference utils.py DNN)."""

    hidden_units: tuple
    activation: str = None

    @nn.compact
    def __call__(self, x):
        act = {"relu": nn.relu, None: lambda y: y}[self.activation]
        for units in self.hidden_units:
            x = act(nn.Dense(units)(x))
        return x


class GroupEmbeddings(nn.Module):
    """Per-group embedding lookup + sum over the group's features
    (reference utils.py lookup_embedding_func). Call with the dict of
    [batch, n_feat] id tensors; returns a list of [batch, embedding_dim]
    tensors, one per group, in group order."""

    max_ids: dict
    embedding_dim: int

    @nn.compact
    def __call__(self, id_tensors):
        embeddings = []
        for name in sorted(
            id_tensors, key=lambda n: int(n.split("_")[-1])
        ):
            ids = id_tensors[name].astype(jnp.int32)
            emb = nn.Embed(
                self.max_ids[name], self.embedding_dim,
                name="%s_dim%d_embedding" % (name, self.embedding_dim),
            )(ids)
            embeddings.append(jnp.sum(emb, axis=1))
        return embeddings


class FM(nn.Module):
    """Factorization-machine pairwise term over stacked field embeddings
    (deepctr.layers.interaction.FM equivalent): input [B, F, D] ->
    0.5 * sum_d((sum_f e)^2 - sum_f e^2) -> [B, 1]."""

    @nn.compact
    def __call__(self, stacked):
        sum_sq = jnp.square(jnp.sum(stacked, axis=1))
        sq_sum = jnp.sum(jnp.square(stacked), axis=1)
        return 0.5 * jnp.sum(sum_sq - sq_sum, axis=1, keepdims=True)


class CrossNet(nn.Module):
    """DCN cross network (deepctr CrossNet equivalent):
    x_{l+1} = x_0 * (x_l . w_l) + b_l + x_l."""

    num_layers: int = 2

    @nn.compact
    def __call__(self, x0):
        x = x0
        dim = x0.shape[-1]
        for layer in range(self.num_layers):
            w = self.param(
                "cross_w_%d" % layer, nn.initializers.normal(0.01), (dim,)
            )
            b = self.param(
                "cross_b_%d" % layer, nn.initializers.zeros, (dim,)
            )
            xw = jnp.einsum("bd,d->b", x, w)[:, None]  # [B, 1]
            x = x0 * xw + b + x
        return x


class CIN(nn.Module):
    """Compressed interaction network (xDeepFM; deepctr CIN equivalent).
    Input [B, F, D]; each layer compresses the outer product of the previous
    feature maps with X^0 along the field axes; sum-pool over D at the end."""

    layer_sizes: tuple = (128, 128)

    @nn.compact
    def __call__(self, x0):
        batch, fields, dim = x0.shape
        finals = []
        xk = x0
        for k, size in enumerate(self.layer_sizes):
            hk = xk.shape[1]
            # outer product along field axes: [B, hk, F, D]
            z = jnp.einsum("bhd,bfd->bhfd", xk, x0)
            z = z.reshape(batch, hk * fields, dim)
            w = self.param(
                "cin_w_%d" % k,
                nn.initializers.normal(0.01),
                (size, hk * fields),
            )
            xk = jnp.einsum("bmd,sm->bsd", z, w)  # [B, size, D]
            finals.append(jnp.sum(xk, axis=2))  # sum pool over D
        return jnp.concatenate(finals, axis=1)  # [B, sum(sizes)]
