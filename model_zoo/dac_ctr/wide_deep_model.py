"""Criteo Wide&Deep — rebuild of the reference
model_zoo/dac_ctr/wide_deep_model.py (linear logits from dim-1 group
embeddings + Dense(1) over the standardized dense tensor; deep tower
DNN[16,4] over dense+flattened dim-8 embeddings; reduce_sum of
[linear, dnn_logit] -> logits)."""

import jax.numpy as jnp
from flax import linen as nn

from model_zoo.dac_ctr.utils import DNN, GroupEmbeddings


class WideDeepCTR(nn.Module):
    max_ids: dict
    deep_embedding_dim: int = 8
    dnn_hidden_units: tuple = (16, 4)

    @nn.compact
    def __call__(self, dense_tensor, id_tensors, training=False):
        linear_logits = GroupEmbeddings(self.max_ids, 1)(id_tensors)
        deep_embeddings = GroupEmbeddings(
            self.max_ids, self.deep_embedding_dim
        )(id_tensors)

        dnn_input = jnp.concatenate(deep_embeddings, axis=-1)
        if dense_tensor is not None:
            dnn_input = jnp.concatenate([dense_tensor, dnn_input], axis=-1)
            linear_logits.append(
                nn.Dense(1, use_bias=False)(dense_tensor)
            )

        linear_logit = jnp.concatenate(linear_logits, axis=-1)
        dnn_output = DNN(self.dnn_hidden_units, "relu")(dnn_input)
        dnn_logit = nn.Dense(1, use_bias=False)(dnn_output)

        concat = jnp.concatenate([linear_logit, dnn_logit], axis=1)
        logits = jnp.sum(concat, axis=1, keepdims=True)
        probs = jnp.reshape(nn.sigmoid(logits), (-1,))
        return {"logits": logits, "probs": probs}


def wide_deep_model(max_ids, deep_embedding_dim=8):
    return WideDeepCTR(max_ids=max_ids,
                       deep_embedding_dim=deep_embedding_dim)
