"""Criteo feature transform — rebuild of the reference
model_zoo/dac_ctr/feature_transform.py (transform_feature/transform_group:
standardize the 13 numerics with Normalizer; per group, Discretize bucket
features / Hash categorical features and offset ids into the group's shared
id space).

Host-side (strings never enter XLA); produces per-example
(dense vector, {group_name: id vector}) consumed by the flax CTR models.
``max_ids`` per group is a static property of the config, so model shapes
compile once."""

import numpy as np

from elasticdl_tpu.preprocessing.layers import (
    Discretization,
    Hashing,
    Normalizer,
)
from model_zoo.dac_ctr.feature_config import (
    BUCKET_FEATURES,
    FEATURE_BOUNDARIES,
    FEATURE_DISTINCT_COUNT,
    FEATURES_AVGS,
    FEATURES_STDDEVS,
    HASH_FEATURES,
    MAX_HASHING_BUCKET_SIZE,
    STANDARDIZED_FEATURES,
)


def _hash_bins(feature, max_bucket):
    return min(FEATURE_DISTINCT_COUNT[feature], max_bucket)


def group_max_ids(feature_groups, max_bucket=MAX_HASHING_BUCKET_SIZE):
    """{group_name: id-space size} — static, drives embedding table shapes
    (reference transform_group id_offsets[-1])."""
    out = {}
    for i, features in enumerate(feature_groups):
        total = 0
        for f in features:
            if f in BUCKET_FEATURES:
                total += len(FEATURE_BOUNDARIES[f]) + 1
            elif f in HASH_FEATURES:
                total += _hash_bins(f, max_bucket)
        out["group_%d" % i] = total
    return out


def transform_feature(example, feature_groups,
                      max_bucket=MAX_HASHING_BUCKET_SIZE):
    """One example -> (standardized dense [13], {group_name: id vector}).

    Mirrors reference transform_feature: Normalizer over
    STANDARDIZED_FEATURES; per group Discretization/Hashing + id offsets.
    """
    dense = np.asarray(
        [
            Normalizer(FEATURES_AVGS[f], FEATURES_STDDEVS[f])(
                np.float32(example[f])
            )
            for f in STANDARDIZED_FEATURES
        ],
        np.float32,
    )

    id_tensors = {}
    for i, features in enumerate(feature_groups):
        ids, offset = [], 0
        for f in features:
            if f in BUCKET_FEATURES:
                layer = Discretization(bins=FEATURE_BOUNDARIES[f])
                ids.append(
                    int(np.asarray(layer(np.float32(example[f])))) + offset
                )
                offset += len(FEATURE_BOUNDARIES[f]) + 1
            elif f in HASH_FEATURES:
                bins = _hash_bins(f, max_bucket)
                ids.append(int(np.asarray(Hashing(bins)(example[f]))) + offset)
                offset += bins
        id_tensors["group_%d" % i] = np.asarray(ids, np.int64)
    return dense, id_tensors
