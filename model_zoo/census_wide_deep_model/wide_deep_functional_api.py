"""Census Wide&Deep — rebuild of the reference
model_zoo/census_wide_deep_model/wide_deep_functional_api.py:164-244:

* features transformed per group into offset id matrices (host-side,
  transform_layers.py),
* wide tower: per-group Embedding(dim 1) summed over the group's features,
* deep tower: per-group Embedding(dim 8) summed, Dense[16, 8, 4],
* concat(wide, deep) -> reduce_sum -> logits; sigmoid -> probs,
* dict outputs {"logits", "probs"}, nested eval metrics with AUC.
"""

import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.data.example_codec import decode_example
from elasticdl_tpu.training.metrics import AUC
from model_zoo.census_wide_deep_model.feature_config import (
    FEATURE_GROUPS,
    LABEL_KEY,
    MODEL_INPUTS,
    get_id_group_dims,
)
from model_zoo.census_wide_deep_model.transform_layers import transform


class WideDeepModel(nn.Module):
    @nn.compact
    def __call__(self, features, training=False):
        id_group_dims = get_id_group_dims()

        def embed_sum(group_name, dim, tower):
            ids = features[group_name].astype(jnp.int32)  # [B, n_feat]
            emb = nn.Embed(
                id_group_dims[group_name], dim,
                name="%s_%s_embedding" % (tower, group_name),
            )(ids)
            return jnp.sum(emb, axis=1)  # [B, dim]

        wide_embeddings = [
            embed_sum(g, 1, "wide") for g in MODEL_INPUTS["wide"]
        ]
        deep_embeddings = [
            embed_sum(g, 8, "deep") for g in MODEL_INPUTS["deep"]
        ]

        wide = jnp.concatenate(wide_embeddings, axis=-1)

        dnn = jnp.concatenate(deep_embeddings, axis=-1)
        for units in (16, 8, 4):
            dnn = nn.Dense(units)(dnn)

        concat = jnp.concatenate([wide, dnn], axis=1)
        logits = jnp.sum(concat, axis=1, keepdims=True)
        probs = jnp.reshape(nn.sigmoid(logits), (-1,))
        return {"logits": logits, "probs": probs}


def custom_model():
    return WideDeepModel()


def loss(labels, predictions):
    logits = predictions["logits"].reshape(-1)
    labels = labels.reshape(-1).astype(jnp.float32)
    return jnp.mean(optax.sigmoid_binary_cross_entropy(logits, labels))


def optimizer(lr=0.001):
    return optax.adam(lr)


def dataset_fn(dataset, mode, _):
    def _parse(record):
        ex = decode_example(record)
        features = {
            name: ids.astype(np.int64)
            for name, ids in transform(ex, FEATURE_GROUPS).items()
        }
        if mode == Mode.PREDICTION:
            return features
        return features, np.asarray(ex[LABEL_KEY], np.int32).reshape(())

    dataset = dataset.map(_parse)
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=1024, seed=0)
    return dataset


def eval_metrics_fn():
    return {
        "logits": {
            "accuracy": lambda labels, predictions: (
                (np.asarray(predictions).reshape(-1) > 0.0).astype(np.int32)
                == np.asarray(labels).reshape(-1)
            ).astype(np.float32)
        },
        "probs": {"auc": AUC()},
    }


def feature_shapes():
    return {
        name: (len(group),) for name, group in FEATURE_GROUPS.items()
    }
