"""Census wide&deep feature configuration — rebuild of the reference
model_zoo/census_wide_deep_model/feature_config.py: vocabularies, bucket
boundaries, the three feature groups, and which groups feed the wide vs deep
towers."""

import numpy as np

from model_zoo.census_wide_deep_model.feature_info_util import (
    FeatureInfo,
    TransformOp,
    get_id_boundaries,
)

WORK_CLASS_VOCABULARY = [
    "Private", "Self-emp-not-inc", "Self-emp-inc", "Federal-gov",
    "Local-gov", "State-gov", "Without-pay", "Never-worked",
]
MARITAL_STATUS_VOCABULARY = [
    "Married-civ-spouse", "Divorced", "Never-married", "Separated",
    "Widowed", "Married-spouse-absent", "Married-AF-spouse",
]
RELATION_SHIP_VOCABULARY = [
    "Wife", "Own-child", "Husband", "Not-in-family", "Other-relative",
    "Unmarried",
]
RACE_VOCABULARY = [
    "White", "Asian-Pac-Islander", "Amer-Indian-Eskimo", "Other", "Black",
]
SEX_VOCABULARY = ["Female", "Male"]

AGE_BOUNDARIES = [0, 20, 40, 60, 80]
CAPITAL_GAIN_BOUNDARIES = [6000, 6500, 7000, 7500, 8000]
CAPITAL_LOSS_BOUNDARIES = [2000, 2500, 3000, 3500, 4000]
HOURS_BOUNDARIES = [10, 20, 30, 40, 50, 60]

education = FeatureInfo("education", TransformOp.HASH, np.str_, 30)
occupation = FeatureInfo("occupation", TransformOp.HASH, np.str_, 30)
native_country = FeatureInfo(
    "native-country", TransformOp.HASH, np.str_, 100
)

workclass = FeatureInfo(
    "workclass", TransformOp.LOOKUP, np.str_, WORK_CLASS_VOCABULARY
)
marital_status = FeatureInfo(
    "marital-status", TransformOp.LOOKUP, np.str_, MARITAL_STATUS_VOCABULARY
)
relationship = FeatureInfo(
    "relationship", TransformOp.LOOKUP, np.str_, RELATION_SHIP_VOCABULARY
)
race = FeatureInfo("race", TransformOp.LOOKUP, np.str_, RACE_VOCABULARY)
sex = FeatureInfo("sex", TransformOp.LOOKUP, np.str_, SEX_VOCABULARY)

age = FeatureInfo("age", TransformOp.BUCKETIZE, np.float32, AGE_BOUNDARIES)
capital_gain = FeatureInfo(
    "capital-gain", TransformOp.BUCKETIZE, np.float32,
    CAPITAL_GAIN_BOUNDARIES,
)
capital_loss = FeatureInfo(
    "capital-loss", TransformOp.BUCKETIZE, np.float32,
    CAPITAL_LOSS_BOUNDARIES,
)
hours_per_week = FeatureInfo(
    "hours-per-week", TransformOp.BUCKETIZE, np.float32, HOURS_BOUNDARIES
)

FEATURE_GROUPS = {
    "group1": [workclass, hours_per_week, capital_gain, capital_loss],
    "group2": [education, marital_status, relationship, occupation],
    "group3": [age, sex, race, native_country],
}

MODEL_INPUTS = {
    "wide": ["group1", "group2"],
    "deep": ["group1", "group2", "group3"],
}

CATEGORICAL_FEATURE_KEYS = [
    "workclass", "education", "marital-status", "occupation",
    "relationship", "race", "sex", "native-country",
]
NUMERIC_FEATURE_KEYS = [
    "age", "capital-gain", "capital-loss", "hours-per-week",
]
LABEL_KEY = "label"


def get_id_group_dims():
    """{group_name: total id-space size} (reference get_id_group_dims)."""
    return {
        name: get_id_boundaries(features)[-1]
        for name, features in FEATURE_GROUPS.items()
    }
