"""Feature transform metadata — rebuild of the reference
model_zoo/census_wide_deep_model/feature_info_util.py (FeatureInfo namedtuple
+ TransformOp names + id-boundary helper used to offset per-feature id spaces
inside a group)."""

from collections import namedtuple

FeatureInfo = namedtuple("FeatureInfo", ["name", "op_name", "dtype", "param"])


class TransformOp(object):
    HASH = "HASH"
    LOOKUP = "LOOKUP"
    BUCKETIZE = "BUCKETIZE"


def feature_id_space(feature_info):
    """Number of distinct ids the transform of one feature can produce."""
    if feature_info.op_name == TransformOp.HASH:
        return int(feature_info.param)
    if feature_info.op_name == TransformOp.LOOKUP:
        return len(feature_info.param) + 1  # + default OOV token
    if feature_info.op_name == TransformOp.BUCKETIZE:
        return len(feature_info.param) + 1
    raise ValueError("Unknown op %r" % (feature_info.op_name,))


def get_id_boundaries(feature_group):
    """Cumulative id offsets [0, s1, s1+s2, ...] for the features of a group
    (reference feature_info_util.get_id_boundaries)."""
    bounds = [0]
    for info in feature_group:
        bounds.append(bounds[-1] + feature_id_space(info))
    return bounds
