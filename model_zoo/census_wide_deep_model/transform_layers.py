"""Host-side transform pipeline — rebuild of the reference's
keras_process_layer.py (CategoryLookup/CategoryHash/NumericBucket) +
wide_deep_functional_api.transform/transform_group: each feature maps to an
int id, per-feature ids inside a group are offset so they share one id space,
and a group becomes a [batch, n_features] id matrix.

Runs host-side in ``dataset_fn`` (strings never enter XLA); the embedding
towers consume the resulting static-shape id matrices."""

import numpy as np

from elasticdl_tpu.preprocessing.layers import (
    Discretization,
    Hashing,
    IndexLookup,
)
from model_zoo.census_wide_deep_model.feature_info_util import (
    TransformOp,
    get_id_boundaries,
)


def get_transform_layer(feature_info):
    """FeatureInfo -> host-side transform callable
    (reference wide_deep_functional_api.get_transform_layer)."""
    if feature_info.op_name == TransformOp.LOOKUP:
        return IndexLookup(vocabulary=list(feature_info.param))
    if feature_info.op_name == TransformOp.HASH:
        return Hashing(num_bins=int(feature_info.param))
    if feature_info.op_name == TransformOp.BUCKETIZE:
        return Discretization(bins=list(feature_info.param))
    raise ValueError("The op %r is not supported" % (feature_info.op_name,))


def transform_group(example, feature_group):
    """Transform one example's features of a group into an offset id vector
    (reference transform_group: per-feature transform + AddIdOffset +
    concatenate)."""
    offsets = get_id_boundaries(feature_group)
    ids = []
    for offset, info in zip(offsets[:-1], feature_group):
        value = example[info.name]
        if info.op_name == TransformOp.BUCKETIZE:
            value = np.asarray(value, np.float32)
        out = np.asarray(get_transform_layer(info)(value)).reshape(-1)
        ids.append(out.astype(np.int64) + offset)
    return np.concatenate(ids)


def transform(example, feature_groups):
    """{group_name: offset id vector} for one example
    (reference wide_deep_functional_api.transform)."""
    return {
        name: transform_group(example, group)
        for name, group in feature_groups.items()
    }
