"""Heart-disease classifier — rebuild of the reference
model_zoo/heart_functional_api/heart_functional_api.py:20-100:

* numeric features trestbps/chol/thalach/oldpeak/slope/ca pass through,
* `age` bucketized at [18,25,30,35,40,45,50,55,60,65] (one-hot indicator),
* `thal` string hashed into 100 buckets and embedded at dim 8
  (framework embedding_column equivalent),
* Dense16-Dense16-Dense1 sigmoid head, SGD(1e-6), binary crossentropy.

TPU split: the string hash + bucketize run host-side in dataset_fn; the
embedding/one-hot + MLP are the jit-compiled model."""

import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.data.example_codec import decode_example
from elasticdl_tpu.preprocessing.layers import Discretization, Hashing

NUMERIC_KEYS = ["trestbps", "chol", "thalach", "oldpeak", "slope", "ca"]
AGE_BOUNDARIES = [18, 25, 30, 35, 40, 45, 50, 55, 60, 65]
THAL_HASH_BUCKETS = 100
THAL_EMBEDDING_DIM = 8


class HeartModel(nn.Module):
    @nn.compact
    def __call__(self, features, training=False):
        parts = [
            features[k].astype(jnp.float32).reshape(-1, 1)
            for k in NUMERIC_KEYS
        ]
        age_onehot = jnp.eye(len(AGE_BOUNDARIES) + 1)[
            features["age_bucket"].astype(jnp.int32).reshape(-1)
        ]
        parts.append(age_onehot)
        thal_emb = nn.Embed(
            THAL_HASH_BUCKETS, THAL_EMBEDDING_DIM, name="thal_embedding"
        )(features["thal_id"].astype(jnp.int32).reshape(-1))
        parts.append(thal_emb)
        x = jnp.concatenate(parts, axis=-1)
        x = nn.relu(nn.Dense(16)(x))
        x = nn.relu(nn.Dense(16)(x))
        return nn.sigmoid(nn.Dense(1)(x))


def custom_model():
    return HeartModel()


def loss(labels, predictions):
    labels = labels.reshape(-1).astype(jnp.float32)
    p = jnp.clip(predictions.reshape(-1), 1e-7, 1 - 1e-7)
    return -jnp.mean(
        labels * jnp.log(p) + (1.0 - labels) * jnp.log(1.0 - p)
    )


def optimizer(lr=1e-6):
    return optax.sgd(lr)


def dataset_fn(dataset, mode, _):
    age_bucketize = Discretization(bins=AGE_BOUNDARIES)
    thal_hash = Hashing(THAL_HASH_BUCKETS)

    def _parse(record):
        ex = decode_example(record)
        features = {
            k: np.asarray(ex[k], dtype=np.float32).reshape(())
            for k in NUMERIC_KEYS
        }
        features["age_bucket"] = np.asarray(
            age_bucketize(np.asarray(ex["age"], np.float32)), np.int32
        ).reshape(())
        features["thal_id"] = np.asarray(
            thal_hash(ex["thal"]), np.int32
        ).reshape(())
        if mode == Mode.PREDICTION:
            return features
        return features, np.asarray(ex["target"], np.int32).reshape(())

    return dataset.map(_parse)


def eval_metrics_fn():
    return {
        "accuracy": lambda labels, predictions: (
            np.round(np.asarray(predictions).reshape(-1)).astype(np.int32)
            == np.asarray(labels).reshape(-1)
        ).astype(np.float32)
    }


def feature_shapes():
    shapes = {k: () for k in NUMERIC_KEYS}
    shapes["age_bucket"] = ()
    shapes["thal_id"] = ()
    return shapes
