"""MNIST CNN — flax port of the reference zoo module
(model_zoo/mnist_functional_api/mnist_functional_api.py:21-103): same
architecture (Conv32-Conv64-BN-MaxPool-Dropout-Dense10), same spec surface
(custom_model/loss/optimizer/dataset_fn/eval_metrics_fn), TPU-idiomatic
implementation (flax.linen + optax, records parsed from TRec examples)."""

import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.data.example_codec import decode_example


class MnistModel(nn.Module):
    @nn.compact
    def __call__(self, features, training=False):
        x = features["image"]
        x = x.reshape(x.shape[0], 28, 28, 1)
        x = nn.relu(nn.Conv(32, (3, 3), padding="VALID")(x))
        x = nn.relu(nn.Conv(64, (3, 3), padding="VALID")(x))
        x = nn.BatchNorm(use_running_average=not training, momentum=0.99)(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Dropout(0.25, deterministic=not training)(x)
        x = x.reshape(x.shape[0], -1)
        return nn.Dense(10)(x)


def custom_model():
    return MnistModel()


def loss(labels, predictions, sample_weights=None):
    labels = labels.reshape(-1)
    ce = optax.softmax_cross_entropy_with_integer_labels(
        predictions, labels
    )
    if sample_weights is None:
        return jnp.mean(ce)
    return jnp.sum(ce * sample_weights) / jnp.maximum(
        jnp.sum(sample_weights), 1.0
    )


def optimizer(lr=0.1):
    return optax.sgd(lr)


def dataset_fn(dataset, mode, metadata):
    def _parse(record):
        ex = decode_example(record)
        features = {"image": ex["image"].astype(np.float32)}
        if mode == Mode.PREDICTION:
            return features
        return features, ex["label"].astype(np.int32)[0]

    dataset = dataset.map(_parse)
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=1024, seed=0)
    return dataset


def eval_metrics_fn():
    return {
        "accuracy": lambda labels, predictions: (
            np.argmax(predictions, axis=1) == np.asarray(labels).reshape(-1)
        ).astype(np.float32)
    }


def feature_shapes():
    return {"image": (28, 28)}
