"""Causal transformer language model — the long-context flagship.

The reference zoo has no sequence model (its largest config is
ResNet50); this family exercises the capabilities the TPU rebuild adds
on top of reference parity: flash attention on one chip and ring
attention over the `sp` mesh axis for sequences that don't fit a single
device (parallel/context_parallel.py). Same zoo spec surface as every
other family (custom_model/loss/optimizer/dataset_fn/eval_metrics_fn).

Records are token sequences; the training pair is (tokens[:-1] →
tokens[1:]) built in dataset_fn, so seq_len below is the model's input
length and records carry seq_len + 1 tokens.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from elasticdl_tpu.common.constants import MeshAxis, Mode
from elasticdl_tpu.data.example_codec import decode_example
from elasticdl_tpu.ops.attention import (
    NEG_INF,
    apply_rope,
    blockwise_attention,
    expand_kv,
    flash_attention,
    jax_flash_attention,
    packed_positions,
    paged_decode_attention,
)
from elasticdl_tpu.ops.losses import chunked_softmax_xent
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.parallel.context_parallel import (
    ring_attention,
    ulysses_attention,
)


def _tp_dense_init(split_axis):
    """Megatron-style kernel annotation: split_axis=1 is column-parallel
    (outputs sharded over tp), split_axis=0 row-parallel (inputs sharded;
    XLA inserts the all-reduce on the partial sums). The annotations are
    metadata only — on a tp=1 mesh they are no-ops; on tp>1 meshes
    parallel/sharding.py collect_annotations turns them into placements
    and GSPMD propagates through the activations."""
    names = [None, None]
    names[split_axis] = MeshAxis.TP
    return nn.with_partitioning(
        nn.initializers.lecun_normal(), tuple(names)
    )


def _kv_quantize_rows(rows):
    """Symmetric per-row int8 for the KV cache: rows [b, hkv, t, d] ->
    (int8 rows, f32 scales [b, hkv, t, 1]); a zero row keeps scale 1 so
    it stays exactly zero."""
    r32 = rows.astype(jnp.float32)
    amax = jnp.max(jnp.abs(r32), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q8 = jnp.clip(jnp.round(r32 / scale), -127, 127).astype(jnp.int8)
    return q8, scale


class CausalSelfAttention(nn.Module):
    """Self-attention block shared by the decoder (causal=True) and the
    BERT-class encoder (causal=False, model_zoo/bert)."""

    num_heads: int
    head_dim: int
    dtype: object = None  # compute dtype (bf16 on TPU); params stay fp32
    # "auto": our Pallas flash on TPU; "xla": blockwise scan;
    # "jax_flash": jax's bundled TPU flash kernel (sweep alternative)
    attn_impl: str = "auto"
    sp_impl: str = "ring"  # sp>1 scheme: "ring" | "ulysses"
    tp_shard: bool = True
    causal: bool = True
    use_rope: bool = False  # rotary q/k (global positions; sp-safe)
    window: int = 0  # sliding-window size; 0 = full attention
    cache_len: int = 0  # KV-cache capacity for decode mode
    # grouped-query attention: kv heads (0 = num_heads, i.e. standard
    # MHA; 1 = multi-query). Q head j reads kv head j // group. Shrinks
    # the qkv projection and the decode KV cache by num_heads/kv_heads;
    # the Pallas flash kernels consume the grouped layout natively.
    num_kv_heads: int = 0
    # LoRA (attention-only): rank-r adapter branches on the qkv and
    # output projections. The base Dense param paths are UNCHANGED, so
    # a dense pretraining checkpoint warm-starts this model
    # (restore strict=False); lora_b is zero-init, so the warm-started
    # model's logits equal the dense model's exactly until the
    # adapters train. Combine with trainable_pattern="lora" to train
    # adapters only.
    lora_rank: int = 0
    lora_alpha: float = 16.0
    # KV-cache storage format: "" = compute dtype; "int8" = symmetric
    # per-row int8 with f32 scales. Decode is cache-bandwidth-bound
    # (every generated token re-reads the whole cache), so int8 halves
    # (vs bf16) the dominant HBM stream; the dequantize fuses into the
    # attention reads. Write-side rounding costs one quantize per
    # generated token — negligible next to the read stream.
    kv_cache_dtype: str = ""

    def _cache_vars(self, b, hkv, d, dtype):
        """The cache buffers in the configured storage format. Returns
        (ck, cv, k_scale, v_scale) — scale vars are None for the
        plain-dtype format."""
        if self.kv_cache_dtype not in ("", "int8"):
            raise ValueError(
                "Unknown kv_cache_dtype %r (valid: '', 'int8')"
                % (self.kv_cache_dtype,)
            )
        if self.kv_cache_dtype == "int8":
            shape = (b, hkv, self.cache_len, d)
            sshape = (b, hkv, self.cache_len, 1)
            return (
                self.variable("cache", "k", jnp.zeros, shape, jnp.int8),
                self.variable("cache", "v", jnp.zeros, shape, jnp.int8),
                self.variable("cache", "k_scale", jnp.zeros, sshape,
                              jnp.float32),
                self.variable("cache", "v_scale", jnp.zeros, sshape,
                              jnp.float32),
            )
        shape = (b, hkv, self.cache_len, d)
        return (
            self.variable("cache", "k", jnp.zeros, shape, dtype),
            self.variable("cache", "v", jnp.zeros, shape, dtype),
            None, None,
        )

    def _cache_write(self, cvars, k, v, idx):
        """Store chunk rows [b, hkv, t, d] at position idx (k already
        RoPE-rotated at its absolute positions)."""
        ck, cv, ks, vs = cvars
        if ks is None:
            ck.value = jax.lax.dynamic_update_slice(
                ck.value, k.astype(ck.value.dtype), (0, 0, idx, 0)
            )
            cv.value = jax.lax.dynamic_update_slice(
                cv.value, v.astype(cv.value.dtype), (0, 0, idx, 0)
            )
            return
        kq, ksc = _kv_quantize_rows(k)
        vq, vsc = _kv_quantize_rows(v)
        ck.value = jax.lax.dynamic_update_slice(
            ck.value, kq, (0, 0, idx, 0)
        )
        cv.value = jax.lax.dynamic_update_slice(
            cv.value, vq, (0, 0, idx, 0)
        )
        ks.value = jax.lax.dynamic_update_slice(
            ks.value, ksc, (0, 0, idx, 0)
        )
        vs.value = jax.lax.dynamic_update_slice(
            vs.value, vsc, (0, 0, idx, 0)
        )

    def _cache_read(self, cvars, dtype):
        """The full cache as compute-dtype floats; for int8 storage the
        dequantize (q8 * scale) fuses into the consuming attention
        einsums — the HBM stream stays int8."""
        ck, cv, ks, vs = cvars
        if ks is None:
            return ck.value, cv.value
        return (
            (ck.value.astype(jnp.float32) * ks.value).astype(dtype),
            (cv.value.astype(jnp.float32) * vs.value).astype(dtype),
        )

    def _lora_branch(self, x, features, name):
        """(x @ A @ B) * alpha/rank — A lecun-init, B zeros."""
        a = self.param(
            "%s_lora_a" % name, nn.initializers.lecun_normal(),
            (x.shape[-1], self.lora_rank),
        )
        b = self.param(
            "%s_lora_b" % name, nn.initializers.zeros,
            (self.lora_rank, features),
        )
        dtype = self.dtype or x.dtype
        return (
            (x @ a.astype(dtype)) @ b.astype(dtype)
        ) * (self.lora_alpha / self.lora_rank)

    @nn.compact
    def __call__(self, x, training=False, decode=False, decode_pos=None,
                 prefill=False, segments=None, positions=None,
                 paged=None):
        b, l, e = x.shape
        h, d = self.num_heads, self.head_dim
        hkv = self.num_kv_heads or h
        if h % hkv:
            raise ValueError(
                "num_heads (%d) must be a multiple of num_kv_heads (%d)"
                % (h, hkv)
            )
        qkv = nn.Dense(
            (h + 2 * hkv) * d, use_bias=False, dtype=self.dtype,
            name="qkv",
            kernel_init=(
                _tp_dense_init(1) if self.tp_shard
                else nn.initializers.lecun_normal()
            ),
        )(x)
        if self.lora_rank:
            qkv = qkv + self._lora_branch(x, (h + 2 * hkv) * d, "qkv")
        q = qkv[..., : h * d].reshape(b, l, h, d).transpose(0, 2, 1, 3)
        k = (
            qkv[..., h * d:(h + hkv) * d]
            .reshape(b, l, hkv, d).transpose(0, 2, 1, 3)
        )
        v = (
            qkv[..., (h + hkv) * d:]
            .reshape(b, l, hkv, d).transpose(0, 2, 1, 3)
        )  # q: [b, h, l, d]; k/v: [b, hkv, l, d]
        if decode:
            return self._decode_step(q, k, v, e, decode_pos,
                                     paged=paged)
        if self.use_rope:
            pos = jnp.arange(l) if positions is None else positions
            q = apply_rope(q, pos)
            k = apply_rope(k, pos)
        if prefill:
            # Batched prompt prefill: one causal forward populates the
            # decode KV cache for positions [0, l) — O(prompt) single-
            # token steps collapse into one MXU-friendly pass. Cache
            # layout/dtype matches _decode_step exactly (grouped hkv
            # heads, k already RoPE-rotated at its absolute position).
            # Positions >= the true prompt length hold pad-token junk;
            # that is safe because decode masks k_pos <= counter and
            # overwrites each position before first attending to it.
            if not self.causal:
                raise ValueError("prefill requires a causal model")
            _mesh = mesh_lib.current_mesh()
            if _mesh is not None and _mesh.shape.get(MeshAxis.SP, 1) > 1:
                raise NotImplementedError(
                    "prefill is single-shard (like decode); drop the "
                    "sp axis for generation"
                )
            if self.cache_len < l:
                raise ValueError(
                    "prefill length %d exceeds cache_len %d"
                    % (l, self.cache_len)
                )
            cvars = self._cache_vars(b, hkv, d, q.dtype)
            self._cache_write(cvars, k, v, 0)
            if self.kv_cache_dtype == "int8":
                # SELF-CONSISTENCY: attend over the rows decode will
                # re-read. The cache stores quantized rows; if prefill
                # attended the original floats, any later recompute of
                # these logits from the cache (a paged shared-prefix
                # seat re-running the last prompt token over resident
                # int8 blocks; a speculative verify tile) would see
                # different values and greedy parity across the
                # offline/serving seams would break. Quantize-dequant
                # here is a one-time prefill cost (the rows are live
                # floats anyway) — decode's per-step reads stay int8
                # with the deferred dequantize.
                kq, ksc = _kv_quantize_rows(k)
                vq, vsc = _kv_quantize_rows(v)
                k = (kq.astype(jnp.float32) * ksc).astype(q.dtype)
                v = (vq.astype(jnp.float32) * vsc).astype(q.dtype)
        if self.attn_impl not in ("auto", "xla", "jax_flash"):
            raise ValueError(
                "Unknown attn_impl %r (valid: 'auto', 'xla', "
                "'jax_flash')" % (self.attn_impl,)
            )
        if self.kv_cache_dtype not in ("", "int8"):
            # eager: a typo must fail the first TRAINING forward, not
            # hours later at the first cached generation
            raise ValueError(
                "Unknown kv_cache_dtype %r (valid: '', 'int8')"
                % (self.kv_cache_dtype,)
            )
        window = self.window or None
        mesh = mesh_lib.current_mesh()
        if mesh is not None and mesh.shape.get(MeshAxis.SP, 1) > 1:
            # ring merges partials per kv rotation and ulysses
            # all-to-alls the head axis over sp — both want the full
            # head count, so GQA kv expands here (the grouped layout
            # still pays off in params and the decode cache)
            k = expand_kv(k, h)
            v = expand_kv(v, h)
            if self.sp_impl == "ulysses":
                out = ulysses_attention(
                    q, k, v, mesh, causal=self.causal,
                    attn_impl=self.attn_impl, segments=segments,
                    window=window,
                )
            elif self.sp_impl == "ring":
                if self.attn_impl == "jax_flash":
                    # the ring merges (o, logsumexp) partials per
                    # rotation; jax's bundled kernel doesn't expose lse
                    raise ValueError(
                        "attn_impl='jax_flash' is incompatible with "
                        "sp_impl='ring' (no logsumexp output); use "
                        "sp_impl='ulysses' or attn_impl='auto'"
                    )
                out = ring_attention(q, k, v, mesh, causal=self.causal,
                                     segments=segments, window=window)
            else:
                raise ValueError(
                    "Unknown sp_impl %r (valid: 'ring', 'ulysses')"
                    % (self.sp_impl,)
                )
        elif self.attn_impl == "xla":
            out = blockwise_attention(
                q, k, v, causal=self.causal, window=window,
                segments=segments,
            )
        elif self.attn_impl == "jax_flash":
            if segments is not None:
                raise ValueError(
                    "attn_impl='jax_flash' does not support packed-"
                    "sequence masking; use attn_impl='auto' or 'xla'"
                )
            out = jax_flash_attention(
                q, k, v, causal=self.causal, window=window
            )
        else:  # "auto" (validated above)
            out = flash_attention(
                q, k, v, causal=self.causal, window=window,
                segments=segments,
            )
        out = out.transpose(0, 2, 1, 3).reshape(b, l, h * d)
        return self._proj(out, e)

    def _proj(self, out, e):
        y = nn.Dense(
            e, use_bias=False, dtype=self.dtype, name="proj",
            kernel_init=(
                _tp_dense_init(0) if self.tp_shard
                else nn.initializers.lecun_normal()
            ),
        )(out)
        if self.lora_rank:
            y = y + self._lora_branch(out, e, "proj")
        return y

    def _decode_step(self, q, k, v, e, decode_pos, paged=None):
        """Chunked decode against the KV cache: q is [b, h, t, d],
        k/v [b, hkv, t, d] for a chunk of t >= 1 tokens at absolute
        positions [decode_pos, decode_pos + t) — t = 1 is the classic
        per-token step; t > 1 is the speculative-verify / chunked-
        prefill-continuation step (one batched read of the cache for t
        queries instead of t reads). Cached keys/values live in the
        `cache` collection in the GROUPED head count — the GQA memory
        win: cache reads scale with hkv, not h. `decode_pos` comes from
        the model's single cache counter (one source of truth —
        per-layer counters could only drift apart). RoPE rotates q/k at
        their absolute positions; row i of the chunk masks
        `k_pos <= pos + i` (windowing `k_pos > pos + i - window`).

        `paged` (serving only): {"k": pool, "v": pool, "table": [b, m]}
        — this layer's slice of the block-paged serving KV pool
        (serving/kv_pool.py). The cached rows then live in the SHARED
        block arenas instead of per-sequence flax cache buffers:
        attention streams the sequence's block table
        (ops.paged_decode_attention) and the new token's k/v rows are
        SOWN into the "kv_out" collection for the engine to scatter
        into the pool — a module has no business writing an arena it
        shares with every other sequence. With kv_cache_dtype="int8"
        the dict also carries "k_scale"/"v_scale" arenas; rows are
        quantized HERE (at the sow — the one insertion point) and the
        dequantize defers into the attention scan, so the arenas
        stream int8 end to end."""
        if not self.causal:
            raise ValueError("decode mode requires a causal model")
        if self.cache_len < 1:
            raise ValueError("decode mode needs cache_len >= 1")
        if decode_pos is None:
            raise ValueError("decode mode needs decode_pos")
        b, h, t, d = q.shape
        hkv = k.shape[1]
        group = h // hkv
        dtype = q.dtype
        idx = decode_pos
        if self.use_rope:
            pos = idx + jnp.arange(t)
            q = apply_rope(q, pos)
            k = apply_rope(k, pos)
        if paged is not None:
            # t = 1: the classic per-token step. t > 1: a query TILE —
            # the speculative verify-k step and the shared-prefix
            # suffix prefill both decode t tokens at positions
            # [idx, idx + t) in ONE batched read of the pool, causal
            # within the tile (ops.paged_decode_attention).
            if self.kv_cache_dtype == "int8":
                # QUANTIZE AT INSERTION: the tile's rows are quantized
                # here, once, and sown in arena format (int8 rows +
                # f32 per-row scales) — the engine scatters them
                # verbatim, so the arenas only ever hold quantized
                # data and every later read defers the dequantize into
                # the scan (no float cache copy anywhere). Attention
                # over the tile's OWN keys uses the quantized rows
                # too, exactly like the dense int8 path that writes
                # the cache before reading it back.
                kq, ksc = _kv_quantize_rows(k)
                vq, vsc = _kv_quantize_rows(v)
                self.sow("kv_out", "k", kq)
                self.sow("kv_out", "v", vq)
                self.sow("kv_out", "k_scale", ksc)
                self.sow("kv_out", "v_scale", vsc)
                out = paged_decode_attention(
                    q, kq, vq,
                    paged["k"], paged["v"], paged["table"],
                    jnp.broadcast_to(idx, (b,)),
                    scale=d ** -0.5, window=self.window or None,
                    k_scale_pool=paged["k_scale"],
                    v_scale_pool=paged["v_scale"],
                    k_cur_scale=ksc, v_cur_scale=vsc,
                ).astype(dtype)
                out = out.transpose(0, 2, 1, 3).reshape(b, t, h * d)
                return self._proj(out, e)
            self.sow("kv_out", "k", k)  # [b, hkv, t, d] for the
            self.sow("kv_out", "v", v)  # engine's pool scatter
            out = paged_decode_attention(
                q, k, v,
                paged["k"], paged["v"], paged["table"],
                jnp.broadcast_to(idx, (b,)),
                scale=d ** -0.5, window=self.window or None,
            ).astype(dtype)
            out = out.transpose(0, 2, 1, 3).reshape(b, t, h * d)
            return self._proj(out, e)
        cvars = self._cache_vars(b, hkv, d, dtype)
        self._cache_write(cvars, k, v, idx)
        scale = d ** -0.5
        # group the q heads under their kv head: [b, hkv, group, t, d]
        qg = (q * scale).reshape(b, hkv, group, t, d)
        ck, cv, ks, vs = cvars
        if ks is None:
            s = jnp.einsum(
                "bhgtd,bhkd->bhgtk", qg, ck.value
            ).astype(jnp.float32)  # [b, hkv, group, t, L]
        else:
            # int8 cache, DEFERRED dequantize: fold the per-row scales
            # into the scores instead of materializing a float copy of
            # the whole cache every step — the scale multiply runs on
            # [*, L] scores, a head_dim-times smaller array than the
            # [*, L, d] rows (the decode_kv_int8 bench regression)
            s = jnp.einsum(
                "bhgtd,bhkd->bhgtk", qg, ck.value.astype(dtype)
            ).astype(jnp.float32) * ks.value[..., 0][:, :, None, None]
        k_pos = jnp.arange(self.cache_len)[None, :]
        row_pos = (idx + jnp.arange(t))[:, None]
        valid = k_pos <= row_pos  # [t, L]
        if self.window:
            valid = valid & (k_pos > row_pos - self.window)
        s = jnp.where(valid[None, None, None], s, NEG_INF)
        w = jax.nn.softmax(s, axis=-1)
        if vs is None:
            out = jnp.einsum(
                "bhgtk,bhkd->bhgtd", w.astype(dtype), cv.value
            )
        else:
            # v-side deferral: scale the [*, L] weights, read int8 rows
            out = jnp.einsum(
                "bhgtk,bhkd->bhgtd",
                (w * vs.value[..., 0][:, :, None, None]).astype(dtype),
                cv.value.astype(dtype),
            )
        # (hkv, group) flattens back to h in q's head order
        out = out.transpose(0, 3, 1, 2, 4).reshape(b, t, h * d)
        return self._proj(out, e)


class Block(nn.Module):
    num_heads: int
    head_dim: int
    mlp_ratio: int = 4
    dtype: object = None
    attn_impl: str = "auto"
    sp_impl: str = "ring"
    tp_shard: bool = True
    causal: bool = True
    use_rope: bool = False
    window: int = 0
    cache_len: int = 0
    num_kv_heads: int = 0  # grouped-query attention (0 = MHA)
    lora_rank: int = 0
    lora_alpha: float = 16.0
    kv_cache_dtype: str = ""  # "" | "int8" (see CausalSelfAttention)

    @nn.compact
    def __call__(self, x, training=False, decode=False, decode_pos=None,
                 prefill=False, segments=None, positions=None,
                 paged=None):
        e = x.shape[-1]
        y = nn.LayerNorm(dtype=self.dtype)(x)
        x = x + CausalSelfAttention(
            self.num_heads, self.head_dim, dtype=self.dtype,
            attn_impl=self.attn_impl, sp_impl=self.sp_impl,
            tp_shard=self.tp_shard, causal=self.causal,
            use_rope=self.use_rope, window=self.window,
            cache_len=self.cache_len,
            num_kv_heads=self.num_kv_heads,
            lora_rank=self.lora_rank, lora_alpha=self.lora_alpha,
            kv_cache_dtype=self.kv_cache_dtype,
            name="attn",
        )(y, training, decode=decode, decode_pos=decode_pos,
          prefill=prefill, segments=segments, positions=positions,
          paged=paged)
        y = nn.LayerNorm(dtype=self.dtype)(x)
        up_init = (
            _tp_dense_init(1) if self.tp_shard
            else nn.initializers.lecun_normal()
        )
        down_init = (
            _tp_dense_init(0) if self.tp_shard
            else nn.initializers.lecun_normal()
        )
        y = nn.Dense(
            self.mlp_ratio * e, dtype=self.dtype, kernel_init=up_init,
            name="mlp_up",
        )(y)
        y = nn.gelu(y)
        y = nn.Dense(
            e, dtype=self.dtype, kernel_init=down_init, name="mlp_down"
        )(y)
        return x + y


class LMHead(nn.Module):
    """Vocab projection. In fused mode it returns the hidden states and
    the kernel instead of running the matmul, so the loss can stream the
    head over sequence chunks (ops/losses.chunked_softmax_xent) and never
    materialize the full [b, s, vocab] fp32 logits — peak residency is
    O(b * s/num_chunks * vocab). The param path stays `head/kernel`,
    checkpoint-compatible with the plain Dense."""

    vocab_size: int
    dtype: object = None
    kernel_init: object = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x, fused=False):
        kernel = self.param(
            "kernel", self.kernel_init,
            (x.shape[-1], self.vocab_size), jnp.float32,
        )
        if fused:
            return x, kernel
        logits = x @ jnp.asarray(kernel, self.dtype or x.dtype)
        # loss math (softmax xent) wants fp32 logits regardless of the
        # compute dtype
        return logits.astype(jnp.float32)


def setup_decode_positions(mdl, tokens, decode, prefill, prompt_len):
    """THE KV-decode position convention, shared by every decoder
    family (TransformerLM here, TransformerMoE via import) so
    api/generation.py's prefill/decode contract lives in one place:

      * decode: one cached scalar counter ("cache"/"pos") that every
        layer's cache write and the position-embedding lookup read;
        advances by the chunk width (tokens [b, t], t >= 1).
      * prefill: the counter is SET to the true prompt length (may be
        < the padded prefill width) so the next decode step writes
        position prompt_len.

    Returns (decode_pos, wpe_idx): the pre-advance counter (None unless
    decode) and the [1, t] index array a learned position table should
    look up for this call."""
    t = tokens.shape[1]
    decode_pos = None
    if decode:
        pi = mdl.variable(
            "cache", "pos", lambda: jnp.zeros((), jnp.int32)
        )
        decode_pos = pi.value
        pi.value = decode_pos + t
        idx = decode_pos + jnp.arange(t)
        # Decode TILES (speculative verify, shared-prefix suffix
        # prefill) may carry PAD rows whose positions run past
        # seq_len. An out-of-bounds wpe gather fills NaN under jit,
        # and a NaN k/v row poisons the whole tile through the
        # attention value sum (0 weight x NaN = NaN) — clamp to the
        # table. Real rows are always in bounds (the engine admits
        # nothing past seq_len), so this only sanitizes pad rows,
        # whose outputs are never read.
        cap = getattr(mdl, "seq_len", None)
        if cap is not None:
            idx = jnp.minimum(idx, cap - 1)
        idx = idx[None, :]
    else:
        if prefill:
            if prompt_len is None:
                raise ValueError("prefill needs prompt_len")
            pi = mdl.variable(
                "cache", "pos", lambda: jnp.zeros((), jnp.int32)
            )
            pi.value = jnp.asarray(prompt_len, jnp.int32)
        idx = jnp.arange(t)[None, :]
    return decode_pos, idx


class TransformerLM(nn.Module):
    vocab_size: int = 256
    seq_len: int = 128
    embed_dim: int = 128
    num_heads: int = 4
    num_layers: int = 2
    dtype: object = None  # compute dtype; None = fp32
    attn_impl: str = "auto"
    sp_impl: str = "ring"  # sequence-parallel scheme: "ring" | "ulysses"
    pos_emb: str = "learned"  # "learned" wpe table | "rope" rotary q/k
    attn_window: int = 0  # sliding-window attention; 0 = full
    tp_shard: bool = True  # annotate kernels over the tp mesh axis
    fused_head: bool = False  # stream the LM head inside the loss
    num_kv_heads: int = 0  # grouped-query attention (0 = MHA)
    lora_rank: int = 0  # attention-LoRA adapters (0 = off)
    lora_alpha: float = 16.0
    # Per-block rematerialization for the training forward: recompute
    # activations in the backward instead of saving them, trading
    # ~1 extra block forward of FLOPs for O(num_layers) less live
    # memory — the knob that admits larger global batches (bigger MXU
    # tiles) when HBM, not FLOPs, limits the step. "" = off;
    # "full" = save only block boundaries; "dots" = additionally save
    # matmul outputs (jax dots_with_no_batch_dims_saveable — cheaper
    # backward, smaller memory win). Decode/prefill are untouched.
    remat: str = ""
    # KV-cache storage: "" = compute dtype; "int8" halves (vs bf16) the
    # decode path's dominant HBM stream (see CausalSelfAttention)
    kv_cache_dtype: str = ""

    @nn.compact
    def __call__(self, features, training=False, decode=False,
                 prefill=False, prompt_len=None, paged=None):
        # `paged` (decode only): the serving engine's block-paged KV
        # pool — {"pools": tree mirroring this model's cache collection
        # with per-layer [num_blocks, block_size, hkv, d] arenas,
        # "table": [b, m] int32 block table}. Each block slices out its
        # own layer's arenas below; see serving/kv_pool.py.
        tokens = features["tokens"]  # [b, seq_len]; [b, 1] when decode
        if decode and prefill:
            raise ValueError("decode and prefill are mutually exclusive")
        if paged is not None and not decode:
            raise ValueError("paged KV applies to decode mode only")
        # sequence packing: [b, seq_len] int ids of contiguous same-id
        # runs. Attention is confined to each run and positions restart
        # at run boundaries (the packed rows behave exactly like the
        # unpacked sequences stacked into separate batch rows).
        segments = features.get("segment_ids")
        positions = None
        if segments is not None:
            if decode or prefill:
                raise ValueError(
                    "segment_ids apply to training/eval forwards, not "
                    "decode/prefill"
                )
            segments = jnp.asarray(segments, jnp.int32)
            positions = packed_positions(segments)
        x = nn.Embed(
            self.vocab_size, self.embed_dim, dtype=self.dtype, name="wte"
        )(tokens)
        # shared decode-counter convention (setup_decode_positions):
        # the counter drives every layer's cache write, RoPE rotation
        # and the wpe lookup
        decode_pos, wpe_idx = setup_decode_positions(
            self, tokens, decode, prefill, prompt_len
        )
        if self.pos_emb == "learned":
            wpe = nn.Embed(
                self.seq_len, self.embed_dim, dtype=self.dtype,
                name="wpe",
            )
            if positions is not None and not decode:
                x = x + wpe(positions)  # [b, l] packed offsets
            else:
                x = x + wpe(wpe_idx)
        elif self.pos_emb != "rope":
            raise ValueError(
                "Unknown pos_emb %r (valid: 'learned', 'rope')"
                % (self.pos_emb,)
            )
        head_dim = self.embed_dim // self.num_heads
        if self.remat not in ("", "full", "dots"):
            raise ValueError(
                "Unknown remat %r (valid: '', 'full', 'dots')"
                % (self.remat,)
            )
        # remat applies to the training/eval forward only: decode and
        # prefill run no backward, so recompute would be pure waste
        use_remat = bool(self.remat) and not decode and not prefill
        if use_remat:
            policy = (
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable
                if self.remat == "dots" else None
            )

            # training is closure-static; segments/positions are
            # non-differentiable int arrays, safe to close over
            def run_block(blk, xx):
                return blk(xx, training, segments=segments,
                           positions=positions)

            # default prevent_cse=True: the layer loop is unrolled (not
            # nn.scan), and CSE could merge the recomputed forward with
            # the primal one, silently negating the memory savings
            run_block = nn.remat(run_block, policy=policy)
        for i in range(self.num_layers):
            blk = Block(
                self.num_heads, head_dim, dtype=self.dtype,
                attn_impl=self.attn_impl, sp_impl=self.sp_impl,
                tp_shard=self.tp_shard,
                use_rope=self.pos_emb == "rope",
                window=self.attn_window,
                cache_len=self.seq_len,
                num_kv_heads=self.num_kv_heads,
                lora_rank=self.lora_rank, lora_alpha=self.lora_alpha,
                kv_cache_dtype=self.kv_cache_dtype,
                name="block_%d" % i,
            )
            blk_paged = None
            if paged is not None:
                arena = paged["pools"]["block_%d" % i]["attn"]
                blk_paged = {
                    "k": arena["k"], "v": arena["v"],
                    "table": paged["table"],
                }
                if "k_scale" in arena:  # int8 arenas carry scale leaves
                    blk_paged["k_scale"] = arena["k_scale"]
                    blk_paged["v_scale"] = arena["v_scale"]
            if use_remat:
                x = run_block(blk, x)
            else:
                x = blk(x, training, decode=decode,
                        decode_pos=decode_pos, prefill=prefill,
                        segments=segments, positions=positions,
                        paged=blk_paged)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        head = LMHead(
            self.vocab_size, dtype=self.dtype, name="head",
            kernel_init=(
                _tp_dense_init(1) if self.tp_shard
                else nn.initializers.lecun_normal()
            ),
        )
        if self.fused_head and training:
            hidden, kernel = head(x, fused=True)
            return {"lm_hidden": hidden, "lm_head_kernel": kernel}
        return head(x)


_DTYPES = {
    "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
    "fp32": jnp.float32, "float32": jnp.float32,
    "fp16": jnp.float16, "float16": jnp.float16,
}


def resolve_dtype(kwargs, family):
    """Shared "dtype": "bf16" -> jnp dtype resolution for the sequence
    families' custom_model kwargs."""
    dtype = kwargs.get("dtype")
    if isinstance(dtype, str):
        if dtype.lower() not in _DTYPES:
            raise ValueError(
                "Unknown dtype %r for %s (valid: %s)"
                % (dtype, family, sorted(_DTYPES))
            )
        kwargs["dtype"] = _DTYPES[dtype.lower()]
    return kwargs


def custom_model(**kwargs):
    return TransformerLM(**resolve_dtype(kwargs, "transformer_lm"))


def loss(labels, predictions, sample_weights=None):
    # labels [b, l] int; predictions [b, l, vocab] logits, or the fused
    # {lm_hidden, lm_head_kernel} dict when fused_head is on (the head
    # matmul then streams inside the loss — ops/losses.py).
    # Negative labels are IGNORED (ce contribution 0; the packed-
    # sequence data path marks cross-segment boundary targets -100) —
    # rows average over their valid tokens only.
    labels = jnp.asarray(labels)
    valid = labels >= 0
    safe = jnp.maximum(labels, 0)
    if isinstance(predictions, dict) and "lm_hidden" in predictions:
        tok_ce = chunked_softmax_xent(
            predictions["lm_hidden"], predictions["lm_head_kernel"], safe
        )
    else:
        tok_ce = optax.softmax_cross_entropy_with_integer_labels(
            predictions, safe
        )
    tok_ce = jnp.where(valid, tok_ce, 0.0)
    ce = tok_ce.sum(axis=-1) / jnp.maximum(valid.sum(axis=-1), 1)
    if sample_weights is None:
        return jnp.mean(ce)
    return jnp.sum(ce * sample_weights) / jnp.maximum(
        jnp.sum(sample_weights), 1.0
    )


def optimizer(lr=3e-4):
    return optax.adamw(lr, weight_decay=0.01)


def dataset_fn(dataset, mode, metadata):
    def _parse(record):
        ex = decode_example(record)
        tokens = ex["tokens"].astype(np.int32)
        features = {"tokens": tokens[:-1]}
        if mode == Mode.PREDICTION:
            return features
        return features, tokens[1:]

    dataset = dataset.map(_parse)
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=1024, seed=0)
    return dataset


def eval_metrics_fn():
    return {
        "token_accuracy": lambda labels, predictions: (
            np.argmax(predictions, axis=-1)
            == np.asarray(labels)
        ).astype(np.float32).reshape(len(labels), -1).mean(axis=1)
    }


def feature_shapes(seq_len=128):
    return {"tokens": (seq_len,)}
