"""Pipeline-parallel causal transformer LM: the layer stack streams
through pp stages (parallel/pipeline.py GPipe schedule), composing with
dp/fsdp on the same mesh.

Net-new beyond the reference (which has no pipeline axis — SURVEY.md
§2.5) and beyond transformer_lm: where that family annotates kernels for
TENSOR parallelism, this one stacks all blocks' params with a leading
layer dim annotated over ``pp`` (nn.with_partitioning, so each device
holds its contiguous chunk of layers + co-sharded optimizer moments) and
runs the stack through pipeline_apply. With pp=1 the identical stage
function runs sequentially — the single-device oracle the tests compare
against. Zoo spec surface matches every other family.
"""

import numpy as np

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn

from elasticdl_tpu.common.constants import MeshAxis, Mode
from elasticdl_tpu.data.example_codec import decode_example
from elasticdl_tpu.ops.attention import blockwise_attention
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.parallel.pipeline import pipeline_apply, sequential_apply

# One transformer block's parameter shapes, given embed dim e, heads h,
# mlp ratio r: {name: shape-without-the-leading-layer-dim}.


def _block_param_shapes(e, r):
    return {
        "ln1_scale": (e,), "ln1_bias": (e,),
        "qkv_w": (e, 3 * e),
        "proj_w": (e, e),
        "ln2_scale": (e,), "ln2_bias": (e,),
        "up_w": (e, r * e), "up_b": (r * e,),
        "down_w": (r * e, e), "down_b": (e,),
    }


def _layer_norm(x, scale, bias, eps=1e-6):
    mean = x.mean(-1, keepdims=True)
    var = ((x - mean) ** 2).mean(-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps) * scale + bias


def _block_apply(p, x, num_heads):
    """One block, pure-fn form of transformer_lm.Block (pre-LN attention
    + MLP residuals); p holds ONE layer's params (no leading dim)."""
    b, l, e = x.shape
    d = e // num_heads
    y = _layer_norm(x, p["ln1_scale"], p["ln1_bias"])
    qkv = (y @ p["qkv_w"]).reshape(b, l, 3, num_heads, d)
    qkv = qkv.transpose(2, 0, 3, 1, 4)
    out = blockwise_attention(qkv[0], qkv[1], qkv[2], causal=True)
    out = out.transpose(0, 2, 1, 3).reshape(b, l, e)
    x = x + out @ p["proj_w"]
    y = _layer_norm(x, p["ln2_scale"], p["ln2_bias"])
    y = jax.nn.gelu(y @ p["up_w"] + p["up_b"])
    return x + y @ p["down_w"] + p["down_b"]


def _stage_fn(num_heads):
    """A pipeline stage = its contiguous chunk of layers, scanned."""

    def stage(local_params, x):
        def body(carry, layer_params):
            return _block_apply(layer_params, carry, num_heads), None

        out, _ = jax.lax.scan(body, x, local_params)
        return out

    return stage


def _stacked_init(name, shape):
    """Per-layer initializer for a stacked [L, ...] param."""
    if name.endswith(("_bias", "_b")):
        return nn.initializers.zeros
    if name.endswith("_scale"):
        return nn.initializers.ones

    base = nn.initializers.lecun_normal()

    def init(key, full_shape, dtype=jnp.float32):
        n_layers = full_shape[0]
        keys = jax.random.split(key, n_layers)
        return jnp.stack(
            [base(k, full_shape[1:], dtype) for k in keys]
        )

    return init


class TransformerPP(nn.Module):
    vocab_size: int = 256
    seq_len: int = 128
    embed_dim: int = 128
    num_heads: int = 4
    num_layers: int = 4
    mlp_ratio: int = 4
    num_microbatches: int = 2
    # pipeline schedule knobs (parallel/pipeline.py): "interleaved"
    # runs the circular schedule — the stacked blk_* rows are then in
    # ring-ordered layout (fresh inits need no conversion; a
    # gpipe-trained checkpoint converts via pipeline.interleave_layers
    # on the blk_* leaves). pp_remat stages activations per microbatch.
    pp_schedule: str = "gpipe"
    pp_interleave: int = 2
    pp_remat: bool = False

    @nn.compact
    def __call__(self, features, training=False):
        tokens = features["tokens"]
        x = nn.Embed(self.vocab_size, self.embed_dim, name="wte")(tokens)
        pos = nn.Embed(self.seq_len, self.embed_dim, name="wpe")(
            jnp.arange(tokens.shape[1])[None, :]
        )
        x = x + pos

        blocks = {}
        for name, shape in _block_param_shapes(
            self.embed_dim, self.mlp_ratio
        ).items():
            blocks[name] = self.param(
                "blk_%s" % name,
                nn.with_partitioning(
                    _stacked_init(name, shape),
                    (MeshAxis.PP,) + (None,) * len(shape),
                ),
                (self.num_layers,) + shape,
            )

        stage = _stage_fn(self.num_heads)
        mesh = mesh_lib.current_mesh()
        pp = mesh.shape.get(MeshAxis.PP, 1) if mesh is not None else 1
        if pp > 1:
            if self.num_layers % pp:
                raise ValueError(
                    "num_layers=%d not divisible by pp=%d"
                    % (self.num_layers, pp)
                )
            x = pipeline_apply(
                stage, blocks, x, mesh, self.num_microbatches,
                schedule=self.pp_schedule,
                interleave=self.pp_interleave,
                remat=self.pp_remat,
            )
        else:
            x = sequential_apply(stage, blocks, x, 1)

        x = _layer_norm(
            x,
            self.param("lnf_scale", nn.initializers.ones,
                       (self.embed_dim,)),
            self.param("lnf_bias", nn.initializers.zeros,
                       (self.embed_dim,)),
        )
        logits = nn.Dense(
            self.vocab_size, use_bias=False, name="head"
        )(x)
        return logits.astype(jnp.float32)


def custom_model(**kwargs):
    return TransformerPP(**kwargs)


def loss(labels, predictions, sample_weights=None):
    ce = optax.softmax_cross_entropy_with_integer_labels(
        predictions, labels
    ).mean(axis=-1)
    if sample_weights is None:
        return jnp.mean(ce)
    return jnp.sum(ce * sample_weights) / jnp.maximum(
        jnp.sum(sample_weights), 1.0
    )


def optimizer(lr=3e-4):
    return optax.adamw(lr, weight_decay=0.01)


def dataset_fn(dataset, mode, metadata):
    def _parse(record):
        ex = decode_example(record)
        tokens = ex["tokens"].astype(np.int32)
        features = {"tokens": tokens[:-1]}
        if mode == Mode.PREDICTION:
            return features
        return features, tokens[1:]

    dataset = dataset.map(_parse)
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=1024, seed=0)
    return dataset


def eval_metrics_fn():
    return {
        "token_accuracy": lambda labels, predictions: (
            np.argmax(predictions, axis=-1)
            == np.asarray(labels)
        ).astype(np.float32).reshape(len(labels), -1).mean(axis=1)
    }


def feature_shapes(seq_len=128):
    return {"tokens": (seq_len,)}
