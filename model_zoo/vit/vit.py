"""Vision Transformer (image classification) — net-new zoo family; the
reference zoo's vision ceiling is ResNet50
(/root/reference/model_zoo/resnet50_subclass/resnet50_subclass.py), with
no attention-based vision model. Same zoo spec surface as every family
(custom_model/loss/optimizer/dataset_fn/eval_metrics_fn/feature_shapes),
trained on the cifar10-shaped TRec records `gen_cifar10_like` emits.

TPU-first choices:
- Patch embedding is a reshape + one Dense (a single [B*N, p*p*C] ×
  [p*p*C, D] matmul on the MXU) rather than a strided conv.
- No CLS token: mean-pool over patch tokens. 32/4 -> 8x8 = 64 tokens,
  which tiles cleanly into the flash kernel's blocks; a 65-token CLS
  sequence would knock attention onto the non-tiling fallback path.
- The encoder reuses transformer_lm's Block with causal=False, so
  attention dispatch (flash/blockwise), Megatron TP annotations, the
  bf16 knob, and LoRA adapters live in ONE place (same reuse as bert).
"""

import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.data.example_codec import decode_example
from model_zoo.transformer_lm.transformer_lm import (
    Block,
    resolve_dtype,
)


class ViT(nn.Module):
    image_size: int = 32
    channels: int = 3
    patch_size: int = 4
    num_classes: int = 10
    embed_dim: int = 128
    num_heads: int = 4
    num_layers: int = 4
    dtype: object = None
    attn_impl: str = "auto"
    tp_shard: bool = True
    lora_rank: int = 0
    lora_alpha: float = 16.0
    dropout: float = 0.1

    @nn.compact
    def __call__(self, features, training=False):
        if self.image_size % self.patch_size:
            raise ValueError(
                "image_size %d not divisible by patch_size %d"
                % (self.image_size, self.patch_size)
            )
        if self.embed_dim % self.num_heads:
            # without this, head_dim silently floors and Block's
            # residual projection hides the shrunken attention width
            raise ValueError(
                "embed_dim %d not divisible by num_heads %d"
                % (self.embed_dim, self.num_heads)
            )
        x = features["image"]
        b = x.shape[0]
        s, p, c = self.image_size, self.patch_size, self.channels
        x = x.reshape(b, s, s, c)
        n = s // p
        # [b, n, p, n, p, c] -> [b, n*n, p*p*c]: each row is one patch
        x = x.reshape(b, n, p, n, p, c).transpose(0, 1, 3, 2, 4, 5)
        x = x.reshape(b, n * n, p * p * c)
        if self.dtype is not None:
            x = x.astype(self.dtype)
        x = nn.Dense(self.embed_dim, dtype=self.dtype,
                     name="patch_embed")(x)
        x = x + nn.Embed(n * n, self.embed_dim, dtype=self.dtype,
                         name="wpe")(jnp.arange(n * n)[None, :])
        x = nn.Dropout(self.dropout, deterministic=not training)(x)
        head_dim = self.embed_dim // self.num_heads
        for i in range(self.num_layers):
            x = Block(
                self.num_heads, head_dim, dtype=self.dtype,
                attn_impl=self.attn_impl, tp_shard=self.tp_shard,
                causal=False,
                lora_rank=self.lora_rank, lora_alpha=self.lora_alpha,
                name="layer_%d" % i,
            )(x, training)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        x = x.mean(axis=1)  # mean-pool patch tokens (no CLS; see above)
        return nn.Dense(
            self.num_classes, dtype=self.dtype, name="head"
        )(x).astype(jnp.float32)


def custom_model(**kwargs):
    return ViT(**resolve_dtype(kwargs, "vit"))


def loss(labels, predictions, sample_weights=None):
    labels = jnp.asarray(labels).reshape(-1)
    per = optax.softmax_cross_entropy_with_integer_labels(
        predictions, labels
    )
    if sample_weights is not None:
        w = jnp.asarray(sample_weights).reshape(-1)
        return jnp.sum(per * w) / jnp.maximum(jnp.sum(w), 1e-8)
    return jnp.mean(per)


def optimizer(lr=3e-4):
    return optax.adamw(lr, weight_decay=0.05)


def dataset_fn(dataset, mode, _):
    def _parse(record):
        ex = decode_example(record)
        features = {"image": ex["image"].astype(np.float32)}
        if mode == Mode.PREDICTION:
            return features
        return features, ex["label"].astype(np.int32)[0]

    dataset = dataset.map(_parse)
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=1024, seed=0)
    return dataset


def eval_metrics_fn():
    return {
        "accuracy": lambda labels, predictions: (
            np.argmax(predictions, axis=1)
            == np.asarray(labels).reshape(-1)
        ).astype(np.float32)
    }


def feature_shapes():
    return {"image": (32, 32, 3)}
