"""ResNet-50 zoo entry — rebuild of the reference
model_zoo/resnet50_subclass/resnet50_subclass.py (CustomModel over cifar-size
images, num_classes=10, momentum SGD). L2 weight decay (reference: per-layer
kernel regularizers, L2_WEIGHT_DECAY=1e-4) is folded into the optimizer as
decoupled decay — the XLA-friendly equivalent."""

import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.data.example_codec import decode_example
from model_zoo.resnet50_subclass.resnet50_model import (
    L2_WEIGHT_DECAY,
    ResNet50,
)


from flax import linen as nn  # noqa: E402


class CustomModel(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, features, training=False):
        return ResNet50(num_classes=self.num_classes, name="resnet50")(
            features["image"], training
        )


def custom_model():
    return CustomModel(num_classes=10)


def loss(labels, predictions):
    labels = labels.reshape(-1)
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(predictions, labels)
    )


def optimizer(lr=0.02):
    return optax.chain(
        optax.add_decayed_weights(L2_WEIGHT_DECAY),
        optax.sgd(lr, momentum=0.9),
    )


def dataset_fn(dataset, mode, _):
    def _parse(record):
        ex = decode_example(record)
        features = {"image": ex["image"].astype(np.float32) / 255.0}
        if mode == Mode.PREDICTION:
            return features
        return features, ex["label"].astype(np.int32)[0]

    dataset = dataset.map(_parse)
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=1024, seed=0)
    return dataset


def eval_metrics_fn():
    return {
        "accuracy": lambda labels, predictions: (
            np.argmax(predictions, axis=1) == np.asarray(labels).reshape(-1)
        ).astype(np.float32)
    }


def feature_shapes():
    return {"image": (32, 32, 3)}
