"""ResNet-50 building blocks in flax — rebuild of the reference's
model_zoo/resnet50_subclass/resnet50_model.py (IdentityBlock / ConvBlock with
BATCH_NORM_DECAY/EPSILON and L2 weight decay). TPU-idiomatic: NHWC layout so
XLA tiles convs onto the MXU; L2 decay is applied in the optimizer
(optax.add_decayed_weights) instead of per-layer kernel regularizers."""

from flax import linen as nn

L2_WEIGHT_DECAY = 1e-4
BATCH_NORM_DECAY = 0.9
BATCH_NORM_EPSILON = 1e-5


class IdentityBlock(nn.Module):
    """3-conv residual block whose shortcut is the identity
    (reference resnet50_model.py IdentityBlock)."""

    kernel_size: int
    filters: tuple

    @nn.compact
    def __call__(self, x, training=False):
        f1, f2, f3 = self.filters

        def bn(y):
            return nn.BatchNorm(
                use_running_average=not training,
                momentum=BATCH_NORM_DECAY,
                epsilon=BATCH_NORM_EPSILON,
            )(y)

        shortcut = x
        y = nn.Conv(f1, (1, 1), use_bias=False)(x)
        y = nn.relu(bn(y))
        y = nn.Conv(
            f2, (self.kernel_size, self.kernel_size), padding="SAME",
            use_bias=False,
        )(y)
        y = nn.relu(bn(y))
        y = nn.Conv(f3, (1, 1), use_bias=False)(y)
        y = bn(y)
        return nn.relu(y + shortcut)


class ConvBlock(nn.Module):
    """3-conv residual block with a strided conv shortcut
    (reference resnet50_model.py ConvBlock)."""

    kernel_size: int
    filters: tuple
    strides: tuple = (2, 2)

    @nn.compact
    def __call__(self, x, training=False):
        f1, f2, f3 = self.filters

        def bn(y):
            return nn.BatchNorm(
                use_running_average=not training,
                momentum=BATCH_NORM_DECAY,
                epsilon=BATCH_NORM_EPSILON,
            )(y)

        y = nn.Conv(f1, (1, 1), strides=self.strides, use_bias=False)(x)
        y = nn.relu(bn(y))
        y = nn.Conv(
            f2, (self.kernel_size, self.kernel_size), padding="SAME",
            use_bias=False,
        )(y)
        y = nn.relu(bn(y))
        y = nn.Conv(f3, (1, 1), use_bias=False)(y)
        y = bn(y)
        shortcut = nn.Conv(
            f3, (1, 1), strides=self.strides, use_bias=False
        )(x)
        shortcut = bn(shortcut)
        return nn.relu(y + shortcut)


class ResNet50(nn.Module):
    """Full ResNet-50 stack (reference resnet50_subclass.py CustomModel:
    7x7/2 stem, maxpool, stages [3,4,6,3], global average pool, Dense)."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x, training=False):
        x = nn.Conv(
            64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
            use_bias=False, name="conv1",
        )(x)
        x = nn.BatchNorm(
            use_running_average=not training,
            momentum=BATCH_NORM_DECAY,
            epsilon=BATCH_NORM_EPSILON,
        )(x)
        x = nn.relu(x)
        x = nn.max_pool(
            x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)]
        )

        x = ConvBlock(3, (64, 64, 256), strides=(1, 1))(x, training)
        x = IdentityBlock(3, (64, 64, 256))(x, training)
        x = IdentityBlock(3, (64, 64, 256))(x, training)

        x = ConvBlock(3, (128, 128, 512))(x, training)
        for _ in range(3):
            x = IdentityBlock(3, (128, 128, 512))(x, training)

        x = ConvBlock(3, (256, 256, 1024))(x, training)
        for _ in range(5):
            x = IdentityBlock(3, (256, 256, 1024))(x, training)

        x = ConvBlock(3, (512, 512, 2048))(x, training)
        x = IdentityBlock(3, (512, 512, 2048))(x, training)
        x = IdentityBlock(3, (512, 512, 2048))(x, training)

        x = x.mean(axis=(1, 2))  # global average pool
        return nn.Dense(self.num_classes, name="fc1000")(x)
