"""CIFAR-10 VGG-style CNN — rebuild of the reference zoo module
model_zoo/cifar10_functional_api/cifar10_functional_api.py:19-176 (three
conv-BN-relu pairs at 32/64/128 channels with maxpool+dropout between, then
Dense10) as a compact flax module. Includes the reference's
LearningRateScheduler callback (steps 5000/15000 -> 0.1/0.01/0.001,
reference :132-141) and a PredictionOutputsProcessor."""

import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from elasticdl_tpu.api.callbacks import LearningRateScheduler
from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.data.example_codec import decode_example
from elasticdl_tpu.worker.prediction_outputs_processor import (
    BasePredictionOutputsProcessor,
)


class Cifar10Model(nn.Module):
    @nn.compact
    def __call__(self, features, training=False):
        x = features["image"]
        x = x.reshape(x.shape[0], 32, 32, 3)

        def conv_bn_relu(x, ch):
            x = nn.Conv(ch, (3, 3), padding="SAME")(x)
            x = nn.BatchNorm(
                use_running_average=not training, momentum=0.9, epsilon=1e-6
            )(x)
            return nn.relu(x)

        for ch, rate in ((32, 0.2), (64, 0.3), (128, 0.4)):
            x = conv_bn_relu(x, ch)
            x = conv_bn_relu(x, ch)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
            x = nn.Dropout(rate, deterministic=not training)(x)

        x = x.reshape(x.shape[0], -1)
        return nn.Dense(10, name="output")(x)


def custom_model():
    return Cifar10Model()


def loss(labels, predictions):
    labels = labels.reshape(-1)
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(predictions, labels)
    )


def optimizer(lr=0.1):
    return optax.sgd(lr)


def callbacks():
    # traced schedule (compiled into the train step): the reference's
    # python-if absolute-LR schedule (cifar10_functional_api.py:132-141),
    # expressed as multipliers of the base lr=0.1
    def _schedule(model_version):
        return jnp.where(
            model_version < 5000, 1.0,
            jnp.where(model_version < 15000, 0.1, 0.01),
        )

    return [LearningRateScheduler(_schedule)]


def dataset_fn(dataset, mode, _):
    def _parse(record):
        ex = decode_example(record)
        features = {"image": ex["image"].astype(np.float32)}
        if mode == Mode.PREDICTION:
            return features
        return features, ex["label"].astype(np.int32)[0]

    dataset = dataset.map(_parse)
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=1024, seed=0)
    return dataset


def eval_metrics_fn():
    return {
        "accuracy": lambda labels, predictions: (
            np.argmax(predictions, axis=1) == np.asarray(labels).reshape(-1)
        ).astype(np.float32)
    }


def feature_shapes():
    return {"image": (32, 32, 3)}


class PredictionOutputsProcessor(BasePredictionOutputsProcessor):
    """Logs prediction batches (the reference writes them to a MaxCompute
    table when ODPS is configured — cifar10_functional_api.py:178-202; here
    the ODPS sink lives behind data/odps gating)."""

    def process(self, predictions, worker_id):
        logger.info(
            "worker %d predictions: %s", worker_id, np.asarray(predictions)
        )
