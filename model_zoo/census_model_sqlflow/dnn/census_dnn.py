"""Census DNN (SQLFlow feature-column style) — rebuild of reference
model_zoo/census_model_sqlflow/dnn/ (census_feature_column.py:34-52 +
census_functional.py:27-37): numeric columns pass through; each
categorical column is hashed into 64 buckets and embedded to 16 dims
(the feature-column DenseFeatures concat); Dense 16/16 relu + sigmoid
head. The hashing runs host-side in dataset_fn (strings never enter
XLA); the embeddings are in-model."""

import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.data.example_codec import decode_example
from elasticdl_tpu.preprocessing.layers import Hashing

CATEGORICAL_FEATURE_KEYS = [
    "workclass", "education", "marital-status", "occupation",
    "relationship", "race", "sex", "native-country",
]
NUMERIC_FEATURE_KEYS = [
    "age", "capital-gain", "capital-loss", "hours-per-week",
]
LABEL_KEY = "label"

HASH_BUCKETS = 64
EMBEDDING_DIM = 16


class CensusDNN(nn.Module):
    @nn.compact
    def __call__(self, features, training=False):
        parts = [
            features[name].astype(jnp.float32).reshape(-1, 1)
            for name in NUMERIC_FEATURE_KEYS
        ]
        for name in CATEGORICAL_FEATURE_KEYS:
            ids = features[name].astype(jnp.int32).reshape(-1)
            emb = nn.Embed(
                HASH_BUCKETS, EMBEDDING_DIM,
                name="%s_embedding" % name.replace("-", "_"),
            )(ids)
            parts.append(emb)
        x = jnp.concatenate(parts, axis=1)
        x = nn.relu(nn.Dense(16)(x))
        x = nn.relu(nn.Dense(16)(x))
        return nn.sigmoid(nn.Dense(1)(x))


def custom_model():
    return CensusDNN()


def loss(labels, predictions):
    probs = jnp.clip(predictions.reshape(-1), 1e-7, 1 - 1e-7)
    labels = labels.reshape(-1).astype(jnp.float32)
    return -jnp.mean(
        labels * jnp.log(probs) + (1.0 - labels) * jnp.log(1.0 - probs)
    )


def optimizer(lr=0.001):
    return optax.adam(lr)


def dataset_fn(dataset, mode, _):
    hashers = {
        name: Hashing(num_bins=HASH_BUCKETS)
        for name in CATEGORICAL_FEATURE_KEYS
    }

    def _parse(record):
        ex = decode_example(record)
        features = {
            name: np.asarray(ex[name], np.float32).reshape(())
            for name in NUMERIC_FEATURE_KEYS
        }
        for name in CATEGORICAL_FEATURE_KEYS:
            features[name] = np.asarray(
                hashers[name](ex[name]), np.int64
            ).reshape(())
        if mode == Mode.PREDICTION:
            return features
        return features, ex[LABEL_KEY].astype(np.int32).reshape(())

    return dataset.map(_parse)


def eval_metrics_fn():
    return {
        "accuracy": lambda labels, predictions: (
            (np.asarray(predictions).reshape(-1) > 0.5).astype(np.int32)
            == np.asarray(labels).reshape(-1)
        ).astype(np.float32)
    }
