"""SQLFlow transform-op metadata + interpreter — rebuild of the reference
model_zoo/census_model_sqlflow/wide_and_deep/transform_ops.py:13-125.

A SQLFlow `COLUMN` clause compiles to a dataflow of named transform ops
(hash / vocab lookup / bucketize / concat-with-offset / embedding /
array). The reference declared the op metadata and then HAND-WROTE the
execution twice (keras layers + feature columns, ~1,200 LoC of unrolled
codegen); here the metadata is executed directly:

* `topo_sort` orders any op list by its input/output names (the
  reference shipped a hand-topologically-sorted array);
* `execute_host_ops` runs the string/id stages (HASH/LOOKUP/BUCKETIZE/
  CONCAT) host-side with the preprocessing layers — strings never enter
  XLA;
* the EMBEDDING/ARRAY stages are consumed by the flax model, which
  builds its towers from the same metadata (census_wide_and_deep.py).
"""

import itertools
from enum import Enum

import numpy as np

from elasticdl_tpu.preprocessing.layers import (
    ConcatenateWithOffset,
    Discretization,
    Hashing,
    IndexLookup,
)


class TransformOpType(Enum):
    HASH = 1
    BUCKETIZE = 2
    LOOKUP = 3
    EMBEDDING = 4
    CONCAT = 5
    ARRAY = 6


class SchemaInfo(object):
    """(column name, numpy dtype) of one source-table column."""

    def __init__(self, name, dtype):
        self.name = name
        self.dtype = dtype


class TransformOp(object):
    def __init__(self, name, input, output):  # noqa: A002 - reference API
        self.name = name
        self.input = input  # one name or a list of names
        self.output = output
        self.op_type = None

    @property
    def inputs(self):
        return self.input if isinstance(self.input, list) else [self.input]


class Hash(TransformOp):
    def __init__(self, name, input, output, hash_bucket_size):  # noqa: A002
        super().__init__(name, input, output)
        self.op_type = TransformOpType.HASH
        self.hash_bucket_size = hash_bucket_size

    @property
    def num_buckets(self):
        return self.hash_bucket_size


class Vocabularize(TransformOp):
    def __init__(self, name, input, output, vocabulary_list=None,  # noqa: A002
                 vocabulary_file=None):
        super().__init__(name, input, output)
        self.op_type = TransformOpType.LOOKUP
        self.vocabulary_list = vocabulary_list
        self.vocabulary_file = vocabulary_file

    @property
    def num_buckets(self):
        # + 1 OOV token (IndexLookup default)
        if self.vocabulary_list is not None:
            return len(self.vocabulary_list) + 1
        with open(self.vocabulary_file) as f:
            return sum(1 for line in f if line.strip()) + 1


class Bucketize(TransformOp):
    def __init__(self, name, input, output, num_buckets=None,  # noqa: A002
                 boundaries=None):
        super().__init__(name, input, output)
        self.op_type = TransformOpType.BUCKETIZE
        self._num_buckets = num_buckets
        self.boundaries = boundaries

    @property
    def num_buckets(self):
        if self._num_buckets is not None:
            return self._num_buckets
        return len(self.boundaries) + 1


class Concat(TransformOp):
    def __init__(self, name, input, output, id_offsets):  # noqa: A002
        super().__init__(name, input, output)
        self.op_type = TransformOpType.CONCAT
        self.id_offsets = id_offsets


class Embedding(TransformOp):
    def __init__(self, name, input, output, input_dim, output_dim):  # noqa: A002
        super().__init__(name, input, output)
        self.op_type = TransformOpType.EMBEDDING
        self.input_dim = input_dim
        self.output_dim = output_dim


class Array(TransformOp):
    """Collect several outputs into one ordered list (the towers)."""

    def __init__(self, name, input, output):  # noqa: A002
        super().__init__(name, input, output)
        self.op_type = TransformOpType.ARRAY


def id_offsets_from_bucket_nums(num_buckets):
    """[8, 7, 6] -> [0, 8, 15]: each member of a Concat group gets its own
    id range (reference _get_id_offsets_from_dependency_bucket_num)."""
    return list(itertools.accumulate([0] + list(num_buckets[:-1])))


def topo_sort(ops, source_names):
    """Order ops so every op runs after its producers (Kahn). The inputs
    available at the start are the source-table columns. Raises on cycles
    or references to names nothing produces."""
    produced = set(source_names)
    remaining = list(ops)
    ordered = []
    while remaining:
        ready = [
            op for op in remaining
            if all(i in produced for i in op.inputs)
        ]
        if not ready:
            missing = {
                i for op in remaining for i in op.inputs
            } - produced - {op.output for op in remaining}
            raise ValueError(
                "transform graph is cyclic or references unknown inputs: "
                "unresolvable ops %s%s"
                % (
                    [op.name for op in remaining],
                    (", undefined inputs %s" % sorted(missing))
                    if missing else "",
                )
            )
        for op in ready:
            ordered.append(op)
            produced.add(op.output)
            remaining.remove(op)
    return ordered


def _host_layer(op):
    if op.op_type == TransformOpType.HASH:
        return Hashing(num_bins=op.hash_bucket_size)
    if op.op_type == TransformOpType.LOOKUP:
        return IndexLookup(
            vocabulary=op.vocabulary_list or op.vocabulary_file
        )
    if op.op_type == TransformOpType.BUCKETIZE:
        if op.boundaries is None:
            raise ValueError(
                "Bucketize %r needs boundaries for host execution" % op.name
            )
        return Discretization(bins=op.boundaries)
    if op.op_type == TransformOpType.CONCAT:
        return ConcatenateWithOffset(op.id_offsets)
    raise ValueError("%r is not a host-stage op" % op)


class HostOpExecutor(object):
    """Compiled form of the host stages: layers (vocab tables, hash
    functions, bucket arrays) are built ONCE here, then reused for every
    example — dataset_fn runs this per record, so per-record layer
    construction (re-reading vocabulary files etc.) is the difference
    between O(1) and O(dataset) setup work."""

    def __init__(self, ops):
        self._ops = [
            (op, _host_layer(op))
            for op in ops
            if op.op_type not in (
                TransformOpType.EMBEDDING, TransformOpType.ARRAY
            )
        ]

    def __call__(self, example):
        """One example dict -> {name: np.ndarray} including the source
        columns; EMBEDDING/ARRAY stages live in the model."""
        values = dict(example)
        for op, layer in self._ops:
            if op.op_type == TransformOpType.CONCAT:
                parts = [
                    np.asarray(values[name]).reshape(-1)
                    for name in op.inputs
                ]
                values[op.output] = layer(parts)
            else:
                value = values[op.input]
                if op.op_type == TransformOpType.BUCKETIZE:
                    value = np.asarray(value, np.float32)
                values[op.output] = np.asarray(layer(value)).reshape(-1)
        return values


def execute_host_ops(ops, example):
    """One-shot convenience over HostOpExecutor (tests); hot paths build
    the executor once instead."""
    return HostOpExecutor(ops)(example)
