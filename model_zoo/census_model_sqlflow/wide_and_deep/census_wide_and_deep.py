"""Census Wide&Deep driven ENTIRELY by the SQLFlow transform-op graph —
rebuild of reference model_zoo/census_model_sqlflow/wide_and_deep/
(wide_deep_subclass_keras.py:55-71 model math; the transform execution
the reference unrolled by hand four times, ~1,200 LoC of generated-style
keras/feature-column code, is here ONE interpreter over the op metadata):

* dataset_fn topo-sorts FEATURE_TRANSFORM_INFO and runs the host stages
  (hash/lookup/bucketize/concat-with-offset) per example;
* the flax model walks the same graph's EMBEDDING/ARRAY stages to build
  its towers — Embedding ops become nn.Embed(input_dim, output_dim),
  Array ops define which embeddings feed the wide vs deep tower;
* model math parity: per-group embedding-sum, deep Dense[16, 8, 4],
  concat(wide, deep) -> reduce_sum -> logits, sigmoid probs.
"""

import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.data.example_codec import decode_example
from elasticdl_tpu.training.metrics import AUC
from model_zoo.census_model_sqlflow import feature_configs as cfg
from model_zoo.census_model_sqlflow.transform_ops import (
    HostOpExecutor,
    TransformOpType,
    topo_sort,
)

_SOURCE_COLUMNS = [s.name for s in cfg.INPUT_SCHEMAS]
_SORTED_OPS = topo_sort(cfg.FEATURE_TRANSFORM_INFO, _SOURCE_COLUMNS)
_OPS_BY_OUTPUT = {op.output: op for op in _SORTED_OPS}
# layers built once (vocab tables etc.), reused for every record
_EXECUTOR = HostOpExecutor(_SORTED_OPS)


class SQLFlowWideDeep(nn.Module):
    """Towers generated from the transform graph, not hand-written."""

    hidden_units: tuple = (16, 8, 4)

    @nn.compact
    def __call__(self, features, training=False):
        def run_array(array_name):
            """An Array op -> list of [B, dim] embedded-sum tensors."""
            outputs = []
            for emb_name in _OPS_BY_OUTPUT[array_name].inputs:
                emb = _OPS_BY_OUTPUT[emb_name]
                assert emb.op_type == TransformOpType.EMBEDDING
                ids = features[emb.input].astype(jnp.int32)  # [B, n_feat]
                vectors = nn.Embed(
                    emb.input_dim, emb.output_dim, name=emb.name
                )(ids)
                outputs.append(jnp.sum(vectors, axis=1))
            return outputs

        wide = jnp.concatenate(run_array("wide_embeddings"), axis=-1)
        deep = jnp.concatenate(run_array("deep_embeddings"), axis=-1)
        for units in self.hidden_units:
            deep = nn.Dense(units)(deep)
        concat = jnp.concatenate([wide, deep], axis=1)
        logits = jnp.sum(concat, axis=1, keepdims=True)
        probs = jnp.reshape(nn.sigmoid(logits), (-1,))
        return {"logits": logits, "probs": probs}


def custom_model():
    return SQLFlowWideDeep()


def loss(labels, predictions):
    logits = predictions["logits"].reshape(-1)
    labels = labels.reshape(-1).astype(jnp.float32)
    return jnp.mean(optax.sigmoid_binary_cross_entropy(logits, labels))


def optimizer(lr=0.001):
    return optax.adam(lr)


def dataset_fn(dataset, mode, _):
    group_names = sorted(
        {
            _OPS_BY_OUTPUT[e].input
            for out in cfg.TRANSFORM_OUTPUTS
            for e in _OPS_BY_OUTPUT[out].inputs
        }
    )

    def _parse(record):
        ex = decode_example(record)
        values = _EXECUTOR(ex)
        features = {
            name: values[name].astype(np.int64) for name in group_names
        }
        if mode == Mode.PREDICTION:
            return features
        return features, ex[cfg.LABEL_KEY].astype(np.int32).reshape(())

    return dataset.map(_parse)


def eval_metrics_fn():
    return {
        "logits": {
            "accuracy": lambda labels, predictions: (
                (np.asarray(predictions).reshape(-1) > 0.0).astype(np.int32)
                == np.asarray(labels).reshape(-1)
            ).astype(np.float32)
        },
        "probs": {"auc": AUC()},
    }


def feature_shapes():
    return {
        op.input: (len(_OPS_BY_OUTPUT[op.input].inputs),)
        for out in cfg.TRANSFORM_OUTPUTS
        for e in _OPS_BY_OUTPUT[out].inputs
        for op in [_OPS_BY_OUTPUT[e]]
    }
