"""Census wide&deep transform config "as parsed from SQLFlow" — rebuild
of reference model_zoo/census_model_sqlflow/wide_and_deep/
feature_configs.py:31-268 (same public census-income vocabularies/
boundaries — they ARE the dataset schema — same three feature groups and
tower wiring). Column names follow the raw census fixture
(data/recordio_gen.gen_census_raw), i.e. the source table's columns.

Unlike the reference, the op list here is NOT hand-topologically-sorted:
census_wide_and_deep.py sorts it with transform_ops.topo_sort, which is
what a real COLUMN-clause compiler must do anyway.
"""

from model_zoo.census_model_sqlflow.transform_ops import (
    Array,
    Bucketize,
    Concat,
    Embedding,
    Hash,
    SchemaInfo,
    Vocabularize,
    id_offsets_from_bucket_nums,
)

WORK_CLASS_VOCABULARY = [
    "Private", "Self-emp-not-inc", "Self-emp-inc", "Federal-gov",
    "Local-gov", "State-gov", "Without-pay", "Never-worked",
]
MARITAL_STATUS_VOCABULARY = [
    "Married-civ-spouse", "Divorced", "Never-married", "Separated",
    "Widowed", "Married-spouse-absent", "Married-AF-spouse",
]
RELATION_SHIP_VOCABULARY = [
    "Wife", "Own-child", "Husband", "Not-in-family", "Other-relative",
    "Unmarried",
]
RACE_VOCABULARY = [
    "White", "Asian-Pac-Islander", "Amer-Indian-Eskimo", "Other", "Black",
]
SEX_VOCABULARY = ["Female", "Male"]

AGE_BOUNDARIES = [0, 20, 40, 60, 80]
CAPITAL_GAIN_BOUNDARIES = [6000, 6500, 7000, 7500, 8000]
CAPITAL_LOSS_BOUNDARIES = [2000, 2500, 3000, 3500, 4000]
HOURS_BOUNDARIES = [10, 20, 30, 40, 50, 60]

LABEL_KEY = "label"

education_hash = Hash("education_hash", "education", "education_hash", 30)
occupation_hash = Hash("occupation_hash", "occupation", "occupation_hash",
                       30)
native_country_hash = Hash(
    "native_country_hash", "native-country", "native_country_hash", 100
)

workclass_lookup = Vocabularize(
    "workclass_lookup", "workclass", "workclass_lookup",
    vocabulary_list=WORK_CLASS_VOCABULARY,
)
marital_status_lookup = Vocabularize(
    "marital_status_lookup", "marital-status", "marital_status_lookup",
    vocabulary_list=MARITAL_STATUS_VOCABULARY,
)
relationship_lookup = Vocabularize(
    "relationship_lookup", "relationship", "relationship_lookup",
    vocabulary_list=RELATION_SHIP_VOCABULARY,
)
race_lookup = Vocabularize(
    "race_lookup", "race", "race_lookup", vocabulary_list=RACE_VOCABULARY
)
sex_lookup = Vocabularize(
    "sex_lookup", "sex", "sex_lookup", vocabulary_list=SEX_VOCABULARY
)

age_bucketize = Bucketize(
    "age_bucketize", "age", "age_bucketize", boundaries=AGE_BOUNDARIES
)
capital_gain_bucketize = Bucketize(
    "capital_gain_bucketize", "capital-gain", "capital_gain_bucketize",
    boundaries=CAPITAL_GAIN_BOUNDARIES,
)
capital_loss_bucketize = Bucketize(
    "capital_loss_bucketize", "capital-loss", "capital_loss_bucketize",
    boundaries=CAPITAL_LOSS_BOUNDARIES,
)
hours_per_week_bucketize = Bucketize(
    "hours_per_week_bucketize", "hours-per-week",
    "hours_per_week_bucketize", boundaries=HOURS_BOUNDARIES,
)

_GROUP1_MEMBERS = [
    workclass_lookup, hours_per_week_bucketize, capital_gain_bucketize,
    capital_loss_bucketize,
]
_GROUP2_MEMBERS = [
    education_hash, marital_status_lookup, relationship_lookup,
    occupation_hash,
]
_GROUP3_MEMBERS = [
    age_bucketize, sex_lookup, race_lookup, native_country_hash,
]


def _concat_group(name, members):
    return Concat(
        name,
        [m.output for m in members],
        name,
        id_offsets=id_offsets_from_bucket_nums(
            [m.num_buckets for m in members]
        ),
    )


def _group_dim(members):
    return sum(m.num_buckets for m in members)


group1 = _concat_group("group1", _GROUP1_MEMBERS)
group2 = _concat_group("group2", _GROUP2_MEMBERS)
group3 = _concat_group("group3", _GROUP3_MEMBERS)

group1_embedding_wide = Embedding(
    "group1_embedding_wide", "group1", "group1_embedding_wide",
    input_dim=_group_dim(_GROUP1_MEMBERS), output_dim=1,
)
group2_embedding_wide = Embedding(
    "group2_embedding_wide", "group2", "group2_embedding_wide",
    input_dim=_group_dim(_GROUP2_MEMBERS), output_dim=1,
)
group1_embedding_deep = Embedding(
    "group1_embedding_deep", "group1", "group1_embedding_deep",
    input_dim=_group_dim(_GROUP1_MEMBERS), output_dim=8,
)
group2_embedding_deep = Embedding(
    "group2_embedding_deep", "group2", "group2_embedding_deep",
    input_dim=_group_dim(_GROUP2_MEMBERS), output_dim=8,
)
group3_embedding_deep = Embedding(
    "group3_embedding_deep", "group3", "group3_embedding_deep",
    input_dim=_group_dim(_GROUP3_MEMBERS), output_dim=8,
)

wide_embeddings = Array(
    "wide_embeddings",
    ["group1_embedding_wide", "group2_embedding_wide"],
    "wide_embeddings",
)
deep_embeddings = Array(
    "deep_embeddings",
    [
        "group1_embedding_deep", "group2_embedding_deep",
        "group3_embedding_deep",
    ],
    "deep_embeddings",
)

TRANSFORM_OUTPUTS = ["wide_embeddings", "deep_embeddings"]

# Deliberately NOT in execution order (reference shipped it pre-sorted;
# the consumer topo-sorts).
FEATURE_TRANSFORM_INFO = [
    wide_embeddings,
    deep_embeddings,
    group1, group2, group3,
    group1_embedding_wide, group2_embedding_wide,
    group1_embedding_deep, group2_embedding_deep, group3_embedding_deep,
    education_hash, occupation_hash, native_country_hash,
    workclass_lookup, marital_status_lookup, relationship_lookup,
    race_lookup, sex_lookup,
    age_bucketize, capital_gain_bucketize, capital_loss_bucketize,
    hours_per_week_bucketize,
]

import numpy as np  # noqa: E402  (dtype constants for the schema)

INPUT_SCHEMAS = [
    SchemaInfo("education", np.str_),
    SchemaInfo("occupation", np.str_),
    SchemaInfo("native-country", np.str_),
    SchemaInfo("workclass", np.str_),
    SchemaInfo("marital-status", np.str_),
    SchemaInfo("relationship", np.str_),
    SchemaInfo("race", np.str_),
    SchemaInfo("sex", np.str_),
    SchemaInfo("age", np.float32),
    SchemaInfo("capital-gain", np.float32),
    SchemaInfo("capital-loss", np.float32),
    SchemaInfo("hours-per-week", np.float32),
]
