"""BERT-class bidirectional encoder with a masked-LM objective — the
"BERT-base config" scale target SURVEY.md §7 stage 8 reserves (the
reference zoo tops out at ResNet50 and has no sequence model at all).

Same zoo spec surface as every family. The encoder reuses
transformer_lm's Block with causal=False, so attention dispatch (flash /
blockwise / ring over `sp`), Megatron TP annotations, and the bf16
compute knob live in ONE place.

Masking (dataset_fn, host-side): 15% of positions are targets; of those
80% -> [MASK], 10% -> random token, 10% -> unchanged — the standard BERT
recipe, STATIC per record (positions derive from the record's content,
original-BERT style: every epoch re-masks a record identically, but
distinct records mask independently). [MASK] is a RESERVED id one past
the data vocabulary: the model's embedding table has vocab_size + 1
rows, so a genuine token can never collide with the mask. Labels carry
the ORIGINAL token at target positions and IGNORE_LABEL elsewhere; the
loss averages cross-entropy over target positions only.
"""

import zlib

import numpy as np

import jax.numpy as jnp
import optax
from flax import linen as nn

from elasticdl_tpu.common.constants import Mode
from elasticdl_tpu.data.example_codec import decode_example
from elasticdl_tpu.ops.attention import packed_positions
from model_zoo.transformer_lm.transformer_lm import (
    Block,
    _tp_dense_init,
    resolve_dtype,
)

IGNORE_LABEL = -1
MASK_PROB = 0.15


class BertEncoder(nn.Module):
    # bidirectional encoder: api/generation.py refuses to decode it.
    # Deliberately a plain class attribute (NOT a dataclass field) so
    # model_params cannot override it out of sync with the hard-coded
    # causal=False attention below.
    causal = False
    vocab_size: int = 256  # DATA vocabulary; [MASK] gets one extra row
    seq_len: int = 128
    embed_dim: int = 128
    num_heads: int = 4
    num_layers: int = 2
    dtype: object = None
    attn_impl: str = "auto"
    tp_shard: bool = True
    lora_rank: int = 0  # attention-LoRA adapters (0 = off)
    lora_alpha: float = 16.0
    attn_window: int = 0  # two-sided sliding window; 0 = full

    @nn.compact
    def __call__(self, features, training=False):
        tokens = features["tokens"]
        # sequence packing (same contract as transformer_lm): attention
        # confined to same-id runs, learned positions restart per run
        segments = features.get("segment_ids")
        positions = None
        if segments is not None:
            segments = jnp.asarray(segments, jnp.int32)
            positions = packed_positions(segments)
        x = nn.Embed(
            self.vocab_size + 1, self.embed_dim, dtype=self.dtype,
            name="wte",
        )(tokens)
        pos = nn.Embed(
            self.seq_len, self.embed_dim, dtype=self.dtype, name="wpe"
        )(
            positions if positions is not None
            else jnp.arange(tokens.shape[1])[None, :]
        )
        x = x + pos
        head_dim = self.embed_dim // self.num_heads
        for i in range(self.num_layers):
            x = Block(
                self.num_heads, head_dim, dtype=self.dtype,
                attn_impl=self.attn_impl, tp_shard=self.tp_shard,
                causal=False, window=self.attn_window,
                lora_rank=self.lora_rank, lora_alpha=self.lora_alpha,
                name="layer_%d" % i,
            )(x, training, segments=segments, positions=positions)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        # MLM head: transform + vocab projection (BERT's cls/predictions)
        x = nn.gelu(
            nn.Dense(self.embed_dim, dtype=self.dtype, name="mlm_dense")(x)
        )
        x = nn.LayerNorm(dtype=self.dtype, name="mlm_ln")(x)
        logits = nn.Dense(
            self.vocab_size, use_bias=True, dtype=self.dtype,
            name="mlm_head",
            kernel_init=(
                _tp_dense_init(1) if self.tp_shard
                else nn.initializers.lecun_normal()
            ),
        )(x)
        return logits.astype(jnp.float32)


def custom_model(**kwargs):
    return BertEncoder(**resolve_dtype(kwargs, "bert"))


def loss(labels, predictions, sample_weights=None):
    """Cross-entropy over masked positions only; labels == IGNORE_LABEL
    elsewhere."""
    mask = (labels != IGNORE_LABEL).astype(jnp.float32)
    safe_labels = jnp.maximum(labels, 0)
    ce = optax.softmax_cross_entropy_with_integer_labels(
        predictions, safe_labels
    ) * mask
    if sample_weights is not None:
        ce = ce * sample_weights[:, None]
        mask = mask * sample_weights[:, None]
    return jnp.sum(ce) / jnp.maximum(jnp.sum(mask), 1.0)


def optimizer(lr=1e-4):
    return optax.adamw(lr, weight_decay=0.01)


def _mask_tokens(tokens, vocab_size, rng):
    """The 80/10/10 BERT masking recipe over one sequence. [MASK] is the
    reserved id `vocab_size` (one past the data vocabulary); random
    replacements draw from the DATA vocabulary only."""
    mask_id = vocab_size
    targets = rng.rand(tokens.size) < MASK_PROB
    labels = np.where(targets, tokens, IGNORE_LABEL).astype(np.int32)
    roll = rng.rand(tokens.size)
    masked = tokens.copy()
    masked[targets & (roll < 0.8)] = mask_id
    rand_pos = targets & (roll >= 0.8) & (roll < 0.9)
    masked[rand_pos] = rng.randint(
        0, vocab_size, size=int(rand_pos.sum())
    )
    return masked, labels


def dataset_fn(dataset, mode, metadata):
    def _parse(record):
        ex = decode_example(record)
        tokens = ex["tokens"].astype(np.int32)
        if mode == Mode.PREDICTION:
            return {"tokens": tokens}
        vocab = int(ex.get("vocab_size", np.array(256)))
        # static masking seeded by the record's CONTENT: deterministic
        # per record, independent across records (constant seeds would
        # replay one mask stream over every task — original BERT's
        # static masking, done right)
        rng = np.random.RandomState(
            zlib.crc32(tokens.tobytes()) & 0x7FFFFFFF
        )
        masked, labels = _mask_tokens(tokens, vocab, rng)
        return {"tokens": masked}, labels

    dataset = dataset.map(_parse)
    if mode == Mode.TRAINING:
        dataset = dataset.shuffle(buffer_size=1024, seed=0)
    return dataset


def eval_metrics_fn():
    def masked_accuracy(labels, predictions):
        labels = np.asarray(labels)
        preds = np.argmax(np.asarray(predictions), axis=-1)
        valid = labels != IGNORE_LABEL
        per_example = []
        for row_pred, row_label, row_valid in zip(preds, labels, valid):
            n = row_valid.sum()
            if n == 0:
                continue  # nothing masked: no opinion, don't inflate
            per_example.append(
                float((row_pred[row_valid] == row_label[row_valid]).sum())
                / n
            )
        return np.asarray(per_example, np.float32)

    return {"masked_token_accuracy": masked_accuracy}


def feature_shapes(seq_len=128):
    return {"tokens": (seq_len,)}
