"""Headline benchmark: the framework's benchable families on real
hardware. The default run is a SUITE — one JSON line per family
(transformer flagship first, then moe/bert/dlrm/decode/decode-int8-KV),
closed by a flagship summary line carrying every family's numbers:
    {"metric": ..., "value": N, ..., "suite": true, "families": {...}}
`EDL_BENCH_MODEL=<family>` runs exactly one family (one JSON line), the
mode every `scripts/hw_session.py` step uses.

The reference publishes no hardware throughput numbers (BASELINE.md), so
the baselines are *established* here: `vs_baseline` is the ratio to the
committed same-config hardware record (BENCH_BASELINE.json for the
flagship, BENCH_BASELINE_<FAMILY>.json otherwise), 1.0 when a TPU run
has no comparable record yet, and **null whenever the run fell back to
CPU** — a wedged-tunnel round must be unmistakable from the artifact
alone, never read as "no regression".

Robustness contract (VERDICT.md round-1 item #1): the TPU backend in this
environment is a tunneled PJRT plugin that can crash or hang on init. The
accelerator is therefore probed in a *subprocess* with a hard deadline; on
probe failure the bench falls back to CPU (clearly tagged
"platform": "cpu") rather than crashing or hanging, so the driver always
records a JSON line with rc=0.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

# Persistent XLA compilation cache: the flagship configs cost 20-40 s of
# compile each through the tunneled backend, and the tunnel's windows are
# short (TUNNEL_LOG.md) — a cache hit turns a re-run inside the same
# window (or the driver's round-end run after hw_session) into pure
# measurement. Env-set before any jax import so the probe subprocess and
# in-process bench both inherit it; harmless on backends that can't
# serialize executables (jax just skips the cache).
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache"))

# Peak bf16 matmul FLOP/s per chip, by TPU generation (public specs).
_PEAK_FLOPS = {
    "v2": 45e12,
    "v3": 123e12,
    "v4": 275e12,
    "v5e": 197e12,
    "v5litepod": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}

_PROBE_CODE = r"""
import jax, jax.numpy as jnp
x = jnp.ones((128, 128), jnp.bfloat16)
(x @ x).block_until_ready()
d = jax.devices()[0]
# jax_platforms distinguishes "host has no TPU plugin at all" from "the
# plugin is configured but its init failed and jax fell back to CPU"
# (the axon sitecustomize force-sets jax_platforms="axon,cpu").
platforms = getattr(jax.config, "jax_platforms", "") or ""
print("PROBE_OK|%s|%s|%s" % (jax.default_backend(),
                             getattr(d, "device_kind", "") or "",
                             platforms))
"""


def _env_float(value, env_key, default, floor):
    """Explicit value, else env var (malformed values warn and fall back
    to the default — the bench's rc=0 contract forbids crashing on bad
    config), floored to keep the retry loop sane."""
    if value is None:
        raw = os.environ.get(env_key, "")
        try:
            value = float(raw) if raw else default
        except ValueError:
            sys.stderr.write("bench: ignoring bad %s=%r\n" % (env_key, raw))
            value = default
    return max(float(value), floor)


def _probe_once(timeout_s):
    """One bounded child-process attempt at TPU backend init.

    Returns (status, backend, device_kind): status "ok" with a live
    non-CPU backend, "cpu_only" when the probe definitively found only a
    CPU backend (no point retrying), or "fail" on crash/timeout (worth
    retrying — the tunnel flaps). The child is killed on timeout, so a
    hung PJRT tunnel cannot hang the bench itself.
    """
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_CODE],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        sys.stderr.write("bench: accelerator probe attempt timed out "
                         "after %.0fs\n" % timeout_s)
        return "fail", None, None
    except Exception as e:  # noqa: BLE001
        sys.stderr.write("bench: accelerator probe error: %r\n" % (e,))
        return "fail", None, None
    for line in (r.stdout or "").splitlines():
        if line.startswith("PROBE_OK|"):
            parts = line.split("|", 3)
            backend, kind = parts[1], parts[2]
            platforms = parts[3] if len(parts) > 3 else ""
            if backend != "cpu":
                return "ok", backend, kind
            non_cpu_configured = any(
                p.strip() and p.strip() != "cpu"
                for p in platforms.split(","))
            if non_cpu_configured:
                # A TPU plugin is configured but init fell back to CPU:
                # that's the flapping tunnel, not a CPU-only host.
                sys.stderr.write(
                    "bench: probe fell back to CPU (platforms=%r); "
                    "retrying\n" % platforms)
                return "fail", None, None
            sys.stderr.write("bench: probe found only CPU backend\n")
            return "cpu_only", None, None
    tail = (r.stderr or "")[-2000:]
    sys.stderr.write("bench: accelerator probe attempt failed (rc=%s):\n%s\n"
                     % (r.returncode, tail))
    return "fail", None, None


def probe_accelerator(deadline_s, attempt_s=None, retry_pause_s=None):
    """Probe the accelerator repeatedly within a total deadline.

    The round-2 failure mode was a single attempt pinned to the full
    deadline: one wedged tunnel burned all 300 s and the bench fell back
    to CPU even though the tunnel flaps back within a minute or two. So:
    short bounded attempts (default 75 s each — healthy init over the
    tunnel is ~10-40 s), retried until the deadline, with a short pause
    after fast failures (crash-on-init) so a flapping plugin gets time to
    come back. When the remaining budget is too small for a pause plus
    attempt, the pause is skipped so a final short attempt still runs. A
    definitive CPU-only answer (host has no TPU plugin at all) stops the
    retries immediately.
    """
    attempt_s = _env_float(attempt_s, "EDL_BENCH_PROBE_ATTEMPT", 75.0, 5.0)
    retry_pause_s = _env_float(retry_pause_s, "EDL_BENCH_PROBE_PAUSE",
                               10.0, 0.0)
    deadline = time.monotonic() + deadline_s
    attempt = 0
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 1.0:
            sys.stderr.write(
                "bench: accelerator probe gave up after %d attempts / "
                "%.0fs deadline\n" % (attempt, deadline_s))
            return None, None
        attempt += 1
        t0 = time.monotonic()
        status, backend, kind = _probe_once(min(attempt_s, remaining))
        if status == "ok":
            return backend, kind
        if status == "cpu_only":
            return None, None
        # Fast failure (crash, not hang): pause so a flapping tunnel can
        # recover — unless that pause would eat the budget for a last
        # real attempt, in which case retry immediately.
        elapsed = time.monotonic() - t0
        if elapsed < attempt_s - 1.0:
            budget_after_pause = deadline - time.monotonic() - retry_pause_s
            if budget_after_pause > 5.0:
                time.sleep(retry_pause_s)


def require_accelerator_or_exit(deadline_s=None):
    """Shared guard for TPU-only measurement scripts (profile_step,
    bench_collectives): fail FAST on a wedged tunnel via the bounded
    subprocess probe instead of hanging until the caller's outer
    timeout — a wedged hw_session step then costs the probe deadline
    (EDL_BENCH_PROBE_TIMEOUT, default 300 s like the bench itself),
    not its 30-min bound. A deliberate CPU-FIRST run (JAX_PLATFORMS
    leading with "cpu", e.g. the virtual 8-device mesh) skips the
    probe entirely; a fallback list like "axon,cpu" does not — its
    jax init still hangs on the wedged tunnel, which is exactly what
    the guard is for."""
    platforms = os.environ.get("JAX_PLATFORMS", "")
    if platforms.split(",")[0].strip() == "cpu":
        return
    if deadline_s is None:
        deadline_s = _env_float(None, "EDL_BENCH_PROBE_TIMEOUT",
                                300.0, 5.0)
    backend, _ = probe_accelerator(deadline_s)
    if backend is None:
        sys.stderr.write("no accelerator within %.0fs; aborting "
                         "(tunnel wedged?)\n" % deadline_s)
        sys.exit(1)


def _peak_flops(device_kind):
    kind = (device_kind or "").lower().replace("tpu", "").strip(" -_")
    for key, peak in _PEAK_FLOPS.items():
        if key in kind:
            return peak
    # tunneled plugins may hide the kind; fall back to the generation
    # advertised by the tunnel env, else assume v5e (this pool's chip)
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e").lower()
    return _PEAK_FLOPS.get(gen, _PEAK_FLOPS["v5e"])


def transformer_flops_per_step(batch, seq, d_model, n_layers, vocab):
    """Matmul FLOPs for one fwd+bwd train step (backward = 2x forward).

    Per token forward: qkv (2*d*3d) + attn proj (2*d*d) + MLP
    (2*d*4d in + 2*4d*d out) = 24*d^2; attention scores+values add
    4*seq*d per token per layer; LM head 2*d*vocab.
    """
    per_token_layer = 24 * d_model * d_model + 4 * seq * d_model
    fwd = batch * seq * (n_layers * per_token_layer + 2 * d_model * vocab)
    return 3 * fwd


def _measure_steps(trainer, state, batch, iters, warmup):
    """Timed compiled-step loop with fetch-forced sync (see
    common/timing_utils.fetch_sync; block_until_ready can return early
    over tunneled PJRT plugins). Returns (step_time_s, last_loss)."""
    import numpy as np

    from elasticdl_tpu.common.timing_utils import fetch_sync

    for _ in range(warmup):
        state, loss = trainer.train_step(state, batch)
    fetch_sync(state.params)
    t0 = time.perf_counter()
    for _ in range(iters):
        state, loss = trainer.train_step(state, batch)
    fetch_sync(state.params)
    dt = (time.perf_counter() - t0) / iters
    assert np.isfinite(float(loss)), "non-finite loss in bench"
    return dt, float(loss)


def apply_extra_params(cfg, batch_size, on_tpu):
    """The A/B channel shared by the transformer and decode benches:
    EDL_BENCH_EXTRA_PARAMS ("fused_head=True; seq_len=2048") model knobs
    and EDL_BENCH_BATCH. Shape-affecting keys merge INTO cfg so the
    synthetic batch follows (and vs_baseline correctly degrades to 1.0
    on config mismatch); the rest ride as model params. Returns
    (params_dict, extra_dict, batch_size); mutates cfg in place."""
    from elasticdl_tpu.common.model_utils import get_dict_from_params_str

    extra = get_dict_from_params_str(
        os.environ.get("EDL_BENCH_EXTRA_PARAMS", "")
    )
    cfg.update({k: v for k, v in extra.items() if k in cfg})
    # warn-and-fall-back on malformed values (the bench's rc=0 contract
    # forbids crashing on bad config — see _env_float)
    batch_size = int(_env_float(None, "EDL_BENCH_BATCH", batch_size, 1))
    params = dict(cfg)
    if on_tpu:
        params["dtype"] = "bf16"
    params.update({k: v for k, v in extra.items() if k not in cfg})
    # the reported extra_params records EVERY ambient override, incl. a
    # bare EDL_BENCH_BATCH (report-only — batch_size is not a model
    # kwarg), so non-default runs are self-identifying and hw_session's
    # baseline guard can refuse them
    reported = dict(extra)
    if "EDL_BENCH_BATCH" in os.environ:
        reported["batch_size"] = batch_size
    return params, reported, batch_size


def run_transformer_bench(on_tpu):
    import numpy as np

    from model_zoo.transformer_lm import transformer_lm as zoo

    if on_tpu:
        # d=1024/heads=8 -> head_dim 128: the flash kernel's 128-lane
        # tiles run unpadded, and the larger matmuls roughly double MFU
        # vs the previous d=512 flagship (0.34 vs 0.16 measured on v5e).
        cfg = dict(vocab_size=32000, seq_len=1024, embed_dim=1024,
                   num_heads=8, num_layers=8)
        batch_size, iters, warmup = 32, 30, 5
    else:
        # CPU fallback: same code path, toy size (the number is tagged
        # "platform": "cpu" and is not a hardware claim)
        cfg = dict(vocab_size=1024, seq_len=128, embed_dim=128,
                   num_heads=4, num_layers=2)
        batch_size, iters, warmup = 8, 10, 2

    from elasticdl_tpu.common.model_utils import format_params_str

    params, extra, batch_size = apply_extra_params(cfg, batch_size, on_tpu)
    # packed=N (bench knob, not a model kwarg): train on rows carrying
    # N packed segments each — measures the segment-mask cost of the
    # sequence-packing path on the same shapes
    packed = int(params.pop("packed", 0))
    model_params = format_params_str(params)

    rng = np.random.RandomState(0)
    tokens = rng.randint(
        0, cfg["vocab_size"], size=(batch_size, cfg["seq_len"] + 1)
    ).astype(np.int32)
    features = {"tokens": tokens[:, :-1]}
    if packed:
        seg = np.minimum(
            np.arange(cfg["seq_len"]) * packed // cfg["seq_len"],
            packed - 1,
        )
        features["segment_ids"] = np.broadcast_to(
            seg.astype(np.int32), (batch_size, cfg["seq_len"])
        ).copy()
    batch = (features, tokens[:, 1:])
    step_time, n_chips, dev, platform, n_params = _run_zoo_bench(
        zoo, batch, iters, warmup, model_params=model_params
    )
    tokens_per_sec = batch_size * cfg["seq_len"] / step_time
    flops = transformer_flops_per_step(
        batch_size, cfg["seq_len"], cfg["embed_dim"], cfg["num_layers"],
        cfg["vocab_size"],
    )
    if platform == "cpu":
        mfu = None
    else:
        mfu = round(flops / step_time / (_peak_flops(
            getattr(dev, "device_kind", "")) * n_chips), 4)
    return {
        "metric": "transformer_lm_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec / n_chips, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": None,  # filled by _apply_vs_baseline
        "mfu": mfu,
        "samples_per_sec_per_chip": round(
            batch_size / step_time / n_chips, 2),
        "step_time_ms": round(step_time * 1e3, 2),
        "platform": platform,
        "device_kind": getattr(dev, "device_kind", "") or platform,
        "params_m": round(n_params / 1e6, 1),
        "config": cfg,
        "extra_params": extra or None,
        "batch_size": batch_size,
    }


def _run_zoo_bench(zoo, batch, iters, warmup, model_params=""):
    """Shared setup + measurement for every bench target: spec -> mesh
    -> Trainer -> init -> pre-staged batch (the benchmark measures the
    compiled step; a real input pipeline double-buffers host->device
    transfers behind it) -> timed steps. Returns
    (step_time_s, n_chips, device, platform, n_params)."""
    import jax
    import numpy as np

    from elasticdl_tpu.common.model_utils import load_model_spec_from_module
    from elasticdl_tpu.parallel import mesh as mesh_lib
    from elasticdl_tpu.training.trainer import Trainer

    spec = load_model_spec_from_module(zoo)
    mesh = mesh_lib.build_mesh()
    trainer = Trainer(spec, mesh=mesh, model_params=model_params)
    state = trainer.init_state(batch)
    n_params = sum(
        int(np.prod(x.shape)) for x in jax.tree.leaves(state.params)
    )
    batch = jax.device_put(batch, mesh_lib.batch_sharding(mesh))
    step_time, _ = _measure_steps(trainer, state, batch, iters, warmup)
    dev = jax.devices()[0]
    return (step_time, max(1, len(jax.devices())), dev,
            jax.default_backend(), n_params)


def run_resnet50_bench(on_tpu):
    """BASELINE.md secondary target: ResNet-50 images/sec (train)."""
    import numpy as np

    from model_zoo.imagenet_resnet50 import imagenet_resnet50 as zoo

    if on_tpu:
        batch_size, size, iters, warmup = 64, 224, 20, 3
    else:
        batch_size, size, iters, warmup = 4, 64, 3, 1

    rng = np.random.RandomState(0)
    batch = (
        {"image": rng.rand(batch_size, size, size, 3).astype(np.float32)},
        rng.randint(1000, size=(batch_size, 1)).astype(np.int32),
    )
    step_time, n_chips, dev, platform, _ = _run_zoo_bench(
        zoo, batch, iters, warmup
    )
    # ResNet-50 fwd ~4.1 GFLOP per 224x224 image; bwd = 2x fwd
    flops = 3 * batch_size * 4.1e9 * (size / 224.0) ** 2
    mfu = None if platform == "cpu" else round(
        flops / step_time / (_peak_flops(
            getattr(dev, "device_kind", "")) * n_chips), 4)
    return {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(batch_size / step_time / n_chips, 2),
        "unit": "images/sec/chip",
        "vs_baseline": None,  # filled by _apply_vs_baseline
        "mfu": mfu,
        "step_time_ms": round(step_time * 1e3, 2),
        "platform": platform,
        "device_kind": getattr(dev, "device_kind", "") or platform,
        "batch_size": batch_size,
        "image_size": size,
    }


def run_vit_bench(on_tpu):
    """ViT images/sec (train) — net-new family (the reference zoo's
    vision ceiling is ResNet50). TPU config is ViT-Base-shaped at
    224px/patch 14 -> 256 patch tokens (tiles into the flash blocks;
    /16 would give 196, which falls back to blockwise)."""
    import numpy as np

    from elasticdl_tpu.common.model_utils import format_params_str
    from model_zoo.vit import vit as zoo

    if on_tpu:
        cfg = dict(image_size=224, patch_size=14, num_classes=1000,
                   embed_dim=768, num_heads=12, num_layers=12)
        batch_size, iters, warmup = 64, 20, 3
    else:
        cfg = dict(image_size=32, patch_size=4, num_classes=10,
                   embed_dim=64, num_heads=4, num_layers=2)
        batch_size, iters, warmup = 4, 3, 1

    params, extra, batch_size = apply_extra_params(cfg, batch_size,
                                                   on_tpu)
    rng = np.random.RandomState(0)
    batch = (
        {"image": rng.rand(
            batch_size, cfg["image_size"], cfg["image_size"], 3
        ).astype(np.float32)},
        rng.randint(cfg["num_classes"],
                    size=(batch_size, 1)).astype(np.int32),
    )
    step_time, n_chips, dev, platform, n_params = _run_zoo_bench(
        zoo, batch, iters, warmup,
        model_params=format_params_str(params),
    )
    # fwd+bwd ~= 3 * 2 * params * tokens FLOPs (dense transformer rule;
    # attention at 256 tokens adds a few % — omitted, keeping the
    # estimate conservative)
    n_tokens = (cfg["image_size"] // cfg["patch_size"]) ** 2
    flops = 6.0 * n_params * n_tokens * batch_size
    mfu = None if platform == "cpu" else round(
        flops / step_time / (_peak_flops(
            getattr(dev, "device_kind", "")) * n_chips), 4)
    return {
        "metric": "vit_train_images_per_sec_per_chip",
        "value": round(batch_size / step_time / n_chips, 2),
        "unit": "images/sec/chip",
        "vs_baseline": None,  # filled by _apply_vs_baseline
        "mfu": mfu,
        "step_time_ms": round(step_time * 1e3, 2),
        "params_m": round(n_params / 1e6, 1),
        "platform": platform,
        "device_kind": getattr(dev, "device_kind", "") or platform,
        "config": cfg,
        "extra_params": extra or None,
        "batch_size": batch_size,
    }


def run_deepfm_bench(on_tpu):
    """BASELINE.md primary recsys target: DeepFM samples/sec (frappe
    schema; embedding + FM + DNN). MFU is not reported — the model is
    lookup/bandwidth-bound, not matmul-bound."""
    import numpy as np

    from model_zoo.deepfm_functional_api import deepfm_functional_api as zoo

    if on_tpu:
        batch_size, iters, warmup = 8192, 30, 5
    else:
        batch_size, iters, warmup = 256, 5, 1

    rng = np.random.RandomState(0)
    batch = (
        {"feature": rng.randint(
            zoo.INPUT_DIM, size=(batch_size, 10)).astype(np.int32)},
        rng.randint(2, size=(batch_size,)).astype(np.int32),
    )
    step_time, n_chips, dev, platform, _ = _run_zoo_bench(
        zoo, batch, iters, warmup
    )
    return {
        "metric": "deepfm_train_samples_per_sec_per_chip",
        "value": round(batch_size / step_time / n_chips, 1),
        "unit": "samples/sec/chip",
        "vs_baseline": None,  # filled by _apply_vs_baseline
        "mfu": None,
        "step_time_ms": round(step_time * 1e3, 2),
        "platform": platform,
        "device_kind": getattr(dev, "device_kind", "") or platform,
        "batch_size": batch_size,
    }


def run_decode_bench(on_tpu):
    """KV-cache autoregressive decode throughput (net-new surface: the
    reference has no generation story). Measures steady-state
    tokens/sec for batch decoding with the per-layer KV caches —
    O(L) attention per generated token."""
    import numpy as np

    from model_zoo.transformer_lm import transformer_lm as zoo

    if on_tpu:
        cfg = dict(vocab_size=32000, seq_len=1024, embed_dim=1024,
                   num_heads=8, num_layers=8)
        batch, prompt, new_tokens, iters = 16, 32, 224, 3
    else:
        cfg = dict(vocab_size=256, seq_len=128, embed_dim=128,
                   num_heads=4, num_layers=2)
        batch, prompt, new_tokens, iters = 4, 8, 24, 2

    from elasticdl_tpu.api.generation import autoregressive_generate
    from elasticdl_tpu.common.model_utils import (
        format_params_str,
        load_model_spec_from_module,
    )
    from elasticdl_tpu.common.timing_utils import fetch_sync
    from elasticdl_tpu.parallel import mesh as mesh_lib
    from elasticdl_tpu.training.trainer import Trainer

    import jax

    # same A/B channel as the training bench (e.g. num_kv_heads for the
    # GQA decode-cache comparison; prompt/new_tokens for the batched-
    # prefill A/B — they are bench knobs, not model kwargs, so they are
    # popped out of the model params but stay in the reported extras)
    params, extra, batch = apply_extra_params(cfg, batch, on_tpu)
    if int(params.pop("moe", 0)):
        # decode the MoE family instead of the dense LM: the drop-free
        # inference dispatch (moe_infer_impl='dense'|'gather', see
        # parallel/moe.py moe_mlp_infer{,_gather}) only runs on
        # decode/prefill paths, so this knob is the one bench surface
        # that can A/B it on hardware:
        #   EDL_BENCH_MODEL=decode \
        #   EDL_BENCH_EXTRA_PARAMS="moe=1; moe_infer_impl='gather'"
        from model_zoo.transformer_moe import (  # noqa: F811
            transformer_moe as zoo,
        )
        params.setdefault("num_experts", 8 if on_tpu else 4)
        params.setdefault("router_top_k", 2)
        if on_tpu and "num_layers" not in extra:
            # match the moe training bench's depth (expert FFNs double
            # the layer cost vs the 8-layer dense decode config)
            params["num_layers"] = 4
        # the reported config must describe what actually ran
        cfg.update(num_layers=params["num_layers"],
                   num_experts=params["num_experts"],
                   router_top_k=params["router_top_k"])
    prompt = int(params.pop("prompt", prompt))
    new_tokens = int(params.pop("new_tokens", new_tokens))
    quantize = bool(params.pop("quantize", 0))
    beams = int(params.pop("beams", 0))  # 0 = greedy KV decode
    # speculative decode: gamma draft proposals per target verify.
    # spec_draft_layers=0 uses the TARGET as its own draft — acceptance
    # ~100%, measuring the mechanics ceiling; a shallow random draft
    # measures the floor (near-zero acceptance on random logits).
    spec_gamma = int(params.pop("spec_gamma", 0))
    spec_draft_layers = int(params.pop("spec_draft_layers", 2))
    # >0 distills the draft against the target before timing
    # (warm-start + KL on the target's own logits — api/distill.py):
    # the decode_spec_trained A/B vs the random-draft floor and the
    # self-draft (spec_draft_layers=0) ceiling
    spec_draft_train_steps = int(params.pop("spec_draft_train_steps", 0))
    # speculative verify chunks reach gamma-1 positions past the stream
    margin = spec_gamma - 1 if spec_gamma else 0
    if prompt + new_tokens + margin > cfg["seq_len"]:
        # scale to fit (the CPU fallback shrinks seq_len under the same
        # knobs; the rc=0 contract forbids dying on that) — the emitted
        # prompt_len/new_tokens fields report what actually ran
        room = cfg["seq_len"] - margin
        f = room / (prompt + new_tokens)
        prompt = max(1, int(prompt * f))
        new_tokens = max(1, min(room - prompt, int(new_tokens * f)))
        sys.stderr.write(
            "bench: prompt+new_tokens exceed seq_len %d (margin %d); "
            "scaled to prompt=%d new_tokens=%d\n"
            % (cfg["seq_len"], margin, prompt, new_tokens)
        )
    spec = load_model_spec_from_module(zoo)
    mesh = mesh_lib.build_mesh()
    trainer = Trainer(spec, mesh=mesh,
                      model_params=format_params_str(params))
    rng = np.random.RandomState(0)
    tokens = rng.randint(
        0, cfg["vocab_size"], size=(batch, cfg["seq_len"] + 1)
    ).astype(np.int32)
    state = trainer.init_state(
        ({"tokens": tokens[:, :-1]}, tokens[:, 1:])
    )
    prompt_ids = tokens[:, :prompt]
    if quantize:
        # weight-only int8 serving path (api/quantization.py): the
        # decode program dequantizes in-jit, weights travel as int8
        from elasticdl_tpu.api.quantization import quantize_params

        state = state.replace(params=quantize_params(state.params))

    if spec_gamma:
        from elasticdl_tpu.api.generation import speculative_generate

        if spec_draft_layers:
            d_params = dict(params, num_layers=spec_draft_layers)
            draft_trainer = Trainer(
                spec, mesh=mesh,
                model_params=format_params_str(d_params),
            )
            d_state = draft_trainer.init_state(
                ({"tokens": tokens[:, :-1]}, tokens[:, 1:])
            )
            if spec_draft_train_steps:
                from elasticdl_tpu.api.distill import (
                    distill_draft,
                    warm_start_draft,
                )

                d_state = warm_start_draft(state, d_state)
                d_state, _ = distill_draft(
                    trainer, state, draft_trainer, d_state,
                    [
                        rng.randint(
                            0, cfg["vocab_size"],
                            size=(batch, cfg["seq_len"]),
                        ).astype(np.int32)
                        for _ in range(spec_draft_train_steps)
                    ],
                )
        else:
            draft_trainer, d_state = trainer, state
        # acceptance telemetry once (same executable — return_stats
        # only gates Python-side post-processing), then the timed path
        # runs without stats
        _, spec_stats = speculative_generate(
            trainer, state, draft_trainer, d_state, prompt_ids,
            new_tokens, gamma=spec_gamma, return_stats=True,
        )
        extra["spec_acceptance_rate"] = round(
            spec_stats["acceptance_rate"], 3
        )
        extra["spec_verify_calls"] = spec_stats["verify_calls"]

        def decode():
            return speculative_generate(
                trainer, state, draft_trainer, d_state, prompt_ids,
                new_tokens, gamma=spec_gamma,
            )
    elif beams:
        from elasticdl_tpu.api.generation import beam_search_generate

        def decode():
            return beam_search_generate(
                trainer, state, prompt_ids, new_tokens,
                num_beams=beams, use_cache=True,
            )
    else:
        def decode():
            return autoregressive_generate(
                trainer, state, prompt_ids, new_tokens, use_cache=True
            )

    out = decode()  # compile
    fetch_sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = decode()
    fetch_sync(out)
    dt = (time.perf_counter() - t0) / iters
    n_chips = max(1, len(jax.devices()))
    platform = jax.default_backend()
    tokens_per_sec = batch * new_tokens / dt
    return {
        "metric": "kv_cache_decode_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec / n_chips, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": None,  # filled by _apply_vs_baseline
        "mfu": None,
        "ms_per_token": round(dt * 1e3 / new_tokens, 3),
        "batch_size": batch,
        "prompt_len": prompt,
        "new_tokens": new_tokens,
        "platform": platform,
        "device_kind": getattr(jax.devices()[0], "device_kind", "")
        or platform,
        "config": cfg,
        "extra_params": extra or None,
    }


def run_dlrm_bench(on_tpu):
    """BASELINE.json configs[4]: DLRM with ~1B embedding parameters
    (26 tables x 1.2M rows x 32 dims = 4 GB fp32 in sharded HBM,
    sparse-row updates). Samples/sec/chip; MFU not reported (the model
    is gather/bandwidth-bound)."""
    import numpy as np

    from model_zoo.dlrm import dlrm as zoo

    if on_tpu:
        table_size, dim, batch_size, iters, warmup = (
            1_200_000, 32, 4096, 20, 3)
    else:
        table_size, dim, batch_size, iters, warmup = 2048, 8, 64, 3, 1

    from elasticdl_tpu.common.model_utils import format_params_str

    rng = np.random.RandomState(0)
    batch = (
        {
            "dense": rng.rand(batch_size, 13).astype(np.float32),
            "sparse": rng.randint(
                0, table_size, size=(batch_size, 26)
            ).astype(np.int32),
        },
        rng.randint(2, size=(batch_size,)).astype(np.int32),
    )
    step_time, n_chips, dev, platform, n_params = _run_zoo_bench(
        zoo, batch, iters, warmup,
        model_params=format_params_str(
            dict(table_size=table_size, embedding_dim=dim)
        ),
    )
    return {
        "metric": "dlrm_train_samples_per_sec_per_chip",
        "value": round(batch_size / step_time / n_chips, 1),
        "unit": "samples/sec/chip",
        "vs_baseline": None,  # filled by _apply_vs_baseline
        "mfu": None,
        "step_time_ms": round(step_time * 1e3, 2),
        "params_b": round(n_params / 1e9, 3),
        "platform": platform,
        "device_kind": getattr(dev, "device_kind", "") or platform,
        "batch_size": batch_size,
        "table_size": table_size,
    }


def run_bert_bench(on_tpu):
    """BASELINE.json configs[4] first half: BERT-base-shape masked-LM
    pretraining throughput (12 layers x 768 x 12 heads, seq 512)."""
    import numpy as np

    from model_zoo.bert import bert as zoo

    if on_tpu:
        cfg = dict(vocab_size=30522, seq_len=512, embed_dim=768,
                   num_heads=12, num_layers=12)
        batch_size, iters, warmup = 16, 20, 3
    else:
        cfg = dict(vocab_size=512, seq_len=64, embed_dim=64,
                   num_heads=4, num_layers=2)
        batch_size, iters, warmup = 4, 3, 1

    from elasticdl_tpu.common.model_utils import format_params_str

    params = dict(cfg)
    if on_tpu:
        params["dtype"] = "bf16"
    rng = np.random.RandomState(0)
    tokens = rng.randint(
        1, cfg["vocab_size"], size=(batch_size, cfg["seq_len"])
    ).astype(np.int32)
    # masked-LM batch matching the zoo's recipe (model_zoo/bert/bert.py
    # _mask_tokens): [MASK] is the reserved id vocab_size, and labels
    # carry the original token at masked positions, IGNORE_LABEL (-1)
    # elsewhere — so the bench loss is the real masked-subset loss
    masked = tokens.copy()
    mask_positions = np.zeros_like(tokens, bool)
    mask_positions[:, ::7] = True
    masked[mask_positions] = cfg["vocab_size"]
    labels = np.where(mask_positions, tokens, -1).astype(np.int32)
    batch = ({"tokens": masked}, labels)
    step_time, n_chips, dev, platform, n_params = _run_zoo_bench(
        zoo, batch, iters, warmup,
        model_params=format_params_str(params),
    )
    tokens_per_sec = batch_size * cfg["seq_len"] / step_time
    flops = transformer_flops_per_step(
        batch_size, cfg["seq_len"], cfg["embed_dim"],
        cfg["num_layers"], cfg["vocab_size"],
    )
    mfu = None if platform == "cpu" else round(
        flops / step_time / (_peak_flops(
            getattr(dev, "device_kind", "")) * n_chips), 4)
    return {
        "metric": "bert_mlm_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec / n_chips, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": None,  # filled by _apply_vs_baseline
        "mfu": mfu,
        "step_time_ms": round(step_time * 1e3, 2),
        "params_m": round(n_params / 1e6, 1),
        "platform": platform,
        "device_kind": getattr(dev, "device_kind", "") or platform,
        "config": cfg,
        "batch_size": batch_size,
    }


def run_moe_bench(on_tpu):
    """Mixture-of-experts LM training throughput: top-2 (GShard)
    routing over a stacked expert bank. Single-chip runs measure the
    dense-equivalent tokens/sec at k-of-E active expert FLOPs per
    token; on an ep mesh the same code all-to-alls tokens to their
    experts (driver dryrun sub-run 5 proves the sharded path)."""
    import numpy as np

    from model_zoo.transformer_moe import transformer_moe as zoo

    if on_tpu:
        cfg = dict(vocab_size=32000, seq_len=1024, embed_dim=1024,
                   num_heads=8, num_layers=4, num_experts=8,
                   router_top_k=2)
        batch_size, iters, warmup = 16, 20, 3
    else:
        cfg = dict(vocab_size=512, seq_len=64, embed_dim=64,
                   num_heads=4, num_layers=2, num_experts=4,
                   router_top_k=2)
        batch_size, iters, warmup = 4, 3, 1

    from elasticdl_tpu.common.model_utils import format_params_str

    params, extra, batch_size = apply_extra_params(cfg, batch_size, on_tpu)
    rng = np.random.RandomState(0)
    tokens = rng.randint(
        0, cfg["vocab_size"], size=(batch_size, cfg["seq_len"] + 1)
    ).astype(np.int32)
    batch = ({"tokens": tokens[:, :-1]}, tokens[:, 1:])
    step_time, n_chips, dev, platform, n_params = _run_zoo_bench(
        zoo, batch, iters, warmup,
        model_params=format_params_str(params),
    )
    tokens_per_sec = batch_size * cfg["seq_len"] / step_time
    return {
        "metric": "moe_lm_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec / n_chips, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": None,  # filled by _apply_vs_baseline
        "mfu": None,  # MoE FLOPs depend on routing; tokens/sec is the claim
        "step_time_ms": round(step_time * 1e3, 2),
        "params_m": round(n_params / 1e6, 1),
        "num_experts": cfg["num_experts"],
        "router_top_k": cfg["router_top_k"],
        "platform": platform,
        "device_kind": getattr(dev, "device_kind", "") or platform,
        "config": cfg,
        "extra_params": extra or None,
        "batch_size": batch_size,
    }


_BENCHES = {
    "transformer": run_transformer_bench,
    "resnet50": run_resnet50_bench,
    "vit": run_vit_bench,
    "deepfm": run_deepfm_bench,
    "decode": run_decode_bench,
    "dlrm": run_dlrm_bench,
    "bert": run_bert_bench,
    "moe": run_moe_bench,
}

# Default-run suite: the VERDICT-r04 family set — flagship FIRST (a
# truncated run still leaves the headline number in the stream), then
# the other train families, then the decode pair (greedy + int8 KV
# cache). resnet50/deepfm stay reachable via EDL_BENCH_MODEL.
_SUITE = (
    # (family, model, env overrides, expected parsed extra_params —
    #  part of the family's baseline identity)
    ("transformer", "transformer", None, None),
    ("moe", "moe", None, None),
    ("bert", "bert", None, None),
    ("dlrm", "dlrm", None, None),
    ("decode", "decode", None, None),
    ("decode_kv_int8", "decode",
     {"EDL_BENCH_EXTRA_PARAMS": "kv_cache_dtype='int8'"},
     {"kv_cache_dtype": "int8"}),
    # tail entry: if the suite budget truncates, only this drops
    ("vit", "vit", None, None),
)


def _baseline_path(family):
    return os.path.join(
        REPO, "BENCH_BASELINE.json" if family == "transformer"
        else "BENCH_BASELINE_%s.json" % family.upper())


def _baseline_comparable(family, base, result):
    """Same-config identity between a committed record and this run.
    Non-transformer families include extra_params in the identity (for
    decode_kv_int8 the extra IS the family); the transformer keeps the
    legacy no-extras check so hw_session A/B knobs read as a direct
    ratio against the plain flagship record."""
    same = (base.get("platform") != "cpu"
            and base.get("metric") == result.get("metric")
            and base.get("config") == result.get("config")
            and base.get("batch_size") == result.get("batch_size")
            and base.get("device_kind") == result.get("device_kind"))
    if family != "transformer":
        same = same and (
            base.get("extra_params") == result.get("extra_params"))
    return same and bool(base.get("value"))


def _apply_vs_baseline(family, result):
    """Fill result["vs_baseline"]: ratio to the committed same-config
    hardware record, 1.0 for a TPU run with no comparable record (this
    run establishes it), None for a CPU fallback (no hardware signal —
    VERDICT r04 weak-#6)."""
    if result.get("platform") == "cpu":
        result["vs_baseline"] = None
        result["no_hw_signal"] = True
        return result
    vs = 1.0
    try:
        with open(_baseline_path(family)) as f:
            base = json.load(f)
        if _baseline_comparable(family, base, result):
            vs = round(result["value"] / float(base["value"]), 4)
    except (OSError, ValueError):
        pass
    result["vs_baseline"] = vs
    return result


def _maybe_persist_baseline(family, result, expected_extra=None):
    """Baseline persistence, the ONE policy for BENCH_BASELINE*.json
    (suite mode and hw_session both route here): a TPU family run
    becomes the committed record when there is no hardware record yet,
    when the existing record's identity (config/batch/chip/extras) no
    longer matches this run's — a retuned config or a new chip
    generation starts a fresh baseline rather than pinning vs_baseline
    to 1.0 forever — or when the same-identity value improved. Refuses
    runs whose extra_params differ from the family's declared identity
    (ambient operator knobs must never become a committed record)."""
    if result.get("platform") == "cpu":
        return
    if result.get("extra_params") != expected_extra:
        return
    path = _baseline_path(family)
    try:
        with open(path) as f:
            old = json.load(f)
    except (OSError, ValueError):
        old = {}
    better = (
        not old or old.get("platform") == "cpu"
        or not _baseline_comparable(family, old, result)
        or result.get("value", 0) > old.get("value", 0)
    )
    if better:
        rec = {k: v for k, v in result.items()
               if k not in ("vs_baseline", "no_hw_signal", "family",
                            "suite", "families")}
        with open(path, "w") as f:
            json.dump(rec, f, indent=1)
        sys.stderr.write("bench: %s updated\n" % os.path.basename(path))


def _run_one(model_name, on_tpu, family=None):
    """One family bench with the Pallas-fallback retry; fills
    vs_baseline. The disable flag is restored afterwards so one family's
    Mosaic failure doesn't silently degrade the rest of a suite."""
    bench_fn = _BENCHES[model_name]
    had_flag = os.environ.get("ELASTICDL_TPU_DISABLE_PALLAS")
    try:
        result = bench_fn(on_tpu)
    except Exception as e:  # noqa: BLE001
        if not on_tpu:
            raise
        # One retry without the Pallas kernels (flash attention): an
        # unproven Mosaic lowering must degrade to the XLA path, not
        # kill the bench.
        sys.stderr.write("bench: TPU run failed (%r); retrying with "
                         "Pallas disabled\n" % (e,))
        os.environ["ELASTICDL_TPU_DISABLE_PALLAS"] = "1"
        try:
            result = bench_fn(on_tpu)
        finally:
            if had_flag is None:
                os.environ.pop("ELASTICDL_TPU_DISABLE_PALLAS", None)
            else:
                os.environ["ELASTICDL_TPU_DISABLE_PALLAS"] = had_flag
        result["pallas_disabled"] = True
    return _apply_vs_baseline(family or model_name, result)


_FAMILY_SUMMARY_KEYS = (
    "metric", "value", "unit", "vs_baseline", "mfu", "step_time_ms",
    "ms_per_token", "platform", "pallas_disabled", "params_m",
    "params_b",
)


def run_suite(on_tpu):
    """Run every suite family, streaming one JSON line per family as it
    completes (a mid-suite wedge or driver timeout still leaves every
    finished family in the stream), then print the flagship summary
    line carrying the whole suite in "families". A per-suite wall-clock
    budget (EDL_BENCH_SUITE_BUDGET, measured after the probe) skips
    trailing families rather than risking a silent driver kill."""
    budget_s = _env_float(None, "EDL_BENCH_SUITE_BUDGET", 900.0, 60.0)
    t0 = time.monotonic()
    families = {}
    flagship = None
    first_attempted = False
    for fam, model, env_extra, expected_extra in _SUITE:
        if first_attempted and time.monotonic() - t0 > budget_s:
            sys.stderr.write(
                "bench: suite budget %.0fs exhausted; skipping %s\n"
                % (budget_s, fam))
            families[fam] = {"skipped": "suite_budget"}
            continue
        first_attempted = True
        saved = {k: os.environ.get(k) for k in (env_extra or {})}
        os.environ.update(env_extra or {})
        try:
            result = _run_one(model, on_tpu, family=fam)
        except Exception as e:  # noqa: BLE001
            sys.stderr.write("bench: family %s failed: %r\n" % (fam, e))
            families[fam] = {"error": repr(e)[:300]}
            continue
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        _maybe_persist_baseline(fam, result, expected_extra)
        result["family"] = fam
        print(json.dumps(result), flush=True)
        families[fam] = {
            k: result[k] for k in _FAMILY_SUMMARY_KEYS if k in result
        }
        if fam == "transformer":
            flagship = result
    if flagship is not None:
        summary = dict(flagship)
        summary.pop("family", None)
    else:
        summary = {
            "metric": "transformer_lm_train_tokens_per_sec_per_chip",
            "value": None, "unit": "tokens/sec/chip",
            "vs_baseline": None,
            "platform": "tpu" if on_tpu else "cpu",
            "error": "flagship family failed",
        }
    summary["suite"] = True
    summary["families"] = families
    print(json.dumps(summary))


def main():
    model_name = os.environ.get("EDL_BENCH_MODEL", "suite")
    if model_name != "suite" and model_name not in _BENCHES:
        sys.exit(
            "bench: unknown EDL_BENCH_MODEL %r (valid: suite, %s)"
            % (model_name, ", ".join(sorted(_BENCHES)))
        )
    probe_timeout = _env_float(None, "EDL_BENCH_PROBE_TIMEOUT", 300.0, 0.0)
    backend, kind = probe_accelerator(probe_timeout)
    on_tpu = backend is not None
    if not on_tpu:
        # Pin CPU before the first in-process jax import so a broken TPU
        # tunnel can't crash or hang backend init (round-1 failure mode).
        os.environ["JAX_PLATFORMS"] = "cpu"
        # Drop the default compile cache on the CPU fallback: XLA:CPU
        # AOT cache entries carry host machine features and loading one
        # with a mismatched feature set warns of possible SIGILL — the
        # fallback's rc=0 contract can't risk that for a toy-size
        # compile. An explicit operator-set cache dir is respected.
        if os.environ.get("JAX_COMPILATION_CACHE_DIR") == os.path.join(
                REPO, ".jax_cache"):
            del os.environ["JAX_COMPILATION_CACHE_DIR"]
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        sys.stderr.write("bench: accelerator ready: %s (%s)\n"
                         % (backend, kind))

    # the driver's plain `python bench.py` records the full family
    # suite; every hw_session step pins one family via EDL_BENCH_MODEL
    if model_name == "suite":
        run_suite(on_tpu)
    else:
        print(json.dumps(_run_one(model_name, on_tpu)))


if __name__ == "__main__":
    main()
