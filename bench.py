"""Headline benchmark: training throughput of the flagship model on real
hardware. Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference publishes no hardware throughput numbers (BASELINE.md), so
vs_baseline is measured against the target set in BASELINE.json round 1
(established here); until a prior round exists, vs_baseline=1.0.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main():
    import numpy as np

    from elasticdl_tpu.common.model_utils import load_model_spec_from_module
    from elasticdl_tpu.parallel import mesh as mesh_lib
    from elasticdl_tpu.training.trainer import Trainer
    from model_zoo.mnist_functional_api import mnist_functional_api as zoo

    batch_size = 1024
    spec = load_model_spec_from_module(zoo)
    mesh = mesh_lib.build_mesh()  # all available chips, dp-filled
    trainer = Trainer(spec, mesh=mesh)

    rng = np.random.RandomState(0)
    features = {"image": rng.rand(batch_size, 28, 28).astype(np.float32)}
    labels = rng.randint(10, size=(batch_size,)).astype(np.int32)
    batch = (features, labels)

    state = trainer.init_state(batch)
    # Pre-stage the batch in HBM with the batch sharding: the benchmark
    # measures the compiled step, not host->device transfer (a real input
    # pipeline double-buffers transfers behind the step).
    import jax

    batch = jax.device_put(batch, mesh_lib.batch_sharding(mesh))
    # warmup (compile + first steps)
    for _ in range(5):
        state, loss = trainer.train_step(state, batch)
    jax.block_until_ready(state.params)

    iters = 50
    t0 = time.perf_counter()
    for _ in range(iters):
        state, loss = trainer.train_step(state, batch)
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0

    n_chips = max(1, len(jax.devices()))
    samples_per_sec = batch_size * iters / dt
    value = samples_per_sec / n_chips
    print(
        json.dumps(
            {
                "metric": "mnist_cnn_train_throughput_per_chip",
                "value": round(value, 2),
                "unit": "samples/sec/chip",
                "vs_baseline": 1.0,
            }
        )
    )


if __name__ == "__main__":
    main()
