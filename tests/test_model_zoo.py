"""End-to-end model-zoo coverage: every reference zoo family trains through
the LocalExecutor on tiny synthetic data (mirrors the reference's
example_test.py:94-174 in-process harness over mnist/cifar10/resnet50/
deepfm/wide-deep/census/heart/iris/dac_ctr)."""

import numpy as np
import pytest

from elasticdl_tpu.api.local_executor import LocalExecutor
from elasticdl_tpu.common.model_utils import get_model_spec
from elasticdl_tpu.data import recordio_gen

# CI drills shard (make test-drills): the sub-5-min per-commit gate excludes this file.
pytestmark = pytest.mark.slow

MODEL_ZOO = "model_zoo"


def _run(spec_key, data_gen, tmp_path, minibatch=8, records=32,
         model_params="", n_files=1, **gen_kwargs):
    train_dir = str(tmp_path / "train")
    val_dir = str(tmp_path / "val")
    data_gen(train_dir, num_files=n_files, records_per_file=records,
             **gen_kwargs)
    data_gen(val_dir, num_files=1, records_per_file=records, seed=7,
             **gen_kwargs)
    spec = get_model_spec(MODEL_ZOO, spec_key)
    executor = LocalExecutor(
        spec,
        training_data=train_dir,
        validation_data=val_dir,
        minibatch_size=minibatch,
        num_epochs=1,
        records_per_task=records,
        model_params=model_params,
    )
    state, metrics = executor.run()
    assert int(state.step) == (records * n_files) // minibatch
    assert np.isfinite(executor.losses).all()
    return metrics


def test_mnist_subclass(tmp_path):
    metrics = _run("mnist_subclass.mnist_subclass.custom_model",
                   recordio_gen.gen_mnist_like, tmp_path)
    assert 0.0 <= metrics["accuracy"] <= 1.0


def test_cifar10_functional_api(tmp_path):
    metrics = _run(
        "cifar10_functional_api.cifar10_functional_api.custom_model",
        recordio_gen.gen_cifar10_like, tmp_path)
    assert 0.0 <= metrics["accuracy"] <= 1.0


def test_cifar10_subclass(tmp_path):
    metrics = _run("cifar10_subclass.cifar10_subclass.custom_model",
                   recordio_gen.gen_cifar10_like, tmp_path)
    assert 0.0 <= metrics["accuracy"] <= 1.0


def test_deepfm_functional_api(tmp_path):
    metrics = _run(
        "deepfm_functional_api.deepfm_functional_api.custom_model",
        recordio_gen.gen_frappe_like, tmp_path)
    assert 0.0 <= metrics["logits_accuracy"] <= 1.0
    assert 0.0 <= metrics["probs_auc"] <= 1.0


def test_deepfm_edl_embedding(tmp_path):
    metrics = _run(
        "deepfm_edl_embedding.deepfm_edl_embedding.custom_model",
        recordio_gen.gen_frappe_like, tmp_path)
    assert 0.0 <= metrics["logits_accuracy"] <= 1.0


@pytest.mark.parametrize("variant", [
    "census_functional_api", "census_sequential", "census_subclass",
])
def test_census_dnn(tmp_path, variant):
    metrics = _run("census_dnn_model.%s.custom_model" % variant,
                   recordio_gen.gen_census_raw, tmp_path)
    assert 0.0 <= metrics["accuracy"] <= 1.0


def test_census_wide_deep(tmp_path):
    metrics = _run(
        "census_wide_deep_model.wide_deep_functional_api.custom_model",
        recordio_gen.gen_census_raw, tmp_path)
    assert 0.0 <= metrics["logits_accuracy"] <= 1.0
    assert 0.0 <= metrics["probs_auc"] <= 1.0


def test_heart_functional_api(tmp_path):
    metrics = _run("heart_functional_api.heart_functional_api.custom_model",
                   recordio_gen.gen_heart_like, tmp_path)
    assert 0.0 <= metrics["accuracy"] <= 1.0


def test_odps_iris_dnn_model(tmp_path):
    train_dir = str(tmp_path / "train")
    recordio_gen.gen_iris_csv(train_dir, num_files=1, rows_per_file=32)
    spec = get_model_spec(
        MODEL_ZOO, "odps_iris_dnn_model.odps_iris_dnn_model.custom_model"
    )
    executor = LocalExecutor(
        spec, training_data=train_dir, minibatch_size=8,
        num_epochs=1, records_per_task=32,
    )
    state, _ = executor.run()
    assert int(state.step) == 4
    assert np.isfinite(executor.losses).all()


@pytest.mark.parametrize("ctr_model", [
    "wide_deep", "deepfm", "dcn", "xdeepfm",
])
def test_dac_ctr(tmp_path, ctr_model):
    metrics = _run(
        "dac_ctr.elasticdl_train.custom_model",
        recordio_gen.gen_criteo_like, tmp_path,
        model_params=(
            "ctr_model='%s'; max_hashing_bucket_size=997" % ctr_model
        ),
    )
    assert 0.0 <= metrics["logits_accuracy"] <= 1.0
    assert 0.0 <= metrics["probs_auc"] <= 1.0


def test_dlrm(tmp_path):
    """BASELINE.json configs[4] DLRM family: Criteo-style records
    through the canonical dense-MLP + 26 embedding tables + pairwise
    interactions; small tables here, billion-parameter capacity via the
    sharded-HBM embedding tier at the stress config."""
    metrics = _run(
        "dlrm.dlrm.custom_model",
        recordio_gen.gen_criteo_like, tmp_path,
        model_params="table_size=1024; embedding_dim=8",
    )
    assert 0.0 <= metrics["logits_accuracy"] <= 1.0
    assert 0.0 <= metrics["probs_auc"] <= 1.0


def test_dlrm_sparse_tier_engages(tmp_path):
    """At stress-like table sizes the tables cross the 2 MB threshold:
    the sparse-row tier must tap them (no dense [vocab, dim] grads)."""
    import jax

    from elasticdl_tpu.common.model_utils import get_model_spec
    from elasticdl_tpu.parallel import mesh as mesh_lib
    from elasticdl_tpu.training.trainer import Trainer

    spec = get_model_spec(MODEL_ZOO, "dlrm.dlrm.custom_model")
    trainer = Trainer(
        spec, mesh=mesh_lib.local_mesh(),
        model_params="table_size=20000; embedding_dim=32; num_tables=4",
    )
    rs = np.random.RandomState(0)
    batch = (
        {
            "dense": rs.rand(8, 13).astype(np.float32),
            "sparse": rs.randint(0, 20000, size=(8, 4)).astype(np.int32),
        },
        rs.randint(0, 2, size=(8,)).astype(np.int32),
    )
    state = trainer.init_state(batch)
    # every table is sparse-tapped (20000*32*4B = 2.56 MB > 2 MB)
    assert len(trainer._sparse_paths) == 4
    state, loss = trainer.train_step(state, batch)
    assert np.isfinite(float(loss))
    n_emb = sum(
        int(np.prod(x.shape))
        for path, x in jax.tree_util.tree_flatten_with_path(
            state.params)[0]
        if "table_" in str(path)
    )
    assert n_emb == 4 * 20000 * 32


def test_resnet50_subclass(tmp_path):
    metrics = _run("resnet50_subclass.resnet50_subclass.custom_model",
                   recordio_gen.gen_cifar10_like, tmp_path,
                   minibatch=4, records=8)
    assert 0.0 <= metrics["accuracy"] <= 1.0


def test_imagenet_resnet50_forward():
    # full training at 224x224 is a TPU-scale job; on the CPU test rig we
    # verify the model builds and produces 1000-way logits at a small size
    import jax

    from elasticdl_tpu.common.model_utils import get_model_spec as gms

    spec = gms(MODEL_ZOO, "imagenet_resnet50.imagenet_resnet50.custom_model")
    model = spec.model_fn()
    feats = {"image": np.random.RandomState(0).rand(2, 64, 64, 3)
             .astype(np.float32)}
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        feats, training=False,
    )
    out = model.apply(variables, feats, training=False)
    assert out.shape == (2, 1000)
    assert out.dtype == np.float32
