"""Autoregressive decoding: shape/contract checks and an end-to-end
learn-a-pattern test (train a tiny LM on a deterministic cycle, greedy
decode must reproduce it)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from elasticdl_tpu.api.generation import autoregressive_generate
from elasticdl_tpu.common.model_utils import load_model_spec_from_module
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.training.trainer import Trainer
from model_zoo.transformer_lm import transformer_lm as zoo

# CI drills shard (make test-drills): the sub-5-min per-commit gate excludes this file.
pytestmark = pytest.mark.slow

PARAMS = (
    "vocab_size=8; seq_len=16; embed_dim=32; num_heads=2; num_layers=1"
)


def _trainer():
    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    return Trainer(
        load_model_spec_from_module(zoo), mesh=mesh, model_params=PARAMS
    )


def _cycle_batch(bsz=8, seq_len=16, vocab=8, seed=0):
    rs = np.random.RandomState(seed)
    starts = rs.randint(0, vocab, size=(bsz, 1))
    tokens = (starts + np.arange(seq_len + 1)[None, :]) % vocab
    tokens = tokens.astype(np.int32)
    return {"tokens": tokens[:, :-1]}, tokens[:, 1:]


def test_generate_contract():
    trainer = _trainer()
    batch = _cycle_batch()
    state = trainer.init_state(batch)
    prompt = np.asarray([[1, 2, 3], [4, 5, 6]], np.int32)
    out = autoregressive_generate(trainer, state, prompt, 5)
    assert out.shape == (2, 8)
    out = np.asarray(out)
    np.testing.assert_array_equal(out[:, :3], prompt)
    assert out.min() >= 0 and out.max() < 8
    # greedy decode is deterministic
    out2 = np.asarray(autoregressive_generate(trainer, state, prompt, 5))
    np.testing.assert_array_equal(out, out2)
    # temperature sampling is seed-deterministic
    s1 = np.asarray(autoregressive_generate(
        trainer, state, prompt, 5, temperature=1.0, seed=7))
    s2 = np.asarray(autoregressive_generate(
        trainer, state, prompt, 5, temperature=1.0, seed=7))
    np.testing.assert_array_equal(s1, s2)
    with pytest.raises(ValueError, match="seq_len"):
        autoregressive_generate(trainer, state, prompt, 14)
    with pytest.raises(ValueError, match="max_new_tokens"):
        autoregressive_generate(trainer, state, prompt, -6)
    # one executable per (batch, sampling mode): varied prompt lengths
    # and token counts reuse it (loop bounds are traced scalars)
    out3 = np.asarray(
        autoregressive_generate(trainer, state, prompt[:, :2], 7)
    )
    assert out3.shape == (2, 9)
    assert len(trainer._generate_cache) == 2  # greedy + temperature

    # a bidirectional model must be refused
    from model_zoo.bert import bert as bert_zoo

    t_bert = Trainer(
        load_model_spec_from_module(bert_zoo),
        mesh=mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1]),
        model_params=(
            "vocab_size=8; seq_len=16; embed_dim=32; num_heads=2; "
            "num_layers=1"
        ),
    )
    rs = np.random.RandomState(0)
    toks = rs.randint(0, 8, size=(2, 16)).astype(np.int32)
    b_state = t_bert.init_state(
        ({"tokens": toks}, {"ids": toks, "mask": np.ones_like(toks)})
    )
    with pytest.raises(ValueError, match="causal"):
        autoregressive_generate(t_bert, b_state, prompt, 5)
    # a causal model without decode support must be refused for
    # use_cache, not crash inside tracing (the pipeline family has no
    # decode/prefill modes; the MoE family gained them — see
    # tests/test_moe.py for its decode parity)
    from model_zoo.transformer_pp import transformer_pp as pp_zoo

    t_pp = Trainer(
        load_model_spec_from_module(pp_zoo),
        mesh=mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1]),
        model_params=(
            "vocab_size=8; seq_len=16; embed_dim=32; num_heads=2; "
            "num_layers=1; num_microbatches=1"
        ),
    )
    p_state = t_pp.init_state(_cycle_batch())
    with pytest.raises(ValueError, match="decode"):
        autoregressive_generate(t_pp, p_state, prompt, 5,
                                use_cache=True)


def test_kv_cache_matches_full_forward():
    """The KV-cached decode must produce the SAME tokens as the
    full-forward decode, for plain, RoPE and windowed configs."""
    for extra in ("", "; pos_emb='rope'", "; attn_window=4",
                  "; num_kv_heads=1"):
        mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
        trainer = Trainer(
            load_model_spec_from_module(zoo), mesh=mesh,
            model_params=PARAMS + extra,
        )
        state = trainer.init_state(_cycle_batch())
        for step in range(30):
            state, _ = trainer.train_step(state, _cycle_batch(seed=step))
        prompt = np.asarray([[2, 3, 4], [5, 6, 7]], np.int32)
        full = np.asarray(
            autoregressive_generate(trainer, state, prompt, 6)
        )
        kv = np.asarray(
            autoregressive_generate(
                trainer, state, prompt, 6, use_cache=True
            )
        )
        np.testing.assert_array_equal(full, kv, err_msg=extra)


def test_sampling_keys_are_position_derived():
    """_next_token derives its key from (rng, position) only: same
    inputs reproduce the draw, and the position changes the key (checked
    across many positions — at temperature 5 over 8 classes, identical
    draws at every position would mean the position is ignored)."""
    import jax.numpy as jnp

    from elasticdl_tpu.api.generation import _next_token

    rs = np.random.RandomState(0)
    logits = jnp.asarray(rs.randn(2, 8).astype(np.float32))
    rng = jax.random.PRNGKey(3)
    a = np.asarray(_next_token(logits, rng, 5, 5.0))
    b = np.asarray(_next_token(logits, rng, 5, 5.0))
    np.testing.assert_array_equal(a, b)
    draws = [
        tuple(np.asarray(_next_token(logits, rng, i, 5.0)))
        for i in range(16)
    ]
    assert len(set(draws)) > 1, "position does not affect the draw"


def test_topk_topp_filters():
    import jax.numpy as jnp

    from elasticdl_tpu.api.generation import _filter_logits, _next_token

    logits = jnp.asarray(
        [[3.0, 2.0, 1.0, 0.0, -1.0], [0.0, 5.0, 4.0, -2.0, 1.0]]
    )
    # top_k=2: exactly the two highest survive per row
    f = np.asarray(_filter_logits(logits, top_k=2, top_p=1.0))
    assert np.isfinite(f).sum(axis=1).tolist() == [2, 2]
    assert np.isfinite(f[0, [0, 1]]).all() and np.isfinite(
        f[1, [1, 2]]
    ).all()
    # tiny top_p: only the argmax survives
    f = np.asarray(_filter_logits(logits, top_k=0, top_p=1e-6))
    assert np.isfinite(f).sum(axis=1).tolist() == [1, 1]
    # top_p=1.0 keeps everything
    f = np.asarray(_filter_logits(logits, top_k=0, top_p=1.0))
    assert np.isfinite(f).all()
    # sampling with top_k=1 is greedy at any temperature
    rng = jax.random.PRNGKey(0)
    for pos in range(8):
        nxt = np.asarray(
            _next_token(logits, rng, pos, temperature=3.0, top_k=1)
        )
        np.testing.assert_array_equal(nxt, [0, 1])
    # top_k=3 draws stay inside the top-3 set
    for pos in range(32):
        nxt = np.asarray(
            _next_token(logits, rng, pos, temperature=3.0, top_k=3)
        )
        assert nxt[0] in (0, 1, 2) and nxt[1] in (1, 2, 4)


def test_topp_range_validated():
    import pytest

    trainer = _trainer()
    state = trainer.init_state(_cycle_batch())
    prompt = np.asarray([[1, 2]], np.int32)
    with pytest.raises(ValueError, match="top_p"):
        autoregressive_generate(trainer, state, prompt, 3,
                                temperature=1.0, top_p=0.0)
    with pytest.raises(ValueError, match="top_k"):
        autoregressive_generate(trainer, state, prompt, 3,
                                temperature=1.0, top_k=-2)


def test_generate_topk_end_to_end():
    trainer = _trainer()
    state = trainer.init_state(_cycle_batch())
    prompt = np.asarray([[1, 2, 3]], np.int32)
    out = np.asarray(autoregressive_generate(
        trainer, state, prompt, 5, temperature=1.0, top_k=2, top_p=0.9
    ))
    assert out.shape == (1, 8)
    assert out.min() >= 0 and out.max() < 8


def test_beam_search():
    from elasticdl_tpu.api.generation import beam_search_generate

    trainer = _trainer()
    state = trainer.init_state(_cycle_batch())
    # beams=1 must equal greedy decoding exactly (untrained model)
    prompt = np.asarray([[1, 2, 3], [4, 5, 6]], np.int32)
    greedy = np.asarray(autoregressive_generate(trainer, state, prompt, 5))
    beam1 = np.asarray(
        beam_search_generate(trainer, state, prompt, 5, num_beams=1)
    )
    np.testing.assert_array_equal(greedy, beam1)
    import pytest

    with pytest.raises(ValueError, match="num_beams"):
        beam_search_generate(trainer, state, prompt, 5, num_beams=9)

    # trained cycle model: every beam width finds the cycle
    for step in range(200):
        state, loss = trainer.train_step(state, _cycle_batch(seed=step))
    assert float(loss) < 0.1
    out = np.asarray(
        beam_search_generate(trainer, state,
                             np.asarray([[3, 4, 5, 6]], np.int32), 8,
                             num_beams=3)
    )[0]
    np.testing.assert_array_equal(out, (3 + np.arange(12)) % 8)


def test_generate_on_sharded_mesh():
    """Decoding composes with dp*fsdp-sharded trainer state: same greedy
    tokens as the single-device trainer from the same seed. Trained
    first so argmax margins are decisive (cross-mesh reduction order can
    differ by ULPs; an untrained 8-way vocab has near-ties)."""
    t1 = _trainer()
    s1 = t1.init_state(_cycle_batch())

    mesh8 = mesh_lib.build_mesh({"dp": 4, "fsdp": 2})
    t8 = Trainer(load_model_spec_from_module(zoo), mesh=mesh8,
                 model_params=PARAMS)
    s8 = t8.init_state(_cycle_batch())
    for step in range(30):
        batch = _cycle_batch(seed=step)
        s1, _ = t1.train_step(s1, batch)
        s8, _ = t8.train_step(s8, batch)

    prompt = np.asarray([[1, 2, 3], [4, 5, 6]], np.int32)
    out1 = np.asarray(autoregressive_generate(t1, s1, prompt, 5))
    out8 = np.asarray(autoregressive_generate(t8, s8, prompt, 5))
    np.testing.assert_array_equal(out1, out8)
    kv8 = np.asarray(
        autoregressive_generate(t8, s8, prompt, 5, use_cache=True)
    )
    np.testing.assert_array_equal(out1, kv8)


def test_generate_learned_cycle():
    """Train on the deterministic next = (tok + 1) % vocab cycle; greedy
    decode must continue the cycle from any prompt."""
    trainer = _trainer()
    state = trainer.init_state(_cycle_batch())
    for step in range(200):
        batch = _cycle_batch(seed=step)
        state, loss = trainer.train_step(state, batch)
    assert float(loss) < 0.1, float(loss)
    prompt = np.asarray([[3, 4, 5, 6]], np.int32)
    out = np.asarray(
        autoregressive_generate(trainer, state, prompt, 8)
    )[0]
    want = (3 + np.arange(12)) % 8
    np.testing.assert_array_equal(out, want)
    # the cached decode continues the cycle identically
    out_kv = np.asarray(
        autoregressive_generate(trainer, state, prompt, 8,
                                use_cache=True)
    )[0]
    np.testing.assert_array_equal(out_kv, want)
    # sampled decode: both paths key the draw by fold_in(rng, position);
    # on this sharply-trained model (decisive logit margins) the kv and
    # full paths must sample identical tokens. CPU-only: other backends'
    # kernel numerics can legitimately flip a near-boundary draw.
    if jax.default_backend() == "cpu":
        st = np.asarray(autoregressive_generate(
            trainer, state, prompt, 8, temperature=0.7, seed=11))
        skv = np.asarray(autoregressive_generate(
            trainer, state, prompt, 8, temperature=0.7, seed=11,
            use_cache=True))
        np.testing.assert_array_equal(st, skv)


def test_decode_cache_is_bounded_lru():
    """Sampling-knob sweeps must not accumulate compiled executables
    without bound (advisor finding): the decode cache evicts
    least-recently-used entries past max_entries, and get() refreshes
    recency."""
    from elasticdl_tpu.api.generation import _LRUCache

    cache = _LRUCache()
    cache.max_entries = 3
    for i in range(3):
        cache[("k", i)] = i
    assert cache.get(("k", 0)) == 0  # refresh 0's recency
    cache[("k", 3)] = 3              # evicts 1 (LRU), not 0
    assert ("k", 1) not in cache
    assert cache.get(("k", 0)) == 0
    assert len(cache) == 3
    cache[("k", 0)] = 99             # overwrite does not evict
    assert len(cache) == 3 and cache.get(("k", 0)) == 99


def test_kv_prefill_bucket_boundaries():
    """The KV path prefills the prompt in one padded causal forward
    (64-token buckets). Tokens must match the full-forward decode
    exactly across the bucket edges: inside the first bucket, at the
    bucket size, and crossing into the next bucket."""
    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = Trainer(
        load_model_spec_from_module(zoo), mesh=mesh,
        model_params=("vocab_size=8; seq_len=160; embed_dim=32; "
                      "num_heads=2; num_layers=1"),
    )
    state = trainer.init_state(_cycle_batch(seq_len=160))
    for p in (1, 63, 64, 65):
        prompt = (
            (np.arange(p)[None, :] + np.asarray([[0], [3]])) % 8
        ).astype(np.int32)
        full = np.asarray(
            autoregressive_generate(trainer, state, prompt, 4)
        )
        kv = np.asarray(
            autoregressive_generate(trainer, state, prompt, 4,
                                    use_cache=True)
        )
        np.testing.assert_array_equal(full, kv, err_msg="p=%d" % p)


def test_beam_search_kv_matches_full_forward():
    """The KV-cached beam strategy (shared prefill + per-step cache-row
    gathers) must return the SAME tokens as the full-forward strategy —
    untrained and trained, several beam widths, both pos_emb modes."""
    from elasticdl_tpu.api.generation import beam_search_generate

    for extra in ("", "; pos_emb='rope'", "; num_kv_heads=1"):
        mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
        trainer = Trainer(
            load_model_spec_from_module(zoo), mesh=mesh,
            model_params=PARAMS + extra,
        )
        state = trainer.init_state(_cycle_batch())
        prompt = np.asarray([[1, 2, 3], [4, 5, 6]], np.int32)
        for beams in (1, 3):
            full = np.asarray(
                beam_search_generate(trainer, state, prompt, 5,
                                     num_beams=beams)
            )
            kv = np.asarray(
                beam_search_generate(trainer, state, prompt, 5,
                                     num_beams=beams, use_cache=True)
            )
            np.testing.assert_array_equal(
                full, kv, err_msg="%s beams=%d" % (extra, beams)
            )

    # trained cycle model: the cached strategy finds the cycle too
    for step in range(200):
        state, loss = trainer.train_step(state, _cycle_batch(seed=step))
    out = np.asarray(
        beam_search_generate(trainer, state,
                             np.asarray([[3, 4, 5, 6]], np.int32), 8,
                             num_beams=3, use_cache=True)
    )[0]
    np.testing.assert_array_equal(out, (3 + np.arange(12)) % 8)


def test_speculative_matches_target_greedy():
    """Speculative decoding must reproduce the TARGET model's greedy
    tokens EXACTLY, independent of draft quality (an untrained draft
    just accepts less) and of gamma."""
    from elasticdl_tpu.api.generation import speculative_generate

    target = _trainer()
    t_state = target.init_state(_cycle_batch())
    for step in range(200):
        t_state, loss = target.train_step(t_state,
                                          _cycle_batch(seed=step))
    assert float(loss) < 0.2

    # draft (a): untrained (worst case — rejects constantly)
    draft_cold = Trainer(
        load_model_spec_from_module(zoo),
        mesh=mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1]),
        model_params=PARAMS,
    )
    d_cold = draft_cold.init_state(_cycle_batch())
    # draft (b): trained (best case — accepts almost everything)
    draft_hot = Trainer(
        load_model_spec_from_module(zoo),
        mesh=mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1]),
        model_params=PARAMS,
    )
    d_hot = draft_hot.init_state(_cycle_batch(seed=1))
    for step in range(200):
        d_hot, _ = draft_hot.train_step(d_hot,
                                        _cycle_batch(seed=step + 7))

    prompt = np.asarray([[1, 2, 3], [4, 5, 6]], np.int32)
    ref = np.asarray(
        autoregressive_generate(target, t_state, prompt, 6,
                                use_cache=True)
    )
    for d_trainer, d_state, name in (
        (draft_cold, d_cold, "cold"),
        (draft_hot, d_hot, "hot"),
    ):
        for gamma in (1, 3, 5):
            got = np.asarray(
                speculative_generate(target, t_state, d_trainer,
                                     d_state, prompt, 6, gamma=gamma)
            )
            np.testing.assert_array_equal(
                ref, got, err_msg="%s gamma=%d" % (name, gamma)
            )


def test_int8_kv_cache_decode():
    """kv_cache_dtype='int8' stores the decode cache as per-row int8
    with f32 scales — the cache-bandwidth knob. On a trained cycle
    model (decisive margins) the greedy tokens must equal the float-
    cache decode for plain, RoPE, GQA, and beam/speculative paths, and
    the cache leaves must actually be int8."""
    from elasticdl_tpu.api.generation import (
        beam_search_generate,
        speculative_generate,
    )

    for extra in ("", "; pos_emb='rope'", "; num_kv_heads=1"):
        mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
        t_f = Trainer(
            load_model_spec_from_module(zoo), mesh=mesh,
            model_params=PARAMS + extra,
        )
        t_q = Trainer(
            load_model_spec_from_module(zoo), mesh=mesh,
            model_params=PARAMS + extra + "; kv_cache_dtype='int8'",
        )
        state = t_f.init_state(_cycle_batch())
        for step in range(200):
            state, loss = t_f.train_step(state, _cycle_batch(seed=step))
        assert float(loss) < 0.25
        # same params serve both trainers (the knob changes only the
        # cache buffers, not the param tree)
        prompt = np.asarray([[2, 3, 4], [5, 6, 7]], np.int32)
        ref = np.asarray(
            autoregressive_generate(t_f, state, prompt, 6,
                                    use_cache=True)
        )
        got = np.asarray(
            autoregressive_generate(t_q, state, prompt, 6,
                                    use_cache=True)
        )
        np.testing.assert_array_equal(ref, got, err_msg=extra)
        if not extra:
            # cache leaves really are int8 (+ f32 scales)
            kv = jax.eval_shape(
                lambda: t_q.model.init(
                    jax.random.PRNGKey(0),
                    {"tokens": jnp.zeros((2, 1), jnp.int32)},
                    training=False, decode=True,
                )
            )["cache"]
            leaves = {
                jax.tree_util.keystr(p): leaf.dtype
                for p, leaf in
                jax.tree_util.tree_flatten_with_path(kv)[0]
            }
            assert any(d == jnp.int8 for d in leaves.values()), leaves
            assert any(
                d == jnp.float32 for k, d in leaves.items()
                if "scale" in k
            ), leaves
            beam = np.asarray(
                beam_search_generate(t_q, state, prompt, 6,
                                     num_beams=2, use_cache=True)
            )
            np.testing.assert_array_equal(ref, beam)
            spec = np.asarray(
                speculative_generate(t_q, state, t_q, state, prompt, 6,
                                     gamma=3)
            )
            np.testing.assert_array_equal(ref, spec)


def test_distilled_draft_raises_acceptance():
    """The trained-draft pipeline (api/distill.py): warm-start a
    1-layer draft from a 2-layer target's own weights, distill it on
    the target's logits, and the speculative acceptance rate must jump
    vs a cold draft while the output stays EXACTLY the target's greedy
    tokens. Fewer verify calls = the wall-clock speedup mechanism."""
    from elasticdl_tpu.api.distill import distill_draft, warm_start_draft
    from elasticdl_tpu.api.generation import speculative_generate

    two_layer = PARAMS.replace("num_layers=1", "num_layers=2")
    target = Trainer(
        load_model_spec_from_module(zoo),
        mesh=mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1]),
        model_params=two_layer,
    )
    t_state = target.init_state(_cycle_batch())
    for step in range(250):
        t_state, loss = target.train_step(t_state,
                                          _cycle_batch(seed=step))
    assert float(loss) < 0.2

    draft = _trainer()  # 1 layer
    d_cold = draft.init_state(_cycle_batch())
    d_warm = warm_start_draft(t_state, d_cold)
    # embeddings/norm/head/block_0 copied; the (absent) block_1 is the
    # only capacity difference
    np.testing.assert_array_equal(
        np.asarray(d_warm.params["wte"]["embedding"]),
        np.asarray(t_state.params["wte"]["embedding"]),
    )
    d_hot, losses = distill_draft(
        target, t_state, draft, d_warm,
        [_cycle_batch(seed=s)[0]["tokens"] for s in range(60)],
        lr=3e-3,
    )
    assert losses[-1] < losses[0]  # KL to the teacher shrank

    prompt = np.asarray([[1, 2, 3], [4, 5, 6]], np.int32)
    ref = np.asarray(
        autoregressive_generate(target, t_state, prompt, 6,
                                use_cache=True)
    )
    out_cold, st_cold = speculative_generate(
        target, t_state, draft, d_cold, prompt, 6, gamma=4,
        return_stats=True,
    )
    out_hot, st_hot = speculative_generate(
        target, t_state, draft, d_hot, prompt, 6, gamma=4,
        return_stats=True,
    )
    np.testing.assert_array_equal(ref, np.asarray(out_cold))
    np.testing.assert_array_equal(ref, np.asarray(out_hot))
    # the distilled draft mimics the (cycle-trained) target well enough
    # to accept most proposals; the cold draft mostly rejects
    assert st_hot["acceptance_rate"] >= 0.6
    assert st_hot["verify_calls"] < st_cold["verify_calls"]
    assert st_hot["verify_calls"] <= 3  # vs 5 target steps plain


def test_speculative_validation():
    from elasticdl_tpu.api.generation import speculative_generate

    target = _trainer()
    t_state = target.init_state(_cycle_batch())
    prompt = np.asarray([[1, 2, 3]], np.int32)
    with pytest.raises(ValueError, match="gamma"):
        speculative_generate(target, t_state, target, t_state, prompt,
                             4, gamma=0)
    with pytest.raises(ValueError, match="verify chunk"):
        # 3 + 12 + 8 - 1 > 16
        speculative_generate(target, t_state, target, t_state, prompt,
                             12, gamma=8)


def test_speculative_draft_swap_not_cached_together():
    """Two drafts with different architectures against one target must
    not share a compiled fn (the executable closes over the draft
    module); output stays exact for both."""
    from elasticdl_tpu.api.generation import speculative_generate

    target = _trainer()
    t_state = target.init_state(_cycle_batch())
    for step in range(200):
        t_state, _ = target.train_step(t_state, _cycle_batch(seed=step))
    prompt = np.asarray([[1, 2, 3]], np.int32)
    ref = np.asarray(
        autoregressive_generate(target, t_state, prompt, 5,
                                use_cache=True)
    )
    for dp in (PARAMS, PARAMS.replace("num_layers=1", "num_layers=2")):
        d_tr = Trainer(
            load_model_spec_from_module(zoo),
            mesh=mesh_lib.build_mesh({"dp": 1},
                                     devices=jax.devices()[:1]),
            model_params=dp,
        )
        d_st = d_tr.init_state(_cycle_batch())
        got = np.asarray(
            speculative_generate(target, t_state, d_tr, d_st, prompt,
                                 5, gamma=3)
        )
        np.testing.assert_array_equal(ref, got, err_msg=dp)


def test_chunked_decode_fuzz_vs_sequential():
    """Seeded sweep: decoding a chunk of t tokens must equal t
    sequential single-token steps — logits AND caches — across random
    (t, start offset, pos_emb, GQA, window) configs."""
    import jax.numpy as jnp

    from model_zoo.transformer_lm.transformer_lm import TransformerLM

    rs = np.random.RandomState(77)
    for trial in range(6):
        extra = {}
        if rs.randint(2):
            extra["pos_emb"] = "rope"
        if rs.randint(2):
            extra["num_kv_heads"] = 1
        if rs.randint(2):
            extra["attn_window"] = int(rs.choice([3, 5]))
        model = TransformerLM(vocab_size=16, seq_len=24, embed_dim=32,
                              num_heads=2, num_layers=1,
                              tp_shard=False, **extra)
        start = int(rs.randint(0, 6))
        t = int(rs.randint(2, 7))
        toks = jnp.asarray(rs.randint(0, 16, size=(2, start + t)),
                           jnp.int32)
        variables = model.init(jax.random.PRNGKey(trial),
                               {"tokens": toks[:, :1]},
                               training=False, decode=True)
        params = variables["params"]
        kv = jax.tree.map(jnp.zeros_like, variables["cache"])
        # consume the first `start` tokens one at a time (both paths)
        for i in range(start):
            _, upd = model.apply({"params": params, "cache": kv},
                                 {"tokens": toks[:, i:i+1]},
                                 training=False, decode=True,
                                 mutable=["cache"])
            kv = upd["cache"]
        kv_seq = kv
        seq_logits = []
        for i in range(start, start + t):
            lg, upd = model.apply({"params": params, "cache": kv_seq},
                                  {"tokens": toks[:, i:i+1]},
                                  training=False, decode=True,
                                  mutable=["cache"])
            kv_seq = upd["cache"]
            seq_logits.append(np.asarray(lg[:, 0]))
        lg_chunk, upd_chunk = model.apply(
            {"params": params, "cache": kv},
            {"tokens": toks[:, start:]},
            training=False, decode=True, mutable=["cache"],
        )
        tag = "trial=%d %r start=%d t=%d" % (trial, extra, start, t)
        np.testing.assert_allclose(
            np.asarray(lg_chunk), np.stack(seq_logits, axis=1),
            rtol=2e-5, atol=2e-6, err_msg=tag,
        )
        for a, b in zip(jax.tree.leaves(upd_chunk["cache"]),
                        jax.tree.leaves(kv_seq)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-5, atol=2e-6,
                                       err_msg=tag)
