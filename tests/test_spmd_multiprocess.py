"""True multi-host SPMD: 2 OS processes x 4 virtual CPU devices each, gloo
collectives, real gRPC master. The TPU-pod execution model end-to-end —
both hosts run the same compiled step in lockstep while pulling tasks
elastically from the master."""

import os
import socket
import subprocess
import sys

import pytest

from elasticdl_tpu.common.model_utils import load_model_spec_from_module
from elasticdl_tpu.data import recordio_gen
from elasticdl_tpu.master.master import Master

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spec():
    from model_zoo.mnist_functional_api import mnist_functional_api as zoo

    return load_model_spec_from_module(zoo)


@pytest.mark.slow
def test_two_process_host_embedding_parity(tmp_path):
    """VERDICT round-2 item #5: host-spill embedding tables partitioned
    over 2 real processes (4 virtual devices each) train to parity with
    a single-process run of the identical global batch stream — the
    reference's PS capacity-scales-with-fleet property, TPU-style."""
    import numpy as np

    out_dir = str(tmp_path)
    coord_port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    steps = 4
    procs = [
        subprocess.Popen(
            [
                sys.executable,
                os.path.join(REPO, "tests", "host_spmd_proc_main.py"),
                str(pid), "2", str(coord_port), out_dir, "4", str(steps),
            ],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(2)
    ]
    try:
        outs = [p.communicate(timeout=300)[0] for p in procs]
        for i, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, "proc %d failed:\n%s" % (
                i, out[-3000:])
            assert "HOST_SPMD_DONE" in out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    # single-process baseline over the identical global stream
    from elasticdl_tpu.common.model_utils import (
        load_model_spec_from_module as _load,
    )
    from elasticdl_tpu.embedding.host_bridge import attach_from_spec
    from elasticdl_tpu.parallel import mesh as mesh_lib
    from elasticdl_tpu.training.trainer import Trainer
    from model_zoo.deepfm_host_embedding import deepfm_host_embedding as z

    spec = _load(z)
    trainer = Trainer(spec, mesh=mesh_lib.local_mesh())
    manager = attach_from_spec(trainer, spec)
    rng = np.random.RandomState(7)
    state = None
    base_losses = []
    for _ in range(steps):
        ids = rng.randint(0, 50, size=(16, 10)).astype(np.int32)
        labels = rng.randint(0, 2, size=(16,)).astype(np.int32)
        batch = ({"feature": ids}, labels)
        if state is None:
            state = trainer.init_state(batch)
        state, loss = trainer.train_step(state, batch)
        base_losses.append(float(loss))

    d0 = np.load(os.path.join(out_dir, "proc0.npz"))
    d1 = np.load(os.path.join(out_dir, "proc1.npz"))
    np.testing.assert_allclose(d0["losses"], base_losses, rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(d1["losses"], base_losses, rtol=1e-5,
                               atol=1e-6)
    for name, t in manager.tables().items():
        base_ids, base_vals = t.engine.param.export_rows()
        base_map = dict(zip(base_ids.tolist(), base_vals))
        merged = {}
        for d in (d0, d1):
            merged.update(
                zip(d[name + ".ids"].tolist(), d[name + ".values"])
            )
        assert sorted(merged) == sorted(base_map)
        for i in merged:
            np.testing.assert_allclose(
                merged[i], base_map[i], rtol=1e-5, atol=1e-6
            )


class _DrillInfraError(AssertionError):
    """Infra-class drill failure (timeout / dead subprocess) — the
    load-sensitive mode the single retry is allowed to absorb. The
    post-completion correctness assertions (step parity, dispatcher
    drained, eval aggregated) are NOT this class and fail hard."""


@pytest.mark.slow
def test_two_process_spmd_train(tmp_path):
    """Known load-sensitive drill (see .claude/skills/verify/SKILL.md):
    the two jax subprocesses + master can outlast their gRPC deadlines
    under heavily parallel pytest runs. One retry with a fresh master/
    ports absorbs INFRA failures only (timeouts, dead subprocesses);
    correctness assertions fail hard, and a real infra regression
    fails both attempts."""
    import warnings

    try:
        _two_process_spmd_drill(tmp_path / "a")
    except _DrillInfraError as e:
        warnings.warn(
            "two-process SPMD drill retried after infra failure: %s"
            % (str(e)[:500],)
        )
        _two_process_spmd_drill(tmp_path / "b")


def _two_process_spmd_drill(tmp_path):
    tmp_path.mkdir(parents=True, exist_ok=True)
    data_dir = str(tmp_path / "train")
    val_dir = str(tmp_path / "val")
    recordio_gen.gen_mnist_like(data_dir, num_files=2, records_per_file=64)
    recordio_gen.gen_mnist_like(val_dir, num_files=1, records_per_file=32,
                                seed=3)

    master = None
    procs = []
    try:
        master = Master(
            _spec(),
            training_data=data_dir,
            validation_data=val_dir,
            minibatch_size=8,   # per-host; global batch = 16
            records_per_task=32,
            num_epochs=1,
            evaluation_steps=4,
            port=0,
        )
        master.prepare()
        coord_port = _free_port()
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        for pid in range(2):
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable,
                        os.path.join(REPO, "tests", "spmd_proc_main.py"),
                        str(pid), "2", str(master.port), str(coord_port),
                        data_dir, "4",
                    ],
                    env=env,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    text=True,
                )
            )
        outs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=300)
            except subprocess.TimeoutExpired as e:
                raise _DrillInfraError("subprocess timeout: %s" % (e,))
            outs.append(out)
        for i, (p, out) in enumerate(zip(procs, outs)):
            if p.returncode != 0 or "SPMD_PROC_DONE" not in out:
                raise _DrillInfraError(
                    "proc %d rc=%s:\n%s" % (i, p.returncode, out[-3000:])
                )
        tail = "\n--- proc0 ---\n%s\n--- proc1 ---\n%s" % (
            outs[0][-1500:], outs[1][-1500:])
        assert master.task_d.finished(), (
            "dispatcher not finished; todo=%r doing=%r%s"
            % (master.task_d._todo, master.task_d._doing, tail))
        # both hosts agreed on the same number of global steps
        import re

        steps = [
            int(re.search(r"steps=(\d+)", o).group(1)) for o in outs
        ]
        assert steps[0] == steps[1], (steps, tail)
        # 128 records / 16 global batch = 8 full global rounds minimum;
        # uneven task streams can add padded rounds, never lose records
        assert steps[0] >= 128 // 16, (steps, tail)
        # eval ran and aggregated on the master
        assert master.evaluation_service.completed_job_metrics, (
            "no completed eval jobs%s" % tail)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()  # reap BEFORE any retry adds fresh load
        if master is not None:
            master.stop()
