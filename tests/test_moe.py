"""Mixture-of-experts over the ep axis: dispatch math vs the token-loop
oracle, capacity semantics, expert params sharded over ep, ep-mesh
training matching single-device, and the zoo family e2e."""

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.common.constants import MeshAxis
from elasticdl_tpu.common.model_utils import (
    format_params_str,
    load_model_spec_from_module,
)
from elasticdl_tpu.parallel import mesh as mesh_lib, moe
from elasticdl_tpu.training.trainer import Trainer

import pytest

# CI drills shard (make test-drills): the sub-5-min per-commit gate excludes this file.
pytestmark = pytest.mark.slow


def _moe_params(d=8, h=16, e=4, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "router": jnp.asarray(rng.standard_normal((d, e)), jnp.float32),
        "w_up": jnp.asarray(
            rng.standard_normal((e, d, h)) / np.sqrt(d), jnp.float32
        ),
        "b_up": jnp.zeros((e, h), jnp.float32),
        "w_down": jnp.asarray(
            rng.standard_normal((e, h, d)) / np.sqrt(h), jnp.float32
        ),
        "b_down": jnp.zeros((e, d), jnp.float32),
    }


def test_moe_matches_token_loop_oracle():
    params = _moe_params()
    x = jnp.asarray(
        np.random.default_rng(1).standard_normal((32, 8)), jnp.float32
    )
    y, aux, stats = moe.moe_mlp_apply(params, x)
    want = moe.moe_reference(params, x)
    np.testing.assert_allclose(np.asarray(y), want, atol=1e-5, rtol=1e-4)
    assert float(aux) > 0
    assert 0.0 <= float(stats["dropped_fraction"]) < 1.0


def test_top2_matches_token_loop_oracle():
    params = _moe_params(seed=3)
    x = jnp.asarray(
        np.random.default_rng(4).standard_normal((32, 8)), jnp.float32
    )
    y, aux, stats = moe.moe_mlp_apply(
        params, x, capacity_factor=2.0, router_top_k=2
    )
    want = moe.moe_reference(
        params, x, capacity_factor=2.0, router_top_k=2
    )
    np.testing.assert_allclose(np.asarray(y), want, atol=1e-5, rtol=1e-4)
    assert float(aux) > 0


def test_infer_formulation_matches_dispatch_at_full_capacity():
    """moe_mlp_infer (dense per-expert, drop-free — the decode/prefill
    path) must equal moe_mlp_apply when the dispatch capacity admits
    every choice (cf = E/k), for both Switch and GShard routing — the
    two formulations are the same math with and without the [T, E, C]
    queues."""
    for k, seed in ((1, 5), (2, 6)):
        params = _moe_params(seed=seed)
        x = jnp.asarray(
            np.random.default_rng(seed + 10).standard_normal((32, 8)),
            jnp.float32,
        )
        y_infer = moe.moe_mlp_infer(params, x, router_top_k=k)
        y_disp, _, stats = moe.moe_mlp_apply(
            params, x, capacity_factor=4.0 / k, router_top_k=k
        )
        assert float(stats["dropped_fraction"]) == 0.0
        np.testing.assert_allclose(
            np.asarray(y_infer), np.asarray(y_disp),
            atol=1e-5, rtol=1e-4,
        )


def test_top2_combine_weights_renormalized():
    """Every token kept in both choices must have combine weights that
    sum to exactly 1 (GShard g1/g2 normalization); with generous
    capacity no token is dropped."""
    params = _moe_params(seed=5)
    x = jnp.asarray(
        np.random.default_rng(6).standard_normal((16, 8)), jnp.float32
    )
    logits = x @ params["router"]
    capacity = moe.expert_capacity(32, 4, 4.0)
    dispatch, combine, _, stats = moe.topk_dispatch(logits, capacity, k=2)
    assert float(stats["dropped_fraction"]) == 0.0
    sums = np.asarray(jnp.sum(combine, axis=(1, 2)))
    np.testing.assert_allclose(sums, np.ones(16), rtol=1e-5)
    # each token occupies exactly two expert queue slots
    np.testing.assert_allclose(
        np.asarray(jnp.sum(dispatch, axis=(1, 2))), 2 * np.ones(16)
    )


def test_topk_capacity_prioritizes_primary_choice():
    """Under overflow, rank-0 choices must claim capacity before any
    rank-1 choice: force every token's top pick to expert 0 and check
    the kept rank-0 count is the full capacity."""
    t, e = 16, 4
    logits = np.zeros((t, e), np.float32)
    logits[:, 0] = 4.0  # every token: top-1 = expert 0
    logits[:, 1] = 2.0  # every token: top-2 = expert 1
    capacity = 4
    dispatch, combine, _, _ = moe.topk_dispatch(
        jnp.asarray(logits), capacity, k=2
    )
    d = np.asarray(dispatch)
    # expert 0 queue: filled by the FIRST 4 tokens' rank-0 picks
    assert d[:4, 0].sum() == 4.0 and d[4:, 0].sum() == 0.0
    # expert 1 queue: rank-1 picks, also first 4 tokens by arrival
    assert d[:4, 1].sum() == 4.0 and d[4:, 1].sum() == 0.0
    import pytest

    with pytest.raises(ValueError, match="top-k"):
        moe.topk_dispatch(jnp.asarray(logits), capacity, k=5)


def test_capacity_drops_overflow_tokens():
    """With capacity_factor tiny, most tokens overflow: their MoE output
    must be exactly zero (residual-only passthrough)."""
    params = _moe_params()
    x = jnp.asarray(
        np.random.default_rng(2).standard_normal((64, 8)), jnp.float32
    )
    y, _, stats = moe.moe_mlp_apply(params, x, capacity_factor=0.1)
    capacity = moe.expert_capacity(64, 4, 0.1)
    n_nonzero = int((np.abs(np.asarray(y)).sum(-1) > 1e-12).sum())
    assert n_nonzero <= capacity * 4
    assert float(stats["dropped_fraction"]) > 0.5


def test_dispatch_one_expert_per_token():
    logits = jnp.asarray(
        np.random.default_rng(3).standard_normal((40, 4)), jnp.float32
    )
    dispatch, combine, aux, _ = moe.top1_dispatch(logits, capacity=16)
    d = np.asarray(dispatch)
    # each token occupies at most one (expert, slot)
    assert (d.reshape(40, -1).sum(-1) <= 1 + 1e-6).all()
    # each (expert, slot) holds at most one token
    assert (d.reshape(40, -1).sum(0) <= 1 + 1e-6).all()
    # combine weights are the chosen-expert softmax probs
    probs = np.asarray(jax.nn.softmax(logits, -1))
    c = np.asarray(combine).sum((1, 2))
    chosen = probs.max(-1)
    kept = d.reshape(40, -1).sum(-1) > 0
    np.testing.assert_allclose(c[kept], chosen[kept], atol=1e-6)


CFG = dict(vocab_size=64, seq_len=16, embed_dim=32, num_heads=4,
           num_layers=1, num_experts=4, attn_impl="xla")


def _trainer(mesh):
    from model_zoo.transformer_moe import transformer_moe as zoo

    return Trainer(
        load_model_spec_from_module(zoo),
        mesh=mesh,
        model_params=format_params_str(CFG),
    )


def _batch(batch=8, seed=0):
    rng = np.random.RandomState(seed)
    tokens = rng.randint(
        0, CFG["vocab_size"], size=(batch, CFG["seq_len"] + 1)
    ).astype(np.int32)
    return ({"tokens": tokens[:, :-1]}, tokens[:, 1:])


def test_expert_params_sharded_over_ep():
    mesh = mesh_lib.build_mesh({"ep": 4, "dp": 2})
    trainer = _trainer(mesh)
    state = trainer.init_state(_batch())
    w_up = state.params["block_0"]["w_up"]
    assert w_up.sharding.spec == P(MeshAxis.EP, None, None)
    assert w_up.sharding.shard_shape(w_up.shape)[0] == 1  # 4 experts / 4
    # router replicated (no annotation)
    router = state.params["block_0"]["router"]
    assert router.sharding.spec in (P(), P(None, None))


def test_ep_mesh_matches_single_device():
    batch = _batch()
    single = _trainer(
        mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    )
    s_state = single.init_state(batch)
    ep = _trainer(mesh_lib.build_mesh({"ep": 4, "dp": 2}))
    e_state = ep.init_state(batch)
    for a, b in zip(jax.tree.leaves(s_state.params),
                    jax.tree.leaves(e_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    for _ in range(3):
        s_state, ls = single.train_step(s_state, batch)
        e_state, le = ep.train_step(e_state, batch)
        np.testing.assert_allclose(float(le), float(ls), rtol=1e-4,
                                   atol=1e-5)


def test_moe_kv_decode_matches_full_forward():
    """The MoE family speaks the KV-cache convention: cached decode
    (batched prefill + per-token steps) must produce exactly the tokens
    of the uncached full-forward decode. Decode/prefill route drop-free
    (moe_mlp_infer); the uncached forward is capacity-bounded, so the
    test sets capacity_factor = num_experts / top_k — the documented
    threshold above which the two formulations provably agree."""
    from model_zoo.transformer_moe import transformer_moe as moe_zoo

    from elasticdl_tpu.api.generation import autoregressive_generate

    trainer = Trainer(
        load_model_spec_from_module(moe_zoo),
        mesh=mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1]),
        model_params=format_params_str(
            dict(vocab_size=16, seq_len=24, embed_dim=32, num_heads=2,
                 num_layers=2, num_experts=4, router_top_k=2,
                 capacity_factor=2.0,  # = E/k: uncached is drop-free too
                 attn_impl="xla")
        ),
    )
    # train on the deterministic cycle so argmax margins are decisive —
    # the int8-cache equality below must not hinge on near-random
    # logits surviving quantization noise
    def cycle(seed):
        rs = np.random.RandomState(seed)
        starts = rs.randint(0, 16, size=(4, 1))
        t = ((starts + np.arange(25)[None, :]) % 16).astype(np.int32)
        return {"tokens": t[:, :-1]}, t[:, 1:]

    state = trainer.init_state(cycle(0))
    for step in range(200):
        state, loss = trainer.train_step(state, cycle(step))
    # the MoE loss carries the aux load-balancing term (~0.04 floor);
    # CE this low means decisive argmax margins on the cycle
    assert float(loss) < 0.4
    prompt = np.asarray([[1, 2, 3], [4, 5, 6], [7, 8, 9], [3, 1, 2]],
                        np.int32)
    full = np.asarray(
        autoregressive_generate(trainer, state, prompt, 8)
    )
    kv = np.asarray(
        autoregressive_generate(trainer, state, prompt, 8,
                                use_cache=True)
    )
    np.testing.assert_array_equal(full, kv)

    # the other strategies ride the same convention: beam(1) and
    # self-draft speculative must reproduce the greedy stream exactly;
    # beam(2) exercises the cache-row gathers for shape/range
    from elasticdl_tpu.api.generation import (
        beam_search_generate,
        speculative_generate,
    )

    beam1 = np.asarray(
        beam_search_generate(trainer, state, prompt, 8, num_beams=1,
                             use_cache=True)
    )
    np.testing.assert_array_equal(full, beam1)
    beam2 = np.asarray(
        beam_search_generate(trainer, state, prompt, 8, num_beams=2,
                             use_cache=True)
    )
    assert beam2.shape == full.shape
    assert beam2.min() >= 0 and beam2.max() < 16
    spec = np.asarray(
        speculative_generate(trainer, state, trainer, state, prompt, 8,
                             gamma=3)
    )
    np.testing.assert_array_equal(full, spec)

    # the int8 KV cache knob plumbs through the MoE family too
    t_q = Trainer(
        load_model_spec_from_module(moe_zoo),
        mesh=mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1]),
        model_params=format_params_str(
            dict(vocab_size=16, seq_len=24, embed_dim=32, num_heads=2,
                 num_layers=2, num_experts=4, router_top_k=2,
                 capacity_factor=2.0, attn_impl="xla",
                 kv_cache_dtype="int8")
        ),
    )
    kv_q = np.asarray(
        autoregressive_generate(t_q, state, prompt, 8, use_cache=True)
    )
    np.testing.assert_array_equal(full, kv_q)


def test_zoo_e2e_local_executor(tmp_path):
    from elasticdl_tpu.api.local_executor import LocalExecutor
    from elasticdl_tpu.common.model_utils import get_model_spec
    from elasticdl_tpu.data import recordio_gen

    train_dir = str(tmp_path / "train")
    recordio_gen.gen_tokens_like(train_dir, num_files=1,
                                 records_per_file=32)
    spec = get_model_spec(
        "model_zoo", "transformer_moe.transformer_moe.custom_model"
    )
    executor = LocalExecutor(
        spec,
        training_data=train_dir,
        validation_data=train_dir,
        minibatch_size=8,
        num_epochs=1,
        records_per_task=32,
        model_params="vocab_size=64;seq_len=32;embed_dim=32;num_heads=2;"
                     "num_layers=1;num_experts=4;attn_impl=xla",
    )
    state, metrics = executor.run()
    assert int(state.step) == 4
    assert np.isfinite(executor.losses).all()
    assert 0.0 <= metrics["token_accuracy"] <= 1.0


def _grouped_oracle(params, x, shards, capacity_factor, k):
    """Per-group semantics of the a2a path: each contiguous token group
    routes independently with its own capacity queues (GShard groups).
    Stitches moe_mlp_apply over row-major groups — exactly how the
    (dp, fsdp, ep) in_spec splits rows."""
    groups = np.split(np.asarray(x), shards)
    ys = [
        np.asarray(moe.moe_mlp_apply(
            params, jnp.asarray(g), capacity_factor=capacity_factor,
            router_top_k=k,
        )[0])
        for g in groups
    ]
    return np.concatenate(ys)


def test_a2a_dispatch_matches_grouped_oracle():
    """Explicit all-to-all path == per-group einsum dispatch, including
    capacity drops (cf small enough to saturate queues)."""
    mesh = mesh_lib.build_mesh({"dp": 2, "ep": 4})
    params = _moe_params(e=8, seed=5)
    x = jnp.asarray(
        np.random.default_rng(6).standard_normal((64, 8)), jnp.float32
    )
    for k, cf in ((1, 1.0), (2, 1.25)):
        with mesh:
            y, aux, stats = jax.jit(
                lambda p, xv, k=k, cf=cf: moe.moe_mlp_apply_a2a(
                    p, xv, mesh, capacity_factor=cf, router_top_k=k
                )
            )(params, x)
        want = _grouped_oracle(params, x, 8, cf, k)
        np.testing.assert_allclose(np.asarray(y), want,
                                   atol=1e-5, rtol=1e-4)
        assert float(aux) > 0
        assert 0.0 <= float(stats["dropped_fraction"]) < 1.0


def test_a2a_dispatch_matches_einsum_drop_free():
    """With capacity that cannot saturate (cf = E), the a2a and global
    einsum paths are the same math — outputs AND aux loss match."""
    mesh = mesh_lib.build_mesh({"dp": 2, "ep": 4})
    params = _moe_params(e=8, seed=7)
    x = jnp.asarray(
        np.random.default_rng(8).standard_normal((64, 8)), jnp.float32
    )
    with mesh:
        y_a, aux_a, stats_a = jax.jit(
            lambda p, xv: moe.moe_mlp_apply_a2a(
                p, xv, mesh, capacity_factor=8.0, router_top_k=2
            )
        )(params, x)
    y_e, aux_e, stats_e = moe.moe_mlp_apply(
        params, x, capacity_factor=8.0, router_top_k=2
    )
    np.testing.assert_allclose(np.asarray(y_a), np.asarray(y_e),
                               atol=1e-5, rtol=1e-4)
    np.testing.assert_allclose(float(aux_a), float(aux_e), rtol=1e-5)
    assert float(stats_a["dropped_fraction"]) == 0.0
    assert float(stats_e["dropped_fraction"]) == 0.0


def test_a2a_dispatch_gradients_flow():
    """AD through the double all_to_all: expert-weight and router grads
    match the grouped einsum formulation."""
    mesh = mesh_lib.build_mesh({"dp": 2, "ep": 4})
    params = _moe_params(e=8, seed=9)
    x = jnp.asarray(
        np.random.default_rng(10).standard_normal((32, 8)), jnp.float32
    )

    def loss_a2a(p):
        with mesh:
            y, aux, _ = moe.moe_mlp_apply_a2a(
                p, x, mesh, capacity_factor=8.0, router_top_k=2
            )
        return jnp.mean(y ** 2) + 0.01 * aux

    def loss_grouped(p):
        ys = []
        auxs = []
        for g in jnp.split(x, 8):
            y, aux, _ = moe.moe_mlp_apply(
                p, g, capacity_factor=8.0, router_top_k=2
            )
            ys.append(y)
            auxs.append(aux)
        # drop-free: grouped aux means == global aux is NOT exact for
        # the product formula, so compare value-side grads only where
        # they agree — use the output loss plus the a2a's own aux via
        # stop-gradient-free recomputation on the full batch
        y_full, aux_full, _ = moe.moe_mlp_apply(
            p, x, capacity_factor=8.0, router_top_k=2
        )
        return jnp.mean(jnp.concatenate(ys) ** 2) + 0.01 * aux_full

    with mesh:
        g_a = jax.jit(jax.grad(loss_a2a))(params)
    g_e = jax.grad(loss_grouped)(params)
    for key in params:
        np.testing.assert_allclose(
            np.asarray(g_a[key]), np.asarray(g_e[key]),
            atol=1e-5, rtol=1e-3,
        )


def test_a2a_dispatch_rejects_bad_shapes():
    import pytest

    mesh = mesh_lib.build_mesh({"dp": 2, "ep": 4})
    params = _moe_params(e=6)
    x = jnp.zeros((64, 8))
    with pytest.raises(ValueError, match="experts not divisible"):
        moe.moe_mlp_apply_a2a(params, x, mesh)
    with pytest.raises(ValueError, match="tokens not divisible"):
        moe.moe_mlp_apply_a2a(_moe_params(e=8), jnp.zeros((63, 8)), mesh)


def test_infer_gather_matches_dense_formulation():
    """moe_mlp_infer_gather (sorted ragged_dot, k/E FLOPs) computes the
    same drop-free function as the dense per-expert loop."""
    for k, e, seed in ((1, 4, 11), (2, 4, 12), (2, 8, 13)):
        params = _moe_params(e=e, seed=seed)
        x = jnp.asarray(
            np.random.default_rng(seed).standard_normal((48, 8)),
            jnp.float32,
        )
        dense = moe.moe_mlp_infer(params, x, router_top_k=k)
        gather = moe.moe_mlp_infer_gather(params, x, router_top_k=k)
        np.testing.assert_allclose(
            np.asarray(gather), np.asarray(dense),
            atol=1e-5, rtol=1e-4,
        )


def test_moe_gather_kv_decode_matches_full_forward():
    """The dropless gather prefill/decode path keeps the KV-cache
    determinism contract: cached decode == uncached full forward."""
    from model_zoo.transformer_moe import transformer_moe as moe_zoo

    from elasticdl_tpu.api.generation import autoregressive_generate

    trainer = Trainer(
        load_model_spec_from_module(moe_zoo),
        mesh=mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1]),
        model_params=format_params_str(
            dict(vocab_size=16, seq_len=24, embed_dim=32, num_heads=2,
                 num_layers=2, num_experts=4, router_top_k=2,
                 capacity_factor=2.0, attn_impl="xla",
                 moe_infer_impl="gather")
        ),
    )

    def cycle(seed):
        rs = np.random.RandomState(seed)
        starts = rs.randint(0, 16, size=(4, 1))
        t = ((starts + np.arange(25)[None, :]) % 16).astype(np.int32)
        return {"tokens": t[:, :-1]}, t[:, 1:]

    state = trainer.init_state(cycle(0))
    for step in range(200):
        state, loss = trainer.train_step(state, cycle(step))
    # decisive argmax margins: equality between the gather prefill path
    # and the capacity-bounded uncached forward must not hinge on
    # near-random logits (same guard as the dense twin test)
    assert float(loss) < 0.4
    prompt = np.asarray([[1, 2, 3], [4, 5, 6]], np.int32)
    full = np.asarray(
        autoregressive_generate(trainer, state, prompt, 8)
    )
    kv = np.asarray(
        autoregressive_generate(trainer, state, prompt, 8,
                                use_cache=True)
    )
    np.testing.assert_array_equal(full, kv)
