"""Transformer LM family: single-chip flash path vs sequence-parallel
ring path produce the same training step, and training reduces loss."""

import numpy as np
import pytest

import jax

from elasticdl_tpu.common.model_utils import load_model_spec_from_module
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.training.trainer import Trainer
from model_zoo.transformer_lm import transformer_lm as zoo

# CI drills shard (make test-drills): the sub-5-min per-commit gate excludes this file.
pytestmark = pytest.mark.slow

PARAMS = (
    "vocab_size=32; seq_len=16; embed_dim=32; num_heads=2; num_layers=1"
)


def _batch(bsz=8, seq_len=16, vocab=32, seed=0):
    rs = np.random.RandomState(seed)
    tokens = rs.randint(0, vocab, size=(bsz, seq_len + 1)).astype(np.int32)
    return {"tokens": tokens[:, :-1]}, tokens[:, 1:]


def test_single_device_vs_ring_same_step():
    spec = load_model_spec_from_module(zoo)
    batch = _batch()

    mesh1 = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    t1 = Trainer(spec, mesh=mesh1, model_params=PARAMS)
    s1 = t1.init_state(batch)
    s1, loss1 = t1.train_step(s1, batch)

    mesh8 = mesh_lib.build_mesh({"dp": 2, "sp": 4})
    t8 = Trainer(spec, mesh=mesh8, model_params=PARAMS)
    s8 = t8.init_state(batch)
    s8, loss8 = t8.train_step(s8, batch)

    np.testing.assert_allclose(float(loss1), float(loss8), rtol=1e-3)
    # parameters after one update agree (same seed -> same init)
    p1 = jax.tree.leaves(s1.params)
    p8 = jax.tree.leaves(s8.params)
    for a, b in zip(p1, p8):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=1e-5
        )


def test_ulysses_attention_matches_naive():
    import jax.numpy as jnp

    from elasticdl_tpu.ops.attention import naive_attention
    from elasticdl_tpu.parallel.context_parallel import ulysses_attention

    mesh = mesh_lib.build_mesh({"dp": 2, "sp": 4})
    rs = np.random.RandomState(0)
    b, h, s, d = 4, 4, 32, 8
    q = jnp.asarray(rs.randn(b, h, s, d).astype(np.float32) * 0.3)
    k = jnp.asarray(rs.randn(b, h, s, d).astype(np.float32) * 0.3)
    v = jnp.asarray(rs.randn(b, h, s, d).astype(np.float32) * 0.3)
    with mesh:
        out = jax.jit(
            lambda q, k, v: ulysses_attention(q, k, v, mesh, causal=True)
        )(q, k, v)
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5
    )
    # heads (4) not divisible by sp (8): explicit error, not wrong math
    mesh8 = mesh_lib.build_mesh({"sp": 8})
    import pytest

    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, k, v, mesh8, causal=True)


def test_single_device_vs_ulysses_same_step():
    """Training parity: the Ulysses sp path reproduces the single-device
    step like the ring path does (heads=4 so sp=4 divides them)."""
    params = (
        "vocab_size=32; seq_len=16; embed_dim=32; num_heads=4; "
        "num_layers=1; sp_impl='ulysses'"
    )
    spec = load_model_spec_from_module(zoo)
    batch = _batch()

    mesh1 = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    t1 = Trainer(spec, mesh=mesh1, model_params=params)
    s1 = t1.init_state(batch)
    s1, loss1 = t1.train_step(s1, batch)

    mesh8 = mesh_lib.build_mesh({"dp": 2, "sp": 4})
    t8 = Trainer(spec, mesh=mesh8, model_params=params)
    s8 = t8.init_state(batch)
    s8, loss8 = t8.train_step(s8, batch)

    np.testing.assert_allclose(float(loss1), float(loss8), rtol=1e-3)
    for a, b in zip(
        jax.tree.leaves(s1.params), jax.tree.leaves(s8.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=1e-5
        )


def test_rope_relative_position_property():
    """Rotated q.k must depend only on relative distance: shifting every
    position by a constant leaves all attention scores unchanged."""
    import jax.numpy as jnp

    from elasticdl_tpu.ops.attention import apply_rope

    rs = np.random.RandomState(9)
    q = jnp.asarray(rs.randn(2, 2, 8, 16).astype(np.float32))
    k = jnp.asarray(rs.randn(2, 2, 8, 16).astype(np.float32))
    pos = jnp.arange(8)
    s0 = jnp.einsum(
        "bhqd,bhkd->bhqk", apply_rope(q, pos), apply_rope(k, pos)
    )
    s_shift = jnp.einsum(
        "bhqd,bhkd->bhqk",
        apply_rope(q, pos + 100), apply_rope(k, pos + 100),
    )
    np.testing.assert_allclose(
        np.asarray(s0), np.asarray(s_shift), rtol=1e-4, atol=1e-4
    )
    # norm-preserving rotation
    np.testing.assert_allclose(
        np.asarray(jnp.linalg.norm(apply_rope(q, pos), axis=-1)),
        np.asarray(jnp.linalg.norm(q, axis=-1)),
        rtol=1e-5,
    )


def test_rope_model_trains_without_wpe():
    params = PARAMS + "; pos_emb='rope'"
    spec = load_model_spec_from_module(zoo)
    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = Trainer(spec, mesh=mesh, model_params=params)
    batch = _batch(seed=5)
    state = trainer.init_state(batch)
    assert "wpe" not in state.params, list(state.params)
    first = None
    for _ in range(15):
        state, loss = trainer.train_step(state, batch)
        if first is None:
            first = float(loss)
    assert float(loss) < first, (first, float(loss))

    # sp mesh parity: rope positions are global under the ring shards
    mesh8 = mesh_lib.build_mesh({"dp": 2, "sp": 4})
    t8 = Trainer(spec, mesh=mesh8, model_params=params)
    s8 = t8.init_state(batch)
    s1 = Trainer(spec, mesh=mesh, model_params=params)
    st1 = s1.init_state(batch)
    st1, l1 = s1.train_step(st1, batch)
    s8, l8 = t8.train_step(s8, batch)
    np.testing.assert_allclose(float(l1), float(l8), rtol=1e-3)


def test_training_reduces_loss_on_ring_mesh():
    spec = load_model_spec_from_module(zoo)
    mesh = mesh_lib.build_mesh({"sp": 8})
    trainer = Trainer(spec, mesh=mesh, model_params=PARAMS)
    batch = _batch(seed=1)
    state = trainer.init_state(batch)
    first = None
    for _ in range(20):
        state, loss = trainer.train_step(state, batch)
        if first is None:
            first = float(loss)
    assert float(loss) < first, (first, float(loss))


def test_chunked_xent_matches_direct():
    import jax.numpy as jnp
    import optax

    from elasticdl_tpu.ops.losses import chunked_softmax_xent

    rs = np.random.RandomState(3)
    b, s, d, v = 4, 32, 16, 64
    hidden = jnp.asarray(rs.randn(b, s, d).astype(np.float32))
    kernel = jnp.asarray(rs.randn(d, v).astype(np.float32) * 0.1)
    labels = jnp.asarray(rs.randint(0, v, size=(b, s)).astype(np.int32))

    def direct(h, k):
        logits = (h @ k).astype(np.float32)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean()

    def chunked(h, k):
        return chunked_softmax_xent(h, k, labels, num_chunks=4).mean()

    np.testing.assert_allclose(
        float(chunked(hidden, kernel)), float(direct(hidden, kernel)),
        rtol=1e-6,
    )
    gh_c, gk_c = jax.grad(chunked, argnums=(0, 1))(hidden, kernel)
    gh_d, gk_d = jax.grad(direct, argnums=(0, 1))(hidden, kernel)
    np.testing.assert_allclose(
        np.asarray(gh_c), np.asarray(gh_d), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(gk_c), np.asarray(gk_d), rtol=1e-5, atol=1e-6
    )
    # non-divisible chunk request zero-pads to 5 chunks of 7, drops tail
    ce = chunked_softmax_xent(hidden, kernel, labels, num_chunks=5)
    assert ce.shape == (b, s)
    np.testing.assert_allclose(
        float(ce.mean()), float(direct(hidden, kernel)), rtol=1e-6
    )
    # prime length: zero-padded to the chunk multiple, tail dropped
    ce1 = chunked_softmax_xent(
        hidden[:, :31], kernel, labels[:, :31], num_chunks=8
    )
    assert ce1.shape == (b, 31)
    logits31 = hidden[:, :31] @ kernel
    ref31 = optax.softmax_cross_entropy_with_integer_labels(
        logits31, labels[:, :31]
    )
    np.testing.assert_allclose(
        np.asarray(ce1), np.asarray(ref31), rtol=1e-5, atol=1e-6
    )


def test_fused_head_trains_identically():
    """fused_head streams the LM head through the loss; the training
    trajectory must match the plain-logits path bit-for-bit in fp32
    (same params pytree — head/kernel path is checkpoint-compatible)."""
    spec = load_model_spec_from_module(zoo)
    batch = _batch(seed=4)
    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])

    t_plain = Trainer(spec, mesh=mesh, model_params=PARAMS)
    t_fused = Trainer(
        spec, mesh=mesh, model_params=PARAMS + "; fused_head=True"
    )
    s_plain = t_plain.init_state(batch)
    s_fused = t_fused.init_state(batch)
    assert (
        jax.tree.structure(s_plain.params)
        == jax.tree.structure(s_fused.params)
    )
    for _ in range(3):
        s_plain, loss_plain = t_plain.train_step(s_plain, batch)
        s_fused, loss_fused = t_fused.train_step(s_fused, batch)
    np.testing.assert_allclose(
        float(loss_plain), float(loss_fused), rtol=1e-5
    )
    for a, b in zip(
        jax.tree.leaves(s_plain.params), jax.tree.leaves(s_fused.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        )
    # eval path still returns logits under fused_head
    outputs, labels = t_fused.evaluate_batch(s_fused, batch)
    assert outputs.shape == (8, 16, 32)


@pytest.mark.parametrize("policy", ["full", "dots"])
def test_remat_trains_identically(policy):
    """Per-block remat changes WHEN activations exist, never the math:
    the training trajectory must match the plain path (same params
    pytree — remat is invisible to checkpoints), under both the
    save-nothing and save-dots policies. Packing (segments/positions
    closed over by the remat body) must also survive."""
    spec = load_model_spec_from_module(zoo)
    batch = _batch(seed=9)
    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])

    t_plain = Trainer(spec, mesh=mesh, model_params=PARAMS)
    t_remat = Trainer(
        spec, mesh=mesh, model_params=PARAMS + "; remat='%s'" % policy
    )
    s_plain = t_plain.init_state(batch)
    s_remat = t_remat.init_state(batch)
    assert (
        jax.tree.structure(s_plain.params)
        == jax.tree.structure(s_remat.params)
    )
    for _ in range(3):
        s_plain, loss_plain = t_plain.train_step(s_plain, batch)
        s_remat, loss_remat = t_remat.train_step(s_remat, batch)
    np.testing.assert_allclose(
        float(loss_plain), float(loss_remat), rtol=1e-6
    )
    for a, b in zip(
        jax.tree.leaves(s_plain.params), jax.tree.leaves(s_remat.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        )
    # decode is untouched by remat (no recompute in generation) —
    # same use_cache on both sides so the cache path isn't conflated
    # with the knob under test
    from elasticdl_tpu.api.generation import autoregressive_generate

    prompt = np.asarray([[1, 2, 3]], np.int32)
    for use_cache in (False, True):
        ref = np.asarray(
            autoregressive_generate(t_plain, s_plain, prompt, 4,
                                    use_cache=use_cache)
        )
        got = np.asarray(
            autoregressive_generate(t_remat, s_remat, prompt, 4,
                                    use_cache=use_cache)
        )
        np.testing.assert_array_equal(ref, got)

    # packing through the remat closure: segments/positions are
    # closed-over non-differentiable tracers in run_block — a packed
    # batch must train identically too
    rs = np.random.RandomState(11)
    toks = rs.randint(0, 32, size=(8, 17)).astype(np.int32)
    segs = np.concatenate(
        [np.zeros((8, 9), np.int32), np.ones((8, 8), np.int32)], axis=1
    )
    packed = (
        {"tokens": toks[:, :-1], "segment_ids": segs[:, :-1]},
        toks[:, 1:],
    )
    sp_plain = t_plain.init_state(packed)
    sp_remat = t_remat.init_state(packed)
    for _ in range(2):
        sp_plain, lp = t_plain.train_step(sp_plain, packed)
        sp_remat, lr = t_remat.train_step(sp_remat, packed)
    np.testing.assert_allclose(float(lp), float(lr), rtol=1e-6)


def test_eval_metrics():
    spec = load_model_spec_from_module(zoo)
    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = Trainer(spec, mesh=mesh, model_params=PARAMS)
    batch = _batch(seed=2)
    state = trainer.init_state(batch)
    outputs, labels = trainer.evaluate_batch(state, batch)
    metrics = spec.eval_metrics_fn()
    acc = metrics["token_accuracy"](labels, outputs)
    assert acc.shape[0] == 8
    assert 0.0 <= float(np.mean(acc)) <= 1.0


def test_gqa_model_trains_with_smaller_projection():
    """num_kv_heads < num_heads: the model trains, the qkv projection
    shrinks to (h + 2*hkv) * head_dim columns, and loss decreases —
    grouped-query attention end-to-end through the trainer."""
    spec = load_model_spec_from_module(zoo)
    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    params = PARAMS + "; num_heads=4; num_kv_heads=2"
    t = Trainer(spec, mesh=mesh, model_params=params)
    batch = _batch()
    state = t.init_state(batch)
    qkv = state.params["block_0"]["attn"]["qkv"]["kernel"]
    head_dim = 32 // 4
    assert qkv.shape[-1] == (4 + 2 * 2) * head_dim  # vs 3*4*head_dim MHA
    losses = []
    for step in range(12):
        state, loss = t.train_step(state, _batch(seed=step % 3))
        losses.append(float(loss))
    assert losses[-1] < losses[0]
