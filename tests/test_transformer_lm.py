"""Transformer LM family: single-chip flash path vs sequence-parallel
ring path produce the same training step, and training reduces loss."""

import numpy as np

import jax

from elasticdl_tpu.common.model_utils import load_model_spec_from_module
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.training.trainer import Trainer
from model_zoo.transformer_lm import transformer_lm as zoo

PARAMS = (
    "vocab_size=32; seq_len=16; embed_dim=32; num_heads=2; num_layers=1"
)


def _batch(bsz=8, seq_len=16, vocab=32, seed=0):
    rs = np.random.RandomState(seed)
    tokens = rs.randint(0, vocab, size=(bsz, seq_len + 1)).astype(np.int32)
    return {"tokens": tokens[:, :-1]}, tokens[:, 1:]


def test_single_device_vs_ring_same_step():
    spec = load_model_spec_from_module(zoo)
    batch = _batch()

    mesh1 = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    t1 = Trainer(spec, mesh=mesh1, model_params=PARAMS)
    s1 = t1.init_state(batch)
    s1, loss1 = t1.train_step(s1, batch)

    mesh8 = mesh_lib.build_mesh({"dp": 2, "sp": 4})
    t8 = Trainer(spec, mesh=mesh8, model_params=PARAMS)
    s8 = t8.init_state(batch)
    s8, loss8 = t8.train_step(s8, batch)

    np.testing.assert_allclose(float(loss1), float(loss8), rtol=1e-3)
    # parameters after one update agree (same seed -> same init)
    p1 = jax.tree.leaves(s1.params)
    p8 = jax.tree.leaves(s8.params)
    for a, b in zip(p1, p8):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=1e-5
        )


def test_training_reduces_loss_on_ring_mesh():
    spec = load_model_spec_from_module(zoo)
    mesh = mesh_lib.build_mesh({"sp": 8})
    trainer = Trainer(spec, mesh=mesh, model_params=PARAMS)
    batch = _batch(seed=1)
    state = trainer.init_state(batch)
    first = None
    for _ in range(20):
        state, loss = trainer.train_step(state, batch)
        if first is None:
            first = float(loss)
    assert float(loss) < first, (first, float(loss))


def test_eval_metrics():
    spec = load_model_spec_from_module(zoo)
    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = Trainer(spec, mesh=mesh, model_params=PARAMS)
    batch = _batch(seed=2)
    state = trainer.init_state(batch)
    outputs, labels = trainer.evaluate_batch(state, batch)
    metrics = spec.eval_metrics_fn()
    acc = metrics["token_accuracy"](labels, outputs)
    assert acc.shape[0] == 8
    assert 0.0 <= float(np.mean(acc)) <= 1.0
