"""JobStateStore + TaskDispatcher crash-recovery unit tests: journal
round-trip, compaction, torn-line tolerance, exact todo ∪ requeued-doing
reconstruction, retry-count carryover, and late-report reconciliation."""

import os

import pytest

from elasticdl_tpu.common.constants import MAX_TASK_RETRIES
from elasticdl_tpu.master.state_store import JobStateStore
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher, TaskType


def make_dispatcher(store, train=None, evaluation=None, records_per_task=10,
                    num_epochs=1):
    return TaskDispatcher(
        train or {}, evaluation or {}, {}, records_per_task, num_epochs,
        state_store=store,
    )


def ranges(tasks):
    return sorted((t.shard_name, t.start, t.end) for t in tasks)


def test_store_load_empty(tmp_path):
    store = JobStateStore(str(tmp_path / "s"))
    assert not store.has_state()
    assert store.load() == (None, [])
    assert store.restart_count == 0


def test_append_and_load_events(tmp_path):
    store = JobStateStore(str(tmp_path / "s"))
    store.append({"ev": "a", "x": 1})
    store.append({"ev": "b"})
    store.close()
    snapshot, events = JobStateStore(str(tmp_path / "s")).load()
    assert snapshot is None
    assert [e["ev"] for e in events] == ["a", "b"]


def test_torn_final_line_is_dropped(tmp_path):
    store = JobStateStore(str(tmp_path / "s"))
    store.append({"ev": "a"})
    store.close()
    with open(os.path.join(str(tmp_path / "s"), "journal.jsonl"),
              "a") as f:
        f.write('{"ev": "tor')  # SIGKILL mid-append
    _, events = JobStateStore(str(tmp_path / "s")).load()
    assert [e["ev"] for e in events] == ["a"]


def test_torn_middle_line_raises(tmp_path):
    store = JobStateStore(str(tmp_path / "s"))
    path = os.path.join(str(tmp_path / "s"), "journal.jsonl")
    with open(path, "w") as f:
        f.write('{"ev": "tor\n{"ev": "b"}\n')
    with pytest.raises(ValueError):
        store.load()


def test_torn_tail_binary_garbage_is_dropped_and_counted(tmp_path):
    # a power-loss torn block write can leave raw non-UTF-8 bytes, not
    # just a JSON prefix; load must survive it at the BYTES level
    store = JobStateStore(str(tmp_path / "s"))
    store.append({"ev": "a"})
    store.close()
    path = os.path.join(str(tmp_path / "s"), "journal.jsonl")
    with open(path, "ab") as f:
        f.write(b'{"ev": "b"}\n\xff\xfe\x00garbage')
    reopened = JobStateStore(str(tmp_path / "s"))
    _, events = reopened.load()
    assert [e["ev"] for e in events] == ["a", "b"]
    assert reopened.torn_lines == 1


def test_torn_tail_with_newline_is_dropped_and_counted(tmp_path):
    store = JobStateStore(str(tmp_path / "s"))
    store.append({"ev": "a"})
    store.close()
    path = os.path.join(str(tmp_path / "s"), "journal.jsonl")
    with open(path, "ab") as f:
        f.write(b"\xc3(not json\n")  # invalid UTF-8, newline landed
    reopened = JobStateStore(str(tmp_path / "s"))
    _, events = reopened.load()
    assert [e["ev"] for e in events] == ["a"]
    assert reopened.torn_lines == 1


def test_append_after_torn_tail_trims_instead_of_concatenating(tmp_path):
    # without the trim, the next append would glue onto the torn line
    # and turn recoverable tail garbage into fatal MID-file corruption
    store = JobStateStore(str(tmp_path / "s"))
    store.append({"ev": "a"})
    store.close()
    path = os.path.join(str(tmp_path / "s"), "journal.jsonl")
    with open(path, "ab") as f:
        f.write(b'{"ev": "tor')
    reopened = JobStateStore(str(tmp_path / "s"))
    reopened.append({"ev": "b"})
    reopened.close()
    assert reopened.torn_lines == 1
    _, events = JobStateStore(str(tmp_path / "s")).load()
    assert [e["ev"] for e in events] == ["a", "b"]


def test_snapshot_compacts_journal(tmp_path):
    store = JobStateStore(str(tmp_path / "s"), snapshot_every=1000)
    for i in range(5):
        store.append({"ev": "e", "i": i})
    store.write_snapshot({"state": 42})
    store.append({"ev": "after"})
    store.close()
    snapshot, events = JobStateStore(str(tmp_path / "s")).load()
    assert snapshot == {"state": 42}
    assert [e["ev"] for e in events] == ["after"]


def test_append_signals_compaction_threshold(tmp_path):
    store = JobStateStore(str(tmp_path / "s"), snapshot_every=3)
    assert not store.append({"ev": "1"})
    assert not store.append({"ev": "2"})
    assert store.append({"ev": "3"})  # caller should compact now


def test_completion_marker_and_restarts(tmp_path):
    d = str(tmp_path / "s")
    store = JobStateStore(d)
    assert not store.is_job_complete()
    store.mark_job_complete()
    store.append({"ev": "x"})
    store.close()
    again = JobStateStore(d)
    assert again.is_job_complete()
    assert again.restart_count == 1
    JobStateStore(d)
    assert again.restart_count == 2


# ------------------------------------------------ dispatcher round-trip


def test_restore_reconstructs_todo_and_requeues_doing(tmp_path):
    d = str(tmp_path / "s")
    disp = make_dispatcher(JobStateStore(d), train={"f": (0, 60)},
                           records_per_task=10)
    all_ranges = ranges(disp._todo)
    ids = [disp.get("w0") for _ in range(3)]
    disp.report(ids[0][0], True)  # done: must NOT reappear
    disp.report(ids[1][0], False)  # failed: requeued
    # ids[2] stays in doing: must be requeued on restore

    disp2 = make_dispatcher(JobStateStore(d), train={"f": (0, 60)},
                            records_per_task=10)
    done_range = (ids[0][1].shard_name, ids[0][1].start, ids[0][1].end)
    expected = sorted(r for r in all_ranges if r != done_range)
    assert ranges(disp2._todo) == expected
    assert disp2.requeued_on_recovery == 1
    assert not disp2._doing
    # the pre-crash doing id is remembered for reconciliation
    assert list(disp2._recovered_doing) == [ids[2][0]]
    # task_id counter continues, never reusing pre-crash ids
    tid, _ = disp2.get("w1")
    assert tid > ids[2][0]


def test_restore_carries_retry_counts(tmp_path):
    d = str(tmp_path / "s")
    disp = make_dispatcher(JobStateStore(d), train={"f": (0, 10)},
                           records_per_task=10)
    tid, task = disp.get("w0")
    disp.report(tid, False)
    disp2 = make_dispatcher(JobStateStore(d), train={"f": (0, 10)},
                            records_per_task=10)
    # one pre-crash failure carried over: MAX_TASK_RETRIES total attempts
    # across BOTH master lifetimes
    fails = 0
    while True:
        tid, task = disp2.get("w0")
        if task is None:
            break
        fails += 1
        disp2.report(tid, False)
    assert fails == MAX_TASK_RETRIES - 1
    assert disp2.finished()


def test_late_report_of_precrash_task_deduplicates(tmp_path):
    d = str(tmp_path / "s")
    disp = make_dispatcher(JobStateStore(d), train={"f": (0, 20)},
                           records_per_task=10)
    tid, task = disp.get("w0")

    disp2 = make_dispatcher(JobStateStore(d), train={"f": (0, 20)},
                            records_per_task=10)
    assert len(disp2._todo) == 2  # 1 untouched + 1 requeued
    # the surviving worker finished the pre-crash task after all
    disp2.report(tid, True)
    assert disp2.recovered_late_completions == 1
    assert len(disp2._todo) == 1  # duplicate pulled back out
    tid2, _ = disp2.get("w0")
    disp2.report(tid2, True)
    assert disp2.finished()


def test_restore_after_compaction_is_exact(tmp_path):
    d = str(tmp_path / "s")
    store = JobStateStore(d, snapshot_every=2)  # compact aggressively
    disp = make_dispatcher(store, train={"f": (0, 50)},
                           records_per_task=10)
    completed = []
    for _ in range(3):
        tid, task = disp.get("w0")
        completed.append((task.shard_name, task.start, task.end))
        disp.report(tid, True)
    assert store.compactions > 0
    disp2 = make_dispatcher(JobStateStore(d), train={"f": (0, 50)},
                            records_per_task=10)
    remaining = ranges(disp2._todo)
    assert len(remaining) == 2
    assert not (set(remaining) & set(completed))


def test_restore_model_version_and_epoch(tmp_path):
    d = str(tmp_path / "s")
    disp = make_dispatcher(JobStateStore(d), train={"f": (0, 10)},
                           records_per_task=5, num_epochs=3)
    while True:
        tid, task = disp.get("w0")
        if task is None:
            break
        disp.report(tid, True)
        if disp.epoch >= 1:
            break
    disp.record_model_version(7)
    disp2 = make_dispatcher(JobStateStore(d), train={"f": (0, 10)},
                            records_per_task=5, num_epochs=3)
    assert disp2.epoch == 1
    assert disp2.model_version == 7


def test_restore_eval_tasks(tmp_path):
    d = str(tmp_path / "s")
    disp = make_dispatcher(JobStateStore(d),
                           evaluation={"e": (0, 30)}, records_per_task=10)
    tid, task = disp.get_eval_task("w0")
    assert task.type == TaskType.EVALUATION
    disp2 = make_dispatcher(JobStateStore(d),
                            evaluation={"e": (0, 30)},
                            records_per_task=10)
    # 2 never-dispatched + 1 requeued from doing
    assert len(disp2._eval_todo) == 3
    assert disp2.requeued_on_recovery == 1


def test_restore_without_store_state_creates_fresh(tmp_path):
    store = JobStateStore(str(tmp_path / "s"))
    disp = make_dispatcher(store, train={"f": (0, 30)},
                           records_per_task=10)
    assert len(disp._todo) == 3
    assert not disp._restored


def test_deferred_train_end_not_duplicated_after_restore(tmp_path):
    d = str(tmp_path / "s")
    disp = make_dispatcher(JobStateStore(d), train={"f": (0, 10)},
                           records_per_task=10)
    disp.add_deferred_callback_create_train_end_task()
    disp2 = make_dispatcher(JobStateStore(d), train={"f": (0, 10)},
                            records_per_task=10)
    # Master.__init__ re-adds the deferred callback on every launch; a
    # restored dispatcher must keep exactly one
    disp2.add_deferred_callback_create_train_end_task()
    assert len(disp2._tasks_done_deferred_callbacks) == 1
    tid, _ = disp2.get("w0")
    disp2.report(tid, True)
    assert disp2.invoke_deferred_callback()
    tid, task = disp2.get("w0")
    assert task.type == TaskType.TRAIN_END_CALLBACK
    disp2.report(tid, True)
    assert disp2.finished()
    assert not disp2.invoke_deferred_callback()


def test_stop_training_clears_todo_across_restore(tmp_path):
    d = str(tmp_path / "s")
    disp = make_dispatcher(JobStateStore(d), train={"f": (0, 100)},
                           records_per_task=10, num_epochs=5)
    tid, _ = disp.get("w0")
    disp.stop_training = True
    disp.report(tid, True)
    disp2 = make_dispatcher(JobStateStore(d), train={"f": (0, 100)},
                            records_per_task=10, num_epochs=5)
    assert disp2.stop_training
    tid, task = disp2.get("w0")
    assert task is None
    assert disp2.finished()


def test_journal_survives_exactly_once_accounting(tmp_path):
    """Dispatch/complete a whole job across a simulated crash; the union
    of done events over both lifetimes covers every range exactly
    once."""
    d = str(tmp_path / "s")
    os.environ.pop("EDL_STATE_SNAPSHOT_EVERY", None)
    store = JobStateStore(d, snapshot_every=10 ** 6)
    disp = make_dispatcher(store, train={"f": (0, 80)},
                           records_per_task=10)
    for _ in range(3):
        tid, task = disp.get("w0")
        disp.report(tid, True)
    tid_doing, _ = disp.get("w0")  # in flight at crash time

    _, events1 = store.load()
    done1 = [tuple(e["task"][:3]) for e in events1
             if e["ev"] in ("done", "done_recovered")]

    store2 = JobStateStore(d, snapshot_every=10 ** 6)
    disp2 = make_dispatcher(store2, train={"f": (0, 80)},
                            records_per_task=10)
    while True:
        tid, task = disp2.get("w1")
        if task is None:
            break
        disp2.report(tid, True)
    assert disp2.finished()
    _, events2 = store2.load()
    done2 = [tuple(e["task"][:3]) for e in events2
             if e["ev"] in ("done", "done_recovered")]
    got = sorted(done1 + done2)
    expected = sorted(("f", s, s + 10) for s in range(0, 80, 10))
    assert got == expected
