"""serving/prefix_affinity.py unit tests (tier-1: pure python).

Locks the three primitives the prefix-affine router tier stands on:
the content-addressed block-chain fingerprint (deterministic across
processes, block-aligned, capped), the consistent-hash ring (stable
ownership, BOUNDED reshuffle on membership change, deterministic
failover walk), and the TTL'd affinity index (expiry, LRU capacity,
address forgetting on replica retirement)."""

import pytest

from elasticdl_tpu.serving.prefix_affinity import (
    AffinityIndex,
    HashRing,
    prefix_fingerprint,
)

# ------------------------------------------------------------ fingerprint


def test_fingerprint_deterministic_across_calls():
    prompt = list(range(40))
    a = prefix_fingerprint(prompt, block_tokens=16)
    b = prefix_fingerprint(list(prompt), block_tokens=16)
    assert a is not None and a == b


def test_fingerprint_none_below_one_full_block():
    # no complete block -> nothing shareable -> no fingerprint
    assert prefix_fingerprint([], block_tokens=16) is None
    assert prefix_fingerprint([1] * 15, block_tokens=16) is None
    assert prefix_fingerprint([1] * 16, block_tokens=16) is not None


def test_fingerprint_ignores_partial_trailing_block():
    # the suffix past the last FULL block must not perturb the key:
    # that is what lets a family of prompts share one fingerprint
    base = [7] * 32
    assert (prefix_fingerprint(base + [9, 9, 9], block_tokens=16)
            == prefix_fingerprint(base, block_tokens=16))


def test_fingerprint_first_block_sensitivity():
    # same-length prompts differing in ONE leading token must diverge
    # (the chain key is content-addressed, not length-addressed)
    a = prefix_fingerprint([1] + [0] * 31, block_tokens=16)
    b = prefix_fingerprint([2] + [0] * 31, block_tokens=16)
    assert a != b


def test_fingerprint_is_chained_not_flat():
    # block order matters: the second block's key is chained on the
    # first, so swapping blocks changes the fingerprint
    blk_a, blk_b = [1] * 16, [2] * 16
    assert (prefix_fingerprint(blk_a + blk_b, block_tokens=16)
            != prefix_fingerprint(blk_b + blk_a, block_tokens=16))


def test_fingerprint_max_blocks_cap():
    # beyond the cap, longer prefixes collapse onto one fingerprint —
    # the router keys on the head of the chain, not the whole prompt
    short = [3] * 32
    long = [3] * 64
    assert (prefix_fingerprint(short, block_tokens=16, max_blocks=2)
            == prefix_fingerprint(long, block_tokens=16, max_blocks=2))
    assert (prefix_fingerprint(short, block_tokens=16, max_blocks=4)
            != prefix_fingerprint(long, block_tokens=16, max_blocks=4))


def test_fingerprint_rejects_bad_block_tokens():
    with pytest.raises(ValueError):
        prefix_fingerprint([1, 2, 3], block_tokens=0)


# -------------------------------------------------------------- hash ring


def test_ring_empty_degenerate():
    ring = HashRing()
    assert ring.lookup("anything") is None
    assert ring.successors("anything") == []
    assert ring.nodes() == []


def test_ring_single_node_owns_everything():
    ring = HashRing(["only"])
    for key in ("a", "b", "c", "zz-%d" % 7):
        assert ring.lookup(key) == "only"
        assert ring.successors(key) == ["only"]


def test_ring_lookup_deterministic_across_instances():
    # two independently-built rings (any insertion order) agree on
    # every key: ownership is a pure function of the membership set
    a = HashRing(["cell0", "cell1", "cell2"])
    b = HashRing(["cell2", "cell0", "cell1"])
    keys = ["k%d" % i for i in range(200)]
    assert [a.lookup(k) for k in keys] == [b.lookup(k) for k in keys]


def test_ring_successors_walk_every_node_once():
    ring = HashRing(["c0", "c1", "c2", "c3"])
    walk = ring.successors("some-key")
    assert sorted(walk) == ["c0", "c1", "c2", "c3"]
    assert walk[0] == ring.lookup("some-key")


def test_ring_add_node_bounded_reshuffle():
    nodes = ["c%d" % i for i in range(4)]
    ring = HashRing(nodes)
    keys = ["req-%d" % i for i in range(400)]
    before = {k: ring.lookup(k) for k in keys}
    ring.add("c4")
    moved = sum(1 for k in keys if ring.lookup(k) != before[k])
    # consistent hashing's whole point: adding the 5th node remaps
    # roughly 1/5 of the keyspace, NOT most of it (modulo hashing
    # would remap ~4/5). Generous bound: strictly under half.
    assert 0 < moved < len(keys) // 2
    # every moved key moved TO the new node, never between old nodes
    for k in keys:
        if ring.lookup(k) != before[k]:
            assert ring.lookup(k) == "c4"


def test_ring_remove_node_only_reassigns_its_keys():
    nodes = ["c%d" % i for i in range(4)]
    ring = HashRing(nodes)
    keys = ["req-%d" % i for i in range(400)]
    before = {k: ring.lookup(k) for k in keys}
    ring.remove("c2")
    for k in keys:
        if before[k] != "c2":
            # keys the dead node did not own must not move at all
            assert ring.lookup(k) == before[k]
        else:
            assert ring.lookup(k) != "c2"


def test_ring_failover_order_stable_under_death():
    # the ring's successor walk IS the failover plan: when the owner
    # dies, every key lands exactly on its precomputed next successor
    ring = HashRing(["c0", "c1", "c2"])
    keys = ["req-%d" % i for i in range(100)]
    planned = {k: ring.successors(k) for k in keys}
    ring.remove("c1")
    for k in keys:
        survivors = [n for n in planned[k] if n != "c1"]
        assert ring.lookup(k) == survivors[0]


# --------------------------------------------------------- affinity index


def test_index_learn_lookup_roundtrip():
    idx = AffinityIndex(ttl_secs=60.0)
    idx.learn("fp1", "rep0", now=100.0)
    assert idx.lookup("fp1", now=101.0) == "rep0"
    assert idx.lookup("missing", now=101.0) is None


def test_index_ttl_expiry():
    idx = AffinityIndex(ttl_secs=60.0)
    idx.learn("fp1", "rep0", now=100.0)
    assert idx.lookup("fp1", now=159.0) == "rep0"
    assert idx.lookup("fp1", now=161.0) is None  # stale -> purged
    assert len(idx) == 0


def test_index_relearn_refreshes_ttl():
    idx = AffinityIndex(ttl_secs=60.0)
    idx.learn("fp1", "rep0", now=100.0)
    idx.learn("fp1", "rep1", now=150.0)  # fresh dispatch re-learns
    assert idx.lookup("fp1", now=205.0) == "rep1"


def test_index_capacity_evicts_least_recently_used():
    idx = AffinityIndex(ttl_secs=1000.0, capacity=3)
    for i in range(3):
        idx.learn("fp%d" % i, "rep0", now=float(i))
    assert idx.lookup("fp0", now=10.0) == "rep0"  # fp0 now MRU
    idx.learn("fp3", "rep1", now=11.0)  # evicts fp1 (the LRU), not fp0
    assert idx.lookup("fp0", now=12.0) == "rep0"
    assert idx.lookup("fp1", now=12.0) is None
    assert len(idx) == 3


def test_index_forget_address_on_replica_retirement():
    idx = AffinityIndex(ttl_secs=1000.0)
    idx.learn("fp1", "rep0", now=0.0)
    idx.learn("fp2", "rep1", now=0.0)
    idx.learn("fp3", "rep0", now=0.0)
    idx.forget_address("rep0")
    assert idx.lookup("fp1", now=1.0) is None
    assert idx.lookup("fp3", now=1.0) is None
    assert idx.lookup("fp2", now=1.0) == "rep1"
