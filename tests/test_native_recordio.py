"""Native C++ TRec scanner parity with the pure-Python codec.

Builds libtrecio.so via the Makefile if a toolchain is present; skips
otherwise (the native path is an optional fast path — reader semantics are
identical either way)."""

import os
import subprocess

import pytest

from elasticdl_tpu.data import record_format as rf

NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "elasticdl_tpu",
    "native",
)


@pytest.fixture(scope="module")
def native():
    from elasticdl_tpu.native import recordio_native as rn

    if not rn.available():
        try:
            subprocess.run(
                ["make", "-C", NATIVE_DIR],
                check=True,
                capture_output=True,
                timeout=120,
            )
        except Exception:
            pytest.skip("no C++ toolchain to build libtrecio.so")
        # force a re-probe after the build
        rn._TRIED = False
        rn._LIB = None
        if not rn.available():
            pytest.skip("libtrecio.so built but not loadable")
    return rn


def test_scan_matches_python_codec(tmp_path, native):
    path = str(tmp_path / "data.trec")
    payloads = [b"hello", b"", b"x" * 10000, "café".encode("utf-8")]
    rf.write_records(path, payloads)

    assert native.record_count(path) == len(payloads)
    assert list(native.scan(path, 0, -1)) == payloads
    assert list(native.scan(path, 1, 2)) == payloads[1:3]
    assert list(rf.Scanner(path, 0, -1)) == payloads


def test_open_rejects_garbage(tmp_path, native):
    path = str(tmp_path / "bogus.trec")
    with open(path, "wb") as f:
        f.write(b"not a trec file at all, definitely not")
    with pytest.raises(IOError):
        native.record_count(path)


def test_crc_corruption_detected(tmp_path, native):
    path = str(tmp_path / "corrupt.trec")
    rf.write_records(path, [b"a" * 64, b"b" * 64])
    # flip a payload byte of record 0 (header=8+4, rec hdr=12)
    with open(path, "r+b") as f:
        f.seek(8 + 4 + 12 + 3)
        f.write(b"\xff")
    with pytest.raises(IOError):
        list(native.scan(path, 0, 1))
