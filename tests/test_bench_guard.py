"""Unit coverage for bench.require_accelerator_or_exit — the fail-fast
guard TPU-only measurement scripts (scripts/profile_step.py,
scripts/bench_collectives.py) call before touching jax, so a wedged
tunnel costs the probe deadline instead of the caller's 30-min bound."""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import bench  # noqa: E402


def test_cpu_first_platform_skips_probe(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setattr(bench, "probe_accelerator",
                        lambda *a, **k: pytest.fail("probe must not run"))
    bench.require_accelerator_or_exit()  # returns, no exit


def test_cpu_fallback_list_still_probes(monkeypatch):
    """'axon,cpu' means jax init would still hang on the wedged axon
    plugin — the guard must probe, not skip."""
    monkeypatch.setenv("JAX_PLATFORMS", "axon,cpu")
    calls = []
    monkeypatch.setattr(bench, "probe_accelerator",
                        lambda d, *a, **k: calls.append(d) or ("tpu", "v5e"))
    bench.require_accelerator_or_exit(deadline_s=7.0)
    assert calls == [7.0]


def test_no_accelerator_exits_nonzero(monkeypatch):
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setattr(bench, "probe_accelerator",
                        lambda *a, **k: (None, None))
    with pytest.raises(SystemExit) as e:
        bench.require_accelerator_or_exit(deadline_s=5.0)
    assert e.value.code == 1


def test_malformed_env_deadline_falls_back(monkeypatch, capsys):
    """EDL_BENCH_PROBE_TIMEOUT=abc must warn and use the default, not
    crash (bench's rc=0 contract)."""
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setenv("EDL_BENCH_PROBE_TIMEOUT", "abc")
    seen = []
    monkeypatch.setattr(bench, "probe_accelerator",
                        lambda d, *a, **k: seen.append(d) or ("tpu", "v5e"))
    bench.require_accelerator_or_exit()
    assert seen == [300.0]
    assert "ignoring bad EDL_BENCH_PROBE_TIMEOUT" in \
        capsys.readouterr().err
