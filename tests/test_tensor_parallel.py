"""Tensor parallelism is real (VERDICT.md round-1 weak #7): transformer
kernels annotated with nn.with_partitioning over `tp` actually shard over
a tp>1 mesh, the compiled train step contains the Megatron all-reduces,
and the math matches the single-device model."""

import numpy as np

import jax
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.common.constants import MeshAxis
from elasticdl_tpu.common.model_utils import (
    format_params_str,
    load_model_spec_from_module,
)
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.training.trainer import Trainer

import pytest

# CI drills shard (make test-drills): the sub-5-min per-commit gate excludes this file.
pytestmark = pytest.mark.slow


def _trainer(mesh, seq_len=32, extra=None):
    from model_zoo.transformer_lm import transformer_lm as zoo

    cfg = dict(vocab_size=64, seq_len=seq_len, embed_dim=32, num_heads=4,
               num_layers=1, attn_impl="xla")
    if extra:
        cfg.update(extra)
    return Trainer(
        load_model_spec_from_module(zoo),
        mesh=mesh,
        model_params=format_params_str(cfg),
    )


def _batch(seq_len=32, batch=8, seed=0):
    rng = np.random.RandomState(seed)
    tokens = rng.randint(0, 64, size=(batch, seq_len + 1)).astype(np.int32)
    return ({"tokens": tokens[:, :-1]}, tokens[:, 1:])


def test_params_sharded_over_tp():
    mesh = mesh_lib.build_mesh({"dp": 2, "tp": 4})
    trainer = _trainer(mesh)
    state = trainer.init_state(_batch())
    p = state.params["block_0"]

    def spec(leaf):
        return leaf.sharding.spec

    # column-parallel: output dim over tp
    assert spec(p["attn"]["qkv"]["kernel"]) == P(None, MeshAxis.TP)
    assert spec(p["mlp_up"]["kernel"]) == P(None, MeshAxis.TP)
    # row-parallel: input dim over tp
    assert spec(p["attn"]["proj"]["kernel"]) == P(MeshAxis.TP, None)
    assert spec(p["mlp_down"]["kernel"]) == P(MeshAxis.TP, None)
    assert spec(state.params["head"]["kernel"]) == P(None, MeshAxis.TP)
    # every device holds only its shard of an annotated kernel
    kernel = p["mlp_up"]["kernel"]
    shard_shape = kernel.sharding.shard_shape(kernel.shape)
    assert shard_shape[1] == kernel.shape[1] // 4


def test_optimizer_state_co_sharded():
    """optax moments mirror their param's tp spec (suffix matching in
    infer_state_pspec)."""
    mesh = mesh_lib.build_mesh({"tp": 8})
    trainer = _trainer(mesh)
    state = trainer.init_state(_batch())
    found = []

    def check(path, leaf):
        keys = tuple(
            str(getattr(k, "key", getattr(k, "name", k))) for k in path
        )
        if keys[-2:] == ("qkv", "kernel") and hasattr(leaf, "sharding"):
            found.append(leaf.sharding.spec)

    jax.tree_util.tree_map_with_path(check, state.opt_state)
    # adamw: mu and nu both carry the annotation
    assert len(found) >= 2
    assert all(s == P(None, MeshAxis.TP) for s in found)


def test_compiled_step_contains_tp_collectives():
    """On a tp-ONLY mesh (dp=fsdp=1) any all-reduce in the compiled step
    is TP-induced: the row-parallel matmuls' partial-sum reductions. A
    replicated (unannotated) model compiles with no such collective."""
    mesh = mesh_lib.build_mesh({"tp": 8})
    trainer = _trainer(mesh)
    batch = _batch()
    state = trainer.init_state(batch)
    trainer._train_step = trainer._build_train_step()
    features, labels = batch
    weights = trainer.make_weights(8, None)
    with trainer.mesh:
        hlo = (
            trainer._train_step.lower(state, features, labels, weights)
            .compile().as_text()
        )
    assert "all-reduce" in hlo or "all-gather" in hlo

    # control: tp annotations off -> no tp collectives on the same mesh
    trainer_off = _trainer(mesh, extra={"tp_shard": False})
    state_off = trainer_off.init_state(batch)
    trainer_off._train_step = trainer_off._build_train_step()
    with trainer_off.mesh:
        hlo_off = (
            trainer_off._train_step.lower(
                state_off, features, labels, weights
            ).compile().as_text()
        )
    assert "all-reduce" not in hlo_off


def test_tp_fused_head_matches_plain():
    """fused_head's chunked cross-entropy must compose with the
    tp-sharded (vocab-split) head kernel: same losses as the plain-head
    tp trainer from the same init."""
    batch = _batch()
    mesh = mesh_lib.build_mesh({"tp": 8})

    plain = _trainer(mesh)
    p_state = plain.init_state(batch)
    fused = _trainer(mesh_lib.build_mesh({"tp": 8}),
                     extra={"fused_head": True})
    f_state = fused.init_state(batch)

    for _ in range(2):
        p_state, lp = plain.train_step(p_state, batch)
        f_state, lf = fused.train_step(f_state, batch)
        np.testing.assert_allclose(float(lf), float(lp), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p_state.params),
                    jax.tree.leaves(f_state.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        )


def test_tp_loss_matches_single_device():
    """The tp=8 compiled step computes the same loss and updates as the
    single-device model from the same init."""
    batch = _batch()

    single = _trainer(mesh_lib.build_mesh(
        {"dp": 1}, devices=jax.devices()[:1]))
    s_state = single.init_state(batch)

    tp = _trainer(mesh_lib.build_mesh({"tp": 8}))
    t_state = tp.init_state(batch)

    # same seed -> same init values regardless of mesh
    for a, b in zip(jax.tree.leaves(s_state.params),
                    jax.tree.leaves(t_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)

    losses_s, losses_t = [], []
    for _ in range(3):
        s_state, ls = single.train_step(s_state, batch)
        t_state, lt = tp.train_step(t_state, batch)
        losses_s.append(float(ls))
        losses_t.append(float(lt))
    np.testing.assert_allclose(losses_t, losses_s, rtol=1e-5, atol=1e-6)
