"""Runtime health plane unit tests (observability/runtime_health.py):
the recompile sentry's compile accounting + steady boundary, the
progress watchdog state machine (idle healthy, compile-is-progress,
transition-edged bundle dump), the flight recorder's bound, the
device-memory accountant's reconciliation math + the deliberate-leak
conviction, the diagnostic bundle's schema/atomicity, the SIGUSR2
dump registration, and the end-to-end self-report through a real
in-process GenerationServer (ServerStatus fields + /metrics family).
"""

import glob
import json
import os
import signal
import threading
import time

import pytest

from elasticdl_tpu.common.fault_injection import FaultInjector
from elasticdl_tpu.observability.runtime_health import (
    BUNDLE_SCHEMA,
    DeviceMemoryAccountant,
    FlightRecorder,
    ProgressWatchdog,
    RecompileSentry,
    RuntimeHealth,
    install_sigusr2_dump,
    tracked_jit,
    validate_bundle,
    write_bundle,
)
from elasticdl_tpu.serving.telemetry import ServingTelemetry


class FakeClock(object):
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------- recompile sentry


def test_tracked_jit_counts_compiles_not_calls():
    import jax.numpy as jnp

    sentry = RecompileSentry()
    fn = tracked_jit(lambda x: x + 1, "add", lambda: sentry)
    fn(jnp.zeros(3))
    fn(jnp.zeros(3))  # cache hit: no new compile
    snap = sentry.snapshot()
    assert snap["compiles"] == {"add": 1}
    assert snap["recompiles"] == 0


def test_recompile_vs_steady_anomaly():
    import jax.numpy as jnp

    sentry = RecompileSentry()
    fn = tracked_jit(lambda x: x * 2, "mul", lambda: sentry)
    fn(jnp.zeros(3))
    fn(jnp.zeros(4))  # new signature: a recompile, pre-boundary
    assert sentry.snapshot()["recompiles"] == 1
    assert sentry.snapshot()["steady_recompiles"] == 0
    sentry.mark_steady()
    # a FIRST compile of a new name after the boundary is the cold
    # path working as designed — never an anomaly
    other = tracked_jit(lambda x: x - 1, "sub", lambda: sentry)
    other(jnp.zeros(3))
    assert sentry.snapshot()["steady_recompiles"] == 0
    # a recompile of an existing name after the boundary IS one
    fn(jnp.zeros(5))
    snap = sentry.snapshot()
    assert snap["steady_recompiles"] == 1
    assert snap["anomalies"][-1]["fn"] == "mul"


def test_tracked_jit_without_sentry_is_plain_jit():
    import jax.numpy as jnp

    fn = tracked_jit(lambda x: x + 1, "loose", lambda: None)
    assert float(fn(jnp.asarray(1.0))) == 2.0


def test_tracked_jit_static_argnames_resolve_through_wrapper():
    import jax.numpy as jnp

    sentry = RecompileSentry()

    def slice_k(x, k):
        return x[:k]

    fn = tracked_jit(slice_k, "slice", lambda: sentry,
                     static_argnames=("k",))
    assert list(fn(jnp.arange(8), k=3)) == [0, 1, 2]
    fn(jnp.arange(8), k=3)
    assert sentry.snapshot()["compiles"]["slice"] == 1


def test_sentry_prometheus_family_shape():
    sentry = RecompileSentry()
    sentry.record_compile("a")
    sentry.record_compile("b")
    sentry.record_compile("b")
    fams = sentry.prometheus()
    assert len(fams) == 1
    name, mtype, _help, samples = fams[0]
    assert name == "edl_serving_recompiles_total"
    assert mtype == "counter"
    by_fn = {labels["fn"]: value for _s, labels, value in samples}
    assert by_fn == {"a": 1, "b": 2}


# ---------------------------------------------------------- watchdog


def test_watchdog_idle_is_healthy_forever():
    clock = FakeClock()
    wd = ProgressWatchdog(stall_after_secs=2.0, clock=clock)
    for _ in range(10):
        assert wd.observe(work=0, progress_counter=0) is False
        clock.advance(5.0)
    assert wd.state == "ok"
    assert wd.last_progress_age_ms() == 0.0


def test_watchdog_stalls_only_on_frozen_progress_with_work():
    clock = FakeClock()
    wd = ProgressWatchdog(stall_after_secs=2.0, clock=clock)
    wd.observe(work=1, progress_counter=5)
    clock.advance(1.0)
    # progress moving: healthy
    assert wd.observe(work=1, progress_counter=6) is False
    clock.advance(1.9)
    assert wd.observe(work=1, progress_counter=6) is False
    assert wd.state == "ok"
    clock.advance(0.2)  # age crosses the budget
    assert wd.observe(work=1, progress_counter=6) is True  # edge
    assert wd.state == "stalled"
    assert wd.stalls == 1
    # sustained stall: no second edge
    clock.advance(5.0)
    assert wd.observe(work=1, progress_counter=6) is False
    assert wd.stalls == 1
    # recovery: tokens flow again
    assert wd.observe(work=1, progress_counter=7) is False
    assert wd.state == "ok"
    assert wd.last_progress_age_ms() == 0.0


def test_watchdog_compile_counts_as_progress():
    """A long cold jit compile must never read as a stall: the caller
    folds compiles into the progress counter, so a moving compile
    count resets the age exactly like a committed token."""
    clock = FakeClock()
    wd = ProgressWatchdog(stall_after_secs=2.0, clock=clock)
    wd.observe(work=1, progress_counter=0)
    for _ in range(5):
        clock.advance(1.5)
        # tokens frozen, but the compile half of the counter moves
        assert wd.observe(work=1, progress_counter=_ + 1) is False
    assert wd.state == "ok"


# ----------------------------------------------------- flight recorder


def test_flight_recorder_bound_and_drop_accounting():
    ring = FlightRecorder(capacity=4)
    for i in range(10):
        ring.record({"tick": i})
    snap = ring.snapshot()
    assert [s["tick"] for s in snap] == [6, 7, 8, 9]  # drop-oldest
    assert ring.recorded == 10
    assert ring.dropped == 6


# --------------------------------------------------- memory accountant


class LedgerEngine(object):
    """Fake engine with a scripted ledger (no jax)."""

    def __init__(self):
        self.kv = {"kv_bytes_total": 1000, "kv_host_bytes": 200}

    def kv_stats(self):
        return dict(self.kv)


def test_accountant_reconciles_drift_since_baseline():
    eng = LedgerEngine()
    live = {"bytes": 1500}
    acct = DeviceMemoryAccountant(
        eng, live_bytes_fn=lambda: (live["bytes"], None)
    )
    view = acct.reconcile()
    # first reconcile baselines the gap: no drift yet
    assert view["unaccounted_bytes"] == 0
    live["bytes"] = 1900  # 400 bytes nothing in the ledger explains
    view = acct.reconcile()
    assert view["unaccounted_bytes"] == 400
    assert view["unaccounted_peak_bytes"] == 400
    # the drift clears (a transient): current drops, the PEAK holds —
    # monotone by construction
    live["bytes"] = 1500
    view = acct.reconcile()
    assert view["unaccounted_bytes"] == 0
    assert view["unaccounted_peak_bytes"] == 400
    # ledger growth the runtime CAN name is not drift
    live["bytes"] = 2000
    eng.kv["kv_bytes_total"] = 1500
    view = acct.reconcile()
    assert view["unaccounted_bytes"] == 0


def test_accountant_rebase_absorbs_presteady_drift():
    eng = LedgerEngine()
    live = {"bytes": 5000}
    acct = DeviceMemoryAccountant(
        eng, live_bytes_fn=lambda: (live["bytes"], None)
    )
    acct.reconcile()
    live["bytes"] = 9000  # warmup junk
    acct.reconcile()
    assert acct.snapshot()["unaccounted_peak_bytes"] == 4000
    acct.rebase()  # the steady boundary forgives it, peak included
    snap = acct.snapshot()
    assert snap["unaccounted_bytes"] == 0
    assert snap["unaccounted_peak_bytes"] == 0
    live["bytes"] = 9100  # ... but post-steady drift convicts
    acct.reconcile()
    assert acct.snapshot()["unaccounted_peak_bytes"] == 100


def test_accountant_param_and_draft_lines_with_real_engine_attrs():
    import jax.numpy as jnp

    class Eng(object):
        def __init__(self):
            self.variables = {"params": {"w": jnp.zeros((4, 4))}}
            self._exec_variables = self.variables  # non-quantized
            self._d_pool = {"k": jnp.zeros((2, 2))}

        def kv_stats(self):
            return {"kv_bytes_total": 0, "kv_host_bytes": 0}

    acct = DeviceMemoryAccountant(Eng(),
                                  live_bytes_fn=lambda: (0, None))
    ledger = acct.ledger()
    # exec IS variables: the shared leaves count once
    assert ledger["param_bytes"] == 4 * 4 * 4
    assert ledger["draft_pool_bytes"] == 2 * 2 * 4


# ------------------------------------------------------------ bundles


def test_bundle_write_is_atomic_and_schema_valid(tmp_path):
    bundle = {
        "schema": BUNDLE_SCHEMA, "reason": "progress_stall",
        "pid": os.getpid(), "seq": 1, "unix_ts": time.time(),
        "health": {"state": "stalled"}, "ring": [{"tick": 1}],
        "kv_ledger": {"kv_bytes_total": 1},
        "memory": {"unaccounted_bytes": 0},
        "recompiles": {"compiles": {}},
        "stacks": {"faulthandler": "Thread 0x1", "threads": []},
    }
    assert validate_bundle(bundle) == []
    path = write_bundle(str(tmp_path), bundle)
    assert os.path.exists(path)
    assert not glob.glob(str(tmp_path / "*.tmp"))  # no torn remnant
    with open(path) as f:
        assert json.load(f)["reason"] == "progress_stall"


def test_validate_bundle_rejects_malformed():
    assert validate_bundle([]) == ["bundle is not a dict"]
    problems = validate_bundle({"schema": "wrong"})
    assert any("missing key" in p for p in problems)
    assert any("schema" in p for p in problems)
    # stacks must actually carry something
    good = {
        "schema": BUNDLE_SCHEMA, "reason": "r", "pid": 1,
        "unix_ts": 1.0, "health": {}, "ring": [], "kv_ledger": {},
        "memory": {}, "recompiles": {},
        "stacks": {"faulthandler": "", "threads": []},
    }
    assert any("stacks" in p for p in validate_bundle(good))


# --------------------------------------------------- RuntimeHealth owner


class TickQueue(object):
    def __init__(self):
        self.n = 0

    def __len__(self):
        return self.n


class StubEngine(LedgerEngine):
    def __init__(self):
        super().__init__()
        self.active = 0

    def active_count(self):
        return self.active


def build_health(tmp_path=None, injector=None, stall_after=2.0):
    clock = FakeClock()
    engine = StubEngine()
    queue = TickQueue()
    telemetry = ServingTelemetry(clock=clock)
    health = RuntimeHealth(
        engine, queue, telemetry,
        stall_after_secs=stall_after,
        health_dir=str(tmp_path) if tmp_path is not None else "",
        injector=injector, clock=clock,
        live_bytes_fn=lambda: (0, None),
    )
    return health, engine, queue, telemetry, clock


def test_health_stall_transition_counts_and_dumps(tmp_path):
    health, engine, queue, telemetry, clock = build_health(tmp_path)
    health.record_tick(0, 1, 0.01, 3)
    engine.active = 1
    health.check()  # work present, counter frozen: window opens
    clock.advance(2.5)
    assert health.check() is True  # the ok->stalled edge
    assert telemetry.counters["stalls"] == 1
    assert health.snapshot()["health_state"] == "stalled"
    assert health.snapshot()["last_progress_age_ms"] >= 2000.0
    paths = glob.glob(str(tmp_path / "health-bundle-*.json"))
    assert len(paths) == 1
    with open(paths[0]) as f:
        bundle = json.load(f)
    assert validate_bundle(bundle) == []
    assert bundle["reason"] == "progress_stall"
    assert bundle["ring"][0]["tokens_committed"] == 3
    # this very test thread is in the stacks
    assert bundle["stacks"]["faulthandler"] or \
        bundle["stacks"]["threads"]
    # sustained stall: one bundle, not one per check
    clock.advance(5.0)
    assert health.check() is False
    assert len(glob.glob(str(tmp_path / "health-bundle-*.json"))) == 1


def test_health_tokens_recover_the_state(tmp_path):
    health, engine, _queue, telemetry, clock = build_health(tmp_path)
    engine.active = 1
    health.check()
    clock.advance(3.0)
    health.check()
    assert health.snapshot()["health_state"] == "stalled"
    telemetry.counters["tokens_generated"] += 1  # progress returns
    health.check()
    assert health.snapshot()["health_state"] == "ok"


def test_health_reconcile_mirrors_gauges_and_anomalies(tmp_path):
    health, _e, _q, telemetry, clock = build_health(tmp_path)
    health.sentry.record_compile("f")
    health.mark_steady()
    health.sentry.record_compile("f")  # anomaly
    clock.advance(1.0)
    health.reconcile()
    assert telemetry.counters["steady_recompiles"] == 1
    assert "last_progress_age_ms" in telemetry.gauges
    # delta mirror: a second reconcile must not double-count
    health.reconcile()
    assert telemetry.counters["steady_recompiles"] == 1


def test_health_leak_hook_fires_once_and_is_convicted():
    pytest.importorskip("jax")
    injector = FaultInjector(spec="health_leak:drop:1")
    health, _e, _q, _t, clock = build_health(injector=injector)
    # pre-steady: the hook must NOT fire (rebase would absorb it)
    health.reconcile()
    assert health.accountant.snapshot()["leaked_buffers"] == 0
    health.mark_steady()
    health.reconcile()  # the armed rule fires exactly once
    snap = health.accountant.snapshot()
    assert snap["leaked_buffers"] == 1
    health.reconcile()
    assert health.accountant.snapshot()["leaked_buffers"] == 1
    assert injector.injected == {"health_leak": 1}


# ------------------------------------------------------------ SIGUSR2


def test_sigusr2_dump_registers_and_fires(tmp_path, monkeypatch):
    monkeypatch.setenv("EDL_HEALTH_DIR", str(tmp_path))
    target = install_sigusr2_dump()
    assert target and target.startswith(str(tmp_path))
    signal.raise_signal(signal.SIGUSR2)
    # faulthandler writes synchronously on delivery in the main thread
    with open(target) as f:
        text = f.read()
    assert "Thread" in text or "File" in text
    # re-registration is safe (entrypoints call unconditionally)
    install_sigusr2_dump()


# ----------------------------------------- end-to-end through a server


@pytest.mark.slow
def test_server_self_reports_health_end_to_end(tmp_path):
    """A real in-process GenerationServer with the plane on: compiles
    counted, ServerStatus carries the self-report, /metrics carries
    the per-fn recompile family, and an injected engine_step delay
    turns into a stalled self-report + bundle while server_status
    stays answerable."""
    np = pytest.importorskip("numpy")
    from elasticdl_tpu.common.model_utils import get_model_spec
    from elasticdl_tpu.observability.metrics import render_prometheus
    from elasticdl_tpu.observability.promparse import (
        parse_prometheus_text,
    )
    from elasticdl_tpu.parallel import mesh as mesh_lib
    from elasticdl_tpu.proto import elasticdl_pb2 as pb
    from elasticdl_tpu.serving.server import (
        GenerationServer,
        ServingConfig,
    )
    from elasticdl_tpu.training.trainer import Trainer

    import jax

    spec = get_model_spec("model_zoo",
                          "transformer_lm.transformer_lm.custom_model")
    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = Trainer(
        spec, mesh=mesh,
        model_params="vocab_size=32; seq_len=32; embed_dim=32; "
                     "num_heads=2; num_layers=1",
    )
    seq_len = int(trainer.model.seq_len)
    dummy = np.zeros((1, seq_len), np.int32)
    state = trainer.init_state(({"tokens": dummy}, dummy))
    injector = FaultInjector(
        spec="engine_step:delay:1:secs=30,skip=2"
    )
    server = GenerationServer(
        trainer, state,
        ServingConfig(
            num_slots=2, kv_paged=True, kv_block_size=4,
            runtime_health=True, stall_after_secs=0.5,
            health_dir=str(tmp_path), idle_wait_secs=0.01,
            handler_poll_secs=0.05,
        ),
        injector=injector,
    ).start(grpc_server=False)
    try:
        server.raw_servicer.generate(
            pb.GenerateRequest(prompt=[1, 2], max_new_tokens=3)
        )
        server.mark_steady()
        st = server.raw_servicer.server_status(
            pb.ServerStatusRequest()
        )
        assert st.health_state == "ok"
        assert st.jit_compiles >= 2  # prefill + paged step at least
        assert st.steady_recompiles == 0

        # the armed delay wedges the scheduler on this request's 3rd
        # tick; the watchdog (own thread) must flip to stalled and
        # the STATUS RPC must keep answering
        done = threading.Event()

        def wedged_request():
            try:
                server.raw_servicer.generate(
                    pb.GenerateRequest(prompt=[3, 4],
                                       max_new_tokens=16,
                                       deadline_ms=20000)
                )
            except Exception:  # noqa: BLE001 - expiry is fine here
                pass
            done.set()

        t = threading.Thread(target=wedged_request, daemon=True)
        t.start()

        deadline = time.monotonic() + 20.0
        st = None
        while time.monotonic() < deadline:
            st = server.raw_servicer.server_status(
                pb.ServerStatusRequest()
            )
            if st.health_state == "stalled":
                break
            time.sleep(0.1)
        assert st is not None and st.health_state == "stalled", (
            "watchdog never declared the injected stall"
        )
        assert st.last_progress_age_ms >= 500.0
        # the bundle landed
        paths = glob.glob(str(tmp_path / "health-bundle-*.json"))
        assert paths
        with open(paths[0]) as f:
            assert not validate_bundle(json.load(f))
        # the scrape surface carries the per-fn family
        text = render_prometheus(server._metrics_families())
        fams = parse_prometheus_text(text)
        assert "edl_serving_recompiles_total" in fams
        assert "edl_serving_stalls_total" in fams
    finally:
        # the scheduler is sleeping inside the injected delay; don't
        # wait for a graceful drain
        server.scheduler._stop_requested.set()
        server.queue.wake()
        if server.health is not None:
            server.health.stop()
        server.telemetry.close()


if __name__ == "__main__":
    raise SystemExit(pytest.main([__file__, "-q"]))
