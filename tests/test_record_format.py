
import numpy as np
import pytest

from elasticdl_tpu.data import record_format
from elasticdl_tpu.data.example_codec import decode_example, encode_example


def test_write_and_scan_all(tmp_path):
    path = str(tmp_path / "a.trec")
    payloads = [b"rec-%d" % i for i in range(100)]
    record_format.write_records(path, payloads)
    assert record_format.get_record_count(path) == 100
    got = list(record_format.Scanner(path))
    assert got == payloads


def test_scan_range(tmp_path):
    path = str(tmp_path / "a.trec")
    record_format.write_records(path, [b"%d" % i for i in range(50)])
    got = list(record_format.Scanner(path, start=10, count=5))
    assert got == [b"10", b"11", b"12", b"13", b"14"]
    # range past EOF clamps
    got = list(record_format.Scanner(path, start=48, count=10))
    assert got == [b"48", b"49"]


def test_crc_detects_corruption(tmp_path):
    path = str(tmp_path / "a.trec")
    record_format.write_records(path, [b"x" * 100])
    data = bytearray(open(path, "rb").read())
    data[20] ^= 0xFF  # flip a payload byte
    open(path, "wb").write(bytes(data))
    with pytest.raises(IOError):
        list(record_format.Scanner(path))


def test_empty_file(tmp_path):
    path = str(tmp_path / "e.trec")
    record_format.write_records(path, [])
    assert record_format.get_record_count(path) == 0
    assert list(record_format.Scanner(path)) == []


def test_example_codec_roundtrip(tmp_path):
    ex = {
        "image": np.random.rand(28, 28).astype(np.float32),
        "label": np.array([3], dtype=np.int32),
        "ids": np.arange(7, dtype=np.int64),
    }
    out = decode_example(encode_example(ex))
    assert set(out) == set(ex)
    for k in ex:
        np.testing.assert_array_equal(out[k], ex[k])
        assert out[k].dtype == ex[k].dtype
