"""SPMD lockstep training tests on the 8-device virtual CPU mesh (single
process), plus the lockstep/assembly primitives."""

import jax
import numpy as np
import pytest

from elasticdl_tpu.common.model_utils import load_model_spec_from_module
from elasticdl_tpu.data import recordio_gen
from elasticdl_tpu.master.master import Master
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.parallel.spmd import (
    MODE_EVAL,
    MODE_TRAIN,
    ElasticSPMDLoop,
    SPMDContext,
    local_row_positions,
)
from elasticdl_tpu.worker.worker import JobType, Worker

# CI drills shard (make test-drills): the sub-5-min per-commit gate excludes this file.
pytestmark = pytest.mark.slow


def _spec():
    from model_zoo.mnist_functional_api import mnist_functional_api as zoo

    return load_model_spec_from_module(zoo)


def test_elastic_loop_eval_priority_and_stop():
    """Eval items preempt buffered train items; loop stops when both
    sources are exhausted (single-host consensus degenerates to local)."""
    ctx = SPMDContext(mesh_lib.build_mesh({"dp": 8}))
    train_items = iter([("item", "t1"), ("item", "t2"), ("done",)])
    eval_items = iter(["e1", None, None, None])
    order = []
    loop = ElasticSPMDLoop(
        ctx,
        poll_train=lambda: next(train_items),
        poll_eval=lambda: next(eval_items, None),
        train_step=lambda item: order.append(("T", item)),
        eval_step=lambda item: order.append(("E", item)),
    )
    rounds = loop.run()
    assert order == [("E", "e1"), ("T", "t1"), ("T", "t2")]
    assert rounds[MODE_EVAL] == 1 and rounds[MODE_TRAIN] == 2


def test_elastic_loop_wait_then_data():
    """A WAIT round sleeps and re-polls instead of stopping."""
    ctx = SPMDContext(mesh_lib.build_mesh({"dp": 8}))
    polls = iter([("wait",), ("item", "a"), ("done",)])
    seen = []
    loop = ElasticSPMDLoop(
        ctx,
        poll_train=lambda: next(polls),
        train_step=lambda item: seen.append(item),
        idle_sleep_secs=0.01,
    )
    loop.run()
    assert seen == ["a"]


def test_local_row_positions_single_process():
    mesh = mesh_lib.build_mesh({"dp": 8})
    sharding = mesh_lib.batch_sharding(mesh)
    rows = local_row_positions(sharding, 16)
    np.testing.assert_array_equal(rows, np.arange(16))


def test_assemble_single_process():
    ctx = SPMDContext(mesh_lib.build_mesh({"dp": 8}))
    batch = {"x": np.arange(32, dtype=np.float32).reshape(8, 4)}
    out = ctx.assemble(batch)
    assert out["x"].shape == (8, 4)
    np.testing.assert_array_equal(np.asarray(out["x"]), batch["x"])


@pytest.fixture()
def mnist_dirs(tmp_path):
    train_dir = str(tmp_path / "train")
    val_dir = str(tmp_path / "val")
    recordio_gen.gen_mnist_like(train_dir, num_files=2, records_per_file=48)
    recordio_gen.gen_mnist_like(val_dir, num_files=1, records_per_file=32,
                                seed=7)
    return train_dir, val_dir


def test_spmd_worker_trains_and_evaluates(mnist_dirs):
    train_dir, val_dir = mnist_dirs
    master = Master(
        _spec(),
        training_data=train_dir,
        validation_data=val_dir,
        minibatch_size=16,
        records_per_task=24,
        num_epochs=1,
        evaluation_steps=2,
    )
    worker = Worker(
        0,
        _spec(),
        master_servicer=master.servicer,
        job_type=JobType.TRAINING_WITH_EVALUATION,
        minibatch_size=16,
        training_data=train_dir,
        wait_sleep_secs=0.05,
        mesh=mesh_lib.build_mesh({"dp": 4, "fsdp": 2}),
        spmd=True,
    )
    state = worker.run()
    assert master.task_d.finished()
    assert int(state.step) == 96 // 16
    assert np.isfinite(worker.losses).all()
    # eval happened after training, aggregated on master
    assert master.evaluation_service.completed_job_metrics
    for _, metrics in master.evaluation_service.completed_job_metrics:
        assert "accuracy" in metrics


def test_spmd_matches_plain_worker(mnist_dirs):
    """SPMD lockstep on a sharded mesh takes the same trajectory as the
    plain single-device worker path on identical task streams."""
    train_dir, _ = mnist_dirs

    def run(spmd, mesh):
        import random

        import optax

        random.seed(42)  # task creation shuffles with the global RNG
        spec = _spec()
        # stable lr: the default 0.1 diverges on random labels, which
        # amplifies benign fp32 reduction-order noise exponentially
        spec.optimizer = lambda: optax.sgd(0.01)
        master = Master(
            spec,
            training_data=train_dir,
            minibatch_size=16,
            records_per_task=96,  # one task per file -> deterministic order
            num_epochs=1,
        )
        worker = Worker(
            0,
            spec,
            master_servicer=master.servicer,
            job_type=JobType.TRAINING_ONLY,
            minibatch_size=16,
            training_data=train_dir,
            wait_sleep_secs=0.05,
            mesh=mesh,
            spmd=spmd,
        )
        state = worker.run()
        return state, worker.losses

    s_plain, l_plain = run(False, mesh_lib.build_mesh(
        {"dp": 1}, devices=jax.devices()[:1]))
    s_spmd, l_spmd = run(True, mesh_lib.build_mesh({"dp": 8}))
    assert len(l_plain) == len(l_spmd)
    np.testing.assert_allclose(l_plain, l_spmd, rtol=1e-4, atol=1e-5)
    for a, b in zip(jax.tree.leaves(s_plain.params),
                    jax.tree.leaves(s_spmd.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-3)
