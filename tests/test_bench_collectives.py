"""The gradient-plane bandwidth bench (BASELINE.md target) stays
runnable: one small payload over the virtual 8-device mesh."""

import json
import os
import subprocess
import sys

import pytest

# CI drills shard (make test-drills): the sub-5-min per-commit gate excludes this file.
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_collectives_bench_smoke():
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = REPO
    r = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "scripts", "bench_collectives.py"), "8"],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["devices"] == 8
    assert out["value"] > 0
    assert out["metric"] == "grad_allreduce_bandwidth"
