"""Convergence invariance under elastic mesh changes — the reference's
published benchmark property (docs/benchmark/report_cn.md:108-120:
Wide&Deep / xDeepFM trained with elastic 4<->8 workers converge
indistinguishably from fixed-size runs; the reference can only show this
empirically because its async PS makes the math worker-count-dependent).

Here the claim is EXACT, not statistical: synchronous data-parallel
training with a fixed global batch makes the device count invisible to
the training math, so a run that re-forms dp=8 -> dp=4 -> dp=8
mid-training (checkpoint + re-shard restore — the elastic path of
test_elastic_reformation) must reproduce the uninterrupted dp=8 run's
losses step for step and land on the same final parameters."""

import numpy as np

import jax

from elasticdl_tpu.checkpoint import (
    CheckpointSaver,
    restore_state_from_checkpoint,
)
from elasticdl_tpu.common.model_utils import load_model_spec_from_module
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.training.trainer import Trainer
from model_zoo.mnist_functional_api import mnist_functional_api as zoo

import pytest

# CI drills shard (make test-drills): the sub-5-min per-commit gate excludes this file.
pytestmark = pytest.mark.slow


def _batches(n, bsz=16, seed=0):
    """Fixed global-batch stream shared by every run (task order is held
    constant; the property under test is the mesh size, not data order)."""
    rs = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        img = rs.rand(bsz, 28, 28).astype(np.float32)
        lab = rs.randint(10, size=(bsz,)).astype(np.int32)
        out.append(({"image": img}, lab))
    return out


def _flat(state):
    from elasticdl_tpu.checkpoint.saver import flatten_state

    return flatten_state(state)


def test_elastic_mesh_changes_do_not_change_convergence(tmp_path):
    import optax

    # lr 0.01 instead of the zoo's 0.1: the property is exact equality
    # of the update math, and a gentler optimizer keeps float
    # reduction-order drift (different device counts sum in different
    # orders) from being chaotically amplified over the 12 steps
    spec = load_model_spec_from_module(zoo)
    spec.optimizer = lambda: optax.sgd(0.01)
    batches = _batches(12)

    # ---- fixed-size run: dp=8 straight through
    t_fixed = Trainer(spec, mesh=mesh_lib.build_mesh({"dp": 8}))
    s = t_fixed.init_state(batches[0])
    fixed_losses = []
    for b in batches:
        s, loss = t_fixed.train_step(s, b)
        fixed_losses.append(float(loss))
    fixed_final = _flat(s)

    # ---- elastic run: dp=8 (4 steps), shrink to dp=4 (4 steps, e.g. a
    # host was preempted), grow back to dp=8 (4 steps) — each transition
    # through a sharded checkpoint + re-shard restore
    elastic_losses = []

    t8 = Trainer(spec, mesh=mesh_lib.build_mesh({"dp": 8}))
    s = t8.init_state(batches[0])
    for b in batches[:4]:
        s, loss = t8.train_step(s, b)
        elastic_losses.append(float(loss))
    saver = CheckpointSaver(
        str(tmp_path / "shrink"), checkpoint_steps=1, num_shards=2
    )
    saver.save(s, version=int(s.step))

    t4 = Trainer(
        spec,
        mesh=mesh_lib.build_mesh({"dp": 4}, devices=jax.devices()[:4]),
    )
    s4 = t4.init_state(batches[0])
    s4, version = restore_state_from_checkpoint(
        s4, str(tmp_path / "shrink")
    )
    assert version == 4
    for b in batches[4:8]:
        s4, loss = t4.train_step(s4, b)
        elastic_losses.append(float(loss))
    saver = CheckpointSaver(
        str(tmp_path / "grow"), checkpoint_steps=1, num_shards=3
    )
    saver.save(s4, version=int(s4.step))

    t8b = Trainer(spec, mesh=mesh_lib.build_mesh({"dp": 8}))
    s8 = t8b.init_state(batches[0])
    s8, version = restore_state_from_checkpoint(s8, str(tmp_path / "grow"))
    assert version == 8
    for b in batches[8:]:
        s8, loss = t8b.train_step(s8, b)
        elastic_losses.append(float(loss))

    # losses agree step for step and the final parameters coincide:
    # convergence is invariant to the elastic resizes (tolerances cover
    # reduction-order float drift across different device counts)
    np.testing.assert_allclose(
        elastic_losses, fixed_losses, rtol=1e-3, atol=1e-6
    )
    elastic_final = _flat(s8)
    assert set(elastic_final) == set(fixed_final)
    for key in fixed_final:
        np.testing.assert_allclose(
            elastic_final[key], fixed_final[key], rtol=1e-2, atol=1e-4,
            err_msg=key,
        )
