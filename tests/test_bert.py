"""BERT-class encoder family: masking recipe, masked-CE loss semantics,
e2e training through the LocalExecutor (plus transformer_lm through the
same path — the sequence families' executor coverage), and TP/SP mesh
compatibility."""

import numpy as np
import pytest

import jax.numpy as jnp

from elasticdl_tpu.api.local_executor import LocalExecutor
from elasticdl_tpu.common.model_utils import get_model_spec
from elasticdl_tpu.data import recordio_gen
from model_zoo.bert import bert

# CI drills shard (make test-drills): the sub-5-min per-commit gate excludes this file.
pytestmark = pytest.mark.slow

MODEL_ZOO = "model_zoo"


def test_mask_tokens_recipe():
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, 256, size=4096).astype(np.int32)
    masked, labels = bert._mask_tokens(tokens, 256, np.random.RandomState(1))
    targets = labels != bert.IGNORE_LABEL
    frac = targets.mean()
    assert 0.10 < frac < 0.20  # ~15%
    # labels carry the ORIGINAL token at target positions
    np.testing.assert_array_equal(labels[targets], tokens[targets])
    # non-target positions unchanged
    np.testing.assert_array_equal(masked[~targets], tokens[~targets])
    # [MASK] is the RESERVED id past the data vocabulary: it never
    # appears at non-target positions and random replacements never
    # introduce it
    assert (masked[~targets] != 256).all()
    mask_frac = (masked[targets] == 256).mean()
    assert 0.7 < mask_frac < 0.9  # ~80% -> [MASK]
    # ~10% keep the original token
    keep_frac = (masked[targets] == tokens[targets]).mean()
    assert keep_frac < 0.2


def test_masking_static_per_record_independent_across_records():
    """Content-seeded static masking: the same record masks identically
    across epochs; different records mask independently."""
    from elasticdl_tpu.common.constants import Mode
    from elasticdl_tpu.data.example_codec import encode_example

    class _FakeDs(object):
        def __init__(self, records):
            self.records = records

        def map(self, fn):
            self.out = [fn(r) for r in self.records]
            return self

        def shuffle(self, **kw):
            return self

    rng = np.random.RandomState(0)
    recs = [
        encode_example({
            "tokens": rng.randint(0, 64, size=33).astype(np.int64),
            "vocab_size": np.array(64, np.int64),
        })
        for _ in range(2)
    ]
    ds1 = bert.dataset_fn(_FakeDs(recs), Mode.EVALUATION, None)
    ds2 = bert.dataset_fn(_FakeDs(recs), Mode.EVALUATION, None)
    # deterministic across "epochs"
    np.testing.assert_array_equal(
        ds1.out[0][0]["tokens"], ds2.out[0][0]["tokens"]
    )
    # independent across records: mask POSITIONS differ
    m1 = ds1.out[0][1] != bert.IGNORE_LABEL
    m2 = ds1.out[1][1] != bert.IGNORE_LABEL
    assert not np.array_equal(m1, m2)


def test_loss_ignores_unmasked_positions():
    b, l, v = 2, 8, 16
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(b, l, v), jnp.float32)
    labels = np.full((b, l), bert.IGNORE_LABEL, np.int32)
    labels[0, 3] = 5
    # only (0,3) contributes; compare against direct CE there
    got = float(bert.loss(jnp.asarray(labels), logits))
    import optax

    want = float(
        optax.softmax_cross_entropy_with_integer_labels(
            logits[0, 3], jnp.asarray(5)
        )
    )
    np.testing.assert_allclose(got, want, rtol=1e-6)
    # all-ignored -> zero loss, no NaN
    all_ignored = jnp.full((b, l), bert.IGNORE_LABEL, jnp.int32)
    assert float(bert.loss(all_ignored, logits)) == 0.0


def _run_executor(spec_key, tmp_path, model_params=""):
    train_dir, val_dir = str(tmp_path / "train"), str(tmp_path / "val")
    recordio_gen.gen_tokens_like(train_dir, num_files=1,
                                 records_per_file=32)
    recordio_gen.gen_tokens_like(val_dir, num_files=1,
                                 records_per_file=16, seed=7)
    spec = get_model_spec(MODEL_ZOO, spec_key)
    executor = LocalExecutor(
        spec,
        training_data=train_dir,
        validation_data=val_dir,
        minibatch_size=8,
        num_epochs=1,
        records_per_task=32,
        model_params=model_params,
    )
    state, metrics = executor.run()
    assert int(state.step) == 4
    assert np.isfinite(executor.losses).all()
    return metrics


def test_bert_e2e_local_executor(tmp_path):
    metrics = _run_executor(
        "bert.bert.custom_model", tmp_path,
        model_params="vocab_size=64;seq_len=33;embed_dim=32;num_heads=2;"
                     "num_layers=1;attn_impl=xla",
    )
    assert 0.0 <= metrics["masked_token_accuracy"] <= 1.0


def test_transformer_lm_e2e_local_executor(tmp_path):
    metrics = _run_executor(
        "transformer_lm.transformer_lm.custom_model", tmp_path,
        model_params="vocab_size=64;seq_len=32;embed_dim=32;num_heads=2;"
                     "num_layers=1;attn_impl=xla",
    )
    assert 0.0 <= metrics["token_accuracy"] <= 1.0


def test_bert_trains_on_tp_mesh():
    from elasticdl_tpu.common.model_utils import (
        format_params_str,
        load_model_spec_from_module,
    )
    from elasticdl_tpu.parallel import mesh as mesh_lib
    from elasticdl_tpu.training.trainer import Trainer
    from jax.sharding import PartitionSpec as P

    mesh = mesh_lib.build_mesh({"dp": 2, "tp": 4})
    trainer = Trainer(
        load_model_spec_from_module(bert),
        mesh=mesh,
        model_params=format_params_str(
            dict(vocab_size=64, seq_len=16, embed_dim=32, num_heads=4,
                 num_layers=1, attn_impl="xla")
        ),
    )
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, 64, size=(8, 16)).astype(np.int32)
    labels = np.where(
        rng.rand(8, 16) < 0.15, tokens, bert.IGNORE_LABEL
    ).astype(np.int32)
    state = trainer.init_state(({"tokens": tokens}, labels))
    assert (
        state.params["layer_0"]["attn"]["qkv"]["kernel"].sharding.spec
        == P(None, "tp")
    )
    losses = []
    for _ in range(3):
        state, loss = trainer.train_step(state, ({"tokens": tokens}, labels))
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]
