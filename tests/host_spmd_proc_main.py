"""Subprocess entry for the 2-process host-spill embedding SPMD test
(test_spmd_multiprocess.py::test_two_process_host_embedding_parity).

Each process is one 'host' owning a partition of the embedding id space
(embedding/host_bridge.py enable_spmd). Batches are generated from a
shared seed so the parent can train the identical global stream
single-process and compare losses + the merged trained tables.
"""

import os
import sys

proc_id = int(sys.argv[1])
num_procs = int(sys.argv[2])
coord_port = sys.argv[3]
out_dir = sys.argv[4]
local_devices = int(sys.argv[5])
steps = int(sys.argv[6])

os.environ["XLA_FLAGS"] = (
    "--xla_force_host_platform_device_count=%d" % local_devices
)
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from elasticdl_tpu.parallel.spmd import initialize_distributed

initialize_distributed(
    coordinator_addr="localhost:%s" % coord_port,
    num_processes=num_procs,
    process_id=proc_id,
    platform="cpu",
)

import numpy as np

from elasticdl_tpu.common.model_utils import load_model_spec_from_module
from elasticdl_tpu.embedding.host_bridge import attach_from_spec
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.parallel.spmd import SPMDContext
from elasticdl_tpu.training.trainer import Trainer
from model_zoo.deepfm_host_embedding import deepfm_host_embedding as zoo

GLOBAL_BATCH = 16
VOCAB = 50

mesh = mesh_lib.build_mesh({"dp": num_procs * local_devices})
spec = load_model_spec_from_module(zoo)
trainer = Trainer(spec, mesh=mesh)
manager = attach_from_spec(trainer, spec)
ctx = SPMDContext(mesh)
manager.enable_spmd(ctx)

my_rows = ctx.rows_positions(GLOBAL_BATCH)[ctx.process_index]
rng = np.random.RandomState(7)
losses = []
state = None
for _ in range(steps):
    ids = rng.randint(0, VOCAB, size=(GLOBAL_BATCH, 10)).astype(np.int32)
    labels = rng.randint(0, 2, size=(GLOBAL_BATCH,)).astype(np.int32)
    feats = {"feature": ids[my_rows]}
    local_labels = labels[my_rows]
    if state is None:
        state = trainer.init_state((feats, local_labels))
    prepped = trainer._host_prepare(feats)
    gf, gl, gw = ctx.assemble(
        (prepped, local_labels,
         np.ones((len(my_rows),), np.float32))
    )
    state, loss = trainer.train_step_assembled(state, gf, gl, gw)
    losses.append(float(loss))

tables = {}
for name, t in manager.tables().items():
    ids_t, vals_t = t.engine.param.export_rows()
    tables[name + ".ids"] = ids_t
    tables[name + ".values"] = vals_t
np.savez(
    os.path.join(out_dir, "proc%d.npz" % proc_id),
    losses=np.asarray(losses, np.float64),
    **tables
)
print("HOST_SPMD_DONE pid=%d steps=%d" % (proc_id, len(losses)),
      flush=True)
