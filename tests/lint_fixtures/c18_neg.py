"""C18 negative fixture — the cell lifecycle pair settled on every
path: spawn_cell ends in adopt on the happy path and retire on every
failure branch (including the exception path), so EDL501 must stay
silent here."""


class CellScaler(object):
    def __init__(self, roster):
        self._roster = roster

    def grow(self, roster, cell_id):
        cell = roster.spawn_cell(cell_id)
        if not self.ready(cell):
            roster.retire(cell)
            return None
        roster.adopt(cell)
        return cell

    def grow_checked(self, roster, cell_id):
        cell = roster.spawn_cell(cell_id)
        try:
            self.probe(cell)
        except Exception:
            roster.retire(cell)
            raise
        roster.adopt(cell)
        return cell

    def ready(self, cell):
        return cell is not None

    def probe(self, cell):
        return bool(cell)
