"""C7 negative fixture — correct per-attribute lock binding in a
two-lock class: each attribute is only ever touched under ITS lock,
and taking both (ordered) for a consistent snapshot is fine because
the binding lock is among the held set."""

import threading


class Registry(object):
    def __init__(self):
        self._lock = threading.Lock()
        self._inflight_lock = threading.Lock()
        self._entries = {}
        self._inflight = 0

    def add(self, key, value):
        with self._lock:
            self._entries[key] = value

    def begin(self):
        with self._inflight_lock:
            self._inflight += 1

    def end(self):
        with self._inflight_lock:
            self._inflight -= 1

    def snapshot(self):
        with self._lock:
            entries = dict(self._entries)
            with self._inflight_lock:
                # both held: the binding lock is in the held set
                inflight = self._inflight
        return entries, inflight
