"""NEGATIVE fixture for EDL107: the sanctioned key idioms — split
then consume each child once, fold_in a counter per iteration (the
api/generation position-keyed sampling shape), rebinding between
sinks, and keys handed to non-sampler consumers. Expected findings:
none."""

import jax


def split_then_sample(shape):
    key = jax.random.PRNGKey(0)
    k_q, k_k = jax.random.split(key)
    q = jax.random.normal(k_q, shape)
    k = jax.random.uniform(k_k, shape)
    return q + k


def fold_per_position(shape, positions):
    rng = jax.random.PRNGKey(11)
    out = []
    for pos in positions:
        # fold_in(rng, position): the generation.py sampling idiom
        sub = jax.random.fold_in(rng, pos)
        out.append(jax.random.categorical(sub, shape))
    return out


def rebind_between_sinks(shape, n):
    key = jax.random.PRNGKey(1)
    rows = []
    for i in range(n):
        rows.append(jax.random.normal(key, shape))
        key, _ = jax.random.split(key)  # fresh key before re-use
    return rows


def closure_folds_inside(n):
    root = jax.random.PRNGKey(5)
    samplers = []
    for i in range(n):
        sub = jax.random.fold_in(root, i)

        def sample(shape, sub=sub):
            return jax.random.normal(sub, shape)

        samplers.append(sample)
    return samplers


def init_consumer(model, batch):
    key = jax.random.PRNGKey(0)
    return model.init(key, batch)  # not a sampler sink
