"""C11 positive fixture — EDL501 leaks of the prefix-shared KV pool's
refcount pairs (serving/kv_pool.py discipline):

1. an incref'd shared chain that an early-return path never decrefs —
   the blocks (and their arena rows) stay pinned forever;
2. a share() seat whose exception path drops the chain;
3. a CoW copy abandoned when the post-copy write fails.
"""


class ChainSeater(object):
    def __init__(self, allocator):
        self._allocator = allocator

    def seat_on_chain(self, allocator, chain, tokens):
        for bid in chain:
            allocator.incref(bid)
        if tokens > self.capacity():
            return None  # leak: the chain's refcounts never drop

    def seat_shared(self, allocator, slot, prompt):
        allocator.share(slot, prompt)
        rows = self.prefill(prompt)
        if rows is None:
            raise RuntimeError("prefill failed")  # leak: no decref/free
        allocator.free(slot)
        return rows

    def diverge(self, allocator, slot, pos):
        allocator.cow(slot, pos)
        ok = self.write_row(slot, pos)
        if not ok:
            return False  # leak: the CoW copy is never settled
        allocator.free(slot)
        return True

    def capacity(self):
        return 0

    def prefill(self, prompt):
        return prompt

    def write_row(self, slot, pos):
        return bool(slot) and pos >= 0
