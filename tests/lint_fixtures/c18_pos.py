"""C18 positive fixture — EDL501 leaks of the cell supervisor's
router-cell lifecycle pair (serving/router_main.py CellRoster
discipline, spawn_cell -> adopt | retire):

1. a spawned cell that an early-return path neither adopts nor
   retires — an orphan router process serving traffic no supervisor
   restarts and no shutdown reaps;
2. a spawned cell whose failed-adoption exception path never retires
   it — the pid leaks past the raise.
"""


class CellScaler(object):
    def __init__(self, roster):
        self._roster = roster

    def grow(self, roster, cell_id):
        cell = roster.spawn_cell(cell_id)
        if not self.ready(cell):
            return None  # leak: the cell is never adopted or retired
        roster.adopt(cell)
        return cell

    def grow_checked(self, roster, cell_id):
        cell = roster.spawn_cell(cell_id)
        if self.port_taken(cell):
            raise RuntimeError("port collision")  # leak: no retire
        roster.adopt(cell)
        return cell

    def ready(self, cell):
        return cell is not None

    def port_taken(self, cell):
        return bool(cell)
