"""C10 positive fixture — EDL104 donated-buffer aliasing.

Both wrapper idioms, each followed by a read of the donated value on
a path with no intervening rebind:

* assignment wrapper (``step = jax.jit(fn, donate_argnums=(0,))``)
  called in a loop, with the OLD state read after the call;
* ``@partial(jax.jit, donate_argnames=...)`` decorator, with the
  donated keyword argument read after the call returns.

Under donation the read either crashes ("array has been deleted") or
silently forces a copy that un-does the optimization.
"""

from functools import partial

import jax


def train_step(state, batch):
    return state


@partial(jax.jit, donate_argnames=("opt_state",))
def update(params, opt_state, grads):
    return params, opt_state


def train_loop(state0, batches):
    step = jax.jit(train_step, donate_argnums=(0,))
    state = state0
    for batch in batches:
        new_state = step(state, batch)
        loss = new_state.loss + state.loss  # EDL104: state was donated
        state = new_state
    return state, loss


def apply_updates(params, opt_state, grads):
    new_params, new_opt = update(params, opt_state=opt_state, grads=grads)
    return new_params, new_opt, opt_state.step  # EDL104: donated
