"""C22 positive fixture — EDL701/EDL702 write/replay closure and
payload-schema drift on a declared journal protocol:

1. an emit of a kind the declared alphabet does not know (EDL701
   undeclared-kind);
2. a replay branch for a kind the protocol does not know (EDL701
   dead-replay) and one for a declared kind no emit site produces
   (EDL701 never-emitted);
3. an emit that drops a `requires` key (EDL702) and an emit missing a
   key the replay reads unconditionally (EDL702, inferred contract).

All events are informational (no state transitions), so the typestate
half (EDL703/EDL704) stays quiet — this fixture isolates the closure
and schema checks.
"""

from elasticdl_tpu.analysis.typestate import JournalProtocol

PROTOCOL = JournalProtocol(
    name="meter",
    kind_key="ev",
    emit="_journal",
    replay="_apply_event",
    states=("idle",),
    initial="idle",
    events={
        "sample": {"informational": True, "requires": ("value",),
                   "optional": ("tag",)},
        "flushed": {"informational": True},
        "rotate": {"informational": True},
    },
    recoverable={"idle": "nothing in flight"},
)


class Meter(object):
    def __init__(self):
        self._samples = []
        self._flushes = 0

    def _journal(self, ev):
        pass

    def record(self, value):
        # drift: the declared contract requires 'value'
        self._journal({"ev": "sample", "tag": "latency"})

    def flush(self):
        # drift: replay reads ev["count"] unconditionally
        self._journal({"ev": "flushed"})

    def purge(self):
        # closure: 'purge' is not in the declared alphabet
        self._journal({"ev": "purge"})

    def _apply_event(self, ev):
        kind = ev.get("ev")
        if kind == "sample":
            self._samples.append(ev["value"])
        elif kind == "flushed":
            self._flushes += ev["count"]
        elif kind == "rotate":
            # closure: declared, replayed, but never emitted
            self._samples = []
        elif kind == "compact":
            # closure: replay branch for an undeclared kind
            self._samples = self._samples[-10:]
