"""C23 positive fixture — EDL703/EDL704 typestate violations on a
declared journal protocol with real transitions:

1. an emit journaled from a machine state its `from` set forbids
   (EDL703: 'finish' while already done);
2. an emit that moves the machine into a state with no declared
   resume action while another journal write is still reachable —
   the window between the two appends is an unrecoverable crash
   point (EDL704: 'start' parks the machine in 'baking', which
   `recoverable` does not cover).

Emit payloads and replay branches agree with the declaration, so the
closure half (EDL701/EDL702) stays quiet.
"""

from elasticdl_tpu.analysis.typestate import JournalProtocol

IDLE = "idle"
BAKING = "baking"
DONE = "done"

PROTOCOL = JournalProtocol(
    name="oven",
    kind_key="ev",
    emit="_journal",
    replay="_apply_event",
    states=(IDLE, BAKING, DONE),
    initial=IDLE,
    terminal=(DONE,),
    events={
        "start": {"from": (IDLE,), "to": BAKING},
        "finish": {"from": (BAKING,), "to": DONE},
    },
    recoverable={
        IDLE: "nothing in flight",
        DONE: "the bake is over",
    },
)


class Oven(object):
    def __init__(self):
        self.phase = IDLE

    def _journal(self, ev):
        pass

    def run(self):
        self.phase = IDLE
        self._journal({"ev": "start"})   # -> baking: unrecoverable window
        self.phase = BAKING
        self._journal({"ev": "finish"})
        self.phase = DONE
        self._journal({"ev": "finish"})  # illegal: finish from done

    def _apply_event(self, ev):
        kind = ev.get("ev")
        if kind == "start":
            self.phase = BAKING
        elif kind == "finish":
            self.phase = DONE
