"""CLEAN fixture for EDL108: pallas_call index-map lambdas that index
the scalar-prefetch block table with jnp/tracer-safe ops only — the
ops/attention.py _paged_decode_fused idiom. Also exercises the
lookalikes the rule must NOT flag: np.asarray OUTSIDE the lambda (host
prep before pallas_call is fine) and a non-BlockSpec call taking a
lambda.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def good_pool_spec(hkv, m, bs, d):
    # table-indirect DMA the tracer-safe way: jnp ops on the ref
    return pl.BlockSpec(
        (1, bs, 1, d),
        lambda i, j, tbl_ref, len_ref: (
            jnp.maximum(tbl_ref[(i // hkv) * m + j], 0),
            0,
            i % hkv,
            0,
        ),
    )


def good_keyword_spec(bs, d):
    return pl.BlockSpec(
        block_shape=(bs, d),
        index_map=lambda i, tbl_ref: (tbl_ref[i], 0),
    )


def kernel(tbl_ref, x_ref, o_ref):
    o_ref[...] = x_ref[...]


def build(x, table):
    # host-side np.asarray BEFORE the call is the normal prep idiom
    tbl = np.asarray(table, np.int32).reshape(-1)
    run = sorted([3, 1, 2], key=lambda v: int(v))  # lambda, not a spec
    del run
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(4,),
            in_specs=[good_pool_spec(2, 4, 8, 128)],
            out_specs=good_pool_spec(2, 4, 8, 128),
        ),
        out_shape=jax.ShapeDtypeStruct(x.shape, jnp.float32),
    )(jnp.asarray(tbl), x)
