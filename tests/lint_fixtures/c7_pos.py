"""C7 positive fixture — EDL004 wrong-lock-held.

A two-lock class (the router Replica shape: a registry lock plus a
fast inflight counter lock). Every locked write binds `_inflight` to
`_inflight_lock`; `snapshot`/`reset` touch it under `_lock` instead —
mutual exclusion holds against NEITHER writer, so both sides can tear.
"""

import threading


class Registry(object):
    def __init__(self):
        self._lock = threading.Lock()
        self._inflight_lock = threading.Lock()
        self._entries = {}
        self._inflight = 0

    def add(self, key, value):
        with self._lock:
            self._entries[key] = value

    def begin(self):
        with self._inflight_lock:
            self._inflight += 1

    def end(self):
        with self._inflight_lock:
            self._inflight -= 1

    def snapshot(self):
        with self._lock:
            # wrong lock: _inflight is bound to _inflight_lock
            return dict(self._entries), self._inflight

    def reset(self):
        with self._lock:
            self._entries.clear()
            self._inflight = 0  # wrong lock: write side
