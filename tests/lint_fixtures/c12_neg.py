"""C12 negative fixture — the supervisor seat pairs settle on every
path: reap on the failure branch, finally-guarded retire, Popen waited
on both branches, and ownership transfer (the handle escapes to the
roster / the caller)."""

import subprocess


class FleetScaler(object):
    def __init__(self, supervisor):
        self._supervisor = supervisor
        self._roster = {}

    def grow(self, supervisor, want):
        seat = supervisor.spawn(want)
        if not self.healthy(seat):
            supervisor.reap(seat)  # failure branch settles by reaping
            return None
        supervisor.adopt(seat)
        return seat

    def shrink(self, supervisor, seat):
        supervisor.begin_drain(seat)
        try:
            return self.wait_drained(seat)
        finally:
            supervisor.retire(seat)

    def shrink_escalating(self, supervisor, seat):
        supervisor.begin_drain(seat)
        ok = self.wait_drained(seat)
        if not ok:
            supervisor.reap(seat)  # drain stuck: escalate, still settled
            return False
        supervisor.retire(seat)
        return True

    def launch_once(self, cmd, deadline):
        proc = subprocess.Popen(["python", "-m", "replica"])
        if deadline <= 0:
            proc.kill()
            proc.wait(timeout=5)  # reap the kill before bailing
            return None
        proc.wait(timeout=deadline)
        return cmd

    def launch_owned(self, seat_id):
        proc = subprocess.Popen(["python", "-m", "replica"])
        self._roster[seat_id] = proc  # ownership transferred to roster
        return seat_id

    def healthy(self, seat):
        return seat is not None

    def wait_drained(self, seat):
        return bool(seat)
