"""EDL401 clean fixture: declared names, non-telemetry receivers,
and dynamic names are all out of scope."""


class Frontend(object):
    def __init__(self, telemetry):
        self.telemetry = telemetry

    def admit(self):
        self.telemetry.count("admitted")  # declared: clean

    def complete(self, name):
        self.telemetry.count(name)  # dynamic: the runtime raise owns it

    def tally(self, items):
        # list.count — receiver doesn't spell telemetry
        return items.count("admittd")
