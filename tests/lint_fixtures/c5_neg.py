"""EDL401 clean fixture: declared names, non-telemetry receivers,
and dynamic names are all out of scope — for counters AND gauges."""


class Frontend(object):
    def __init__(self, telemetry):
        self.telemetry = telemetry

    def admit(self):
        self.telemetry.count("admitted")  # declared: clean

    def complete(self, name):
        self.telemetry.count(name)  # dynamic: the runtime raise owns it

    def depth(self):
        self.telemetry.gauge("queue_depth", 3)  # declared gauge: clean

    def dynamic_gauge(self, name):
        self.telemetry.gauge(name, 1)  # dynamic: runtime raise owns it

    def tally(self, items):
        # list.count — receiver doesn't spell telemetry
        return items.count("admittd")

    def probe(self, meter):
        # .gauge through a non-telemetry receiver: out of scope
        return meter.gauge("whatever", 0)

    def slow(self):
        # declared cause (forensics.CAUSES): clean
        self.telemetry.count_slow_cause("prefill_blocked_by_other")

    def slow_dynamic(self, cause):
        # dynamic cause: the runtime raise owns it
        self.telemetry.count_slow_cause(cause)

    def health(self):
        # the runtime-health plane's declared names: clean
        self.telemetry.count("steady_recompiles")
        self.telemetry.count("stalls")
        self.telemetry.gauge("last_progress_age_ms", 0.0)
        self.telemetry.gauge("memory_unaccounted_bytes", 0)
