"""C11 negative fixture — the refcount pairs settle on every path:
finally-guarded decref, the slot-level free as the chain's settle, and
ownership transfer (the chain escapes to the caller / a container)."""


class ChainSeater(object):
    def __init__(self, allocator):
        self._allocator = allocator
        self._seated = {}

    def seat_on_chain(self, allocator, bid, tokens):
        allocator.incref(bid)
        try:
            if tokens > self.capacity():
                return None
            return bid
        finally:
            # a loop-shaped settle would NOT discharge the obligation
            # (zero iterations is a real path); the direct call does
            allocator.decref(bid)

    def seat_shared(self, allocator, slot, prompt):
        allocator.share(slot, prompt)
        try:
            rows = self.prefill(prompt)
        except Exception:
            allocator.free(slot)
            raise
        if rows is None:
            allocator.free(slot)
            return None
        allocator.free(slot)
        return rows

    def diverge(self, allocator, slot, pos):
        allocator.cow(slot, pos)
        try:
            return self.write_row(slot, pos)
        finally:
            allocator.free(slot)

    def seat_deferred(self, allocator, bid, key):
        allocator.incref(bid)
        self._seated[key] = allocator  # ownership transferred to the map

    def capacity(self):
        return 0

    def prefill(self, prompt):
        return prompt

    def write_row(self, slot, pos):
        return bool(slot) and pos >= 0
