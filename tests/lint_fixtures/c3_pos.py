"""POSITIVE fixture for EDL201: unbounded blocking inside gRPC
servicer methods and router dispatch paths. Expected findings:
EDL201 x8 (time.sleep, queue.get, stub call w/o timeout, .wait(),
dispatch-path queue.get, untimed Future.result(), untimed
futures.wait(), untimed as_completed())."""

import queue
import time
from concurrent import futures
from concurrent.futures import as_completed


class SlowServicer(object):
    def __init__(self, stub, done_event):
        self._stub = stub
        self._done = done_event
        self._results = queue.Queue()

    def generate(self, request, context=None):
        time.sleep(0.5)  # EDL201
        return self._results.get()  # EDL201

    def forward(self, request, context=None):
        return self._stub.generate(request)  # EDL201: no timeout=

    def flush(self, request, context=None):
        self._done.wait()  # EDL201
        return None

    def gather(self, request, context=None):
        futs = [self._pool.submit(item) for item in request.items]
        done = futures.wait(futs)  # EDL201: untimed futures.wait
        for fut in as_completed(futs):  # EDL201: untimed as_completed
            fut.result()  # EDL201: untimed Future.result
        return done


class EdgeRouter(object):
    def __init__(self):
        self._results = queue.Queue()

    def dispatch_generate(self, request):
        return self._results.get()  # EDL201

    def housekeeping(self):
        # NOT a dispatch-path method: unbounded wait tolerated here
        return self._results.get()
