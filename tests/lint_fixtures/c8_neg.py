"""C8 negative fixture — every acquisition settles on all paths:
three-way breaker settle (the PR 4 fix), finally-guarded release,
guarded acquire with a bail-out branch, and the ownership-transfer
escapes (return / container store / thread handoff)."""

import threading


class ProbeDispatcher(object):
    def __init__(self, clock):
        self._clock = clock

    def _transient(self, exc):
        return isinstance(exc, TimeoutError)

    def _backpressure(self, exc):
        return isinstance(exc, BlockingIOError)

    def probe_dispatch(self, rep, req):
        now = self._clock()
        if not rep.breaker.acquire(now):
            return None  # never acquired on this path
        try:
            resp = rep.stub.generate(req, timeout=1.0)
        except Exception as e:
            if self._transient(e):
                rep.breaker.record_failure(now)
            elif self._backpressure(e):
                rep.breaker.record_success()
            else:
                rep.breaker.release_probe()  # the PR 4 fix
            raise
        rep.breaker.record_success()
        return resp


class SpanScoped(object):
    def __init__(self, recorder):
        self._recorder = recorder
        self._open = {}

    def trace_step(self, item):
        span = self._recorder.start_span("step", item=item)
        try:
            if not item:
                return 0
            span.event("ran")
            return 1
        finally:
            span.finish("ok")

    def trace_deferred(self, key):
        span = self._recorder.start_span("deferred", key=key)
        self._open[key] = span  # ownership transferred to the map
        return key

    def trace_handoff(self, rep):
        rep.begin_dispatch()
        t = threading.Thread(target=self._finish, args=(rep,))
        t.start()  # the poll_once shape: the thread owns end_dispatch

    def _finish(self, rep):
        rep.end_dispatch()

    def pick(self, reps, now):
        for rep in reps:
            if rep.breaker.acquire(now):
                return rep  # caller inherits the probe obligation
        return None


def read_header(path):
    with open(path) as f:  # context manager releases
        return f.read(16)


def read_header_manual(path):
    f = open(path)
    try:
        return f.read(16)
    finally:
        f.close()
