"""C23 negative fixture — the same two-transition machine as c23_pos
with both defects repaired: the intermediate 'baking' state declares a
resume action (so the start->finish window is a legal crash point) and
'finish' is journaled exactly once, from 'baking'. Clean under
EDL701-EDL704.
"""

from elasticdl_tpu.analysis.typestate import JournalProtocol

IDLE = "idle"
BAKING = "baking"
DONE = "done"

PROTOCOL = JournalProtocol(
    name="oven",
    kind_key="ev",
    emit="_journal",
    replay="_apply_event",
    states=(IDLE, BAKING, DONE),
    initial=IDLE,
    terminal=(DONE,),
    events={
        "start": {"from": (IDLE,), "to": BAKING},
        "finish": {"from": (BAKING,), "to": DONE},
    },
    recoverable={
        IDLE: "nothing in flight",
        BAKING: "replay re-enters baking; the tick resumes the bake",
        DONE: "the bake is over",
    },
)


class Oven(object):
    def __init__(self):
        self.phase = IDLE

    def _journal(self, ev):
        pass

    def run(self):
        self.phase = IDLE
        self._journal({"ev": "start"})
        self.phase = BAKING
        self._journal({"ev": "finish"})
        self.phase = DONE

    def _apply_event(self, ev):
        kind = ev.get("ev")
        if kind == "start":
            self.phase = BAKING
        elif kind == "finish":
            self.phase = DONE
