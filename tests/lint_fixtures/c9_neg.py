"""C9 negative fixture — deadlines that FLOW. The entry derives a
remaining budget from the request, decrements it into helpers and
nested stream generators, and every downstream stub call's timeout=
traces back to it. Heartbeat/poll paths are not dispatch-reachable
and keep their static poll timeouts without complaint."""


class FrontendServicer(object):
    def __init__(self, stub):
        self._stub = stub

    def generate(self, request, context=None):
        remaining = request.deadline_ms / 1000.0
        resp = self._stub.generate(request, timeout=remaining)
        return resp or self._relay(request, remaining * 0.5)

    def _relay(self, request, budget):
        # the budget is threaded in and the timeout derives from it
        return self._stub.generate(request, timeout=min(budget, 10.0))

    def generate_stream(self, request, context=None):
        budget = request.deadline_ms / 1000.0

        def gen():
            # closure over a budget-derived local: still derived
            yield self._stub.generate(request, timeout=budget)

        return gen()

    def heartbeat_poll(self):
        # no inbound deadline exists here; a static poll bound is the
        # correct design (lease renewal must not inherit a request's)
        return self._stub.server_status(None, timeout=2.0)


class EdgeRouter(object):
    def __init__(self, stub):
        self._stub = stub

    def dispatch(self, request, deadline_ms):
        spent = 0.25
        return self._stub.generate(
            request, timeout=deadline_ms / 1000.0 - spent
        )
