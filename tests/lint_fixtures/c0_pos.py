"""POSITIVE fixture for EDL000 (unused suppression): pragmas that
suppress nothing — the line they vetted was fixed or deleted, and the
dead pragma now stands ready to hide the NEXT real finding there.
Expected findings: EDL000 x2 (the trailing and the whole-line
pragma). The used pragmas in c1_pragma.py are the clean twin."""

import threading


class Ledger(object):
    def __init__(self):
        self._lock = threading.Lock()
        self._total = 0

    def add(self, n):
        with self._lock:
            self._total += n

    def total(self):
        with self._lock:
            return self._total  # edl-lint: disable=EDL002

    # edl-lint: disable=EDL001
    def reset_locked(self):
        self._total = 0
