"""C12 positive fixture — EDL501 leaks of the replica supervisor's
seat lifecycle pairs (serving/autoscaler.py discipline):

1. a spawned seat that an early-return path neither adopts nor reaps —
   an orphan replica process no journal remembers;
2. a drain begun whose exception path never retires (or reaps) the
   seat — it sits mid-drain forever;
3. a launcher Popen handle killed but never waited on — a zombie
   pinned until the supervisor exits.
"""

import subprocess


class FleetScaler(object):
    def __init__(self, supervisor):
        self._supervisor = supervisor

    def grow(self, supervisor, want):
        seat = supervisor.spawn(want)
        if not self.healthy(seat):
            return None  # leak: the seat is never adopted or reaped
        supervisor.adopt(seat)
        return seat

    def shrink(self, supervisor, seat):
        supervisor.begin_drain(seat)
        ok = self.wait_drained(seat)
        if not ok:
            raise RuntimeError("drain stuck")  # leak: no retire/reap
        supervisor.retire(seat)
        return seat

    def launch_once(self, cmd, deadline):
        proc = subprocess.Popen(["python", "-m", "replica"])
        if deadline <= 0:
            proc.kill()
            return None  # leak: killed but never waited (zombie)
        proc.wait(timeout=deadline)
        return cmd

    def healthy(self, seat):
        return seat is not None

    def wait_drained(self, seat):
        return bool(seat)
