"""NEGATIVE fixture for EDL201: the sanctioned forms — every wait
bounded, every RPC deadlined, the injected sleep, bounded
concurrent.futures waits, and blocking calls in classes outside the
servicer/dispatch surface. Expected findings: none."""

import queue
import time
from concurrent import futures
from concurrent.futures import as_completed


class PromptServicer(object):
    def __init__(self, stub, done_event, sleep=None):
        self._stub = stub
        self._done = done_event
        self._results = queue.Queue()
        self._sleep = sleep or (lambda s: None)

    def generate(self, request, context=None):
        self._sleep(0.01)  # injected sleep: testable and bounded
        try:
            return self._results.get(timeout=1.0)
        except queue.Empty:
            return None

    def forward(self, request, context=None):
        # bounded AND derived from the inbound budget (C9-clean too)
        return self._stub.generate(
            request, timeout=request.deadline_ms / 1000.0
        )

    def flush(self, request, context=None):
        self._done.wait(2.0)
        return None

    def gather(self, request, context=None):
        futs = [self._pool.submit(item) for item in request.items]
        done, _ = futures.wait(futs, timeout=5.0)
        for fut in as_completed(futs, timeout=5.0):
            fut.result(timeout=1.0)
        return done


class BatchWorker(object):
    """Not a servicer, not a router: a background consumer thread MAY
    block forever on its feed queue."""

    def __init__(self):
        self._q = queue.Queue()

    def run(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            time.sleep(0.0)

    def drain(self, futs):
        # outside the servicer/dispatch surface: an untimed result()
        # on a worker thread is the owner's choice
        return [f.result() for f in futs]
