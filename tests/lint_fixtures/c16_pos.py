"""POSITIVE fixture for EDL107 (PRNG-key discipline): one key feeding
two sampler sinks, a key re-consumed across loop iterations, and a
per-iteration closure sharing one pre-loop key. Expected findings:
EDL107 x3."""

import jax


def double_sink(shape):
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, shape)
    k = jax.random.uniform(key, shape)  # EDL107: identical randomness
    return q + k


def loop_reconsume(shape, n):
    key = jax.random.PRNGKey(7)
    rows = []
    for _ in range(n):
        # every iteration draws with the SAME key: n identical rows
        rows.append(jax.random.normal(key, shape))  # EDL107
    return rows


def closure_shares_key(n):
    key = jax.random.PRNGKey(3)
    samplers = []
    for i in range(n):
        def sample(shape):
            return jax.random.normal(key, shape)  # EDL107 (closure)

        samplers.append(sample)
    return samplers
