"""NEGATIVE fixture for EDL105: the sanctioned stabilizer idioms —
bucket helpers, ceil-to-multiple pads, power-of-two tiles, min clamps,
scalar device binding, and per-shape wrappers rebuilt in the loop.
Expected findings: none."""

import jax
import jax.numpy as jnp
import numpy as np


def _prefill_bucket(p, seq_len):
    return min(seq_len, -(-p // 64) * 64)


def bucketed_prefill(model, prompts, seq_len):
    fn = jax.jit(model)
    out = []
    for p in prompts:
        p_pad = _prefill_bucket(len(out), seq_len)  # bucketed
        out.append(fn(np.zeros((1, p_pad))))
    return out


def ceil_multiple_inline(model, items, seq_len):
    fn = jax.jit(model)
    out = []
    for i in range(len(items)):
        t_pad = min(seq_len, ((i + 7) // 8) * 8)  # tile bucket of 8
        out.append(fn(np.zeros((1, t_pad))))
    return out


def pow2_pad(model, items):
    fn = jax.jit(model)
    out = []
    for i, item in enumerate(items):
        width = 1 << max(1, i).bit_length()  # next power of two
        out.append(fn(np.zeros((1, width))))
    return out


def device_bound_index(write_fn, pool, table, start, stop):
    fn = jax.jit(write_fn)
    for j in range(start, stop):
        # the counter is a shape-() device scalar: traced DATA, the
        # signature never changes (the kv_pool block-write idiom)
        pool = fn(pool, jnp.asarray(j, jnp.int32),
                  jnp.asarray(table[j], jnp.int32))
    return pool


def per_shape_wrapper(make_step, shapes):
    out = []
    for n in shapes:
        fn = jax.jit(make_step(n))  # fresh executable per shape:
        out.append(fn(np.zeros((1, n))))  # deliberate, not churn
    return out
