"""C21 negative fixture — the rollout lifecycles settled on every
path: commit_wave on the soaked happy path, rollback_wave on the
not-converged branch, the burn alert, and the exception path;
stage_checkpoint settles through activate on success and discard on
the failed-verification branch — EDL501 must stay silent."""


class RolloutDriver(object):
    def __init__(self, ctl):
        self._ctl = ctl

    def advance(self, ctl, wave, addrs, reports):
        converged = ctl.begin_wave(wave, addrs)
        if not converged or self.alerting(reports):
            ctl.rollback_wave(wave, "swap failed or SLO burn")
            return False
        ctl.commit_wave(wave)
        return True

    def advance_checked(self, ctl, wave, addrs):
        ctl.begin_wave(wave, addrs)
        try:
            self.soak(ctl)
        except Exception:
            ctl.rollback_wave(wave, "soak raised")
            raise
        ctl.commit_wave(wave)
        return True

    def prepare(self, stager, version):
        if not stager.stage_checkpoint(version):
            raise RuntimeError(stager.discard())
        return stager.activate()

    def alerting(self, reports):
        return bool(reports)

    def soak(self, ctl):
        return ctl
