"""POSITIVE fixture for EDL108: host-side materialization inside
pallas_call BlockSpec index-map lambdas — the hazard class the fused
paged decode kernel introduced (the block table rides a
scalar-prefetch ref; np.asarray/.item()/int() on it concretizes a
tracer or bakes a stale table in). Expected findings: EDL108 x4
(np.asarray, .item(), int() cast, keyword index_map= spelling).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def bad_positional_specs(table, hkv, m, bs, d):
    # positional index map (2nd arg), two hazards inside one lambda
    return pl.BlockSpec(
        (1, bs, 1, d),
        lambda i, j, tbl_ref, len_ref: (
            np.asarray(tbl_ref)[i * m + j],  # EDL108
            0,
            int(i) % hkv,  # EDL108
            0,
        ),
    )


def bad_item_spec(bs, d):
    return pl.BlockSpec(
        (1, bs, 1, d),
        lambda i, j, tbl_ref: (tbl_ref[j].item(), 0, 0, 0),  # EDL108
    )


def bad_keyword_spec(bs, d):
    # keyword spelling of the same mistake
    return pl.BlockSpec(
        block_shape=(bs, d),
        index_map=lambda i, tbl_ref: (np.array(tbl_ref[i]), 0),  # EDL108
    )


def kernel(tbl_ref, x_ref, o_ref):
    o_ref[...] = x_ref[...]


def build(x, table):
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(4,),
            in_specs=[bad_item_spec(8, 128)],
            out_specs=bad_item_spec(8, 128),
        ),
        out_shape=jax.ShapeDtypeStruct(x.shape, jnp.float32),
    )(table, x)
