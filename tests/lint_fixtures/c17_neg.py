"""NEGATIVE fixture for EDL601: constraints inside jit contexts
(decorator, wrap idiom, and a helper nested in one), canonical and
mesh-declared axis names, constant-derived axes (never guessed), and
the sanctioned donate + in/out shardings shape. Expected findings:
none."""

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from elasticdl_tpu.common.constants import MeshAxis


@jax.jit
def decorated_pin(x):
    return jax.lax.with_sharding_constraint(x, P("dp"))


def wrapped_pin(x, sharding):
    def step(v):
        def helper(u):
            # nested inside a traced function: traced with it
            return jax.lax.with_sharding_constraint(u, sharding)

        return helper(v + 1)

    return jax.jit(step)(x)


def declared_axes(devices):
    mesh = Mesh(np.asarray(devices), ("dp", "fsdp", "ep"))
    return NamedSharding(mesh, P(("dp", "fsdp"), "ep"))


def canonical_axes():
    return P("tp", "sp")


def constant_axes(mesh):
    # non-literal axis expressions contribute nothing (never guess)
    return NamedSharding(mesh, P(MeshAxis.EP))


def donated_sharded_update(step_fn, state_sharding, batch_sharding):
    return jax.jit(
        step_fn,
        donate_argnums=(0,),
        in_shardings=(state_sharding, batch_sharding),
        out_shardings=state_sharding,
    )
