"""NEGATIVE fixture for EDL001/EDL002: every guarded access is under
the lock, via the `*_locked` convention, via a helper whose only call
sites are locked (the call-graph-light fixpoint), or in __init__ /
ctor-only helpers. Expected findings: none."""

import threading


class Counter(object):
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._items = []
        self._seed_initial()  # ctor-only helper: exempt

    def _seed_initial(self):
        self._items.append(0)

    def bump(self):
        with self._lock:
            self._count += 1
            self._record()

    def _record(self):
        # only called from bump's locked region -> treated as locked
        self._items.append(self._count)

    def _drain_locked(self):
        # the *_locked suffix declares "caller holds the lock"
        self._items.clear()
        self._count = 0

    def reset(self):
        with self._lock:
            self._drain_locked()

    def snapshot(self):
        with self._lock:
            return list(self._items), self._count
