"""POSITIVE fixture for EDL105 (recompile hazard): jit-wrapped
executables fed arguments whose abstract signature varies across
executions. Expected findings: EDL105 x4 — a loop-derived shape, a
len() of a growing attribute container, a wall-clock read and an
environment read in the signature."""

import os
import time

import jax
import numpy as np


def churn_loop(model, n_iters):
    step = jax.jit(model)
    out = None
    for i in range(n_iters):
        # the loop counter becomes an array SHAPE: one compile per
        # iteration — the steady-state recompile loop
        out = step(np.zeros((1, i + 1)))  # EDL105 (loop)
    return out


class BatchRunner(object):
    def __init__(self, model):
        self._fn = jax.jit(model)
        self._staging = []

    def run(self, item):
        self._staging.append(item)
        # the staging list grows across calls; its len re-keys the
        # compile cache on every admission
        return self._fn(np.zeros((len(self._staging), 8)))  # EDL105


def stamped(fn0, x):
    fn = jax.jit(fn0)
    return fn(x, time.time())  # EDL105 (clock)


def env_sized(fn0):
    fn = jax.jit(fn0)
    width = int(os.environ.get("EDL_WIDTH", "64"))
    return fn(np.zeros((1, width)))  # EDL105 (config)
