"""C19 positive fixture — EDL501 leaks of the disaggregated handoff's
transfer obligation (serving/disagg.py HandoffCoordinator discipline,
export_chain -> import_chain | abort_transfer, receiver hint
"disagg"):

1. an exported chain that a not-ready early return neither imports nor
   aborts — a transfer the two-pool ledger cannot reconcile;
2. an export whose failed-import exception path never records the
   abort — the failure leaves no ledger entry past the raise.
"""


class HandoffDriver(object):
    def __init__(self, disagg):
        self._disagg = disagg

    def warm(self, disagg, prefill_rep, decode_rep, request, tid):
        payload = disagg.export_chain(prefill_rep, request, tid)
        if not self.ready(decode_rep):
            return None  # leak: neither imported nor aborted
        disagg.import_chain(decode_rep, payload)
        return payload

    def warm_checked(self, disagg, prefill_rep, decode_rep, request,
                     tid):
        payload = disagg.export_chain(prefill_rep, request, tid)
        if self.draining(decode_rep):
            raise RuntimeError("decode draining")  # leak: no abort
        disagg.import_chain(decode_rep, payload)
        return payload

    def ready(self, rep):
        return rep is not None

    def draining(self, rep):
        return bool(rep)
