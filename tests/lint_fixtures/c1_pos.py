"""POSITIVE fixture for EDL001/EDL002: a lock-owning class that
mutates and reads its guarded attributes outside the lock. Expected
findings: EDL001 at bump_unlocked/append_unlocked, EDL002 at
peek_unlocked."""

import threading


class Counter(object):
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._items = []

    def bump(self):
        with self._lock:
            self._count += 1
            self._items.append(self._count)

    def bump_unlocked(self):
        self._count += 1  # EDL001

    def append_unlocked(self, x):
        self._items.append(x)  # EDL001

    def peek_unlocked(self):
        return self._count  # EDL002
