"""C10 negative fixture — the sanctioned donation idioms: rebinding
the result to the donated name at the call itself, branch-local
rebinds before any read, and computed donate declarations (which the
rule deliberately treats as "nothing donated" — precision over
recall)."""

from functools import partial

import jax


def train_step(state, batch):
    return state


@partial(jax.jit, donate_argnames=("opt_state",))
def update(params, opt_state, grads):
    return params, opt_state


def train_loop(state0, batches):
    step = jax.jit(train_step, donate_argnums=(0,))
    state = state0
    for batch in batches:
        state = step(state, batch)  # rebind at the call: clean
    return state


def apply_updates(params, opt_state, grads):
    params, opt_state = update(params, opt_state=opt_state, grads=grads)
    return params, opt_state


def computed_declaration(fn, ns, state, batch):
    step = jax.jit(fn, donate_argnums=ns)  # computed: not tracked
    out = step(state, batch)
    return out, state


def rebind_before_read(state, batch):
    step = jax.jit(train_step, donate_argnums=(0,))
    out = step(state, batch)
    state = out  # rebind kills the dead value before any read
    return state.loss
