"""C22 negative fixture — a journal protocol whose emit sites, replay
branches, and declared alphabet agree exactly: every declared kind is
emitted with its full payload contract, every replay branch matches a
declared kind, optional keys are read via .get(). Clean under
EDL701-EDL704.
"""

from elasticdl_tpu.analysis.typestate import JournalProtocol

PROTOCOL = JournalProtocol(
    name="meter",
    kind_key="ev",
    emit="_journal",
    replay="_apply_event",
    states=("idle",),
    initial="idle",
    events={
        "sample": {"informational": True, "requires": ("value",),
                   "optional": ("tag",)},
        "flushed": {"informational": True, "requires": ("count",)},
        "rotate": {"informational": True},
    },
    recoverable={"idle": "nothing in flight"},
)


class Meter(object):
    def __init__(self):
        self._samples = []
        self._flushes = 0

    def _journal(self, ev):
        pass

    def record(self, value, tag=None):
        ev = {"ev": "sample", "value": value}
        if tag is not None:
            ev["tag"] = tag
        self._journal(ev)

    def flush(self):
        self._journal({"ev": "flushed", "count": len(self._samples)})

    def rotate(self):
        self._journal({"ev": "rotate"})

    def _apply_event(self, ev):
        kind = ev.get("ev")
        if kind == "sample":
            self._samples.append((ev["value"], ev.get("tag")))
        elif kind == "flushed":
            self._flushes += ev["count"]
        elif kind == "rotate":
            self._samples = []
