"""PRAGMA fixture: the same violation as c1_pos, suppressed in place.
Expected findings: none (both pragma placements: same line and the
line above)."""

import threading


class Gauge(object):
    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0

    def set(self, v):
        with self._lock:
            self._value = v

    def read_fast(self):
        # monotonic int; GIL-atomic single read
        return self._value  # edl-lint: disable=EDL002

    def read_fast_too(self):
        # edl-lint: disable=EDL002 — same justification, line above
        return self._value
