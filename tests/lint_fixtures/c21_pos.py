"""C21 positive fixture — EDL501 leaks of the rollout controller's
lifecycles (serving/rollout.py discipline, begin_wave -> commit_wave |
rollback_wave and stage_checkpoint -> activate | discard):

1. a wave opened and then abandoned by a not-converged early return —
   the fleet sits on a mixed version with the journal claiming the
   wave is still in flight;
2. a wave whose SLO-burn exception path never turns the fleet around —
   the alert raises past the rollback;
3. a staged checkpoint whose failed-verification branch never discards
   the verdict — a verification error nobody reads.
"""


class RolloutDriver(object):
    def __init__(self, ctl):
        self._ctl = ctl

    def advance(self, ctl, wave, addrs):
        converged = ctl.begin_wave(wave, addrs)
        if not converged:
            return None  # leak: neither committed nor rolled back
        ctl.commit_wave(wave)
        return wave

    def advance_checked(self, ctl, wave, addrs, reports):
        ctl.begin_wave(wave, addrs)
        if self.alerting(reports):
            raise RuntimeError("SLO burn")  # leak: no rollback_wave
        ctl.commit_wave(wave)
        return wave

    def prepare(self, stager, version):
        staged = stager.stage_checkpoint(version)
        if not staged:
            return None  # leak: the verification error is never read
        return stager.activate()

    def alerting(self, reports):
        return bool(reports)
