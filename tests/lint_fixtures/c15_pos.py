"""POSITIVE fixture for EDL106 (captured-constant bloat): traced
functions capturing materialized ndarrays by closure. Expected
findings: EDL106 x3 — a module-level table baked into a decorated jit
fn, a device matrix captured by the wrap idiom, and a numpy buffer
captured through a partial-decorated step."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

VOCAB_TABLE = np.arange(1 << 20).reshape(1 << 10, 1 << 10)


@jax.jit
def lookup(idx):
    # the whole table is re-hashed and re-baked on every retrace
    return VOCAB_TABLE[idx]  # EDL106


def build_step(scale):
    weights = jnp.asarray(np.ones((4096, 4096)))

    def step(x):
        return x @ weights * scale  # EDL106 (weights; scale is fine)

    return jax.jit(step)


def build_masked():
    mask = np.ones((2048, 2048))

    @partial(jax.jit, static_argnames=("causal",))
    def apply(scores, causal):
        if causal:
            return scores * mask  # EDL106
        return scores

    return apply
