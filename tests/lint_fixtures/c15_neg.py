"""NEGATIVE fixture for EDL106: arrays threaded as arguments, scalar/
config captures, and untraced closures over arrays. Expected
findings: none."""

import jax
import jax.numpy as jnp
import numpy as np


def build_step(scale, causal):
    def step(weights, x):
        # params threaded as proper args: donated/updated normally
        y = x @ weights * scale
        return jnp.where(causal, y, x)

    return jax.jit(step)


def make_weights():
    return jnp.asarray(np.ones((4096, 4096)))


def run(x):
    weights = make_weights()  # call result, not a ctor literal: the
    fn = build_step(2.0, True)  # rule never guesses through calls
    return fn(weights, x)


def untraced_closure():
    table = np.arange(100)

    def host_side(i):
        return table[i]  # never jitted: a plain python closure

    return host_side
