"""C13 positive fixture — EDL501 leaks of the tiered KV cache's spill
lifecycle (serving/kv_pool.py discipline: spill -> revive | drop):

1. a block spilled to the host tier that an early-return path neither
   revives nor drops — host bytes pinned forever;
2. a spill whose exception path loses the entry;
3. a spill abandoned when the budget check bails out of the demotion.
"""


class ChainSpiller(object):
    def __init__(self, tier):
        self._tier = tier

    def demote(self, tier, bid, vid):
        tier.spill(bid, vid)
        if not self.indexable(vid):
            return None  # leak: the spilled entry is never settled

    def demote_checked(self, tier, bid, vid):
        tier.spill(bid, vid)
        rows = self.gather(bid)
        if rows is None:
            raise RuntimeError("gather failed")  # leak: no revive/drop
        tier.drop(vid)
        return rows

    def demote_budgeted(self, tier, bid, vid, budget):
        tier.spill(bid, vid)
        if self.bytes_used() > budget:
            return False  # leak: over budget, entry lost anyway
        tier.revive(vid)
        return True

    def indexable(self, vid):
        return vid < -1

    def gather(self, bid):
        return [bid]

    def bytes_used(self):
        return 0
