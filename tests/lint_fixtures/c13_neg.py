"""C13 negative fixture — the spill lifecycle settles on every path:
finally-guarded drop, revive-or-drop on every branch, and ownership
transfer (the spilled entry escapes to the host store the caller
owns)."""


class ChainSpiller(object):
    def __init__(self, tier):
        self._tier = tier
        self._host = {}

    def demote(self, tier, bid, vid):
        tier.spill(bid, vid)
        try:
            if not self.indexable(vid):
                return None
            return vid
        finally:
            tier.drop(vid)

    def demote_checked(self, tier, bid, vid):
        tier.spill(bid, vid)
        try:
            rows = self.gather(bid)
        except Exception:
            tier.drop(vid)
            raise
        if rows is None:
            tier.drop(vid)
            return None
        tier.revive(vid)
        return rows

    def demote_budgeted(self, tier, bid, vid, budget):
        tier.spill(bid, vid)
        if self.bytes_used() > budget:
            tier.drop(vid)
            return False
        tier.revive(vid)
        return True

    def demote_deferred(self, tier, bid, vid):
        tier.spill(bid, vid)
        self._host[vid] = tier  # ownership transferred to the store

    def indexable(self, vid):
        return vid < -1

    def gather(self, bid):
        return [bid]

    def bytes_used(self):
        return 0
