"""C19 negative fixture — the handoff transfer obligation settled on
every path: import_chain on the happy path, abort_transfer on the
not-ready branch and on the exception path, so EDL501 must stay silent.
The last method calls a POOL-level export_chain through a receiver
without the "disagg" hint spelling — plain data with no obligation
(tests and benches do this constantly), which the hint exists to keep
untracked."""


class HandoffDriver(object):
    def __init__(self, disagg):
        self._disagg = disagg

    def warm(self, disagg, prefill_rep, decode_rep, request, tid):
        payload = disagg.export_chain(prefill_rep, request, tid)
        if not self.ready(decode_rep):
            disagg.abort_transfer(prefill_rep, tid)
            return None
        disagg.import_chain(decode_rep, payload)
        return payload

    def warm_checked(self, disagg, prefill_rep, decode_rep, request,
                     tid):
        payload = disagg.export_chain(prefill_rep, request, tid)
        try:
            disagg.import_chain(decode_rep, payload)
        except Exception:
            disagg.abort_transfer(prefill_rep, tid)
            raise
        return payload

    def snapshot(self, pool, prompt):
        # pool-level export: returns block rows, owes nothing
        return pool.export_chain(prompt)

    def ready(self, rep):
        return rep is not None
