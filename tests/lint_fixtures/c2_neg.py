"""NEGATIVE fixture for EDL101/EDL102/EDL103: the sanctioned idioms —
jnp ops on tracers, branches on static config (closures, shapes,
static_argnames), host syncs OUTSIDE jit. Expected findings: none."""

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def build_decode(cfg):
    causal = cfg["causal"]

    def decode(x, length):
        if causal:  # closure config: static, fine
            x = jnp.tril(x)
        if x.shape[0] > 8:  # shapes are trace-static, fine
            x = x[:8]
        y = jnp.where(x > 0, x, 0.0)  # traced branch, the right way
        return y * length

    return jax.jit(decode)


@partial(jax.jit, static_argnames=("n_steps",))
def unrolled(x, n_steps):
    for _ in range(int(n_steps)):  # static arg: int() is fine
        x = x + 1.0
    return x


def host_side_driver(step_fn, state):
    # NOT a jit context: host syncs and timing are the point here
    t0 = time.time()
    state = step_fn(state)
    state.block_until_ready()
    loss = float(np.asarray(state).mean())
    print("step took", time.time() - t0, loss)
    return state
