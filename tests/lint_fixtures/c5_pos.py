"""EDL401 triggering fixture: telemetry counter/gauge-name typos."""


class Frontend(object):
    def __init__(self, telemetry):
        self.telemetry = telemetry
        self._telemetry = telemetry

    def admit(self):
        # typo'd counter: forks a new counter silently -> EDL401
        self.telemetry.count("admittd")

    def reject(self):
        self._telemetry.count("rejectd", 2)  # EDL401 (underscored attr)

    def depth(self):
        # typo'd gauge: forks a dead TB tag + Prometheus series -> EDL401
        self.telemetry.gauge("queue_dept", 3)


def module_level(router_telemetry):
    router_telemetry.count("breaker_tripz")  # EDL401 (bare receiver)
    router_telemetry.gauge("healthy_replica", 1)  # EDL401 (gauge typo)


def slow(telemetry):
    # typo'd slow cause: forks a labeled series no cause taxonomy
    # consumer will ever aggregate -> EDL401
    telemetry.count_slow_cause("queue_wiat")


def health(telemetry):
    # typo'd runtime-health counter (steady_recompiles): the anomaly
    # count would fork and serve-smoke's zero-recompile gate would
    # watch a dead series -> EDL401
    telemetry.count("steady_recompile")
    # typo'd runtime-health gauge (last_progress_age_ms): the
    # autoscaler's self-report signal would scrape a dead series
    # -> EDL401
    telemetry.gauge("last_progress_age", 120.0)
