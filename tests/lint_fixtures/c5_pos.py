"""EDL401 triggering fixture: telemetry counter-name typos."""


class Frontend(object):
    def __init__(self, telemetry):
        self.telemetry = telemetry
        self._telemetry = telemetry

    def admit(self):
        # typo'd counter: forks a new counter silently -> EDL401
        self.telemetry.count("admittd")

    def reject(self):
        self._telemetry.count("rejectd", 2)  # EDL401 (underscored attr)


def module_level(router_telemetry):
    router_telemetry.count("breaker_tripz")  # EDL401 (bare receiver)
