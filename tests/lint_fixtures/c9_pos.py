"""C9 positive fixture — EDL202/EDL203 deadline propagation.

A servicer entry that RECEIVES a budget (``request.deadline_ms``)
and then loses it, plus a router dispatch path that replaces its
explicit budget parameter:

* entry stub call with a static 120 s default while the remaining
  budget sits in scope (EDL203, "replaced");
* a helper CLASS the dispatch path flows through — outside EDL201's
  servicer/router syntactic surface — whose stub call drops the
  deadline entirely (EDL202) or pins a static one the budget can
  never reach (EDL203, "never threaded in").
"""


class BackendClient(object):
    def __init__(self, stub):
        self._stub = stub

    def call_backend(self, payload):
        # EDL202: dispatch-reachable helper drops the deadline
        return self._stub.generate(payload)

    def call_backend_static(self, payload):
        # EDL203: static timeout; the budget is never threaded in
        return self._stub.generate(payload, timeout=60.0)


class FrontendServicer(object):
    def __init__(self, stub):
        self._stub = stub
        self._client = BackendClient(stub)

    def generate(self, request, context=None):
        remaining = request.deadline_ms / 1000.0
        # EDL203: budget in scope, replaced by a static default
        first = self._stub.generate(request, timeout=120.0)
        second = self._client.call_backend(request.payload)
        third = self._client.call_backend_static(request.payload)
        return first or second or third or remaining


class EdgeRouter(object):
    def __init__(self, stub):
        self._stub = stub

    def dispatch(self, request, deadline_ms):
        # EDL203: the caller handed us a deadline; we wait 5 s anyway
        return self._stub.generate(request, timeout=5.0)
