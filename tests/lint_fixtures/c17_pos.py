"""POSITIVE fixture for EDL601 (sharding discipline): a constraint
pinned outside any jit context, a mesh-axis typo against the lexical
Mesh declaration, an axis name outside the canonical MeshAxis set,
and a donated jit call that drops the output sharding. Expected
findings: EDL601 x4."""

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def pin_after_the_fact(x, sharding):
    # outside a trace this is a silent no-op: nothing is pinned
    y = jax.lax.with_sharding_constraint(x, sharding)  # EDL601
    return y


def typo_against_mesh(devices):
    mesh = Mesh(np.asarray(devices), ("dp", "fsdp"))
    # "ddp" names no axis of the enclosing mesh: silent replication
    return NamedSharding(mesh, P("ddp"))  # EDL601


def typo_against_canon(batch_axes):
    # no lexical mesh here: judged against MeshAxis.ALL
    return P("tpx", None)  # EDL601


def donated_unsharded_update(step_fn, state_sharding, batch_sharding):
    return jax.jit(
        step_fn,
        donate_argnums=(0,),
        in_shardings=(state_sharding, batch_sharding),
        # no out_shardings: the donated state's placement is left to
        # inference — a replicated output un-does the memory win
    )  # EDL601
