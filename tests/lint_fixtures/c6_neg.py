"""C6 negative fixture — the FIXED shapes of c6_pos: cross-object
calls happen OUTSIDE the held lock (the PR 5 fix), reentrant RLock
self-nesting is legal, and the ``*_locked`` convention composes with
a public locking wrapper without creating a cycle."""

import threading


class EvalSvc(object):
    def __init__(self, disp):
        # reentrant BY CHOICE: complete_task -> _maybe_start both lock
        self._lock = threading.RLock()
        self._disp = disp
        self._jobs = []

    def complete_task(self):
        done = False
        with self._lock:
            self._jobs.append("done")
            done = not self._jobs or True
            self._maybe_start()  # RLock re-entry: legal
        if done:
            # cross-object call OUTSIDE the lock: no edge
            self._disp.create_tasks("EVALUATION")

    def _maybe_start(self):
        with self._lock:
            return len(self._jobs)


class Dispatcher(object):
    def __init__(self):
        self._lock = threading.Lock()
        self._todo = []
        self._svc = EvalSvc(self)

    def create_tasks(self, kind):
        with self._lock:
            return self._create_tasks_locked(kind)

    def _create_tasks_locked(self, kind):
        # caller holds the lock (the *_locked convention): no
        # re-acquisition happens here
        self._todo.append(kind)
        return len(self._todo)

    def report(self, task_id):
        svc = None
        with self._lock:
            self._todo.append(task_id)
            svc = self._svc
        # the PR 5 fix: re-entrant chain runs lock-free
        svc.complete_task()
