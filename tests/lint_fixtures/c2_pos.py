"""POSITIVE fixture for EDL101/EDL102/EDL103: host syncs, tracer
branches, and trace-time side effects inside jit contexts, in both the
decorator and the wrap idiom. Expected findings: EDL101 x4 (.item(),
float(), np.asarray, block_until_ready), EDL102 x2 (if, while),
EDL103 x2 (time.time, print)."""

import time
from functools import partial

import jax
import numpy as np


@jax.jit
def decorated_hazards(x):
    v = x.sum()
    host = v.item()  # EDL101
    if v > 0:  # EDL102
        v = v + 1.0
    print(v)  # EDL103
    return host


@partial(jax.jit, static_argnames=("n",))
def partial_decorated(x, n):
    t0 = time.time()  # EDL103
    y = float(x[0])  # EDL101 (x is traced; n is static)
    while y > 0:  # EDL102
        y = y - n
    return y + t0


def build_step():
    def step(state, tokens):
        arr = np.asarray(tokens)  # EDL101
        state.block_until_ready()  # EDL101
        return state, arr

    return jax.jit(step)
