"""C6 positive fixture — EDL003 lock-order deadlock cycles.

Two distinct deadlock shapes the rule must flag:

1. the PR 5 re-entry chain: Dispatcher.report holds the dispatcher's
   NON-reentrant lock while calling EvalSvc.complete_task, which calls
   back into Dispatcher.create_tasks — re-acquiring the held lock.
   (threading.Lock is not reentrant: this deadlocks the reporting
   thread against itself.)
2. a classic AB/BA ordering cycle between two sibling locks.
"""

import threading


class EvalSvc(object):
    def __init__(self, disp):
        self._lock = threading.RLock()
        self._disp = disp
        self._jobs = []

    def complete_task(self):
        with self._lock:
            self._jobs.append("done")
            # EvalSvc._lock -> Dispatcher._lock edge
            self._disp.create_tasks("EVALUATION")


class Dispatcher(object):
    def __init__(self):
        self._lock = threading.Lock()
        self._todo = []
        self._svc = EvalSvc(self)

    def create_tasks(self, kind):
        with self._lock:
            self._todo.append(kind)

    def report(self, task_id):
        with self._lock:
            self._todo.append(task_id)
            # Dispatcher._lock -> (EvalSvc._lock -> Dispatcher._lock):
            # the re-entry deadlock, reachable interprocedurally
            self._svc.complete_task()


class PairA(object):
    def __init__(self, pair_b):
        self._a_lock = threading.Lock()
        self._pair_b = pair_b  # binds by the camel-case convention
        self._items = []

    def push(self, x):
        with self._a_lock:
            self._items.append(x)
            self._pair_b.push(x)  # A held, then B acquired


class PairB(object):
    def __init__(self):
        self._b_lock = threading.Lock()
        self._items = []
        self._pair_a = None

    def attach(self, pair_a):
        self._pair_a = pair_a

    def push(self, x):
        with self._b_lock:
            self._items.append(x)

    def drain(self):
        with self._b_lock:
            # B held, then A acquired: closes the AB/BA cycle
            self._pair_a.push("flush")
