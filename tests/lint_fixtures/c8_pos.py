"""C8 positive fixture — EDL501 must-release leaks.

1. the PR 4 circuit-breaker probe leak, verbatim shape: the HALF_OPEN
   probe slot is acquired, and the NON-transient failure branch
   re-raises without settling — the replica is evicted forever;
2. a span handle that escapes on no path and is never finished when
   the early-return path triggers;
3. a file opened outside ``with`` that a handler branch abandons.
"""


class ProbeDispatcher(object):
    def __init__(self, clock):
        self._clock = clock

    def _transient(self, exc):
        return isinstance(exc, TimeoutError)

    def probe_dispatch(self, rep, req):
        now = self._clock()
        if not rep.breaker.acquire(now):
            return None
        try:
            return rep.stub.generate(req, timeout=1.0)
        except Exception as e:
            if self._transient(e):
                rep.breaker.record_failure(now)
                raise
            raise  # leak: probe slot never released on this branch


class SpanLeaker(object):
    def __init__(self, recorder):
        self._recorder = recorder

    def trace_step(self, item):
        span = self._recorder.start_span("step", item=item)
        if not item:
            return 0  # leak: early return skips finish
        span.event("ran")
        span.finish("ok")
        return 1


def read_header(path):
    f = open(path)
    try:
        return f.read(16)
    except OSError:
        return b""  # leak: handler returns without close
    finally:
        pass
