"""Block-paged KV pool unit tests (tier-1).

The host-side block allocator (serving/kv_pool.py): alloc/extend/free
reuse order, reservation-backed extends, fragmentation invariants under
random request lengths, clean out-of-blocks signalling; plus the
paged decode-attention op (ops/attention.py) against a dense oracle.
Engine/server-level paged behavior (parity at concurrency, admission
backpressure, reclamation on evict) lives in tests/test_serving_e2e.py
on the drills shard."""

import numpy as np
import pytest

from elasticdl_tpu.serving.kv_pool import (
    BlockAllocator,
    OutOfBlocks,
    blocks_for,
)


def test_blocks_for():
    assert blocks_for(0, 4) == 0
    assert blocks_for(1, 4) == 1
    assert blocks_for(4, 4) == 1
    assert blocks_for(5, 4) == 2
    assert blocks_for(17, 4) == 5


def test_alloc_free_reuse_order_is_lifo():
    a = BlockAllocator(num_blocks=8, block_size=4)
    t0 = a.alloc("r0", tokens=8)          # 2 blocks
    t1 = a.alloc("r1", tokens=4)          # 1 block
    assert len(t0) == 2 and len(t1) == 1
    assert len(set(t0) | set(t1)) == 3    # disjoint
    assert a.num_free() == 5
    # free r0: its blocks come back and are reused FIRST, last-out
    # first-in (warm reuse)
    assert a.free("r0") == 2
    t2 = a.alloc("r2", tokens=8)
    assert t2 == list(reversed(t0))
    # double free is a harmless no-op
    assert a.free("r0") == 0


def test_alloc_reserves_full_commitment():
    a = BlockAllocator(num_blocks=4, block_size=4)
    # 1 block materialized now, 3 promised in total
    a.alloc("r0", tokens=4, commit_tokens=12)
    assert a.num_free() == 3
    assert a.available() == 1  # 3 free minus 2 reserved
    assert a.can_fit(4) and not a.can_fit(8)
    with pytest.raises(OutOfBlocks):
        a.alloc("r1", tokens=8)
    # the reservation makes the seated request's growth infallible
    a.extend("r0", total_tokens=8)
    a.extend("r0", total_tokens=12)
    assert len(a.table("r0")) == 3
    assert a.available() == 1  # reservation fully drawn down
    # freeing returns blocks AND releases nothing extra (none left)
    assert a.free("r0") == 3
    assert a.num_free() == 4 and a.available() == 4


def test_free_releases_undrawn_reservation():
    a = BlockAllocator(num_blocks=4, block_size=4)
    a.alloc("r0", tokens=4, commit_tokens=16)  # commit all 4
    assert a.available() == 0
    a.free("r0")  # only 1 block was materialized
    assert a.num_free() == 4 and a.available() == 4


def test_extend_beyond_commitment_competes_with_admission():
    a = BlockAllocator(num_blocks=2, block_size=4)
    a.alloc("r0", tokens=4, commit_tokens=4)
    a.alloc("r1", tokens=4, commit_tokens=4)
    with pytest.raises(OutOfBlocks):
        a.extend("r0", total_tokens=8)  # past its commitment, pool dry
    assert len(a.table("r0")) == 1  # untouched by the failed extend


def test_alloc_failure_leaves_state_clean():
    a = BlockAllocator(num_blocks=2, block_size=4)
    a.alloc("r0", tokens=4)
    free_before = a.num_free()
    with pytest.raises(OutOfBlocks):
        a.alloc("r1", tokens=4, commit_tokens=12)
    assert a.num_free() == free_before
    assert a.table("r1") == []
    a.alloc("r1", tokens=4)  # a fitting request still seats


def test_fragmentation_under_random_request_lengths():
    """Random admit/complete churn with mixed lengths: the allocator's
    invariants (conservation, disjoint ownership, non-negative
    availability) must hold at every step, and a drained pool must be
    whole again."""
    rs = np.random.RandomState(7)
    a = BlockAllocator(num_blocks=32, block_size=4)
    live = {}
    for i in range(300):
        if live and (rs.rand() < 0.4 or not a.can_fit(24)):
            slot = rs.choice(sorted(live))
            a.free(slot)
            del live[slot]
        else:
            tokens = int(rs.randint(1, 25))
            total = tokens + int(rs.randint(0, 25))
            slot = "r%d" % i
            if a.can_fit(total):
                a.alloc(slot, tokens, commit_tokens=total)
                live[slot] = total
                # grow a random live request inside its commitment
                a.extend(slot, min(total, tokens + int(rs.randint(0, 8))))
        # ---- invariants
        used = sum(len(a.table(s)) for s in live)
        assert used == a.blocks_in_use()
        assert used + a.num_free() == 32
        assert a.available() >= 0
        owned = [b for s in live for b in a.table(s)]
        assert len(owned) == len(set(owned))  # no block owned twice
    for slot in list(live):
        a.free(slot)
    assert a.num_free() == 32 and a.available() == 32


def test_paged_decode_attention_matches_dense_oracle():
    """The op must equal plain softmax attention over the logically
    contiguous cache (pool rows gathered in table order + the current
    token), for MHA and GQA, with and without a sliding window."""
    import jax.numpy as jnp

    from elasticdl_tpu.ops.attention import paged_decode_attention

    rs = np.random.RandomState(0)
    bs, nb = 4, 10
    for hkv, h in ((2, 2), (1, 4)):
        for window in (None, 5):
            d = 8
            b = 3
            k_pool = rs.randn(nb, bs, hkv, d).astype(np.float32)
            v_pool = rs.randn(nb, bs, hkv, d).astype(np.float32)
            q = rs.randn(b, h, d).astype(np.float32)
            k_cur = rs.randn(b, hkv, d).astype(np.float32)
            v_cur = rs.randn(b, hkv, d).astype(np.float32)
            # each row: different length + scattered table, -1 padded
            lengths = np.asarray([0, 5, 11], np.int32)
            table = np.full((b, 3), -1, np.int32)
            table[1, :2] = [7, 2]
            table[2, :3] = [4, 9, 1]
            out = np.asarray(paged_decode_attention(
                jnp.asarray(q), jnp.asarray(k_cur), jnp.asarray(v_cur),
                jnp.asarray(k_pool), jnp.asarray(v_pool),
                jnp.asarray(table), jnp.asarray(lengths),
                window=window,
            ))
            group = h // hkv
            for i in range(b):
                ln = int(lengths[i])
                rows_k = np.concatenate(
                    [k_pool[bid] for bid in table[i] if bid >= 0]
                    or [np.zeros((0, hkv, d), np.float32)]
                )[:ln]
                rows_v = np.concatenate(
                    [v_pool[bid] for bid in table[i] if bid >= 0]
                    or [np.zeros((0, hkv, d), np.float32)]
                )[:ln]
                keys = np.concatenate([rows_k, k_cur[i][None]])
                vals = np.concatenate([rows_v, v_cur[i][None]])
                if window is not None:
                    # visible: k_pos in (ln - window, ln]
                    k_pos = np.arange(ln + 1)
                    keep = k_pos > ln - window
                    keys, vals = keys[keep], vals[keep]
                for j in range(h):
                    kvh = j // group
                    s = keys[:, kvh] @ q[i, j] * d ** -0.5
                    w = np.exp(s - s.max())
                    w = w / w.sum()
                    ref = w @ vals[:, kvh]
                    np.testing.assert_allclose(
                        out[i, j], ref, rtol=2e-5, atol=2e-5,
                        err_msg="row %d head %d hkv=%d window=%r"
                                % (i, j, hkv, window),
                    )
