"""Block-paged KV pool unit tests (tier-1).

The host-side block allocator (serving/kv_pool.py): alloc/extend/free
reuse order, reservation-backed extends, fragmentation invariants under
random request lengths, clean out-of-blocks signalling; the
prefix-sharing layer (refcounted chains, the content-addressed index,
reclaimable-LRU revival/eviction, copy-on-write under reservation
pressure); plus the paged decode-attention op (ops/attention.py)
against a dense oracle for both the single-token step and the
verify-k query tile. Engine/server-level paged behavior (parity at
concurrency, admission backpressure, reclamation on evict) lives in
tests/test_serving_e2e.py on the drills shard."""

import numpy as np
import pytest

from elasticdl_tpu.serving.kv_pool import (
    BlockAllocator,
    OutOfBlocks,
    blocks_for,
)


def test_blocks_for():
    assert blocks_for(0, 4) == 0
    assert blocks_for(1, 4) == 1
    assert blocks_for(4, 4) == 1
    assert blocks_for(5, 4) == 2
    assert blocks_for(17, 4) == 5


def test_alloc_free_reuse_order_is_lifo():
    a = BlockAllocator(num_blocks=8, block_size=4)
    assert a.alloc("r0", tokens=8) == 0   # 2 blocks, nothing shared
    assert a.alloc("r1", tokens=4) == 0   # 1 block
    t0, t1 = a.table("r0"), a.table("r1")
    assert len(t0) == 2 and len(t1) == 1
    assert len(set(t0) | set(t1)) == 3    # disjoint
    assert a.num_free() == 5
    # free r0: its blocks come back and are reused FIRST, last-out
    # first-in (warm reuse)
    assert a.free("r0") == 2
    a.alloc("r2", tokens=8)
    assert a.table("r2") == list(reversed(t0))
    # double free is a harmless no-op
    assert a.free("r0") == 0


def test_alloc_reserves_full_commitment():
    a = BlockAllocator(num_blocks=4, block_size=4)
    # 1 block materialized now, 3 promised in total
    a.alloc("r0", tokens=4, commit_tokens=12)
    assert a.num_free() == 3
    assert a.available() == 1  # 3 free minus 2 reserved
    assert a.can_fit(4) and not a.can_fit(8)
    with pytest.raises(OutOfBlocks):
        a.alloc("r1", tokens=8)
    # the reservation makes the seated request's growth infallible
    a.extend("r0", total_tokens=8)
    a.extend("r0", total_tokens=12)
    assert len(a.table("r0")) == 3
    assert a.available() == 1  # reservation fully drawn down
    # freeing returns blocks AND releases nothing extra (none left)
    assert a.free("r0") == 3
    assert a.num_free() == 4 and a.available() == 4


def test_free_releases_undrawn_reservation():
    a = BlockAllocator(num_blocks=4, block_size=4)
    a.alloc("r0", tokens=4, commit_tokens=16)  # commit all 4
    assert a.available() == 0
    a.free("r0")  # only 1 block was materialized
    assert a.num_free() == 4 and a.available() == 4


def test_extend_beyond_commitment_competes_with_admission():
    a = BlockAllocator(num_blocks=2, block_size=4)
    a.alloc("r0", tokens=4, commit_tokens=4)
    a.alloc("r1", tokens=4, commit_tokens=4)
    with pytest.raises(OutOfBlocks):
        a.extend("r0", total_tokens=8)  # past its commitment, pool dry
    assert len(a.table("r0")) == 1  # untouched by the failed extend


def test_alloc_failure_leaves_state_clean():
    a = BlockAllocator(num_blocks=2, block_size=4)
    a.alloc("r0", tokens=4)
    free_before = a.num_free()
    with pytest.raises(OutOfBlocks):
        a.alloc("r1", tokens=4, commit_tokens=12)
    assert a.num_free() == free_before
    assert a.table("r1") == []
    a.alloc("r1", tokens=4)  # a fitting request still seats


def test_fragmentation_under_random_request_lengths():
    """Random admit/complete churn with mixed lengths: the allocator's
    invariants (conservation, disjoint ownership, non-negative
    availability) must hold at every step, and a drained pool must be
    whole again."""
    rs = np.random.RandomState(7)
    a = BlockAllocator(num_blocks=32, block_size=4)
    live = {}
    for i in range(300):
        if live and (rs.rand() < 0.4 or not a.can_fit(24)):
            slot = rs.choice(sorted(live))
            a.free(slot)
            del live[slot]
        else:
            tokens = int(rs.randint(1, 25))
            total = tokens + int(rs.randint(0, 25))
            slot = "r%d" % i
            if a.can_fit(total):
                a.alloc(slot, tokens, commit_tokens=total)
                live[slot] = total
                # grow a random live request inside its commitment
                a.extend(slot, min(total, tokens + int(rs.randint(0, 8))))
        # ---- invariants
        used = sum(len(a.table(s)) for s in live)
        assert used == a.blocks_in_use()
        assert used + a.num_free() == 32
        assert a.available() >= 0
        owned = [b for s in live for b in a.table(s)]
        assert len(owned) == len(set(owned))  # no block owned twice
    for slot in list(live):
        a.free(slot)
    assert a.num_free() == 32 and a.available() == 32


def test_paged_decode_attention_matches_dense_oracle():
    """The op must equal plain softmax attention over the logically
    contiguous cache (pool rows gathered in table order + the current
    token), for MHA and GQA, with and without a sliding window."""
    import jax.numpy as jnp

    from elasticdl_tpu.ops.attention import paged_decode_attention

    rs = np.random.RandomState(0)
    bs, nb = 4, 10
    for hkv, h in ((2, 2), (1, 4)):
        for window in (None, 5):
            d = 8
            b = 3
            k_pool = rs.randn(nb, bs, hkv, d).astype(np.float32)
            v_pool = rs.randn(nb, bs, hkv, d).astype(np.float32)
            q = rs.randn(b, h, d).astype(np.float32)
            k_cur = rs.randn(b, hkv, d).astype(np.float32)
            v_cur = rs.randn(b, hkv, d).astype(np.float32)
            # each row: different length + scattered table, -1 padded
            lengths = np.asarray([0, 5, 11], np.int32)
            table = np.full((b, 3), -1, np.int32)
            table[1, :2] = [7, 2]
            table[2, :3] = [4, 9, 1]
            out = np.asarray(paged_decode_attention(
                jnp.asarray(q), jnp.asarray(k_cur), jnp.asarray(v_cur),
                jnp.asarray(k_pool), jnp.asarray(v_pool),
                jnp.asarray(table), jnp.asarray(lengths),
                window=window,
            ))
            group = h // hkv
            for i in range(b):
                ln = int(lengths[i])
                rows_k = np.concatenate(
                    [k_pool[bid] for bid in table[i] if bid >= 0]
                    or [np.zeros((0, hkv, d), np.float32)]
                )[:ln]
                rows_v = np.concatenate(
                    [v_pool[bid] for bid in table[i] if bid >= 0]
                    or [np.zeros((0, hkv, d), np.float32)]
                )[:ln]
                keys = np.concatenate([rows_k, k_cur[i][None]])
                vals = np.concatenate([rows_v, v_cur[i][None]])
                if window is not None:
                    # visible: k_pos in (ln - window, ln]
                    k_pos = np.arange(ln + 1)
                    keep = k_pos > ln - window
                    keys, vals = keys[keep], vals[keep]
                for j in range(h):
                    kvh = j // group
                    s = keys[:, kvh] @ q[i, j] * d ** -0.5
                    w = np.exp(s - s.max())
                    w = w / w.sum()
                    ref = w @ vals[:, kvh]
                    np.testing.assert_allclose(
                        out[i, j], ref, rtol=2e-5, atol=2e-5,
                        err_msg="row %d head %d hkv=%d window=%r"
                                % (i, j, hkv, window),
                    )


def test_paged_decode_attention_tile_matches_dense_oracle():
    """The verify-k query tile (speculative verify / shared-prefix
    suffix prefill): row j attends every pool row < length plus tile
    keys j' <= j, for MHA and GQA, with and without a window."""
    import jax.numpy as jnp

    from elasticdl_tpu.ops.attention import paged_decode_attention

    rs = np.random.RandomState(1)
    bs, nb, d, b, t = 4, 10, 8, 3, 3
    for hkv, h in ((2, 2), (1, 4)):
        for window in (None, 5):
            k_pool = rs.randn(nb, bs, hkv, d).astype(np.float32)
            v_pool = rs.randn(nb, bs, hkv, d).astype(np.float32)
            q = rs.randn(b, h, t, d).astype(np.float32)
            k_cur = rs.randn(b, hkv, t, d).astype(np.float32)
            v_cur = rs.randn(b, hkv, t, d).astype(np.float32)
            lengths = np.asarray([0, 5, 11], np.int32)
            table = np.full((b, 3), -1, np.int32)
            table[1, :2] = [7, 2]
            table[2, :3] = [4, 9, 1]
            out = np.asarray(paged_decode_attention(
                jnp.asarray(q), jnp.asarray(k_cur), jnp.asarray(v_cur),
                jnp.asarray(k_pool), jnp.asarray(v_pool),
                jnp.asarray(table), jnp.asarray(lengths),
                window=window,
            ))
            assert out.shape == (b, h, t, d)
            group = h // hkv
            for i in range(b):
                ln = int(lengths[i])
                rows_k = np.concatenate(
                    [k_pool[bid] for bid in table[i] if bid >= 0]
                    or [np.zeros((0, hkv, d), np.float32)]
                )[:ln]
                rows_v = np.concatenate(
                    [v_pool[bid] for bid in table[i] if bid >= 0]
                    or [np.zeros((0, hkv, d), np.float32)]
                )[:ln]
                for jq in range(t):
                    keys = np.concatenate(
                        [rows_k, k_cur[i].transpose(1, 0, 2)[:jq + 1]]
                    )
                    vals = np.concatenate(
                        [rows_v, v_cur[i].transpose(1, 0, 2)[:jq + 1]]
                    )
                    k_pos = np.arange(ln + jq + 1)
                    keep = np.ones(len(k_pos), bool)
                    if window is not None:
                        keep = k_pos > ln + jq - window
                    keys, vals = keys[keep], vals[keep]
                    for j in range(h):
                        kvh = j // group
                        s = keys[:, kvh] @ q[i, j, jq] * d ** -0.5
                        w = np.exp(s - s.max())
                        w = w / w.sum()
                        ref = w @ vals[:, kvh]
                        np.testing.assert_allclose(
                            out[i, j, jq], ref, rtol=2e-5, atol=2e-5,
                            err_msg="row %d head %d tile %d hkv=%d "
                                    "window=%r" % (i, j, jq, hkv,
                                                   window),
                        )


# --------------------------------------------------- int8 arenas


def _np_quantize_rows(rows):
    """Numpy twin of the model's `_kv_quantize_rows` (symmetric
    per-row int8, f32 scales, zero rows keep scale 1) — the oracle the
    arena round-trip and attention tests quantize with."""
    amax = np.abs(rows).max(-1, keepdims=True)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q8 = np.clip(np.round(rows / scale), -127, 127).astype(np.int8)
    return q8, scale


def test_np_quantizer_matches_model_quantizer():
    import jax.numpy as jnp

    from model_zoo.transformer_lm.transformer_lm import (
        _kv_quantize_rows,
    )

    rows = np.random.RandomState(2).randn(1, 2, 6, 8).astype(np.float32)
    rows[0, 1, 3] = 0.0  # a zero row must keep scale 1
    q8, sc = _np_quantize_rows(rows)
    mq8, msc = _kv_quantize_rows(jnp.asarray(rows))
    np.testing.assert_array_equal(q8, np.asarray(mq8))
    np.testing.assert_allclose(sc, np.asarray(msc), rtol=1e-6)


def test_int8_prompt_block_write_round_trips_quantizer():
    """build_pools maps int8 rows AND their f32 scale leaves through
    the same kv_row_leaf convention, and write_prompt_block inserts a
    quantized cache block bit-exactly (quantize-at-insertion: the
    arena holds exactly what the quantizer produced)."""
    import jax.numpy as jnp

    from elasticdl_tpu.serving.kv_pool import (
        build_pools,
        write_prompt_block,
    )

    rs = np.random.RandomState(3)
    hkv, d, cache_len, bs, nb = 2, 8, 16, 4, 6
    rows = rs.randn(1, hkv, cache_len, d).astype(np.float32)
    q8, sc = _np_quantize_rows(rows)
    kv = {
        "k": jnp.asarray(q8), "k_scale": jnp.asarray(sc),
        "pos": jnp.zeros((), jnp.int32),
    }
    pools = build_pools(kv, cache_len, nb, bs)
    assert pools["k"].dtype == jnp.int8
    assert pools["k"].shape == (nb, bs, hkv, d)
    assert pools["k_scale"].dtype == jnp.float32
    assert pools["k_scale"].shape == (nb, bs, hkv, 1)
    assert pools["pos"].shape == ()  # non-row leaf stays a placeholder
    pools = write_prompt_block(
        pools, kv, jnp.asarray(1, jnp.int32), jnp.asarray(4, jnp.int32),
        block_size=bs,
    )
    np.testing.assert_array_equal(
        np.asarray(pools["k"][4]),
        q8[0, :, bs:2 * bs, :].transpose(1, 0, 2),
    )
    np.testing.assert_array_equal(
        np.asarray(pools["k_scale"][4]),
        sc[0, :, bs:2 * bs, :].transpose(1, 0, 2),
    )
    # untouched blocks stay zero
    assert not np.asarray(pools["k"][0]).any()


def test_int8_scatter_rows_round_trips_and_drops():
    """The per-step decode scatter writes int8 rows + scale rows in
    lockstep; out-of-bounds lanes drop from BOTH leaves."""
    import jax.numpy as jnp

    from elasticdl_tpu.serving.kv_pool import scatter_rows

    rs = np.random.RandomState(4)
    hkv, d, bs, nb, s = 2, 8, 4, 6, 3
    pools = {
        "k": jnp.zeros((nb, bs, hkv, d), jnp.int8),
        "k_scale": jnp.zeros((nb, bs, hkv, 1), jnp.float32),
        "pos": jnp.zeros((), jnp.int32),
    }
    raw = rs.randn(s, hkv, d).astype(np.float32)
    q8, sc = _np_quantize_rows(raw)
    rows = {"k": jnp.asarray(q8), "k_scale": jnp.asarray(sc)}
    bids = jnp.asarray([2, nb, 5], jnp.int32)  # lane 1 = drop sentinel
    offs = jnp.asarray([1, 0, 3], jnp.int32)
    out = scatter_rows(pools, rows, bids, offs)
    np.testing.assert_array_equal(np.asarray(out["k"][2, 1]), q8[0])
    np.testing.assert_array_equal(
        np.asarray(out["k_scale"][2, 1]), sc[0]
    )
    np.testing.assert_array_equal(np.asarray(out["k"][5, 3]), q8[2])
    np.testing.assert_array_equal(
        np.asarray(out["k_scale"][5, 3]), sc[2]
    )
    # the dropped lane touched nothing: everything else is still zero
    mask = np.ones((nb, bs), bool)
    mask[2, 1] = mask[5, 3] = False
    assert not np.asarray(out["k"])[mask].any()
    assert not np.asarray(out["k_scale"])[mask].any()


def test_copy_block_carries_scale_leaves():
    """Device-side CoW must duplicate the scale arenas alongside the
    int8 rows — a copied block that kept stale scales would silently
    dequantize to wrong values."""
    import jax.numpy as jnp

    from elasticdl_tpu.serving.kv_pool import copy_block

    rs = np.random.RandomState(5)
    nb, bs, hkv, d = 6, 4, 2, 8
    pools = {
        "k": jnp.asarray(
            rs.randint(-127, 128, size=(nb, bs, hkv, d)), jnp.int8
        ),
        "k_scale": jnp.asarray(
            rs.rand(nb, bs, hkv, 1).astype(np.float32)
        ),
        "pos": jnp.zeros((), jnp.int32),
    }
    out = copy_block(pools, 1, 4)
    np.testing.assert_array_equal(
        np.asarray(out["k"][4]), np.asarray(pools["k"][1])
    )
    np.testing.assert_array_equal(
        np.asarray(out["k_scale"][4]), np.asarray(pools["k_scale"][1])
    )
    # source untouched
    np.testing.assert_array_equal(
        np.asarray(out["k"][1]), np.asarray(pools["k"][1])
    )


def test_paged_int8_attention_matches_dense_deferred_oracle():
    """The streaming int8 scan vs the dense DEFERRED-dequantize oracle
    (same quantizer, so the comparison carries no quantization error —
    float tolerance only): s = (q·k8)·ks, softmax, out = (w·vs)@v8,
    for the t=1 legacy shape and the verify-k tile, MHA and GQA, with
    and without a sliding window."""
    import jax.numpy as jnp

    from elasticdl_tpu.ops.attention import paged_decode_attention

    rs = np.random.RandomState(6)
    bs, nb, d, b = 4, 10, 8, 3
    for t in (1, 3):
        for hkv, h in ((2, 2), (1, 4)):
            for window in (None, 5):
                kf = rs.randn(nb, bs, hkv, d).astype(np.float32)
                vf = rs.randn(nb, bs, hkv, d).astype(np.float32)
                k_pool, ks_pool = _np_quantize_rows(kf)
                v_pool, vs_pool = _np_quantize_rows(vf)
                q = rs.randn(b, h, t, d).astype(np.float32)
                kc_f = rs.randn(b, hkv, t, d).astype(np.float32)
                vc_f = rs.randn(b, hkv, t, d).astype(np.float32)
                k_cur, ks_cur = _np_quantize_rows(kc_f)
                v_cur, vs_cur = _np_quantize_rows(vc_f)
                lengths = np.asarray([0, 5, 11], np.int32)
                table = np.full((b, 3), -1, np.int32)
                table[1, :2] = [7, 2]
                table[2, :3] = [4, 9, 1]
                args = (
                    jnp.asarray(q), jnp.asarray(k_cur),
                    jnp.asarray(v_cur), jnp.asarray(k_pool),
                    jnp.asarray(v_pool), jnp.asarray(table),
                    jnp.asarray(lengths),
                )
                kwargs = dict(
                    window=window,
                    k_scale_pool=jnp.asarray(ks_pool),
                    v_scale_pool=jnp.asarray(vs_pool),
                    k_cur_scale=jnp.asarray(ks_cur),
                    v_cur_scale=jnp.asarray(vs_cur),
                )
                if t == 1:  # exercise the squeezed legacy shape
                    args = (
                        jnp.asarray(q[:, :, 0]),
                        jnp.asarray(k_cur[:, :, 0]),
                        jnp.asarray(v_cur[:, :, 0]),
                    ) + args[3:]
                    kwargs["k_cur_scale"] = jnp.asarray(ks_cur[:, :, 0])
                    kwargs["v_cur_scale"] = jnp.asarray(vs_cur[:, :, 0])
                out = np.asarray(
                    paged_decode_attention(*args, **kwargs)
                )
                if t == 1:
                    out = out[:, :, None, :]
                group = h // hkv
                for i in range(b):
                    ln = int(lengths[i])
                    zero = np.zeros((0, hkv, d), np.float32)
                    pk = np.concatenate(
                        [k_pool[bid].astype(np.float32)
                         * ks_pool[bid]
                         for bid in table[i] if bid >= 0] or [zero]
                    )[:ln]
                    pv8 = np.concatenate(
                        [v_pool[bid].astype(np.float32)
                         for bid in table[i] if bid >= 0] or [zero]
                    )[:ln]
                    pvs = np.concatenate(
                        [np.broadcast_to(vs_pool[bid],
                                         (bs, hkv, 1))
                         for bid in table[i] if bid >= 0]
                        or [np.zeros((0, hkv, 1), np.float32)]
                    )[:ln]
                    for jq in range(t):
                        # deferred oracle: keys pre-scaled by ks; the
                        # weights (not the values) carry vs
                        ck = (k_cur[i].astype(np.float32)
                              * ks_cur[i]).transpose(1, 0, 2)[:jq + 1]
                        keys = np.concatenate([pk, ck])
                        v8 = np.concatenate(
                            [pv8,
                             v_cur[i].astype(np.float32)
                             .transpose(1, 0, 2)[:jq + 1]]
                        )
                        vs = np.concatenate(
                            [pvs,
                             vs_cur[i].transpose(1, 0, 2)[:jq + 1]]
                        )
                        k_pos = np.arange(ln + jq + 1)
                        keep = np.ones(len(k_pos), bool)
                        if window is not None:
                            keep = k_pos > ln + jq - window
                        keys, v8, vs = keys[keep], v8[keep], vs[keep]
                        for j in range(h):
                            kvh = j // group
                            s = keys[:, kvh] @ q[i, j, jq] * d ** -0.5
                            w = np.exp(s - s.max())
                            w = w / w.sum()
                            ref = (w * vs[:, kvh, 0]) @ v8[:, kvh]
                            np.testing.assert_allclose(
                                out[i, j, jq], ref,
                                rtol=5e-5, atol=5e-5,
                                err_msg="row %d head %d tile %d t=%d "
                                        "hkv=%d window=%r"
                                        % (i, j, jq, t, hkv, window),
                            )


def test_paged_int8_attention_requires_all_scales():
    import jax.numpy as jnp
    import pytest as _pytest

    from elasticdl_tpu.ops.attention import paged_decode_attention

    z8 = jnp.zeros((2, 4, 1, 8), jnp.int8)
    zf = jnp.zeros((2, 4, 1, 1), jnp.float32)
    with _pytest.raises(ValueError, match="scale operands"):
        paged_decode_attention(
            jnp.zeros((1, 1, 8)), jnp.zeros((1, 1, 8), jnp.int8),
            jnp.zeros((1, 1, 8), jnp.int8), z8, z8,
            jnp.zeros((1, 2), jnp.int32), jnp.zeros((1,), jnp.int32),
            k_scale_pool=zf,  # v-side scales missing
        )


# ------------------------------------------- prefix sharing + CoW


def _shared(num_blocks=16, block_size=4):
    return BlockAllocator(num_blocks=num_blocks, block_size=block_size,
                          share_prefix=True)


def test_prefix_match_seats_by_incref():
    """An identical prompt seats on the resident chain: refcounts
    bump, no fresh blocks are drawn for the shared prefix, and the
    admission planner (can_seat) agrees with the seat."""
    a = _shared()
    prompt = list(range(10))  # 2 full blocks + a partial tail
    a.alloc("r0", tokens=10, commit_tokens=14, prompt=prompt)
    a.register_prefix("r0", prompt)
    free_before = a.num_free()
    chain, needed = a.plan(prompt, 10, 14)
    assert len(chain) == 2 and needed == 2  # 1 private + 1 growth
    assert a.can_seat(prompt, 10, 14)
    shared = a.alloc("r1", tokens=10, commit_tokens=14, prompt=prompt)
    assert shared == 8
    assert a.num_free() == free_before - 1  # only the private tail
    assert a.table("r1")[:2] == a.table("r0")[:2]
    assert a.table("r1")[2] != a.table("r0")[2]
    assert a.shared_blocks() == 2
    assert a.prefix_hits == 1 and a.prefix_hit_tokens == 8


def test_shared_chain_freed_only_at_refcount_zero():
    """free() decrefs; the chain's blocks leave the live set only when
    the LAST owner releases them — and then to the reclaimable cache,
    not the free list (they are still indexed)."""
    a = _shared()
    prompt = list(range(8))
    a.alloc("r0", tokens=8, prompt=prompt)
    a.register_prefix("r0", prompt)
    a.alloc("r1", tokens=8, prompt=prompt)
    chain = a.table("r0")
    assert a.table("r1") == chain  # fully shared (seat recomputes the
    assert a.shared_blocks() == 2  # tail row via the CoW-credit path)
    a.free("r0")
    # r1 still owns the chain: nothing freed, nothing cached
    assert a.blocks_in_use() == 2 and a.num_cached() == 0
    a.free("r1")
    assert a.blocks_in_use() == 0
    assert a.num_cached() == 2  # reclaimable, revivable by a match
    # a third request revives the chain at zero cost
    free_before = a.num_free()
    assert a.alloc("r2", tokens=8, prompt=prompt) == 8
    assert a.num_cached() == 0 and a.num_free() == free_before


def test_cow_under_reservation_pressure():
    """A full-prompt match reserves ONE CoW credit at seat; the fault
    draws it even when the pool is otherwise fully promised — and an
    unplanned CoW with a dry pool raises cleanly."""
    a = _shared(num_blocks=4, block_size=4)
    prompt = list(range(8))
    a.alloc("r0", tokens=8, prompt=prompt)
    a.register_prefix("r0", prompt)
    # full-prompt match: 2 shared + 1 CoW credit reserved
    chain, needed = a.plan(prompt, 8, 8)
    assert len(chain) == 2 and needed == 1
    a.alloc("r1", tokens=8, prompt=prompt)
    # pool: 2 live shared + 2 free, 1 of them reserved for r1's CoW
    assert a.available() == 1
    # a competing alloc may take only the unreserved remainder
    with pytest.raises(OutOfBlocks):
        a.alloc("r2", tokens=8)
    a.alloc("r2", tokens=4)
    assert a.available() == 0
    # the planned CoW still succeeds: it draws r1's credit
    old, new = a.cow("r1", 1)
    assert old == a.table("r0")[1] and a.table("r1")[1] == new
    assert a.table("r0")[1] == old  # r0 keeps the original
    # a SECOND (unplanned) CoW on the same slot has no credit and no
    # free block -> clean OutOfBlocks, nothing taken
    a.alloc("rX", tokens=0)  # no-op slot; keeps accounting honest
    with pytest.raises(OutOfBlocks):
        a.cow("r1", 0)
    assert a.table("r1")[0] == a.table("r0")[0]


def test_seat_on_reclaimable_chain_charges_revived_blocks():
    """Admission must charge the reclaimable chain blocks a seat
    revives: incref pops them out of the cache available() counts, so
    an uncharged revival lets _reserved exceed free + cached and a
    reservation-backed extend strands MID-DECODE. Repro from review:
    4-block pool, a 12-token prompt cached whole, then the same prompt
    with a commitment of 5 blocks — it must be refused at admission,
    not admitted and killed at its first extend."""
    a = _shared(num_blocks=4, block_size=4)
    prompt = list(range(12))  # 3 full blocks
    a.alloc("e", tokens=12, commit_tokens=13, prompt=prompt)
    a.register_prefix("e", prompt)
    a.free("e")
    assert a.num_cached() == 3 and a.num_free() == 1
    # commit 17 tokens = 5 blocks > pool; the shared seat would revive
    # 3 cached blocks (charged) + 2 growth = 5 > 4 (no CoW charge: the
    # revived tail is sole-owned, its re-write lands in place)
    chain, needed = a.plan(prompt, 12, 17)
    assert len(chain) == 3 and needed == 5
    assert not a.can_seat(prompt, 12, 17)
    with pytest.raises(OutOfBlocks):
        a.alloc("b", tokens=12, commit_tokens=17, prompt=prompt)
    # nothing was taken by the refused seat
    assert a.num_cached() == 3 and a.num_free() == 1
    # the revival charge must not DOUBLE-charge the tail as a CoW
    # credit: a full-budget reseat (commit = the whole pool) is
    # physically seatable — 3 revived + 1 growth — and refusing it
    # would starve it forever on an idle pool
    assert a.can_seat(prompt, 12, 16)
    assert a.alloc("b", tokens=12, commit_tokens=16,
                   prompt=prompt) == 12
    assert a.available() == 0  # 3 revived live, 1 free reserved
    # "b" owns the revived tail alone: write-in-place, no copy
    assert a.cow("b", 2) is None
    a.extend("b", 16)  # the growth block draws the reservation
    assert a.num_free() == 0 and a.available() == 0
    a.free("b")
    assert a.num_free() + a.num_cached() == 4 and a.available() == 4


def test_reclaimable_lru_eviction_is_leaf_first():
    """Under pressure the allocator evicts reclaimable blocks from the
    index; a chain's deeper blocks (leaves) go before their parents,
    so a surviving partial chain still matches."""
    a = _shared(num_blocks=4, block_size=4)
    prompt = list(range(16))  # 4 full blocks
    a.alloc("r0", tokens=16, prompt=prompt)
    a.register_prefix("r0", prompt)
    a.free("r0")
    assert a.num_cached() == 4 and a.num_free() == 0
    # a private alloc must evict exactly one reclaimable block — and
    # the LEAF (deepest chain block), never a parent
    a.alloc("r1", tokens=4)
    assert a.num_cached() == 3
    chain = a.match_prefix(prompt)
    assert len(chain) == 3  # prefix [0, 12) still matchable
    a.free("r1")
    # flush (the hot-reload hook) returns every cached block to free
    a.flush_index()
    assert a.num_cached() == 0 and a.num_free() == 4
    assert a.match_prefix(prompt) == []


# --------------------------------------------------- tiered host spill


def _tiered(num_blocks=4, block_size=4, host_blocks=8):
    return BlockAllocator(num_blocks=num_blocks, block_size=block_size,
                          share_prefix=True, host_blocks=host_blocks)


def test_eviction_spills_instead_of_forgetting():
    """With a host tier, device eviction DEMOTES the chain: the trie
    keeps resolving it (tail re-keyed onto a virtual id < -1), and the
    admission planner charges the spilled entry like a fresh draw —
    the chain saves its prefill, never its bytes."""
    a = _tiered()
    prompt = list(range(16))  # 4 full blocks
    a.alloc("r0", tokens=16, prompt=prompt)
    a.register_prefix("r0", prompt)
    a.free("r0")
    assert a.num_cached() == 4 and a.num_free() == 0
    a.alloc("r1", tokens=4)  # pressure: evicts ONE block — the leaf
    assert a.num_cached() == 3 and a.num_spilled() == 1
    assert a.spills == 1
    chain = a.match_prefix(prompt)
    assert len(chain) == 4 and chain[-1] < -1  # still fully matchable
    assert all(b >= 0 for b in chain[:3])  # resident prefix intact
    # plan: 3 reclaimable revivals + 1 spilled upload = 4 fresh-like
    # charges (no CoW: the tail is spilled, revival owns it solely)
    _chain, needed = a.plan(prompt, 16, 16)
    assert needed == 4
    a.free("r1")
    shared = a.alloc("r2", tokens=16, prompt=prompt)
    assert shared == 16  # the WHOLE prompt seated without prefill
    assert a.blocks_revived == 1
    assert a.num_spilled() == 0  # revival is a move, not a copy
    moves = a.take_revived()
    assert len(moves) == 1 and moves[0][0] < -1 and moves[0][1] >= 0
    a.free("r2")
    assert a.num_free() + a.num_cached() == 4


def test_spill_is_leaf_first_and_chain_stays_complete():
    """Deeper blocks spill before their parents, so every surviving
    trie path is a resident prefix + a spilled suffix — never a hole
    a revival could not reconstruct through."""
    a = _tiered()
    prompt = list(range(16))
    a.alloc("r0", tokens=16, prompt=prompt)
    a.register_prefix("r0", prompt)
    a.free("r0")
    for k in range(1, 5):
        a.alloc("p%d" % k, tokens=4)  # one eviction each
        chain = a.match_prefix(prompt)
        assert len(chain) == 4  # the full chain always resolves
        spilled = [b < 0 for b in chain]
        assert spilled == [False] * (4 - k) + [True] * k
    assert a.num_spilled() == 4 and a.num_cached() == 0


def test_host_budget_drops_leaf_first_and_is_bounded():
    """The host tier never exceeds its block budget: the oldest
    CHILDLESS spilled entry drops to make room (dropping an interior
    entry would orphan its children's keys)."""
    a = _tiered(num_blocks=2, block_size=4, host_blocks=1)
    prompt = list(range(8))  # 2 full blocks
    a.alloc("r0", tokens=8, prompt=prompt)
    a.register_prefix("r0", prompt)
    a.free("r0")
    # both cached blocks evict for a private 8-token alloc: the leaf
    # spills first, then the parent spills and the leaf (now the
    # oldest spilled entry, childless) drops for room
    a.alloc("r1", tokens=8)
    assert a.spills == 2 and a.host_drops == 1
    assert a.num_spilled() == 1  # never above the budget
    chain = a.match_prefix(prompt)
    assert len(chain) == 1 and chain[0] < -1  # root survived
    a.free("r1")
    # the surviving root still revives; the dropped tail re-prefills
    shared = a.alloc("r2", tokens=8, prompt=prompt)
    assert shared == 4 and a.blocks_revived == 1
    a.take_revived()
    a.free("r2")


def test_flush_index_clears_both_tiers():
    """Hot reload: stale-params rows must never seat a new request
    from either tier — the flush drops every spilled entry (counted
    as host drops) and empties the index."""
    drops = []
    a = _tiered()
    a._drop_sink = drops.append
    prompt = list(range(16))
    a.alloc("r0", tokens=16, prompt=prompt)
    a.register_prefix("r0", prompt)
    a.free("r0")
    a.alloc("r1", tokens=8)  # spill two blocks
    assert a.num_spilled() == 2
    a.flush_index()
    assert a.num_spilled() == 0 and a.num_cached() == 0
    assert len(drops) == 2 and a.host_drops == 2
    assert a.match_prefix(prompt) == []
    a.free("r1")
    assert a.num_free() == 4


def test_sinks_fire_in_order_spill_before_bid_reuse():
    """The spill sink must see the dying block id BEFORE it is
    recycled (the pool copies rows out through it), and the revival
    log pairs every vid with its fresh device block."""
    events = []
    a = _tiered(num_blocks=2, block_size=4, host_blocks=4)
    a._spill_sink = lambda bid, vid: events.append(("spill", bid, vid))
    a._drop_sink = lambda vid: events.append(("drop", vid))
    prompt = list(range(8))
    a.alloc("r0", tokens=8, prompt=prompt)
    a.register_prefix("r0", prompt)
    chain_bids = a.table("r0")
    a.free("r0")
    a.alloc("r1", tokens=8)  # both blocks spill, leaf first
    assert events == [("spill", chain_bids[1], -2),
                      ("spill", chain_bids[0], -3)]
    a.free("r1")
    shared = a.alloc("r2", tokens=8, prompt=prompt)
    assert shared == 8
    moves = a.take_revived()
    assert [vid for vid, _bid in moves] == [-3, -2]  # root-first
    assert sorted(bid for _vid, bid in moves) == sorted(a.table("r2"))
    a.free("r2")


def test_evictable_frontier_matches_brute_force_under_churn():
    """The O(1) eviction frontier must equal the brute-force
    definition — cached AND no resident indexed children — after every
    operation, and host accounting must conserve across spills, drops,
    revivals and flushes."""
    rs = np.random.RandomState(23)
    a = _tiered(num_blocks=16, block_size=4, host_blocks=6)
    prompts = [list(range(100 + 10 * i, 100 + 10 * i + 8))
               for i in range(4)]
    live = {}
    for i in range(500):
        roll = rs.rand()
        if live and (roll < 0.45 or not a.can_fit(16)):
            slot = rs.choice(sorted(live))
            a.free(slot)
            del live[slot]
        elif roll < 0.9:
            prompt = (prompts[rs.randint(len(prompts))]
                      if rs.rand() < 0.7 else
                      [int(x) for x in rs.randint(0, 50, size=6)])
            total = len(prompt) + int(rs.randint(1, 13))
            slot = "r%d" % i
            if a.can_seat(prompt, len(prompt), total):
                a.alloc(slot, len(prompt), commit_tokens=total,
                        prompt=prompt)
                a.take_revived()
                a.register_prefix(slot, prompt)
                live[slot] = prompt
        else:
            a.flush_index()
        # ---- invariants, after every op
        assert a.blocks_in_use() + a.num_free() + a.num_cached() == 16
        assert a.num_spilled() <= 6  # the budget holds at all times
        # brute-force evictability: cached, no resident indexed child
        brute = {
            bid for bid in a._cached
            if not any(c >= 0 for c in a._children.get(bid, ()))
        }
        assert set(a._evictable) == brute, (i, a._evictable, brute)
        # droppable spilled entries: childless, and every spilled
        # node's children are spilled (leaf-first both tiers)
        for vid in a._spilled:
            kids = a._children.get(vid, set())
            assert all(c < 0 for c in kids), (i, vid, kids)
        brute_leaves = {
            vid for vid in a._spilled if not a._children.get(vid)
        }
        assert set(a._spill_leaves) == brute_leaves
        # every index path is complete: a child's key parent resolves
        for node, key in a._index_key.items():
            parent = key[0]
            assert parent == -1 or parent in a._index_key, (i, node)
    for slot in list(live):
        a.free(slot)
    a.flush_index()
    assert a.num_free() == 16 and a.available() == 16


def test_pool_spill_revive_round_trips_rows_and_scales():
    """PagedKVPool-level: a spilled block's rows — int8 rows AND f32
    scale leaves — must round-trip the host tier bit-exactly through
    revival, and the host byte gauge must track block_bytes."""
    import jax.numpy as jnp

    from elasticdl_tpu.serving.kv_pool import PagedKVPool

    rs = np.random.RandomState(31)
    hkv, d, cache_len, bs, nb = 2, 8, 16, 4, 4
    kv_shapes = {
        "k": jnp.zeros((1, hkv, cache_len, d), jnp.int8),
        "k_scale": jnp.zeros((1, hkv, cache_len, 1), jnp.float32),
        "pos": jnp.zeros((), jnp.int32),
    }
    pool = PagedKVPool(kv_shapes, cache_len, num_slots=2,
                       num_blocks=nb, block_size=bs,
                       share_prefix=True, host_bytes=10 ** 6)
    prompt = list(range(100, 116))
    pool.seat(0, prompt, 16)
    table0 = pool.allocator.table(0)
    pat = rs.randint(-127, 128, size=(nb, bs, hkv, d)).astype(np.int8)
    sca = rs.rand(nb, bs, hkv, 1).astype(np.float32)
    pool.pools = dict(pool.pools, k=jnp.asarray(pat),
                      k_scale=jnp.asarray(sca))
    pool.register_prefix(0, prompt)
    pool.release(0)
    # a colliding-size seat evicts all four blocks -> all spill
    pool.seat(1, list(range(16)), 16)
    assert pool.allocator.num_spilled() == 4
    assert pool.host_bytes_in_use() == 4 * pool.block_bytes
    assert pool.stats()["kv_host_blocks"] == 4
    pool.release(1)
    shared = pool.seat(0, prompt, 16)
    assert shared == 16 and pool.revive_uploads == 1
    assert pool.host_bytes_in_use() == 0  # moved, not copied
    k = np.asarray(pool.pools["k"])
    ks = np.asarray(pool.pools["k_scale"])
    for old, new in zip(table0, pool.allocator.table(0)):
        np.testing.assert_array_equal(k[new], pat[old])
        np.testing.assert_array_equal(ks[new], sca[old])
    assert pool.stats()["prefill_tokens_revived"] == 16
    pool.release(0)


def test_pool_host_budget_never_exceeded():
    """The budget pin: under sustained eviction pressure the host
    tier's bytes stay at or under kv_host_bytes at every step."""
    import jax.numpy as jnp

    from elasticdl_tpu.serving.kv_pool import PagedKVPool

    hkv, d, cache_len, bs, nb = 1, 4, 16, 4, 4
    kv_shapes = {
        "k": jnp.zeros((1, hkv, cache_len, d), jnp.float32),
        "pos": jnp.zeros((), jnp.int32),
    }
    probe = PagedKVPool(kv_shapes, cache_len, num_slots=2,
                        num_blocks=nb, block_size=bs,
                        share_prefix=True, host_bytes=0)
    budget = 2 * probe.block_bytes  # room for exactly two blocks
    pool = PagedKVPool(kv_shapes, cache_len, num_slots=2,
                       num_blocks=nb, block_size=bs,
                       share_prefix=True, host_bytes=budget)
    assert pool.allocator.host_blocks == 2
    rs = np.random.RandomState(7)
    for i in range(40):
        prompt = [int(x) for x in rs.randint(0, 9, size=12)]
        if pool.can_seat(prompt, len(prompt), 16):
            pool.seat(0, prompt, 16)
            pool.register_prefix(0, prompt)
            pool.release(0)
        assert pool.host_bytes_in_use() <= budget, i
        assert pool.stats()["kv_host_bytes"] <= budget, i
    assert pool.allocator.spills > 2  # pressure actually engaged


def test_fragmentation_under_mixed_shared_private_churn():
    """Random admit/complete churn with a pool of recurring system
    prompts: conservation (live + free + cached == total), disjoint
    private ownership, refcount consistency, and a drained pool is
    whole again."""
    rs = np.random.RandomState(11)
    a = _shared(num_blocks=32, block_size=4)
    prompts = [list(range(100 + i, 100 + i + 8)) for i in range(3)]
    live = {}
    for i in range(400):
        if live and (rs.rand() < 0.45 or not a.can_fit(24)):
            slot = rs.choice(sorted(live))
            a.free(slot)
            del live[slot]
        else:
            shared_prompt = rs.rand() < 0.6
            prompt = (prompts[rs.randint(len(prompts))]
                      if shared_prompt else
                      [int(x) for x in rs.randint(0, 50, size=6)])
            total = len(prompt) + int(rs.randint(1, 17))
            slot = "r%d" % i
            if a.can_seat(prompt, len(prompt), total):
                a.alloc(slot, len(prompt), commit_tokens=total,
                        prompt=prompt)
                a.register_prefix(slot, prompt)
                live[slot] = prompt
                a.extend(slot, min(total,
                                   len(prompt) + int(rs.randint(0, 9))))
        # ---- invariants
        assert a.blocks_in_use() + a.num_free() + a.num_cached() == 32
        assert a.available() >= 0
        refs = {}
        for s in live:
            for b in a.table(s):
                refs[b] = refs.get(b, 0) + 1
        # every live table block carries exactly its reference count
        for b, n in refs.items():
            assert a._refcount.get(b, 0) == n, (b, n)
        # no block is simultaneously free/cached and referenced
        assert not (set(refs) & set(a._free))
        assert not (set(refs) & set(a._cached))
    for slot in list(live):
        a.free(slot)
    assert a.blocks_in_use() == 0
    assert a.num_free() + a.num_cached() == 32
    a.flush_index()
    assert a.num_free() == 32 and a.available() == 32


def test_chain_export_import_round_trip():
    """Disagg handoff, pool level: export_chain's dense byte copy of a
    registered chain (int8 rows + f32 scale leaves) must equal both the
    arena rows it was gathered from AND the host-tier bytes the same
    chain spills to; importing it into a FRESH pool re-keys the trie
    (refcount-0 reclaimable, dedup on re-import), a seat shares the
    whole chain with identical rows, and the ledger settles clean."""
    import jax.numpy as jnp

    from elasticdl_tpu.serving.kv_pool import PagedKVPool

    rs = np.random.RandomState(41)
    hkv, d, cache_len, bs, nb = 2, 8, 16, 4, 4
    kv_shapes = {
        "k": jnp.zeros((1, hkv, cache_len, d), jnp.int8),
        "k_scale": jnp.zeros((1, hkv, cache_len, 1), jnp.float32),
        "pos": jnp.zeros((), jnp.int32),
    }

    def _pool():
        return PagedKVPool(kv_shapes, cache_len, num_slots=2,
                           num_blocks=nb, block_size=bs,
                           share_prefix=True, host_bytes=10 ** 6)

    src = _pool()
    prompt = list(range(100, 116))
    src.seat(0, prompt, 16)
    table0 = src.allocator.table(0)
    pat = rs.randint(-127, 128, size=(nb, bs, hkv, d)).astype(np.int8)
    sca = rs.rand(nb, bs, hkv, 1).astype(np.float32)
    src.pools = dict(src.pools, k=jnp.asarray(pat),
                     k_scale=jnp.asarray(sca))
    src.register_prefix(0, prompt)
    src.release(0)

    blocks = src.export_chain(prompt)
    assert src.chain_exports == 1
    assert len(blocks) == 4
    assert src.leaf_dtypes() == ["int8", "float32"]
    for i, ((toks, rows), bid) in enumerate(zip(blocks, table0)):
        assert list(toks) == prompt[i * bs:(i + 1) * bs]
        np.testing.assert_array_equal(rows[0], pat[bid])
        np.testing.assert_array_equal(rows[1], sca[bid])
    # exported bytes == the host-tier bytes the same chain spills to:
    # a colliding-size seat evicts all four cached blocks to the host
    # store, and the spill reads through the same gather
    src.seat(1, list(range(16)), 16)
    assert src.allocator.num_spilled() == 4
    spilled = {tuple(np.asarray(r).tobytes() for r in rows)
               for rows in src._host_rows.values()}
    exported = {tuple(np.ascontiguousarray(r).tobytes() for r in rows)
                for _, rows in blocks}
    assert exported == spilled
    src.release(1)

    dst = _pool()
    added, tokens = dst.import_chain(
        blocks, leaf_dtypes=src.leaf_dtypes()
    )
    assert (added, tokens) == (4, 16)
    assert dst.chain_imports == 1
    assert dst.chain_import_tokens == 16
    # re-import dedups: the trie already resolves every level
    assert dst.import_chain(blocks) == (0, 0)
    assert dst.chain_imports == 1
    # imported chain parks refcount-0 reclaimable: nothing in use,
    # nothing pinned — the importer's walk references all settled
    a = dst.allocator
    assert a.blocks_in_use() == 0
    assert a.num_free() + a.num_cached() == nb
    # a seat shares the whole chain and reads back identical rows
    shared = dst.seat(0, prompt, 16)
    assert shared == 16
    k = np.asarray(dst.pools["k"])
    ks = np.asarray(dst.pools["k_scale"])
    for old, new in zip(table0, dst.allocator.table(0)):
        np.testing.assert_array_equal(k[new], pat[old])
        np.testing.assert_array_equal(ks[new], sca[old])
    dst.release(0)
    assert a.blocks_in_use() == 0
    # refused payloads fail BEFORE any allocation mutates the ledger
    with pytest.raises(ValueError):
        dst.import_chain(blocks, leaf_dtypes=["float32", "float32"])
    with pytest.raises(ValueError):
        dst.import_chain([((1, 2), blocks[0][1])])
    assert a.blocks_in_use() == 0
    assert a.num_free() + a.num_cached() == nb


@pytest.mark.slow
def test_disagg_handoff_matches_offline_int8_32way():
    """The disagg acceptance pin (drills shard): 32 concurrent GREEDY
    requests against a phase-split pair — a dedicated prefill replica
    and a paged + shared + speculative + INT8 decode replica — where
    EVERY unique prompt crosses a prefill->decode chain handoff before
    its requests decode. Token streams must equal the offline int8
    oracle (the handoff is token-exact by the prefix-sharing
    argument), both pools must drain to a clean two-pool ledger with
    zero transfers in flight, and the chain counters must show the
    handoff machinery actually carried the prompts."""
    import threading

    import jax

    from elasticdl_tpu.api.generation import autoregressive_generate
    from elasticdl_tpu.common.model_utils import (
        load_model_spec_from_module,
    )
    from elasticdl_tpu.parallel import mesh as mesh_lib
    from elasticdl_tpu.proto import elasticdl_pb2 as pb
    from elasticdl_tpu.proto.service import ServingStub, build_channel
    from elasticdl_tpu.serving import GenerationServer, ServingConfig
    from elasticdl_tpu.serving.disagg import HandoffCoordinator
    from elasticdl_tpu.training.trainer import Trainer
    from model_zoo.transformer_lm import transformer_lm as zoo

    params = ("vocab_size=8; seq_len=16; embed_dim=32; num_heads=2; "
              "num_layers=1")
    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = Trainer(
        load_model_spec_from_module(zoo), mesh=mesh,
        model_params=params + "; kv_cache_dtype='int8'",
    )
    toks = (np.arange(17)[None, :] % 8).astype(np.int32)
    batch = ({"tokens": toks[:, :-1]}, toks[:, 1:])
    state = trainer.init_state(batch)
    draft_trainer = Trainer(  # float draft, mismatched weights
        load_model_spec_from_module(zoo), mesh=mesh,
        model_params=params, seed=321,
    )
    draft_state = draft_trainer.init_state(batch)

    systems = [[1, 2, 3, 4], [5, 6, 7, 1, 2, 3, 4, 5]]
    specs = []
    for i in range(32):
        prompt = list(systems[i % 2]) + ([1 + i % 3] if i % 4 else [])
        specs.append({"prompt": prompt, "new": 3 + i % 5})

    cfg_p = ServingConfig(
        num_slots=2, queue_capacity=16, kv_paged=True,
        kv_block_size=4, kv_num_blocks=24, kv_shared=True,
        role="prefill",
    )
    cfg_d = ServingConfig(
        num_slots=6, queue_capacity=64, kv_paged=True,
        kv_block_size=4, kv_num_blocks=24, kv_shared=True,
        draft_k=2, role="decode",
    )
    sp = GenerationServer(trainer, state, cfg_p).start()
    sd = GenerationServer(
        trainer, state, cfg_d, draft=(draft_trainer, draft_state)
    ).start()

    class _Rep(object):
        def __init__(self, port):
            self.address = "localhost:%d" % port
            self.stub = ServingStub(build_channel(self.address))

    class _Req(object):
        def __init__(self, prompt):
            self.prompt = prompt
            self.temperature = 0.0
            self.seed = 0

    try:
        rp, rd = _Rep(sp.port), _Rep(sd.port)
        co = HandoffCoordinator()
        unique = sorted({tuple(s["prompt"]) for s in specs})
        for p in unique:
            payload = co.export_chain(
                rp, _Req(list(p)), co.new_transfer_id()
            )
            co.import_chain(rd, payload)

        results, errors = {}, {}

        def call(i, s):
            try:
                r = rd.stub.generate(
                    pb.GenerateRequest(
                        prompt=s["prompt"],
                        max_new_tokens=s["new"],
                    ),
                    timeout=120,
                )
                results[i] = list(r.tokens)
            except Exception as e:  # noqa: BLE001
                errors[i] = e

        threads = [
            threading.Thread(target=call, args=(i, s))
            for i, s in enumerate(specs)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert not errors, errors
        assert len(results) == 32

        stp = rp.stub.server_status(pb.ServerStatusRequest(),
                                    timeout=10)
        std = rd.stub.server_status(pb.ServerStatusRequest(),
                                    timeout=10)
        assert stp.role == "prefill" and std.role == "decode"
        assert stp.chain_exports == len(unique)
        assert std.chain_imports >= 1
        assert std.chain_import_tokens >= 4
        # every decode request seated on an imported chain
        assert std.prefix_hit_tokens > 0
        assert std.draft_k == 2 and std.draft_proposed > 0
        # clean two-pool post-drain ledger, nothing in flight
        assert stp.transfers_inflight == 0
        assert std.transfers_inflight == 0
        assert stp.kv_blocks_free == stp.kv_blocks_total == 24
        assert std.kv_blocks_free == std.kv_blocks_total == 24
    finally:
        sp.stop()
        sd.stop()

    for i, s in enumerate(specs):
        off = np.asarray(autoregressive_generate(
            trainer, state, np.asarray([s["prompt"]], np.int32),
            s["new"], use_cache=True,
        ))[0]
        assert list(off) == results[i], (i, s)
