"""Unit tests for the RPC resilience layer (common/retry.py), the fault
injector (common/fault_injection.py), and the worker's explicit
end-of-job handling — the two acceptance paths:

* a worker that sees a TRANSIENT master outage retries inside the
  bounded reconnect window instead of exiting as "end of job";
* a genuinely finished job still shuts the worker down cleanly via the
  explicit JOB_COMPLETE signal.
"""

import grpc
import pytest

from elasticdl_tpu.common.fault_injection import (
    FaultInjectingServicer,
    FaultInjector,
    FaultRule,
    InjectedRpcError,
    maybe_wrap_servicer,
)
from elasticdl_tpu.common.retry import (
    RetryPolicy,
    is_backpressure_rpc_error,
    is_retryable_rpc_error,
    is_transient_rpc_error,
    retry_call,
)
from elasticdl_tpu.proto import elasticdl_pb2 as pb


def fast_policy(window=5.0):
    return RetryPolicy(
        rpc_timeout_secs=5.0,
        base_delay_secs=0.001,
        max_delay_secs=0.01,
        reconnect_window_secs=window,
    )


# ------------------------------------------------------------ retry_call


def test_retry_call_returns_on_first_success():
    result, attempts = retry_call(lambda: 42, policy=fast_policy())
    assert (result, attempts) == (42, 0)


def test_retry_call_retries_transient_then_succeeds():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 4:
            raise InjectedRpcError(grpc.StatusCode.UNAVAILABLE, "boom")
        return "ok"

    retried = []
    result, attempts = retry_call(
        flaky, policy=fast_policy(),
        on_retry=lambda i, e: retried.append(i),
    )
    assert result == "ok"
    assert attempts == 3
    assert retried == [0, 1, 2]


def test_retry_call_raises_non_retryable_immediately():
    calls = {"n": 0}

    def bad():
        calls["n"] += 1
        raise ValueError("config error")

    with pytest.raises(ValueError):
        retry_call(bad, policy=fast_policy())
    assert calls["n"] == 1


def test_retry_call_gives_up_after_reconnect_window():
    clock = {"t": 0.0}

    def fake_clock():
        return clock["t"]

    def fake_sleep(s):
        clock["t"] += max(s, 0.05)

    def always_down():
        raise InjectedRpcError(grpc.StatusCode.UNAVAILABLE, "down")

    with pytest.raises(InjectedRpcError):
        retry_call(
            always_down,
            policy=RetryPolicy(reconnect_window_secs=1.0,
                               base_delay_secs=0.1),
            sleep=fake_sleep,
            clock=fake_clock,
        )
    assert clock["t"] >= 1.0  # the whole window was used


def test_is_transient_rpc_error_classification():
    assert is_transient_rpc_error(
        InjectedRpcError(grpc.StatusCode.UNAVAILABLE, "x"))
    assert is_transient_rpc_error(
        InjectedRpcError(grpc.StatusCode.CANCELLED, "x"))
    assert is_transient_rpc_error(
        InjectedRpcError(grpc.StatusCode.DEADLINE_EXCEEDED, "x"))
    assert not is_transient_rpc_error(
        InjectedRpcError(grpc.StatusCode.INVALID_ARGUMENT, "x"))
    assert not is_transient_rpc_error(ValueError("x"))


def test_backpressure_is_distinct_from_transient():
    """RESOURCE_EXHAUSTED is backpressure from a LIVE server: retryable
    (the router re-routes on it) but NOT transient (a single-target
    retry loop into a full queue is just more load, and the router must
    not charge it against a replica's circuit breaker)."""
    full = InjectedRpcError(grpc.StatusCode.RESOURCE_EXHAUSTED, "full")
    assert is_backpressure_rpc_error(full)
    assert not is_transient_rpc_error(full)
    assert is_retryable_rpc_error(full)
    down = InjectedRpcError(grpc.StatusCode.UNAVAILABLE, "down")
    assert not is_backpressure_rpc_error(down)
    assert is_retryable_rpc_error(down)
    assert not is_backpressure_rpc_error(ValueError("x"))
    assert not is_retryable_rpc_error(
        InjectedRpcError(grpc.StatusCode.INVALID_ARGUMENT, "x"))


def test_retry_call_window_edge_clamp_gives_one_final_attempt(
        monkeypatch):
    """Regression: a backoff delay clamped to the reconnect-window edge
    must still buy exactly ONE final attempt — the clamp exists so the
    last attempt lands just inside the window, not so the caller loses
    it (or gets extras past the window)."""
    # pin the jitter draw to the cap so the clamp is guaranteed to
    # engage (full jitter would otherwise occasionally draw under the
    # window and sneak in a third attempt)
    import elasticdl_tpu.common.retry as retry_mod

    monkeypatch.setattr(retry_mod.random, "uniform", lambda a, b: b)
    clock = {"t": 0.0}
    sleeps = []

    def fake_clock():
        return clock["t"]

    def fake_sleep(s):
        sleeps.append(s)
        clock["t"] += s

    calls = {"n": 0}

    def always_down():
        calls["n"] += 1
        raise InjectedRpcError(grpc.StatusCode.UNAVAILABLE, "down")

    # base delay far larger than the window: the very first backoff is
    # clamped from 100s down to exactly the 1.0s window remainder
    with pytest.raises(InjectedRpcError):
        retry_call(
            always_down,
            policy=RetryPolicy(reconnect_window_secs=1.0,
                               base_delay_secs=100.0,
                               max_delay_secs=100.0),
            sleep=fake_sleep,
            clock=fake_clock,
        )
    # attempt 0 at t=0, one clamped sleep to the edge, final attempt at
    # t=1.0 (now >= deadline -> raise). Exactly 2 calls, never 1 or 3.
    assert calls["n"] == 2
    assert len(sleeps) == 1 and sleeps[0] <= 1.0
    assert clock["t"] == pytest.approx(1.0)


def test_backoff_is_bounded():
    p = RetryPolicy(base_delay_secs=0.5, max_delay_secs=2.0)
    for attempt in range(10):
        d = p.backoff(attempt)
        assert 0.0 <= d <= 2.0


# --------------------------------------------------------- fault injector


def test_fault_rule_parsing():
    r = FaultRule.parse("get_task:drop:3")
    assert (r.rpc, r.action, r.count) == ("get_task", "drop", 3)
    r = FaultRule.parse("worker_launch:delay:*:secs=1.5,skip=2")
    assert r.count is None and r.secs == 1.5 and r.skip == 2
    r = FaultRule.parse("report_task_result:error")
    assert r.count == 1
    with pytest.raises(ValueError):
        FaultRule.parse("get_task")
    with pytest.raises(ValueError):
        FaultRule.parse("get_task:explode")


def test_injector_drop_fires_limited_times():
    inj = FaultInjector(spec="get_task:drop:2")
    for _ in range(2):
        with pytest.raises(InjectedRpcError):
            inj.intercept("get_task")
    inj.intercept("get_task")  # armed count exhausted: no-op
    inj.intercept("report_task_result")  # different rpc: no-op
    assert inj.injected == {"get_task": 2}


def test_injector_skip_lets_first_calls_through():
    inj = FaultInjector(spec="get_task:drop:1:skip=2")
    inj.intercept("get_task")
    inj.intercept("get_task")
    with pytest.raises(InjectedRpcError):
        inj.intercept("get_task")


def test_injector_kill_action_uses_kill_fn():
    killed = []
    inj = FaultInjector(spec="get_task:kill:1",
                        kill_fn=lambda: killed.append(1))
    inj.intercept("get_task")
    assert killed == [1]


def test_injector_from_env(monkeypatch):
    monkeypatch.delenv("EDL_FAULT_SPEC", raising=False)
    assert FaultInjector.from_env() is None
    monkeypatch.setenv("EDL_FAULT_SPEC", "get_task:drop:1")
    inj = FaultInjector.from_env()
    assert inj is not None and len(inj.rules) == 1


class _FakeServicer(object):
    def __init__(self):
        self.calls = []

    def get_task(self, request, _context=None):
        self.calls.append("get_task")
        return pb.Task(type=pb.WAIT)

    def report_task_result(self, request, _context=None):
        self.calls.append("report")
        return pb.Empty()

    def report_evaluation_metrics(self, request, _context=None):
        return pb.Empty()

    def report_version(self, request, _context=None):
        return pb.Empty()

    def register_worker(self, request, _context=None):
        return pb.RegisterWorkerResponse()

    def get_model_version(self):
        return 17


def test_fault_injecting_servicer_drop_vs_error():
    fake = _FakeServicer()
    wrapped = FaultInjectingServicer(
        fake,
        FaultInjector(spec="get_task:drop:1;report_task_result:error:1"),
    )
    # drop: handler must NOT run (request lost before processing)
    with pytest.raises(InjectedRpcError):
        wrapped.get_task(pb.GetTaskRequest())
    assert "get_task" not in fake.calls
    # error: handler RUNS, response lost (duplicate-side-effect case)
    with pytest.raises(InjectedRpcError):
        wrapped.report_task_result(pb.ReportTaskResultRequest())
    assert "report" in fake.calls
    # rules exhausted: passthrough
    assert wrapped.get_task(pb.GetTaskRequest()).type == pb.WAIT
    # non-RPC attributes proxy through
    assert wrapped.get_model_version() == 17


def test_maybe_wrap_servicer_is_identity_without_rules(monkeypatch):
    monkeypatch.delenv("EDL_FAULT_SPEC", raising=False)
    fake = _FakeServicer()
    assert maybe_wrap_servicer(fake) is fake
    assert maybe_wrap_servicer(fake, FaultInjector()) is fake
    wrapped = maybe_wrap_servicer(
        fake, FaultInjector(spec="get_task:drop:1"))
    assert wrapped is not fake
