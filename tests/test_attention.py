"""Attention stack tests: blockwise and flash vs naive oracle; ring
attention on the virtual 8-device mesh vs single-device full attention
(values AND gradients)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from elasticdl_tpu.ops.attention import (
    blockwise_attention,
    flash_attention,
    naive_attention,
)
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.parallel.context_parallel import ring_attention


@pytest.fixture(autouse=True)
def _opt_into_interpreted_kernels(monkeypatch):
    """use_pallas() routes to the jnp reference paths off-TPU; these
    tests exist to exercise the kernel code itself, so they opt into
    Pallas interpreter mode explicitly."""
    monkeypatch.setenv("ELASTICDL_TPU_FORCE_INTERPRET", "1")

B, H, L, D = 2, 2, 64, 8


def _qkv(seed=0, l=L, d=D):
    rs = np.random.RandomState(seed)
    mk = lambda: rs.randn(B, H, l, d).astype(np.float32)
    return jnp.array(mk()), jnp.array(mk()), jnp.array(mk())


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_matches_naive(causal):
    q, k, v = _qkv(0)
    ref = naive_attention(q, k, v, causal=causal)
    out = blockwise_attention(q, k, v, causal=causal, block_size=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_blockwise_uneven_blocks():
    q, k, v = _qkv(1, l=50)
    ref = naive_attention(q, k, v, causal=True)
    out = blockwise_attention(q, k, v, causal=True, block_size=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_naive(causal):
    # d=128 lane-aligned so the real kernel path runs (interpreted on CPU)
    q, k, v = _qkv(2, l=32, d=128)
    ref = naive_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_pallas_bwd(causal):
    """The Pallas two-pass backward (dq + dkv kernels) against the naive
    oracle: rectangular seq (lq != lk), mixed block sizes."""
    rs = np.random.RandomState(7)
    q = jnp.asarray(rs.randn(2, 2, 64, 128).astype(np.float32) * 0.3)
    k = jnp.asarray(rs.randn(2, 2, 32, 128).astype(np.float32) * 0.3)
    v = jnp.asarray(rs.randn(2, 2, 32, 128).astype(np.float32) * 0.3)

    def loss_flash(q, k, v):
        return (
            flash_attention(q, k, v, causal=causal, block_q=32,
                            block_k=16) ** 2
        ).sum()

    def loss_ref(q, k, v):
        return (naive_attention(q, k, v, causal=causal) ** 2).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=1e-3, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_sliding_window_matches_naive(causal):
    """Window-masked flash (fwd + Pallas bwd) against the naive oracle,
    block-skip predicate included (window smaller than a block)."""
    q, k, v = _qkv(11, l=64, d=128)
    w = 12
    ref = naive_attention(q, k, v, causal=causal, window=w)
    out = flash_attention(q, k, v, causal=causal, window=w,
                          block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)
    blk = blockwise_attention(q, k, v, causal=causal, window=w,
                              block_size=16)
    np.testing.assert_allclose(np.asarray(blk), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=causal, window=w,
                                block_q=16, block_k=16) ** 2).sum()

    def loss_ref(q, k, v):
        return (naive_attention(q, k, v, causal=causal, window=w) ** 2
                ).sum()

    g_f = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_f, g_r):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=1e-3, atol=1e-4)


def test_sliding_window_validation():
    q, k, v = _qkv(12, l=32, d=128)
    with pytest.raises(ValueError, match="window"):
        flash_attention(q, k, v, causal=True, window=0)
    with pytest.raises(ValueError, match="window"):
        blockwise_attention(q, k, v, causal=True, window=-2)
    with pytest.raises(ValueError, match="square"):
        flash_attention(q, k[:, :, :16], v[:, :, :16], causal=True,
                        window=4)
    with pytest.raises(ValueError, match="square"):
        blockwise_attention(q, k[:, :, :16], v[:, :, :16], window=4)


@pytest.mark.slow
def test_sliding_window_model_trains():
    """transformer_lm with attn_window trains and differs from full
    attention (the mask actually bites)."""
    from elasticdl_tpu.common.model_utils import (
        format_params_str,
        load_model_spec_from_module,
    )
    from elasticdl_tpu.parallel import mesh as mesh_lib
    from elasticdl_tpu.training.trainer import Trainer
    from model_zoo.transformer_lm import transformer_lm as zoo

    cfg = dict(vocab_size=32, seq_len=32, embed_dim=32, num_heads=2,
               num_layers=1, attn_window=4)
    rs = np.random.RandomState(0)
    tokens = rs.randint(0, 32, size=(4, 33)).astype(np.int32)
    batch = ({"tokens": tokens[:, :-1]}, tokens[:, 1:])
    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    spec = load_model_spec_from_module(zoo)
    t_win = Trainer(spec, mesh=mesh,
                    model_params=format_params_str(cfg))
    s_win = t_win.init_state(batch)
    s_win, l_win = t_win.train_step(s_win, batch)
    cfg_full = dict(cfg, attn_window=0)
    t_full = Trainer(spec, mesh=mesh,
                     model_params=format_params_str(cfg_full))
    s_full = t_full.init_state(batch)
    s_full, l_full = t_full.train_step(s_full, batch)
    assert abs(float(l_win) - float(l_full)) > 1e-6


def test_flash_gradients():
    q, k, v = _qkv(3, l=32, d=128)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, causal=True, block_q=16,
                               block_k=16).sum()

    def loss_ref(q, k, v):
        return naive_attention(q, k, v, causal=True).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=1e-3, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.slow
def test_ring_attention_8dev(causal):
    mesh = mesh_lib.build_mesh({"sp": 8})
    q, k, v = _qkv(4)
    ref = naive_attention(q, k, v, causal=causal)
    out = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_ring_attention_dp_sp_mesh():
    mesh = mesh_lib.build_mesh({"dp": 2, "sp": 4})
    q, k, v = _qkv(5)
    ref = naive_attention(q, k, v, causal=True)
    out = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_ring_attention_gradients():
    mesh = mesh_lib.build_mesh({"sp": 8})
    q, k, v = _qkv(6)

    def loss_ring(q, k, v):
        return ring_attention(q, k, v, mesh, causal=True).sum()

    def loss_ref(q, k, v):
        return naive_attention(q, k, v, causal=True).sum()

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gr_, gn in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr_), np.asarray(gn),
                                   rtol=1e-3, atol=1e-4)


@pytest.mark.slow
def test_ring_attention_jit_compiles_once():
    mesh = mesh_lib.build_mesh({"sp": 8})
    q, k, v = _qkv(7)
    fn = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh))
    out1 = fn(q, k, v)
    out2 = fn(q + 1, k, v)
    assert out1.shape == q.shape and out2.shape == q.shape


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.slow
def test_ring_attention_jnp_fallback(causal, monkeypatch):
    """The non-Pallas ring path (blockwise forward + dense jnp backward
    recomputing P from the global lse) against the naive oracle."""
    monkeypatch.setenv("ELASTICDL_TPU_DISABLE_PALLAS", "1")
    mesh = mesh_lib.build_mesh({"sp": 8})
    q, k, v = _qkv(8)
    out = ring_attention(q, k, v, mesh, causal=causal)
    ref = naive_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)
    g_ring = jax.grad(
        lambda a, b, c: (ring_attention(a, b, c, mesh,
                                        causal=causal) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        lambda a, b, c: (naive_attention(a, b, c,
                                         causal=causal) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for gr_, gn in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr_), np.asarray(gn),
                                   rtol=1e-3, atol=1e-4)


@pytest.mark.slow
def test_ring_attention_uses_flash_kernels(monkeypatch):
    """Proof the ring's local compute is the Pallas flash kernel, both
    directions: count _flash_forward / _flash_backward invocations while
    tracing a ring attention value+grad on the sp mesh."""
    import elasticdl_tpu.ops.attention as attn_mod

    calls = {"fwd": 0, "bwd": 0}
    real_fwd, real_bwd = attn_mod._flash_forward, attn_mod._flash_backward

    def spy_fwd(*a, **kw):
        calls["fwd"] += 1
        return real_fwd(*a, **kw)

    def spy_bwd(*a, **kw):
        calls["bwd"] += 1
        return real_bwd(*a, **kw)

    monkeypatch.setattr(attn_mod, "_flash_forward", spy_fwd)
    monkeypatch.setattr(attn_mod, "_flash_backward", spy_bwd)
    mesh = mesh_lib.build_mesh({"sp": 8})
    q, k, v = _qkv(9)
    g = jax.grad(
        lambda a, b, c: ring_attention(a, b, c, mesh, causal=True).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    assert calls["fwd"] > 0, "ring forward never reached the flash kernel"
    assert calls["bwd"] > 0, "ring backward never reached the flash kernel"
    assert all(x.shape == q.shape for x in g)


def test_ulysses_auto_picks_flash(monkeypatch):
    """Ulysses attn_impl='auto' must route the full-sequence local
    attention through the Pallas flash kernel (the _flash custom-vjp
    entry) whenever it can run."""
    import elasticdl_tpu.ops.attention as attn_mod
    from elasticdl_tpu.parallel.context_parallel import ulysses_attention

    calls = {"n": 0}
    real = attn_mod._flash

    def spy(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(attn_mod, "_flash", spy)
    mesh = mesh_lib.build_mesh({"sp": 8})
    rs = np.random.RandomState(10)
    mk = lambda: jnp.asarray(rs.randn(2, 8, 64, 16).astype(np.float32))
    out = ulysses_attention(mk(), mk(), mk(), mesh, causal=True,
                            attn_impl="auto")
    assert calls["n"] > 0, "ulysses auto did not reach the flash kernel"
    assert out.shape == (2, 8, 64, 16)


def test_jax_flash_off_tpu_fallback_and_window_rejection():
    """attn_impl='jax_flash' off-TPU falls back to the blockwise path
    (values match naive); sliding windows are rejected explicitly."""
    from elasticdl_tpu.ops.attention import jax_flash_attention

    q, k, v = _qkv(13, l=32, d=16)
    out = jax_flash_attention(q, k, v, causal=True)
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)
    with pytest.raises(ValueError, match="sliding-window"):
        jax_flash_attention(q, k, v, causal=True, window=4)


def test_ulysses_jax_flash_matches_naive():
    """attn_impl='jax_flash' through Ulysses: the dispatch map routes
    the local full-sequence attention to jax's bundled kernel (which
    falls back to blockwise off-TPU) — values must match the naive
    oracle on the sp mesh."""
    from elasticdl_tpu.parallel.context_parallel import ulysses_attention

    rs = np.random.RandomState(21)
    mk = lambda: jnp.asarray(rs.randn(2, 8, 64, 16).astype(np.float32))
    q, k, v = mk(), mk(), mk()
    mesh = mesh_lib.build_mesh({"sp": 8})
    out = ulysses_attention(q, k, v, mesh, causal=True,
                            attn_impl="jax_flash")
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


# ------------------------------------------------- grouped-query (GQA)


@pytest.mark.parametrize("hkv", [1, 2])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_gqa_matches_expanded_naive(causal, hkv):
    """GQA/MQA through the Pallas kernels (fwd + both backward passes)
    vs the naive oracle on repeat-expanded kv. dk/dv must come back
    group-summed in the kv head count."""
    from elasticdl_tpu.ops.attention import expand_kv

    rs = np.random.RandomState(31)
    b, h, l, d = 2, 4, 64, 128
    q = jnp.asarray(rs.randn(b, h, l, d).astype(np.float32) * 0.3)
    k = jnp.asarray(rs.randn(b, hkv, l, d).astype(np.float32) * 0.3)
    v = jnp.asarray(rs.randn(b, hkv, l, d).astype(np.float32) * 0.3)

    def loss_flash(q, k, v):
        return (
            flash_attention(q, k, v, causal=causal, block_q=16,
                            block_k=16) ** 2
        ).sum()

    def loss_ref(q, k, v):
        return (
            naive_attention(q, expand_kv(k, h), expand_kv(v, h),
                            causal=causal) ** 2
        ).sum()

    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    ref = naive_attention(q, expand_kv(k, h), expand_kv(v, h),
                          causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        assert gf.shape == gr.shape
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=1e-3, atol=1e-4)


def test_gqa_sliding_window_matches_naive():
    """GQA composes with the sliding-window block-skip predicate."""
    from elasticdl_tpu.ops.attention import expand_kv

    rs = np.random.RandomState(32)
    b, h, hkv, l, d = 1, 4, 2, 64, 128
    q = jnp.asarray(rs.randn(b, h, l, d).astype(np.float32) * 0.3)
    k = jnp.asarray(rs.randn(b, hkv, l, d).astype(np.float32) * 0.3)
    v = jnp.asarray(rs.randn(b, hkv, l, d).astype(np.float32) * 0.3)
    out = flash_attention(q, k, v, causal=True, window=16, block_q=16,
                          block_k=16)
    ref = naive_attention(q, expand_kv(k, h), expand_kv(v, h),
                          causal=True, window=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_gqa_head_divisibility_validated():
    rs = np.random.RandomState(33)
    q = jnp.asarray(rs.randn(1, 4, 32, 16).astype(np.float32))
    k = jnp.asarray(rs.randn(1, 3, 32, 16).astype(np.float32))
    v = jnp.asarray(rs.randn(1, 3, 32, 16).astype(np.float32))
    with pytest.raises(ValueError, match="num_kv_heads"):
        flash_attention(q, k, v, causal=True)


def test_gqa_lse_surface_both_paths(monkeypatch):
    """The ring-attention (out, lse) surface under GQA: kernel path and
    the pure-jnp fallback agree, dk/dv group-summed in both."""
    from elasticdl_tpu.ops.attention import (
        attention_backward_lse,
        attention_forward_lse,
        expand_kv,
    )

    rs = np.random.RandomState(34)
    b, h, hkv, l, d = 2, 4, 2, 32, 128
    q = jnp.asarray(rs.randn(b, h, l, d).astype(np.float32) * 0.3)
    k = jnp.asarray(rs.randn(b, hkv, l, d).astype(np.float32) * 0.3)
    v = jnp.asarray(rs.randn(b, hkv, l, d).astype(np.float32) * 0.3)
    o_k, lse_k = attention_forward_lse(q, k, v, causal=True,
                                       block_q=16, block_k=16)
    g = jnp.ones_like(o_k)
    grads_k = attention_backward_lse(q, k, v, o_k, lse_k, g, causal=True,
                                     block_q=16, block_k=16)
    # jnp fallback path (kernels disabled; monkeypatch restores the env
    # at test end, after which only jnp-path asserts remain)
    monkeypatch.setenv("ELASTICDL_TPU_DISABLE_PALLAS", "1")
    o_j, lse_j = attention_forward_lse(q, k, v, causal=True)
    grads_j = attention_backward_lse(q, k, v, o_j, lse_j, g,
                                     causal=True)
    np.testing.assert_allclose(np.asarray(o_k), np.asarray(o_j),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(lse_k), np.asarray(lse_j),
                               rtol=1e-4, atol=1e-5)
    for gk, gj in zip(grads_k, grads_j):
        assert gk.shape == gj.shape
        np.testing.assert_allclose(np.asarray(gk), np.asarray(gj),
                                   rtol=1e-3, atol=1e-4)
    assert grads_k[1].shape == k.shape and grads_k[2].shape == v.shape


def test_gqa_sliding_window_gradients():
    """Windowed GQA through BOTH Pallas backward passes: the dkv
    kernel's remapped q-block index (qb = qi % n_q while the streamed
    dim enumerates (group, q_block) pairs) drives the window mask — a
    regression that masked with the raw streamed index would corrupt
    dk/dv here and nowhere else in the suite."""
    from elasticdl_tpu.ops.attention import expand_kv

    rs = np.random.RandomState(35)
    b, h, hkv, l, d = 1, 4, 2, 64, 128
    q = jnp.asarray(rs.randn(b, h, l, d).astype(np.float32) * 0.3)
    k = jnp.asarray(rs.randn(b, hkv, l, d).astype(np.float32) * 0.3)
    v = jnp.asarray(rs.randn(b, hkv, l, d).astype(np.float32) * 0.3)

    def loss_flash(q, k, v):
        return (
            flash_attention(q, k, v, causal=True, window=16, block_q=16,
                            block_k=16) ** 2
        ).sum()

    def loss_ref(q, k, v):
        return (
            naive_attention(q, expand_kv(k, h), expand_kv(v, h),
                            causal=True, window=16) ** 2
        ).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        assert gf.shape == gr.shape
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=1e-3, atol=1e-4)


def _pack_segments(b, l, seed=11):
    """Random packing: each row is 2-4 contiguous same-id runs."""
    rs = np.random.RandomState(seed)
    seg = np.zeros((b, l), np.int32)
    for r in range(b):
        cuts = np.sort(rs.choice(np.arange(8, l - 1), size=rs.randint(1, 4),
                                 replace=False))
        sid, prev = 0, 0
        for c in list(cuts) + [l]:
            seg[r, prev:c] = sid
            sid, prev = sid + 1, c
    return jnp.asarray(seg)


@pytest.mark.parametrize("causal", [False, True])
def test_segment_mask_blockwise_matches_naive(causal):
    q, k, v = _qkv(3)
    seg = _pack_segments(B, L)
    ref = naive_attention(q, k, v, causal=causal, segments=seg)
    out = blockwise_attention(q, k, v, causal=causal, block_size=16,
                              segments=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_segment_mask_flash_matches_naive(causal):
    """Packed-sequence masking through the Pallas kernel: the segment-id
    tiles must mask cross-segment blocks identically to the oracle,
    with segment boundaries landing INSIDE blocks (block 16, cuts
    anywhere)."""
    q, k, v = _qkv(4, l=64, d=128)
    seg = _pack_segments(B, 64)
    ref = naive_attention(q, k, v, causal=causal, segments=seg)
    out = flash_attention(q, k, v, causal=causal, block_q=16,
                          block_k=16, segments=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("hkv", [2, 1])
def test_segment_mask_flash_gradients(hkv):
    """Segment masking through BOTH Pallas backward kernels (dq and the
    group-summed dk/dv), including under GQA/MQA."""
    rs = np.random.RandomState(9)
    q = jnp.asarray(rs.randn(2, 2, 64, 128).astype(np.float32) * 0.3)
    k = jnp.asarray(rs.randn(2, hkv, 64, 128).astype(np.float32) * 0.3)
    v = jnp.asarray(rs.randn(2, hkv, 64, 128).astype(np.float32) * 0.3)
    seg = _pack_segments(2, 64)

    def loss_flash(q, k, v):
        return (
            flash_attention(q, k, v, causal=True, block_q=16,
                            block_k=16, segments=seg) ** 2
        ).sum()

    def loss_ref(q, k, v):
        return (
            naive_attention(q, k, v, causal=True, segments=seg) ** 2
        ).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=1e-3, atol=1e-4)


def test_segment_validation():
    q, k, v = _qkv(5, l=32, d=128)
    with pytest.raises(ValueError, match="batch, seq"):
        flash_attention(q, k, v, segments=jnp.zeros((B, 7), jnp.int32))
    rect_k = jnp.concatenate([k, k], axis=2)
    with pytest.raises(ValueError, match="square"):
        flash_attention(q, rect_k, rect_k,
                        segments=jnp.zeros((B, 32), jnp.int32))


@pytest.mark.parametrize("pos_emb", ["learned", "rope"])
@pytest.mark.slow
def test_packed_rows_match_unpacked_model(pos_emb):
    """End-to-end packing contract on the LM: a row packing two
    sequences (segment_ids + restarting positions) must produce the
    SAME logits as the two sequences run as separate rows."""
    from model_zoo.transformer_lm.transformer_lm import TransformerLM

    model = TransformerLM(
        vocab_size=32, seq_len=32, embed_dim=32, num_heads=2,
        num_layers=2, pos_emb=pos_emb, tp_shard=False,
    )
    rs = np.random.RandomState(0)
    seq_a = rs.randint(0, 32, size=(1, 16)).astype(np.int32)
    seq_b = rs.randint(0, 32, size=(1, 16)).astype(np.int32)
    packed = jnp.asarray(np.concatenate([seq_a, seq_b], axis=1))
    seg = jnp.asarray([[0] * 16 + [1] * 16], jnp.int32)
    params = model.init(jax.random.PRNGKey(0), {"tokens": packed})
    lp = model.apply(params, {"tokens": packed, "segment_ids": seg})
    la = model.apply(params, {"tokens": jnp.asarray(seq_a)})
    lb = model.apply(params, {"tokens": jnp.asarray(seq_b)})
    np.testing.assert_allclose(np.asarray(lp[:, :16]), np.asarray(la),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(lp[:, 16:]), np.asarray(lb),
                               rtol=2e-4, atol=2e-5)


def test_loss_ignores_negative_labels():
    """Packed boundaries mark cross-segment targets -100; the LM loss
    must average over valid tokens only."""
    from model_zoo.transformer_lm.transformer_lm import loss

    rs = np.random.RandomState(1)
    logits = jnp.asarray(rs.randn(2, 4, 8).astype(np.float32))
    labels = jnp.asarray([[1, 2, 3, 4], [5, 6, 7, 0]], jnp.int32)
    base = loss(labels, logits)
    # masking one target changes the average over the REMAINING ones
    masked = labels.at[0, 1].set(-100)
    got = loss(masked, logits)
    import optax as _optax
    tok = _optax.softmax_cross_entropy_with_integer_labels(
        logits, labels
    )
    row0 = (tok[0, [0, 2, 3]].mean(), tok[1].mean())
    np.testing.assert_allclose(
        float(got), float((row0[0] + row0[1]) / 2), rtol=1e-6
    )
    assert not np.isclose(float(base), float(got))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_rectangular_segment_pair(causal):
    """The (q_seg, k_seg) pair form on rectangular shapes — one ring
    rotation's geometry — through the Pallas kernels, vs the oracle.
    Rows with NO matching key in the k shard (ids 9) must come back
    EXACTLY 0 on both the Pallas and blockwise-fallback backends (the
    public contract), and flagged with the lse sentinel on the
    attention_forward_lse surface ring merges consume."""
    from elasticdl_tpu.ops import attention as attn_mod
    from elasticdl_tpu.ops.attention import attention_forward_lse

    rs = np.random.RandomState(21)
    q = jnp.asarray(rs.randn(2, 2, 32, 128).astype(np.float32) * 0.3)
    k = jnp.asarray(rs.randn(2, 2, 16, 128).astype(np.float32) * 0.3)
    v = jnp.asarray(rs.randn(2, 2, 16, 128).astype(np.float32) * 0.3)
    q_seg = jnp.asarray(
        np.concatenate([np.zeros((2, 12)), np.full((2, 10), 1),
                        np.full((2, 10), 9)], axis=1), jnp.int32)
    k_seg = jnp.asarray(
        np.concatenate([np.zeros((2, 8)), np.ones((2, 8))], axis=1),
        jnp.int32)
    ref = naive_attention(q, k, v, causal=causal,
                          segments=(q_seg, k_seg))
    out = flash_attention(q, k, v, causal=causal, block_q=16,
                          block_k=16, segments=(q_seg, k_seg))
    # blockwise fallback backend (block sizes that do not tile)
    out_bw = flash_attention(q, k, v, causal=causal, block_q=24,
                             block_k=24, segments=(q_seg, k_seg))
    visible = np.asarray(q_seg[0]) != 9
    for got in (out, out_bw):
        np.testing.assert_allclose(
            np.asarray(got)[:, :, visible],
            np.asarray(ref)[:, :, visible],
            rtol=1e-4, atol=1e-5,
        )
        np.testing.assert_array_equal(
            np.asarray(got)[:, :, ~visible], 0.0
        )
    out_lse, lse = attention_forward_lse(
        q, k, v, causal=causal, block_q=16, block_k=16,
        segments=(q_seg, k_seg)
    )
    np.testing.assert_allclose(
        np.asarray(out_lse)[:, :, visible],
        np.asarray(ref)[:, :, visible], rtol=1e-4, atol=1e-5,
    )
    masked_lse = np.asarray(lse)[:, :, ~visible]
    np.testing.assert_array_equal(
        masked_lse, np.float32(attn_mod._NEG_INF)
    )


@pytest.mark.parametrize("causal,window", [(True, None), (True, 16),
                                           (False, 16)])
@pytest.mark.slow
def test_cond_mask_matches_default(monkeypatch, causal, window):
    """EDL_FLASH_COND_MASK=1 branches the per-element mask out of
    interior blocks; outputs and gradients must equal the default
    straight-line-select path exactly."""
    rs = np.random.RandomState(77)
    q = jnp.asarray(rs.randn(2, 2, 64, 128).astype(np.float32) * 0.3)
    k = jnp.asarray(rs.randn(2, 2, 64, 128).astype(np.float32) * 0.3)
    v = jnp.asarray(rs.randn(2, 2, 64, 128).astype(np.float32) * 0.3)

    def run():
        def loss(q, k, v):
            return (flash_attention(
                q, k, v, causal=causal, window=window,
                block_q=16, block_k=16,
            ) ** 2).sum()

        out = flash_attention(q, k, v, causal=causal, window=window,
                              block_q=16, block_k=16)
        grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        return out, grads

    monkeypatch.delenv("EDL_FLASH_COND_MASK", raising=False)
    out_ref, g_ref = run()
    monkeypatch.setenv("EDL_FLASH_COND_MASK", "1")
    out_cond, g_cond = run()
    np.testing.assert_array_equal(np.asarray(out_ref),
                                  np.asarray(out_cond))
    for a, b in zip(g_ref, g_cond):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_stream_clamps_cover_every_running_block():
    """Property: the DMA-clamp ranges (which pin out-of-mask streamed
    blocks to a resident index) must contain EVERY block the kernels
    actually compute on — a clamp that excludes a run=True step would
    silently feed the wrong k/v (or q) tile. Brute-forced against
    _block_run over causal x window x block sizes x ring offsets."""
    from elasticdl_tpu.ops.attention import (
        _block_run,
        _kv_stream_clamp,
        _q_stream_clamp,
    )

    cases = 0
    for causal in (False, True):
        for window in (None, 8, 24, 64):
            for block_q, block_k in ((16, 16), (16, 32), (32, 16),
                                     (8, 64)):
                for lq, lk in ((64, 64), (128, 64), (64, 128)):
                    # offsets include fully-masked geometries (ring
                    # rotations where no block runs) on purpose: the
                    # clamps must still emit valid indices there
                    for pos_offset in (0, -64, 64, lk):
                        n_q, n_k = lq // block_q, lk // block_k
                        kv_cl = _kv_stream_clamp(
                            causal, window, block_q, block_k, n_k,
                            pos_offset,
                        )
                        q_cl = _q_stream_clamp(
                            causal, window, block_q, block_k, n_q,
                            pos_offset,
                        )
                        if kv_cl is None:
                            assert not causal and window is None
                            continue
                        for qi in range(n_q):
                            for ki in range(n_k):
                                if not bool(_block_run(
                                        qi, ki, block_q, block_k,
                                        causal, window, pos_offset)):
                                    continue
                                # a computing step must read its TRUE
                                # block on both streamed sides
                                assert int(kv_cl(qi, ki)) == ki, (
                                    causal, window, block_q, block_k,
                                    lq, lk, pos_offset, qi, ki,
                                )
                                assert int(q_cl(ki, qi)) == qi, (
                                    causal, window, block_q, block_k,
                                    lq, lk, pos_offset, qi, ki,
                                )
                                cases += 1
                        # and every clamped index is a valid block
                        for qi in range(n_q):
                            for t in range(n_k):
                                assert 0 <= int(kv_cl(qi, t)) < n_k
                        for ki in range(n_k):
                            for t in range(n_q):
                                assert 0 <= int(q_cl(ki, t)) < n_q
    assert cases > 1000  # the sweep actually exercised running blocks


def _packed_seg_for_ring(b, l, seed=31):
    """Packing whose segments CROSS shard boundaries on an 8-way ring
    (l=64 -> 8-token shards; cuts not at multiples of 8)."""
    rs = np.random.RandomState(seed)
    seg = np.zeros((b, l), np.int32)
    for r in range(b):
        cuts = sorted(rs.choice(np.arange(3, l - 1), size=3,
                                replace=False))
        sid, prev = 0, 0
        for c in list(cuts) + [l]:
            seg[r, prev:c] = sid
            sid, prev = sid + 1, c
    return jnp.asarray(seg)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.slow
def test_ring_attention_segments(causal):
    """Packed long-context: ring attention with sequence-sharded
    segment ids (k-side ids rotate with their shard) vs the oracle."""
    mesh = mesh_lib.build_mesh({"sp": 8})
    q, k, v = _qkv(24)
    seg = _packed_seg_for_ring(B, L)
    ref = naive_attention(q, k, v, causal=causal, segments=seg)
    out = ring_attention(q, k, v, mesh, causal=causal, segments=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_ring_attention_segments_gradients():
    mesh = mesh_lib.build_mesh({"sp": 8})
    q, k, v = _qkv(25)
    seg = _packed_seg_for_ring(B, L, seed=32)

    def loss_ring(q, k, v):
        return ring_attention(q, k, v, mesh, causal=True,
                              segments=seg).sum()

    def loss_ref(q, k, v):
        return naive_attention(q, k, v, causal=True,
                               segments=seg).sum()

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gr_, gn in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr_), np.asarray(gn),
                                   rtol=1e-3, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_segments(causal):
    from elasticdl_tpu.parallel.context_parallel import ulysses_attention

    mesh = mesh_lib.build_mesh({"dp": 4, "sp": 2})
    rs = np.random.RandomState(26)
    mk = lambda: jnp.asarray(rs.randn(4, 2, L, D).astype(np.float32))
    q, k, v = mk(), mk(), mk()
    seg = _packed_seg_for_ring(4, L, seed=33)
    ref = naive_attention(q, k, v, causal=causal, segments=seg)
    out = ulysses_attention(q, k, v, mesh, causal=causal, segments=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_flash_rectangular_pair_gradients():
    """Backward through the rectangular (q_seg, k_seg) pair with rows
    whose segment id is absent from the k shard: (a) with the masked
    rows excluded from the loss (how packed losses behave), kernel
    grads match the oracle; (b) with them included, grads stay finite
    and the masked rows contribute ZERO (the -1e30-class lse rows are
    forced to p=0 in both backward kernels — without that they would
    contaminate dk/dv with p=1 garbage)."""
    rs = np.random.RandomState(41)
    q = jnp.asarray(rs.randn(2, 2, 32, 128).astype(np.float32) * 0.3)
    k = jnp.asarray(rs.randn(2, 2, 16, 128).astype(np.float32) * 0.3)
    v = jnp.asarray(rs.randn(2, 2, 16, 128).astype(np.float32) * 0.3)
    q_seg = jnp.asarray(
        np.concatenate([np.zeros((2, 12)), np.ones((2, 10)),
                        np.full((2, 10), 9)], axis=1), jnp.int32)
    k_seg = jnp.asarray(
        np.concatenate([np.zeros((2, 8)), np.ones((2, 8))], axis=1),
        jnp.int32)
    visible = jnp.asarray((np.asarray(q_seg) != 9)[:, None, :, None])

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, block_q=16, block_k=16,
                              segments=(q_seg, k_seg))
        return (jnp.where(visible, out, 0.0) ** 2).sum()

    def loss_ref(q, k, v):
        out = naive_attention(q, k, v, segments=(q_seg, k_seg))
        return (jnp.where(visible, out, 0.0) ** 2).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=1e-3, atol=1e-4)

    # (b) loss reads every row, masked included: finite grads, zero
    # contribution from the fully-masked rows
    def loss_all(q, k, v):
        return (flash_attention(q, k, v, block_q=16, block_k=16,
                                segments=(q_seg, k_seg)) ** 2).sum()

    dq, dk, dv = jax.grad(loss_all, argnums=(0, 1, 2))(q, k, v)
    for g_ in (dq, dk, dv):
        assert np.isfinite(np.asarray(g_)).all()
    masked_dq = np.asarray(dq)[:, :, np.asarray(q_seg[0]) == 9]
    np.testing.assert_array_equal(masked_dq, 0.0)


@pytest.mark.slow
def test_flash_config_fuzz_vs_oracle(monkeypatch):
    """Seeded sweep across the kernel config lattice (causal x window x
    GQA x segments x block sizes x rectangular shapes x cond-mask) in
    interpret mode vs the naive oracle — forward always, gradients on a
    subset. Catches interaction bugs no single-feature test exercises."""
    rs = np.random.RandomState(123)
    for trial in range(10):
        monkeypatch.setenv(
            "EDL_FLASH_COND_MASK", "1" if rs.randint(2) else ""
        )
        causal = bool(rs.randint(2))
        lq = int(rs.choice([16, 32, 48]))
        rect = (not causal) and rs.randint(2)
        lk = int(rs.choice([16, 32])) if rect else lq
        h = int(rs.choice([2, 4]))
        hkv = int(rs.choice([g for g in (1, 2, h) if h % g == 0]))
        window = None
        if not rect and rs.randint(2):
            window = int(rs.choice([4, 8, lq]))
        use_seg = bool(rs.randint(2)) and not rect
        bq = int(rs.choice([8, 16, 32]))
        bk = int(rs.choice([8, 16]))
        q = jnp.asarray(rs.randn(2, h, lq, 128).astype(np.float32) * .3)
        k = jnp.asarray(
            rs.randn(2, hkv, lk, 128).astype(np.float32) * .3)
        v = jnp.asarray(
            rs.randn(2, hkv, lk, 128).astype(np.float32) * .3)
        seg = None
        if use_seg:
            cuts = np.sort(rs.choice(np.arange(2, lq - 1), size=2,
                                     replace=False))
            s = np.zeros((2, lq), np.int32)
            s[:, cuts[0]:cuts[1]] = 1
            s[:, cuts[1]:] = 2
            seg = jnp.asarray(s)
        tag = ("trial=%d causal=%s lq=%d lk=%d hkv=%d window=%s "
               "seg=%s bq=%d bk=%d"
               % (trial, causal, lq, lk, hkv, window, use_seg, bq, bk))
        ref = naive_attention(q, k, v, causal=causal, window=window,
                              segments=seg)
        out = flash_attention(q, k, v, causal=causal, window=window,
                              block_q=bq, block_k=bk, segments=seg)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5, err_msg=tag)
        if trial % 3 == 0:
            def lf(q, k, v):
                return (flash_attention(
                    q, k, v, causal=causal, window=window,
                    block_q=bq, block_k=bk, segments=seg) ** 2).sum()

            def lr(q, k, v):
                return (naive_attention(
                    q, k, v, causal=causal, window=window,
                    segments=seg) ** 2).sum()

            gf = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
            gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
            for a, b_ in zip(gf, gr):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b_), rtol=1e-3,
                    atol=1e-4, err_msg=tag)


@pytest.mark.parametrize("window", [4, 13, 24, 64])
@pytest.mark.slow
def test_ring_attention_window(window):
    """Causal sliding-window through the ring: rotation r applies the
    local window mask at static offset r*shard_len (causal auto-holds
    off-diagonal), band-empty rotations skip. Windows smaller than,
    straddling, and larger than the 8-token shards, vs the global
    oracle."""
    mesh = mesh_lib.build_mesh({"sp": 8})
    q, k, v = _qkv(51)
    ref = naive_attention(q, k, v, causal=True, window=window)
    out = ring_attention(q, k, v, mesh, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_ring_attention_window_gradients():
    mesh = mesh_lib.build_mesh({"sp": 8})
    q, k, v = _qkv(52)

    def loss_ring(q, k, v):
        return ring_attention(q, k, v, mesh, causal=True,
                              window=13).sum()

    def loss_ref(q, k, v):
        return naive_attention(q, k, v, causal=True, window=13).sum()

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gr_, gn in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr_), np.asarray(gn),
                                   rtol=1e-3, atol=1e-4)


@pytest.mark.slow
def test_ring_attention_window_with_segments():
    """Window AND packing compose through the ring."""
    mesh = mesh_lib.build_mesh({"sp": 8})
    q, k, v = _qkv(53)
    seg = _packed_seg_for_ring(B, L, seed=54)
    ref = naive_attention(q, k, v, causal=True, window=13,
                          segments=seg)
    out = ring_attention(q, k, v, mesh, causal=True, window=13,
                         segments=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("window", [4, 13, 30])
@pytest.mark.slow
def test_ring_attention_window_noncausal(window):
    """Two-sided (encoder) windows through the ring: signed-offset
    branches cover shards on BOTH sides of the diagonal; out-of-band
    rotations skip."""
    mesh = mesh_lib.build_mesh({"sp": 8})
    q, k, v = _qkv(55)
    ref = naive_attention(q, k, v, causal=False, window=window)
    out = ring_attention(q, k, v, mesh, causal=False, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_ring_attention_window_noncausal_gradients():
    mesh = mesh_lib.build_mesh({"sp": 8})
    q, k, v = _qkv(57)

    def loss_ring(q, k, v):
        return ring_attention(q, k, v, mesh, causal=False,
                              window=11).sum()

    def loss_ref(q, k, v):
        return naive_attention(q, k, v, causal=False, window=11).sum()

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gr_, gn in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr_), np.asarray(gn),
                                   rtol=1e-3, atol=1e-4)


def test_ulysses_attention_window():
    from elasticdl_tpu.parallel.context_parallel import ulysses_attention

    mesh = mesh_lib.build_mesh({"dp": 4, "sp": 2})
    rs = np.random.RandomState(56)
    mk = lambda: jnp.asarray(rs.randn(4, 2, L, D).astype(np.float32))
    q, k, v = mk(), mk(), mk()
    ref = naive_attention(q, k, v, causal=True, window=9)
    out = ulysses_attention(q, k, v, mesh, causal=True, window=9)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_ring_attention_window_noncausal_with_segments():
    """Two-sided window AND packing compose through the non-causal
    ring (the BertEncoder attn_window + packed path)."""
    mesh = mesh_lib.build_mesh({"sp": 8})
    q, k, v = _qkv(58)
    seg = _packed_seg_for_ring(B, L, seed=59)
    ref = naive_attention(q, k, v, causal=False, window=11,
                          segments=seg)
    out = ring_attention(q, k, v, mesh, causal=False, window=11,
                         segments=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_flash_bf16_matches_oracle_fwd_and_grads():
    """bf16 inputs are the ONLY dtype where _mxu_cast changes numerics
    (softmax weights / ds rounded to bf16 so p@V, ds@K, p@dO run at
    MXU bf16 rate) — so the bf16 path gets its own fwd+grad oracle
    check at bf16 tolerances (f32 tests are no-ops through the cast)."""
    rs = np.random.RandomState(11)
    mk = lambda: jnp.asarray(
        rs.randn(2, 2, 64, 128).astype(np.float32) * 0.3
    ).astype(jnp.bfloat16)
    q, k, v = mk(), mk(), mk()
    cot = jnp.asarray(
        rs.randn(2, 2, 64, 128).astype(np.float32) * 0.5
    )

    def f32(t):
        return t.astype(jnp.float32)

    out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
    ref = naive_attention(f32(q), f32(k), f32(v), causal=True)
    np.testing.assert_allclose(
        np.asarray(f32(out)), np.asarray(ref), rtol=0.05, atol=0.02
    )

    def loss_flash(q, k, v):
        return jnp.sum(f32(flash_attention(
            q, k, v, causal=True, block_q=16, block_k=16)) * cot)

    def loss_ref(q, k, v):
        return jnp.sum(naive_attention(f32(q), f32(k), f32(v),
                                       causal=True) * cot)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(f32(gf)), np.asarray(f32(gr)),
            rtol=0.1, atol=0.05,
            err_msg="bf16 flash grad d%s diverges from oracle" % name,
        )


def test_fully_masked_rows_chunked_matches_one_shot():
    """The chunked (fori_loop) visibility reduction must equal the
    single fused expression for every mask flavor, including ragged
    final chunks."""
    from elasticdl_tpu.ops.attention import _fully_masked_rows

    rs = np.random.RandomState(3)
    q_seg = jnp.asarray(rs.randint(0, 4, (2, 45)))
    k_seg = jnp.asarray(rs.randint(0, 4, (2, 83)))
    for causal in (False, True):
        for window in (None, 9):
            one = _fully_masked_rows(q_seg, k_seg, causal, window,
                                     45, 83)
            chunked = _fully_masked_rows(q_seg, k_seg, causal, window,
                                         45, 83, chunk=32)
            np.testing.assert_array_equal(np.asarray(one),
                                          np.asarray(chunked))


# ------------------------------------------- fused paged decode kernel
#
# The CPU interpret=True parity battery for _paged_decode_fused (the
# autouse fixture above sets FORCE_INTERPRET=1, so use_kernel=True runs
# the REAL kernel body through the Pallas interpreter). Two oracles:
# the lax.scan path of paged_decode_attention itself (bit-for-bit the
# shared masks/merge, only reduction order differs) and naive_attention
# over the logically contiguous cache (independent math). Every case
# includes a drop-lane row (length=0, all-(-1) table) — the masked
# lanes the serving engine scatters between seated requests.


def _rowquant(rows):
    """Symmetric per-row int8 + f32 scale, matching the serving
    quantizer's layout (scale leaf on the trailing axis)."""
    sc = (np.abs(rows).max(-1, keepdims=True) / 127.0
          + 1e-8).astype(np.float32)
    q8 = np.clip(np.round(rows / sc), -127, 127).astype(np.int8)
    return q8, sc


def _paged_case(seed, b, h, hkv, t, d, bs, nb, m, quantized):
    """Pools + scattered -1-padded table + current tile; row 0 is the
    drop lane (nothing cached, no blocks)."""
    rs = np.random.RandomState(seed)
    q = rs.randn(b, h, t, d).astype(np.float32)
    k_cur = rs.randn(b, hkv, t, d).astype(np.float32)
    v_cur = rs.randn(b, hkv, t, d).astype(np.float32)
    k_pool = rs.randn(nb, bs, hkv, d).astype(np.float32)
    v_pool = rs.randn(nb, bs, hkv, d).astype(np.float32)
    length = rs.randint(1, m * bs + 1, size=(b,)).astype(np.int32)
    length[0] = 0  # drop lane
    table = np.full((b, m), -1, np.int32)
    order = rs.permutation(nb)
    ptr = 0
    for i in range(b):
        for j in range(-(-int(length[i]) // bs)):
            table[i, j] = order[ptr % nb]
            ptr += 1
    kwargs = dict(window=None)
    if quantized:
        k_pool, ksp = _rowquant(k_pool)
        v_pool, vsp = _rowquant(v_pool)
        k_cur, kcs = _rowquant(k_cur)
        v_cur, vcs = _rowquant(v_cur)
        kwargs.update(
            k_scale_pool=jnp.asarray(ksp), v_scale_pool=jnp.asarray(vsp),
            k_cur_scale=jnp.asarray(kcs), v_cur_scale=jnp.asarray(vcs),
        )
    args = tuple(jnp.asarray(a) for a in
                 (q, k_cur, v_cur, k_pool, v_pool, table, length))
    return args, kwargs


@pytest.mark.parametrize("quantized", (False, True),
                         ids=("fp32", "int8"))
@pytest.mark.parametrize("t", (1, 3))
@pytest.mark.parametrize("window", (None, 5))
@pytest.mark.parametrize("h,hkv", ((4, 4), (4, 2)),
                         ids=("mha", "gqa"))
def test_paged_fused_matches_scan_oracle(h, hkv, window, t, quantized):
    """use_kernel=True vs use_kernel=False on identical inputs: the
    two paths share _paged_valid/_tile_causal_mask and the tile merge,
    so any drift is a kernel bug, not a mask disagreement. t=1 runs
    the legacy [b, h, d] squeeze shape."""
    from elasticdl_tpu.ops.attention import paged_decode_attention

    args, kwargs = _paged_case(
        seed=17 * t + hkv, b=3, h=h, hkv=hkv, t=t, d=8, bs=4, nb=12,
        m=3, quantized=quantized,
    )
    kwargs["window"] = window
    if t == 1:  # legacy single-token shape (and its scale shapes)
        q, k_cur, v_cur = (a[:, :, 0] for a in args[:3])
        args = (q, k_cur, v_cur) + args[3:]
        for key in ("k_cur_scale", "v_cur_scale"):
            if key in kwargs:
                kwargs[key] = kwargs[key][:, :, 0]
    scan = paged_decode_attention(*args, use_kernel=False, **kwargs)
    fused = paged_decode_attention(*args, use_kernel=True, **kwargs)
    assert fused.shape == scan.shape
    np.testing.assert_allclose(
        np.asarray(fused), np.asarray(scan), rtol=2e-5, atol=2e-5,
        err_msg="h=%d hkv=%d window=%r t=%d quantized=%r"
                % (h, hkv, window, t, quantized),
    )


@pytest.mark.parametrize("quantized", (False, True),
                         ids=("fp32", "int8"))
@pytest.mark.parametrize("window", (None, 4))
def test_paged_fused_matches_naive(window, quantized):
    """Independent oracle: gather each row's cache contiguously
    (table order, dequantized for int8 — the kernel's in-register
    dequant is exact, so parity carries no quantization slack), append
    the current tile, and run naive_attention causally over the full
    sequence; the last t rows must equal the fused output."""
    from elasticdl_tpu.ops.attention import paged_decode_attention

    b, h, hkv, t, d, bs, nb, m = 3, 4, 2, 3, 8, 4, 12, 3
    args, kwargs = _paged_case(
        seed=5 if quantized else 6, b=b, h=h, hkv=hkv, t=t, d=d,
        bs=bs, nb=nb, m=m, quantized=quantized,
    )
    kwargs["window"] = window
    fused = np.asarray(
        paged_decode_attention(*args, use_kernel=True, **kwargs)
    )
    q, k_cur, v_cur, k_pool, v_pool, table, length = (
        np.asarray(a) for a in args
    )
    if quantized:
        k_pool = k_pool * np.asarray(kwargs["k_scale_pool"])
        v_pool = v_pool * np.asarray(kwargs["v_scale_pool"])
        k_cur = k_cur * np.asarray(kwargs["k_cur_scale"])
        v_cur = v_cur * np.asarray(kwargs["v_cur_scale"])
    for i in range(b):
        ln = int(length[i])
        rows_k = np.concatenate(
            [k_pool[bid] for bid in table[i] if bid >= 0]
            or [np.zeros((0, bs, hkv, d), np.float32).reshape(0, hkv, d)]
        )[:ln]
        rows_v = np.concatenate(
            [v_pool[bid] for bid in table[i] if bid >= 0]
            or [np.zeros((0, bs, hkv, d), np.float32).reshape(0, hkv, d)]
        )[:ln]
        # [ln + t, hkv, d] -> [1, hkv, ln + t, d]
        keys = np.concatenate(
            [rows_k, k_cur[i].transpose(1, 0, 2)]
        ).transpose(1, 0, 2)[None]
        vals = np.concatenate(
            [rows_v, v_cur[i].transpose(1, 0, 2)]
        ).transpose(1, 0, 2)[None]
        # tail-align the tile in a full-length causal query: rows
        # [ln, ln + t) get the tile's queries (the prefix rows carry
        # zeros — their outputs are ignored), so naive's square causal
        # + window mask at those rows IS the decode visibility
        q_full = np.zeros((1, h, ln + t, d), np.float32)
        q_full[:, :, ln:] = q[i]
        ref = np.asarray(naive_attention(
            jnp.asarray(q_full), jnp.asarray(keys), jnp.asarray(vals),
            causal=True, window=window, scale=d ** -0.5,
        ))[0, :, ln:]
        np.testing.assert_allclose(
            fused[i], ref, rtol=2e-5, atol=2e-5,
            err_msg="row %d window=%r int8=%r" % (i, window, quantized),
        )
