"""End-to-end serving tests on the CPU mesh (drills shard).

The acceptance battery for the online serving subsystem: a real gRPC
server over the continuous-batching engine, ≥32 concurrent requests
with mixed prompt/output lengths whose tokens must equal the offline
`autoregressive_generate` for the same knobs, demonstrable
interleaving (slot occupancy > 1 while the queue drains), hot
checkpoint reload mid-stream without dropping in-flight requests, and
overload/shutdown semantics that terminate every request with a clean
status."""

import os
import threading
import time

import numpy as np
import pytest

import jax

from elasticdl_tpu.api.generation import autoregressive_generate
from elasticdl_tpu.checkpoint.saver import CheckpointSaver
from elasticdl_tpu.common.model_utils import load_model_spec_from_module
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.proto import elasticdl_pb2 as pb
from elasticdl_tpu.proto.service import ServingStub, build_channel
from elasticdl_tpu.serving import GenerationServer, ServingConfig
from elasticdl_tpu.training.trainer import Trainer
from model_zoo.transformer_lm import transformer_lm as zoo

pytestmark = pytest.mark.slow

PARAMS = (
    "vocab_size=8; seq_len=16; embed_dim=32; num_heads=2; num_layers=1"
)


def _trainer(seed=0):
    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    return Trainer(
        load_model_spec_from_module(zoo), mesh=mesh,
        model_params=PARAMS, seed=seed,
    )


def _state(trainer):
    toks = (np.arange(17)[None, :] % 8).astype(np.int32)
    return trainer.init_state(
        ({"tokens": toks[:, :-1]}, toks[:, 1:])
    )


@pytest.fixture(scope="module")
def rig():
    trainer = _trainer()
    state = _state(trainer)
    return trainer, state


def _start(trainer, state, **cfg_kwargs):
    cfg = ServingConfig(**cfg_kwargs)
    return GenerationServer(trainer, state, cfg).start()


def test_concurrent_requests_match_offline_and_interleave(rig, tmp_path):
    """≥32 concurrent mixed-length requests; every response must be
    token-identical to the offline decoder with the same (prompt, seed,
    temperature); the pool must demonstrably interleave."""
    trainer, state = rig
    server = _start(
        trainer, state, num_slots=4, queue_capacity=64,
        telemetry_dir=str(tmp_path),
    )
    try:
        stub = ServingStub(build_channel("localhost:%d" % server.port))
        specs = []
        for i in range(32):
            prompt = [int(x) for x in np.arange(1 + i % 4) % 8 + 1]
            specs.append({
                "prompt": prompt,
                "new": 3 + i % 7,
                "temperature": 0.0 if i % 3 == 0 else 1.0,
                "seed": i,
            })
        results = {}
        errors = {}

        def call(i, s):
            try:
                r = stub.generate(
                    pb.GenerateRequest(
                        prompt=s["prompt"], max_new_tokens=s["new"],
                        temperature=s["temperature"], seed=s["seed"],
                    ),
                    timeout=120,
                )
                results[i] = list(r.tokens)
            except Exception as e:  # noqa: BLE001
                errors[i] = e

        threads = [
            threading.Thread(target=call, args=(i, s))
            for i, s in enumerate(specs)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert not errors, errors
        assert len(results) == 32
        for i, s in enumerate(specs):
            off = np.asarray(autoregressive_generate(
                trainer, state, np.asarray([s["prompt"]], np.int32),
                s["new"], temperature=s["temperature"], seed=s["seed"],
                use_cache=True,
            ))[0]
            assert list(off) == results[i], (i, s, off, results[i])
        st = stub.server_status(pb.ServerStatusRequest(), timeout=10)
        # continuous batching demonstrably interleaved: more than one
        # slot decoded at once while the queue drained
        assert st.max_active_slots > 1
        assert st.completed == 32 and st.admitted == 32
        assert st.tokens_generated >= sum(s["new"] for s in specs)
    finally:
        server.stop()


def test_greedy_matches_full_recompute_offline(rig):
    """The serving path must agree with BOTH offline strategies for
    greedy decode (full-recompute == KV == serving)."""
    trainer, state = rig
    server = _start(trainer, state, num_slots=2, queue_capacity=8)
    try:
        stub = ServingStub(build_channel("localhost:%d" % server.port))
        r = stub.generate(
            pb.GenerateRequest(prompt=[1, 2, 3], max_new_tokens=6),
            timeout=60,
        )
        off_full = np.asarray(autoregressive_generate(
            trainer, state, np.asarray([[1, 2, 3]], np.int32), 6,
        ))[0]
        assert list(off_full) == list(r.tokens)
    finally:
        server.stop()


def test_streaming_chunks_and_ttft(rig, tmp_path):
    trainer, state = rig
    server = _start(
        trainer, state, num_slots=2, queue_capacity=8,
        telemetry_dir=str(tmp_path),
    )
    try:
        stub = ServingStub(build_channel("localhost:%d" % server.port))
        chunks = list(stub.generate_stream(
            pb.GenerateRequest(prompt=[1, 2], max_new_tokens=5),
            timeout=60,
        ))
        toks = [t for c in chunks for t in c.tokens]
        assert len(toks) == 5
        assert chunks[-1].done and not chunks[-1].tokens
        off = np.asarray(autoregressive_generate(
            trainer, state, np.asarray([[1, 2]], np.int32), 5,
            use_cache=True,
        ))[0]
        assert list(off[2:]) == toks
    finally:
        server.stop()


def test_hot_reload_swaps_params_mid_stream(rig, tmp_path):
    """A checkpoint landing mid-decode swaps params between steps: the
    in-flight stream keeps running (no drop), later requests decode
    under the new version, and the version gauge moves."""
    trainer, state = rig
    ckpt_dir = str(tmp_path / "ckpt")
    server = _start(
        trainer, state, num_slots=2, queue_capacity=8,
        checkpoint_dir=ckpt_dir, reload_poll_secs=0.05,
    )
    try:
        stub = ServingStub(build_channel("localhost:%d" % server.port))
        # long-running stream to straddle the reload
        stream = stub.generate_stream(
            pb.GenerateRequest(prompt=[1], max_new_tokens=14),
            timeout=120,
        )
        first = next(stream)
        assert first.model_version == 0
        # new params under a new version, written mid-stream
        trainer2 = _trainer(seed=123)
        state2 = _state(trainer2).replace(step=jax.numpy.asarray(7))
        CheckpointSaver(ckpt_dir, checkpoint_steps=1).save(state2, 7)
        chunks = [first] + list(stream)
        toks = [t for c in chunks for t in c.tokens]
        assert len(toks) == 14  # nothing dropped
        # wait until the reload has landed (a straddling request can
        # legitimately mix versions — its version field reports the
        # params that produced its LAST token)...
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            r = stub.generate(
                pb.GenerateRequest(prompt=[1, 2, 3], max_new_tokens=4),
                timeout=60,
            )
            if r.model_version == 7:
                break
        assert r.model_version == 7
        # ...then a fresh request runs FULLY on the reloaded params and
        # must be token-identical to offline decode with them
        r2 = stub.generate(
            pb.GenerateRequest(prompt=[1, 2, 3], max_new_tokens=4),
            timeout=60,
        )
        assert r2.model_version == 7
        off = np.asarray(autoregressive_generate(
            trainer, state2, np.asarray([[1, 2, 3]], np.int32), 4,
            use_cache=True,
        ))[0]
        assert list(off) == list(r2.tokens)
        st = stub.server_status(pb.ServerStatusRequest(), timeout=10)
        assert st.model_version == 7 and st.reloads >= 1
    finally:
        server.stop()


def test_backpressure_rejects_overload_cleanly(rig):
    """Overload: a tiny queue must reject the excess with
    RESOURCE_EXHAUSTED immediately; admitted requests complete; no
    request rides the client timeout (no hangs)."""
    import grpc

    trainer, state = rig
    server = _start(trainer, state, num_slots=1, queue_capacity=2)
    try:
        stub = ServingStub(build_channel("localhost:%d" % server.port))
        outcomes = []
        lock = threading.Lock()

        def call(i):
            try:
                stub.generate(
                    pb.GenerateRequest(
                        prompt=[1, 2], max_new_tokens=12,
                    ),
                    timeout=90,
                )
                code = "OK"
            except grpc.RpcError as e:
                code = e.code().name
            with lock:
                outcomes.append(code)

        threads = [
            threading.Thread(target=call, args=(i,)) for i in range(12)
        ]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        elapsed = time.monotonic() - t0
        assert len(outcomes) == 12  # every request terminated
        assert elapsed < 90  # ...and none rode the client timeout
        assert set(outcomes) <= {"OK", "RESOURCE_EXHAUSTED"}, outcomes
        assert outcomes.count("OK") >= 1
        # 12 near-simultaneous submits into 1 slot + 2 queue places
        # must shed load
        assert outcomes.count("RESOURCE_EXHAUSTED") >= 1
    finally:
        server.stop()


def test_deadline_exceeded_behind_slow_request(rig):
    """A short-deadline request queued behind a long decode must get
    DEADLINE_EXCEEDED (queued expiry or mid-decode eviction), never a
    hang; partial streams keep their tokens."""
    import grpc

    trainer, state = rig
    server = _start(trainer, state, num_slots=1, queue_capacity=8)
    try:
        stub = ServingStub(build_channel("localhost:%d" % server.port))
        long_done = {}

        def long_call():
            r = stub.generate(
                pb.GenerateRequest(prompt=[1], max_new_tokens=14),
                timeout=90,
            )
            long_done["tokens"] = len(r.tokens)

        t = threading.Thread(target=long_call)
        t.start()
        deadline = time.monotonic() + 30
        while (server.engine.active_count() == 0
               and time.monotonic() < deadline):
            time.sleep(0.005)
        with pytest.raises(grpc.RpcError) as e:
            stub.generate(
                pb.GenerateRequest(
                    prompt=[2], max_new_tokens=14, deadline_ms=5
                ),
                timeout=90,
            )
        assert e.value.code() == grpc.StatusCode.DEADLINE_EXCEEDED
        t.join(timeout=120)
        assert long_done.get("tokens") == 15  # the long one was unharmed
        st = stub.server_status(pb.ServerStatusRequest(), timeout=10)
        assert st.expired >= 1
    finally:
        server.stop()


def test_graceful_stop_drains_active_rejects_queued(rig):
    """stop(drain=True): in-flight slots run to completion; the queued
    backlog gets RESOURCE_EXHAUSTED. The kill-drill invariant, in-proc."""
    import grpc

    trainer, state = rig
    server = _start(trainer, state, num_slots=1, queue_capacity=8)
    try:
        stub = ServingStub(build_channel("localhost:%d" % server.port))
        outcomes = {}

        def call(i):
            try:
                r = stub.generate(
                    pb.GenerateRequest(
                        prompt=[1 + i % 3], max_new_tokens=12
                    ),
                    timeout=90,
                )
                outcomes[i] = ("OK", len(r.tokens))
            except grpc.RpcError as e:
                outcomes[i] = (e.code().name, 0)

        threads = [
            threading.Thread(target=call, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        # let the first request seat, then pull the plug
        deadline = time.monotonic() + 30
        while (server.engine.active_count() == 0
               and time.monotonic() < deadline):
            time.sleep(0.01)
        server.stop(drain=True)
        for t in threads:
            t.join(timeout=120)
        assert len(outcomes) == 4
        codes = [c for c, _ in outcomes.values()]
        assert set(codes) <= {"OK", "RESOURCE_EXHAUSTED"}, outcomes
        # the seated request completed with its full token budget
        ok = [n for c, n in outcomes.values() if c == "OK"]
        assert ok and all(n >= 12 for n in ok)
    finally:
        server.stop()


def test_fault_injection_error_at_serving_boundary(rig):
    """EDL_FAULT_SPEC-style rules fire on the serving RPC surface over
    real gRPC: an injected error surfaces as UNAVAILABLE to the client
    and the next call succeeds."""
    import grpc

    from elasticdl_tpu.common.fault_injection import FaultInjector

    trainer, state = rig
    cfg = ServingConfig(num_slots=1, queue_capacity=4)
    server = GenerationServer(
        trainer, state, cfg,
        injector=FaultInjector(spec="generate:drop:1"),
    ).start()
    try:
        stub = ServingStub(build_channel("localhost:%d" % server.port))
        with pytest.raises(grpc.RpcError) as e:
            stub.generate(
                pb.GenerateRequest(prompt=[1], max_new_tokens=2),
                timeout=30,
            )
        assert e.value.code() == grpc.StatusCode.UNAVAILABLE
        r = stub.generate(
            pb.GenerateRequest(prompt=[1], max_new_tokens=2), timeout=60
        )
        assert len(r.tokens) == 3
    finally:
        server.stop()


def test_paged_engine_matches_dense_and_offline_concurrent(rig):
    """The block-paged pool must be TOKEN-EXACT with the dense engine
    and offline decode: 32 concurrent mixed-length requests against a
    paged server (tight block budget, slots > dense-equivalent) vs the
    same requests against a dense server vs offline
    autoregressive_generate — three identical streams per request."""
    trainer, state = rig

    def collect(server):
        stub = ServingStub(build_channel("localhost:%d" % server.port))
        specs = []
        for i in range(32):
            prompt = [int(x) for x in np.arange(1 + i % 4) % 8 + 1]
            specs.append({
                "prompt": prompt,
                "new": 3 + i % 7,
                "temperature": 0.0 if i % 3 == 0 else 1.0,
                "seed": i,
            })
        results, errors = {}, {}

        def call(i, s):
            try:
                r = stub.generate(
                    pb.GenerateRequest(
                        prompt=s["prompt"], max_new_tokens=s["new"],
                        temperature=s["temperature"], seed=s["seed"],
                    ),
                    timeout=120,
                )
                results[i] = list(r.tokens)
            except Exception as e:  # noqa: BLE001
                errors[i] = e

        threads = [
            threading.Thread(target=call, args=(i, s))
            for i, s in enumerate(specs)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert not errors, errors
        assert len(results) == 32
        return specs, results

    paged = _start(
        trainer, state, num_slots=6, queue_capacity=64,
        kv_paged=True, kv_block_size=4, kv_num_blocks=16,
    )
    try:
        specs, paged_results = collect(paged)
        stub = ServingStub(build_channel("localhost:%d" % paged.port))
        st = stub.server_status(pb.ServerStatusRequest(), timeout=10)
        assert st.kv_paged and st.kv_blocks_total == 16
        assert st.max_active_slots > 1  # interleaving under paging
        assert st.kv_blocks_free == 16  # everything reclaimed
        assert st.kv_bytes_in_use_peak > 0
    finally:
        paged.stop()
    dense = _start(trainer, state, num_slots=4, queue_capacity=64)
    try:
        _, dense_results = collect(dense)
    finally:
        dense.stop()
    for i, s in enumerate(specs):
        off = np.asarray(autoregressive_generate(
            trainer, state, np.asarray([s["prompt"]], np.int32),
            s["new"], temperature=s["temperature"], seed=s["seed"],
            use_cache=True,
        ))[0]
        assert list(off) == paged_results[i], (i, s)
        assert dense_results[i] == paged_results[i], (i, s)


def test_paged_out_of_blocks_is_backpressure_not_crash(rig):
    """A block budget that fits ~one request at a time: excess
    requests WAIT (admission backpressure via the fit predicate) and
    complete serially as completions free blocks — nothing crashes,
    nothing is rejected below queue capacity, and the pool drains back
    to whole."""
    trainer, state = rig
    # 4 blocks x 4 tokens = 16 cache rows total; each request needs
    # 1 + 12 - 1 = 12 rows (3 blocks), so no two can overlap fully
    server = _start(
        trainer, state, num_slots=3, queue_capacity=8,
        kv_paged=True, kv_block_size=4, kv_num_blocks=4,
    )
    try:
        stub = ServingStub(build_channel("localhost:%d" % server.port))
        outcomes = {}

        def call(i):
            r = stub.generate(
                pb.GenerateRequest(prompt=[1 + i], max_new_tokens=12),
                timeout=120,
            )
            outcomes[i] = list(r.tokens)

        threads = [
            threading.Thread(target=call, args=(i,)) for i in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert len(outcomes) == 3
        for i in range(3):
            off = np.asarray(autoregressive_generate(
                trainer, state, np.asarray([[1 + i]], np.int32), 12,
                use_cache=True,
            ))[0]
            assert list(off) == outcomes[i]
        st = stub.server_status(pb.ServerStatusRequest(), timeout=10)
        assert st.completed == 3 and st.rejected == 0
        assert st.kv_blocks_free == st.kv_blocks_total == 4
        # a request larger than the WHOLE budget is invalid, fast
        import grpc

        with pytest.raises(grpc.RpcError) as e:
            stub.generate(
                pb.GenerateRequest(prompt=[1, 2, 3], max_new_tokens=15),
                timeout=30,
            )
        assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    finally:
        server.stop()


def test_paged_blocks_reclaimed_on_deadline_eviction(rig):
    """evict_expired must return a mid-decode casualty's blocks to the
    free list (reclamation on evict), and later requests must reuse
    them correctly."""
    from elasticdl_tpu.serving.admission import ServingRequest
    from elasticdl_tpu.serving.engine import (
        PagedContinuousBatchingEngine,
    )

    trainer, state = rig
    eng = PagedContinuousBatchingEngine(
        trainer, state, num_slots=2, block_size=4, num_blocks=6,
    )
    doomed = ServingRequest([1, 2], 10, deadline_ms=1)
    eng.insert(doomed)
    eng.step()
    assert eng.kv.allocator.blocks_in_use() > 0
    evicted = eng.evict_expired(now=doomed.deadline + 1.0)
    assert evicted == [doomed]
    assert eng.kv.allocator.blocks_in_use() == 0
    assert eng.kv.allocator.num_free() == 6
    assert (eng.kv.tables == -1).all()
    # the freed blocks serve a fresh request, token-exact vs offline
    fresh = ServingRequest([3, 4], 6)
    eng.insert(fresh)
    while eng.active_count():
        eng.step()
    off = np.asarray(autoregressive_generate(
        trainer, state, np.asarray([[3, 4]], np.int32), 6,
        use_cache=True,
    ))[0]
    assert list(off[2:]) == fresh.generated
    assert eng.kv.allocator.num_free() == 6


def test_serving_telemetry_event_file_written(rig, tmp_path):
    trainer, state = rig
    server = _start(
        trainer, state, num_slots=2, queue_capacity=8,
        telemetry_dir=str(tmp_path), telemetry_flush_every=1,
    )
    try:
        stub = ServingStub(build_channel("localhost:%d" % server.port))
        stub.generate(
            pb.GenerateRequest(prompt=[1, 2], max_new_tokens=4),
            timeout=60,
        )
    finally:
        server.stop()
    files = [f for f in os.listdir(str(tmp_path))
             if f.startswith("events.out.tfevents")]
    assert files, os.listdir(str(tmp_path))
    assert os.path.getsize(os.path.join(str(tmp_path), files[0])) > 0


def _run_paged_int8_shared_spec_32way():
    """Body of the int8-arena acceptance pin, shared by the scan-path
    test and the fused-kernel variant below (which reroutes
    paged_decode_attention before calling this)."""
    int8_params = PARAMS + "; kv_cache_dtype='int8'"
    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = Trainer(
        load_model_spec_from_module(zoo), mesh=mesh,
        model_params=int8_params,
    )
    state = _state(trainer)
    draft_trainer = _trainer(seed=321)  # float draft, mismatched
    draft_state = _state(draft_trainer)

    systems = [[1, 2, 3, 4], [5, 6, 7, 1, 2, 3, 4, 5]]
    specs = []
    for i in range(32):
        prompt = list(systems[i % 2]) + ([1 + i % 3] if i % 4 else [])
        specs.append({"prompt": prompt, "new": 3 + i % 5})

    cfg = ServingConfig(
        num_slots=6, queue_capacity=64, kv_paged=True,
        kv_block_size=4, kv_num_blocks=24, kv_shared=True, draft_k=2,
    )
    server = GenerationServer(
        trainer, state, cfg, draft=(draft_trainer, draft_state)
    ).start()
    try:
        stub = ServingStub(build_channel("localhost:%d" % server.port))
        results, errors = {}, {}

        def call(i, s):
            try:
                r = stub.generate(
                    pb.GenerateRequest(
                        prompt=s["prompt"], max_new_tokens=s["new"],
                    ),
                    timeout=120,
                )
                results[i] = list(r.tokens)
            except Exception as e:  # noqa: BLE001
                errors[i] = e

        threads = [
            threading.Thread(target=call, args=(i, s))
            for i, s in enumerate(specs)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert not errors, errors
        assert len(results) == 32
        st = stub.server_status(pb.ServerStatusRequest(), timeout=10)
        assert st.kv_paged and st.kv_shared
        assert st.kv_cache_dtype == "int8"
        assert st.prefix_hit_tokens > 0  # sharing engaged over int8
        assert st.draft_k == 2 and st.draft_proposed > 0
        assert st.max_active_slots > 1
        # clean post-drain ledger with scale leaves in the arenas
        assert st.kv_blocks_free == st.kv_blocks_total == 24
        assert st.completed == 32
        # the byte accounting counts TRUE arena bytes (int8 rows + f32
        # scales): strictly between the pure-int8 and pure-f32 figures
        eng = server.engine
        rows = eng.kv.num_blocks * eng.kv.block_size
        hkv = trainer.model.num_kv_heads or trainer.model.num_heads
        d = trainer.model.embed_dim // trainer.model.num_heads
        layers = trainer.model.num_layers
        expect = rows * layers * 2 * hkv * (d + 4)  # int8 rows + scales
        assert st.kv_bytes_total == expect
    finally:
        server.stop()

    for i, s in enumerate(specs):
        off = np.asarray(autoregressive_generate(
            trainer, state, np.asarray([s["prompt"]], np.int32),
            s["new"], use_cache=True,
        ))[0]
        assert list(off) == results[i], (i, s)


def test_paged_int8_shared_spec_matches_offline_int8_32way():
    """The int8-arena acceptance pin: 32 concurrent GREEDY requests
    drawn from a small system-prompt pool against a paged + shared +
    speculative server whose arenas are INT8 (kv_cache_dtype='int8',
    mismatched draft so rollback exercises) — every token stream must
    equal offline `autoregressive_generate(use_cache=True)` on the
    SAME int8 model (the int8 dense oracle: same quantizer, so parity
    carries no quantization slack). The post-drain ledger must be
    clean with scale leaves in the arenas, and ServerStatus must
    advertise the format."""
    _run_paged_int8_shared_spec_32way()


def test_paged_int8_32way_token_exact_with_fused_kernel(monkeypatch):
    """Serving-level pin for the fused paged decode kernel: the SAME
    32-way paged + shared + spec + int8 battery, but with
    paged_decode_attention routed through _paged_decode_fused (forced
    on via use_paged_kernel; interpret_mode() makes the Pallas call
    interpret on CPU, so the real kernel body runs inside the jitted
    serving step). Token streams must stay EXACTLY equal to the dense
    int8 offline oracle — the kernel may differ from the scan only in
    fp reduction order, and greedy argmax over a real vocab gap
    doesn't flip on that. The spy proves the kernel actually traced
    into the serving step rather than silently falling back."""
    import elasticdl_tpu.ops.attention as attn_mod

    calls = {"n": 0}
    real = attn_mod._paged_decode_fused

    def spy(*a, **k):
        calls["n"] += 1
        return real(*a, **k)

    monkeypatch.setattr(attn_mod, "_paged_decode_fused", spy)
    monkeypatch.setattr(attn_mod, "use_paged_kernel", lambda: True)
    _run_paged_int8_shared_spec_32way()
    assert calls["n"] > 0, "fused kernel never engaged in the server"


def test_host_tier_spill_revive_matches_offline_int8_32way():
    """The tiered-KV acceptance pin: 32 concurrent GREEDY requests
    over a small system-prompt pool against a paged + shared +
    speculative + INT8 server whose device pool is deliberately too
    small for the prefix working set plus the active seats — chains
    are forced to EVICT mid-run, spill to the host tier, and revive by
    upload — and every token stream must still equal offline
    `autoregressive_generate(use_cache=True)` on the same int8 model.
    The drill-grade ledger must drain clean in BOTH tiers, the host
    tier must never exceed its byte budget, and ServerStatus must show
    the spill machinery actually engaged (revive_uploads > 0)."""
    int8_params = PARAMS + "; kv_cache_dtype='int8'"
    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = Trainer(
        load_model_spec_from_module(zoo), mesh=mesh,
        model_params=int8_params,
    )
    state = _state(trainer)
    draft_trainer = _trainer(seed=321)  # float draft, mismatched
    draft_state = _state(draft_trainer)

    systems = [[1, 2, 3, 4], [5, 6, 7, 1, 2, 3, 4, 5]]
    specs = []
    for i in range(32):
        prompt = list(systems[i % 2]) + ([1 + i % 3] if i % 4 else [])
        specs.append({"prompt": prompt, "new": 3 + i % 5})

    # 8 blocks x 4 tokens: two concurrent seats of the long-prompt
    # shape (4 blocks committed each) consume the WHOLE pool, so the
    # reclaimable prefix chains (3 blocks) are forced to evict — and
    # spill — mid-run, then revive when the next wave re-matches them;
    # the host budget holds the whole working set
    host_budget = 1 << 20
    cfg = ServingConfig(
        num_slots=4, queue_capacity=64, kv_paged=True,
        kv_block_size=4, kv_num_blocks=8, kv_shared=True, draft_k=2,
        kv_host_bytes=host_budget,
    )
    server = GenerationServer(
        trainer, state, cfg, draft=(draft_trainer, draft_state)
    ).start()
    try:
        stub = ServingStub(build_channel("localhost:%d" % server.port))
        results, errors = {}, {}

        def call(i, s):
            try:
                r = stub.generate(
                    pb.GenerateRequest(
                        prompt=s["prompt"], max_new_tokens=s["new"],
                    ),
                    timeout=120,
                )
                results[i] = list(r.tokens)
            except Exception as e:  # noqa: BLE001
                errors[i] = e

        threads = [
            threading.Thread(target=call, args=(i, s))
            for i, s in enumerate(specs)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert not errors, errors
        assert len(results) == 32
        st = stub.server_status(pb.ServerStatusRequest(), timeout=10)
        assert st.kv_paged and st.kv_shared
        assert st.kv_cache_dtype == "int8"
        assert st.completed == 32
        # the spill machinery demonstrably engaged mid-run: chains
        # were demoted under pressure AND came back by upload
        assert st.revive_uploads > 0
        assert st.prefill_tokens_revived > 0
        assert st.prefix_hit_tokens >= st.prefill_tokens_revived
        # the host tier never exceeded its budget (engine-side pin —
        # the peak tracks every spill, not just the final state)
        eng = server.engine
        assert eng.kv.host_blocks_peak <= eng.kv.allocator.host_blocks
        assert (eng.kv.host_blocks_peak * eng.kv.block_bytes
                <= host_budget)
        assert eng.kv.allocator.spills > 0
        # clean two-tier post-drain ledger: every device block free or
        # cached, no leaked refcount; spilled entries all accounted
        assert st.kv_blocks_free == st.kv_blocks_total == 8
        assert (eng.kv.allocator.num_spilled()
                == len(eng.kv._host_rows))
        assert st.kv_host_blocks == eng.kv.allocator.num_spilled()
    finally:
        server.stop()

    for i, s in enumerate(specs):
        off = np.asarray(autoregressive_generate(
            trainer, state, np.asarray([s["prompt"]], np.int32),
            s["new"], use_cache=True,
        ))[0]
        assert list(off) == results[i], (i, s)


def test_host_tier_reload_flushes_both_tiers(rig, tmp_path):
    """A hot reload must flush the host tier too: spilled chains were
    computed under superseded params and can never seat (or revive
    for) a new request."""
    from elasticdl_tpu.serving.admission import ServingRequest
    from elasticdl_tpu.serving.engine import (
        PagedContinuousBatchingEngine,
    )

    trainer, state = rig
    eng = PagedContinuousBatchingEngine(
        trainer, state, num_slots=2, block_size=4, num_blocks=4,
        host_bytes=1 << 20,
    )
    # seat + index a 2-block prompt chain, then evict it under
    # pressure so it spills
    prompt = [1, 2, 3, 4, 5, 6, 7, 1]
    r0 = ServingRequest(prompt, 2)
    eng.insert(r0)
    while eng.active_count():
        eng.step()
    assert eng.kv.allocator.num_cached() == 2
    r1 = ServingRequest([2, 3], 14)  # commits all 4 blocks
    eng.insert(r1)
    while eng.active_count():
        eng.step()
    # decode growth drew the cached chain out of the device tier:
    # both indexed blocks spilled instead of being forgotten
    assert eng.kv.allocator.num_spilled() == 2
    # reload: both tiers flush
    eng.set_params(state, version=1)
    assert eng.kv.allocator.num_spilled() == 0
    assert eng.kv.host_bytes_in_use() == 0
    assert eng.kv.allocator.match_prefix(prompt) == []
    # and the device ledger is whole again
    assert eng.kv.allocator.num_free() == 4


def test_shared_prefix_speculative_matches_dense_greedy_32way(rig):
    """The acceptance pin for prefix sharing + speculative decode:
    32 concurrent GREEDY requests drawn from a small system-prompt
    pool (so prefixes dedupe and full-prompt matches CoW) against a
    paged+shared server running a MISMATCHED draft (rollback actually
    exercised) — every token stream must equal the dense engine's and
    offline decode's. Server status must show the sharing and draft
    machinery actually engaged."""
    trainer, state = rig
    draft_trainer = _trainer(seed=321)
    draft_state = _state(draft_trainer)

    # prompts share 4- and 8-token prefixes (block_size 4): pool of 2
    # system prompts + tiny per-request suffixes
    systems = [[1, 2, 3, 4], [5, 6, 7, 1, 2, 3, 4, 5]]
    specs = []
    for i in range(32):
        prompt = list(systems[i % 2]) + ([1 + i % 3] if i % 4 else [])
        specs.append({"prompt": prompt, "new": 3 + i % 5})

    def collect(server):
        stub = ServingStub(build_channel("localhost:%d" % server.port))
        results, errors = {}, {}

        def call(i, s):
            try:
                r = stub.generate(
                    pb.GenerateRequest(
                        prompt=s["prompt"], max_new_tokens=s["new"],
                    ),
                    timeout=120,
                )
                results[i] = list(r.tokens)
            except Exception as e:  # noqa: BLE001
                errors[i] = e

        threads = [
            threading.Thread(target=call, args=(i, s))
            for i, s in enumerate(specs)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert not errors, errors
        assert len(results) == 32
        return results

    cfg = ServingConfig(
        num_slots=6, queue_capacity=64, kv_paged=True,
        kv_block_size=4, kv_num_blocks=24, kv_shared=True, draft_k=2,
    )
    shared = GenerationServer(
        trainer, state, cfg, draft=(draft_trainer, draft_state)
    ).start()
    try:
        shared_results = collect(shared)
        stub = ServingStub(build_channel("localhost:%d" % shared.port))
        st = stub.server_status(pb.ServerStatusRequest(), timeout=10)
        assert st.kv_paged and st.kv_shared
        assert st.prefix_hit_tokens > 0  # sharing actually engaged
        assert st.draft_k == 2 and st.draft_proposed > 0
        assert st.draft_accepted >= 0
        assert st.max_active_slots > 1
        # clean post-drain ledger: every block free or cached, none
        # leaked by a refcount
        assert st.kv_blocks_free == st.kv_blocks_total == 24
        assert st.completed == 32
    finally:
        shared.stop()

    dense = _start(trainer, state, num_slots=4, queue_capacity=64)
    try:
        dense_results = collect(dense)
    finally:
        dense.stop()

    for i, s in enumerate(specs):
        off = np.asarray(autoregressive_generate(
            trainer, state, np.asarray([s["prompt"]], np.int32),
            s["new"], use_cache=True,
        ))[0]
        assert list(off) == shared_results[i], (i, s)
        assert dense_results[i] == shared_results[i], (i, s)


def test_profiled_split_step_matches_offline_int8_32way():
    """The metrics-plane parity pin: with the per-step decode profiler
    ENABLED the paged engine runs SPLIT compiled steps (decode|scatter
    and draft|verify|scatter instead of the fused executables) — the
    token streams must STILL equal the offline int8 oracle at 32-way
    paged + shared + speculative + int8 concurrency (mismatched draft,
    so rollback exercises the split verify path). Also pins that every
    speculative-path phase actually recorded, and that the /metrics
    exposition of a live replica parses through the INDEPENDENT
    text-format parser with the phase histogram present — the
    acceptance criterion's "live replica serves Prometheus text"."""
    import urllib.request

    from elasticdl_tpu.observability.promparse import (
        parse_prometheus_text,
    )

    int8_params = PARAMS + "; kv_cache_dtype='int8'"
    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = Trainer(
        load_model_spec_from_module(zoo), mesh=mesh,
        model_params=int8_params,
    )
    state = _state(trainer)
    draft_trainer = _trainer(seed=321)  # float draft, mismatched
    draft_state = _state(draft_trainer)

    systems = [[1, 2, 3, 4], [5, 6, 7, 1, 2, 3, 4, 5]]
    specs = []
    for i in range(32):
        prompt = list(systems[i % 2]) + ([1 + i % 3] if i % 4 else [])
        specs.append({"prompt": prompt, "new": 3 + i % 5})

    cfg = ServingConfig(
        num_slots=6, queue_capacity=64, kv_paged=True,
        kv_block_size=4, kv_num_blocks=24, kv_shared=True, draft_k=2,
        profile=True, metrics_port=0,
    )
    server = GenerationServer(
        trainer, state, cfg, draft=(draft_trainer, draft_state)
    ).start()
    try:
        assert server.engine.profiler is not None
        # the pool shares the profiler (revive-upload attribution)
        assert server.engine.kv.profiler is server.engine.profiler
        stub = ServingStub(build_channel("localhost:%d" % server.port))
        results, errors = {}, {}

        def call(i, s):
            try:
                r = stub.generate(
                    pb.GenerateRequest(
                        prompt=s["prompt"], max_new_tokens=s["new"],
                    ),
                    timeout=120,
                )
                results[i] = list(r.tokens)
            except Exception as e:  # noqa: BLE001
                errors[i] = e

        threads = [
            threading.Thread(target=call, args=(i, s))
            for i, s in enumerate(specs)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert not errors, errors
        assert len(results) == 32
        st = stub.server_status(pb.ServerStatusRequest(), timeout=10)
        assert st.kv_cache_dtype == "int8"
        assert st.draft_proposed > 0
        assert st.prefix_hit_tokens > 0
        # the windowed hit-rate signal is live and sane
        assert 0.0 <= st.prefix_hit_rate_window <= 1.0
        assert st.kv_blocks_free == st.kv_blocks_total == 24

        snap = server.engine.profiler.snapshot()
        # every phase the speculative+shared workload exercises
        for phase in ("prefill", "suffix_tile", "draft",
                      "verify_commit", "scatter"):
            assert phase in snap and snap[phase]["count"] > 0, (
                phase, snap,
            )

        text = urllib.request.urlopen(
            "http://127.0.0.1:%d/metrics" % server.metrics.port,
            timeout=10,
        ).read().decode("utf-8")
        fams = parse_prometheus_text(text)  # raises on malformation
        assert "edl_serving_phase_ms" in fams
        assert "edl_serving_ttft_ms" in fams
        assert "edl_serving_completed_total" in fams
        completed = [
            v for n, lab, v in
            fams["edl_serving_completed_total"]["samples"]
        ]
        assert completed == [32]
    finally:
        server.stop()

    for i, s in enumerate(specs):
        off = np.asarray(autoregressive_generate(
            trainer, state, np.asarray([s["prompt"]], np.int32),
            s["new"], use_cache=True,
        ))[0]
        assert list(off) == results[i], (i, s)
