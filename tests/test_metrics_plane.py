"""Live-metrics-plane tests (tier-1: no jax compute, loopback-only
sockets for the scrape server).

Locks the ISSUE 12 tentpole semantics: the windowed time-series ring
against a brute-force oracle under churn (window deltas, conservation,
bound + drop accounting), cross-replica window merge = bucket/counter
addition, the Prometheus exposition round-tripped through the
INDEPENDENT text-format parser (and that parser rejecting malformed
documents), the stdlib scrape server, the closed GAUGE sets (the
counter-set contract, extended), the snapshot()/close() vs ring
window-boundary regression (identical totals on both paths), the
windowed prefix-hit-rate, the SLO burn-rate math (multi-window rule,
finiteness), the per-step profiler's closed phase set, and the
router's SloObjective blocks + /metrics endpoint."""

import math
import os
import random
import urllib.error
import urllib.request

import pytest

from elasticdl_tpu.observability.histogram import (
    LogLinearHistogram,
    bucket_index,
)
from elasticdl_tpu.observability.metrics import (
    MetricsServer,
    TimeSeriesRing,
    counter_family,
    gauge_family,
    hist_family,
    merge_window_deltas,
    render_prometheus,
)
from elasticdl_tpu.observability.promparse import parse_prometheus_text
from elasticdl_tpu.observability.slo import BurnRateEngine, SloSpec
from elasticdl_tpu.proto import elasticdl_pb2 as pb
from elasticdl_tpu.serving.router import Router, RouterConfig
from elasticdl_tpu.serving.telemetry import (
    RouterTelemetry,
    ServingTelemetry,
)


class FakeClock(object):
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# ------------------------------------------------------------------ ring


def _trim(counts):
    out = list(counts)
    while out and not out[-1]:
        out.pop()
    return out


def _sub(cur, base):
    return _trim([
        c - (base[i] if i < len(base) else 0)
        for i, c in enumerate(cur)
    ])


def test_ring_window_deltas_match_brute_force_oracle_under_churn():
    """Randomized churn (new counter names appearing, the histogram
    growing, irregular observation gaps) against a straight-line
    reference implementation of the close rule: a window closes at the
    first observation >= interval past the window start and carries
    cumulative-difference deltas."""
    rng = random.Random(7)
    clock = FakeClock()
    ring = TimeSeriesRing(interval_secs=1.0, capacity=10_000,
                          clock=clock)
    counters, hist = {}, []
    observations = []
    for _ in range(400):
        clock.t += rng.random() * 0.4
        for name in rng.sample("abcd", rng.randint(0, 3)):
            counters[name] = counters.get(name, 0) + rng.randint(1, 5)
        if rng.random() < 0.7:
            idx = rng.randint(0, 40)
            while len(hist) <= idx:
                hist.append(0)
            hist[idx] += rng.randint(1, 3)
        ring.observe(counters=counters, gauges={"g": clock.t},
                     hists={"h": hist})
        observations.append((clock.t, dict(counters), list(hist)))
    clock.t += 0.01
    ring.flush()

    # the oracle: replay the rule with plain loops
    expected = []
    t0, base_c, base_h, seen = 0.0, {}, [], False
    for t, cs, hs in observations:
        seen = True
        if t - t0 >= 1.0:
            expected.append((t0, t,
                             {k: v - base_c.get(k, 0)
                              for k, v in cs.items()},
                             _sub(hs, base_h), t))
            t0, base_c, base_h, seen = t, dict(cs), list(hs), False
    if seen:
        t, cs, hs = observations[-1]
        expected.append((t0, clock.t,
                         {k: v - base_c.get(k, 0)
                          for k, v in cs.items()},
                         _sub(hs, base_h), t))

    windows = ring.windows()
    assert len(windows) == len(expected) > 50
    for w, (et0, et1, ec, eh, _tc) in zip(windows, expected):
        assert w["t0"] == pytest.approx(et0)
        assert w["t1"] == pytest.approx(et1)
        assert w["counters"] == ec
        assert w["hists"]["h"] == eh
    # conservation: sum of window deltas == final cumulative, exactly
    for name, total in counters.items():
        assert sum(w["counters"].get(name, 0) for w in windows) == total
    merged = []
    for w in windows:
        for i, c in enumerate(w["hists"].get("h", [])):
            while len(merged) <= i:
                merged.append(0)
            merged[i] += c
    assert _trim(merged) == _trim(hist)


def test_ring_cross_replica_merge_is_bucket_addition():
    """Two replicas' window deltas merge exactly like router_status
    merges lifetime histograms: counter addition + elementwise bucket
    addition — and percentiles of the merged counts equal percentiles
    of union recording."""
    h1, h2 = LogLinearHistogram(), LogLinearHistogram()
    for v in (10.0, 12.0, 14.0):
        h1.record(v)
    for v in (200.0, 220.0):
        h2.record(v)
    a = {"t0": 0.0, "t1": 1.0, "counters": {"x": 2},
         "gauges": {"g": 1}, "hists": {"h": h1.to_counts()}}
    b = {"t0": 0.0, "t1": 1.0, "counters": {"x": 3, "y": 1},
         "gauges": {"g": 2}, "hists": {"h": h2.to_counts()}}
    m = merge_window_deltas(a, b)
    assert m["counters"] == {"x": 5, "y": 1}
    assert m["gauges"] == {"g": 3}
    union = LogLinearHistogram()
    union.merge(h1)
    union.merge(h2)
    merged_hist = LogLinearHistogram.from_counts(m["hists"]["h"])
    for q in (50, 90, 99):
        assert merged_hist.percentile(q) == pytest.approx(
            union.percentile(q), rel=0.05
        )
    # inputs untouched
    assert a["counters"] == {"x": 2} and b["counters"] == {"x": 3,
                                                          "y": 1}


def test_ring_bound_and_drop_accounting():
    clock = FakeClock()
    ring = TimeSeriesRing(interval_secs=1.0, capacity=5, clock=clock)
    for i in range(12):
        clock.t += 1.0
        ring.observe(counters={"n": i + 1})
    assert len(ring.windows()) == 5
    assert ring.dropped == 7  # 12 closed - 5 retained
    # the RETAINED windows are the newest; conservation now holds only
    # over retained + dropped, which is the point of the counter
    kept = sum(w["counters"]["n"] for w in ring.windows())
    assert kept < 12  # old deltas genuinely gone...
    assert ring.windows()[-1]["counters"]["n"] == 1  # ...newest kept


def test_ring_flush_closes_partial_window_and_horizon_queries():
    clock = FakeClock()
    ring = TimeSeriesRing(interval_secs=10.0, capacity=100,
                          clock=clock)
    clock.t = 1.0
    ring.observe(counters={"n": 4})
    assert ring.windows() == []  # interval not elapsed
    assert ring.pending_counter("n") == 4
    ring.flush()
    assert len(ring.windows()) == 1  # partial window force-closed
    assert ring.windows()[0]["counters"]["n"] == 4
    assert ring.pending_counter("n") == 0
    clock.t = 50.0
    ring.observe(counters={"n": 10})
    clock.t = 61.0
    ring.observe(counters={"n": 16})
    # horizon: only windows ENDING inside the trailing span count
    assert ring.sum_counter("n", horizon_secs=5.0, now=61.0) == 6
    assert ring.sum_counter("n") == 16


# ------------------------------------------------------------ exposition


def test_render_parse_round_trip():
    """The renderer's output through the INDEPENDENT parser: families,
    types, labels (escapes included), values and histogram structure
    all survive."""
    h = LogLinearHistogram()
    for v in (0.5, 3.0, 250.0):
        h.record(v)
    fams = [
        counter_family("edl_test_requests_total", "requests", 42),
        gauge_family("edl_test_depth", "queue depth",
                     [({"shard": 'a"b\\c'}, 3.5), ({"shard": "d"}, 0)]),
        hist_family("edl_test_latency_ms", "latency",
                    [({"phase": "prefill"}, h.to_counts(), h.sum)]),
    ]
    text = render_prometheus(fams)
    parsed = parse_prometheus_text(text)
    assert set(parsed) == {"edl_test_requests_total", "edl_test_depth",
                           "edl_test_latency_ms"}
    assert parsed["edl_test_requests_total"]["type"] == "counter"
    [(name, labels, value)] = [
        s for s in parsed["edl_test_requests_total"]["samples"]
    ]
    assert (name, labels, value) == ("edl_test_requests_total", {}, 42)
    depth = {tuple(sorted(s[1].items())): s[2]
             for s in parsed["edl_test_depth"]["samples"]}
    assert depth[(("shard", 'a"b\\c'),)] == 3.5
    hist_samples = parsed["edl_test_latency_ms"]["samples"]
    count = [v for n, lab, v in hist_samples
             if n.endswith("_count")]
    assert count == [3]
    sums = [v for n, lab, v in hist_samples if n.endswith("_sum")]
    assert sums[0] == pytest.approx(h.sum)
    inf_bucket = [v for n, lab, v in hist_samples
                  if n.endswith("_bucket") and lab.get("le") == "+Inf"]
    assert inf_bucket == [3]


def test_parser_rejects_malformed_expositions():
    ok_head = "# HELP f help\n# TYPE f histogram\n"
    cases = [
        # histogram buckets not monotone
        ok_head + 'f_bucket{le="1"} 5\nf_bucket{le="2"} 3\n'
        'f_bucket{le="+Inf"} 5\n',
        # no +Inf bucket
        ok_head + 'f_bucket{le="1"} 1\n',
        # _count disagrees with +Inf
        ok_head + 'f_bucket{le="+Inf"} 3\nf_count 4\n',
        # counter not ending in _total
        "# HELP c help\n# TYPE c counter\nc 1\n",
        # sample with no announced family
        "orphan_metric 1\n",
        # sample with no value
        "# HELP g help\n# TYPE g gauge\ng\n",
    ]
    for text in cases:
        with pytest.raises(ValueError):
            parse_prometheus_text(text)
    # and the happy path really is happy
    parse_prometheus_text(
        ok_head + 'f_bucket{le="1"} 3\nf_bucket{le="+Inf"} 5\n'
        "f_sum 9.5\nf_count 5\n"
    )


def test_metrics_server_serves_scrape_and_404():
    calls = []

    def collect():
        calls.append(1)
        return [counter_family("edl_t_total", "t", len(calls))]

    server = MetricsServer(collect, port=0)
    try:
        base = "http://127.0.0.1:%d" % server.port
        text = urllib.request.urlopen(
            base + "/metrics", timeout=5
        ).read().decode()
        fams = parse_prometheus_text(text)
        assert fams["edl_t_total"]["samples"][0][2] == 1
        # collect runs per scrape (live values, not a cached page)
        text = urllib.request.urlopen(
            base + "/metrics", timeout=5
        ).read().decode()
        assert parse_prometheus_text(
            text
        )["edl_t_total"]["samples"][0][2] == 2
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(base + "/other", timeout=5)
    finally:
        server.close()


# ----------------------------------------------------- closed gauge sets


def test_serving_gauge_set_is_closed():
    t = ServingTelemetry(log_dir=None)
    t.gauge("queue_depth", 5)
    with pytest.raises(ValueError, match="unknown serving gauge"):
        t.gauge("queue_dept", 5)
    assert set(t.gauges) == set(ServingTelemetry.GAUGES)


def test_router_gauge_set_is_closed():
    t = RouterTelemetry(log_dir=None)
    t.gauge("healthy_replicas", 2)
    with pytest.raises(ValueError, match="unknown router gauge"):
        t.gauge("healthy_replica", 2)


# --------------------------- snapshot()/close() vs ring window boundary


def test_close_flushes_identical_totals_to_tb_events_and_ring(tmp_path):
    """The satellite FIX pin: a server stopped mid-window must flush
    the SAME totals to the tb_events path and to the last ring window
    — for every counter, final event-file total == telemetry counter
    == sum of ring window deltas (the partial window included)."""
    from test_observability import _parse_event_file

    t = ServingTelemetry(log_dir=str(tmp_path), flush_every=50,
                         ring_secs=3600.0)  # ring window stays OPEN
    t.count("admitted", 3)
    t.count("completed", 2)
    t.count("prompt_tokens", 11)
    t.record_step(queue_depth=1, active_slots=2, step_secs=0.01,
                  tokens_committed=5)
    t.count("admitted", 1)  # after the last step: close() must see it
    snap = t.snapshot()  # == the totals both flush paths must land
    t.close()  # mid-window on BOTH paths (step 1/50, ring 0/3600s)

    files = [f for f in os.listdir(str(tmp_path))
             if f.startswith("events.out.tfevents")]
    assert len(files) == 1
    tags = {}
    for e in _parse_event_file(os.path.join(str(tmp_path), files[0])):
        tags.update(e["tags"])
    windows = t.ring.windows()
    assert windows, "close() did not flush the partial ring window"
    for name in ServingTelemetry.COUNTERS:
        ring_total = sum(w["counters"].get(name, 0) for w in windows)
        assert tags["serving/%s_total" % name] == pytest.approx(
            ring_total
        ), name
        assert ring_total == snap[name], name
    # the histogram bucket deltas land too (step_ms recorded once)
    assert sum(sum(w["hists"].get("step_ms", [])) for w in windows) == 1


def test_windowed_prefix_hit_rate():
    clock = FakeClock()
    t = ServingTelemetry(log_dir=None, clock=clock, ring_secs=1.0)
    t.count("prompt_tokens", 80)
    t.count("prefix_hit_tokens", 60)
    # live partial window already answers (pending deltas)
    assert t.snapshot()["prefix_hit_rate_window"] == pytest.approx(
        0.75
    )
    clock.t += 2.0
    t.record_step(0, 1, 0.001, 1)  # rolls the ring window
    assert t.snapshot()["prefix_hit_rate_window"] == pytest.approx(
        0.75
    )
    # a cold burst shifts the WINDOWED rate while the lifetime ratio
    # would lag: new window, all-miss traffic
    clock.t += 40.0  # previous window ages out of the 30s horizon
    t.count("prompt_tokens", 50)
    clock.t += 2.0
    t.record_step(0, 1, 0.001, 1)
    assert t.snapshot()["prefix_hit_rate_window"] == pytest.approx(
        0.0
    )


def test_serving_telemetry_exposition_parses_with_live_values():
    t = ServingTelemetry(log_dir=None)
    t.count("admitted", 4)
    t.record_e2e(12.0)
    t.record_step(1, 1, 0.004, 2)
    fams = parse_prometheus_text(render_prometheus(t.prometheus()))
    admitted = fams["edl_serving_admitted_total"]["samples"][0][2]
    assert admitted == 4
    e2e_count = [v for n, lab, v in
                 fams["edl_serving_e2e_ms"]["samples"]
                 if n.endswith("_count")]
    assert e2e_count == [1]
    assert "edl_serving_prefix_hit_rate_window" in fams
    assert "edl_serving_ring_windows_dropped" in fams


# ------------------------------------------------------- SLO burn rates


def _ring_with_hist(values, clock, name="ttft_ms", counters=None):
    ring = TimeSeriesRing(interval_secs=1.0, capacity=100, clock=clock)
    h = LogLinearHistogram()
    for v in values:
        h.record(v)
    clock.t += 5.0
    ring.observe(counters=counters or {}, hists={name: h.to_counts()})
    clock.t += 0.1
    ring.flush()
    return ring


def test_latency_burn_rate_math_and_multiwindow_rule():
    clock = FakeClock()
    # 8 good (50 ms), 2 bad (500 ms) against a 100 ms threshold with a
    # 1% budget: bad fraction 0.2 => burn 20x on both windows
    ring = _ring_with_hist([50.0] * 8 + [500.0] * 2, clock)
    engine = BurnRateEngine(
        [SloSpec("ttft_p99", "latency", 0.01, hist="ttft_ms",
                 threshold_ms=100.0)],
        fast_window_secs=30.0, slow_window_secs=120.0,
    )
    [r] = engine.evaluate(ring, now=clock.t)
    assert r["fast_burn"] == pytest.approx(20.0)
    assert r["slow_burn"] == pytest.approx(20.0)
    assert r["fast_samples"] == 10
    assert r["alerting"] is True

    # fast-only burn is a blip, not an alert: age the bad window out
    # of the fast horizon, then record fresh good-only traffic
    clock2 = FakeClock()
    ring2 = TimeSeriesRing(interval_secs=1.0, capacity=100,
                           clock=clock2)
    bad = LogLinearHistogram()
    for v in [500.0] * 2 + [50.0] * 8:
        bad.record(v)
    clock2.t = 5.0
    ring2.observe(hists={"ttft_ms": bad.to_counts()})
    clock2.t = 100.0  # bad window now outside fast=30, inside slow=120
    ring2.observe(hists={"ttft_ms": bad.to_counts()})
    ring2.flush()
    [r2] = engine.evaluate(ring2, now=clock2.t)
    assert r2["fast_burn"] == 0.0  # no fresh samples
    assert r2["slow_burn"] == pytest.approx(20.0)
    assert r2["alerting"] is False


def test_threshold_bucket_counts_as_good_within_resolution():
    clock = FakeClock()
    ring = _ring_with_hist([100.0] * 10, clock)
    engine = BurnRateEngine(
        [SloSpec("ttft_p99", "latency", 0.01, hist="ttft_ms",
                 threshold_ms=100.0)],
    )
    [r] = engine.evaluate(ring, now=clock.t)
    assert r["fast_burn"] == 0.0  # the threshold's own bucket is good
    assert bucket_index(100.0) == bucket_index(100.0)  # tautology pin


def test_availability_burn_and_finiteness_on_empty_ring():
    clock = FakeClock()
    ring = _ring_with_hist([], clock,
                           counters={"routed": 100, "shed": 3,
                                     "errors": 1})
    engine = BurnRateEngine(
        [SloSpec("goodput", "availability", 0.02,
                 bad_counters=("shed", "errors"),
                 total_counters=("routed",))],
    )
    [r] = engine.evaluate(ring, now=clock.t)
    assert r["fast_burn"] == pytest.approx((4 / 100) / 0.02)  # 2x
    # empty ring: burns are 0.0 and FINITE, never NaN/inf
    empty = TimeSeriesRing(clock=clock)
    [r0] = engine.evaluate(empty, now=clock.t)
    assert r0["fast_burn"] == 0.0 and r0["slow_burn"] == 0.0
    assert math.isfinite(r0["fast_burn"])
    assert r0["alerting"] is False


def test_slo_spec_validation():
    with pytest.raises(ValueError):
        SloSpec("x", "latency", 0.01)  # no hist/threshold
    with pytest.raises(ValueError):
        SloSpec("x", "availability", 0.01)  # no counters
    with pytest.raises(ValueError):
        SloSpec("x", "latency", 0.0, hist="h", threshold_ms=1.0)
    with pytest.raises(ValueError):
        SloSpec("x", "nonsense", 0.01)


# ------------------------------------------------------ profiler (unit)


def test_step_profiler_closed_phase_set_and_exposition():
    from elasticdl_tpu.serving.engine import StepProfiler

    p = StepProfiler()
    p.observe("prefill", 0.002)
    p.observe("scatter", 0.0001)
    with pytest.raises(ValueError, match="unknown profiler phase"):
        p.observe("prefil", 0.002)
    snap = p.snapshot()
    assert set(snap) == {"prefill", "scatter"}
    assert snap["prefill"]["count"] == 1
    assert snap["prefill"]["p50_ms"] == pytest.approx(2.0, rel=0.05)
    fams = parse_prometheus_text(render_prometheus(p.prometheus()))
    phases = {lab["phase"] for n, lab, v in
              fams["edl_serving_phase_ms"]["samples"]}
    assert phases == {"prefill", "scatter"}


# --------------------------------------------- router SLO + /metrics


class _HistStub(object):
    """Replica stub answering server_status with fixed histogram
    buckets + a windowed hit rate."""

    def __init__(self, hist, hit_rate=0.0):
        self._hist = hist
        self._hit = hit_rate

    def server_status(self, request, timeout=None):
        return pb.ServerStatusResponse(
            ttft_hist=self._hist.to_counts(),
            queue_wait_hist=self._hist.to_counts(),
            prefix_hit_rate_window=self._hit,
        )


def _slo_router(**cfg_kwargs):
    h = LogLinearHistogram()
    for v in (10.0, 50_000.0, 60_000.0):
        h.record(v)
    stub = _HistStub(h, hit_rate=0.4)
    router = Router(
        ["rep0"],
        RouterConfig(slo_ttft_p99_ms=100.0, **cfg_kwargs),
        stub_factory=lambda a: stub,
    )
    router.telemetry.count("routed", 10)
    router.poll_once()
    router.telemetry.ring.interval_secs = 0.0  # close on next poll
    router.poll_once()
    return router


def test_router_status_carries_slo_blocks_and_hit_rate():
    router = _slo_router()
    try:
        st = router.status_response()
        by_name = {s.name: s for s in st.slo}
        assert set(by_name) == {"ttft_p99", "e2e_p99", "goodput"}
        ttft = by_name["ttft_p99"]
        # 2 of 3 samples above 100 ms with a 1% budget: ~66.7x burn
        assert ttft.fast_burn == pytest.approx(66.67, rel=0.01)
        assert ttft.alerting
        assert ttft.fast_samples == 3
        for s in st.slo:
            assert math.isfinite(s.fast_burn)
            assert math.isfinite(s.slow_burn)
        assert st.replica[0].prefix_hit_rate_window == pytest.approx(
            0.4
        )
    finally:
        router._stop.set()


def test_router_metrics_endpoint_exposes_burn_rates():
    router = _slo_router(metrics_port=0)
    router.start(grpc_server=False)
    try:
        text = urllib.request.urlopen(
            "http://127.0.0.1:%d/metrics" % router.metrics.port,
            timeout=5,
        ).read().decode()
        fams = parse_prometheus_text(text)
        assert "edl_router_routed_total" in fams
        assert "edl_router_fleet_ttft_ms" in fams  # fleet-merged hist
        burns = {
            (lab["slo"], lab["window"]): v
            for n, lab, v in fams["edl_router_slo_burn"]["samples"]
        }
        assert burns[("ttft_p99", "fast")] == pytest.approx(
            66.67, rel=0.01
        )
        assert ("goodput", "slow") in burns
        alerting = {
            lab["slo"]: v
            for n, lab, v in
            fams["edl_router_slo_alerting"]["samples"]
        }
        assert alerting["ttft_p99"] == 1.0
    finally:
        router.stop()
