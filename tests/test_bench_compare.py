"""Unit coverage for scripts/bench_compare.py — the serve-smoke
regression gate: direction-aware relative tolerances, absolute
invariants that no baseline drift may relax, new-metric grace,
vanished-leg failure, and the CLI exit contract."""

import json
import os
import sys

import pytest

sys.path.insert(
    0, os.path.join(os.path.dirname(__file__), "..", "scripts")
)

import bench_compare  # noqa: E402


def record(tok=100.0, good=10.0, bpt=500.0, overhead=0.99, steady=0):
    return {
        "tokens_per_sec": tok,
        "goodput_rps": good,
        "kv": {"bytes_per_token": bpt},
        "paged_shared": {
            "tokens_per_sec": tok,
            "kv": {"bytes_per_token": bpt},
        },
        "paged_int8": {"kv": {"bytes_per_token": bpt / 4}},
        "profiler_overhead": {"tokens_per_sec_ratio": overhead},
        "health": {"steady_recompiles": steady},
    }


def statuses(result):
    return {r["metric"]: r["status"] for r in result["rows"]}


def test_identical_records_pass():
    base = record()
    result = bench_compare.compare(record(), base)
    assert result["ok"], result["regressions"]
    assert set(statuses(result).values()) == {"ok"}


def test_throughput_tolerance_is_directional():
    base = record(tok=100.0)
    # 20% slower: inside the 30% band
    assert bench_compare.compare(record(tok=80.0), base)["ok"]
    # 40% slower: a collapse
    result = bench_compare.compare(record(tok=60.0), base)
    assert not result["ok"]
    assert statuses(result)["tokens_per_sec"] == "regression"
    # 40% FASTER is never a regression (direction-aware)
    assert bench_compare.compare(record(tok=140.0), base)["ok"]


def test_memory_tolerance_is_tight_and_lower_is_better():
    base = record(bpt=500.0)
    assert bench_compare.compare(record(bpt=540.0), base)["ok"]
    result = bench_compare.compare(record(bpt=600.0), base)
    assert not result["ok"]
    assert statuses(result)["kv.bytes_per_token"] == "regression"
    # less memory per token passes at any magnitude
    assert bench_compare.compare(record(bpt=100.0), base)["ok"]


def test_absolute_invariants_ignore_the_baseline():
    # a rotten baseline must not grandfather a violation in
    base = record(overhead=0.80, steady=3)
    result = bench_compare.compare(record(overhead=0.80), base)
    assert statuses(result)[
        "profiler_overhead.tokens_per_sec_ratio"] == "regression"
    result = bench_compare.compare(record(steady=1), base)
    assert statuses(result)["health.steady_recompiles"] == "regression"
    assert bench_compare.compare(record(), base)["ok"]


def test_new_metric_passes_vanished_leg_fails():
    base = record()
    del base["paged_int8"]  # baseline predates the int8 leg
    assert bench_compare.compare(record(), base)["ok"]
    fresh = record()
    del fresh["paged_shared"]  # a bench leg silently vanished
    result = bench_compare.compare(fresh, base)
    assert not result["ok"]
    assert statuses(result)[
        "paged_shared.tokens_per_sec"] == "missing_fresh"


def test_tolerance_override():
    base = record(tok=100.0)
    fresh = record(tok=60.0)
    assert not bench_compare.compare(fresh, base)["ok"]
    assert bench_compare.compare(
        fresh, base, tolerances={"tokens_per_sec": 0.5,
                                 "paged_shared.tokens_per_sec": 0.5}
    )["ok"]


def test_cli_exit_codes(tmp_path, capsys):
    fresh, base = tmp_path / "fresh.json", tmp_path / "base.json"
    base.write_text(json.dumps(record()))
    fresh.write_text(json.dumps(record()))
    assert bench_compare.main(
        ["--fresh", str(fresh), "--baseline", str(base)]
    ) == 0
    fresh.write_text(json.dumps(record(tok=10.0)))
    out = tmp_path / "cmp.json"
    assert bench_compare.main(
        ["--fresh", str(fresh), "--baseline", str(base),
         "--out", str(out)]
    ) == 1
    summary = json.loads(out.read_text())
    assert summary["regressions"]
    # the override rescues a deliberate trade
    assert bench_compare.main(
        ["--fresh", str(fresh), "--baseline", str(base),
         "--tol", "tokens_per_sec=0.95",
         "--tol", "paged_shared.tokens_per_sec=0.95"]
    ) == 0
    capsys.readouterr()


if __name__ == "__main__":
    raise SystemExit(pytest.main([__file__, "-q"]))
