"""manifests/ validated by machinery (VERDICT round-2 missing #5):

* always: YAML parses; the master manifest's labels match the selector
  keys the k8s client generates services against, its args parse with
  the real master argparser, and the RBAC rules cover every verb the
  client code calls;
* when a cluster is reachable (kind/minikube): a server-side dry-run
  apply through scripts/run_cluster_job_smoke.sh (skipped otherwise —
  mirroring the reference's minikube CI job, scripts/travis/run_job.sh).
"""

import os
import shutil
import subprocess

import pytest
import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MANIFESTS = os.path.join(REPO, "manifests")


def _load(name):
    with open(os.path.join(MANIFESTS, name)) as f:
        return list(yaml.safe_load_all(f))


def test_manifests_parse():
    names = sorted(os.listdir(MANIFESTS))
    assert "master-example.yaml" in names
    assert "elasticdl-tpu-rbac.yaml" in names
    for name in names:
        docs = [d for d in _load(name) if d]
        assert docs, name


def test_master_manifest_matches_client_label_contract():
    """The by-hand master pod must carry exactly the labels the k8s
    client selects on (services, watch streams) — validated against the
    client's constants, not by eye."""
    from elasticdl_tpu.common import k8s_client as k8s

    (pod,) = [d for d in _load("master-example.yaml") if d]
    assert pod["kind"] == "Pod"
    labels = pod["metadata"]["labels"]
    assert labels["app"] == k8s.ELASTICDL_APP_NAME
    job_name = labels[k8s.ELASTICDL_JOB_KEY]
    assert labels[k8s.ELASTICDL_REPLICA_TYPE_KEY] == "master"
    assert labels[k8s.ELASTICDL_REPLICA_INDEX_KEY] == "0"
    # the pod name must equal what Client.get_master_pod_name derives,
    # or the master's owner references / TB service selector dangle
    assert pod["metadata"]["name"] == k8s.get_master_pod_name(job_name)


def test_master_manifest_args_parse():
    """The example args must satisfy the real master argparser — a
    manifest drift (renamed flag, missing required arg) fails here, not
    in the cluster."""
    from elasticdl_tpu.common.args import parse_master_args

    (pod,) = [d for d in _load("master-example.yaml") if d]
    (container,) = pod["spec"]["containers"]
    args = parse_master_args(container["args"])
    assert args.model_zoo == "/model_zoo"
    assert args.num_workers == 2


def test_rbac_covers_client_verbs():
    """The RBAC role must allow every operation common/k8s_client.py
    performs (pods create/get/delete/patch/watch, services create/get)."""
    docs = [d for d in _load("elasticdl-tpu-rbac.yaml") if d]
    roles = [d for d in docs if d["kind"] in ("Role", "ClusterRole")]
    assert roles
    allowed = {}
    for role in roles:
        for rule in role.get("rules", []):
            for res in rule.get("resources", []):
                allowed.setdefault(res, set()).update(rule["verbs"])
    for verb in ("create", "get", "delete", "patch", "list", "watch"):
        assert verb in allowed.get("pods", set()), (verb, allowed)
    for verb in ("create", "get"):
        assert verb in allowed.get("services", set()), (verb, allowed)


def test_cluster_dry_run_smoke():
    """Server-side validation against a real (kind/minikube) cluster;
    skipped when no cluster is reachable — the reference ran this level
    in CI only (scripts/travis/run_job.sh:32-45)."""
    if shutil.which("kubectl") is None:
        pytest.skip("kubectl not installed")
    r = subprocess.run(
        [os.path.join(REPO, "scripts", "run_cluster_job_smoke.sh")],
        capture_output=True, text=True, timeout=300,
    )
    if r.returncode == 3:
        pytest.skip("no reachable cluster")
    assert r.returncode == 0, r.stdout + r.stderr
