"""Export parity for the host-resident embedding tier (VERDICT.md weak
#6): the exported artifact carries host rows, serving reproduces
training-time predictions exactly, and the mesh handler validates the
artifact (the reference's model_handler_test export-parity coverage)."""

import numpy as np
import pytest

from elasticdl_tpu.api import exporter
from elasticdl_tpu.common.constants import DistributionStrategy
from elasticdl_tpu.common.model_handler import (
    MeshModelHandler,
    ModelHandler,
)
from elasticdl_tpu.embedding.host_bridge import HostEmbeddingManager
from elasticdl_tpu.embedding.host_spill import HostSpillEmbeddingEngine
from tests.test_host_bridge import _batches, _host_trainer


def _fresh_manager():
    manager = HostEmbeddingManager()
    manager.register(
        "edl_embedding", "feature",
        HostSpillEmbeddingEngine(8, optimizer="sgd", lr=0.1),
    )
    manager.register(
        "edl_id_bias", "feature",
        HostSpillEmbeddingEngine(1, optimizer="sgd", lr=0.1),
    )
    return manager


def _train(n=3):
    trainer, manager = _host_trainer()
    batches = _batches(n)
    state = trainer.init_state(batches[0])
    for b in batches:
        state, _ = trainer.train_step(state, b)
    return trainer, manager, state, batches


def test_export_and_serve_parity(tmp_path):
    trainer, manager, state, batches = _train()
    export_dir = str(tmp_path / "export")
    exporter.export_model(
        trainer.model, state, export_dir, host_manager=manager
    )

    payload, meta = exporter.load_exported(export_dir)
    assert set(payload["host_embeddings"]) == {
        "edl_embedding", "edl_id_bias",
    }
    assert meta["version"] == int(state.step)

    # a FRESH manager (as a serving process would build from the spec)
    serving_manager = _fresh_manager()
    serve = exporter.make_serving_fn(
        trainer.model, payload, host_manager=serving_manager
    )
    features = batches[0][0]
    want = trainer.forward(state, dict(features))
    got = serve(dict(features))
    for key in want:
        np.testing.assert_allclose(
            np.asarray(got[key]), np.asarray(want[key]), atol=1e-6
        )


def test_serving_without_manager_raises(tmp_path):
    trainer, manager, state, _ = _train(1)
    export_dir = str(tmp_path / "export")
    exporter.export_model(
        trainer.model, state, export_dir, host_manager=manager
    )
    payload, _ = exporter.load_exported(export_dir)
    with pytest.raises(ValueError, match="host-resident tables"):
        exporter.make_serving_fn(trainer.model, payload)
    # strict table-set equality: a manager table absent from the
    # artifact would serve lazily-initialized random rows
    bigger = _fresh_manager()
    bigger.register(
        "extra", "feature", HostSpillEmbeddingEngine(2, optimizer="sgd")
    )
    with pytest.raises(ValueError, match="host-table mismatch"):
        exporter.make_serving_fn(trainer.model, payload,
                                 host_manager=bigger)
    # artifact written WITHOUT the manager + host-tier manager at serve
    # time -> clear construction-time error, not a KeyError inside jit
    bare_dir = str(tmp_path / "bare")
    exporter.export_model(trainer.model, state, bare_dir)
    bare_payload, _ = exporter.load_exported(bare_dir)
    with pytest.raises(ValueError, match="artifact carries none"):
        exporter.make_serving_fn(trainer.model, bare_payload,
                                 host_manager=_fresh_manager())


def test_serving_never_mutates_callers_manager(tmp_path):
    """make_serving_fn seeds a fresh clone: a live training manager
    passed in keeps its rows (slots/step stay aligned)."""
    trainer, manager, state, batches = _train(2)
    export_dir = str(tmp_path / "export")
    exporter.export_model(
        trainer.model, state, export_dir, host_manager=manager
    )
    # train one more step: live rows move past the exported ones
    state, _ = trainer.train_step(state, batches[0])
    engine = manager.tables()["edl_embedding"].engine
    ids_live, vals_live = engine.param.export_rows()
    ids_live, vals_live = ids_live.copy(), vals_live.copy()

    payload, _ = exporter.load_exported(export_dir)
    serve = exporter.make_serving_fn(
        trainer.model, payload, host_manager=manager
    )
    serve(dict(batches[0][0]))  # serving works...
    ids_after, vals_after = engine.param.export_rows()
    # ...and the live engine is bit-identical to before
    np.testing.assert_array_equal(np.sort(ids_after), np.sort(ids_live))
    np.testing.assert_allclose(
        vals_after[np.argsort(ids_after)],
        vals_live[np.argsort(ids_live)], atol=0,
    )


def test_mesh_handler_validates_and_exports(tmp_path):
    trainer, manager, state, batches = _train(1)
    handler = ModelHandler.get_model_handler(
        DistributionStrategy.PARAMETER_SERVER
    )
    assert isinstance(handler, MeshModelHandler)
    export_dir = str(tmp_path / "export")
    handler.get_model_to_export(
        trainer.model, state, export_dir, host_manager=manager
    )
    payload, _ = exporter.load_exported(export_dir)
    assert set(payload["host_embeddings"]) == set(manager.tables())

    # validation: a manager expecting MORE tables than the artifact has
    bigger = _fresh_manager()
    bigger.register(
        "extra", "feature", HostSpillEmbeddingEngine(2, optimizer="sgd")
    )
    with pytest.raises(RuntimeError, match="host-table mismatch"):
        handler._validate_export(state, export_dir, bigger)


def test_export_from_checkpoint_with_host_state(tmp_path):
    """Handler export prefers the checkpoint AND restores host rows from
    the same version."""
    from elasticdl_tpu.checkpoint import CheckpointSaver

    trainer, manager, state, batches = _train(2)
    ckpt_dir = str(tmp_path / "ckpt")
    saver = CheckpointSaver(ckpt_dir, checkpoint_steps=1,
                           extra_state_fn=manager.flat_state)
    ckpt_version = int(state.step)
    saver.save(state, ckpt_version)
    # export the saved manager's rows now: the extra train step below
    # mutates the live engines in place
    ids_b, vals_b = (
        manager.tables()["edl_embedding"].engine.param.export_rows()
    )
    ids_b, vals_b = ids_b.copy(), vals_b.copy()

    # train further: live state is now AHEAD of the checkpoint
    state_live, _ = trainer.train_step(state, batches[0])

    # live engine rows AFTER the extra step (to prove no mutation below)
    live_ids, live_vals = (
        manager.tables()["edl_embedding"].engine.param.export_rows()
    )
    live_ids, live_vals = live_ids.copy(), live_vals.copy()

    handler = MeshModelHandler(checkpoint_dir=ckpt_dir)
    export_dir = str(tmp_path / "export")
    handler.get_model_to_export(
        trainer.model, state_live, export_dir, host_manager=manager
    )
    payload, meta = exporter.load_exported(export_dir)
    # exported the checkpointed version, not the live step
    assert meta["version"] == ckpt_version
    # artifact host rows == rows at CHECKPOINT time (not the further-
    # trained live rows), id-aligned
    rec = payload["host_embeddings"]["edl_embedding"]
    ids_a, vals_a = np.asarray(rec["ids"]), np.asarray(rec["values"])
    np.testing.assert_array_equal(np.sort(ids_a), np.sort(ids_b))
    np.testing.assert_allclose(
        vals_a[np.argsort(ids_a)], vals_b[np.argsort(ids_b)], atol=1e-6
    )
    # ...and the LIVE engines were NOT rewound by the export (restore
    # goes into a throwaway clone)
    ids_now, vals_now = (
        manager.tables()["edl_embedding"].engine.param.export_rows()
    )
    np.testing.assert_array_equal(np.sort(ids_now), np.sort(live_ids))
    np.testing.assert_allclose(
        vals_now[np.argsort(ids_now)], live_vals[np.argsort(live_ids)],
        atol=0,
    )
