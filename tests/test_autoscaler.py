"""Replica supervisor/autoscaler unit tests (tier-1: no jax, no
sockets, no real processes — a fake launcher + fake replica stubs
drive serving/autoscaler.py against a real Router).

Locks the ISSUE's elasticity semantics: spawn-to-min + adoption,
sustained-pressure scale-up with hysteresis/cooldown (flapping
structurally impossible), drain-based scale-down that closes the
retired replica's channel, crash replacement with full-jitter backoff
and the max-restarts circuit, wedged-replica (lease-decay) kill and
replace, supervisor crash-recovery from the journal (re-adopt, no
double-spawn, no orphan — including mid-scale-up), and the
SUPERVISOR_RPCS fault-injection boundary."""

import random

import pytest

from elasticdl_tpu.common.fault_injection import FaultInjector
from elasticdl_tpu.proto import elasticdl_pb2 as pb
from elasticdl_tpu.serving.autoscaler import (
    DRAINING,
    LIVE,
    STARTING,
    AutoscalerConfig,
    ReplicaSupervisor,
)
from elasticdl_tpu.serving.router import Router, RouterConfig


class FakeClock(object):
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class FakeReplicaStub(object):
    """ServingStub-shaped fake: scripted status + a close() recorder
    (the retire path must close the channel exactly once)."""

    def __init__(self):
        self.poll_ok = True
        self.draining = False
        self.queue_depth = 0
        self.active_slots = 0
        self.kv_blocks_free = 8
        self.kv_blocks_cached = 0
        self.queue_wait_ms = 0.0
        # runtime-health self-report: "" = a pre-health replica (the
        # lease-decay fallback's whole constituency)
        self.health_state = ""
        self.last_progress_age_ms = 0.0
        self.closed = 0

    def server_status(self, request, timeout=None):
        if not self.poll_ok:
            raise RuntimeError("poll down")
        return pb.ServerStatusResponse(
            queue_depth=self.queue_depth,
            active_slots=self.active_slots,
            kv_blocks_free=self.kv_blocks_free,
            kv_blocks_cached=self.kv_blocks_cached,
            queue_wait_ms=self.queue_wait_ms,
            draining=self.draining,
            health_state=self.health_state,
            last_progress_age_ms=self.last_progress_age_ms,
        )

    def close(self):
        self.closed += 1


class FakeHandle(object):
    """A fake replica process: the test scripts readiness and death."""

    def __init__(self, pid, seat_id, launcher):
        self.pid = pid
        self.seat_id = seat_id
        self.launcher = launcher
        self.rc = None
        self.address = None
        self.log_path = "log-%d" % seat_id
        self.terminated = False
        self.killed = False
        # emulate a fast graceful drain by default; drain tests flip
        # this off to hold the seat mid-drain
        self.exit_on_terminate = True

    def poll(self):
        return self.rc

    def ready(self):
        return self.address

    def terminate(self):
        self.terminated = True
        if self.exit_on_terminate and self.rc is None:
            self.rc = 0

    def kill(self):
        self.killed = True
        if self.rc is None:
            self.rc = -9


class FakeLauncher(object):
    def __init__(self, stubs):
        self.stubs = stubs  # address -> FakeReplicaStub (router view)
        self.spawned = []
        self.auto_ready = True
        self._pid = 4000

    def make_ready(self, handle):
        address = "rep%d" % handle.pid
        self.stubs[address] = FakeReplicaStub()
        handle.address = address
        return address

    def spawn(self, seat_id):
        self._pid += 1
        handle = FakeHandle(self._pid, seat_id, self)
        if self.auto_ready:
            self.make_ready(handle)
        self.spawned.append(handle)
        return handle

    def attach(self, seat_id, pid, log_path):
        # "the process is still running": hand back the same handle a
        # previous supervisor spawned, like a pid re-attach would
        for handle in self.spawned:
            if handle.pid == pid:
                return handle
        dead = FakeHandle(pid, seat_id, self)
        dead.rc = 1
        return dead


def build(journal_dir="", injector=None, lease_secs=1000.0, **cfg_kw):
    clock = FakeClock()
    stubs = {}
    launcher = FakeLauncher(stubs)
    kw = dict(
        min_replicas=1, max_replicas=3, decide_secs=0.1,
        up_queue_wait_ms=100.0, up_queue_depth=4, up_window_secs=1.0,
        idle_queue_wait_ms=20.0, down_window_secs=2.0,
        down_free_kv_blocks=1, cooldown_secs=3.0,
        ready_timeout_secs=30.0, drain_timeout_secs=10.0,
        wedged_after_secs=2.0, max_restarts=3,
        base_delay_secs=0.1, max_delay_secs=1.0,
        journal_dir=journal_dir,
    )
    kw.update(cfg_kw)
    router = Router(
        [], config=RouterConfig(lease_secs=lease_secs),
        stub_factory=lambda a: stubs[a], clock=clock,
        sleep=lambda s: None,
    )
    sup = ReplicaSupervisor(
        router, launcher, AutoscalerConfig(**kw), clock=clock,
        injector=injector, rng=random.Random(0),
    )
    router.set_autoscaler(sup)
    return sup, router, launcher, clock


def settle(sup, router, ticks=4):
    """A few decide ticks with heartbeats in between: enough for
    spawn -> adopt -> signals to flow."""
    for _ in range(ticks):
        sup.decide_once()
        router.poll_once()


def live_addresses(sup):
    return [s["address"] for s in sup.roster() if s["state"] == LIVE]


# -------------------------------------------------------- spawn/adopt


def test_spawns_to_min_and_adopts():
    sup, router, launcher, _ = build()
    sup.decide_once()  # reconcile: deficit -> spawn
    assert [s["state"] for s in sup.roster()] == [STARTING]
    sup.decide_once()  # poll: ready -> adopt + register with router
    assert [s["state"] for s in sup.roster()] == [LIVE]
    addrs = [r.address for r in router.replicas()]
    assert addrs == live_addresses(sup)
    st = sup.status_block()
    assert st.enabled and st.target == 1 and st.live == 1
    assert len(launcher.spawned) == 1


def test_status_block_rides_router_status():
    sup, router, _launcher, _ = build()
    settle(sup, router)
    resp = router.status_response()
    assert resp.HasField("autoscaler")
    assert resp.autoscaler.enabled and resp.autoscaler.live == 1
    wire = pb.RouterStatusResponse.FromString(resp.SerializeToString())
    assert wire.autoscaler.target == 1
    # a static-fleet router has no autoscaler block at all
    bare = Router([], stub_factory=lambda a: None)
    assert not bare.status_response().HasField("autoscaler")


# ------------------------------------------------------------ scale up


def _pressure(launcher, router, on=True):
    for stub in launcher.stubs.values():
        # real pressure = high measured waits AND work present (a
        # frozen EWMA over an empty queue is history, not pressure)
        stub.queue_wait_ms = 500.0 if on else 0.0
        stub.queue_depth = 1 if on else 0
    router.poll_once()


def test_scale_up_needs_a_sustained_window():
    sup, router, launcher, clock = build()
    settle(sup, router)
    _pressure(launcher, router)
    sup.decide_once()  # pressure seen; window starts
    assert sup.target == 1
    # pressure breaks before the window elapses: no decision, and the
    # window must RESTART (hysteresis, not accumulation)
    _pressure(launcher, router, on=False)
    sup.decide_once()
    clock.advance(2.0)
    _pressure(launcher, router)
    sup.decide_once()  # window restarts now
    assert sup.target == 1
    clock.advance(1.1)
    _pressure(launcher, router)
    sup.decide_once()
    assert sup.target == 2 and sup.scale_ups == 1
    assert sup.last_decision == "scale_up"


def test_scale_up_cooldown_and_max_bound():
    sup, router, launcher, clock = build()
    settle(sup, router)
    _pressure(launcher, router)
    sup.decide_once()  # window opens
    clock.advance(1.1)
    _pressure(launcher, router)
    sup.decide_once()
    assert sup.target == 2
    settle(sup, router)  # second replica spawns + adopts
    assert sup.status_block().live == 2
    # pressure persists, but the cooldown holds the next decision
    _pressure(launcher, router)
    clock.advance(1.2)
    _pressure(launcher, router)
    sup.decide_once()
    assert sup.target == 2
    # cooldown elapses: third replica; then the max bound caps it
    clock.advance(3.0)
    _pressure(launcher, router)
    sup.decide_once()
    clock.advance(1.1)
    _pressure(launcher, router)
    sup.decide_once()
    assert sup.target == 3
    settle(sup, router)
    for _ in range(3):
        clock.advance(5.0)
        _pressure(launcher, router)
        sup.decide_once()
    assert sup.target == 3  # max_replicas is a hard ceiling


def test_no_decision_while_fleet_unsettled():
    """A scale decision while a spawn is still starting would be
    acting blind: the settled-fleet gate blocks it."""
    sup, router, launcher, clock = build()
    launcher.auto_ready = False
    settle(sup, router)
    assert [s["state"] for s in sup.roster()] == [STARTING]
    _pressure(launcher, router)
    clock.advance(5.0)
    sup.decide_once()
    assert sup.target == 1 and sup.scale_ups == 0


# ---------------------------------------------------------- scale down


def test_scale_down_drains_gracefully_and_closes_channel():
    sup, router, launcher, clock = build(min_replicas=1)
    sup.target = 2
    settle(sup, router, ticks=6)
    assert sup.status_block().live == 2
    # on an idle tie the NEWEST seat drains (load tie-break); hold it
    # mid-drain so the DRAINING state is observable
    roster = sup.roster()
    victim_addr = roster[1]["address"]
    victim_handle = launcher.spawned[1]
    victim_handle.exit_on_terminate = False
    router.poll_once()
    sup.decide_once()  # idle window starts
    clock.advance(2.1)
    router.poll_once()
    sup.decide_once()  # sustained idle -> target 1, drain begins
    assert sup.target == 1 and sup.scale_downs == 1
    assert victim_handle.terminated and not victim_handle.killed
    roster = {s["seat"]: s for s in sup.roster()}
    assert roster[1]["state"] == DRAINING
    # still registered (its in-flight streams finish through the
    # router's drain advertisement), channel still open
    assert victim_addr in [r.address for r in router.replicas()]
    assert launcher.stubs[victim_addr].closed == 0
    # the replica finishes draining and exits 0 -> retire: channel
    # closed, registry entry gone
    victim_handle.rc = 0
    sup.decide_once()
    assert victim_addr not in [r.address for r in router.replicas()]
    assert launcher.stubs[victim_addr].closed == 1
    assert sup.status_block().live == 1


def test_scale_down_after_burst_with_stale_ewma():
    """After a burst stops DEAD, the queue-wait EWMA freezes at its
    last (high) value — no samples flow to decay it. Zero routed
    traffic across the idle window must satisfy the gate anyway, or a
    post-burst fleet could never scale down."""
    sup, router, launcher, clock = build()
    sup.target = 2
    settle(sup, router, ticks=6)
    for stub in launcher.stubs.values():
        stub.queue_wait_ms = 5000.0  # the burst's frozen EWMA
    router.poll_once()
    sup.decide_once()  # quiet tick: routed baseline recorded
    sup.decide_once()  # routed unchanged -> idle window opens
    clock.advance(2.1)
    router.poll_once()
    sup.decide_once()
    assert sup.target == 1 and sup.scale_downs == 1


def test_scale_down_requires_kv_headroom():
    sup, router, launcher, clock = build(down_free_kv_blocks=100)
    sup.target = 2
    settle(sup, router, ticks=6)
    # idle, but the fleet has no free-KV headroom: hold the capacity
    for stub in launcher.stubs.values():
        stub.kv_blocks_free = 10  # sum 20 < 100
    router.poll_once()
    sup.decide_once()
    clock.advance(3.0)
    router.poll_once()
    sup.decide_once()
    assert sup.target == 2 and sup.scale_downs == 0
    # reclaimable cached blocks ARE headroom: with prefix sharing on,
    # a drained fleet parks everything in the refcount-0 cache and
    # kv_blocks_free alone reads zero forever
    for stub in launcher.stubs.values():
        stub.kv_blocks_free = 0
        stub.kv_blocks_cached = 60  # sum 120 >= 100
    router.poll_once()
    sup.decide_once()  # idle window opens now that the gate passes
    clock.advance(2.1)
    router.poll_once()
    sup.decide_once()
    assert sup.target == 1 and sup.scale_downs == 1


def test_drain_timeout_escalates_to_kill():
    sup, router, launcher, clock = build()
    sup.target = 2
    settle(sup, router, ticks=6)
    victim_handle = launcher.spawned[1]
    victim_handle.exit_on_terminate = False
    router.poll_once()
    sup.decide_once()
    clock.advance(2.1)
    router.poll_once()
    sup.decide_once()  # drain begins
    assert victim_handle.terminated
    clock.advance(10.1)  # drain_timeout_secs
    sup.decide_once()
    assert victim_handle.killed
    sup.decide_once()  # the kill's exit retires the seat
    assert sup.status_block().live == 1


# -------------------------------------------------- crash replacement


def test_crashed_replica_is_replaced():
    sup, router, launcher, _clock = build()
    settle(sup, router)
    dead_addr = live_addresses(sup)[0]
    launcher.spawned[0].rc = -9  # SIGKILLed from outside
    sup.decide_once()  # reap + respawn in one tick
    assert sup.replacements == 1
    assert dead_addr not in [r.address for r in router.replicas()]
    settle(sup, router)
    assert sup.status_block().live == 1
    assert len(launcher.spawned) == 2


def test_spawn_failures_back_off_then_open_the_circuit():
    sup, router, launcher, clock = build()
    launcher.auto_ready = False

    def fail_current_spawn():
        launcher.spawned[-1].rc = 1  # dies before ready

    sup.decide_once()  # spawn 1
    fail_current_spawn()
    sup.decide_once()  # reap: failure 1, backoff armed
    assert sup.spawn_failures == 1
    spawns = len(launcher.spawned)
    sup.decide_once()  # inside the backoff window: no spawn
    assert len(launcher.spawned) == spawns
    clock.advance(1.1)  # past max_delay_secs
    sup.decide_once()  # spawn 2
    assert len(launcher.spawned) == spawns + 1
    fail_current_spawn()
    sup.decide_once()  # failure 2
    clock.advance(1.1)
    sup.decide_once()  # spawn 3
    fail_current_spawn()
    sup.decide_once()  # failure 3 == max_restarts -> circuit OPEN
    assert sup.circuit_open
    assert sup.last_decision == "circuit_open"
    spawns = len(launcher.spawned)
    for _ in range(5):
        clock.advance(5.0)
        sup.decide_once()
    assert len(launcher.spawned) == spawns  # no hot respawn loop
    assert sup.status_block().circuit_open


def test_successful_adoption_resets_the_failure_streak():
    sup, router, launcher, clock = build()
    launcher.auto_ready = False
    sup.decide_once()
    launcher.spawned[-1].rc = 1
    sup.decide_once()
    clock.advance(1.1)
    sup.decide_once()  # respawn
    launcher.make_ready(launcher.spawned[-1])
    sup.decide_once()  # adopt
    assert sup.status_block().live == 1
    assert sup._consec_failures == 0


def test_wedged_replica_is_killed_and_replaced():
    """LEASE-DECAY FALLBACK path (pre-health replicas: the stub's
    health_state is ""): a SIGSTOPped/hung replica never exits, but
    its lease decays — the supervisor must kill and replace it on the
    conservative wedged_after_secs window."""
    sup, router, launcher, clock = build(lease_secs=5.0)
    settle(sup, router)
    wedged = launcher.spawned[0]
    assert launcher.stubs[wedged.address].health_state == ""
    launcher.stubs[wedged.address].poll_ok = False
    clock.advance(6.0)  # lease decays un-renewed
    router.poll_once()
    sup.decide_once()  # unhealthy window starts
    assert not wedged.killed
    clock.advance(2.1)  # wedged_after_secs
    sup.decide_once()
    assert wedged.killed
    sup.decide_once()  # the kill's exit -> reap + respawn
    assert sup.replacements == 1
    settle(sup, router)
    assert sup.status_block().live == 1


def test_self_reported_stall_beats_the_lease_heuristic():
    """SELF-REPORT path (runtime health plane): a replica whose
    watchdog says `stalled` keeps renewing its lease (the gRPC
    threads are fine — only the scheduler is wedged), so the lease
    path would need wedged_after_secs of silence that never comes.
    The supervisor must kill it on the seconds-scale
    stalled_kill_after_secs budget instead, while the lease stays
    VALID the whole way."""
    sup, router, launcher, clock = build(
        wedged_after_secs=30.0, stalled_kill_after_secs=1.0,
    )
    settle(sup, router)
    wedged = launcher.spawned[0]
    stub = launcher.stubs[wedged.address]
    stub.health_state = "stalled"
    stub.last_progress_age_ms = 4000.0
    router.poll_once()
    # the stalled replica leaves the dispatch rotation immediately
    # (still registered, lease still valid)
    rep = {r.address: r for r in router.replicas()}[wedged.address]
    assert rep.lease_ok(clock()) and not rep.in_rotation(clock())
    assert rep.health_state == "stalled"
    sup.decide_once()  # stalled window opens
    assert not wedged.killed
    clock.advance(1.1)  # stalled_kill_after_secs — NOT 30 s
    router.poll_once()
    sup.decide_once()
    assert wedged.killed
    sup.decide_once()
    assert sup.replacements == 1
    settle(sup, router)
    assert sup.status_block().live == 1


def test_stall_self_report_recovery_cancels_the_kill():
    """A stall that RECOVERS (tokens flow again — e.g. a pathological
    but finite compile) before the kill budget elapses must reset the
    window: transient pain is not grounds for execution."""
    sup, router, launcher, clock = build(stalled_kill_after_secs=2.0)
    settle(sup, router)
    seat = launcher.spawned[0]
    stub = launcher.stubs[seat.address]
    stub.health_state = "stalled"
    router.poll_once()
    sup.decide_once()  # window opens
    clock.advance(1.0)
    stub.health_state = "ok"  # recovered
    router.poll_once()
    sup.decide_once()  # window must reset
    clock.advance(5.0)
    router.poll_once()
    sup.decide_once()
    assert not seat.killed
    assert sup.replacements == 0
    # a replica back to "ok" rejoins the rotation
    rep = {r.address: r for r in router.replicas()}[seat.address]
    assert rep.in_rotation(clock())


# ------------------------------------------------------ fault injection


def test_spawn_fail_injection_backs_off_and_recovers():
    injector = FaultInjector(spec="supervisor_spawn:drop:1")
    sup, router, launcher, clock = build(injector=injector)
    sup.decide_once()  # injected spawn failure
    assert sup.spawn_failures == 1 and not launcher.spawned
    clock.advance(1.1)
    settle(sup, router)
    assert sup.status_block().live == 1
    assert injector.injected == {"supervisor_spawn": 1}


def test_adopt_drop_injection_reaps_and_respawns():
    injector = FaultInjector(spec="supervisor_adopt:drop:1")
    sup, router, launcher, clock = build(injector=injector)
    sup.decide_once()  # spawn
    sup.decide_once()  # ready, but the adoption is dropped
    assert sup.spawn_failures == 1
    assert launcher.spawned[0].killed
    assert not router.replicas()
    clock.advance(1.1)
    settle(sup, router)
    assert sup.status_block().live == 1
    assert len(launcher.spawned) == 2


def test_slow_ready_injection_delays_adoption_only():
    injector = FaultInjector(spec="supervisor_ready:delay:1:secs=0.01")
    sup, router, _launcher, _ = build(injector=injector)
    settle(sup, router)
    assert sup.status_block().live == 1
    assert injector.injected == {"supervisor_ready": 1}


# ------------------------------------------------------ crash recovery


def test_supervisor_crash_recovery_readopts_live_fleet(tmp_path):
    journal = str(tmp_path / "fleet")
    sup, router, launcher, clock = build(
        journal_dir=journal, min_replicas=2,
    )
    settle(sup, router, ticks=6)
    pids = sorted(s["pid"] for s in sup.roster())
    assert sup.status_block().live == 2
    sup.abandon()  # process death: journal + replicas left as-is

    sup2 = ReplicaSupervisor(
        router, launcher,
        AutoscalerConfig(min_replicas=2, max_replicas=3,
                         journal_dir=journal),
        clock=clock, rng=random.Random(1),
    )
    assert sorted(s["pid"] for s in sup2.roster()) == pids
    assert sup2.supervisor_restarts == 1
    spawned_before = len(launcher.spawned)
    settle(sup2, router, ticks=4)
    # re-adopted, never re-spawned: same pids, no new processes
    assert len(launcher.spawned) == spawned_before
    assert sorted(s["pid"] for s in sup2.roster()) == pids
    assert sup2.status_block().live == 2
    assert sup2.status_block().supervisor_restarts == 1


def test_recovery_mid_scale_up_finishes_the_spawn_without_doubling(
        tmp_path):
    """Killed between launch and adoption: the new supervisor must
    attach to the half-started process and adopt it when it becomes
    ready — not spawn a second one."""
    journal = str(tmp_path / "fleet")
    sup, router, launcher, clock = build(
        journal_dir=journal, min_replicas=2,
    )
    launcher.auto_ready = False
    sup.decide_once()
    sup.decide_once()  # two seats launched, neither ready yet
    assert [s["state"] for s in sup.roster()] == [STARTING, STARTING]
    sup.abandon()

    sup2 = ReplicaSupervisor(
        router, launcher,
        AutoscalerConfig(min_replicas=2, max_replicas=3,
                         journal_dir=journal),
        clock=clock, rng=random.Random(1),
    )
    assert [s["state"] for s in sup2.roster()] == [STARTING, STARTING]
    for _ in range(3):
        sup2.decide_once()
    assert len(launcher.spawned) == 2  # no double-spawn
    # the half-started replicas become ready under the NEW supervisor
    for handle in launcher.spawned:
        launcher.make_ready(handle)
    settle(sup2, router)
    assert sup2.status_block().live == 2
    assert sorted(s["pid"] for s in sup2.roster()) == sorted(
        h.pid for h in launcher.spawned
    )


def test_recovery_reaps_dead_seats_and_respawns(tmp_path):
    journal = str(tmp_path / "fleet")
    sup, router, launcher, clock = build(
        journal_dir=journal, min_replicas=2,
    )
    settle(sup, router, ticks=6)
    dead = launcher.spawned[0]
    dead_addr = dead.address
    sup.abandon()
    dead.rc = -9  # dies during the supervisor outage

    sup2 = ReplicaSupervisor(
        router, launcher,
        AutoscalerConfig(min_replicas=2, max_replicas=3,
                         journal_dir=journal),
        clock=clock, rng=random.Random(1),
    )
    # only the survivor is re-adopted; the dead seat was reaped
    assert [s["pid"] for s in sup2.roster()] == [
        launcher.spawned[1].pid
    ]
    settle(sup2, router, ticks=6)
    assert sup2.status_block().live == 2
    assert len(launcher.spawned) == 3  # exactly one respawn
    assert dead_addr not in [r.address for r in router.replicas()]


def test_stop_terminates_and_retires_the_fleet(tmp_path):
    journal = str(tmp_path / "fleet")
    sup, router, launcher, _clock = build(
        journal_dir=journal, min_replicas=2,
    )
    settle(sup, router, ticks=6)
    sup.stop(grace=1.0)
    assert sup.roster() == []
    assert not router.replicas()
    assert all(h.terminated for h in launcher.spawned)
    # a successor sees an empty roster, not ghosts
    sup2 = ReplicaSupervisor(
        router, launcher,
        AutoscalerConfig(min_replicas=2, journal_dir=journal),
    )
    assert sup2.roster() == []


def test_recovery_replays_decision_counters(tmp_path):
    """Scale decisions and replacements made BEFORE the crash survive
    it: the journal's target/reap events recount them on replay, so a
    recovered supervisor reports the roster's history, not just what
    happened since the last snapshot."""
    journal = str(tmp_path / "fleet")
    sup, router, launcher, clock = build(journal_dir=journal)
    settle(sup, router)
    _pressure(launcher, router)
    sup.decide_once()
    clock.advance(1.1)
    _pressure(launcher, router)
    sup.decide_once()
    assert sup.scale_ups == 1
    settle(sup, router)
    launcher.spawned[0].rc = -9
    sup.decide_once()  # reap + replace
    assert sup.replacements == 1
    settle(sup, router)
    sup.abandon()

    sup2 = ReplicaSupervisor(
        router, launcher,
        AutoscalerConfig(min_replicas=1, max_replicas=3,
                         journal_dir=journal),
        clock=clock, rng=random.Random(1),
    )
    st = sup2.status_block()
    assert st.scale_ups == 1 and st.replacements == 1


def test_journal_is_wal_compacted(tmp_path):
    """Snapshot compaction keeps replay bounded without losing the
    roster (snapshot_every=3 forces compactions in a short run)."""
    journal = str(tmp_path / "fleet")
    sup, router, launcher, clock = build(
        journal_dir=journal, min_replicas=2, snapshot_every=3,
    )
    settle(sup, router, ticks=6)
    assert sup._store.compactions >= 1
    sup.abandon()
    sup2 = ReplicaSupervisor(
        router, launcher,
        AutoscalerConfig(min_replicas=2, journal_dir=journal,
                         snapshot_every=3),
        clock=clock,
    )
    assert sup2.status_block().live == 2


if __name__ == "__main__":
    raise SystemExit(pytest.main([__file__, "-q"]))
