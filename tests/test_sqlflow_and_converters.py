"""census_model_sqlflow zoo family + real-dataset converters (VERDICT.md
round-1 missing #4/#5): the transform-op graph interpreter, both sqlflow
variants training e2e, and the image/CSV -> TRec converters."""

import os

import numpy as np
import pytest

from elasticdl_tpu.api.local_executor import LocalExecutor
from elasticdl_tpu.common.model_utils import get_model_spec
from elasticdl_tpu.data import recordio_gen
from elasticdl_tpu.data.record_format import Scanner, get_record_count
from elasticdl_tpu.data.example_codec import decode_example
from model_zoo.census_model_sqlflow import feature_configs as cfg
from model_zoo.census_model_sqlflow import transform_ops as ops

# CI drills shard (make test-drills): the sub-5-min per-commit gate excludes this file.
pytestmark = pytest.mark.slow

MODEL_ZOO = "model_zoo"


# ------------------------------------------------------- transform graph


def test_topo_sort_orders_dependencies():
    sources = [s.name for s in cfg.INPUT_SCHEMAS]
    ordered = ops.topo_sort(cfg.FEATURE_TRANSFORM_INFO, sources)
    seen = set(sources)
    for op in ordered:
        assert all(i in seen for i in op.inputs), (
            "%s ran before its inputs" % op.name
        )
        seen.add(op.output)
    assert len(ordered) == len(cfg.FEATURE_TRANSFORM_INFO)


def test_topo_sort_rejects_unknown_inputs():
    bad = [ops.Hash("h", "nonexistent_column", "h", 8)]
    with pytest.raises(ValueError, match="unknown inputs|unresolvable"):
        ops.topo_sort(bad, ["a"])


def test_execute_host_ops_offsets_and_groups():
    sources = [s.name for s in cfg.INPUT_SCHEMAS]
    ordered = ops.topo_sort(cfg.FEATURE_TRANSFORM_INFO, sources)
    example = {
        "education": np.array(b"Bachelors"),
        "occupation": np.array(b"Sales"),
        "native-country": np.array(b"United-States"),
        "workclass": np.array(b"Private"),
        "marital-status": np.array(b"Divorced"),
        "relationship": np.array(b"Wife"),
        "race": np.array(b"White"),
        "sex": np.array(b"Female"),
        "age": np.array(38.0, np.float32),
        "capital-gain": np.array(6200.0, np.float32),
        "capital-loss": np.array(0.0, np.float32),
        "hours-per-week": np.array(40.0, np.float32),
    }
    values = ops.execute_host_ops(ordered, example)
    # group1 = workclass lookup + 3 bucketized numerics, offset into one
    # id space of sum([9, 7, 6, 6]) ids (vocab 8 + 1 OOV, boundaries+1)
    g1 = values["group1"]
    assert g1.shape == (4,)
    dim1 = cfg.group1_embedding_wide.input_dim
    assert (0 <= g1).all() and (g1 < dim1).all()
    # workclass "Private" is vocab index 0; offsets put it at 0
    assert g1[0] == 0
    # hours 40 -> bucket 4 of boundaries [10,20,30,40,50,60] + offset 9
    assert g1[1] == 9 + 4
    # capital-gain 6200 -> bucket 1 + offset 9+7
    assert g1[2] == 16 + 1
    for name in ("group2", "group3"):
        g = values[name]
        emb = {"group2": cfg.group2_embedding_deep,
               "group3": cfg.group3_embedding_deep}[name]
        assert g.shape == (4,)
        assert (0 <= g).all() and (g < emb.input_dim).all()


# ----------------------------------------------------------- e2e training


def _run(spec_key, tmp_path):
    train_dir, val_dir = str(tmp_path / "train"), str(tmp_path / "val")
    recordio_gen.gen_census_raw(train_dir, num_files=1, records_per_file=32)
    recordio_gen.gen_census_raw(val_dir, num_files=1, records_per_file=32,
                                seed=7)
    spec = get_model_spec(MODEL_ZOO, spec_key)
    executor = LocalExecutor(
        spec,
        training_data=train_dir,
        validation_data=val_dir,
        minibatch_size=8,
        num_epochs=1,
        records_per_task=32,
    )
    state, metrics = executor.run()
    assert int(state.step) == 4
    assert np.isfinite(executor.losses).all()
    return metrics


def test_sqlflow_wide_and_deep_e2e(tmp_path):
    metrics = _run(
        "census_model_sqlflow.wide_and_deep.census_wide_and_deep"
        ".custom_model",
        tmp_path,
    )
    assert 0.0 <= metrics["logits_accuracy"] <= 1.0
    assert 0.0 <= metrics["probs_auc"] <= 1.0


def test_sqlflow_dnn_e2e(tmp_path):
    metrics = _run(
        "census_model_sqlflow.dnn.census_dnn.custom_model", tmp_path
    )
    assert 0.0 <= metrics["accuracy"] <= 1.0


# ------------------------------------------------------------- converters


def test_convert_arrays_sharding(tmp_path):
    x = np.arange(25 * 4 * 4, dtype=np.float32).reshape(25, 4, 4)
    y = np.arange(25) % 3
    paths = recordio_gen.convert_arrays(
        str(tmp_path), x, y, records_per_shard=10
    )
    assert [os.path.basename(p) for p in paths] == [
        "data-00000.trec", "data-00001.trec", "data-00002.trec",
    ]
    assert [get_record_count(p) for p in paths] == [10, 10, 5]
    ex = decode_example(next(iter(Scanner(paths[1]))))
    np.testing.assert_allclose(ex["image"], x[10])
    assert int(ex["label"]) == y[10]
    # fraction keeps the leading slice (reference image_label.py args)
    paths = recordio_gen.convert_arrays(
        str(tmp_path / "frac"), x, y, records_per_shard=10, fraction=0.4
    )
    assert sum(get_record_count(p) for p in paths) == 10


def test_convert_image_dir(tmp_path):
    PIL = pytest.importorskip("PIL")
    from PIL import Image

    img_root = tmp_path / "imgs"
    for cls in ("cat", "dog"):
        (img_root / cls).mkdir(parents=True)
        for i in range(3):
            arr = np.full((8, 8), 40 * i, np.uint8)
            Image.fromarray(arr).save(img_root / cls / ("%d.png" % i))
    out = str(tmp_path / "rec")
    paths, classes = recordio_gen.convert_image_dir(str(img_root), out)
    assert classes == ["cat", "dog"]
    records = [decode_example(r) for p in paths for r in Scanner(p)]
    assert len(records) == 6
    labels = sorted(int(r["label"]) for r in records)
    assert labels == [0, 0, 0, 1, 1, 1]
    assert records[0]["image"].shape == (8, 8)


def test_convert_csv(tmp_path):
    csv_path = tmp_path / "heart.csv"
    csv_path.write_text(
        "age,chol,thal,target\n"
        "63,233,fixed,1\n"
        "37,250.5,normal,0\n"
        "41,204,reversible,1\n"
    )
    out = str(tmp_path / "rec")
    paths = recordio_gen.convert_csv(
        str(csv_path), out, records_per_shard=2, label_column="target"
    )
    assert [get_record_count(p) for p in paths] == [2, 1]
    records = [decode_example(r) for p in paths for r in Scanner(p)]
    assert int(records[0]["age"]) == 63
    assert records[1]["chol"].dtype == np.float32  # column sniffed float
    assert records[0]["thal"] == b"fixed"
    assert records[2]["target"] == 1 and records[2]["target"].dtype == np.int64


def test_convert_image_dir_mixed_shapes(tmp_path):
    pytest.importorskip("PIL")
    from PIL import Image

    img_root = tmp_path / "imgs"
    (img_root / "a").mkdir(parents=True)
    Image.fromarray(np.zeros((8, 8), np.uint8)).save(img_root / "a" / "g.png")
    Image.fromarray(np.zeros((8, 8, 3), np.uint8)).save(
        img_root / "a" / "rgb.png"
    )
    with pytest.raises(ValueError, match="image_size and/or image_mode"):
        recordio_gen.convert_image_dir(str(img_root), str(tmp_path / "o"))
    # normalizing the mode fixes it
    paths, _ = recordio_gen.convert_image_dir(
        str(img_root), str(tmp_path / "o2"), image_mode="RGB"
    )
    records = [decode_example(r) for p in paths for r in Scanner(p)]
    assert all(r["image"].shape == (8, 8, 3) for r in records)
    # stray non-image files and nested dirs are skipped, not fatal
    (img_root / "a" / ".DS_Store").write_bytes(b"\x00junk")
    (img_root / "a" / "nested").mkdir()
    paths, _ = recordio_gen.convert_image_dir(
        str(img_root), str(tmp_path / "o3"), image_mode="RGB"
    )
    assert sum(1 for p in paths for _ in Scanner(p)) == 2


def test_convert_csv_ragged_row_and_long_strings(tmp_path):
    p = tmp_path / "r.csv"
    p.write_text("a,b\n1,2\n3\n")
    with pytest.raises(ValueError, match="line 3"):
        recordio_gen.convert_csv(str(p), str(tmp_path / "o"))
    # >64-byte strings survive exactly (no fixed-width truncation)
    long = "x" * 200
    p2 = tmp_path / "s.csv"
    p2.write_text("a,s\n1,%s\n" % long)
    paths = recordio_gen.convert_csv(str(p2), str(tmp_path / "o2"))
    rec = decode_example(next(iter(Scanner(paths[0]))))
    assert rec["s"] == long.encode()


def test_convert_csv_empty_and_bad_label(tmp_path):
    p = tmp_path / "empty.csv"
    p.write_text("a,b\n")
    assert recordio_gen.convert_csv(str(p), str(tmp_path / "o")) == []
    p2 = tmp_path / "x.csv"
    p2.write_text("a,b\n1,2\n")
    with pytest.raises(ValueError, match="label column"):
        recordio_gen.convert_csv(str(p2), str(tmp_path / "o2"),
                                 label_column="nope")
