
import numpy as np

from elasticdl_tpu.data import recordio_gen
from elasticdl_tpu.data.example_codec import decode_example
from elasticdl_tpu.data.reader.csv_reader import CSVDataReader
from elasticdl_tpu.data.reader.data_reader_factory import create_data_reader
from elasticdl_tpu.data.reader.recordio_reader import RecordIODataReader
from elasticdl_tpu.master.task_dispatcher import Task, TaskType


def _task(shard, start, end):
    return Task(shard, start, end, TaskType.TRAINING)


def test_recordio_reader_shards_and_records(tmp_path):
    data_dir = str(tmp_path / "mnist")
    recordio_gen.gen_mnist_like(data_dir, num_files=3, records_per_file=17)
    reader = RecordIODataReader(data_dir=data_dir)
    shards = reader.create_shards()
    assert len(shards) == 3
    assert all(v == (0, 17) for v in shards.values())
    shard = next(iter(shards))
    records = list(reader.read_records(_task(shard, 5, 12)))
    assert len(records) == 7
    ex = decode_example(records[0])
    assert ex["image"].shape == (28, 28)
    assert ex["label"].dtype == np.int32


def test_csv_reader(tmp_path):
    path = tmp_path / "d.csv"
    path.write_text("a,b,c\n" + "\n".join("%d,%d,%d" % (i, i, i) for i in range(20)) + "\n")
    reader = CSVDataReader(data_dir=str(tmp_path))
    shards = reader.create_shards()
    assert shards[str(path)] == (0, 20)
    rows = list(reader.read_records(_task(str(path), 3, 6)))
    assert rows == [["3", "3", "3"], ["4", "4", "4"], ["5", "5", "5"]]
    assert reader.metadata.column_names == ["a", "b", "c"]


def test_factory_sniffs(tmp_path):
    csv_dir = tmp_path / "csvs"
    csv_dir.mkdir()
    (csv_dir / "x.csv").write_text("a\n1\n")
    assert isinstance(create_data_reader(str(csv_dir)), CSVDataReader)

    rec_dir = str(tmp_path / "recs")
    recordio_gen.gen_mnist_like(rec_dir, num_files=1, records_per_file=2)
    assert isinstance(create_data_reader(rec_dir), RecordIODataReader)

    assert isinstance(
        create_data_reader(str(csv_dir), reader_type="RecordIO"),
        RecordIODataReader,
    )
