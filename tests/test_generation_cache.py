"""Locks the two offline-decode properties the serving scheduler builds
on (tier-1: the serving engine reuses the compile cache and the
KV-cache decode path):

* `_LRUCache` — the bounded compile cache: insertion bound, true LRU
  eviction order, get() recency refresh, reinsert move-to-back;
* cache-strategy parity — greedy decode with use_cache=True must
  produce exactly the tokens of the full-recompute strategy."""

import numpy as np

import jax

from elasticdl_tpu.api.generation import (
    _LRUCache,
    autoregressive_generate,
)


# ------------------------------------------------------------ _LRUCache


def test_lru_bound_holds_under_overflow():
    c = _LRUCache()
    for i in range(3 * c.max_entries):
        c[("k", i)] = i
        assert len(c) <= c.max_entries
    # the survivors are exactly the most recent max_entries inserts
    lo = 3 * c.max_entries - c.max_entries
    assert set(c) == {("k", i) for i in range(lo, 3 * c.max_entries)}


def test_lru_evicts_least_recently_used_first():
    c = _LRUCache()
    c.max_entries = 3
    c["a"], c["b"], c["c"] = 1, 2, 3
    # touch "a": "b" becomes the LRU entry
    assert c.get("a") == 1
    c["d"] = 4
    assert "b" not in c and set(c) == {"a", "c", "d"}
    # untouched order: "c" is now LRU
    c["e"] = 5
    assert "c" not in c and set(c) == {"a", "d", "e"}


def test_lru_get_miss_and_reinsert_refresh():
    c = _LRUCache()
    c.max_entries = 2
    assert c.get("missing") is None
    assert c.get("missing", 7) == 7
    c["a"], c["b"] = 1, 2
    # reinserting an existing key must refresh recency, not grow
    c["a"] = 10
    assert len(c) == 2 and c.get("a") == 10
    c["c"] = 3  # evicts "b" (LRU after a's refresh)
    assert "b" not in c and set(c) == {"a", "c"}


def test_trainer_compile_cache_is_bounded(monkeypatch):
    """A sweep over sampling configs must not grow the per-trainer
    compile cache past the bound (each distinct temperature is one
    compiled executable)."""
    trainer, state = _tiny_rig()
    monkeypatch.setattr(_LRUCache, "max_entries", 4)
    prompt = np.asarray([[1, 2, 3]], np.int32)
    for i in range(8):
        autoregressive_generate(
            trainer, state, prompt, 2, temperature=0.5 + 0.1 * i, seed=0
        )
    assert len(trainer._generate_cache) <= 4


# ------------------------------------------------- cache-strategy parity


def _tiny_rig():
    from elasticdl_tpu.common.model_utils import (
        load_model_spec_from_module,
    )
    from elasticdl_tpu.parallel import mesh as mesh_lib
    from elasticdl_tpu.training.trainer import Trainer
    from model_zoo.transformer_lm import transformer_lm as zoo

    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = Trainer(
        load_model_spec_from_module(zoo), mesh=mesh,
        model_params=(
            "vocab_size=8; seq_len=16; embed_dim=32; num_heads=2; "
            "num_layers=1"
        ),
    )
    toks = (np.arange(17)[None, :] % 8).astype(np.int32)
    state = trainer.init_state(({"tokens": toks[:, :-1]}, toks[:, 1:]))
    return trainer, state


def test_greedy_cache_strategy_parity():
    """use_cache=True (batched prefill + per-token KV steps) and the
    full-recompute strategy must emit IDENTICAL greedy tokens for mixed
    prompt lengths and continuation budgets."""
    trainer, state = _tiny_rig()
    for prompt, new in (
        ([[1, 2, 3], [4, 5, 6]], 5),
        ([[2]], 8),
        ([[7, 0, 1, 2, 3, 4]], 3),
    ):
        p = np.asarray(prompt, np.int32)
        full = np.asarray(
            autoregressive_generate(trainer, state, p, new)
        )
        cached = np.asarray(
            autoregressive_generate(
                trainer, state, p, new, use_cache=True
            )
        )
        np.testing.assert_array_equal(full, cached)
