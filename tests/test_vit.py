"""ViT family: patch-embedding geometry, learning on separable synthetic
images, and e2e training through the LocalExecutor on cifar10-shaped
TRec records."""

import numpy as np
import pytest

import jax

from elasticdl_tpu.api.local_executor import LocalExecutor
from elasticdl_tpu.common.model_utils import (
    format_params_str,
    get_model_spec,
    load_model_spec_from_module,
)
from elasticdl_tpu.data import recordio_gen
from elasticdl_tpu.training.trainer import Trainer
from model_zoo.vit import vit

# CI drills shard (make test-drills): the per-commit gate excludes this file.
pytestmark = pytest.mark.slow

MODEL_ZOO = "model_zoo"


def test_patchify_geometry():
    """Each projected row must be one spatial patch. The invariant:
    perturbing a pixel changes EXACTLY the perturbed patch's row of the
    patch_embed output (captured via flax intermediates) — a reshape
    that produced pixel stripes instead of spatial patches would smear
    the change across rows."""
    m = vit.ViT(image_size=8, patch_size=4, embed_dim=16, num_heads=2,
                num_layers=0, dropout=0.0)
    base_img = np.zeros((1, 8, 8, 3), np.float32)
    params = m.init(jax.random.PRNGKey(0), {"image": base_img})

    def patch_rows(img):
        out, inter = m.apply(params, {"image": img},
                             capture_intermediates=True)
        assert out.shape == (1, 10)
        assert np.isfinite(np.asarray(out)).all()
        return np.asarray(
            inter["intermediates"]["patch_embed"]["__call__"][0]
        )[0]  # [n_patches, embed_dim]

    base = patch_rows(base_img)
    assert base.shape[0] == 4  # 8/4 x 8/4 patches
    for (r, c), row in [((1, 1), 0), ((1, 5), 1), ((5, 1), 2),
                        ((6, 7), 3)]:
        img = base_img.copy()
        img[0, r, c, 0] = 1.0
        changed = np.abs(patch_rows(img) - base).max(axis=1) > 1e-7
        expect = np.zeros(4, bool)
        expect[row] = True
        np.testing.assert_array_equal(
            changed, expect,
            err_msg="pixel (%d,%d) must touch only patch row %d"
                    % (r, c, row),
        )


def _separable_batch(rng, b=16):
    """Class k = bright 8x8 quadrant k (trivially separable)."""
    labels = rng.randint(0, 4, size=b).astype(np.int32)
    imgs = rng.rand(b, 32, 32, 3).astype(np.float32) * 0.1
    for i, k in enumerate(labels):
        r, c = divmod(int(k), 2)
        imgs[i, r * 16:(r + 1) * 16, c * 16:(c + 1) * 16, :] += 0.9
    return {"image": imgs.reshape(b, -1)}, labels


def test_vit_learns_separable_images():
    spec = load_model_spec_from_module(vit)
    trainer = Trainer(
        spec,
        model_params=format_params_str(
            dict(num_classes=4, embed_dim=32, num_heads=2, num_layers=1,
                 attn_impl="xla")
        ),
    )
    rng = np.random.RandomState(0)
    batch = _separable_batch(rng)
    state = trainer.init_state(batch)
    losses = []
    for _ in range(80):
        state, loss = trainer.train_step(state, batch)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.35, losses[::20]


def test_vit_e2e_local_executor(tmp_path):
    train_dir = str(tmp_path / "train")
    val_dir = str(tmp_path / "val")
    recordio_gen.gen_cifar10_like(train_dir, num_files=1,
                                  records_per_file=64)
    recordio_gen.gen_cifar10_like(val_dir, num_files=1,
                                  records_per_file=32, seed=7)
    spec = get_model_spec(MODEL_ZOO, "vit.vit.custom_model")
    executor = LocalExecutor(
        spec, training_data=train_dir, validation_data=val_dir,
        num_epochs=1, minibatch_size=16,
        model_params="embed_dim=32;num_heads=2;num_layers=1;"
                     "attn_impl=xla",
    )
    _, metrics = executor.run()
    assert 0.0 <= metrics["accuracy"] <= 1.0


def test_invalid_geometry_raises():
    """Config validation fails fast: indivisible patch grid AND an
    embed_dim that doesn't split across heads (which would otherwise
    silently floor head_dim and shrink attention width)."""
    img = {"image": np.zeros((1, 8, 8, 3), np.float32)}
    bad_patch = vit.ViT(image_size=8, patch_size=3, embed_dim=16,
                        num_heads=2, num_layers=1, dropout=0.0)
    with pytest.raises(ValueError, match="patch_size"):
        bad_patch.init(jax.random.PRNGKey(0), img)
    bad_heads = vit.ViT(image_size=8, patch_size=4, embed_dim=15,
                        num_heads=4, num_layers=1, dropout=0.0)
    with pytest.raises(ValueError, match="num_heads"):
        bad_heads.init(jax.random.PRNGKey(0), img)
