"""Multi-cell router tier unit tests (tier-1: no jax, no sockets —
fake replica stubs behind real RouterCells sharing a real on-disk
journal, fake cell stubs in front of a real CellFront).

Locks the ISSUE's failover semantics: journaled registry sharing
(adopt/retire replay, cross-cell tailing, tick-boundary compaction,
torn-tail tolerance, crash-restart recovery), the cell_kill chaos
hook at the heartbeat tick, and the client-side cell front's bounded
reroute ladder (transient -> next ring successor, backpressure ->
propagate, stream reroute only before the first delivered chunk)."""

import json
import os
import threading

import pytest
from test_router import FakeClock, FakeReplicaStub, _req

from elasticdl_tpu.common.fault_injection import FaultInjector
from elasticdl_tpu.master.state_store import JOURNAL_FILE
from elasticdl_tpu.proto import elasticdl_pb2 as pb
from elasticdl_tpu.serving.router import RouterConfig, RouterError
from elasticdl_tpu.serving.router_cell import (
    CellFront,
    CellRegistryJournal,
    RouterCell,
)


def make_cell(journal_dir, seeds=(), cell_id=0, cells=2, clock=None,
              stubs=None, **cfg_kwargs):
    """RouterCell over fake replica stubs; the stub factory mints a
    FakeReplicaStub on demand so journal-learned replicas resolve."""
    clock = clock or FakeClock()
    stubs = {} if stubs is None else stubs

    def factory(addr):
        if addr not in stubs:
            stubs[addr] = FakeReplicaStub(
                token=100 * (len(stubs) + 1)
            )
        return stubs[addr]

    cfg = RouterConfig(
        lease_secs=10.0, breaker_threshold=2,
        breaker_cooldown_secs=5.0, redispatch_window_secs=8.0,
        base_delay_secs=0.01, max_delay_secs=0.05,
        cell_id=cell_id, cells=cells, **cfg_kwargs
    )
    cell = RouterCell(
        list(seeds), config=cfg, journal_dir=str(journal_dir),
        stub_factory=factory, clock=clock, sleep=clock.advance,
    )
    return cell, stubs, clock


# ------------------------------------------------------- journal sharing


def test_sibling_cell_learns_fleet_from_journal_alone(tmp_path):
    c0, _, _ = make_cell(tmp_path, seeds=["rep0", "rep1", "rep2"])
    # the sibling starts with NO seeds: its whole fleet view is replay
    c1, _, _ = make_cell(tmp_path, seeds=[], cell_id=1)
    assert sorted(r.address for r in c1.replicas()) == [
        "rep0", "rep1", "rep2"
    ]
    assert c1._journal.replayed >= 3
    c0.stop()
    c1.stop()


def test_membership_change_propagates_at_heartbeat_tick(tmp_path):
    c0, _, _ = make_cell(tmp_path, seeds=["rep0"])
    c1, _, _ = make_cell(tmp_path, seeds=[], cell_id=1)
    c0.add_replica("rep9")
    assert "rep9" not in [r.address for r in c1.replicas()]
    c1.poll_once()  # the tick tails the journal
    assert "rep9" in [r.address for r in c1.replicas()]
    c0.remove_replica("rep9")
    c1.poll_once()
    assert "rep9" not in [r.address for r in c1.replicas()]
    c0.stop()
    c1.stop()


def test_own_appends_are_never_replayed_back(tmp_path):
    c0, _, _ = make_cell(tmp_path, seeds=["rep0"])
    c0.add_replica("rep1")
    before = [r.address for r in c0.replicas()]
    for _ in range(3):
        c0.poll_once()
    assert [r.address for r in c0.replicas()] == before
    c0.stop()


def test_restarted_cell_recovers_fleet_from_disk(tmp_path):
    c0, _, _ = make_cell(tmp_path, seeds=["rep0", "rep1"])
    c0.stop()  # simulated crash+restart: a new process, same dir
    c0b, _, _ = make_cell(tmp_path, seeds=[])
    assert sorted(r.address for r in c0b.replicas()) == [
        "rep0", "rep1"
    ]
    # the store's cold-start-over-existing-state odometer moved
    assert c0b._journal.restarts >= 1
    c0b.stop()


def test_compaction_truncates_journal_and_preserves_state(tmp_path):
    c0, _, _ = make_cell(tmp_path, seeds=["rep0"])
    # force the snapshot threshold with direct journal records
    c0._journal._store.snapshot_every = 4
    for i in range(6):
        c0.add_replica("extra%d" % i)
        c0.remove_replica("extra%d" % i)
    assert c0._journal._pending_compact
    journal_path = os.path.join(str(tmp_path), JOURNAL_FILE)
    assert os.path.getsize(journal_path) > 0
    assert c0._journal.compact_at_tick()
    assert os.path.getsize(journal_path) == 0
    # a cold start now rebuilds purely from the snapshot
    c1, _, _ = make_cell(tmp_path, seeds=[], cell_id=1)
    assert [r.address for r in c1.replicas()] == ["rep0"]
    c0.stop()
    c1.stop()


def test_tailing_cell_resyncs_after_remote_compaction(tmp_path):
    c0, _, _ = make_cell(tmp_path, seeds=["rep0"])
    c1, _, _ = make_cell(tmp_path, seeds=[], cell_id=1)
    c0._journal._store.snapshot_every = 2
    for i in range(4):
        c0.add_replica("r%d" % i)
    c0._journal.compact_at_tick()  # journal shrinks under c1's offset
    c1.poll_once()
    assert c1._journal.resyncs >= 1
    assert set(r.address for r in c1.replicas()) >= {
        "rep0", "r0", "r1", "r2", "r3"
    }
    c0.stop()
    c1.stop()


def test_torn_journal_tail_is_tolerated(tmp_path):
    c0, _, _ = make_cell(tmp_path, seeds=["rep0"])
    c1, _, _ = make_cell(tmp_path, seeds=[], cell_id=1)
    journal_path = os.path.join(str(tmp_path), JOURNAL_FILE)
    # another cell dies mid-append: a torn, newline-less half event
    with open(journal_path, "a") as f:
        f.write('{"op": "adopt", "addr')
    c1.poll_once()  # must not crash, must not apply the torn tail
    # the writer comes back and completes its line as a FRESH event
    with open(journal_path, "a") as f:
        f.write('\n')
        f.write(json.dumps(
            {"op": "adopt", "address": "late", "cell": 0}
        ) + "\n")
    c1.poll_once()
    assert "late" in [r.address for r in c1.replicas()]
    c0.stop()
    c1.stop()


def test_lease_beacon_journaled_and_inert_under_replay(tmp_path):
    c0, stubs, _ = make_cell(tmp_path, seeds=["rep0"])
    for _ in range(RouterCell.LEASE_JOURNAL_EVERY):
        c0.poll_once()
    journal_path = os.path.join(str(tmp_path), JOURNAL_FILE)
    with open(journal_path) as f:
        ops = [json.loads(line)["op"] for line in f if line.strip()]
    assert "lease" in ops
    # a fresh cell replays the beacon as a no-op: same fleet, no crash
    c1, _, _ = make_cell(tmp_path, seeds=[], cell_id=1)
    assert [r.address for r in c1.replicas()] == ["rep0"]
    c0.stop()
    c1.stop()


def test_status_response_reports_cell_and_journal_block(tmp_path):
    c0, _, _ = make_cell(tmp_path, seeds=["rep0"], cell_id=1, cells=3)
    resp = c0.status_response()
    assert resp.cell_id == 1
    assert resp.cells == 3
    assert resp.journal_events >= 1   # the seed adopt
    assert resp.cell_restarts == c0._journal.restarts
    c0.stop()


# --------------------------------------------------------- cell_kill hook


def test_cell_kill_hook_fires_at_the_heartbeat_tick(tmp_path):
    killed = []
    injector = FaultInjector(spec="cell_kill:kill:1:skip=2",
                             kill_fn=lambda: killed.append(True))
    c0, _, _ = make_cell(tmp_path, seeds=["rep0"])
    c0._cell_injector = injector
    c0.poll_once()
    c0.poll_once()
    assert not killed  # skip=2: the first two ticks survive
    c0.poll_once()
    assert killed == [True]
    c0.stop()


# ------------------------------------------------------------- cell front


class FakeCellStub(object):
    """RouterStub-shaped fake cell: scripted failures per call."""

    def __init__(self, token):
        self.token = token
        self.gen_errors = []
        self.stream_errors = []
        self.stream_fail_after_chunks = None
        self.calls = 0
        self.closed = 0

    def close(self):
        self.closed += 1

    def router_generate(self, request, timeout=None):
        self.calls += 1
        if self.gen_errors:
            raise self.gen_errors.pop(0)
        return pb.GenerateResponse(
            tokens=list(request.prompt) + [self.token],
            model_version=1,
        )

    def router_generate_stream(self, request, timeout=None):
        self.calls += 1
        if self.stream_errors:
            raise self.stream_errors.pop(0)

        def chunks():
            for i in range(request.max_new_tokens):
                if self.stream_fail_after_chunks is not None \
                        and i >= self.stream_fail_after_chunks:
                    from test_router import _unavailable

                    raise _unavailable("cell died mid-stream")
                yield pb.TokenChunk(tokens=[self.token + i],
                                    model_version=1)
            yield pb.TokenChunk(tokens=[], done=True, model_version=1)

        return chunks()

    def router_status(self, request, timeout=None):
        return pb.RouterStatusResponse(replicas=1, healthy=1)


def make_front(n=2, clock=None):
    clock = clock or FakeClock()
    stubs = {"cell%d" % i: FakeCellStub(token=100 * (i + 1))
             for i in range(n)}
    front = CellFront(
        sorted(stubs), stub_factory=lambda a: stubs[a],
        reroute_window_secs=8.0, base_delay_secs=0.01,
        max_delay_secs=0.05, clock=clock, sleep=clock.advance,
    )
    return front, stubs, clock


def _long_req(seed_token=5):
    # >= one full block (16 tokens): fingerprint-keyed routing
    return _req(prompt=[seed_token] * 16 + [1, 2], new=3)


def test_front_routes_to_ring_owner_deterministically():
    front_a, _, _ = make_front(3)
    front_b, _, _ = make_front(3)
    req = _long_req()
    key = front_a._route_key(req)
    assert key == front_b._route_key(req)  # content-addressed
    assert (front_a._targets(key)[0][0]
            == front_b._targets(key)[0][0])


def test_front_reroutes_dead_cell_zero_loss():
    front, stubs, _ = make_front(2)
    req = _long_req()
    owner = front._targets(front._route_key(req))[0][0]
    from test_router import _unavailable

    stubs[owner].gen_errors = [_unavailable()]
    resp = front.generate(req)
    assert list(resp.tokens)[-1] in (100, 200)  # a cell DID answer
    assert front.counters["rerouted"] == 1
    assert front.counters["cell_failures"] == 1
    assert front.counters["completed"] == 1


def test_front_breaker_stops_probing_a_dead_cell():
    front, stubs, _ = make_front(2)
    req = _long_req()
    owner = front._targets(front._route_key(req))[0][0]
    dead = stubs[owner]
    from test_router import _unavailable

    dead.gen_errors = [_unavailable() for _ in range(50)]
    for _ in range(5):
        front.generate(req)
    # threshold=3 transient failures tripped the owner's breaker:
    # later requests skip it entirely instead of paying a probe each
    assert dead.calls < 5


def test_front_backpressure_propagates_not_rerouted():
    front, stubs, _ = make_front(2)
    req = _long_req()
    owner = front._targets(front._route_key(req))[0][0]
    other = [a for a in stubs if a != owner][0]
    from test_router import _exhausted

    stubs[owner].gen_errors = [_exhausted()]
    with pytest.raises(RouterError) as err:
        front.generate(req)
    assert err.value.code == "RESOURCE_EXHAUSTED"
    # the registry is shared: rerouting a shed would only re-shed
    assert stubs[other].calls == 0
    assert front.counters["shed"] == 1
    assert front.counters["rerouted"] == 0


def test_front_application_error_propagates_untouched():
    front, stubs, _ = make_front(2)
    req = _long_req()
    owner = front._targets(front._route_key(req))[0][0]
    other = [a for a in stubs if a != owner][0]
    from test_router import _invalid

    stubs[owner].gen_errors = [_invalid()]
    with pytest.raises(RouterError) as err:
        front.generate(req)
    assert err.value.code == "INVALID_ARGUMENT"
    assert stubs[other].calls == 0


def test_front_all_cells_dead_raises_after_window():
    front, stubs, clock = make_front(2)
    from test_router import _unavailable

    for stub in stubs.values():
        stub.gen_errors = [_unavailable() for _ in range(100)]
    with pytest.raises(RouterError) as err:
        front.generate(_long_req())
    assert err.value.code == "UNAVAILABLE"


def test_front_stream_reroutes_before_first_chunk():
    front, stubs, _ = make_front(2)
    req = _long_req()
    owner = front._targets(front._route_key(req))[0][0]
    from test_router import _unavailable

    stubs[owner].gen_errors = []
    stubs[owner].stream_errors = [_unavailable()]
    tokens = []
    for chunk in front.generate_stream(req):
        tokens.extend(chunk.tokens)
    assert tokens  # the survivor streamed the whole request
    assert front.counters["rerouted"] == 1


def test_front_stream_never_reroutes_after_first_chunk():
    front, stubs, _ = make_front(2)
    req = _long_req()
    owner = front._targets(front._route_key(req))[0][0]
    stubs[owner].stream_fail_after_chunks = 1
    delivered = []
    with pytest.raises(RouterError) as err:
        for chunk in front.generate_stream(req):
            delivered.extend(chunk.tokens)
    # a replay past a delivered chunk would duplicate tokens: the
    # stream fails EXPLICITLY instead, with the partial delivery
    assert err.value.code == "UNAVAILABLE"
    assert len(delivered) == 1
    assert front.counters["rerouted"] == 0


def test_front_add_remove_cell_closes_channel():
    front, stubs, _ = make_front(2)
    gone = front.cells()[0]
    front.remove_cell(gone)
    assert stubs[gone].closed == 1
    assert gone not in front.cells()
    front.close()
    assert all(s.closed == 1 for s in stubs.values())


def test_front_short_prompt_still_routes():
    front, _, _ = make_front(2)
    resp = front.generate(_req(prompt=(1, 2), new=2))
    assert list(resp.tokens)[-1] in (100, 200)
    assert front.counters["completed"] == 1


def test_front_concurrent_requests_thread_safe():
    front, stubs, _ = make_front(2)
    done = []

    def one(i):
        resp = front.generate(_long_req(seed_token=i % 7))
        done.append(list(resp.tokens)[-1])

    threads = [threading.Thread(target=one, args=(i,))
               for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert len(done) == 16
    assert front.counters["completed"] == 16
