"""Master<->worker integration: in-process servicer and real gRPC on
localhost — the spirit of the reference's worker_ps_interaction_test.py and
test_utils.distributed_train_and_evaluate harness (fakes only at the
process/k8s boundary, never in the math path)."""

import threading

import pytest

from elasticdl_tpu.common.model_utils import load_model_spec_from_module
from elasticdl_tpu.data import recordio_gen
from elasticdl_tpu.master.master import Master
from elasticdl_tpu.worker.worker import JobType, Worker

# CI drills shard (make test-drills): the sub-5-min per-commit gate excludes this file.
pytestmark = pytest.mark.integration


def _spec():
    from model_zoo.mnist_functional_api import mnist_functional_api as zoo

    return load_model_spec_from_module(zoo)


@pytest.fixture()
def mnist_dirs(tmp_path):
    train_dir = str(tmp_path / "train")
    val_dir = str(tmp_path / "val")
    recordio_gen.gen_mnist_like(train_dir, num_files=2, records_per_file=48)
    recordio_gen.gen_mnist_like(val_dir, num_files=1, records_per_file=32,
                                seed=7)
    return train_dir, val_dir


def test_inprocess_train_with_evaluation(mnist_dirs):
    train_dir, val_dir = mnist_dirs
    master = Master(
        _spec(),
        training_data=train_dir,
        validation_data=val_dir,
        minibatch_size=16,
        records_per_task=24,
        num_epochs=1,
        evaluation_steps=2,
    )
    worker = Worker(
        0,
        _spec(),
        master_servicer=master.servicer,
        job_type=JobType.TRAINING_WITH_EVALUATION,
        minibatch_size=16,
        training_data=train_dir,
        wait_sleep_secs=0.05,
    )
    state = worker.run()
    assert master.task_d.finished()
    assert int(state.step) == 96 // 16
    # eval jobs completed and aggregated master-side
    assert master.evaluation_service.completed_job_metrics
    for version, metrics in master.evaluation_service.completed_job_metrics:
        assert "accuracy" in metrics
        assert 0.0 <= metrics["accuracy"] <= 1.0


def test_grpc_train(mnist_dirs):
    train_dir, _ = mnist_dirs
    master = Master(
        _spec(),
        training_data=train_dir,
        minibatch_size=16,
        records_per_task=32,
        num_epochs=1,
    )
    master.prepare()
    try:
        worker = Worker(
            0,
            _spec(),
            master_addr="localhost:%d" % master.port,
            job_type=JobType.TRAINING_ONLY,
            minibatch_size=16,
            training_data=train_dir,
            wait_sleep_secs=0.05,
        )
        state = worker.run()
        worker.close()
        assert int(state.step) == 96 // 16
        assert master.task_d.finished()
    finally:
        master.stop()


def test_grpc_multi_worker_task_partitioning(mnist_dirs):
    """Two workers pull from the same queue; all records get consumed
    exactly once (dispatch correctness; gradient-sync lockstep across hosts
    is the SPMD executor's job, tested in parallel tests)."""
    train_dir, _ = mnist_dirs
    master = Master(
        _spec(),
        training_data=train_dir,
        minibatch_size=8,
        records_per_task=16,
        num_epochs=1,
    )
    master.prepare()
    workers, threads, states = [], [], {}
    try:
        def run_worker(wid):
            w = Worker(
                wid,
                _spec(),
                master_addr="localhost:%d" % master.port,
                job_type=JobType.TRAINING_ONLY,
                minibatch_size=8,
                training_data=train_dir,
                wait_sleep_secs=0.05,
            )
            workers.append(w)
            states[wid] = w.run()
            w.close()

        for wid in range(2):
            t = threading.Thread(target=run_worker, args=(wid,))
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=120)
        assert master.task_d.finished()
        total_steps = sum(int(s.step) for s in states.values())
        assert total_steps == 96 // 8
    finally:
        master.stop()


def test_grpc_predict(mnist_dirs):
    train_dir, _ = mnist_dirs
    collected = []

    spec = _spec()
    spec.prediction_outputs_processor = lambda preds: collected.append(preds)
    master = Master(
        spec,
        prediction_data=train_dir,
        minibatch_size=16,
        records_per_task=32,
    )
    master.prepare()
    try:
        worker = Worker(
            0,
            spec,
            master_addr="localhost:%d" % master.port,
            job_type=JobType.PREDICTION_ONLY,
            minibatch_size=16,
            training_data=train_dir,
            wait_sleep_secs=0.05,
        )
        preds = worker.run()
        worker.close()
        assert preds.shape == (96, 10)
        assert collected
    finally:
        master.stop()


def test_worker_failure_task_recovery(mnist_dirs):
    """Kill a worker mid-job; recover_tasks requeues its doing tasks and a
    second worker finishes the job (reference fault-injection pattern,
    worker_ps_interaction_test.py:350-402)."""
    train_dir, _ = mnist_dirs
    master = Master(
        _spec(),
        training_data=train_dir,
        minibatch_size=8,
        records_per_task=16,
        num_epochs=1,
    )
    master.prepare()
    try:
        # worker 0 grabs a task then "dies" without reporting
        from elasticdl_tpu.proto import elasticdl_pb2 as pb
        from elasticdl_tpu.proto.service import MasterStub, build_channel

        chan = build_channel("localhost:%d" % master.port)
        try:
            stub = MasterStub(chan)
            task = stub.get_task(pb.GetTaskRequest(worker_id=0))
            assert task.shard_name
        finally:
            chan.close()
        # master notices the death (simulating the instance-manager event)
        master.task_d.recover_tasks(0)

        worker = Worker(
            1,
            _spec(),
            master_addr="localhost:%d" % master.port,
            job_type=JobType.TRAINING_ONLY,
            minibatch_size=8,
            training_data=train_dir,
            wait_sleep_secs=0.05,
        )
        state = worker.run()
        worker.close()
        assert master.task_d.finished()
        # every record trained exactly once despite the recovery
        assert int(state.step) == 96 // 8
    finally:
        master.stop()
