"""Preprocessing layers: behavior parity with the reference docstring
examples (elasticdl_preprocessing/layers/*, tests/*)."""

import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.embedding.layer import PADDING_ID
from elasticdl_tpu.preprocessing import (
    ConcatenateWithOffset,
    Discretization,
    Hashing,
    IndexLookup,
    LogRound,
    Normalizer,
    RoundIdentity,
    SparseEmbedding,
    ToNumber,
    ToRagged,
    ToSparse,
)
from elasticdl_tpu.preprocessing import analyzer_utils, feature_column


def test_normalizer():
    layer = Normalizer(subtractor=1.0, divisor=2.0)
    out = layer(np.asarray([[3.0], [5.0], [7.0]]))
    np.testing.assert_allclose(np.asarray(out), [[1.0], [2.0], [3.0]])
    with pytest.raises(ValueError):
        Normalizer(subtractor=0.0, divisor=0.0)
    # jnp path
    out_j = layer(jnp.asarray([[3.0]]))
    np.testing.assert_allclose(np.asarray(out_j), [[1.0]])


def test_round_identity():
    layer = RoundIdentity(num_buckets=10)
    inp = np.asarray([[1.2], [1.6], [0.2], [3.1], [4.9]])
    np.testing.assert_array_equal(
        np.asarray(layer(inp)), [[1], [2], [0], [3], [5]]
    )
    # out-of-range → default_value
    np.testing.assert_array_equal(
        np.asarray(RoundIdentity(num_buckets=5)(np.asarray([[7.9], [-2.0]]))),
        [[0], [0]],
    )


def test_log_round():
    layer = LogRound(num_bins=16, base=2)
    inp = np.asarray([[1.2], [1.6], [0.2], [3.1], [100]])
    np.testing.assert_array_equal(
        np.asarray(layer(inp)), [[0], [1], [0], [2], [7]]
    )


def test_discretization():
    layer = Discretization(bins=[0.0, 1.0, 2.0])
    assert layer.num_bins() == 4
    inp = np.asarray([[-1.0], [0.0], [0.5], [1.5], [5.0]])
    np.testing.assert_array_equal(
        np.asarray(layer(inp)), [[0], [1], [1], [2], [3]]
    )
    np.testing.assert_array_equal(
        np.asarray(layer(jnp.asarray(inp))), [[0], [1], [1], [2], [3]]
    )


def test_hashing():
    layer = Hashing(num_bins=3)
    out = layer(np.asarray([["A"], ["B"], ["C"], ["D"], ["E"]]))
    assert out.shape == (5, 1)
    assert ((out >= 0) & (out < 3)).all()
    # deterministic
    np.testing.assert_array_equal(
        out, layer(np.asarray([["A"], ["B"], ["C"], ["D"], ["E"]]))
    )
    # int inputs stringify like the reference; padding passes through
    ints = layer(np.asarray([[7, PADDING_ID]]))
    assert ints[0, 1] == PADDING_ID
    assert 0 <= ints[0, 0] < 3
    with pytest.raises(ValueError):
        Hashing(num_bins=0)


def test_index_lookup():
    layer = IndexLookup(vocabulary=["A", "B", "C"])
    out = layer(np.array([["A"], ["B"], ["C"], ["D"], ["E"]]))
    np.testing.assert_array_equal(out, [[0], [1], [2], [3], [3]])
    assert layer.vocab_size() == 4
    # bytes input (TRec payloads decode to bytes)
    np.testing.assert_array_equal(
        layer(np.array([[b"B"]], dtype=object)), [[1]]
    )
    # multiple OOV buckets spread deterministically in [n, n+num_oov)
    multi = IndexLookup(vocabulary=["A"], num_oov_tokens=4)
    oov = multi(np.array([["X"], ["Y"], ["Z"]]))
    assert ((oov >= 1) & (oov < 5)).all()
    with pytest.raises(ValueError):
        IndexLookup(vocabulary=["A", "A"])


def test_index_lookup_from_file(tmp_path):
    p = tmp_path / "vocab.txt"
    p.write_text("A\nB\nC\n")
    layer = IndexLookup(vocabulary=str(p))
    np.testing.assert_array_equal(layer(np.array([["C"]])), [[2]])


def test_concatenate_with_offset():
    a1 = np.asarray([[1], [1], [1]])
    a2 = np.asarray([[2], [2], [2]])
    layer = ConcatenateWithOffset(offsets=[0, 10], axis=1)
    np.testing.assert_array_equal(
        np.asarray(layer([a1, a2])), [[1, 12], [1, 12], [1, 12]]
    )
    # padding ids don't get shifted
    b = np.asarray([[PADDING_ID], [2], [PADDING_ID]])
    out = np.asarray(ConcatenateWithOffset(offsets=[0, 10], axis=1)([a1, b]))
    np.testing.assert_array_equal(
        out, [[1, PADDING_ID], [1, 12], [1, PADDING_ID]]
    )
    with pytest.raises(ValueError):
        ConcatenateWithOffset(offsets=[0])([a1, a2])


def test_to_number():
    layer = ToNumber(np.float32, default_value=-1.0)
    out = layer(np.array([["1.5"], ["oops"], [""]], dtype=object))
    np.testing.assert_allclose(out, [[1.5], [-1.0], [-1.0]])
    assert out.dtype == np.float32
    out_i = ToNumber(np.int64, 0)(np.array([[b"7"]], dtype=object))
    np.testing.assert_array_equal(out_i, [[7]])


def test_to_ragged_and_to_sparse():
    dense = np.asarray([[3, -1, 5], [-1, -1, -1], [2, 4, -1]])
    ragged = ToRagged(ignore_value=-1)(dense)
    np.testing.assert_array_equal(
        ragged,
        [[3, 5, PADDING_ID], [PADDING_ID] * 3, [2, 4, PADDING_ID]],
    )
    sparse = ToSparse(ignore_value=-1)(dense)
    np.testing.assert_array_equal(
        sparse,
        [[3, PADDING_ID, 5], [PADDING_ID] * 3, [2, 4, PADDING_ID]],
    )
    # 0 as ignore_value (the reference SparseEmbedding dense-input trick)
    z = ToSparse(ignore_value=0)(np.asarray([[3, 0], [0, 1]]))
    np.testing.assert_array_equal(z, [[3, PADDING_ID], [PADDING_ID, 1]])


def test_sparse_embedding_layer():
    import jax

    layer = SparseEmbedding(input_dim=10, output_dim=4, combiner="sum")
    ids = jnp.asarray([[1, 3, PADDING_ID]])
    params = layer.init(jax.random.PRNGKey(0), ids)
    out = layer.apply(params, ids)
    table = np.asarray(params["params"]["embedding_table"])
    np.testing.assert_allclose(
        np.asarray(out)[0], table[1] + table[3], rtol=1e-6
    )


def test_concatenated_categorical_column():
    c1 = feature_column.categorical_column_with_identity("a", num_buckets=10)
    c2 = feature_column.categorical_column_with_identity("b", num_buckets=20)
    col = feature_column.concatenated_categorical_column([c1, c2])
    assert col.num_buckets == 30
    features = {
        "a": np.asarray([1, 2]),
        "b": np.asarray([0, 5]),
    }
    out = col(features)
    # second column's ids shifted by c1.num_buckets
    np.testing.assert_array_equal(out, [[1, 10], [2, 15]])


def test_embedding_column():
    c = feature_column.categorical_column_with_identity("x", num_buckets=8)
    col, layer_factory = feature_column.embedding_column(
        c, dimension=3, combiner="mean"
    )
    layer = layer_factory()
    assert layer.input_dim == 8 and layer.output_dim == 3


def test_analyzer_env_channel(monkeypatch):
    """Reference parity: accessors keyed by feature NAME read the
    SQLFlow analysis env vars (_<name>_min etc., constants.py:15-22),
    falling back to defaults; publish_analysis is the analysis pass
    that fills them."""
    assert analyzer_utils.get_min("age", default=-1.0) == -1.0
    assert analyzer_utils.get_distinct_count("age", default=7) == 7
    monkeypatch.setenv("_age_min", "18")
    monkeypatch.setenv("_age_stddev", "2.5")
    monkeypatch.setenv("_age_boundaries", "30,10,20,10")
    monkeypatch.setenv("_age_distinct_count", "42")
    monkeypatch.setenv("_city_vocab", "sf,nyc")
    assert analyzer_utils.get_min("age", default=-1.0) == 18.0
    assert analyzer_utils.get_stddev("age") == 2.5
    assert analyzer_utils.get_bucket_boundaries("age") == [
        10.0, 20.0, 30.0,
    ]
    assert analyzer_utils.get_distinct_count("age") == 42
    assert analyzer_utils.get_vocabulary("city") == ["sf", "nyc"]
    monkeypatch.setenv("_city_vocab", "/data/vocab/city.txt")
    assert analyzer_utils.get_vocabulary("city") == "/data/vocab/city.txt"

    col = np.asarray([4.0, 1.0, 3.0, 2.0])
    published = analyzer_utils.publish_analysis("wage", col, num_buckets=2)
    assert analyzer_utils.get_min("wage") == 1.0
    assert analyzer_utils.get_max("wage") == 4.0
    assert analyzer_utils.get_distinct_count("wage") == 4
    assert len(analyzer_utils.get_bucket_boundaries("wage")) == 1
    for k in published:
        monkeypatch.delenv(k)

    analyzer_utils.publish_analysis("town", np.array(["b", "a", "b"]))
    assert analyzer_utils.get_vocabulary("town") == ["b", "a"]
    assert analyzer_utils.get_distinct_count("town") == 2
    import os
    for k in list(os.environ):
        if k.startswith("_town_"):
            monkeypatch.delenv(k)


def test_analyzer_utils():
    col = np.asarray([1.0, 2.0, 3.0, 4.0])
    assert analyzer_utils.get_min(col) == 1.0
    assert analyzer_utils.get_max(col) == 4.0
    assert analyzer_utils.get_avg(col) == 2.5
    assert analyzer_utils.get_stddev(col) > 0
    bounds = analyzer_utils.get_bucket_boundaries(col, num_buckets=2)
    assert len(bounds) == 1
    assert analyzer_utils.get_vocabulary(np.array(["b", "a", "b"])) == [
        "b", "a",
    ]
    # placeholder fallbacks
    assert analyzer_utils.get_min() == 0.0
    assert analyzer_utils.get_bucket_boundaries() == []
