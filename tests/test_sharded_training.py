"""Multi-device sharding tests on the 8-device virtual CPU mesh: the
TPU-native replacement for the reference's multi-worker PS tests
(worker_ps_interaction_test.py test_compare_mnist_train) — the distributed
run must match the single-device run."""

import jax
import numpy as np
import pytest

from elasticdl_tpu.common.model_utils import load_model_spec_from_module
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.training.trainer import Trainer

# CI drills shard (make test-drills): the sub-5-min per-commit gate excludes this file.
pytestmark = pytest.mark.slow


def _spec():
    from model_zoo.mnist_functional_api import mnist_functional_api as zoo

    return load_model_spec_from_module(zoo)


def _batch(bsz, seed=0):
    rng = np.random.RandomState(seed)
    return (
        {"image": rng.rand(bsz, 28, 28).astype(np.float32)},
        rng.randint(10, size=(bsz,)).astype(np.int32),
    )


def test_mesh_spec_parsing():
    sizes = mesh_lib.parse_mesh_spec("dp=2,fsdp=4")
    assert sizes["dp"] == 2 and sizes["fsdp"] == 4 and sizes["tp"] == 1
    sizes = mesh_lib.parse_mesh_spec(None)
    assert sizes["dp"] == -1


def test_build_mesh_fills_dp():
    mesh = mesh_lib.build_mesh()
    assert mesh.shape["dp"] == len(jax.devices())


def test_dp_matches_single_device():
    """Same data, same seed: an 8-way dp run takes the same training
    trajectory as a 1-device run (sync DP is exact, unlike the reference's
    async PS which only converges statistically)."""
    spec = _spec()
    batch = _batch(32)

    t1 = Trainer(spec, mesh=mesh_lib.build_mesh({"dp": 1},
                                                devices=jax.devices()[:1]))
    s1 = t1.init_state(batch)
    for _ in range(3):
        s1, loss1 = t1.train_step(s1, batch)

    t8 = Trainer(spec, mesh=mesh_lib.build_mesh({"dp": 8}))
    s8 = t8.init_state(batch)
    for _ in range(3):
        s8, loss8 = t8.train_step(s8, batch)

    np.testing.assert_allclose(float(loss1), float(loss8), rtol=2e-5)
    # fp32 reduction order differs between 1-dev reduce and 8-way psum, and
    # the divergence compounds over steps — close but not bitwise equal
    p1 = jax.tree.leaves(s1.params)
    p8 = jax.tree.leaves(s8.params)
    for a, b in zip(p1, p8):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def test_fsdp_shards_large_params():
    spec = _spec()
    mesh = mesh_lib.build_mesh({"dp": 2, "fsdp": 4})
    trainer = Trainer(spec, mesh=mesh)
    state = trainer.init_state(_batch(16))
    # the Dense(10) kernel (9216x10 = 92160 elems) must be sharded over fsdp
    # on its largest axis; each device holds a 1/4 slice
    dense_kernel = state.params["Dense_0"]["kernel"]
    assert tuple(dense_kernel.sharding.spec)[0] == "fsdp"
    shard_shape = dense_kernel.sharding.shard_shape(dense_kernel.shape)
    assert shard_shape == (9216 // 4, 10)
    # optimizer state co-sharded: sgd has no moments, so check via a fresh
    # adam-like check on params only (moments covered in deepfm tests later)
    # training still works and matches dp-only
    state, loss = trainer.train_step(state, _batch(16))
    assert np.isfinite(float(loss))


def test_padded_batch_masking():
    """A padded batch with mask must give the same loss as the same batch
    padded with correct rows (guards the static-shape padding path). Uses a
    deterministic linear model so dropout/BN noise can't leak between the
    two runs."""
    import jax.numpy as jnp
    import optax
    from flax import linen as nn

    from elasticdl_tpu.common.model_utils import ModelSpec

    class Linear(nn.Module):
        @nn.compact
        def __call__(self, features, training=False):
            return nn.Dense(10)(features["x"])

    def loss(labels, predictions, sample_weights=None):
        ce = optax.softmax_cross_entropy_with_integer_labels(
            predictions, labels.reshape(-1)
        )
        if sample_weights is None:
            return jnp.mean(ce)
        return jnp.sum(ce * sample_weights) / jnp.maximum(
            jnp.sum(sample_weights), 1.0
        )

    spec = ModelSpec(
        model_fn=Linear,
        dataset_fn=None,
        loss=loss,
        optimizer=lambda: optax.sgd(0.1),
        eval_metrics_fn=lambda: {},
    )
    mesh = mesh_lib.build_mesh({"dp": 8})
    trainer = Trainer(spec, mesh=mesh)
    rng = np.random.RandomState(0)
    feats8 = rng.rand(8, 12).astype(np.float32)
    labels8 = rng.randint(10, size=(8,)).astype(np.int32)
    feats_pad = {"x": np.concatenate([feats8] * 2)}
    state = trainer.init_state((feats_pad, np.concatenate([labels8] * 2)))
    state_copy = jax.tree.map(jnp.copy, state)  # train_step donates its input

    garbage = (labels8 + 5) % 10
    state2, loss_masked = trainer.train_step(
        state, (feats_pad, np.concatenate([labels8, garbage])), true_count=8
    )
    state3, loss_dup = trainer.train_step(
        state_copy, (feats_pad, np.concatenate([labels8] * 2))
    )
    np.testing.assert_allclose(
        float(loss_masked), float(loss_dup), rtol=2e-5
    )
    # and the resulting params must match (garbage rows contribute nothing)
    for a, b in zip(jax.tree.leaves(state2.params),
                    jax.tree.leaves(state3.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
