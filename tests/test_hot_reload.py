"""CheckpointWatcher failure isolation (serving/hot_reload.py).

The hardened reload path's contract, gRPC-free: a torn or corrupt
checkpoint must NEVER displace the serving params — the watcher retries
with backoff, then latches `reload_failed` / `last_error` (the
ServerStatus advertisement the router and the rollout controller read)
while the old version keeps serving; a later GOOD version clears the
latch. `load_version` is the rollout handshake: any-direction explicit
loads, idempotent at the serving version, ReloadError on exhaustion.
"""

import os

import numpy as np
import pytest

from elasticdl_tpu.checkpoint import CheckpointSaver
from elasticdl_tpu.checkpoint.saver import verify_checkpoint
from elasticdl_tpu.common.fault_injection import FaultInjector
from elasticdl_tpu.serving.hot_reload import CheckpointWatcher, ReloadError


def save(ckpt_dir, version, scale=1.0):
    CheckpointSaver(str(ckpt_dir), checkpoint_steps=1,
                    num_shards=2).save(
        {"w": np.arange(8, dtype=np.float32) * scale}, version=version
    )


def truncate_shard(ckpt_dir, version):
    path = os.path.join(str(ckpt_dir), "version-%d" % version,
                        "variables-0-of-2.ckpt")
    with open(path, "r+b") as f:
        f.truncate(10)
    return path


def make_watcher(ckpt_dir, sleeps=None, **kwargs):
    kwargs.setdefault("poll_secs", 0.0)
    kwargs.setdefault(
        "sleep", sleeps.append if sleeps is not None else lambda s: None
    )
    return CheckpointWatcher(
        str(ckpt_dir), {"w": np.zeros(8, dtype=np.float32)}, **kwargs
    )


def test_poll_loads_newer_version(tmp_path):
    save(tmp_path, 3)
    w = make_watcher(tmp_path)
    state, version = w.poll(force=True)
    assert version == w.version == 3
    np.testing.assert_array_equal(
        np.asarray(state["w"]), np.arange(8, dtype=np.float32)
    )
    assert not w.reload_failed
    assert w.poll(force=True) is None  # nothing newer


def test_truncated_checkpoint_latches_and_keeps_old_params(tmp_path):
    save(tmp_path, 3)
    sleeps = []
    w = make_watcher(tmp_path, sleeps=sleeps)
    w.poll(force=True)
    save(tmp_path, 5, scale=2.0)
    truncate_shard(tmp_path, 5)
    assert w.poll(force=True) is None
    # exhausted the retry ladder with exponential backoff...
    assert sleeps == [w.backoff_secs, w.backoff_secs * 2]
    # ...latched the failure for ServerStatus, old params serving
    assert w.reload_failed
    assert "CheckpointCorruptError" in w.last_error
    assert w.version == 3
    # the failed version is remembered: the next poll does not re-chew
    # the same torn bytes (no further sleeps)
    assert w.poll(force=True) is None
    assert sleeps == [w.backoff_secs, w.backoff_secs * 2]


def test_good_version_clears_the_failure_latch(tmp_path):
    save(tmp_path, 3)
    w = make_watcher(tmp_path)
    w.poll(force=True)
    save(tmp_path, 5)
    truncate_shard(tmp_path, 5)
    w.poll(force=True)
    assert w.reload_failed and w.version == 3
    save(tmp_path, 7, scale=3.0)
    state, version = w.poll(force=True)
    assert version == 7
    assert not w.reload_failed
    assert w.last_error == ""


def test_load_version_rolls_back_and_is_idempotent(tmp_path):
    save(tmp_path, 3)
    save(tmp_path, 5, scale=2.0)
    w = make_watcher(tmp_path)
    w.poll(force=True)
    assert w.version == 5
    # poll never goes backwards; the explicit handshake does
    state, version = w.load_version(3)
    assert version == w.version == 3
    assert w.load_version(3) is None  # already serving: no-op


def test_load_version_failure_raises_reload_error(tmp_path):
    save(tmp_path, 3)
    w = make_watcher(tmp_path)
    w.poll(force=True)
    save(tmp_path, 5)
    truncate_shard(tmp_path, 5)
    with pytest.raises(ReloadError):
        w.load_version(5)
    assert w.reload_failed and w.version == 3


def test_injected_checkpoint_read_fault_is_survived(tmp_path):
    save(tmp_path, 3)
    w = make_watcher(
        tmp_path,
        injector=FaultInjector(spec="checkpoint_read:error:2"),
    )
    # two injected read faults burn two attempts; the third succeeds
    state, version = w.poll(force=True)
    assert version == 3
    assert not w.reload_failed


def test_poll_disabled_leaves_explicit_reloads_only(tmp_path):
    # --reload_poll_secs 0: a rollout-managed replica must not
    # self-upgrade (or self-revert a rollback) behind the controller
    save(tmp_path, 3)
    w = make_watcher(tmp_path, poll_secs=0)
    assert w.poll() is None
    state, version = w.load_version(3)
    assert version == w.version == 3


def test_saver_writes_verifiable_digests(tmp_path):
    save(tmp_path, 3)
    manifest = verify_checkpoint(str(tmp_path), 3)
    assert manifest["num_shards"] == manifest["verified_digests"] == 2
    assert manifest["version"] == 3 and manifest["bytes"] > 0
