"""Spec-derived crash-point replay batteries (tier-1, no jax).

For each of the four WAL-backed controllers, record a journal from a
live run of the REAL controller (fake processes/replicas, real emit
path), then walk every crash point with
`analysis.protocol_testgen.replay_battery`: truncate after each
event, rebuild through the controller's real replay surface, compare
against the declared `JournalProtocol` machine's own simulation of
the prefix, and require deterministic recovery. The
snapshot/journal-overlap contract (`write_snapshot` lands before the
journal truncate) is pinned by `double_replay_idempotent` — journal
counters that deliberately fold full event history are excluded from
that comparison and ONLY that comparison.

These are the dynamic twins of the EDL701-EDL704 static checks: the
lint proves the emit/replay surfaces agree with the declaration; the
battery proves the declaration agrees with what the controllers
actually do.
"""

import json
import os

from test_autoscaler import build as build_supervisor
from test_autoscaler import settle
from test_rollout import NEW, drive, make_controller
from test_router import FakeClock as CellClock
from test_router import FakeReplicaStub

from elasticdl_tpu.analysis.protocol_testgen import (
    double_replay_idempotent,
    kind_coverage,
    replay_battery,
    validate_journal,
)
from elasticdl_tpu.master.state_store import JOURNAL_FILE, JobStateStore
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.master import task_dispatcher
from elasticdl_tpu.serving import autoscaler, rollout, router_cell
from elasticdl_tpu.serving.router import RouterConfig
from elasticdl_tpu.serving.router_cell import RouterCell


# ------------------------------------------------------ master dispatcher


def record_dispatcher_journal(tmp_path):
    """Drive a real dispatcher over a store that never compacts: the
    full journal of one small job (create, dispatch, done, a fail and
    its re-run, a model-version bump)."""
    store = JobStateStore(str(tmp_path / "disp"), snapshot_every=10**6)
    disp = TaskDispatcher({"f": (0, 30)}, {}, {}, 10, 1,
                          state_store=store)
    tid, _task = disp.get(1)
    disp.report(tid, True)
    tid, _task = disp.get(1)
    disp.report(tid, False)  # requeued with a retry bump
    disp.record_model_version(3)
    tid, _task = disp.get(2)
    disp.report(tid, True)
    store.close()
    _snapshot, events = JobStateStore(str(tmp_path / "disp")).load()
    return events


def dispatcher_recover(snapshot, events):
    disp = TaskDispatcher({"f": (0, 30)}, {}, {}, 10, 1)
    disp.restore(snapshot, events)
    snap = disp.snapshot()
    snap["todo"] = sorted(snap["todo"])
    snap["eval_todo"] = sorted(snap["eval_todo"])
    snap["recovered_doing"] = sorted(snap["recovered_doing"])
    snap["retry"] = sorted(snap["retry"])
    return snap


def test_dispatcher_crash_point_battery(tmp_path):
    spec = task_dispatcher.PROTOCOL
    events = record_dispatcher_journal(tmp_path)
    # the recorded job exercises the whole alphabet except the
    # recovery-only and callback-bookkeeping kinds
    assert kind_coverage(spec, events) == [
        "deferred_add", "deferred_invoked", "done_recovered", "stop",
    ]

    def check(k, sim, snap):
        recovered_ids = {tid for tid, _w, _key in
                         snap["recovered_doing"]}
        for tid, state in sim[1].items():
            if state == "doing":
                # in flight at the crash: requeued + parked for
                # late-report reconciliation
                assert tid in recovered_ids, (k, tid)
            elif state == "done":
                assert tid not in recovered_ids, (k, tid)

    points = replay_battery(spec, events, dispatcher_recover,
                            check=check)
    assert points == len(events) + 1


def test_dispatcher_snapshot_overlap_replay(tmp_path):
    # retry counts fold journal history and may inflate by one in the
    # overlap window (bounded: the journal truncates at the next
    # compaction); everything stateful must agree exactly
    events = record_dispatcher_journal(tmp_path)
    double_replay_idempotent(
        task_dispatcher.PROTOCOL, events, dispatcher_recover,
        snapshot_of=lambda snap: json.loads(json.dumps(snap)),
        fingerprint=lambda snap: {k: v for k, v in snap.items()
                                  if k != "retry"},
    )


# -------------------------------------------------- autoscaler supervisor


def record_supervisor_journal(tmp_path):
    """A real supervisor lifecycle: spawn to min, an unplanned live
    death (reap + replacement), a shrink (drain + retire), then a
    supervisor stop retiring the survivors."""
    sup, router, _launcher, _clock = build_supervisor(
        journal_dir=str(tmp_path / "scale"), min_replicas=2,
        snapshot_every=10**6,
    )
    settle(sup, router, ticks=4)
    victim = sup._seats[min(sup._seats)].handle
    victim.rc = 1  # crash of a live replica
    settle(sup, router, ticks=4)  # reap + respawn + re-adopt
    sup.target = 1
    settle(sup, router, ticks=4)  # drain one seat, retire on exit
    sup.stop()
    _snapshot, events = JobStateStore(str(tmp_path / "scale")).load()
    return events


def supervisor_recover(snapshot, events):
    state = snapshot or {"target": 0, "next_seat": 0, "seats": {},
                         "counters": {}}
    for ev in events:
        autoscaler.ReplicaSupervisor._apply_event(state, ev)
    return state


def test_supervisor_crash_point_battery(tmp_path):
    spec = autoscaler.PROTOCOL
    events = record_supervisor_journal(tmp_path)
    assert kind_coverage(spec, events) == []  # full alphabet

    def check(k, sim, state):
        for sid, entity_state in sim[1].items():
            if entity_state in (autoscaler.STARTING, autoscaler.LIVE,
                                autoscaler.DRAINING):
                assert state["seats"][str(sid)]["state"] == \
                    entity_state, (k, sid)
            else:  # absent / allocated: no process on the roster yet
                assert str(sid) not in state["seats"], (k, sid)

    points = replay_battery(spec, events, supervisor_recover,
                            check=check)
    assert points == len(events) + 1


def test_supervisor_snapshot_overlap_replay(tmp_path):
    events = record_supervisor_journal(tmp_path)
    double_replay_idempotent(
        autoscaler.PROTOCOL, events, supervisor_recover,
        snapshot_of=lambda state: json.loads(json.dumps(state)),
        fingerprint=lambda state: {k: v for k, v in state.items()
                                   if k != "counters"},
    )


# ----------------------------------------------------- rollout controller


def record_rollout_journal(tmp_path, wave_alert=False):
    """A real rollout run over a store that never compacts: the
    healthy path commits, the wave_alert path trips the pager during
    a progressive wave and reverse-rolls."""
    ctl, router, clock, _calls = make_controller(
        tmp_path, journal=True, snapshot_every=10**6,
    )
    assert ctl.begin(NEW)
    if not wave_alert:
        assert drive(ctl, clock) == rollout.COMMITTED
    else:
        from test_rollout import report

        for _ in range(100):
            ctl.decide_once()
            if ctl.phase in rollout.TERMINAL:
                break
            if (ctl.phase == rollout.WAVE
                    and len(ctl.swapped) == 2):
                router.reports = [report(fast=2.0, slow=2.0,
                                         alerting=True)]
            clock.advance(1.0)
        assert ctl.phase == rollout.ROLLED_BACK
    _snapshot, events = JobStateStore(
        str(tmp_path / "journal")).load()
    return events


def rollout_recover(snapshot, events):
    state = dict(snapshot) if snapshot else {}
    for ev in events:
        rollout.RolloutController._apply_event(state, ev)
    return state


#: an operator-driven wave abort: the one declared kind the recorded
#: runs above cannot reach (wave_rollback is the explicit
#: rollback_wave() API); strict-validated against the machine before
#: the battery replays it
WAVE_ROLLBACK_JOURNAL = [
    {"ev": "begin", "target": 2, "old": 1, "plan": ["a:1", "b:1"],
     "dir": "/ckpt"},
    {"ev": "staged", "baseline": []},
    {"ev": "phase", "to": rollout.CANARY},
    {"ev": "swap_done", "addr": "a:1", "to": 2, "ok": True},
    {"ev": "phase", "to": rollout.JUDGING},
    {"ev": "judge", "verdict": "pass"},
    {"ev": "phase", "to": rollout.WAVE},
    {"ev": "wave_begin", "wave": 1, "addrs": ["b:1"]},
    {"ev": "swap_done", "addr": "b:1", "to": 2, "ok": True},
    {"ev": "wave_rollback", "wave": 1},
    {"ev": "phase", "to": rollout.ROLLING_BACK, "why": "operator"},
    {"ev": "swap_done", "addr": "b:1", "to": 1, "ok": True,
     "why": "rollback"},
    {"ev": "swap_done", "addr": "a:1", "to": 1, "ok": True,
     "why": "rollback"},
    {"ev": "phase", "to": rollout.ROLLED_BACK},
]


def rollout_check(k, sim, state):
    assert state.get("phase", rollout.IDLE) == sim[0], (
        k, state.get("phase"), sim[0],
    )


def test_rollout_crash_point_battery_commit_path(tmp_path):
    spec = rollout.PROTOCOL
    events = record_rollout_journal(tmp_path)
    replay_battery(spec, events, rollout_recover, check=rollout_check)


def test_rollout_crash_point_battery_alert_rollback_path(tmp_path):
    spec = rollout.PROTOCOL
    events = record_rollout_journal(tmp_path, wave_alert=True)
    replay_battery(spec, events, rollout_recover, check=rollout_check)


def test_rollout_crash_point_battery_wave_rollback_path(tmp_path):
    spec = rollout.PROTOCOL
    events = [dict(ev) for ev in WAVE_ROLLBACK_JOURNAL]
    replay_battery(spec, events, rollout_recover, check=rollout_check)


def test_rollout_journals_cover_the_alphabet(tmp_path):
    spec = rollout.PROTOCOL
    covered = set()
    for events in (
        record_rollout_journal(tmp_path / "commit"),
        record_rollout_journal(tmp_path / "alert", wave_alert=True),
        WAVE_ROLLBACK_JOURNAL,
    ):
        covered |= {ev["ev"] for ev in events}
    assert spec.replayed_kinds() <= covered


def test_rollout_snapshot_overlap_replay(tmp_path):
    events = record_rollout_journal(tmp_path)
    double_replay_idempotent(
        rollout.PROTOCOL, events, rollout_recover,
        snapshot_of=lambda state: json.loads(json.dumps(state)),
        fingerprint=lambda state: {k: v for k, v in state.items()
                                   if k != "counters"},
    )


# --------------------------------------------------- router cell registry


def record_cell_journal(tmp_path):
    """A real cell's registry life: seed adopts at construction, a
    runtime adopt, a retire, and the periodic lease beacon."""
    from test_router_cells import make_cell

    cell, _stubs, _clock = make_cell(
        tmp_path / "cells", seeds=("a:1", "b:1"),
    )
    cell.add_replica("c:1")
    cell.remove_replica("b:1")
    for _ in range(cell.LEASE_JOURNAL_EVERY):
        cell.poll_once()  # the 8th tick records the lease beacon
    path = os.path.join(str(tmp_path / "cells"), JOURNAL_FILE)
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def make_bare_cell(seeds):
    stubs = {}

    def factory(addr):
        if addr not in stubs:
            stubs[addr] = FakeReplicaStub(token=7)
        return stubs[addr]

    return RouterCell(
        list(seeds), config=RouterConfig(cell_id=0, cells=2),
        journal_dir=None, stub_factory=factory, clock=CellClock(),
        sleep=lambda s: None,
    )


def cell_recover(snapshot, events):
    cell = make_bare_cell(snapshot["replicas"] if snapshot else ())
    for ev in events:
        cell._apply_event(ev)
    return sorted(r.address for r in cell.replicas())


def test_cell_crash_point_battery(tmp_path):
    spec = router_cell.PROTOCOL
    events = record_cell_journal(tmp_path)
    assert kind_coverage(spec, events) == []  # full alphabet

    def check(k, sim, addresses):
        members = sorted(a for a, st in sim[1].items()
                         if st == "member")
        assert addresses == members, (k, addresses, members)

    points = replay_battery(spec, events, cell_recover, check=check)
    assert points == len(events) + 1


def test_cell_snapshot_overlap_replay(tmp_path):
    events = record_cell_journal(tmp_path)
    double_replay_idempotent(
        router_cell.PROTOCOL, events, cell_recover,
        snapshot_of=lambda addresses: {"replicas": list(addresses)},
    )


def test_cell_retire_of_absent_address_is_legal(tmp_path):
    # the idempotence the from-sets declare on purpose: a sibling
    # already removed it; replaying both retires is a no-op
    spec = router_cell.PROTOCOL
    events = [
        {"op": "adopt", "address": "a:1", "cell": 0},
        {"op": "retire", "address": "a:1", "cell": 0},
        {"op": "retire", "address": "a:1", "cell": 1},
        {"op": "adopt", "address": "a:1", "cell": 1},
    ]
    validate_journal(spec, events)
    assert cell_recover(None, events) == ["a:1"]
