"""Master fault-tolerance failover tests.

Worker-level (in-process servicer + fault injector): a transient master
outage no longer terminates the worker as "end of job" — it retries
inside the bounded reconnect window; a genuinely finished job shuts the
worker down via the explicit JOB_COMPLETE signal even when the master
disappears right after.

End-to-end drill (subprocess): SIGKILL the master mid-job, restart it
from --job_state_dir, and prove the orphaned worker reconnects with
backoff, the job completes, every record range is processed exactly
once, and the recovery gauges land in the TensorBoard stream
(scripts/run_master_kill_drill.py owns the sequence; CI runs it on
every PR through this test).
"""

import grpc
import pytest

from elasticdl_tpu.common.fault_injection import (
    FaultInjectingServicer,
    FaultInjector,
)
from elasticdl_tpu.common.model_utils import load_model_spec_from_module
from elasticdl_tpu.common.retry import RetryPolicy
from elasticdl_tpu.data import recordio_gen
from elasticdl_tpu.master.master import Master
from elasticdl_tpu.proto import elasticdl_pb2 as pb
from elasticdl_tpu.worker.worker import JobType, Worker

# CI drills shard companion of test_worker_master_integration; tier-1
# ('not slow') includes this file so the failover drill gates every PR.
pytestmark = pytest.mark.integration


def _spec():
    from model_zoo.mnist_functional_api import mnist_functional_api as zoo

    return load_model_spec_from_module(zoo)


def _fast_policy(window=20.0):
    return RetryPolicy(
        rpc_timeout_secs=5.0,
        base_delay_secs=0.005,
        max_delay_secs=0.05,
        reconnect_window_secs=window,
    )


@pytest.fixture()
def train_dir(tmp_path):
    d = str(tmp_path / "train")
    recordio_gen.gen_mnist_like(d, num_files=2, records_per_file=48)
    return d


def _worker(master_servicer, train_dir, **kwargs):
    return Worker(
        0,
        _spec(),
        master_servicer=master_servicer,
        job_type=JobType.TRAINING_ONLY,
        minibatch_size=16,
        training_data=train_dir,
        wait_sleep_secs=0.05,
        retry_policy=_fast_policy(),
        **kwargs,
    )


def test_transient_outage_is_retried_not_end_of_job(train_dir):
    """RPC drops mid-job (the wire signature of a master restart) must
    NOT terminate the worker; it retries and the job completes with
    every record trained."""
    master = Master(
        _spec(), training_data=train_dir, minibatch_size=16,
        records_per_task=24, num_epochs=1,
    )
    injector = FaultInjector(
        # drop three polls mid-job + lose one applied report response
        # (the duplicate-side-effect path)
        spec="get_task:drop:3:skip=2;report_task_result:error:1",
    )
    worker = _worker(
        FaultInjectingServicer(master.servicer, injector), train_dir
    )
    state = worker.run()
    assert master.task_d.finished()
    assert int(state.step) == 96 // 16  # every range trained exactly once
    assert worker.rpc_retry_count >= 4
    assert injector.injected["get_task"] == 3
    assert worker.job_complete  # exited on the explicit signal


def test_clean_completion_via_explicit_signal(train_dir):
    """A finished job shuts the worker down via JOB_COMPLETE even when
    the master becomes unreachable immediately afterwards: post-signal
    RPCs degrade to best-effort instead of retrying a dead master."""
    master = Master(
        _spec(), training_data=train_dir, minibatch_size=16,
        records_per_task=48, num_epochs=1,
    )
    worker = _worker(master.servicer, train_dir)
    state = worker.run()
    assert worker.job_complete
    assert master.task_d.finished()
    assert int(state.step) == 96 // 16
    # master gone now: every further call is best-effort, never raises
    worker._master = FaultInjectingServicer(
        master.servicer, FaultInjector(spec="*:drop:*")
    )
    task = worker.get_task()
    assert task.type == pb.NONE and task.reason == pb.JOB_COMPLETE
    worker.report_task_result(1)
    worker.report_version(3)


def test_reconnect_window_exhaustion_raises(train_dir):
    """A master that never comes back must fail the worker LOUDLY after
    the bounded window — not silently, and not as a fake end-of-job."""
    master = Master(
        _spec(), training_data=train_dir, minibatch_size=16,
        records_per_task=48, num_epochs=1,
    )
    worker = Worker(
        0,
        _spec(),
        master_servicer=FaultInjectingServicer(
            master.servicer, FaultInjector(spec="get_task:drop:*")
        ),
        job_type=JobType.TRAINING_ONLY,
        minibatch_size=16,
        training_data=train_dir,
        retry_policy=RetryPolicy(base_delay_secs=0.005,
                                 max_delay_secs=0.02,
                                 reconnect_window_secs=0.3),
    )
    with pytest.raises(grpc.RpcError):
        worker.get_task()
    assert not worker.job_complete
    assert worker.rpc_retry_count > 0


def test_worker_reregisters_after_master_restart(train_dir):
    """A retried RPC that eventually lands means the master restarted:
    the worker re-registers so the new master's membership is whole."""
    master = Master(
        _spec(), training_data=train_dir, minibatch_size=16,
        records_per_task=24, num_epochs=1,
    )
    worker = _worker(
        FaultInjectingServicer(
            master.servicer, FaultInjector(spec="get_task:drop:2:skip=1")
        ),
        train_dir,
    )
    worker.run()
    assert worker.reconnect_count >= 1
    # re-registration reached the servicer (initial + at least one more)
    assert 0 in master.servicer._workers
    assert master.servicer._cluster_version >= 2


def test_master_recovery_gauges_exported(tmp_path, train_dir):
    """master/restarts + master/recovery_requeued_tasks ride the
    existing TensorBoard gauge path on a recovered master."""
    from elasticdl_tpu.master.tensorboard_service import TensorboardService

    state_dir = str(tmp_path / "state")
    master = Master(
        _spec(), training_data=train_dir, minibatch_size=16,
        records_per_task=24, num_epochs=1, job_state_dir=state_dir,
    )
    tid, _ = master.task_d.get(0)  # leave one task in-flight

    tb_dir = str(tmp_path / "tb")
    master2 = Master(
        _spec(), training_data=train_dir, minibatch_size=16,
        records_per_task=24, num_epochs=1, job_state_dir=state_dir,
        tensorboard_service=TensorboardService(tb_dir),
    )
    assert master2.task_d.requeued_on_recovery == 1
    assert master2.state_store.restart_count == 1
    master2._write_recovery_gauges()
    master2.tensorboard_service.stop()
    from scripts.run_master_kill_drill import tb_stream_contains

    assert tb_stream_contains(
        tb_dir, ["master/restarts", "master/recovery_requeued_tasks"]
    )


def test_master_kill_drill_end_to_end(tmp_path):
    """The full SIGKILL drill: master dies mid-job, restarts from the
    journal, the orphan worker reconnects (never exits), the job
    completes with exactly-once range accounting, and the recovery
    gauges appear in the TB stream."""
    from scripts.run_master_kill_drill import run_drill

    result = run_drill(
        workdir=str(tmp_path),
        num_files=2,
        records_per_file=32,
        records_per_task=8,
        minibatch_size=8,
        num_epochs=1,
        reconnect_window_secs=120,
        log=lambda *a: None,
    )
    assert result["ranges"] == 8  # 2 files x 32 records / 8 per task
