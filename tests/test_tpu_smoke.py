"""Hardware smoke tests: every Pallas kernel compiled (interpret=False)
on a real TPU chip, checked against the pure-jnp oracles.

Skipped under the default CPU test rig (tests/conftest.py pins
JAX_PLATFORMS=cpu). Run on hardware with:

    EDL_TPU_TEST_PLATFORM=tpu python -m pytest tests/test_tpu_smoke.py -q

VERDICT.md round-1 item #3: Mosaic lowering can reject shapes the Pallas
interpreter accepts, so interpreter-mode coverage (tests/test_ops.py)
does not prove these kernels run where it counts. This module is that
proof; scripts/build_and_test.sh runs it when a TPU is reachable.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

if jax.default_backend() != "tpu":  # pragma: no cover - rig-dependent
    pytest.skip(
        "TPU hardware smoke tests need a real chip "
        "(EDL_TPU_TEST_PLATFORM=tpu)",
        allow_module_level=True,
    )

from elasticdl_tpu.ops import attention, embedding_ops, optimizer_kernels
from elasticdl_tpu.ops import update_math as um


def _rand(rng, *shape, dtype=np.float32):
    return rng.standard_normal(shape).astype(dtype)


# ------------------------------------------------------------- attention


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_flash_attention_compiled(causal, dtype):
    rng = np.random.default_rng(0)
    b, h, seq, d = 2, 4, 256, 64
    q = jnp.asarray(_rand(rng, b, h, seq, d), dtype)
    k = jnp.asarray(_rand(rng, b, h, seq, d), dtype)
    v = jnp.asarray(_rand(rng, b, h, seq, d), dtype)
    out = attention.flash_attention(q, k, v, causal=causal,
                                    interpret=False)
    oracle = attention.naive_attention(
        q.astype(jnp.float32), k.astype(jnp.float32),
        v.astype(jnp.float32), causal=causal,
    )
    # fp32 matmuls on the MXU use bf16 multiply passes under default
    # precision, so even fp32 carries ~1e-3-scale error vs the fp32 oracle.
    tol = 2e-2
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(oracle), atol=tol, rtol=tol
    )


def test_jax_flash_dispatch_compiled():
    """attn_impl='jax_flash' routes to jax's bundled TPU flash kernel;
    values must match the naive oracle (the hardware sweep compares its
    speed against ours — scripts/bench_attention.py)."""
    rng = np.random.default_rng(3)
    b, h, seq, d = 2, 4, 256, 128
    q = jnp.asarray(_rand(rng, b, h, seq, d), jnp.bfloat16)
    k = jnp.asarray(_rand(rng, b, h, seq, d), jnp.bfloat16)
    v = jnp.asarray(_rand(rng, b, h, seq, d), jnp.bfloat16)
    out = attention.jax_flash_attention(q, k, v, causal=True)
    oracle = attention.naive_attention(
        q.astype(jnp.float32), k.astype(jnp.float32),
        v.astype(jnp.float32), causal=True,
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(oracle), atol=2e-2,
        rtol=2e-2,
    )


def test_flash_attention_grad_compiled():
    rng = np.random.default_rng(1)
    b, h, seq, d = 1, 2, 128, 64
    q = jnp.asarray(_rand(rng, b, h, seq, d))
    k = jnp.asarray(_rand(rng, b, h, seq, d))
    v = jnp.asarray(_rand(rng, b, h, seq, d))

    def loss_flash(q, k, v):
        return attention.flash_attention(
            q, k, v, causal=True, interpret=False
        ).sum()

    def loss_ref(q, k, v):
        return attention.naive_attention(q, k, v, causal=True).sum()

    grads = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    refs = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, r in zip(grads, refs):
        # MXU default-precision numerics (see forward test); compare by
        # absolute tolerance only — rtol misfires on near-zero grads.
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(r), atol=3e-2, rtol=0
        )


@pytest.mark.parametrize("causal", [False, True])
def test_sliding_window_compiled(causal):
    """Windowed flash fwd + two-pass Pallas bwd compiled on hardware,
    with a window smaller than a block (block-skip predicate active)."""
    rng = np.random.default_rng(5)
    b, h, seq, d = 1, 2, 256, 128
    q = jnp.asarray(_rand(rng, b, h, seq, d))
    k = jnp.asarray(_rand(rng, b, h, seq, d))
    v = jnp.asarray(_rand(rng, b, h, seq, d))
    w = 48

    def loss_flash(q, k, v):
        return (attention.flash_attention(
            q, k, v, causal=causal, window=w, interpret=False
        ) ** 2).sum()

    def loss_ref(q, k, v):
        return (attention.naive_attention(
            q, k, v, causal=causal, window=w
        ) ** 2).sum()

    out = attention.flash_attention(q, k, v, causal=causal, window=w,
                                    interpret=False)
    oracle = attention.naive_attention(q, k, v, causal=causal, window=w)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(oracle), atol=2e-2, rtol=2e-2
    )
    grads = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    refs = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, r in zip(grads, refs):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(r), atol=5e-2, rtol=0
        )


def test_rope_flash_compiled():
    """RoPE'd q/k through the compiled flash kernel vs the fp32 oracle."""
    rng = np.random.default_rng(6)
    b, h, seq, d = 1, 2, 256, 128
    q = jnp.asarray(_rand(rng, b, h, seq, d), jnp.bfloat16)
    k = jnp.asarray(_rand(rng, b, h, seq, d), jnp.bfloat16)
    v = jnp.asarray(_rand(rng, b, h, seq, d), jnp.bfloat16)
    pos = jnp.arange(seq)
    qr = attention.apply_rope(q, pos)
    kr = attention.apply_rope(k, pos)
    out = attention.flash_attention(qr, kr, v, causal=True,
                                    interpret=False)
    oracle = attention.naive_attention(
        attention.apply_rope(q.astype(jnp.float32), pos),
        attention.apply_rope(k.astype(jnp.float32), pos),
        v.astype(jnp.float32), causal=True,
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(oracle),
        atol=2e-2, rtol=2e-2,
    )


# ------------------------------------------------- dense optimizer kernels


def test_sgd_kernel_compiled():
    rng = np.random.default_rng(2)
    p, g = _rand(rng, 1000, 37), _rand(rng, 1000, 37)
    out = optimizer_kernels.sgd_update(p, g, 0.1, interpret=False)
    np.testing.assert_allclose(
        np.asarray(out), um.sgd_math(p, g, 0.1), atol=1e-6
    )


def test_momentum_kernel_compiled():
    rng = np.random.default_rng(3)
    p, v, g = (_rand(rng, 513, 129) for _ in range(3))
    new_p, new_v = optimizer_kernels.momentum_update(
        p, v, g, 0.01, momentum=0.9, nesterov=True, interpret=False
    )
    ref_p, ref_v = um.momentum_math(p, v, g, 0.01, 0.9, 1.0)
    np.testing.assert_allclose(np.asarray(new_p), np.asarray(ref_p),
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(new_v), np.asarray(ref_v),
                               atol=1e-6)


def test_adam_kernel_compiled():
    rng = np.random.default_rng(4)
    p, m, v, g = (_rand(rng, 2048) for _ in range(4))
    outs = optimizer_kernels.adam_update(
        p, m, v, g, step=3, lr=1e-3, interpret=False
    )
    alpha = um.adam_alpha(1e-3, 0.9, 0.999, 3)
    refs = um.adam_math(p, m, v, g, alpha, 0.9, 0.999, 1e-8)
    for o, r in zip(outs, refs):
        np.testing.assert_allclose(np.asarray(o), np.asarray(r), atol=1e-6)


def test_adam_amsgrad_kernel_compiled():
    rng = np.random.default_rng(5)
    p, m, v, ms, g = (_rand(rng, 300, 7) for _ in range(5))
    ms = np.abs(ms)
    outs = optimizer_kernels.adam_update(
        p, m, v, g, step=1, lr=1e-3, max_square=ms, interpret=False
    )
    alpha = um.adam_alpha(1e-3, 0.9, 0.999, 1)
    refs = um.adam_amsgrad_math(p, m, v, ms, g, alpha, 0.9, 0.999, 1e-8)
    for o, r in zip(outs, refs):
        np.testing.assert_allclose(np.asarray(o), np.asarray(r), atol=1e-6)


def test_adagrad_kernel_compiled():
    rng = np.random.default_rng(6)
    p, a, g = (_rand(rng, 4096) for _ in range(3))
    a = np.abs(a)
    new_p, new_a = optimizer_kernels.adagrad_update(
        p, a, g, 0.05, interpret=False
    )
    ref_p, ref_a = um.adagrad_math(p, a, g, 0.05, 1e-10)
    np.testing.assert_allclose(np.asarray(new_p), np.asarray(ref_p),
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(new_a), np.asarray(ref_a),
                               atol=1e-6)


# ----------------------------------------------------- sparse row kernels


def test_embedding_gather_compiled():
    rng = np.random.default_rng(7)
    table = _rand(rng, 5000, 64)
    ids = rng.integers(0, 5000, size=37).astype(np.int32)
    out = embedding_ops.embedding_gather(
        jnp.asarray(table), jnp.asarray(ids), interpret=False
    )
    np.testing.assert_allclose(np.asarray(out), table[ids], atol=1e-6)


def test_sparse_sgd_update_compiled():
    rng = np.random.default_rng(8)
    table = _rand(rng, 1000, 128)
    ids = np.array([3, 77, 500, 999], np.int32)
    grads = _rand(rng, 4, 128)
    out = embedding_ops.sparse_sgd_update(
        jnp.asarray(table), jnp.asarray(ids), jnp.asarray(grads), 0.1,
        interpret=False,
    )
    ref = table.copy()
    ref[ids] -= 0.1 * grads
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-6)


def test_sparse_adam_update_compiled():
    rng = np.random.default_rng(9)
    vocab, dim, n = 800, 64, 16
    table, m, v = (_rand(rng, vocab, dim) for _ in range(3))
    v = np.abs(v)
    ids = rng.integers(0, vocab, size=n).astype(np.int32)
    ids = np.unique(ids).astype(np.int32)  # kernel expects deduped rows
    grads = _rand(rng, ids.size, dim)
    new_t, new_m, new_v = embedding_ops.sparse_adam_update(
        jnp.asarray(table), jnp.asarray(m), jnp.asarray(v),
        jnp.asarray(ids), jnp.asarray(grads), step=2, lr=1e-3,
        interpret=False,
    )
    alpha = um.adam_alpha(1e-3, 0.9, 0.999, 2)
    ref_rows = um.adam_math(
        table[ids], m[ids], v[ids], grads, alpha, 0.9, 0.999, 1e-8
    )
    for new, base, ref in zip((new_t, new_m, new_v), (table, m, v),
                              ref_rows):
        expect = base.copy()
        expect[ids] = np.asarray(ref)
        np.testing.assert_allclose(np.asarray(new), expect, atol=1e-5)


def test_sparse_adagrad_update_compiled():
    rng = np.random.default_rng(10)
    vocab, dim = 600, 32
    table, accum = _rand(rng, vocab, dim), np.abs(_rand(rng, vocab, dim))
    ids = np.array([0, 5, 599], np.int32)
    grads = _rand(rng, 3, dim)
    new_t, new_a = embedding_ops.sparse_adagrad_update(
        jnp.asarray(table), jnp.asarray(accum), jnp.asarray(ids),
        jnp.asarray(grads), 0.05, interpret=False,
    )
    ref_t, ref_a = um.adagrad_math(
        table[ids], accum[ids], grads, 0.05, 1e-10
    )
    expect_t, expect_a = table.copy(), accum.copy()
    expect_t[ids] = np.asarray(ref_t)
    expect_a[ids] = np.asarray(ref_a)
    np.testing.assert_allclose(np.asarray(new_t), expect_t, atol=1e-6)
    np.testing.assert_allclose(np.asarray(new_a), expect_a, atol=1e-6)


# -------------------------------------------------- end-to-end on hardware


def test_trainer_step_on_tpu():
    """One real compiled train step (trainer + flash attention path) on
    the chip — the bench's hot loop, as a pass/fail correctness check."""
    from elasticdl_tpu.common.model_utils import (
        format_params_str,
        load_model_spec_from_module,
    )
    from elasticdl_tpu.parallel import mesh as mesh_lib
    from elasticdl_tpu.training.trainer import Trainer
    from model_zoo.transformer_lm import transformer_lm as zoo

    spec = load_model_spec_from_module(zoo)
    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = Trainer(
        spec,
        mesh=mesh,
        model_params=format_params_str(
            dict(vocab_size=256, seq_len=128, embed_dim=128,
                 num_heads=4, num_layers=2, dtype="bf16")
        ),
    )
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, 256, size=(8, 129)).astype(np.int32)
    batch = ({"tokens": tokens[:, :-1]}, tokens[:, 1:])
    state = trainer.init_state(batch)
    losses = []
    for _ in range(5):
        state, loss = trainer.train_step(state, batch)
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], "loss did not decrease on-chip"
