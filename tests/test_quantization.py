"""Weight-only int8 decode (api/quantization.py): quantize/dequantize
round-trip quality, bandwidth accounting, and generation through the
quantized path (all four decode strategies share _maybe_dequantize)."""

import numpy as np

import jax
import jax.numpy as jnp

from elasticdl_tpu.api.generation import (
    autoregressive_generate,
    beam_search_generate,
)
from elasticdl_tpu.api.quantization import (
    dequantize_params,
    is_quantized,
    quantize_params,
    quantized_bytes,
)
from elasticdl_tpu.common.model_utils import load_model_spec_from_module
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.training.trainer import Trainer
from model_zoo.transformer_lm import transformer_lm as zoo

import pytest

# CI drills shard (make test-drills): the sub-5-min per-commit gate excludes this file.
pytestmark = pytest.mark.slow

PARAMS = (
    "vocab_size=8; seq_len=16; embed_dim=32; num_heads=2; num_layers=1"
)


def _cycle_batch(bsz=8, seq_len=16, vocab=8, seed=0):
    rs = np.random.RandomState(seed)
    starts = rs.randint(0, vocab, size=(bsz, 1))
    tokens = (starts + np.arange(seq_len + 1)[None, :]) % vocab
    tokens = tokens.astype(np.int32)
    return {"tokens": tokens[:, :-1]}, tokens[:, 1:]


def _trained_trainer(steps=250):
    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = Trainer(
        load_model_spec_from_module(zoo), mesh=mesh, model_params=PARAMS
    )
    state = trainer.init_state(_cycle_batch())
    for step in range(steps):
        state, loss = trainer.train_step(state, _cycle_batch(seed=step))
    if steps >= 200:  # short warmups are for structural tests
        assert float(loss) < 0.15
    return trainer, state


def test_roundtrip_and_detection():
    rs = np.random.RandomState(0)
    params = {
        "dense": {"kernel": rs.randn(128, 64).astype(np.float32)},
        "norm": {"scale": rs.randn(64).astype(np.float32)},
        "tiny": {"kernel": rs.randn(4, 4).astype(np.float32)},
    }
    q = quantize_params(params, min_size=1024)
    assert is_quantized(q) and not is_quantized(params)
    # untouched leaves stay identical
    np.testing.assert_array_equal(q["norm"]["scale"],
                                  params["norm"]["scale"])
    np.testing.assert_array_equal(q["tiny"]["kernel"],
                                  params["tiny"]["kernel"])
    deq = dequantize_params(q)
    w = params["dense"]["kernel"]
    # per-channel symmetric int8: error bounded by scale/2 per entry
    amax = np.abs(w).max(axis=0)
    err = np.abs(np.asarray(deq["dense"]["kernel"]) - w)
    assert (err <= amax / 127.0 * 0.5 + 1e-7).all()
    qb, ob = quantized_bytes(q)
    # fp32 kernel -> ~4x smaller (scales + unquantized leaves dilute)
    assert qb < ob * 0.45


def test_bfloat16_params_quantize_with_true_ratio():
    """bf16 kernels (the usual TPU param dtype) must quantize — numpy's
    issubdtype does not consider ml_dtypes.bfloat16 a floating type, so
    the gate goes through jnp — and the bandwidth accounting must use
    the recorded 2-byte source itemsize, not assume float32."""
    rs = np.random.RandomState(0)
    w = rs.randn(128, 64).astype(np.float32)
    params = {"dense": {"kernel": jnp.asarray(w, jnp.bfloat16)}}
    q = quantize_params(params, min_size=1024)
    assert is_quantized(q)
    deq = np.asarray(dequantize_params(q)["dense"]["kernel"])
    amax = np.abs(w).max(axis=0)
    # int8 grid over a bf16 source: half-step of the int8 scale plus
    # the bf16 rounding already present in the input
    assert (np.abs(deq - w) <= amax / 127.0 * 0.5 + np.abs(w) * 0.01
            + 1e-6).all()
    qb, ob = quantized_bytes(q)
    assert ob == w.size * 2  # source itemsize recorded, not 4
    # int8 + f32 scales vs bf16 original: just under 2x, not "4x"
    assert ob * 0.5 <= qb < ob * 0.6


def test_quantized_decode_all_strategies():
    """A trained cycle model decodes the cycle through int8 weights on
    every strategy; greedy tokens match the float path (decisive
    margins after training)."""
    trainer, state = _trained_trainer()
    qstate = state.replace(params=quantize_params(state.params))
    assert is_quantized(qstate.params)
    prompt = np.asarray([[3, 4, 5], [6, 7, 0]], np.int32)
    ref = np.asarray(autoregressive_generate(trainer, state, prompt, 6))
    for kwargs in (
        {},
        {"use_cache": True},
    ):
        got = np.asarray(
            autoregressive_generate(trainer, qstate, prompt, 6, **kwargs)
        )
        np.testing.assert_array_equal(ref, got, err_msg=str(kwargs))
    for kwargs in ({}, {"use_cache": True}):
        got = np.asarray(
            beam_search_generate(trainer, qstate, prompt, 6,
                                 num_beams=2, **kwargs)
        )
        np.testing.assert_array_equal(ref, got, err_msg=str(kwargs))


def test_quantized_state_checkpoint_roundtrip(tmp_path):
    """An int8-quantized serving state survives the sharded checkpoint
    (the marker dicts are ordinary pytree nodes with array leaves), so
    a serving artifact can be exported/restored without the float
    originals."""
    from elasticdl_tpu.checkpoint.saver import (
        CheckpointSaver,
        flatten_state,
        load_checkpoint,
    )

    trainer, state = _trained_trainer(steps=5)
    qstate = state.replace(params=quantize_params(state.params))
    saver = CheckpointSaver(str(tmp_path), checkpoint_steps=1,
                            num_shards=2)
    saver.save(qstate, version=1)
    flat, version = load_checkpoint(str(tmp_path))
    assert version == 1
    expect = flatten_state(qstate)
    assert set(flat) == set(expect)
    for key in expect:
        np.testing.assert_array_equal(np.asarray(flat[key]),
                                      np.asarray(expect[key]))
    # int8 payloads persisted as int8 (not upcast)
    int8_keys = [k for k in flat if "__w8__" in k]
    assert int8_keys
    assert all(flat[k].dtype == np.int8 for k in int8_keys)


def test_distill_from_quantized_target():
    """warm_start_draft/distill_draft accept an int8-quantized target
    (dequantized float view) — the serving combo of quantization +
    trained-draft speculative decode."""
    from elasticdl_tpu.api.distill import distill_draft, warm_start_draft

    trainer, state = _trained_trainer(steps=5)
    # low min_size so the tiny model's kernels actually quantize
    qstate = state.replace(
        params=quantize_params(state.params, min_size=64)
    )
    assert is_quantized(qstate.params)
    draft = Trainer(
        load_model_spec_from_module(zoo),
        mesh=mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1]),
        model_params=PARAMS,
    )
    d_state = draft.init_state(_cycle_batch())
    d_warm = warm_start_draft(qstate, d_state)
    # the copy lands dense floats, dequantized from the int8 view
    np.testing.assert_allclose(
        np.asarray(d_warm.params["wte"]["embedding"]),
        np.asarray(dequantize_params(qstate.params)["wte"]["embedding"]),
    )
    assert not is_quantized(d_warm.params)
    rs = np.random.RandomState(0)
    d_new, losses = distill_draft(
        trainer, qstate, draft, d_warm,
        [rs.randint(0, 8, size=(4, 16)).astype(np.int32)
         for _ in range(3)],
    )
    assert len(losses) == 3 and np.isfinite(losses).all()


def test_quantized_speculative_decode():
    """Speculative decoding with an int8 target (and float draft) must
    equal the float target's greedy output — the serving combo of the
    two features."""
    from elasticdl_tpu.api.generation import speculative_generate

    target, t_state = _trained_trainer()
    draft, d_state = _trained_trainer(steps=200)
    prompt = np.asarray([[3, 4, 5]], np.int32)
    ref = np.asarray(
        autoregressive_generate(target, t_state, prompt, 6,
                                use_cache=True)
    )
    qt = t_state.replace(params=quantize_params(t_state.params))
    got = np.asarray(
        speculative_generate(target, qt, draft, d_state, prompt, 6,
                             gamma=3)
    )
    np.testing.assert_array_equal(ref, got)
