"""Sparse embedding engine tests.

Mirrors the reference's layer_test.py (combiner math vs hand-computed) and
optimizer_wrapper_test.py (sparse updates: only touched rows + slots move).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from elasticdl_tpu.embedding import (
    Embedding,
    make_row_sparse,
    safe_embedding_lookup,
)
from elasticdl_tpu.embedding.layer import PADDING_ID


@pytest.fixture(scope="module")
def table():
    rng = np.random.RandomState(0)
    return jnp.asarray(rng.randn(10, 4).astype(np.float32))


class TestSafeEmbeddingLookup:
    def test_sum_mean_sqrtn(self, table):
        ids = np.array([[1, 3, PADDING_ID], [2, PADDING_ID, PADDING_ID]])
        t = np.asarray(table)
        out_sum = safe_embedding_lookup(table, ids, "sum")
        np.testing.assert_allclose(
            np.asarray(out_sum),
            np.stack([t[1] + t[3], t[2]]),
            rtol=1e-6,
        )
        out_mean = safe_embedding_lookup(table, ids, "mean")
        np.testing.assert_allclose(
            np.asarray(out_mean),
            np.stack([(t[1] + t[3]) / 2.0, t[2]]),
            rtol=1e-6,
        )
        out_sqrtn = safe_embedding_lookup(table, ids, "sqrtn")
        np.testing.assert_allclose(
            np.asarray(out_sqrtn),
            np.stack([(t[1] + t[3]) / np.sqrt(2.0), t[2]]),
            rtol=1e-6,
        )

    def test_empty_row_is_zero(self, table):
        """safe_embedding_lookup_sparse parity: a batch row with no ids
        yields a zero vector, not NaN (embedding_delegate.py:108-230)."""
        ids = np.array([[PADDING_ID, PADDING_ID], [5, PADDING_ID]])
        for combiner in ("sum", "mean", "sqrtn"):
            out = np.asarray(safe_embedding_lookup(table, ids, combiner))
            np.testing.assert_allclose(out[0], np.zeros(4), atol=0)
            assert np.isfinite(out).all()

    def test_weights(self, table):
        ids = np.array([[1, 3, PADDING_ID]])
        w = np.array([[2.0, 0.5, 7.0]])  # padding weight must be ignored
        t = np.asarray(table)
        out = np.asarray(safe_embedding_lookup(table, ids, "sum", w))
        np.testing.assert_allclose(
            out[0], 2.0 * t[1] + 0.5 * t[3], rtol=1e-6
        )
        out_mean = np.asarray(safe_embedding_lookup(table, ids, "mean", w))
        np.testing.assert_allclose(
            out_mean[0], (2.0 * t[1] + 0.5 * t[3]) / 2.5, rtol=1e-6
        )


class TestEmbeddingLayer:
    def test_dense_ids(self):
        layer = Embedding(input_dim=10, output_dim=4)
        params = layer.init(jax.random.PRNGKey(0), jnp.zeros((2,), jnp.int32))
        ids = jnp.asarray([3, 7])
        out = layer.apply(params, ids)
        table = params["params"]["embedding_table"]
        np.testing.assert_array_equal(
            np.asarray(out), np.asarray(table)[np.array([3, 7])]
        )
        # keras Embedding behavior: [batch, k] -> [batch, k, dim]
        out2 = layer.apply(params, jnp.asarray([[1, 2], [3, 4]]))
        assert out2.shape == (2, 2, 4)

    def test_combiner_layer(self):
        layer = Embedding(input_dim=10, output_dim=4, combiner="mean")
        ids = jnp.asarray([[1, 3, PADDING_ID]])
        params = layer.init(jax.random.PRNGKey(0), ids)
        out = layer.apply(params, ids)
        table = np.asarray(params["params"]["embedding_table"])
        np.testing.assert_allclose(
            np.asarray(out)[0], (table[1] + table[3]) / 2.0, rtol=1e-6
        )

    def test_initializer_distribution(self):
        """'uniform' must be keras RandomUniform(-0.05, 0.05) — also what
        the reference Go PS hard-codes (embedding_table.go:50-54)."""
        layer = Embedding(input_dim=1000, output_dim=8)
        params = layer.init(
            jax.random.PRNGKey(0), jnp.zeros((2,), jnp.int32)
        )
        table = np.asarray(params["params"]["embedding_table"])
        assert table.min() >= -0.05 and table.max() <= 0.05
        assert table.std() > 0.02  # roughly uniform, not degenerate


class TestRowSparseOptimizer:
    def _setup(self, tx):
        rng = np.random.RandomState(1)
        params = {
            "layer": {"embedding_table": jnp.asarray(
                rng.randn(8, 3).astype(np.float32))},
            "dense": {"kernel": jnp.asarray(
                rng.randn(3, 2).astype(np.float32))},
        }
        state = tx.init(params)
        return params, state

    def _grads(self, touched_rows, dense_val=0.1):
        g = np.zeros((8, 3), np.float32)
        for r in touched_rows:
            g[r] = 0.5
        return {
            "layer": {"embedding_table": jnp.asarray(g)},
            "dense": {"kernel": jnp.full((3, 2), dense_val, jnp.float32)},
        }

    def test_untouched_rows_frozen_under_adam(self):
        tx = make_row_sparse(optax.adam(0.1))
        params, state = self._setup(tx)
        p0 = np.asarray(params["layer"]["embedding_table"]).copy()

        grads = self._grads([1, 4])
        updates, state = tx.update(grads, state, params)
        params = optax.apply_updates(params, updates)
        p1 = np.asarray(params["layer"]["embedding_table"])
        for r in range(8):
            if r in (1, 4):
                assert not np.allclose(p1[r], p0[r])
            else:
                np.testing.assert_array_equal(p1[r], p0[r])
        # dense params always update
        assert not np.allclose(
            np.asarray(params["dense"]["kernel"]),
            np.asarray(self._setup(tx)[0]["dense"]["kernel"]),
        )

    def test_slots_frozen_for_untouched_rows(self):
        tx = make_row_sparse(optax.adam(0.1))
        params, state = self._setup(tx)
        _, state1 = tx.update(self._grads([2]), state, params)
        mu = jax.tree.leaves(
            jax.tree_util.tree_map(
                lambda x: x, state1[0].mu["layer"]["embedding_table"]
            )
        )[0]
        mu = np.asarray(mu)
        assert np.any(mu[2] != 0)
        for r in range(8):
            if r != 2:
                np.testing.assert_array_equal(mu[r], np.zeros(3))

    def test_late_touched_row_behaves_like_first_step(self):
        """A row first touched at step 3 must see zero moments (sparse Adam
        semantics: its slots never decayed during steps 1-2)."""
        tx = make_row_sparse(optax.adam(0.1))
        params, state = self._setup(tx)
        p_init = np.asarray(params["layer"]["embedding_table"]).copy()
        for _ in range(2):
            updates, state = tx.update(self._grads([0]), state, params)
            params = optax.apply_updates(params, updates)
        # row 5 untouched so far: identical to init
        np.testing.assert_array_equal(
            np.asarray(params["layer"]["embedding_table"])[5], p_init[5]
        )
        updates, state = tx.update(self._grads([5]), state, params)
        mu5 = np.asarray(state[0].mu["layer"]["embedding_table"])[5]
        # fresh first-moment: (1 - b1) * g
        np.testing.assert_allclose(mu5, 0.1 * 0.5 * np.ones(3), rtol=1e-5)

    def test_sgd_matches_dense_on_touched_rows(self):
        tx_sparse = make_row_sparse(optax.sgd(0.2))
        tx_dense = optax.sgd(0.2)
        params, state = self._setup(tx_sparse)
        params_d = jax.tree.map(jnp.copy, params)
        state_d = tx_dense.init(params_d)
        g = self._grads([3, 6])
        u_s, _ = tx_sparse.update(g, state, params)
        u_d, _ = tx_dense.update(g, state_d, params_d)
        np.testing.assert_array_equal(
            np.asarray(u_s["layer"]["embedding_table"]),
            np.asarray(u_d["layer"]["embedding_table"]),
        )

    def test_no_embedding_passthrough(self):
        tx = make_row_sparse(optax.adam(0.1))
        params = {"dense": jnp.ones((4, 2))}
        state = tx.init(params)
        updates, _ = tx.update({"dense": jnp.ones((4, 2))}, state, params)
        assert np.asarray(updates["dense"]).shape == (4, 2)


class TestShardedEmbeddingTraining:
    def test_train_step_with_ep_sharded_table(self):
        """End-to-end: a model with an Embedding table trains on a mesh with
        ep=2; table + slots shard over (ep, fsdp); loss decreases."""
        import flax.linen as nn

        from elasticdl_tpu.common.model_utils import ModelSpec
        from elasticdl_tpu.parallel import mesh as mesh_lib
        from elasticdl_tpu.parallel.sharding import infer_state_pspec
        from elasticdl_tpu.training.trainer import Trainer

        class TinyRec(nn.Module):
            @nn.compact
            def __call__(self, features, training=False):
                emb = Embedding(
                    input_dim=16, output_dim=8, combiner="sum",
                    name="cat_embed",
                )(features["ids"])
                x = jnp.concatenate([emb, features["num"]], axis=-1)
                x = nn.relu(nn.Dense(16)(x))
                return nn.Dense(1)(x)[:, 0]

        def loss(labels, predictions, weights=None):
            per = optax.sigmoid_binary_cross_entropy(
                predictions, labels.astype(jnp.float32)
            )
            if weights is None:
                return jnp.mean(per)
            return jnp.sum(per * weights) / jnp.maximum(
                jnp.sum(weights), 1.0
            )

        spec = ModelSpec(
            model_fn=TinyRec,
            dataset_fn=lambda ds, mode, meta: ds,
            loss=loss,
            optimizer=lambda: optax.adam(1e-2),
            eval_metrics_fn=lambda: {},
        )
        mesh = mesh_lib.build_mesh({"dp": 2, "fsdp": 2, "ep": 2})
        # threshold 0: force ep-sharding even for this tiny test table
        trainer = Trainer(spec, mesh=mesh, embedding_partition_threshold=0)
        rng = np.random.RandomState(0)
        batch = (
            {
                "ids": rng.randint(0, 16, size=(16, 4)).astype(np.int32),
                "num": rng.randn(16, 2).astype(np.float32),
            },
            (rng.rand(16) > 0.5).astype(np.int32),
        )
        state = trainer.init_state(batch)

        # the table (and its adam moments) actually shard over (ep, fsdp)
        specs = infer_state_pspec(
            jax.tree.map(lambda x: x, state), mesh,
            embedding_threshold_bytes=0,
        )
        from jax.sharding import PartitionSpec

        flat = {
            jax.tree_util.keystr(p): s
            for p, s in jax.tree_util.tree_flatten_with_path(
                specs, is_leaf=lambda x: isinstance(x, PartitionSpec)
            )[0]
        }
        table_specs = [v for k, v in flat.items() if "embedding_table" in k]
        assert len(table_specs) >= 3  # param + mu + nu
        for s in table_specs:
            assert s[0] == ("ep", "fsdp")

        losses = []
        for _ in range(10):
            state, l = trainer.train_step(state, batch)
            losses.append(float(l))
        assert losses[-1] < losses[0]
