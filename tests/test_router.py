"""Router tier unit tests (tier-1: no jax, no sockets — in-process
fake replica stubs drive serving/router.py).

Locks the ISSUE's robustness semantics: lease expiry removes silent
replicas, breaker trip/half-open/close, re-dispatch before first
token (unary and stream), drain-aware rotation removal, all-breakers-
open shed-load, backpressure rerouting without breaker damage, hedged
dispatch, and fault injection at the router RPC boundary."""

import threading

import grpc
import pytest

from elasticdl_tpu.common.fault_injection import (
    SERVING_RPCS,
    FaultInjector,
    InjectedRpcError,
    maybe_wrap_servicer,
)
from elasticdl_tpu.proto import elasticdl_pb2 as pb
from elasticdl_tpu.serving.router import (
    CircuitBreaker,
    Router,
    RouterConfig,
    RouterError,
    RouterServicer,
)


class FakeClock(object):
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _unavailable(msg="replica down"):
    return InjectedRpcError(grpc.StatusCode.UNAVAILABLE, msg)


def _exhausted(msg="queue full"):
    return InjectedRpcError(grpc.StatusCode.RESOURCE_EXHAUSTED, msg)


def _invalid(msg="bad request"):
    return InjectedRpcError(grpc.StatusCode.INVALID_ARGUMENT, msg)


class FakeReplicaStub(object):
    """ServingStub-shaped fake: scripted failures, scripted status."""

    def __init__(self, token):
        self.token = token  # marks which replica answered
        self.poll_ok = True
        self.draining = False
        self.queue_depth = 0
        self.active_slots = 0
        self.kv_blocks_free = 0
        self.kv_blocks_cached = 0
        self.kv_blocks_shared = 0
        self.health_state = ""
        self.queue_wait_ms = 0.0
        self.gen_errors = []  # exceptions raised by upcoming generates
        self.stream_errors = []
        self.stream_fail_after_chunks = None
        self.calls = 0
        self.block_until = None  # Event: generate blocks until set
        self.status_calls = 0
        self.status_block_until = None  # Event: status blocks until set
        self.closed = 0  # channel closes via the retire path

    def close(self):
        self.closed += 1

    def server_status(self, request, timeout=None):
        self.status_calls += 1
        if self.status_block_until is not None:
            assert self.status_block_until.wait(5.0)
        if not self.poll_ok:
            raise _unavailable("poll down")
        return pb.ServerStatusResponse(
            queue_depth=self.queue_depth,
            active_slots=self.active_slots,
            kv_blocks_free=self.kv_blocks_free,
            kv_blocks_cached=self.kv_blocks_cached,
            kv_blocks_shared=self.kv_blocks_shared,
            health_state=self.health_state,
            queue_wait_ms=self.queue_wait_ms,
            draining=self.draining,
        )

    def generate(self, request, timeout=None):
        self.calls += 1
        if self.block_until is not None:
            assert self.block_until.wait(5.0)
        if self.gen_errors:
            raise self.gen_errors.pop(0)
        return pb.GenerateResponse(
            tokens=list(request.prompt) + [self.token], model_version=1
        )

    def generate_stream(self, request, timeout=None):
        self.calls += 1
        if self.stream_errors:
            raise self.stream_errors.pop(0)

        def chunks():
            for i in range(request.max_new_tokens):
                if self.stream_fail_after_chunks is not None \
                        and i >= self.stream_fail_after_chunks:
                    raise _unavailable("died mid-stream")
                yield pb.TokenChunk(tokens=[self.token + i],
                                    model_version=1)
            yield pb.TokenChunk(tokens=[], done=True, model_version=1)

        return chunks()


def make_router(n=2, clock=None, advance_on_sleep=True, **cfg_kwargs):
    """Router over n fake replicas with a fake clock; sleeps advance
    the clock so backoff/window logic runs without real waiting."""
    clock = clock or FakeClock()
    stubs = {"rep%d" % i: FakeReplicaStub(token=100 * (i + 1))
             for i in range(n)}
    cfg = RouterConfig(
        lease_secs=10.0, breaker_threshold=2,
        breaker_cooldown_secs=5.0, redispatch_window_secs=8.0,
        base_delay_secs=0.01, max_delay_secs=0.05, **cfg_kwargs
    )
    sleep = clock.advance if advance_on_sleep else (lambda s: None)
    router = Router(
        sorted(stubs), config=cfg, stub_factory=lambda a: stubs[a],
        clock=clock, sleep=sleep,
    )
    return router, stubs, clock


def _req(prompt=(1, 2), new=3, deadline_ms=0):
    return pb.GenerateRequest(prompt=list(prompt), max_new_tokens=new,
                              deadline_ms=deadline_ms)


# ------------------------------------------------------- circuit breaker


def test_breaker_trip_half_open_close_cycle():
    b = CircuitBreaker(threshold=3, cooldown_secs=2.0)
    now = 0.0
    assert b.state == CircuitBreaker.CLOSED
    assert not b.record_failure(now)
    assert not b.record_failure(now)
    assert b.record_failure(now)  # third consecutive -> trips
    assert b.state == CircuitBreaker.OPEN
    assert not b.eligible(now + 1.0)  # cooldown running
    assert b.eligible(now + 2.0)  # cooldown elapsed
    # half-open admits exactly ONE probe
    assert b.acquire(now + 2.0)
    assert b.state == CircuitBreaker.HALF_OPEN
    assert not b.eligible(now + 2.0)  # probe in flight
    assert not b.acquire(now + 2.0)
    assert b.record_success()  # probe wins -> CLOSED
    assert b.state == CircuitBreaker.CLOSED and b.failures == 0


def test_breaker_half_open_failure_reopens():
    b = CircuitBreaker(threshold=1, cooldown_secs=2.0)
    b.record_failure(0.0)
    assert b.state == CircuitBreaker.OPEN
    assert b.acquire(2.5)
    assert b.state == CircuitBreaker.HALF_OPEN
    # the probe fails -> straight back to OPEN with a fresh cooldown
    assert b.record_failure(2.5)
    assert b.state == CircuitBreaker.OPEN
    assert not b.eligible(4.0)  # cooldown restarted at 2.5
    assert b.eligible(4.6)


def test_breaker_success_resets_consecutive_count():
    b = CircuitBreaker(threshold=2, cooldown_secs=1.0)
    b.record_failure(0.0)
    b.record_success()
    # the streak broke: one more failure must NOT trip
    assert not b.record_failure(0.0)
    assert b.state == CircuitBreaker.CLOSED


def test_breaker_release_probe_frees_slot_without_judging():
    b = CircuitBreaker(threshold=1, cooldown_secs=2.0)
    b.record_failure(0.0)
    assert b.acquire(2.5)
    assert b.state == CircuitBreaker.HALF_OPEN
    assert not b.eligible(2.5)  # the single probe slot is held
    # the probe failed for a reason that says nothing about transport
    # health: the slot frees, the state does not move
    b.release_probe()
    assert b.state == CircuitBreaker.HALF_OPEN
    assert b.eligible(2.5)
    assert b.acquire(2.5)  # the NEXT probe can run
    assert b.record_success()
    assert b.state == CircuitBreaker.CLOSED


# ---------------------------------------------------------------- routing


def test_least_loaded_routing():
    router, stubs, _ = make_router(2)
    stubs["rep0"].queue_depth = 5
    stubs["rep1"].queue_depth = 0
    router.poll_once()
    resp = router.dispatch_generate(_req())
    assert list(resp.tokens) == [1, 2, 200]  # rep1 answered
    assert stubs["rep1"].calls == 1 and stubs["rep0"].calls == 0


def test_inflight_dispatches_spread_ties():
    """Polled load freezes between heartbeats; the router's own
    in-flight count must break ties or every request in a poll window
    herds onto one replica."""
    router, stubs, _ = make_router(2)
    router.poll_once()
    reps = {r.address: r for r in router.replicas()}
    gate = threading.Event()
    stubs["rep0"].block_until = gate
    stubs["rep1"].block_until = gate
    done = []
    ts = [threading.Thread(
        target=lambda: done.append(router.dispatch_generate(_req()))
    ) for _ in range(2)]
    for t in ts:
        t.start()
    import time as _time
    t0 = _time.monotonic()
    while _time.monotonic() - t0 < 2.0:
        if reps["rep0"].inflight == 1 and reps["rep1"].inflight == 1:
            break
        _time.sleep(0.005)
    spread = (reps["rep0"].inflight, reps["rep1"].inflight)
    gate.set()
    for t in ts:
        t.join(timeout=5)
    assert spread == (1, 1)  # one each, not two on the tie-winner
    assert reps["rep0"].inflight == reps["rep1"].inflight == 0
    assert len(done) == 2


def test_queue_wait_signal_breaks_depth_ties():
    router, stubs, _ = make_router(2)
    # equal depth, but rep0's requests WAIT far longer before seating
    stubs["rep0"].queue_depth = stubs["rep1"].queue_depth = 2
    stubs["rep0"].queue_wait_ms = 500.0
    router.poll_once()
    resp = router.dispatch_generate(_req())
    assert list(resp.tokens) == [1, 2, 200]


def test_lease_expiry_removes_silent_replica():
    router, stubs, clock = make_router(2)
    router.poll_once()
    # rep0 stops answering polls; its lease decays with no explicit
    # death signal
    stubs["rep0"].poll_ok = False
    clock.advance(11.0)  # past lease_secs=10
    router.poll_once()  # renews rep1 only
    reps = {r.address: r for r in router.replicas()}
    assert not reps["rep0"].lease_ok(clock())
    assert reps["rep1"].lease_ok(clock())
    resp = router.dispatch_generate(_req())
    assert list(resp.tokens) == [1, 2, 200]
    assert stubs["rep0"].calls == 0


def test_all_leases_expired_sheds():
    router, stubs, clock = make_router(2)
    for s in stubs.values():
        s.poll_ok = False
    clock.advance(11.0)
    router.poll_once()
    with pytest.raises(RouterError) as e:
        router.dispatch_generate(_req())
    assert e.value.code == "RESOURCE_EXHAUSTED"
    assert router.telemetry.snapshot()["shed"] == 1


def test_wedged_replica_does_not_stall_sweep_or_pile_up_polls():
    """Regression: polls ran sequentially, so each wedged (SIGSTOPped)
    replica stalled the sweep by up to poll_timeout and healthy
    replicas' leases could expire un-renewed. Polls are concurrent and
    bounded now, and a replica whose previous poll is still in flight
    is skipped rather than re-polled every sweep."""
    router, stubs, clock = make_router(2, poll_timeout_secs=0.2)
    gate = threading.Event()
    stubs["rep0"].status_block_until = gate
    try:
        clock.advance(11.0)  # both registration leases decayed
        import time as _time
        t0 = _time.monotonic()
        router.poll_once()
        elapsed = _time.monotonic() - t0
        # the sweep is bounded by poll_timeout, not by the wedged stub
        assert elapsed < 2.0
        reps = {r.address: r for r in router.replicas()}
        # rep1 renewed concurrently despite rep0 hanging; rep0 decays
        assert reps["rep1"].lease_ok(clock())
        assert not reps["rep0"].lease_ok(clock())
        resp = router.dispatch_generate(_req())
        assert list(resp.tokens) == [1, 2, 200]
        # later sweeps skip the still-stuck poll instead of stacking a
        # fresh thread onto the wedged replica every period
        router.poll_once()
        router.poll_once()
        assert stubs["rep0"].status_calls == 1
        assert stubs["rep1"].status_calls == 3
    finally:
        gate.set()


def test_redispatch_on_transient_failure_before_first_token():
    """The headline invariant: an accepted request survives its first
    replica dying — re-dispatched, the client sees a normal OK."""
    router, stubs, _ = make_router(2)
    router.poll_once()
    # make rep0 the preferred target, then kill its dispatch
    stubs["rep1"].queue_depth = 3
    router.poll_once()
    stubs["rep0"].gen_errors.append(_unavailable())
    resp = router.dispatch_generate(_req())
    assert list(resp.tokens) == [1, 2, 200]  # rep1 rescued it
    snap = router.telemetry.snapshot()
    assert snap["redispatched"] == 1 and snap["completed"] == 1


def test_backpressure_reroutes_without_breaker_damage():
    router, stubs, _ = make_router(2)
    stubs["rep1"].queue_depth = 3
    router.poll_once()
    stubs["rep0"].gen_errors.append(_exhausted())
    resp = router.dispatch_generate(_req())
    assert list(resp.tokens) == [1, 2, 200]
    # RESOURCE_EXHAUSTED is a live replica shedding — not a breaker hit
    reps = {r.address: r for r in router.replicas()}
    assert reps["rep0"].breaker.state == CircuitBreaker.CLOSED
    assert reps["rep0"].breaker.failures == 0
    assert router.telemetry.snapshot()["breaker_trips"] == 0


def test_invalid_argument_propagates_without_redispatch():
    router, stubs, _ = make_router(2)
    stubs["rep1"].queue_depth = 3
    router.poll_once()
    stubs["rep0"].gen_errors.append(_invalid())
    with pytest.raises(RouterError) as e:
        router.dispatch_generate(_req())
    assert e.value.code == "INVALID_ARGUMENT"
    assert stubs["rep1"].calls == 0  # never re-dispatched
    assert router.telemetry.snapshot()["redispatched"] == 0


def test_breaker_trips_then_half_open_probe_closes():
    router, stubs, clock = make_router(1)
    router.poll_once()
    rep = router.replicas()[0]
    # threshold=2 consecutive transient failures trip the breaker; the
    # dispatch loop itself retries until the window (8s) expires
    stubs["rep0"].gen_errors = [_unavailable() for _ in range(50)]
    with pytest.raises(RouterError):
        router.dispatch_generate(_req())
    assert rep.breaker.state == CircuitBreaker.OPEN
    assert router.telemetry.snapshot()["breaker_trips"] == 1
    # while OPEN inside the cooldown: immediate shed, no dispatch
    stubs["rep0"].gen_errors = []
    calls_before = stubs["rep0"].calls
    router.poll_once()  # poll renews the lease; breaker stays open
    with pytest.raises(RouterError) as e:
        router.dispatch_generate(_req())
    assert e.value.code == "RESOURCE_EXHAUSTED"
    assert stubs["rep0"].calls == calls_before
    # cooldown elapses -> HALF_OPEN probe goes through and CLOSES it
    clock.advance(router.config.breaker_cooldown_secs + 0.1)
    router.poll_once()
    resp = router.dispatch_generate(_req())
    assert list(resp.tokens) == [1, 2, 100]
    assert rep.breaker.state == CircuitBreaker.CLOSED


def test_half_open_probe_non_transient_failure_does_not_leak_slot():
    """Regression: a HALF_OPEN probe failing with a NON-transient error
    (INVALID_ARGUMENT) used to leave _probe_inflight set forever — the
    replica was permanently evicted from rotation and every later
    request shed despite a healthy backend."""
    router, stubs, clock = make_router(1)
    router.poll_once()
    rep = router.replicas()[0]
    stubs["rep0"].gen_errors = [_unavailable() for _ in range(50)]
    with pytest.raises(RouterError):
        router.dispatch_generate(_req())
    assert rep.breaker.state == CircuitBreaker.OPEN
    stubs["rep0"].gen_errors = []
    clock.advance(router.config.breaker_cooldown_secs + 0.1)
    router.poll_once()
    # the probe fails with an application error: it propagates to the
    # client (never re-dispatched), but the probe slot must release
    stubs["rep0"].gen_errors = [_invalid()]
    with pytest.raises(RouterError) as e:
        router.dispatch_generate(_req())
    assert e.value.code == "INVALID_ARGUMENT"
    # the replica is still probe-able: the next dispatch reaches it and
    # closes the breaker instead of shedding forever
    calls_before = stubs["rep0"].calls
    resp = router.dispatch_generate(_req())
    assert list(resp.tokens) == [1, 2, 100]
    assert stubs["rep0"].calls == calls_before + 1
    assert rep.breaker.state == CircuitBreaker.CLOSED


def test_half_open_probe_backpressure_recovers_replica():
    """A HALF_OPEN probe answered with RESOURCE_EXHAUSTED proves the
    replica ALIVE (it answered): the breaker closes and the dispatch
    loop retries into the capacity as it frees — no probe-slot leak,
    no permanent eviction."""
    router, stubs, clock = make_router(1)
    router.poll_once()
    rep = router.replicas()[0]
    stubs["rep0"].gen_errors = [_unavailable() for _ in range(50)]
    with pytest.raises(RouterError):
        router.dispatch_generate(_req())
    assert rep.breaker.state == CircuitBreaker.OPEN
    clock.advance(router.config.breaker_cooldown_secs + 0.1)
    router.poll_once()
    stubs["rep0"].gen_errors = [_exhausted()]
    resp = router.dispatch_generate(_req())
    assert list(resp.tokens) == [1, 2, 100]
    assert rep.breaker.state == CircuitBreaker.CLOSED


def test_all_breakers_open_sheds_immediately():
    router, stubs, _ = make_router(2)
    router.poll_once()
    for s in stubs.values():
        s.gen_errors = [_unavailable() for _ in range(50)]
    with pytest.raises(RouterError) as e:
        router.dispatch_generate(_req())
    # both breakers tripped during the retry loop; the terminal error
    # is either the shed (both open) or the exhausted window
    assert e.value.code in ("RESOURCE_EXHAUSTED", "UNAVAILABLE")
    for r in router.replicas():
        assert r.breaker.state == CircuitBreaker.OPEN
    for s in stubs.values():
        s.gen_errors = []
    with pytest.raises(RouterError) as e:
        router.dispatch_generate(_req())
    assert e.value.code == "RESOURCE_EXHAUSTED"
    assert str(e.value).startswith("no healthy replicas")


def test_drain_advertisement_removes_from_rotation():
    router, stubs, _ = make_router(2)
    stubs["rep0"].draining = True
    router.poll_once()
    for _ in range(3):
        resp = router.dispatch_generate(_req())
        assert list(resp.tokens) == [1, 2, 200]
    assert stubs["rep0"].calls == 0
    # drain completes (restart/reload done) -> back in rotation
    stubs["rep0"].draining = False
    stubs["rep1"].queue_depth = 5
    router.poll_once()
    resp = router.dispatch_generate(_req())
    assert list(resp.tokens) == [1, 2, 100]


def test_all_draining_sheds():
    router, stubs, _ = make_router(2)
    for s in stubs.values():
        s.draining = True
    router.poll_once()
    with pytest.raises(RouterError) as e:
        router.dispatch_generate(_req())
    assert e.value.code == "RESOURCE_EXHAUSTED"


# --------------------------------------------------------------- streams


def test_stream_redispatch_before_first_token():
    router, stubs, _ = make_router(2)
    stubs["rep1"].queue_depth = 3
    router.poll_once()
    stubs["rep0"].stream_errors.append(_unavailable())
    chunks = list(router.dispatch_stream(_req(new=3)))
    tokens = [t for c in chunks for t in c.tokens]
    assert tokens == [200, 201, 202]
    assert chunks[-1].done
    assert router.telemetry.snapshot()["redispatched"] == 1


def test_stream_failure_after_first_token_is_explicit():
    """Past the first delivered chunk a replay would duplicate tokens:
    the stream must fail LOUDLY, not re-dispatch and not hang."""
    router, stubs, _ = make_router(2)
    stubs["rep1"].queue_depth = 3
    router.poll_once()
    stubs["rep0"].stream_fail_after_chunks = 2
    got = []
    with pytest.raises(RouterError) as e:
        for chunk in router.dispatch_stream(_req(new=5)):
            got.extend(chunk.tokens)
    assert got == [100, 101]  # the delivered prefix stands
    assert e.value.code == "UNAVAILABLE"
    assert "mid-stream after 2" in str(e.value)
    assert stubs["rep1"].calls == 0  # no replay to another replica


# --------------------------------------------------------------- hedging


def test_hedged_dispatch_second_replica_wins():
    router, stubs, clock = make_router(
        2, advance_on_sleep=False, hedge_delay_secs=0.05
    )
    router.poll_once()
    stubs["rep1"].queue_depth = 3  # rep0 is primary
    router.poll_once()
    gate = threading.Event()
    stubs["rep0"].block_until = gate  # primary stalls
    try:
        resp = router.dispatch_generate(_req())
    finally:
        gate.set()  # release the stalled primary thread
    assert list(resp.tokens) == [1, 2, 200]  # the hedge answered
    snap = router.telemetry.snapshot()
    assert snap["hedges"] == 1 and snap["hedge_wins"] == 1


def test_hedge_leg_failure_excluded_from_redispatch():
    """A hedge replica that failed THIS request lands in the request's
    failed set too: when both legs fail, the re-dispatch goes to a
    THIRD replica instead of burning an attempt on the hedge replica
    already known bad."""
    router, stubs, _ = make_router(
        3, advance_on_sleep=False, hedge_delay_secs=0.05
    )
    stubs["rep2"].queue_depth = 5  # least preferred
    router.poll_once()
    gate = threading.Event()
    stubs["rep0"].block_until = gate  # primary stalls, then fails
    stubs["rep0"].gen_errors.append(_unavailable())
    stubs["rep1"].gen_errors.append(_unavailable())  # hedge leg fails
    releaser = threading.Timer(0.3, gate.set)
    releaser.start()
    try:
        resp = router.dispatch_generate(_req())
    finally:
        gate.set()
        releaser.cancel()
    assert list(resp.tokens) == [1, 2, 300]  # rep2 rescued it
    assert stubs["rep1"].calls == 1  # the failed hedge is not re-picked
    assert router.telemetry.snapshot()["hedges"] == 1


def test_hedged_dispatch_primary_wins_without_hedge():
    router, stubs, _ = make_router(
        2, advance_on_sleep=False, hedge_delay_secs=5.0
    )
    router.poll_once()
    resp = router.dispatch_generate(_req())
    assert list(resp.tokens)[-1] in (100, 200)
    snap = router.telemetry.snapshot()
    assert snap["hedges"] == 0 and snap["hedge_wins"] == 0


# ------------------------------------------------------ servicer / proto


def test_router_servicer_and_status_response():
    router, stubs, _ = make_router(2)
    stubs["rep0"].draining = True
    router.poll_once()
    servicer = RouterServicer(router)
    resp = servicer.router_generate(_req())
    assert list(resp.tokens) == [1, 2, 200]
    chunks = list(servicer.router_generate_stream(_req(new=2)))
    assert chunks[-1].done
    st = servicer.router_status(pb.RouterStatusRequest())
    assert st.replicas == 2 and st.healthy == 1
    assert st.routed == 2 and st.completed == 2
    by_addr = {r.address: r for r in st.replica}
    assert by_addr["rep0"].draining and not by_addr["rep0"].healthy
    assert by_addr["rep1"].healthy
    assert by_addr["rep1"].breaker == "closed"
    # round-trips through the wire format
    st2 = pb.RouterStatusResponse.FromString(st.SerializeToString())
    assert st2.replica[0].address in ("rep0", "rep1")


def test_router_servicer_maps_shed_to_admission_error():
    router, stubs, clock = make_router(1)
    stubs["rep0"].poll_ok = False
    clock.advance(11.0)
    router.poll_once()
    with pytest.raises(RouterError) as e:
        RouterServicer(router).router_generate(_req())
    assert e.value.code == "RESOURCE_EXHAUSTED"


# -------------------------------------------------------- retire / close


def test_remove_replica_closes_channel_once():
    router, stubs, _ = make_router(2)
    router.poll_once()
    rep = router.remove_replica("rep0")
    assert rep is not None and rep.retired
    assert stubs["rep0"].closed == 1
    assert [r.address for r in router.replicas()] == ["rep1"]
    # idempotent: removing again neither errors nor double-closes
    assert router.remove_replica("rep0") is None
    assert stubs["rep0"].closed == 1
    # traffic keeps flowing to the survivor
    resp = router.dispatch_generate(_req())
    assert list(resp.tokens) == [1, 2, 200]


def test_remove_replica_defers_close_past_inflight_poll():
    """Regression: remove_replica used to just pop the registry entry,
    leaving the gRPC channel open forever — and closing it EAGERLY
    would tear the socket out from under a concurrent heartbeat poll.
    The close must wait for the in-flight poll to settle."""
    router, stubs, _ = make_router(2)
    gate = threading.Event()
    stubs["rep0"].status_block_until = gate
    try:
        t = threading.Thread(target=router.poll_once)
        t.start()
        # wait until rep0's poll is provably in flight
        import time as _time
        t0 = _time.monotonic()
        while _time.monotonic() - t0 < 2.0:
            if stubs["rep0"].status_calls == 1:
                break
            _time.sleep(0.005)
        assert stubs["rep0"].status_calls == 1
        rep = router.remove_replica("rep0")
        assert rep.retired
        assert stubs["rep0"].closed == 0  # poll still holds the channel
    finally:
        gate.set()
    t.join(timeout=5)
    t0 = _time.monotonic()
    while _time.monotonic() - t0 < 2.0 and not stubs["rep0"].closed:
        _time.sleep(0.005)
    assert stubs["rep0"].closed == 1  # settled poll released the close


def test_remove_replica_defers_close_past_inflight_dispatch():
    """Same deferral for a dispatch already running on the replica:
    the in-flight counters settle (begin/end balanced to zero) and
    only THEN does the channel close."""
    router, stubs, _ = make_router(1)
    router.poll_once()
    rep = router.replicas()[0]
    gate = threading.Event()
    stubs["rep0"].block_until = gate
    done = []
    t = threading.Thread(
        target=lambda: done.append(router.dispatch_generate(_req()))
    )
    t.start()
    import time as _time
    t0 = _time.monotonic()
    while _time.monotonic() - t0 < 2.0 and rep.inflight != 1:
        _time.sleep(0.005)
    assert rep.inflight == 1
    removed = router.remove_replica("rep0")
    assert removed is rep
    assert stubs["rep0"].closed == 0  # dispatch still on the wire
    gate.set()
    t.join(timeout=5)
    assert len(done) == 1  # the in-flight request still completed
    assert rep.inflight == 0  # counters settled, not abandoned
    assert stubs["rep0"].closed == 1


# ------------------------------------------------------- fault injection


class _EchoReplicaServicer(object):
    def generate(self, request, _context=None):
        return pb.GenerateResponse(tokens=list(request.prompt))

    def generate_stream(self, request, _context=None):
        return iter([pb.TokenChunk(tokens=list(request.prompt))])

    def server_status(self, request, _context=None):
        return pb.ServerStatusResponse(num_slots=1)


def test_fault_spec_targets_router_without_touching_replicas():
    """A spec naming only router_* RPCs must fire at the router
    boundary and leave replica servicers completely untouched — the
    names are disjoint by design."""
    spec = "router_generate:drop:1;router_status:error:1"
    # replica servicer wrapped with the SAME tuple: no router_* attrs,
    # so nothing intercepts and every replica RPC flows untouched
    replica_inj = FaultInjector(spec=spec)
    replica = maybe_wrap_servicer(
        _EchoReplicaServicer(), replica_inj, rpcs=SERVING_RPCS
    )
    for _ in range(3):
        assert list(replica.generate(_req(prompt=[7])).tokens) == [7]
    assert replica.server_status(pb.ServerStatusRequest()).num_slots == 1
    assert replica_inj.injected == {}
    # the router servicer DOES get intercepted under the same spec
    router, _stubs, _ = make_router(2)
    router.poll_once()
    router_inj = FaultInjector(spec=spec)
    wrapped = maybe_wrap_servicer(
        RouterServicer(router), router_inj, rpcs=SERVING_RPCS
    )
    with pytest.raises(InjectedRpcError):
        wrapped.router_generate(_req())
    assert list(wrapped.router_generate(_req()).tokens)[-1] in (100, 200)
    with pytest.raises(InjectedRpcError):
        wrapped.router_status(pb.RouterStatusRequest())
    assert router_inj.injected == {
        "router_generate": 1, "router_status": 1
    }


def test_router_start_stop_in_process():
    router, _stubs, _ = make_router(2)
    router.start(grpc_server=False)
    try:
        assert router.servicer is not None
        resp = router.servicer.router_generate(_req())
        assert len(resp.tokens) == 3
    finally:
        router.stop()

# ----------------------------------------- field-table completeness pin


def test_status_field_tables_cover_the_protos_exactly():
    """The declared signal tables ARE the contract: a field added to
    pb.ReplicaStatus must land in STATUS_FORWARD or STATUS_COMPUTED,
    and every observed heartbeat name must exist on
    pb.ServerStatusResponse — otherwise new telemetry silently goes
    dark between servicer and router_status."""
    from elasticdl_tpu.serving.router import Replica

    replica_fields = {f.name for f in pb.ReplicaStatus.DESCRIPTOR.fields}
    forward = set(Replica.STATUS_FORWARD)
    computed = set(Replica.STATUS_COMPUTED)
    assert not forward & computed  # one owner per field
    assert forward | computed == replica_fields

    status_fields = {
        f.name for f in pb.ServerStatusResponse.DESCRIPTOR.fields
    }
    observed = set(Replica.OBSERVED_SCALARS) | set(Replica.OBSERVED_LISTS)
    assert not set(Replica.OBSERVED_SCALARS) & set(Replica.OBSERVED_LISTS)
    assert observed <= status_fields

    # every observed/forwarded name resolves on a live entry, so the
    # table-driven observe()/status_response() loops cannot AttributeError
    rep = Replica("addr", object(), CircuitBreaker(2, 1.0), 0.0)
    for name in observed | forward - {"address"}:
        assert hasattr(rep, name), name


# ------------------------------------------------- prefix-affine dispatch


_PREFIX = tuple([7] * 16)  # one full affinity block (block_tokens=16)


def _warm(stub):
    stub.kv_blocks_cached = 4
    stub.kv_blocks_shared = 2


def test_affinity_sticks_within_load_margin():
    """A learned prefix keeps landing on its replica while the load
    penalty stays inside affinity_load_margin, even when another
    replica is strictly less loaded."""
    router, stubs, _ = make_router(2)
    _warm(stubs["rep0"])
    router.poll_once()
    resp = router.dispatch_generate(_req(prompt=_PREFIX + (1, 2)))
    assert list(resp.tokens)[-1] == 100  # rep0: first by address tie
    # rep0 is now the BUSIER replica, but within the margin (2.0)
    stubs["rep0"].queue_depth = 1
    router.poll_once()
    resp = router.dispatch_generate(_req(prompt=_PREFIX + (3, 4)))
    assert list(resp.tokens)[-1] == 100  # affinity held
    snap = router.telemetry.snapshot()
    assert snap["affinity_hits"] == 1


def test_affinity_decays_to_least_loaded_past_margin():
    router, stubs, _ = make_router(2)
    _warm(stubs["rep0"])
    router.poll_once()
    router.dispatch_generate(_req(prompt=_PREFIX + (1, 2)))  # learn rep0
    stubs["rep0"].queue_depth = 5  # margin (2.0) blown
    router.poll_once()
    resp = router.dispatch_generate(_req(prompt=_PREFIX + (3, 4)))
    assert list(resp.tokens)[-1] == 200
    assert router.telemetry.snapshot()["affinity_misses"] >= 1


def test_affinity_decays_when_target_reports_no_warm_capacity():
    """The chain evicted fleet-side: all warm signals zero means the
    match would prefill cold anyway — route by load instead."""
    router, stubs, _ = make_router(2)
    _warm(stubs["rep0"])
    router.poll_once()
    router.dispatch_generate(_req(prompt=_PREFIX + (1, 2)))  # learn rep0
    stubs["rep0"].kv_blocks_cached = 0
    stubs["rep0"].kv_blocks_shared = 0
    stubs["rep0"].queue_depth = 1  # rep1 is otherwise least-loaded
    router.poll_once()
    resp = router.dispatch_generate(_req(prompt=_PREFIX + (3, 4)))
    assert list(resp.tokens)[-1] == 200


def test_affinity_never_dispatches_to_draining_replica():
    """ISSUE regression: however perfect the prefix match, a draining
    replica is out of rotation — the candidate filter IS the guard."""
    router, stubs, _ = make_router(2)
    _warm(stubs["rep0"])
    router.poll_once()
    router.dispatch_generate(_req(prompt=_PREFIX + (1, 2)))  # learn rep0
    stubs["rep0"].draining = True
    router.poll_once()
    resp = router.dispatch_generate(_req(prompt=_PREFIX + (3, 4)))
    assert list(resp.tokens)[-1] == 200
    assert stubs["rep0"].calls == 1  # only the learning dispatch


def test_affinity_never_dispatches_to_stalled_replica():
    router, stubs, _ = make_router(2)
    _warm(stubs["rep0"])
    router.poll_once()
    router.dispatch_generate(_req(prompt=_PREFIX + (1, 2)))  # learn rep0
    stubs["rep0"].health_state = "stalled"
    router.poll_once()
    resp = router.dispatch_generate(_req(prompt=_PREFIX + (3, 4)))
    assert list(resp.tokens)[-1] == 200
    assert stubs["rep0"].calls == 1


def test_affinity_skips_open_breaker_and_reroutes():
    router, stubs, _ = make_router(2)
    _warm(stubs["rep0"])
    router.poll_once()
    router.dispatch_generate(_req(prompt=_PREFIX + (1, 2)))  # learn rep0
    # two consecutive transport failures trip rep0's breaker (threshold
    # 2); the affine rung must not probe an OPEN breaker
    stubs["rep0"].gen_errors = [_unavailable(), _unavailable()]
    router.dispatch_generate(_req(prompt=_PREFIX + (3, 4)))
    router.dispatch_generate(_req(prompt=_PREFIX + (5, 6)))
    calls_before = stubs["rep0"].calls
    resp = router.dispatch_generate(_req(prompt=_PREFIX + (9, 9)))
    assert list(resp.tokens)[-1] == 200
    assert stubs["rep0"].calls == calls_before


def test_short_prompt_never_learns_affinity():
    """Below one full block there is nothing shareable: no fingerprint,
    no index entry, pure least-loaded routing."""
    router, stubs, _ = make_router(2)
    _warm(stubs["rep0"])
    router.poll_once()
    router.dispatch_generate(_req(prompt=(1, 2)))
    assert len(router._affinity) == 0


def test_stream_success_teaches_affinity():
    router, stubs, _ = make_router(2)
    _warm(stubs["rep0"])
    router.poll_once()
    chunks = list(router.dispatch_stream(_req(prompt=_PREFIX + (1, 2))))
    assert chunks[-1].done
    stubs["rep0"].queue_depth = 1  # within margin
    router.poll_once()
    resp = router.dispatch_generate(_req(prompt=_PREFIX + (3, 4)))
    assert list(resp.tokens)[-1] == 100  # the stream taught the chain


def test_remove_replica_forgets_learned_affinity():
    router, stubs, _ = make_router(2)
    _warm(stubs["rep0"])
    router.poll_once()
    router.dispatch_generate(_req(prompt=_PREFIX + (1, 2)))  # learn rep0
    router.remove_replica("rep0")
    assert len(router._affinity) == 0  # forgotten WITH the membership
    router.poll_once()
    resp = router.dispatch_generate(_req(prompt=_PREFIX + (3, 4)))
    assert list(resp.tokens)[-1] == 200  # ...and relearned on rep1


def test_affinity_off_routes_pure_least_loaded():
    router, stubs, _ = make_router(2, affinity=False)
    _warm(stubs["rep0"])
    stubs["rep1"].queue_depth = 1
    router.poll_once()
    router.dispatch_generate(_req(prompt=_PREFIX + (1, 2)))  # rep0
    stubs["rep0"].queue_depth = 2
    stubs["rep1"].queue_depth = 0
    router.poll_once()
    resp = router.dispatch_generate(_req(prompt=_PREFIX + (3, 4)))
    assert list(resp.tokens)[-1] == 200  # no stickiness whatsoever
