import numpy as np

from elasticdl_tpu.common import hash_utils
from elasticdl_tpu.common.model_utils import get_dict_from_params_str
from elasticdl_tpu.common.tensor_utils import (
    deduplicate_indexed_slices,
    deserialize_ndarray,
    deserialize_ndarray_dict,
    merge_indexed_slices,
    serialize_ndarray,
    serialize_ndarray_dict,
)


def test_string_to_id_stable_and_bounded():
    ids = [hash_utils.string_to_id("dense/kernel:0", 4) for _ in range(3)]
    assert len(set(ids)) == 1
    assert 0 <= ids[0] < 4
    assert hash_utils.string_to_id("a", 1) == 0


def test_int_to_id():
    assert hash_utils.int_to_id(10, 3) == 1
    assert hash_utils.int_to_id(2, 3) == 2


def test_scatter_ids():
    ids = np.array([0, 3, 4, 7, 9, 1])
    bucket_ids, bucket_pos = hash_utils.scatter_ids(ids, 3)
    assert [list(b) for b in bucket_ids] == [[0, 3, 9], [4, 7, 1], []]
    # positions map back
    for b in range(3):
        np.testing.assert_array_equal(ids[bucket_pos[b]], bucket_ids[b])


def test_tensor_roundtrip():
    arr = np.random.rand(3, 4, 5).astype(np.float32)
    name, out, off = deserialize_ndarray(serialize_ndarray(arr, "w"))
    assert name == "w"
    np.testing.assert_array_equal(out, arr)


def test_tensor_dict_roundtrip():
    d = {
        "a": np.arange(6, dtype=np.int64).reshape(2, 3),
        "b": np.array(1.5, dtype=np.float64),
    }
    out = deserialize_ndarray_dict(serialize_ndarray_dict(d))
    assert set(out) == {"a", "b"}
    np.testing.assert_array_equal(out["a"], d["a"])
    np.testing.assert_array_equal(out["b"], d["b"])


def test_bfloat16_roundtrip():
    import ml_dtypes

    arr = np.arange(8, dtype=np.float32).astype(ml_dtypes.bfloat16)
    _, out, _ = deserialize_ndarray(serialize_ndarray(arr))
    assert out.dtype == arr.dtype
    np.testing.assert_array_equal(out, arr)


def test_deduplicate_indexed_slices():
    values = np.array([[1.0, 2.0], [3.0, 4.0], [10.0, 20.0]])
    indices = np.array([5, 2, 5])
    summed, ids = deduplicate_indexed_slices(values, indices)
    np.testing.assert_array_equal(ids, [2, 5])
    np.testing.assert_allclose(summed, [[3.0, 4.0], [11.0, 22.0]])


def test_merge_indexed_slices():
    v, i = merge_indexed_slices(
        (np.ones((2, 3)), np.array([0, 1])),
        (np.full((1, 3), 2.0), np.array([1])),
    )
    assert v.shape == (3, 3)
    np.testing.assert_array_equal(i, [0, 1, 1])


def test_params_str_parsing():
    d = get_dict_from_params_str("lr=0.1; hidden=[10, 20]; name='x'; flag=True")
    assert d == {"lr": 0.1, "hidden": [10, 20], "name": "x", "flag": True}
    assert get_dict_from_params_str("") == {}
    assert get_dict_from_params_str(None) == {}
