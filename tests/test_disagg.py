"""Disaggregated prefill/decode serving unit tests (tier-1).

The handoff plumbing from serving/disagg.py without a fleet: the wire
codec (chain_to_proto / proto_to_blocks round-trips a real pool export
byte-exactly and refuses mismatched arena layouts), the
HandoffCoordinator's three obligations against fake stubs (export
warms then exports, empty exports and refused imports raise
HandoffError, abort swallows transport errors), and the chunked
prefill scheduler on a real CPU engine: a long prompt advances tile by
tile across calls, stays token-exact against the offline decoder, a
full-prompt prefix match collapses to zero tiles, and an aborted job
returns every block. Fleet-level behavior (router pairing, two-pool
ledgers, the 32-way handoff battery) lives on the drills shard."""

import numpy as np
import pytest

from elasticdl_tpu.proto import elasticdl_pb2 as pb
from elasticdl_tpu.serving.disagg import (
    HandoffCoordinator,
    HandoffError,
    chain_to_proto,
    proto_to_blocks,
)

# --------------------------------------------------------------- codec


def _int8_pool(num_blocks=4, block_size=4, leaves=("k", "k_scale")):
    import jax.numpy as jnp

    from elasticdl_tpu.serving.kv_pool import PagedKVPool

    hkv, d, cache_len = 2, 8, 16
    shapes = {
        "k": jnp.zeros((1, hkv, cache_len, d), jnp.int8),
        "k_scale": jnp.zeros((1, hkv, cache_len, 1), jnp.float32),
        "pos": jnp.zeros((), jnp.int32),
    }
    shapes = {k: v for k, v in shapes.items()
              if k == "pos" or k in leaves}
    return PagedKVPool(shapes, cache_len, num_slots=2,
                       num_blocks=num_blocks, block_size=block_size,
                       share_prefix=True)


def _exported_chain(pool, prompt):
    import jax.numpy as jnp

    rs = np.random.RandomState(17)
    pool.seat(0, prompt, len(prompt))
    arenas = {}
    for name, leaf in pool.pools.items():
        if getattr(leaf, "ndim", 0) == 4:
            arenas[name] = jnp.asarray(
                rs.randint(-127, 128, size=leaf.shape)
                .astype(np.asarray(leaf).dtype)
            )
    pool.pools = dict(pool.pools, **arenas)
    pool.register_prefix(0, prompt)
    pool.release(0)
    return pool.export_chain(prompt)


def test_codec_round_trips_pool_export_byte_exactly():
    """chain_to_proto -> proto_to_blocks over a real int8+scale export
    must reproduce every row leaf byte-for-byte in import_chain's
    argument shape, and the decoded payload must import cleanly into a
    same-geometry pool."""
    src = _int8_pool()
    prompt = list(range(100, 116))
    chain = _exported_chain(src, prompt)
    assert len(chain) == 4

    msg = chain_to_proto(chain, src.block_size, src.leaf_dtypes(),
                         "xfer-t")
    assert msg.transfer_id == "xfer-t"
    assert msg.block_size == 4
    assert list(msg.leaf_dtypes) == ["int8", "float32"]
    assert len(msg.blocks) == 4

    dst = _int8_pool()
    blocks, dtypes = proto_to_blocks(msg, dst)
    assert dtypes == ["int8", "float32"]
    for (toks, rows), (otoks, orows) in zip(blocks, chain):
        assert tuple(toks) == tuple(otoks)
        for r, o in zip(rows, orows):
            assert r.dtype == o.dtype
            np.testing.assert_array_equal(r, o)
    assert dst.import_chain(blocks, leaf_dtypes=dtypes) == (4, 16)
    assert dst.seat(0, prompt, 16) == 16
    dst.release(0)


def test_codec_refuses_mismatched_arena_layouts():
    """Every geometry mismatch must surface as ValueError BEFORE any
    import: block_size, leaf count (payload vs pool), and a malformed
    block's leaf list."""
    src = _int8_pool()
    chain = _exported_chain(src, list(range(100, 116)))
    msg = chain_to_proto(chain, src.block_size, src.leaf_dtypes(),
                         "xfer-m")

    with pytest.raises(ValueError, match="block_size"):
        proto_to_blocks(msg, _int8_pool(block_size=8, num_blocks=2))
    with pytest.raises(ValueError, match="leaves"):
        proto_to_blocks(msg, _int8_pool(leaves=("k",)))
    bad = pb.TransferChainRequest()
    bad.CopyFrom(msg)
    del bad.blocks[0].leaves[-1]
    with pytest.raises(ValueError, match="leaves"):
        proto_to_blocks(bad, _int8_pool())


# --------------------------------------------------- coordinator units


class _FakeStub(object):
    """ServingStub surface the coordinator drives, scripted."""

    def __init__(self, payload=None, resp=None, abort_exc=None):
        self.payload = payload
        self.resp = resp
        self.abort_exc = abort_exc
        self.calls = []

    def generate(self, request, timeout=None):
        self.calls.append(("generate", request))
        return pb.GenerateResponse(tokens=list(request.prompt) + [0])

    def export_chain(self, request, timeout=None):
        self.calls.append(("export_chain", request))
        return self.payload

    def transfer_chain(self, payload, timeout=None):
        self.calls.append(("transfer_chain", payload))
        return self.resp

    def abort_transfer(self, request, timeout=None):
        self.calls.append(("abort_transfer", request))
        if self.abort_exc is not None:
            raise self.abort_exc
        return pb.TransferChainResponse(ok=True)


class _FakeRep(object):
    def __init__(self, stub):
        self.address = "fake:0"
        self.stub = stub


class _Req(object):
    def __init__(self, prompt):
        self.prompt = prompt
        self.temperature = 0.0
        self.seed = 7


def _payload(nblocks):
    return pb.TransferChainRequest(
        transfer_id="xfer-f", block_size=4, leaf_dtypes=["int8"],
        blocks=[pb.KvChainBlock(tokens=[1, 2, 3, 4], leaves=[b"x"])
                for _ in range(nblocks)],
    )


def test_coordinator_export_warms_then_exports():
    """export_chain runs ONE prefill_only generate (the warm) before
    the export RPC, forwards the request's sampling knobs, and returns
    the payload."""
    stub = _FakeStub(payload=_payload(2))
    co = HandoffCoordinator()
    payload = co.export_chain(_FakeRep(stub), _Req([1, 2, 3, 4, 5]),
                              "xfer-f")
    assert len(payload.blocks) == 2
    assert [c[0] for c in stub.calls] == ["generate", "export_chain"]
    gen = stub.calls[0][1]
    assert gen.prefill_only and gen.max_new_tokens == 1
    assert list(gen.prompt) == [1, 2, 3, 4, 5] and gen.seed == 7
    assert stub.calls[1][1].transfer_id == "xfer-f"


def test_coordinator_raises_on_empty_export():
    stub = _FakeStub(payload=_payload(0))
    with pytest.raises(HandoffError, match="empty chain"):
        HandoffCoordinator().export_chain(
            _FakeRep(stub), _Req([1, 2]), "xfer-f"
        )


def test_coordinator_import_raises_on_refusal_or_no_coverage():
    """ok=False (arena mismatch) and blocks=0 (nothing of the chain
    landed) both raise; resolved coverage > 0 succeeds even when the
    import was fully deduped on the far side."""
    co = HandoffCoordinator()
    refused = pb.TransferChainResponse(ok=False, error="dtype")
    with pytest.raises(HandoffError, match="dtype"):
        co.import_chain(_FakeRep(_FakeStub(resp=refused)),
                        _payload(1))
    empty = pb.TransferChainResponse(ok=True, blocks=0)
    with pytest.raises(HandoffError, match="no blocks"):
        co.import_chain(_FakeRep(_FakeStub(resp=empty)), _payload(1))
    warm = pb.TransferChainResponse(ok=True, blocks=3, tokens=12)
    resp = co.import_chain(_FakeRep(_FakeStub(resp=warm)),
                           _payload(1))
    assert resp.blocks == 3


def test_coordinator_abort_is_best_effort_accounting():
    """abort_transfer swallows transport errors — exports hold no pool
    references, so a lost abort leaks nothing."""
    stub = _FakeStub(abort_exc=RuntimeError("replica gone"))
    HandoffCoordinator().abort_transfer(_FakeRep(stub), "xfer-f")
    assert [c[0] for c in stub.calls] == ["abort_transfer"]


def test_transfer_ids_are_unique_across_coordinators():
    a, b = HandoffCoordinator(), HandoffCoordinator()
    ids = [a.new_transfer_id() for _ in range(3)]
    ids += [b.new_transfer_id() for _ in range(3)]
    assert len(set(ids)) == 6


# ------------------------------------------------------ chunked prefill


@pytest.fixture(scope="module")
def rig():
    import jax

    from elasticdl_tpu.common.model_utils import (
        load_model_spec_from_module,
    )
    from elasticdl_tpu.parallel import mesh as mesh_lib
    from elasticdl_tpu.training.trainer import Trainer
    from model_zoo.transformer_lm import transformer_lm as zoo

    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = Trainer(
        load_model_spec_from_module(zoo), mesh=mesh,
        model_params=("vocab_size=8; seq_len=16; embed_dim=32; "
                      "num_heads=2; num_layers=1"),
    )
    toks = (np.arange(17)[None, :] % 8).astype(np.int32)
    state = trainer.init_state(({"tokens": toks[:, :-1]},
                                toks[:, 1:]))
    return trainer, state


def _chunked_engine(rig, chunk=2, num_blocks=12):
    from elasticdl_tpu.serving.engine import (
        PagedContinuousBatchingEngine,
    )

    trainer, state = rig
    return PagedContinuousBatchingEngine(
        trainer, state, num_slots=2, block_size=4,
        num_blocks=num_blocks, prefill_chunk_tokens=chunk,
    )


def _run_chunked(eng, request):
    job = eng.begin_insert(request)
    tiles = 0
    while not job.done():
        tiles += 1
        eng.advance_prefill(job)
    while not job.finished and request in eng.active_requests():
        eng.step()
    return job, tiles


def test_chunked_prefill_is_token_exact_and_tiled(rig):
    """A 7-token prompt under a 2-token chunk budget must take ceil
    tiles (no tile runs the whole prompt) and still produce the exact
    offline token stream — tile boundaries may not perturb sampling."""
    from elasticdl_tpu.api.generation import autoregressive_generate
    from elasticdl_tpu.serving.admission import ServingRequest

    trainer, state = rig
    eng = _chunked_engine(rig, chunk=2)
    prompt = [1, 2, 3, 4, 5, 6, 7]
    req = ServingRequest(prompt, 5)
    job, tiles = _run_chunked(eng, req)
    assert tiles == 4 and job.tiles == 4  # ceil(7 / 2)
    off = np.asarray(autoregressive_generate(
        trainer, state, np.asarray([prompt], np.int32), 5,
        use_cache=True,
    ))[0]
    assert req.generated == list(off[len(prompt):])
    # the chain the first request registered answers the full-block
    # prefix (4 of 7 tokens): the repeat prompt tiles only its tail
    req2 = ServingRequest(prompt, 3)
    job2, tiles2 = _run_chunked(eng, req2)
    assert tiles2 == 2  # ceil((7 - 4) / 2)
    assert req2.generated == list(off[len(prompt):len(prompt) + 3])
    # a block-ALIGNED repeat prompt collapses to ZERO tiles: the
    # full-prompt match IS the prefill
    aligned = [1, 2, 3, 4, 5, 6, 7, 0]
    reqa = ServingRequest(aligned, 3)
    ja, ta = _run_chunked(eng, reqa)
    assert ta == 2  # shares [1,2,3,4]; ceil((8 - 4) / 2) for the tail
    reqb = ServingRequest(aligned, 3)
    jb = eng.begin_insert(reqb)
    assert jb.done() and jb.tiles == 0
    while reqb in eng.active_requests():
        eng.step()
    assert reqb.generated == reqa.generated


def test_chunked_prefill_abort_returns_every_block(rig):
    """abort_prefill between tiles must release the seat: the ledger
    returns to whole (shared ancestors excepted) and the slot frees."""
    from elasticdl_tpu.serving.admission import ServingRequest

    eng = _chunked_engine(rig, chunk=2)
    a = eng.kv.allocator
    whole = a.num_free() + a.num_cached()
    req = ServingRequest([7, 6, 5, 4, 3, 2, 1], 5)
    job = eng.begin_insert(req)
    assert not job.done()
    eng.advance_prefill(job)  # one tile in flight
    assert eng.prefilling_count() == 1
    assert a.blocks_in_use() > 0
    eng.abort_prefill(job)
    assert eng.prefilling_count() == 0
    assert a.blocks_in_use() == 0
    assert a.num_free() + a.num_cached() == whole
    assert eng.free_slots() == [0, 1]
