"""Subprocess entry for multi-process SPMD tests (launched by
test_spmd_multiprocess.py). Each process = one 'host' of the mesh, with its
own gRPC connection to the master — the CPU-rig equivalent of a TPU pod
slice host."""

import os
import sys

proc_id = int(sys.argv[1])
num_procs = int(sys.argv[2])
master_port = sys.argv[3]
coord_port = sys.argv[4]
data_dir = sys.argv[5]
local_devices = int(sys.argv[6])
# Optional (elastic re-formation drill, test_elastic_reformation.py):
# die_after_steps: os._exit(137) after N train steps (preemption SIGKILL
# exit code, the one the reference's instance manager special-cases —
# k8s_instance_manager.py:310-338); ckpt_dir/ckpt_steps: cooperative
# sharded checkpointing.
die_after_steps = int(sys.argv[7]) if len(sys.argv) > 7 else -1
ckpt_dir = sys.argv[8] if len(sys.argv) > 8 else ""
ckpt_steps = int(sys.argv[9]) if len(sys.argv) > 9 else 0

os.environ["XLA_FLAGS"] = (
    "--xla_force_host_platform_device_count=%d" % local_devices
)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from elasticdl_tpu.parallel.spmd import initialize_distributed

initialize_distributed(
    coordinator_addr="localhost:%s" % coord_port,
    num_processes=num_procs,
    process_id=proc_id,
    platform="cpu",
)

from elasticdl_tpu.common.model_utils import load_model_spec_from_module
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.worker.worker import JobType, Worker
from model_zoo.mnist_functional_api import mnist_functional_api as zoo

mesh = mesh_lib.build_mesh({"dp": num_procs * local_devices})
saver = None
if ckpt_dir and ckpt_steps:
    from elasticdl_tpu.checkpoint import CheckpointSaver

    saver = CheckpointSaver(ckpt_dir, checkpoint_steps=ckpt_steps)
worker = Worker(
    proc_id,
    load_model_spec_from_module(zoo),
    master_addr="localhost:%s" % master_port,
    job_type=JobType.TRAINING_WITH_EVALUATION,
    minibatch_size=8,
    training_data=data_dir,
    wait_sleep_secs=0.1,
    mesh=mesh,
    spmd=True,
    checkpoint_saver=saver,
)

if die_after_steps > 0:
    # Preemption injection: vanish without goodbye (no task reporting, no
    # cleanup) after the Nth completed global step — the surviving hosts
    # and the master must recover on their own.
    real_step = worker.trainer.train_step_assembled
    counter = {"n": 0}

    def _counting_step(*args, **kwargs):
        out = real_step(*args, **kwargs)
        counter["n"] += 1
        if counter["n"] >= die_after_steps:
            sys.stdout.flush()
            os._exit(137)
        return out

    worker.trainer.train_step_assembled = _counting_step

state = worker.run()
print(
    "SPMD_PROC_DONE pid=%d steps=%d real_batches=%d"
    % (proc_id, int(state.step) if state else -1, len(worker.losses)),
    flush=True,
)
