"""Elastic mesh re-formation drill (VERDICT.md round-1 item #6, SURVEY
hard part #1): a 2-host SPMD job loses a host mid-training (preemption
SIGKILL, exit 137), the sharded checkpoint carries continuity, and the
job finishes on a RE-FORMED, SMALLER mesh — re-jit, re-shard restore —
with the task queue as the unit of continuity (the reference's key
insight: tasks, not ranks, are the unit of work; its equivalent drill is
report_cn.md:108-120 convergence-invariance under 4<->8 workers +
test_restart_ps fault injection)."""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import jax

from elasticdl_tpu.common.model_utils import load_model_spec_from_module
from elasticdl_tpu.data import recordio_gen
from elasticdl_tpu.master.master import Master
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.worker.worker import JobType, Worker

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spec():
    from model_zoo.mnist_functional_api import mnist_functional_api as zoo

    return load_model_spec_from_module(zoo)


@pytest.mark.slow
def test_mesh_reformation_after_host_loss(tmp_path):
    data_dir = str(tmp_path / "train")
    ckpt_dir = str(tmp_path / "ckpt")
    # 192 records, global batch 16 -> 12 full lockstep rounds if nothing
    # fails; checkpoint every 4 steps; host 1 is preempted after step 6,
    # so version-4 is the continuity point.
    recordio_gen.gen_mnist_like(data_dir, num_files=2, records_per_file=96)

    master = Master(
        _spec(),
        training_data=data_dir,
        minibatch_size=8,
        records_per_task=32,
        num_epochs=1,
        port=0,
    )
    master.prepare()
    coord_port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"

    procs = []
    try:
        # ---- phase 1: 2 hosts x 4 devices; host 1 dies after 6 steps
        for pid, die_after in ((0, -1), (1, 6)):
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable,
                        os.path.join(REPO, "tests", "spmd_proc_main.py"),
                        str(pid), "2", str(master.port), str(coord_port),
                        data_dir, "4", str(die_after), ckpt_dir, "4",
                    ],
                    env=env,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    text=True,
                )
            )
        out1, _ = procs[1].communicate(timeout=300)
        assert procs[1].returncode == 137, (
            "host 1 should die preempted (137):\n%s" % out1[-3000:]
        )
        # The survivor's next collective can only fail or stall without
        # its peer; its failure handler reports in-flight tasks back to
        # the master. Give it a moment, then treat the whole phase-1 job
        # as dead (what the instance manager concludes from pod events).
        try:
            procs[0].communicate(timeout=60)
        except subprocess.TimeoutExpired:
            procs[0].kill()
            procs[0].communicate()

        # Master-side recovery — exactly what InstanceManager._event_cb
        # runs on a pod Failed/DELETED event: requeue the lost workers'
        # in-flight tasks.
        for wid in ("0", "1", 0, 1):
            master.task_d.recover_tasks(wid)
        assert not master.task_d.finished(), (
            "tasks must remain after losing the job mid-training"
        )

        # ---- phase 2: re-formed SMALLER mesh (1 host x 4 devices),
        # restore from the sharded checkpoint (re-shard), finish the job.
        assert os.path.isdir(os.path.join(ckpt_dir, "version-4")), (
            "phase 1 must have checkpointed version-4 before the loss"
        )
        mesh = mesh_lib.build_mesh({"dp": 4}, devices=jax.devices()[:4])
        worker = Worker(
            2,
            _spec(),
            master_addr="localhost:%d" % master.port,
            job_type=JobType.TRAINING_ONLY,
            minibatch_size=8,
            training_data=data_dir,
            wait_sleep_secs=0.1,
            mesh=mesh,
            spmd=True,
            checkpoint_dir_for_init=ckpt_dir,
        )
        state = worker.run()

        # continuity: restored from version-4, then kept stepping
        assert state is not None
        assert int(state.step) > 4
        assert np.isfinite(worker.losses).all()
        # completion: every task accounted for on the re-formed mesh
        assert master.task_d.finished()
        # the checkpoint restore really fed phase 2 (not a fresh init):
        # the final step count must equal restored version 4 + exactly
        # the batches phase 2 ran — a fresh init would start at 0 and
        # give step == len(losses).
        assert len(worker.losses) >= 1
        assert int(state.step) == 4 + len(worker.losses)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        master.stop()
