"""Regression tests for the races edl-lint's EDL001/EDL002 surfaced
(PR 5) — see docs/designs/static_analysis.md.

Race reproductions are inherently flaky, so these tests assert the
STRUCTURAL property instead: the fixed methods acquire the object's
lock (a recording wrapper counts acquisitions), and the re-entrancy
fix is checked by driving the exact call chain that would deadlock if
`report()` still called the evaluation service under the dispatcher
lock. The analyzer itself guards the other direction: tests/
test_lint.py pins the shipped tree clean, so reintroducing an
unlocked access fails CI through the lint gate.
"""

import threading

from elasticdl_tpu.master.evaluation_service import EvaluationService
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher, TaskType
from elasticdl_tpu.serving.router import CircuitBreaker, Replica


class RecordingLock(object):
    """A context-manager lock wrapper that counts acquisitions."""

    def __init__(self, inner=None):
        self._inner = inner or threading.Lock()
        self.acquisitions = 0

    def __enter__(self):
        self._inner.acquire()
        self.acquisitions += 1
        return self

    def __exit__(self, *exc):
        self._inner.release()
        return False

    def acquire(self, *a, **kw):
        got = self._inner.acquire(*a, **kw)
        if got:
            self.acquisitions += 1
        return got

    def release(self):
        self._inner.release()


def _dispatcher(**kwargs):
    return TaskDispatcher(
        training_shards={"shard": (0, 8)},
        evaluation_shards={},
        prediction_shards={},
        records_per_task=2,
        num_epochs=1,
        **kwargs,
    )


# ------------------------------------------------------- TaskDispatcher


def test_dispatcher_finished_takes_lock():
    d = _dispatcher()
    lock = RecordingLock()
    d._lock = lock
    before = lock.acquisitions
    assert d.finished() is False
    assert lock.acquisitions == before + 1


def test_dispatcher_add_deferred_callback_takes_lock():
    d = _dispatcher()
    lock = RecordingLock()
    d._lock = lock
    before = lock.acquisitions
    d.add_deferred_callback_create_train_end_task()
    assert lock.acquisitions == before + 1
    assert len(d._tasks_done_deferred_callbacks) == 1


def test_dispatcher_external_create_tasks_takes_lock():
    """The evaluation service's trigger thread calls create_tasks
    without holding the dispatcher lock; the public entry must take it
    (workers pop the same queues concurrently)."""
    d = _dispatcher()
    lock = RecordingLock()
    d._lock = lock
    before = lock.acquisitions
    n = d.create_tasks(TaskType.EVALUATION, model_version=3)
    assert lock.acquisitions == before + 1
    assert n == 0  # no evaluation shards configured


def test_dispatcher_report_reenters_eval_service_without_deadlock():
    """report() -> complete_task() -> try_to_create_new_job() ->
    create_tasks() re-acquires the dispatcher's non-reentrant lock.
    Before the fix report held the lock across the complete_task call,
    so this exact chain self-deadlocked; it must finish promptly now."""
    d = TaskDispatcher(
        training_shards={},
        evaluation_shards={"shard": (0, 4)},
        prediction_shards={},
        records_per_task=2,
        num_epochs=1,
    )

    class ReenteringEvalService(object):
        def __init__(self, task_d):
            self.task_d = task_d
            self.completions = 0

        def init_eval_only_job(self, num_task):
            pass

        def complete_task(self):
            self.completions += 1
            # the re-entrant hop that used to deadlock:
            self.task_d.create_tasks(TaskType.EVALUATION, 5)

    svc = ReenteringEvalService(d)
    d.set_evaluation_service(svc)
    task_id, task = d.get_eval_task(worker_id=0)
    assert task is not None

    done = threading.Event()

    def run_report():
        d.report(task_id, True)
        done.set()

    t = threading.Thread(target=run_report, daemon=True)
    t.start()
    assert done.wait(timeout=10.0), (
        "report() deadlocked re-entering the dispatcher through the "
        "evaluation service"
    )
    assert svc.completions == 1


# ------------------------------------------------------- MasterServicer


def test_servicer_watchdog_reads_take_lock():
    d = _dispatcher()
    servicer = MasterServicer(minibatch_size=4, task_d=d)
    lock = RecordingLock()
    servicer._lock = lock

    before = lock.acquisitions
    avg = servicer.get_average_task_complete_time()
    assert lock.acquisitions == before + 1
    assert avg[TaskType.TRAINING] == 300.0

    before = lock.acquisitions
    assert servicer.get_worker_liveness_time(0) is None
    assert lock.acquisitions == before + 1


def test_servicer_register_worker_returns_own_version():
    """Each registration must answer with the cluster version ITS bump
    produced, captured under the lock — two racing registrations must
    not both observe the later value."""
    d = _dispatcher()
    servicer = MasterServicer(minibatch_size=4, task_d=d)

    class Req(object):
        def __init__(self, wid):
            self.worker_id = wid
            self.address = "w%d" % wid
            self.num_devices = 1

    barrier = threading.Barrier(8)
    versions = []
    versions_lock = threading.Lock()

    def register(wid):
        barrier.wait()
        resp = servicer.register_worker(Req(wid))
        with versions_lock:
            versions.append(resp.cluster_version)

    threads = [
        threading.Thread(target=register, args=(i,)) for i in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10.0)
    assert sorted(versions) == list(range(1, 9))


# ---------------------------------------------------- EvaluationService


def test_eval_service_init_eval_only_job_takes_lock():
    d = _dispatcher()
    svc = EvaluationService(
        None, d, start_delay_secs=0, throttle_secs=0, eval_steps=0,
        eval_only=True, eval_metrics_fn=dict,
    )
    lock = RecordingLock()
    svc._lock = lock
    before = lock.acquisitions
    svc.init_eval_only_job(3)
    assert lock.acquisitions == before + 1
    assert svc._eval_job is not None


# ------------------------------------------------------- Router replica


def test_replica_load_score_reads_inflight_under_lock():
    rep = Replica("r0", stub=None, breaker=CircuitBreaker(),
                  lease_until=0.0)
    lock = RecordingLock()
    rep._inflight_lock = lock
    rep.queue_depth = 2
    rep.active_slots = 1
    rep.queue_wait_ms = 100.0
    rep.begin_dispatch()
    before = lock.acquisitions
    score = rep.load_score()
    assert lock.acquisitions == before + 1
    assert score == 2 + 1 + 1 + 100.0 / 50.0
    rep.end_dispatch()


# ------------------------------------------------------ TaskDataService


def test_task_data_service_report_record_done_takes_lock():
    from elasticdl_tpu.worker.task_data_service import TaskDataService

    class FakeWorker(object):
        def __init__(self):
            self.reported = []

        def report_task_result(self, task_id, err_msg, exec_counters=None):
            self.reported.append((task_id, err_msg, exec_counters))

    class FakeTask(object):
        def __init__(self, task_id, start, end):
            self.task_id = task_id
            self.start = start
            self.end = end

    worker = FakeWorker()
    svc = TaskDataService(worker, data_origin="unused.csv")
    lock = RecordingLock()
    svc._lock = lock
    svc._pending_tasks.append(FakeTask(7, 0, 4))
    svc._current_task = svc._pending_tasks[0]

    # partial coverage: counters mutate under the lock, nothing reported
    before = lock.acquisitions
    assert svc.report_record_done(2) is False
    assert lock.acquisitions == before + 1
    assert worker.reported == []

    # completing the task pops and reports it, still one lock scope
    before = lock.acquisitions
    assert svc.report_record_done(2) is True
    assert lock.acquisitions == before + 1
    assert [r[0] for r in worker.reported] == [7]
