"""edl-lint fixture battery + gate semantics (tier-1 fast shard).

Every rule family is exercised by at least one TRIGGERING and one
CLEAN fixture under tests/lint_fixtures/; the gate semantics tests pin
exactly what CI relies on: the shipped tree lints clean, deleting a
baseline entry fails, a stale baseline entry fails, and injecting any
fixture snippet into a linted file fails. The proto-drift tests pin
byte-determinism of scripts/gen_serving_proto.py (regen-twice) and
drift detection on a tampered pb2.
"""

import json
import os
import shutil

import pytest

from elasticdl_tpu.analysis import Baseline, all_rules, run_rules
from elasticdl_tpu.analysis.lint import (
    REPO_ROOT,
    RULE_FAMILIES,
    main as lint_main,
)

FIXTURES = os.path.join(os.path.dirname(__file__), "lint_fixtures")


def lint_file(name):
    path = os.path.join(FIXTURES, name)
    findings, errors = run_rules([path], root=None, excludes=())
    assert not errors, errors
    # repo-level rules (EDL301) don't fire with root=None
    return findings


def rule_ids(findings):
    return sorted(f.rule for f in findings)


# ----------------------------------------------------------- C1 fixtures


def test_c1_positive():
    findings = lint_file("c1_pos.py")
    assert rule_ids(findings) == ["EDL001", "EDL001", "EDL002"]
    details = {(f.scope, f.detail) for f in findings}
    assert ("Counter.bump_unlocked", "_count") in details
    assert ("Counter.append_unlocked", "_items") in details
    assert ("Counter.peek_unlocked", "_count") in details


def test_c1_negative():
    assert lint_file("c1_neg.py") == []


def test_c1_pragma_suppresses_both_placements():
    assert lint_file("c1_pragma.py") == []


# ----------------------------------------------------------- C2 fixtures


def test_c2_positive():
    findings = lint_file("c2_pos.py")
    ids = rule_ids(findings)
    assert ids.count("EDL101") == 4, findings
    assert ids.count("EDL102") == 2, findings
    assert ids.count("EDL103") == 2, findings
    details = {f.detail for f in findings}
    assert {".item()", "float()", "np.asarray",
            ".block_until_ready()"} <= details
    assert {"if", "while", "time.time", "print"} <= details


def test_c2_negative():
    assert lint_file("c2_neg.py") == []


def test_c20_positive_index_map_host_sync():
    """EDL108: np.asarray/.item()/int() inside BlockSpec index-map
    lambdas, positional and index_map= spellings both."""
    findings = lint_file("c20_pos.py")
    ids = rule_ids(findings)
    assert ids.count("EDL108") == 4, findings
    assert {f.scope for f in findings
            if f.rule == "EDL108"} == {"BlockSpec.index_map"}
    details = [f.detail for f in findings if f.rule == "EDL108"]
    assert sorted(details) == [
        ".item()", "int()", "np.array", "np.asarray",
    ], details


def test_c20_negative_index_map_clean():
    """The tracer-safe index-map idiom (jnp ops on the prefetch ref),
    host-side np.asarray BEFORE pallas_call, and non-BlockSpec lambdas
    must all stay clean."""
    findings = [f for f in lint_file("c20_neg.py")
                if f.rule in RULE_FAMILIES["EDL101"]]
    assert findings == [], findings


# ----------------------------------------------------------- C3 fixtures


def test_c3_positive():
    findings = lint_file("c3_pos.py")
    assert rule_ids(findings) == ["EDL201"] * 8, findings
    scopes = {f.scope for f in findings}
    assert "EdgeRouter.dispatch_generate" in scopes
    assert "EdgeRouter.housekeeping" not in scopes
    # the concurrent.futures coverage gap: untimed result()/wait()/
    # as_completed() in dispatch paths (the PR 4 heartbeat-poll shape)
    details = {f.detail for f in findings}
    assert {".result()", "futures.wait", "as_completed"} <= details


def test_c3_negative():
    assert lint_file("c3_neg.py") == []


# ----------------------------------------------------------- C5 fixtures


def test_c5_positive():
    findings = lint_file("c5_pos.py")
    assert rule_ids(findings) == ["EDL401"] * 8, findings
    details = {f.detail for f in findings}
    assert details == {"admittd", "rejectd", "breaker_tripz",
                       "queue_dept", "healthy_replica", "queue_wiat",
                       "steady_recompile", "last_progress_age"}
    scopes = {f.scope for f in findings}
    assert "Frontend.admit" in scopes and "module_level" in scopes
    # gauge typos report as gauges, counter typos as counters,
    # slow-cause typos as slow causes
    by_detail = {f.detail: f.message for f in findings}
    assert "gauge" in by_detail["queue_dept"]
    assert "counter" in by_detail["admittd"]
    assert "slow cause" in by_detail["queue_wiat"]
    # the runtime-health names extend the same closed sets
    assert "counter" in by_detail["steady_recompile"]
    assert "gauge" in by_detail["last_progress_age"]


def test_c5_negative():
    assert lint_file("c5_neg.py") == []


def test_c5_allowed_set_tracks_telemetry_declarations():
    """The rule reads the declared sets from serving/telemetry.py —
    one source of truth, no drift-prone second list (counters AND the
    gauge set the metrics plane closed)."""
    from elasticdl_tpu.analysis.telemetry_rules import (
        declared_counters,
        declared_gauges,
    )
    from elasticdl_tpu.serving.telemetry import (
        RouterTelemetry,
        ServingTelemetry,
    )

    assert declared_counters() == (
        frozenset(ServingTelemetry.COUNTERS)
        | frozenset(RouterTelemetry.COUNTERS)
    )
    assert "admitted" in declared_counters()
    assert declared_gauges() == (
        frozenset(ServingTelemetry.GAUGES)
        | frozenset(RouterTelemetry.GAUGES)
    )
    assert "queue_depth" in declared_gauges()
    assert "healthy_replicas" in declared_gauges()
    # the runtime-health extension rides the SAME single source: the
    # new counter/gauge names are in the unions because telemetry.py
    # declares them, not because any list here grew
    assert "steady_recompiles" in declared_counters()
    assert "stalls" in declared_counters()
    assert "last_progress_age_ms" in declared_gauges()
    assert "memory_unaccounted_bytes" in declared_gauges()
    from elasticdl_tpu.analysis.telemetry_rules import (
        declared_slow_causes,
    )
    from elasticdl_tpu.observability.forensics import CAUSES

    assert declared_slow_causes() == frozenset(CAUSES)
    assert declared_slow_causes() == frozenset(
        ServingTelemetry.SLOW_CAUSES
    )
    assert "prefill_blocked_by_other" in declared_slow_causes()


# ------------------------------------------ C6: EDL003 lock-order cycles


def test_c6_positive_flags_deadlock_cycles():
    """The synthetic PR 5 deadlock chain: report holds the dispatcher
    lock while complete_task calls back into create_tasks (a
    non-reentrant re-entry), plus a classic AB/BA cycle, plus the
    transitive self-deadlock the AB/BA chain implies."""
    findings = lint_file("c6_pos.py")
    assert rule_ids(findings) == ["EDL003"] * 4, findings
    details = {f.detail for f in findings}
    assert "Dispatcher._lock->Dispatcher._lock" in details
    assert "Dispatcher._lock->EvalSvc._lock->Dispatcher._lock" in details
    assert "PairA._a_lock->PairB._b_lock->PairA._a_lock" in details


def test_c6_negative_fixed_shapes_are_clean():
    """The PR 5 fix shape (cross-object call outside the lock),
    reentrant RLock self-nesting, and the *_locked convention."""
    assert lint_file("c6_neg.py") == []


# ------------------------------------------- C7: EDL004 wrong-lock-held


def test_c7_positive_flags_wrong_lock():
    findings = lint_file("c7_pos.py")
    assert rule_ids(findings) == ["EDL004"] * 2, findings
    assert {(f.scope, f.detail) for f in findings} == {
        ("Registry.snapshot", "_inflight"),
        ("Registry.reset", "_inflight"),
    }


def test_c7_negative_bound_accesses_are_clean():
    assert lint_file("c7_neg.py") == []


# ------------------------------------------- C8: EDL501 must-release


def test_c8_positive_flags_leaks():
    """The synthetic PR 4 probe leak (breaker slot lost on the
    non-transient re-raise), a span lost to an early return, and a
    file handle dropped by a handler branch."""
    findings = lint_file("c8_pos.py")
    assert rule_ids(findings) == ["EDL501"] * 3, findings
    details = {f.detail for f in findings}
    assert "rep.breaker.acquire" in details
    assert "span=start_span" in details
    assert "f=open" in details


def test_c8_negative_settled_paths_are_clean():
    """The PR 4 fix (three-way settle on every outcome), finally-
    guarded release, and the ownership-transfer escapes."""
    assert lint_file("c8_neg.py") == []


def test_c11_positive_flags_refcount_leaks():
    """The prefix-shared KV pool's refcount pairs: an incref'd chain
    lost to an early return, a share() seat dropped on the exception
    path, and an abandoned CoW copy."""
    findings = lint_file("c11_pos.py")
    assert rule_ids(findings) == ["EDL501"] * 3, findings
    details = {f.detail for f in findings}
    assert {"allocator.incref", "allocator.share",
            "allocator.cow"} == details


def test_c11_negative_settled_refcounts_are_clean():
    """finally-guarded decref, slot-level free settles on every
    branch, and the ownership-transfer escape."""
    assert lint_file("c11_neg.py") == []


def test_c12_positive_flags_supervisor_lifecycle_leaks():
    """The replica supervisor's seat pairs (serving/autoscaler.py): a
    spawned seat never adopted nor reaped (an orphan process), a drain
    begun that an exception path never retires, and a launcher Popen
    handle killed but never waited on (a zombie)."""
    findings = lint_file("c12_pos.py")
    assert rule_ids(findings) == ["EDL501"] * 3, findings
    assert {f.detail for f in findings} == {
        "supervisor.spawn", "supervisor.begin_drain", "proc=Popen",
    }


def test_c12_negative_settled_lifecycles_are_clean():
    """Reap on the failure branch, finally-guarded retire, waited
    kills, and the roster ownership-transfer escape."""
    assert lint_file("c12_neg.py") == []


def test_c13_positive_flags_spill_lifecycle_leaks():
    """The tiered KV cache's spill pair (serving/kv_pool.py): a block
    spilled to the host tier must REVIVE or DROP on every path — an
    early return, an exception path, and a budget bail-out that each
    lose the spilled entry are convicted leaks."""
    findings = lint_file("c13_pos.py")
    assert rule_ids(findings) == ["EDL501"] * 3, findings
    assert {f.detail for f in findings} == {"tier.spill"}
    scopes = {f.scope for f in findings}
    assert scopes == {"ChainSpiller.demote",
                      "ChainSpiller.demote_checked",
                      "ChainSpiller.demote_budgeted"}


def test_c13_negative_settled_spills_are_clean():
    """finally-guarded drop, revive-or-drop on every branch, and the
    host-store ownership-transfer escape."""
    assert lint_file("c13_neg.py") == []


def test_c18_positive_flags_cell_lifecycle_leaks():
    """The cell supervisor's router-cell pair (serving/router_main.py
    CellRoster): a spawned cell never adopted nor retired (an orphan
    router process), and a failed-adoption exception path that leaks
    the pid past the raise."""
    findings = lint_file("c18_pos.py")
    assert rule_ids(findings) == ["EDL501"] * 2, findings
    assert {f.detail for f in findings} == {"roster.spawn_cell"}
    assert {f.scope for f in findings} == {
        "CellScaler.grow", "CellScaler.grow_checked",
    }


def test_c18_negative_settled_cells_are_clean():
    """Adopt on the happy path, retire on the not-ready branch and on
    the exception path — every spawn settles, EDL501 stays silent."""
    assert lint_file("c18_neg.py") == []


def test_c19_positive_flags_unsettled_handoff_exports():
    """The disaggregated transfer pair (serving/disagg.py
    HandoffCoordinator): an exported chain that an early return
    neither imports nor aborts, and a failed-import exception path
    that records no abort past the raise."""
    findings = lint_file("c19_pos.py")
    assert rule_ids(findings) == ["EDL501"] * 2, findings
    assert {f.detail for f in findings} == {"disagg.export_chain"}
    assert {f.scope for f in findings} == {
        "HandoffDriver.warm", "HandoffDriver.warm_checked",
    }


def test_c19_negative_settled_handoffs_are_clean():
    """import_chain on the happy path, abort_transfer on the not-ready
    branch and the exception path — and the pool-level export_chain
    (no "disagg" receiver spelling) stays untracked, because pool
    exports return plain data and owe nothing."""
    assert lint_file("c19_neg.py") == []


def test_c21_positive_flags_rollout_lifecycle_leaks():
    """The rollout controller's pairs (serving/rollout.py): a wave
    abandoned by a not-converged early return, a burn alert that
    raises past the rollback, and a staged checkpoint whose failed
    verification is never discarded."""
    findings = lint_file("c21_pos.py")
    assert rule_ids(findings) == ["EDL501"] * 3, findings
    assert {f.detail for f in findings} == {
        "ctl.begin_wave", "stager.stage_checkpoint",
    }
    assert {f.scope for f in findings} == {
        "RolloutDriver.advance", "RolloutDriver.advance_checked",
        "RolloutDriver.prepare",
    }


def test_c21_negative_settled_rollouts_are_clean():
    """commit_wave on the soaked path, rollback_wave on the failure
    branches and the exception path, activate/discard closing both
    staging outcomes — every lifecycle settles, EDL501 stays silent."""
    assert lint_file("c21_neg.py") == []


# -------------- C22/C23: EDL701-EDL704 journal-protocol typestate (v4)


def test_c22_positive_write_replay_closure_and_payload_drift():
    """The closure half of a declared journal protocol: an emit of an
    undeclared kind, a replay branch for an unknown kind, a replay
    branch no emit produces (EDL701), plus an emit dropping a
    `requires` key and one missing a key the replay reads
    unconditionally (EDL702)."""
    findings = lint_file("c22_pos.py")
    assert rule_ids(findings) == [
        "EDL701", "EDL701", "EDL701", "EDL702", "EDL702",
    ], findings
    assert {(f.scope, f.detail) for f in findings} == {
        ("Meter.purge", "undeclared-kind:purge"),
        ("Meter._apply_event", "dead-replay:compact"),
        ("Meter._apply_event", "never-emitted:rotate"),
        ("Meter.record", "sample.value"),
        ("Meter.flush", "flushed.count"),
    }


def test_c22_negative_closed_protocol_is_clean():
    """Alphabet == emit sites == replay branches, payload contracts
    satisfied, optional keys read via .get(): the whole EDL701-EDL704
    family stays silent."""
    assert lint_file("c22_neg.py") == []


def test_c23_positive_typestate_and_crash_windows():
    """The machine half: 'finish' journaled from the terminal state
    its from-set forbids (EDL703), and 'start' parking the machine in
    an unrecoverable state while another journal write is still
    reachable (EDL704)."""
    findings = lint_file("c23_pos.py")
    assert rule_ids(findings) == ["EDL703", "EDL704"], findings
    assert {(f.rule, f.scope, f.detail) for f in findings} == {
        ("EDL703", "Oven.run", "finish@done"),
        ("EDL704", "Oven.run", "start@baking"),
    }


def test_c23_negative_recoverable_machine_is_clean():
    """Same machine with the defects repaired — 'baking' declares a
    resume action and 'finish' fires exactly once, from 'baking'."""
    assert lint_file("c23_neg.py") == []


# ------------------- C14: EDL105 recompile hazard (value-origin v3)


def test_c14_positive_flags_unstable_signatures():
    """Calls to jit-wrapped executables whose argument origins vary
    per execution: loop-derived shapes, len() of a growing attribute
    container (cross-method self._fn wrapper), wall-clock and env
    reads in the signature."""
    findings = lint_file("c14_pos.py")
    assert rule_ids(findings) == ["EDL105"] * 4, findings
    assert {(f.scope, f.detail) for f in findings} == {
        ("churn_loop", "step(loop)"),
        ("BatchRunner.run", "self._fn(len)"),
        ("stamped", "fn(clock)"),
        ("env_sized", "fn(config)"),
    }


def test_c14_negative_stabilizers_are_clean():
    """The engine/kv_pool bucketing idioms are stabilizers, not
    hazards: *_bucket helpers, ceil-to-multiple pads, power-of-two
    tiles, min clamps, scalar device binding (jnp.asarray of a loop
    counter), and per-shape wrappers rebuilt inside the loop."""
    assert lint_file("c14_neg.py") == []


# ----------------------- C15: EDL106 captured-constant bloat


def test_c15_positive_flags_captured_arrays():
    findings = lint_file("c15_pos.py")
    assert rule_ids(findings) == ["EDL106"] * 3, findings
    assert {(f.scope, f.detail) for f in findings} == {
        ("lookup", "VOCAB_TABLE"),
        ("step", "weights"),
        ("apply", "mask"),
    }


def test_c15_negative_threaded_params_are_clean():
    """Arrays threaded as proper arguments, scalar/config captures,
    call-result bindings (never guessed) and untraced closures."""
    assert lint_file("c15_neg.py") == []


# ------------------------- C16: EDL107 PRNG-key discipline


def test_c16_positive_flags_key_reuse():
    """One key feeding two sampler sinks, an in-loop sink re-consuming
    the same key every iteration, and per-iteration closures sharing a
    pre-loop key."""
    findings = lint_file("c16_pos.py")
    assert rule_ids(findings) == ["EDL107"] * 3, findings
    scopes = {f.scope for f in findings}
    assert scopes == {"double_sink", "loop_reconsume",
                      "closure_shares_key"}
    assert {f.detail for f in findings} == {"key"}


def test_c16_negative_split_fold_idioms_are_clean():
    """split-then-consume-once, the generation.py fold_in(rng,
    position) idiom, rebind-between-sinks, per-iteration fold_in
    closures, and non-sampler consumers."""
    assert lint_file("c16_neg.py") == []


# ------------------- C17: EDL601 sharding discipline (born gated)


def test_c17_positive_flags_sharding_drift():
    findings = lint_file("c17_pos.py")
    assert rule_ids(findings) == ["EDL601"] * 4, findings
    details = {f.detail for f in findings}
    assert details == {"with_sharding_constraint", "axis:ddp",
                       "axis:tpx", "donate:step_fn"}
    by_detail = {f.detail: f.scope for f in findings}
    assert by_detail["with_sharding_constraint"] == "pin_after_the_fact"
    assert by_detail["axis:ddp"] == "typo_against_mesh"


def test_c17_negative_disciplined_sharding_is_clean():
    """Constraints inside jit contexts (decorator/wrap/nested helper),
    mesh-declared and canonical axis names, constant-derived axes,
    and donate with out_shardings re-declared."""
    assert lint_file("c17_neg.py") == []


def test_edl601_axis_canon_tracks_mesh_constants():
    """The fallback axis union is MeshAxis.ALL — one source of truth
    with the mesh builder, so a new axis name there is automatically
    sanctioned here."""
    from elasticdl_tpu.analysis.sharding_rules import canonical_axes
    from elasticdl_tpu.common.constants import MeshAxis

    assert canonical_axes() == frozenset(MeshAxis.ALL)
    assert {"dp", "fsdp", "ep", "tp", "sp"} <= canonical_axes()


# ------------------ the EDL105 <-> runtime recompile sentry contract


def test_edl105_conviction_set_matches_runtime_sentry():
    """Cross-check of the static rule against the PR 14 runtime
    sentry: the serving decode paths (engine, kv_pool, offline
    generation) compile exclusively through tracked_jit-adopted sites,
    and serve-smoke pins their steady_recompiles at ZERO. The static
    conviction set over those files must therefore be EMPTY — any
    EDL105 finding here would be a shape the runtime sentry could
    observe as a steady-state recompile (conviction set is a subset
    of sentry-observable shapes, and the sentry's record says there
    are none)."""
    sentry_files = [
        os.path.join(REPO_ROOT, "elasticdl_tpu", "serving",
                     "engine.py"),
        os.path.join(REPO_ROOT, "elasticdl_tpu", "serving",
                     "kv_pool.py"),
        os.path.join(REPO_ROOT, "elasticdl_tpu", "api",
                     "generation.py"),
    ]
    for path in sentry_files:
        with open(path) as f:
            assert "tracked_jit" in f.read(), (
                "%s lost its sentry adoption — the cross-check below "
                "is vacuous without it" % path
            )
    from elasticdl_tpu.analysis import all_rules

    rules = [r for r in all_rules() if r.id == "EDL105"]
    findings, errors = run_rules(sentry_files, rules=rules,
                                 root=REPO_ROOT, excludes=())
    assert errors == []
    assert findings == [], (
        "EDL105 convicts a serving decode path the runtime sentry "
        "holds at steady_recompiles == 0 — fix the code (and add a "
        "regression test) or teach the analysis the stabilizer: %s"
        % [f.format() for f in findings]
    )


# ------------------------------ C9: EDL202/EDL203 deadline propagation


def test_c9_positive_flags_dropped_and_replaced_deadlines():
    findings = lint_file("c9_pos.py")
    assert rule_ids(findings) == ["EDL202", "EDL203", "EDL203",
                                  "EDL203"], findings
    by_scope = {f.scope: f.rule for f in findings}
    assert by_scope["BackendClient.call_backend"] == "EDL202"
    assert by_scope["BackendClient.call_backend_static"] == "EDL203"
    assert by_scope["FrontendServicer.generate"] == "EDL203"
    assert by_scope["EdgeRouter.dispatch"] == "EDL203"


def test_c9_negative_derived_timeouts_are_clean():
    """Decremented budgets, closure-over-budget stream generators, and
    non-dispatch heartbeat polls with static bounds: all sanctioned."""
    assert lint_file("c9_neg.py") == []


# -------------------------------- C10: EDL104 donated-buffer aliasing


def test_c10_positive_flags_read_after_donation():
    findings = lint_file("c10_pos.py")
    assert rule_ids(findings) == ["EDL104"] * 2, findings
    assert {(f.scope, f.detail) for f in findings} == {
        ("train_loop", "state"),
        ("apply_updates", "opt_state"),
    }


def test_c10_negative_rebind_idioms_are_clean():
    assert lint_file("c10_neg.py") == []


def test_new_rules_pragma_suppression(tmp_path):
    """The pragma layer applies to CFG-based rules like any other."""
    src = os.path.join(FIXTURES, "c7_pos.py")
    with open(src) as f:
        text = f.read()
    text = text.replace(
        "return dict(self._entries), self._inflight",
        "return dict(self._entries), self._inflight"
        "  # edl-lint: disable=EDL004",
    )
    mod = tmp_path / "pragma_mod.py"
    mod.write_text(text)
    findings, errors = run_rules([str(mod)], root=None, excludes=())
    assert not errors
    assert {(f.scope, f.detail) for f in findings} == {
        ("Registry.reset", "_inflight"),
    }


# --------------------------------------------------- every-rule coverage


#: checker family -> (triggering fixtures, clean fixture). EVERY
#: registered family must appear here with BOTH halves — the
#: meta-test below fails a new rule until its fixtures exist.
FAMILY_FIXTURES = {
    "EDL000": (("c0_pos.py",), "c1_pragma.py"),
    "EDL001": (("c1_pos.py",), "c1_neg.py"),
    "EDL003": (("c6_pos.py",), "c6_neg.py"),
    "EDL004": (("c7_pos.py",), "c7_neg.py"),
    "EDL101": (("c2_pos.py", "c20_pos.py"), "c2_neg.py"),
    "EDL104": (("c10_pos.py",), "c10_neg.py"),
    "EDL105": (("c14_pos.py",), "c14_neg.py"),
    "EDL106": (("c15_pos.py",), "c15_neg.py"),
    "EDL107": (("c16_pos.py",), "c16_neg.py"),
    "EDL201": (("c3_pos.py",), "c3_neg.py"),
    "EDL202": (("c9_pos.py",), "c9_neg.py"),
    "EDL401": (("c5_pos.py",), "c5_neg.py"),
    "EDL501": (("c8_pos.py", "c11_pos.py", "c12_pos.py",
                "c13_pos.py", "c18_pos.py", "c19_pos.py",
                "c21_pos.py"), "c8_neg.py"),
    "EDL601": (("c17_pos.py",), "c17_neg.py"),
    # the closure half fires in c22, the typestate half in c23; both
    # negatives are pinned clean by their dedicated tests above
    "EDL701": (("c22_pos.py", "c23_pos.py"), "c22_neg.py"),
    # EDL301 is repo-level; its trigger/clean pair is the tampered/
    # pristine pb2 in the proto tests below
    "EDL301": ((), None),
}


def test_every_rule_has_fixture_coverage():
    """Meta-test: EVERY registered rule family is proven live by at
    least one triggering fixture and kept honest by a clean one. A
    new rule family cannot register without growing FAMILY_FIXTURES
    (KeyError here) and shipping fixtures that actually fire."""
    assert set(FAMILY_FIXTURES) == {r.id for r in all_rules()}
    emitted = set()
    for rule in all_rules():
        pos_names, neg_name = FAMILY_FIXTURES[rule.id]
        if not pos_names:  # repo-level: proto tests own it
            continue
        family_hits = set()
        for name in pos_names:
            hits = {f.rule for f in lint_file(name)}
            family_hits |= hits
            emitted |= hits
        assert family_hits & set(RULE_FAMILIES[rule.id]), (
            "family %s has no triggering fixture evidence" % rule.id
        )
        assert neg_name is not None
        neg_findings = [
            f for f in lint_file(neg_name)
            if f.rule in RULE_FAMILIES[rule.id]
        ]
        assert neg_findings == [], (
            "clean fixture for %s is not clean: %r"
            % (rule.id, neg_findings)
        )
    ast_rule_ids = set()
    for rule in all_rules():
        ast_rule_ids.update(RULE_FAMILIES[rule.id])
    # EDL301 is repo-level, covered by the proto tests below
    assert emitted == ast_rule_ids - {"EDL301"}


# -------------------------------------------------------- baseline gate


def test_baseline_round_trip(tmp_path):
    src = os.path.join(FIXTURES, "c1_pos.py")
    findings, _ = run_rules([src], root=None, excludes=())
    assert findings
    base_path = str(tmp_path / "baseline.json")
    Baseline.from_findings(
        findings, reason="vetted in test", path=base_path
    ).save()

    reloaded = Baseline.load(base_path)
    remaining, stale = reloaded.apply(findings)
    assert remaining == [] and stale == []

    # deleting any one entry un-suppresses its finding
    with open(base_path) as f:
        data = json.load(f)
    dropped = data["entries"].pop(0)
    with open(base_path, "w") as f:
        json.dump(data, f)
    remaining, stale = Baseline.load(base_path).apply(findings)
    assert len(remaining) >= 1 and stale == []
    assert any(
        (f.rule, f.scope, f.detail)
        == (dropped["rule"], dropped["scope"], dropped["detail"])
        for f in remaining
    )


def test_stale_baseline_entry_fails():
    findings_fp_free = Baseline(entries=[{
        "rule": "EDL001", "path": "gone.py", "scope": "X.y",
        "detail": "_z", "reason": "the code this vetted was deleted",
    }])
    remaining, stale = findings_fp_free.apply([])
    assert remaining == [] and len(stale) == 1


def test_baseline_rejects_missing_reason():
    with pytest.raises(Exception):
        Baseline(entries=[{
            "rule": "EDL001", "path": "a.py", "scope": "X.y",
            "detail": "_z",
        }])


# ------------------------------------------------------------- CLI gate


def test_shipped_tree_is_clean_within_ci_budget():
    """The CI contract, both halves in one run: `make lint`'s analyzer
    half exits 0 on the shipped tree with the checked-in baseline,
    and the full-repo SINGLE-PROCESS sweep stays under the documented
    60 s budget (docs/ci.md) — the v3 value-origin pass must not blow
    the pre-shard gate's latency."""
    import time

    t0 = time.monotonic()
    assert lint_main(["--no-cache"]) == 0
    elapsed = time.monotonic() - t0
    assert elapsed < 60.0, (
        "full-repo single-process COLD lint took %.1fs (budget 60s); "
        "profile the newest rules" % elapsed
    )


def test_cache_cold_warm_parity_and_no_cache_bypass(tmp_path):
    """The incremental-cache contract, all three legs in one scenario:
    a warm run replays byte-identical SARIF to the cold run; the warm
    run genuinely READS the cache (a tampered entry with a matching
    content hash surfaces in the output — proof of hits, not re-
    analysis); and --no-cache bypasses the tampered cache back to the
    cold bytes."""
    srcdir = tmp_path / "pkg"
    srcdir.mkdir()
    for name in ("c1_pos.py", "c22_pos.py"):
        shutil.copy(
            os.path.join(FIXTURES, name),
            str(srcdir / name.replace("_pos", "_mod")),
        )
    root = str(tmp_path)
    cache_path = tmp_path / ".edl-lint-cache.json"

    def run(extra, out):
        rc = lint_main(
            [str(srcdir), "--root", root,
             "--format", "sarif", "--output", str(out)] + extra
        )
        with open(str(out), "rb") as f:
            return rc, f.read()

    rc, cold = run([], tmp_path / "cold.sarif")
    assert rc == 1
    assert cache_path.exists(), "cold run must write the cache"

    rc, warm = run([], tmp_path / "warm.sarif")
    assert rc == 1
    assert warm == cold, "warm run is not byte-identical to cold"

    with open(str(cache_path)) as f:
        data = json.load(f)
    entry = next(e for e in data["files"].values() if e["findings"])
    entry["findings"][0][5] = "TAMPERED-CACHE-SENTINEL"
    with open(str(cache_path), "w") as f:
        json.dump(data, f)
    rc, tampered = run([], tmp_path / "tampered.sarif")
    assert b"TAMPERED-CACHE-SENTINEL" in tampered, (
        "warm run re-analyzed instead of reading the cache"
    )

    rc, bypass = run(["--no-cache"], tmp_path / "bypass.sarif")
    assert bypass == cold, "--no-cache did not bypass the cache"


def test_cache_invalidated_by_file_edit(tmp_path):
    """Editing a linted file invalidates exactly its entry: the next
    run re-analyzes it and reports the new findings."""
    srcdir = tmp_path / "pkg"
    srcdir.mkdir()
    target = srcdir / "c1_mod.py"
    shutil.copy(os.path.join(FIXTURES, "c1_pos.py"), str(target))
    root = str(tmp_path)
    args = [str(srcdir), "--root", root, "--select", "EDL001"]
    assert lint_main(args) == 1
    with open(str(target), "w") as f:
        f.write("X = 1\n")
    assert lint_main(args) == 0, (
        "stale cache entry survived a content change"
    )


def test_shipped_baseline_entries_are_all_live(tmp_path):
    """Deleting ANY entry from the shipped baseline makes the run fail:
    every entry suppresses a live finding (no rot)."""
    shipped = os.path.join(REPO_ROOT, ".edl-lint-baseline.json")
    with open(shipped) as f:
        data = json.load(f)
    assert data["entries"], "shipped baseline unexpectedly empty"
    for e in data["entries"]:
        assert e["reason"].strip(), "entry without justification: %r" % e
    pruned = str(tmp_path / "pruned.json")
    for i in range(len(data["entries"])):
        dropped = dict(data)
        dropped["entries"] = (
            data["entries"][:i] + data["entries"][i + 1:]
        )
        with open(pruned, "w") as f:
            json.dump(dropped, f)
        assert lint_main(["--baseline", pruned]) == 1, (
            "baseline entry %d (%s) is not live" % (i, data["entries"][i])
        )


def test_injected_fixture_snippet_fails(tmp_path):
    """Copying any triggering fixture into a linted source tree flips
    the gate to non-zero (with the shipped baseline)."""
    srcdir = tmp_path / "pkg"
    srcdir.mkdir()
    shutil.copy(
        os.path.join(FIXTURES, "c1_pos.py"),
        str(srcdir / "injected_module.py"),
    )
    rc = lint_main([
        str(srcdir),
        "--baseline", os.path.join(REPO_ROOT, ".edl-lint-baseline.json"),
        "--select", "EDL001",
    ])
    assert rc == 1


def test_select_limits_rules(tmp_path):
    srcdir = tmp_path / "pkg"
    srcdir.mkdir()
    shutil.copy(
        os.path.join(FIXTURES, "c1_pos.py"),
        str(srcdir / "injected_module.py"),
    )
    # only the jit family selected: the C1 violation is out of scope
    rc = lint_main([
        str(srcdir),
        "--baseline", str(tmp_path / "absent.json"),
        "--select", "EDL101",
    ])
    assert rc == 0


# ------------------------------------------------ driver modes (v2 CLI)


def test_parallel_jobs_output_parity():
    """--jobs fans per-file analysis over a process pool; findings
    must be byte-identical to the serial run (same order, same
    fingerprints) so CI can use either."""
    paths = [os.path.join(FIXTURES, n)
             for n in ("c1_pos.py", "c6_pos.py", "c8_pos.py",
                       "c9_pos.py", "c10_pos.py")]
    serial, es = run_rules(paths, root=None, excludes=(), jobs=1)
    fanned, ep = run_rules(paths, root=None, excludes=(), jobs=2)
    assert not es and not ep
    assert [f.format() for f in serial] == [f.format() for f in fanned]
    assert serial, "parity test needs a non-empty finding set"


def test_github_format_annotations(tmp_path, capsys):
    srcdir = tmp_path / "pkg"
    srcdir.mkdir()
    shutil.copy(
        os.path.join(FIXTURES, "c7_pos.py"),
        str(srcdir / "injected_module.py"),
    )
    rc = lint_main([
        str(srcdir),
        "--baseline", str(tmp_path / "absent.json"),
        "--select", "EDL004", "--format", "github",
    ])
    assert rc == 1
    out = capsys.readouterr().out
    lines = [ln for ln in out.splitlines() if ln.startswith("::error ")]
    assert len(lines) == 2
    assert "file=" in lines[0] and "line=" in lines[0]
    assert "title=EDL004" in lines[0]


def test_explicit_file_paths_respect_excludes(tmp_path):
    """--changed-only hands individual FILES to the runner; excluded
    paths (fixtures, generated pb2) must stay excluded even when
    named explicitly, or a fixture edit would fail the gate."""
    fixture = os.path.join(FIXTURES, "c1_pos.py")
    findings, errors = run_rules([fixture], root=None)  # default excludes
    assert findings == [] and errors == []


def test_changed_only_merge_base_diff(tmp_path):
    """changed_files returns tracked-modified plus untracked .py files
    vs the merge base, as absolute paths."""
    import subprocess

    from elasticdl_tpu.analysis.lint import changed_files

    repo = str(tmp_path / "repo")
    os.makedirs(repo)

    def git(*args):
        subprocess.run(
            ("git", "-C", repo) + args, check=True,
            capture_output=True,
            env={**os.environ,
                 "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
                 "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"},
        )

    git("init", "-b", "main")
    with open(os.path.join(repo, "a.py"), "w") as f:
        f.write("A = 1\n")
    with open(os.path.join(repo, "b.py"), "w") as f:
        f.write("B = 1\n")
    git("add", "-A")
    git("commit", "-m", "seed")
    with open(os.path.join(repo, "a.py"), "w") as f:
        f.write("A = 2\n")          # tracked, modified
    with open(os.path.join(repo, "c.py"), "w") as f:
        f.write("C = 1\n")          # untracked
    changed = changed_files(repo, base="main")
    assert changed == [
        os.path.join(repo, "a.py"), os.path.join(repo, "c.py"),
    ]


# --------------------------------- EDL000 / --fix-pragmas gate semantics


# @PRAGMA@ is substituted below so the scratch module's pragmas are
# invisible to the line-based pragma scanner when THIS file is linted
_PRAGMA_MOD = '''\
"""Scratch module: one used pragma, one unused trailing pragma, one
unused whole-line pragma."""
import threading


class Counter(object):
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0

    def bump(self):
        with self._lock:
            self._count += 1

    def peek(self):
        return self._count  # @PRAGMA@ disable=EDL002

    def fine(self):
        with self._lock:
            return self._count  # @PRAGMA@ disable=EDL002

    # @PRAGMA@ disable=EDL101
    def also_fine(self):
        return 1
'''.replace("@PRAGMA@", "edl-lint:")


def _write_pragma_pkg(tmp_path):
    srcdir = tmp_path / "pkg"
    srcdir.mkdir()
    (srcdir / "mod.py").write_text(_PRAGMA_MOD)
    return srcdir


def test_unused_pragma_is_a_finding(tmp_path):
    """A pragma that suppresses zero findings is itself an EDL000
    finding (the suppression mirror of the stale-baseline failure);
    the USED pragma on the same file stays silent."""
    srcdir = _write_pragma_pkg(tmp_path)
    findings, errors = run_rules([str(srcdir)], root=str(tmp_path),
                                 excludes=())
    assert not errors
    edl000 = [f for f in findings if f.rule == "EDL000"]
    assert [f.detail for f in edl000] == [
        "disable=EDL002", "disable=EDL101",
    ]
    assert {f.line for f in edl000} == {20, 22}
    # the used pragma (line 16) suppressed the real EDL002 — neither
    # that finding nor an EDL000 for it appears
    assert not any(f.rule == "EDL002" for f in findings)


def test_unused_pragma_skipped_when_rule_not_selected(tmp_path):
    """--select subsets cannot vindicate a pragma for an unselected
    rule, so they must not convict it either; disable=all needs the
    full registry."""
    from elasticdl_tpu.analysis.lint import _selected_rules

    srcdir = _write_pragma_pkg(tmp_path)
    rules = _selected_rules("EDL001,EDL000")
    findings, errors = run_rules([str(srcdir)], rules=rules,
                                 root=str(tmp_path), excludes=())
    assert not errors
    # only the EDL101-naming pragma escapes judgment (its rule did
    # not run); the unused EDL002 pragma is still convicted because
    # the lock-discipline checker DID run
    assert [f.detail for f in findings if f.rule == "EDL000"] == [
        "disable=EDL002",
    ]


def test_fix_pragmas_deletes_only_unused(tmp_path):
    srcdir = _write_pragma_pkg(tmp_path)
    rc = lint_main([
        str(srcdir), "--root", str(tmp_path),
        "--baseline", str(tmp_path / "absent.json"),
        "--fix-pragmas",
    ])
    assert rc == 0
    text = (srcdir / "mod.py").read_text()
    # the used pragma survives; the trailing one is stripped in
    # place; the whole-line one is deleted entirely
    assert text.count("edl-lint: disable") == 1
    assert "return self._count  # edl-lint: disable=EDL002\n" in text
    assert "# edl-lint: disable=EDL101" not in text
    assert "\n\n    def also_fine" in text
    # and the re-run is clean (root=None: module rules only — the
    # scratch tree has no pb2 for the repo-level EDL301 pass)
    findings, errors = run_rules([str(srcdir)], root=None,
                                 excludes=())
    assert not errors and findings == []


def test_shipped_tree_has_no_unused_pragmas():
    """The one-time repo sweep stays done: every pragma in the shipped
    tree suppresses a live finding (the full-tree run above would
    carry EDL000 findings otherwise, but pin it explicitly)."""
    from elasticdl_tpu.analysis.lint import DEFAULT_PATHS

    paths = [os.path.join(REPO_ROOT, p) for p in DEFAULT_PATHS]
    findings, errors = run_rules(paths, root=REPO_ROOT)
    assert not errors
    assert [f for f in findings if f.rule == "EDL000"] == []


# ------------------------------------------------- SARIF output (v3 CLI)


def test_sarif_output_is_byte_deterministic(tmp_path):
    """--format sarif must be byte-identical across runs AND across
    --jobs fan-out (same contract as the github/human formats), so
    the code-scanning upload can never flake on ordering."""
    srcdir = tmp_path / "pkg"
    srcdir.mkdir()
    shutil.copy(os.path.join(FIXTURES, "c7_pos.py"),
                str(srcdir / "injected_module.py"))
    outs = []
    for jobs in ("1", "2", "1"):
        out = tmp_path / ("out_%s_%d.sarif" % (jobs, len(outs)))
        rc = lint_main([
            str(srcdir),
            "--baseline", str(tmp_path / "absent.json"),
            "--select", "EDL004", "--format", "sarif",
            "--jobs", jobs, "--output", str(out),
        ])
        assert rc == 1
        outs.append(out.read_bytes())
    assert outs[0] == outs[1] == outs[2]


def test_sarif_document_structure(tmp_path):
    srcdir = tmp_path / "pkg"
    srcdir.mkdir()
    shutil.copy(os.path.join(FIXTURES, "c7_pos.py"),
                str(srcdir / "injected_module.py"))
    out = tmp_path / "edl-lint.sarif"
    rc = lint_main([
        str(srcdir),
        "--baseline", str(tmp_path / "absent.json"),
        "--select", "EDL004", "--format", "sarif",
        "--output", str(out),
    ])
    assert rc == 1
    doc = json.loads(out.read_text())
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "edl-lint"
    results = run["results"]
    assert len(results) == 2
    for res in results:
        assert res["ruleId"] == "EDL004"
        assert res["level"] == "error"
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith(
            "injected_module.py"
        )
        assert loc["region"]["startLine"] >= 1
        assert "edlLintFingerprint/v1" in res["partialFingerprints"]
    rule_ids_meta = [r["id"] for r in run["tool"]["driver"]["rules"]]
    assert rule_ids_meta == sorted(rule_ids_meta)
    assert "EDL004" in rule_ids_meta
    for meta in run["tool"]["driver"]["rules"]:
        assert meta["helpUri"] == (
            "docs/designs/static_analysis.md#%s" % meta["id"].lower()
        )


def test_sarif_carries_protocol_family_descriptors(tmp_path):
    """The EDL701-EDL704 family ships one reportingDescriptor per
    emitted id, each with a helpUri anchored to its catalogue row —
    without the descriptor the uploader drops the alert's rule link."""
    srcdir = tmp_path / "pkg"
    srcdir.mkdir()
    for name in ("c22_pos.py", "c23_pos.py"):
        shutil.copy(os.path.join(FIXTURES, name), str(srcdir / name))
    out = tmp_path / "protocol.sarif"
    rc = lint_main([
        str(srcdir),
        "--baseline", str(tmp_path / "absent.json"),
        "--select", "EDL701", "--format", "sarif",
        "--output", str(out),
    ])
    assert rc == 1
    with open(str(out)) as f:
        doc = json.load(f)
    run = doc["runs"][0]
    metas = {r["id"]: r for r in run["tool"]["driver"]["rules"]}
    for fid in ("EDL701", "EDL702", "EDL703", "EDL704"):
        assert metas[fid]["helpUri"] == (
            "docs/designs/static_analysis.md#%s" % fid.lower()
        )
    assert {res["ruleId"] for res in run["results"]} == {
        "EDL701", "EDL702", "EDL703", "EDL704",
    }


def test_sarif_clean_tree_writes_empty_results(tmp_path):
    srcdir = tmp_path / "pkg"
    srcdir.mkdir()
    (srcdir / "ok.py").write_text("X = 1\n")
    out = tmp_path / "clean.sarif"
    rc = lint_main([
        str(srcdir),
        "--baseline", str(tmp_path / "absent.json"),
        "--format", "sarif", "--output", str(out),
    ])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert doc["runs"][0]["results"] == []


# ------------------------------------------------- C4: proto drift gate


def test_proto_regen_twice_is_byte_identical():
    """Determinism satellite: regenerating from the regenerated text
    yields identical bytes — field/table ordering is stable, so the
    drift gate can never flake."""
    from scripts.gen_serving_proto import generate_text

    once = generate_text()
    twice = generate_text(once)
    assert once == twice
    with open(os.path.join(
        REPO_ROOT, "elasticdl_tpu", "proto", "elasticdl_pb2.py"
    )) as f:
        assert f.read() == once, (
            "checked-in pb2 drifted: rerun scripts/gen_serving_proto.py"
        )


def test_proto_drift_detected_on_tampered_pb2(tmp_path):
    from elasticdl_tpu.analysis.proto_rules import ProtoDriftRule

    pb2 = os.path.join(
        REPO_ROOT, "elasticdl_tpu", "proto", "elasticdl_pb2.py"
    )
    with open(pb2) as f:
        text = f.read()
    tampered = str(tmp_path / "elasticdl_pb2.py")
    with open(tampered, "w") as f:
        f.write("# tampered by test\n" + text)
    findings = ProtoDriftRule().check_repo(REPO_ROOT, pb2_path=tampered)
    assert [f.rule for f in findings] == ["EDL301"]
    assert findings[0].detail == "drift"

    clean = ProtoDriftRule().check_repo(REPO_ROOT, pb2_path=pb2)
    assert clean == []
