"""edl-lint fixture battery + gate semantics (tier-1 fast shard).

Every rule family is exercised by at least one TRIGGERING and one
CLEAN fixture under tests/lint_fixtures/; the gate semantics tests pin
exactly what CI relies on: the shipped tree lints clean, deleting a
baseline entry fails, a stale baseline entry fails, and injecting any
fixture snippet into a linted file fails. The proto-drift tests pin
byte-determinism of scripts/gen_serving_proto.py (regen-twice) and
drift detection on a tampered pb2.
"""

import json
import os
import shutil

import pytest

from elasticdl_tpu.analysis import Baseline, all_rules, run_rules
from elasticdl_tpu.analysis.lint import (
    REPO_ROOT,
    RULE_FAMILIES,
    main as lint_main,
)

FIXTURES = os.path.join(os.path.dirname(__file__), "lint_fixtures")


def lint_file(name):
    path = os.path.join(FIXTURES, name)
    findings, errors = run_rules([path], root=None, excludes=())
    assert not errors, errors
    # repo-level rules (EDL301) don't fire with root=None
    return findings


def rule_ids(findings):
    return sorted(f.rule for f in findings)


# ----------------------------------------------------------- C1 fixtures


def test_c1_positive():
    findings = lint_file("c1_pos.py")
    assert rule_ids(findings) == ["EDL001", "EDL001", "EDL002"]
    details = {(f.scope, f.detail) for f in findings}
    assert ("Counter.bump_unlocked", "_count") in details
    assert ("Counter.append_unlocked", "_items") in details
    assert ("Counter.peek_unlocked", "_count") in details


def test_c1_negative():
    assert lint_file("c1_neg.py") == []


def test_c1_pragma_suppresses_both_placements():
    assert lint_file("c1_pragma.py") == []


# ----------------------------------------------------------- C2 fixtures


def test_c2_positive():
    findings = lint_file("c2_pos.py")
    ids = rule_ids(findings)
    assert ids.count("EDL101") == 4, findings
    assert ids.count("EDL102") == 2, findings
    assert ids.count("EDL103") == 2, findings
    details = {f.detail for f in findings}
    assert {".item()", "float()", "np.asarray",
            ".block_until_ready()"} <= details
    assert {"if", "while", "time.time", "print"} <= details


def test_c2_negative():
    assert lint_file("c2_neg.py") == []


# ----------------------------------------------------------- C3 fixtures


def test_c3_positive():
    findings = lint_file("c3_pos.py")
    assert rule_ids(findings) == ["EDL201"] * 8, findings
    scopes = {f.scope for f in findings}
    assert "EdgeRouter.dispatch_generate" in scopes
    assert "EdgeRouter.housekeeping" not in scopes
    # the concurrent.futures coverage gap: untimed result()/wait()/
    # as_completed() in dispatch paths (the PR 4 heartbeat-poll shape)
    details = {f.detail for f in findings}
    assert {".result()", "futures.wait", "as_completed"} <= details


def test_c3_negative():
    assert lint_file("c3_neg.py") == []


# ----------------------------------------------------------- C5 fixtures


def test_c5_positive():
    findings = lint_file("c5_pos.py")
    assert rule_ids(findings) == ["EDL401"] * 8, findings
    details = {f.detail for f in findings}
    assert details == {"admittd", "rejectd", "breaker_tripz",
                       "queue_dept", "healthy_replica", "queue_wiat",
                       "steady_recompile", "last_progress_age"}
    scopes = {f.scope for f in findings}
    assert "Frontend.admit" in scopes and "module_level" in scopes
    # gauge typos report as gauges, counter typos as counters,
    # slow-cause typos as slow causes
    by_detail = {f.detail: f.message for f in findings}
    assert "gauge" in by_detail["queue_dept"]
    assert "counter" in by_detail["admittd"]
    assert "slow cause" in by_detail["queue_wiat"]
    # the runtime-health names extend the same closed sets
    assert "counter" in by_detail["steady_recompile"]
    assert "gauge" in by_detail["last_progress_age"]


def test_c5_negative():
    assert lint_file("c5_neg.py") == []


def test_c5_allowed_set_tracks_telemetry_declarations():
    """The rule reads the declared sets from serving/telemetry.py —
    one source of truth, no drift-prone second list (counters AND the
    gauge set the metrics plane closed)."""
    from elasticdl_tpu.analysis.telemetry_rules import (
        declared_counters,
        declared_gauges,
    )
    from elasticdl_tpu.serving.telemetry import (
        RouterTelemetry,
        ServingTelemetry,
    )

    assert declared_counters() == (
        frozenset(ServingTelemetry.COUNTERS)
        | frozenset(RouterTelemetry.COUNTERS)
    )
    assert "admitted" in declared_counters()
    assert declared_gauges() == (
        frozenset(ServingTelemetry.GAUGES)
        | frozenset(RouterTelemetry.GAUGES)
    )
    assert "queue_depth" in declared_gauges()
    assert "healthy_replicas" in declared_gauges()
    # the runtime-health extension rides the SAME single source: the
    # new counter/gauge names are in the unions because telemetry.py
    # declares them, not because any list here grew
    assert "steady_recompiles" in declared_counters()
    assert "stalls" in declared_counters()
    assert "last_progress_age_ms" in declared_gauges()
    assert "memory_unaccounted_bytes" in declared_gauges()
    from elasticdl_tpu.analysis.telemetry_rules import (
        declared_slow_causes,
    )
    from elasticdl_tpu.observability.forensics import CAUSES

    assert declared_slow_causes() == frozenset(CAUSES)
    assert declared_slow_causes() == frozenset(
        ServingTelemetry.SLOW_CAUSES
    )
    assert "prefill_blocked_by_other" in declared_slow_causes()


# ------------------------------------------ C6: EDL003 lock-order cycles


def test_c6_positive_flags_deadlock_cycles():
    """The synthetic PR 5 deadlock chain: report holds the dispatcher
    lock while complete_task calls back into create_tasks (a
    non-reentrant re-entry), plus a classic AB/BA cycle, plus the
    transitive self-deadlock the AB/BA chain implies."""
    findings = lint_file("c6_pos.py")
    assert rule_ids(findings) == ["EDL003"] * 4, findings
    details = {f.detail for f in findings}
    assert "Dispatcher._lock->Dispatcher._lock" in details
    assert "Dispatcher._lock->EvalSvc._lock->Dispatcher._lock" in details
    assert "PairA._a_lock->PairB._b_lock->PairA._a_lock" in details


def test_c6_negative_fixed_shapes_are_clean():
    """The PR 5 fix shape (cross-object call outside the lock),
    reentrant RLock self-nesting, and the *_locked convention."""
    assert lint_file("c6_neg.py") == []


# ------------------------------------------- C7: EDL004 wrong-lock-held


def test_c7_positive_flags_wrong_lock():
    findings = lint_file("c7_pos.py")
    assert rule_ids(findings) == ["EDL004"] * 2, findings
    assert {(f.scope, f.detail) for f in findings} == {
        ("Registry.snapshot", "_inflight"),
        ("Registry.reset", "_inflight"),
    }


def test_c7_negative_bound_accesses_are_clean():
    assert lint_file("c7_neg.py") == []


# ------------------------------------------- C8: EDL501 must-release


def test_c8_positive_flags_leaks():
    """The synthetic PR 4 probe leak (breaker slot lost on the
    non-transient re-raise), a span lost to an early return, and a
    file handle dropped by a handler branch."""
    findings = lint_file("c8_pos.py")
    assert rule_ids(findings) == ["EDL501"] * 3, findings
    details = {f.detail for f in findings}
    assert "rep.breaker.acquire" in details
    assert "span=start_span" in details
    assert "f=open" in details


def test_c8_negative_settled_paths_are_clean():
    """The PR 4 fix (three-way settle on every outcome), finally-
    guarded release, and the ownership-transfer escapes."""
    assert lint_file("c8_neg.py") == []


def test_c11_positive_flags_refcount_leaks():
    """The prefix-shared KV pool's refcount pairs: an incref'd chain
    lost to an early return, a share() seat dropped on the exception
    path, and an abandoned CoW copy."""
    findings = lint_file("c11_pos.py")
    assert rule_ids(findings) == ["EDL501"] * 3, findings
    details = {f.detail for f in findings}
    assert {"allocator.incref", "allocator.share",
            "allocator.cow"} == details


def test_c11_negative_settled_refcounts_are_clean():
    """finally-guarded decref, slot-level free settles on every
    branch, and the ownership-transfer escape."""
    assert lint_file("c11_neg.py") == []


def test_c12_positive_flags_supervisor_lifecycle_leaks():
    """The replica supervisor's seat pairs (serving/autoscaler.py): a
    spawned seat never adopted nor reaped (an orphan process), a drain
    begun that an exception path never retires, and a launcher Popen
    handle killed but never waited on (a zombie)."""
    findings = lint_file("c12_pos.py")
    assert rule_ids(findings) == ["EDL501"] * 3, findings
    assert {f.detail for f in findings} == {
        "supervisor.spawn", "supervisor.begin_drain", "proc=Popen",
    }


def test_c12_negative_settled_lifecycles_are_clean():
    """Reap on the failure branch, finally-guarded retire, waited
    kills, and the roster ownership-transfer escape."""
    assert lint_file("c12_neg.py") == []


def test_c13_positive_flags_spill_lifecycle_leaks():
    """The tiered KV cache's spill pair (serving/kv_pool.py): a block
    spilled to the host tier must REVIVE or DROP on every path — an
    early return, an exception path, and a budget bail-out that each
    lose the spilled entry are convicted leaks."""
    findings = lint_file("c13_pos.py")
    assert rule_ids(findings) == ["EDL501"] * 3, findings
    assert {f.detail for f in findings} == {"tier.spill"}
    scopes = {f.scope for f in findings}
    assert scopes == {"ChainSpiller.demote",
                      "ChainSpiller.demote_checked",
                      "ChainSpiller.demote_budgeted"}


def test_c13_negative_settled_spills_are_clean():
    """finally-guarded drop, revive-or-drop on every branch, and the
    host-store ownership-transfer escape."""
    assert lint_file("c13_neg.py") == []


# ------------------------------ C9: EDL202/EDL203 deadline propagation


def test_c9_positive_flags_dropped_and_replaced_deadlines():
    findings = lint_file("c9_pos.py")
    assert rule_ids(findings) == ["EDL202", "EDL203", "EDL203",
                                  "EDL203"], findings
    by_scope = {f.scope: f.rule for f in findings}
    assert by_scope["BackendClient.call_backend"] == "EDL202"
    assert by_scope["BackendClient.call_backend_static"] == "EDL203"
    assert by_scope["FrontendServicer.generate"] == "EDL203"
    assert by_scope["EdgeRouter.dispatch"] == "EDL203"


def test_c9_negative_derived_timeouts_are_clean():
    """Decremented budgets, closure-over-budget stream generators, and
    non-dispatch heartbeat polls with static bounds: all sanctioned."""
    assert lint_file("c9_neg.py") == []


# -------------------------------- C10: EDL104 donated-buffer aliasing


def test_c10_positive_flags_read_after_donation():
    findings = lint_file("c10_pos.py")
    assert rule_ids(findings) == ["EDL104"] * 2, findings
    assert {(f.scope, f.detail) for f in findings} == {
        ("train_loop", "state"),
        ("apply_updates", "opt_state"),
    }


def test_c10_negative_rebind_idioms_are_clean():
    assert lint_file("c10_neg.py") == []


def test_new_rules_pragma_suppression(tmp_path):
    """The pragma layer applies to CFG-based rules like any other."""
    src = os.path.join(FIXTURES, "c7_pos.py")
    with open(src) as f:
        text = f.read()
    text = text.replace(
        "return dict(self._entries), self._inflight",
        "return dict(self._entries), self._inflight"
        "  # edl-lint: disable=EDL004",
    )
    mod = tmp_path / "pragma_mod.py"
    mod.write_text(text)
    findings, errors = run_rules([str(mod)], root=None, excludes=())
    assert not errors
    assert {(f.scope, f.detail) for f in findings} == {
        ("Registry.reset", "_inflight"),
    }


# --------------------------------------------------- every-rule coverage


def test_every_rule_has_fixture_coverage():
    """Meta-test: the fixture battery above exercises every registered
    rule id positively, and every checker has a clean fixture."""
    emitted = set()
    for name in ("c1_pos.py", "c2_pos.py", "c3_pos.py", "c5_pos.py",
                 "c6_pos.py", "c7_pos.py", "c8_pos.py", "c9_pos.py",
                 "c10_pos.py", "c11_pos.py", "c12_pos.py",
                 "c13_pos.py"):
        emitted.update(f.rule for f in lint_file(name))
    ast_rule_ids = set()
    for rule in all_rules():
        ast_rule_ids.update(RULE_FAMILIES[rule.id])
    # EDL301 is repo-level, covered by the proto tests below
    assert emitted == ast_rule_ids - {"EDL301"}


# -------------------------------------------------------- baseline gate


def test_baseline_round_trip(tmp_path):
    src = os.path.join(FIXTURES, "c1_pos.py")
    findings, _ = run_rules([src], root=None, excludes=())
    assert findings
    base_path = str(tmp_path / "baseline.json")
    Baseline.from_findings(
        findings, reason="vetted in test", path=base_path
    ).save()

    reloaded = Baseline.load(base_path)
    remaining, stale = reloaded.apply(findings)
    assert remaining == [] and stale == []

    # deleting any one entry un-suppresses its finding
    with open(base_path) as f:
        data = json.load(f)
    dropped = data["entries"].pop(0)
    with open(base_path, "w") as f:
        json.dump(data, f)
    remaining, stale = Baseline.load(base_path).apply(findings)
    assert len(remaining) >= 1 and stale == []
    assert any(
        (f.rule, f.scope, f.detail)
        == (dropped["rule"], dropped["scope"], dropped["detail"])
        for f in remaining
    )


def test_stale_baseline_entry_fails():
    findings_fp_free = Baseline(entries=[{
        "rule": "EDL001", "path": "gone.py", "scope": "X.y",
        "detail": "_z", "reason": "the code this vetted was deleted",
    }])
    remaining, stale = findings_fp_free.apply([])
    assert remaining == [] and len(stale) == 1


def test_baseline_rejects_missing_reason():
    with pytest.raises(Exception):
        Baseline(entries=[{
            "rule": "EDL001", "path": "a.py", "scope": "X.y",
            "detail": "_z",
        }])


# ------------------------------------------------------------- CLI gate


def test_shipped_tree_is_clean():
    """The CI contract: `make lint`'s analyzer half exits 0 on the
    shipped tree with the checked-in baseline."""
    assert lint_main([]) == 0


def test_shipped_baseline_entries_are_all_live(tmp_path):
    """Deleting ANY entry from the shipped baseline makes the run fail:
    every entry suppresses a live finding (no rot)."""
    shipped = os.path.join(REPO_ROOT, ".edl-lint-baseline.json")
    with open(shipped) as f:
        data = json.load(f)
    assert data["entries"], "shipped baseline unexpectedly empty"
    for e in data["entries"]:
        assert e["reason"].strip(), "entry without justification: %r" % e
    pruned = str(tmp_path / "pruned.json")
    for i in range(len(data["entries"])):
        dropped = dict(data)
        dropped["entries"] = (
            data["entries"][:i] + data["entries"][i + 1:]
        )
        with open(pruned, "w") as f:
            json.dump(dropped, f)
        assert lint_main(["--baseline", pruned]) == 1, (
            "baseline entry %d (%s) is not live" % (i, data["entries"][i])
        )


def test_injected_fixture_snippet_fails(tmp_path):
    """Copying any triggering fixture into a linted source tree flips
    the gate to non-zero (with the shipped baseline)."""
    srcdir = tmp_path / "pkg"
    srcdir.mkdir()
    shutil.copy(
        os.path.join(FIXTURES, "c1_pos.py"),
        str(srcdir / "injected_module.py"),
    )
    rc = lint_main([
        str(srcdir),
        "--baseline", os.path.join(REPO_ROOT, ".edl-lint-baseline.json"),
        "--select", "EDL001",
    ])
    assert rc == 1


def test_select_limits_rules(tmp_path):
    srcdir = tmp_path / "pkg"
    srcdir.mkdir()
    shutil.copy(
        os.path.join(FIXTURES, "c1_pos.py"),
        str(srcdir / "injected_module.py"),
    )
    # only the jit family selected: the C1 violation is out of scope
    rc = lint_main([
        str(srcdir),
        "--baseline", str(tmp_path / "absent.json"),
        "--select", "EDL101",
    ])
    assert rc == 0


# ------------------------------------------------ driver modes (v2 CLI)


def test_parallel_jobs_output_parity():
    """--jobs fans per-file analysis over a process pool; findings
    must be byte-identical to the serial run (same order, same
    fingerprints) so CI can use either."""
    paths = [os.path.join(FIXTURES, n)
             for n in ("c1_pos.py", "c6_pos.py", "c8_pos.py",
                       "c9_pos.py", "c10_pos.py")]
    serial, es = run_rules(paths, root=None, excludes=(), jobs=1)
    fanned, ep = run_rules(paths, root=None, excludes=(), jobs=2)
    assert not es and not ep
    assert [f.format() for f in serial] == [f.format() for f in fanned]
    assert serial, "parity test needs a non-empty finding set"


def test_github_format_annotations(tmp_path, capsys):
    srcdir = tmp_path / "pkg"
    srcdir.mkdir()
    shutil.copy(
        os.path.join(FIXTURES, "c7_pos.py"),
        str(srcdir / "injected_module.py"),
    )
    rc = lint_main([
        str(srcdir),
        "--baseline", str(tmp_path / "absent.json"),
        "--select", "EDL004", "--format", "github",
    ])
    assert rc == 1
    out = capsys.readouterr().out
    lines = [ln for ln in out.splitlines() if ln.startswith("::error ")]
    assert len(lines) == 2
    assert "file=" in lines[0] and "line=" in lines[0]
    assert "title=EDL004" in lines[0]


def test_explicit_file_paths_respect_excludes(tmp_path):
    """--changed-only hands individual FILES to the runner; excluded
    paths (fixtures, generated pb2) must stay excluded even when
    named explicitly, or a fixture edit would fail the gate."""
    fixture = os.path.join(FIXTURES, "c1_pos.py")
    findings, errors = run_rules([fixture], root=None)  # default excludes
    assert findings == [] and errors == []


def test_changed_only_merge_base_diff(tmp_path):
    """changed_files returns tracked-modified plus untracked .py files
    vs the merge base, as absolute paths."""
    import subprocess

    from elasticdl_tpu.analysis.lint import changed_files

    repo = str(tmp_path / "repo")
    os.makedirs(repo)

    def git(*args):
        subprocess.run(
            ("git", "-C", repo) + args, check=True,
            capture_output=True,
            env={**os.environ,
                 "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
                 "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"},
        )

    git("init", "-b", "main")
    with open(os.path.join(repo, "a.py"), "w") as f:
        f.write("A = 1\n")
    with open(os.path.join(repo, "b.py"), "w") as f:
        f.write("B = 1\n")
    git("add", "-A")
    git("commit", "-m", "seed")
    with open(os.path.join(repo, "a.py"), "w") as f:
        f.write("A = 2\n")          # tracked, modified
    with open(os.path.join(repo, "c.py"), "w") as f:
        f.write("C = 1\n")          # untracked
    changed = changed_files(repo, base="main")
    assert changed == [
        os.path.join(repo, "a.py"), os.path.join(repo, "c.py"),
    ]


# ------------------------------------------------- C4: proto drift gate


def test_proto_regen_twice_is_byte_identical():
    """Determinism satellite: regenerating from the regenerated text
    yields identical bytes — field/table ordering is stable, so the
    drift gate can never flake."""
    from scripts.gen_serving_proto import generate_text

    once = generate_text()
    twice = generate_text(once)
    assert once == twice
    with open(os.path.join(
        REPO_ROOT, "elasticdl_tpu", "proto", "elasticdl_pb2.py"
    )) as f:
        assert f.read() == once, (
            "checked-in pb2 drifted: rerun scripts/gen_serving_proto.py"
        )


def test_proto_drift_detected_on_tampered_pb2(tmp_path):
    from elasticdl_tpu.analysis.proto_rules import ProtoDriftRule

    pb2 = os.path.join(
        REPO_ROOT, "elasticdl_tpu", "proto", "elasticdl_pb2.py"
    )
    with open(pb2) as f:
        text = f.read()
    tampered = str(tmp_path / "elasticdl_pb2.py")
    with open(tampered, "w") as f:
        f.write("# tampered by test\n" + text)
    findings = ProtoDriftRule().check_repo(REPO_ROOT, pb2_path=tampered)
    assert [f.rule for f in findings] == ["EDL301"]
    assert findings[0].detail == "drift"

    clean = ProtoDriftRule().check_repo(REPO_ROOT, pb2_path=pb2)
    assert clean == []
