"""Fine-tuning via Trainer(trainable_pattern=...): non-matching params
must not move AT ALL (including under adamw's decoupled weight decay),
matching params must train, and checkpoints/grad-accum compose."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from elasticdl_tpu.common.model_utils import load_model_spec_from_module
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.training.trainer import Trainer
from model_zoo.transformer_lm import transformer_lm as zoo

# CI drills shard (make test-drills): the sub-5-min per-commit gate excludes this file.
pytestmark = pytest.mark.slow

PARAMS = (
    "vocab_size=8; seq_len=16; embed_dim=32; num_heads=2; num_layers=2"
)


def _batch(seed=0):
    rs = np.random.RandomState(seed)
    s = rs.randint(0, 8, size=(8, 1))
    tok = ((s + np.arange(17)[None, :]) % 8).astype(np.int32)
    return {"tokens": tok[:, :-1]}, tok[:, 1:]


def _flat(params):
    out = {}

    def visit(node, prefix):
        if isinstance(node, dict):
            for k, v in node.items():
                visit(v, prefix + (str(k),))
        else:
            out["/".join(prefix)] = np.asarray(node)

    visit(params, ())
    return out


@pytest.mark.parametrize("accum", [1, 2])
def test_frozen_params_do_not_move(accum):
    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = Trainer(
        load_model_spec_from_module(zoo), mesh=mesh,
        model_params=PARAMS,
        trainable_pattern="head|block_1",
        grad_accum_steps=accum,
    )
    state = trainer.init_state(_batch())
    before = _flat(state.params)
    for i in range(6 * accum):
        state, loss = trainer.train_step(state, _batch(seed=i))
    after = _flat(state.params)
    moved, still = [], []
    for k in before:
        if np.array_equal(before[k], after[k]):
            still.append(k)
        else:
            moved.append(k)
    # the head and last block train; embeddings and block_0 are frozen
    assert any("head" in k for k in moved)
    assert any("block_1" in k for k in moved)
    assert all("block_0" not in k for k in moved)
    assert all("wte" not in k and "wpe" not in k for k in moved)
    # adamw weight decay must not have nudged frozen tensors
    assert any("block_0" in k for k in still)
    assert np.isfinite(float(loss))


def test_finetune_learns_with_frozen_backbone():
    """Head-only fine-tuning still reduces loss on the cycle data (the
    embeddings are random but fixed; the head can fit next-token for a
    tiny vocab)."""
    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = Trainer(
        load_model_spec_from_module(zoo), mesh=mesh,
        model_params=PARAMS, trainable_pattern="head",
    )
    state = trainer.init_state(_batch())
    losses = []
    for i in range(200):
        state, loss = trainer.train_step(state, _batch(seed=i))
        losses.append(float(loss))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.9


def test_match_nothing_warns_and_freezes_all(caplog):
    from elasticdl_tpu.common.log_utils import default_logger

    # the project logger does not propagate to root; capture directly
    default_logger.addHandler(caplog.handler)
    try:
        mesh = mesh_lib.build_mesh({"dp": 1},
                                   devices=jax.devices()[:1])
        trainer = Trainer(
            load_model_spec_from_module(zoo), mesh=mesh,
            model_params=PARAMS, trainable_pattern="no_such_param",
        )
        state = trainer.init_state(_batch())
        before = _flat(state.params)
        for i in range(3):
            state, _ = trainer.train_step(state, _batch(seed=i))
        after = _flat(state.params)
    finally:
        default_logger.removeHandler(caplog.handler)
    assert all(np.array_equal(before[k], after[k]) for k in before)
    assert any("matches NOTHING" in r.getMessage()
               for r in caplog.records)


def test_lora_warm_start_and_adapter_training(tmp_path):
    """The LoRA fine-tuning story end to end: pretrain dense ->
    checkpoint -> warm-start a lora_rank model (strict=False; base
    Dense paths unchanged, lora_b zero-init => logits EQUAL the dense
    model's) -> train with trainable_pattern='lora' (only adapters
    move) -> loss falls."""
    from elasticdl_tpu.checkpoint.saver import (
        CheckpointSaver,
        restore_state_from_checkpoint,
    )

    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    dense = Trainer(load_model_spec_from_module(zoo), mesh=mesh,
                    model_params=PARAMS)
    d_state = dense.init_state(_batch())
    for i in range(20):
        d_state, _ = dense.train_step(d_state, _batch(seed=i))
    saver = CheckpointSaver(str(tmp_path), checkpoint_steps=1,
                            num_shards=2)
    saver.save(d_state, version=1)

    lora = Trainer(
        load_model_spec_from_module(zoo), mesh=mesh,
        model_params=PARAMS + "; lora_rank=4",
        trainable_pattern="lora",
    )
    l_state = lora.init_state(_batch())
    # strict restore must refuse (adapter leaves missing)
    with pytest.raises(ValueError, match="strict=False"):
        restore_state_from_checkpoint(l_state, str(tmp_path))
    l_state, version = restore_state_from_checkpoint(
        l_state, str(tmp_path), strict=False
    )
    assert version == 1
    # zero-init lora_b => warm-started logits == dense logits exactly
    feats, _ = _batch(seed=99)
    ld = dense.model.apply({"params": d_state.params}, feats)
    ll = lora.model.apply({"params": l_state.params}, feats)
    np.testing.assert_allclose(np.asarray(ld), np.asarray(ll),
                               rtol=1e-6, atol=1e-7)

    before = _flat(l_state.params)
    losses = []
    for i in range(60):
        l_state, loss = lora.train_step(l_state, _batch(seed=i))
        losses.append(float(loss))
    after = _flat(l_state.params)
    for k in before:
        if "lora" in k:
            if "lora_b" in k or "lora_a" in k:
                continue  # movement asserted collectively below
        else:
            np.testing.assert_array_equal(
                before[k], after[k], err_msg="%s moved" % k
            )
    assert any(
        "lora" in k and not np.array_equal(before[k], after[k])
        for k in before
    )
    assert np.mean(losses[-5:]) < np.mean(losses[:5])


def test_pattern_refuses_unfrozen_sparse_tier():
    """trainable_pattern freezes the dense path only; a sparse-tapped
    embedding table NOT covered by the pattern must be refused, not
    silently left training."""
    import optax
    from flax import linen as nn

    from elasticdl_tpu.common.model_utils import ModelSpec
    from elasticdl_tpu.embedding.layer import Embedding

    class Rec(nn.Module):
        @nn.compact
        def __call__(self, features, training=False):
            emb = Embedding(input_dim=64, output_dim=8, combiner="sum",
                            sparse_grads=True, name="cat")(
                features["ids"])
            return nn.Dense(1, name="out")(emb)[:, 0]

    def _loss(labels, predictions, weights=None):
        import jax.numpy as jnp2
        per = optax.sigmoid_binary_cross_entropy(
            predictions, labels.astype(jnp2.float32))
        return jnp2.mean(per)

    spec = ModelSpec(
        model_fn=Rec, dataset_fn=lambda ds, mode, meta: ds,
        loss=_loss, optimizer=lambda: optax.adam(1e-3),
        eval_metrics_fn=lambda: {},
    )
    rs = np.random.RandomState(0)
    batch = (
        {"ids": rs.randint(0, 16, size=(8, 4)).astype(np.int32)},
        rs.randint(0, 2, size=(8,)).astype(np.int32),
    )
    trainer = Trainer(spec, mesh=mesh_lib.local_mesh(),
                      trainable_pattern="out")
    with pytest.raises(NotImplementedError, match="sparse-row"):
        trainer.init_state(batch)
    # covering the table in the pattern is allowed
    trainer2 = Trainer(spec, mesh=mesh_lib.local_mesh(),
                       trainable_pattern="out|cat")
    state = trainer2.init_state(batch)
    state, loss = trainer2.train_step(state, batch)
    assert np.isfinite(float(loss))


def test_merge_lora_matches_adapter_model():
    """Folding trained adapters into the base kernels yields a PLAIN
    dense model with the same outputs — the serving export."""
    from elasticdl_tpu.api.finetune import merge_lora

    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    lora = Trainer(
        load_model_spec_from_module(zoo), mesh=mesh,
        model_params=PARAMS + "; lora_rank=4",
        trainable_pattern="lora",
    )
    state = lora.init_state(_batch())
    for i in range(30):
        state, _ = lora.train_step(state, _batch(seed=i))
    merged = merge_lora(state.params, model=lora.model)
    # structure now matches the dense model exactly
    dense = Trainer(load_model_spec_from_module(zoo), mesh=mesh,
                    model_params=PARAMS)
    d_state = dense.init_state(_batch())
    assert (
        jax.tree_util.tree_structure(jax.tree.map(lambda x: 0, merged))
        == jax.tree_util.tree_structure(
            jax.tree.map(lambda x: 0, d_state.params))
    )
    feats, _ = _batch(seed=77)
    out_lora = lora.model.apply({"params": state.params}, feats)
    out_merged = dense.model.apply({"params": merged}, feats)
    np.testing.assert_allclose(np.asarray(out_lora),
                               np.asarray(out_merged),
                               rtol=2e-5, atol=2e-6)
    # incomplete pair / missing base validations
    import pytest as _pytest
    bad = {"attn": {"qkv_lora_a": np.zeros((4, 2), np.float32)}}
    with _pytest.raises(ValueError, match="incomplete"):
        merge_lora(bad, lora_alpha=16.0)
    bad2 = {"qkv_lora_a": np.zeros((4, 2), np.float32),
            "qkv_lora_b": np.zeros((2, 8), np.float32)}
    with _pytest.raises(ValueError, match="base kernel"):
        merge_lora(bad2, lora_alpha=16.0)
    with _pytest.raises(ValueError, match="lora_alpha"):
        merge_lora(state.params)
    with _pytest.raises(ValueError, match="contradicts"):
        merge_lora(state.params, model=lora.model, lora_alpha=32.0)


def test_bert_lora_adapters_train():
    """The encoder family takes LoRA too (Block is shared): adapter
    params exist, the zero-init merge reproduces the model, and under
    trainable_pattern='lora' ONLY the adapters move in training."""
    import optax

    from elasticdl_tpu.api.finetune import merge_lora
    from elasticdl_tpu.common.model_utils import ModelSpec
    from model_zoo.bert.bert import BertEncoder, loss as bert_loss

    bert_params = ("vocab_size=32; seq_len=16; embed_dim=32; "
                   "num_heads=2; num_layers=1; tp_shard=False; "
                   "lora_rank=4")
    spec = ModelSpec(
        model_fn=lambda **kw: BertEncoder(**kw),
        dataset_fn=lambda ds, mode, meta: ds,
        loss=bert_loss,
        optimizer=lambda: optax.adamw(1e-3, weight_decay=0.01),
        eval_metrics_fn=lambda: {},
    )
    mesh = mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    trainer = Trainer(spec, mesh=mesh, model_params=bert_params,
                      trainable_pattern="lora")
    rs = np.random.RandomState(0)
    toks = rs.randint(0, 32, size=(4, 16)).astype(np.int32)
    labels = np.where(rs.rand(4, 16) < 0.3, toks, -1).astype(np.int32)
    batch = ({"tokens": jnp.asarray(toks)}, jnp.asarray(labels))
    state = trainer.init_state(batch)
    flat0 = _flat(state.params)
    assert sum("lora" in k for k in flat0) == 4  # qkv+proj a/b
    # zero-init adapters: merged dense encoder == lora encoder
    dense = BertEncoder(vocab_size=32, seq_len=16, embed_dim=32,
                        num_heads=2, num_layers=1, tp_shard=False)
    merged = merge_lora(state.params, model=trainer.model)
    out = trainer.model.apply({"params": state.params},
                              {"tokens": batch[0]["tokens"]})
    out_merged = dense.apply({"params": merged},
                             {"tokens": batch[0]["tokens"]})
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_merged),
                               rtol=2e-5, atol=2e-6)
    # adapter-only training
    for i in range(5):
        state, loss_val = trainer.train_step(state, batch)
    flat1 = _flat(state.params)
    for k in flat0:
        if "lora" not in k:
            np.testing.assert_array_equal(flat0[k], flat1[k],
                                          err_msg="%s moved" % k)
    assert any(
        "lora" in k and not np.array_equal(flat0[k], flat1[k])
        for k in flat0
    )
    assert np.isfinite(float(loss_val))
