"""Rollout controller (serving/rollout.py): canary judgment and the
journaled wave state machine, gRPC-free.

The fleet here is fakes — replicas are (address, model_version) records,
swap_fn mutates them, generate_fn derives tokens from the version a
replica currently serves — so every test isolates exactly one claim:

* the burn-verdict matrix (pass / fast-burn fail / slow-burn-only pass)
  and the parity matrix;
* judgment wiring: parity mismatch rolls the canary back, a fast burn
  rolls it back, and sustained silence (the judge path erroring) is
  itself a verdict — no promotion past judge_timeout_secs;
* an SLO alert during a progressive wave pauses the rollout and rolls
  every swapped replica back in REVERSE swap order;
* journal replay: a controller abandoned (SIGKILL stand-in) mid-canary,
  mid-wave, or mid-rollback resumes from the journal and finishes with
  every replica swapped exactly once — the no-double-swap invariant.

The real-RPC, real-subprocess version of the same claims is the rollout
drill (scripts/run_rollout_drill.py).
"""

import numpy as np
import pytest

from elasticdl_tpu.checkpoint import CheckpointSaver
from elasticdl_tpu.serving import rollout
from elasticdl_tpu.serving.rollout import (
    CheckpointStager,
    RolloutConfig,
    RolloutController,
    burn_verdict,
    parity_verdict,
    parse_parity_prompts,
    wave_alerting,
)

OLD, NEW = 1, 2


class FakeReplica(object):
    def __init__(self, address, model_version=OLD):
        self.address = address
        self.model_version = model_version
        self.reload_failed = False


class FakeRouter(object):
    def __init__(self, addrs):
        self.fleet = {a: FakeReplica(a) for a in addrs}
        self.reports = []
        self._held = set()

    def replicas(self):
        return list(self.fleet.values())

    def slo_reports(self):
        return list(self.reports)

    def hold_replica(self, address):
        self._held.add(address)

    def release_replica(self, address):
        self._held.discard(address)

    def held_replicas(self):
        return set(self._held)


class FakeClock(object):
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def make_swap(router, calls, fail_addrs=()):
    def swap(addr, version):
        calls.append((addr, version))
        if addr in fail_addrs:
            return False, router.fleet[addr].model_version, "injected"
        router.fleet[addr].model_version = version
        return True, version, ""

    return swap


def make_generate(router, poisoned=False, broken=False):
    """Greedy generation as a pure function of (prompt, served
    version): the healthy new version reproduces the old version's
    tokens (same lineage), the poisoned one drifts."""

    def generate(addr, prompt, max_tokens):
        v = router.fleet[addr].model_version
        if broken and v != OLD:
            # only the post-swap judge path is down; the baseline
            # (recorded while the canary still serves OLD) works
            raise RuntimeError("judge path down")
        if poisoned and v != OLD:
            return [999] * len(prompt)
        return [t + 1 for t in prompt]

    return generate


def make_checkpoint(tmp_path, versions=(NEW,)):
    saver = CheckpointSaver(str(tmp_path), checkpoint_steps=1,
                            num_shards=2)
    for v in versions:
        saver.save({"w": np.arange(8, dtype=np.float32) * v}, version=v)
    return str(tmp_path)


def make_controller(tmp_path, addrs=("a:1", "b:1", "c:1"),
                    journal=False, poisoned=False, broken=False,
                    fail_addrs=(), **cfg_kwargs):
    router = FakeRouter(addrs)
    clock = FakeClock()
    calls = []
    cfg = RolloutConfig(
        checkpoint_dir=make_checkpoint(tmp_path / "ckpt"),
        journal_dir=str(tmp_path / "journal") if journal else "",
        soak_secs=3.0, judge_timeout_secs=20.0,
        parity_prompts=((1, 2, 3), (4, 5)),
        **cfg_kwargs,
    )
    ctl = RolloutController(
        router, cfg, clock=clock,
        swap_fn=make_swap(router, calls, fail_addrs=fail_addrs),
        generate_fn=make_generate(router, poisoned=poisoned,
                                  broken=broken),
    )
    return ctl, router, clock, calls


def drive(ctl, clock, max_ticks=100, dt=1.0):
    for _ in range(max_ticks):
        ctl.decide_once()
        if ctl.phase in rollout.TERMINAL:
            return ctl.phase
        clock.advance(dt)
    raise AssertionError("no terminal phase, stuck at %s" % ctl.phase)


def fleet_versions(router):
    return {a: r.model_version for a, r in router.fleet.items()}


# ----------------------------------------------- judgment matrices


def report(fast=0.0, slow=0.0, fast_samples=10, alerting=False):
    return {"name": "ttft_p99", "fast_burn": fast, "slow_burn": slow,
            "fast_samples": fast_samples, "slow_samples": 10,
            "alerting": alerting}


def test_burn_verdict_clean_passes():
    failed, _ = burn_verdict([report(fast=0.4, slow=0.2)])
    assert not failed


def test_burn_verdict_fast_burn_fails():
    failed, reason = burn_verdict([report(fast=2.5, slow=0.2)])
    assert failed
    assert "ttft_p99" in reason


def test_burn_verdict_slow_burn_only_passes():
    # the slow window averages over history the canary never touched:
    # a rollout that follows a rough patch must still be judgeable
    failed, _ = burn_verdict([report(fast=0.3, slow=4.0)])
    assert not failed


def test_burn_verdict_unsampled_fast_window_is_silent():
    failed, _ = burn_verdict([report(fast=9.0, fast_samples=0)])
    assert not failed


def test_wave_alerting_requires_both_windows():
    assert wave_alerting([report(fast=2.0, slow=0.1)]) == []
    assert wave_alerting(
        [report(fast=2.0, slow=2.0, alerting=True)]
    ) == ["ttft_p99"]


def test_parity_verdict_exact_match_passes():
    failed, matched, total = parity_verdict([[1, 2], [3]], [[1, 2], [3]])
    assert (failed, matched, total) == (False, 2, 2)


def test_parity_verdict_drift_fails():
    failed, matched, total = parity_verdict([[1, 2], [3]], [[1, 2], [9]])
    assert failed and (matched, total) == (1, 2)


def test_parity_verdict_min_match_knob():
    failed, _, _ = parity_verdict([[1], [2]], [[1], [9]], min_match=0.5)
    assert not failed


def test_parse_parity_prompts_grammar():
    assert parse_parity_prompts("1,2,3; 4,5 ;") == ((1, 2, 3), (4, 5))
    assert parse_parity_prompts("") == ()


# ----------------------------------------------- checkpoint staging


def test_stager_pair_and_corrupt_checkpoint(tmp_path):
    ckpt = make_checkpoint(tmp_path, versions=(NEW,))
    stager = CheckpointStager(ckpt)
    assert stager.stage_checkpoint(NEW)
    manifest = stager.activate()
    assert manifest["version"] == NEW
    assert manifest["verified_digests"] == manifest["num_shards"] == 2
    # a staged version that does not exist discards with the error
    assert not stager.stage_checkpoint(99)
    assert isinstance(stager.discard(), Exception)
    with pytest.raises(RuntimeError):
        stager.activate()


def test_corrupt_checkpoint_aborts_before_any_swap(tmp_path):
    ctl, router, clock, calls = make_controller(tmp_path)
    shard = (tmp_path / "ckpt" / ("version-%d" % NEW)
             / "variables-0-of-2.ckpt")
    shard.write_bytes(shard.read_bytes()[:-7])  # torn write
    assert ctl.begin(NEW)
    assert drive(ctl, clock) == rollout.ABORTED
    assert calls == []  # zero fleet impact: no replica ever swapped
    assert fleet_versions(router) == {a: OLD for a in router.fleet}
    assert "verification" in ctl.last_error


# ----------------------------------------------- happy path


def test_healthy_rollout_commits_canary_first(tmp_path):
    ctl, router, clock, calls = make_controller(tmp_path)
    assert ctl.begin(NEW)
    assert drive(ctl, clock) == rollout.COMMITTED
    assert fleet_versions(router) == {a: NEW for a in router.fleet}
    # canary (lowest address) swaps first, then waves in plan order,
    # and nothing swaps twice
    assert calls == [("a:1", NEW), ("b:1", NEW), ("c:1", NEW)]
    assert ctl.verdict == "pass"
    assert ctl.swapped == ["a:1", "b:1", "c:1"]
    block = ctl.status_block()
    assert block.phase == "committed"
    assert block.swapped == block.fleet == 3
    assert block.waves_total == 3  # canary + two waves of 1


def test_already_serving_replica_is_not_reswapped(tmp_path):
    ctl, router, clock, calls = make_controller(tmp_path)
    router.fleet["b:1"].model_version = NEW  # converged out of band
    assert ctl.begin(NEW)
    assert drive(ctl, clock) == rollout.COMMITTED
    assert ("b:1", NEW) not in calls  # recognized, not repeated
    assert ctl.swapped == ["a:1", "b:1", "c:1"]


def test_begin_guards(tmp_path):
    ctl, router, clock, _ = make_controller(tmp_path)
    router.fleet.clear()
    assert not ctl.begin(NEW)
    assert "no replicas" in ctl.last_error
    router.fleet["a:1"] = FakeReplica("a:1")
    assert ctl.begin(NEW)
    assert not ctl.begin(NEW)  # already in flight
    assert "in flight" in ctl.last_error


# ----------------------------------------------- failed judgment


def test_parity_mismatch_rolls_canary_back(tmp_path):
    ctl, router, clock, calls = make_controller(tmp_path, poisoned=True)
    assert ctl.begin(NEW)
    assert drive(ctl, clock) == rollout.ROLLED_BACK
    assert ctl.verdict == "parity_fail"
    # the canary swapped up, drifted on the pinned prompts, and came
    # back down; the rest of the fleet never left the old version
    assert calls == [("a:1", NEW), ("a:1", OLD)]
    assert fleet_versions(router) == {a: OLD for a in router.fleet}
    assert ctl.rollbacks == 1


def test_fast_burn_during_judging_rolls_back(tmp_path):
    ctl, router, clock, calls = make_controller(tmp_path)
    router.reports = [report(fast=3.0, slow=0.1)]
    assert ctl.begin(NEW)
    assert drive(ctl, clock) == rollout.ROLLED_BACK
    assert ctl.verdict == "burn_fail"
    assert fleet_versions(router) == {a: OLD for a in router.fleet}


def test_judge_timeout_is_a_verdict(tmp_path):
    # the judge path itself erroring yields NO evidence tick after
    # tick; sustained silence must not promote — the timeout converts
    # it into a rollback
    ctl, router, clock, calls = make_controller(tmp_path, broken=True)
    assert ctl.begin(NEW)
    # staging records the baseline BEFORE the judge path breaks
    ctl.decide_once()
    assert ctl.phase == rollout.CANARY
    assert drive(ctl, clock) == rollout.ROLLED_BACK
    assert ctl.verdict == "timeout"
    assert fleet_versions(router) == {a: OLD for a in router.fleet}


def test_judge_timeout_baseline_break_aborts(tmp_path):
    # broken from the start: the baseline itself cannot be recorded,
    # so staging aborts with the fleet untouched
    ctl, router, clock, calls = make_controller(tmp_path)

    def down(addr, prompt, max_tokens):
        raise RuntimeError("generation down")

    ctl._generate_fn = down
    assert ctl.begin(NEW)
    assert drive(ctl, clock) == rollout.ABORTED
    assert calls == []


def test_wave_alert_pauses_and_reverse_rolls(tmp_path):
    ctl, router, clock, calls = make_controller(tmp_path)
    assert ctl.begin(NEW)
    # let the canary pass judgment cleanly, then trip the pager the
    # moment a progressive wave is soaking
    for _ in range(100):
        ctl.decide_once()
        if ctl.phase in rollout.TERMINAL:
            break
        if ctl.phase == rollout.WAVE and len(ctl.swapped) == 2:
            router.reports = [report(fast=2.0, slow=2.0, alerting=True)]
        clock.advance(1.0)
    assert ctl.phase == rollout.ROLLED_BACK
    # rollback is REVERSE swap order: the wave member first, the
    # canary (longest on the new version) last
    assert calls == [("a:1", NEW), ("b:1", NEW),
                     ("b:1", OLD), ("a:1", OLD)]
    assert fleet_versions(router) == {a: OLD for a in router.fleet}
    assert "SLO burn alert" in ctl.last_error
    assert ctl.rollbacks == 2


def test_canary_swap_failure_aborts_without_rollback(tmp_path):
    ctl, router, clock, calls = make_controller(
        tmp_path, fail_addrs=("a:1",)
    )
    assert ctl.begin(NEW)
    assert drive(ctl, clock) == rollout.ABORTED
    # nothing ever swapped, so there is nothing to roll back
    assert fleet_versions(router) == {a: OLD for a in router.fleet}
    assert ctl.swapped == []


# ----------------------------------------------- journal replay


def resume(tmp_path, ctl, router, **kwargs):
    """A fresh controller over the same journal — the post-SIGKILL
    process. abandon() (not stop()) first: nothing journals on the
    way down, exactly like a kill."""
    ctl.abandon()
    calls = []
    cfg = RolloutConfig(
        checkpoint_dir=str(tmp_path / "ckpt"),
        journal_dir=str(tmp_path / "journal"),
        soak_secs=3.0, judge_timeout_secs=20.0,
        parity_prompts=((1, 2, 3), (4, 5)),
    )
    clock = FakeClock()
    ctl2 = RolloutController(
        router, cfg, clock=clock,
        swap_fn=make_swap(router, calls),
        generate_fn=make_generate(router, **kwargs),
    )
    return ctl2, clock, calls


def test_resume_mid_canary_does_not_double_swap(tmp_path):
    ctl, router, clock, calls = make_controller(tmp_path, journal=True)
    assert ctl.begin(NEW)
    ctl.decide_once()  # staging
    ctl.decide_once()  # canary swap lands, then the controller dies
    assert ctl.phase == rollout.JUDGING
    ctl2, clock2, calls2 = resume(tmp_path, ctl, router)
    assert ctl2.phase == rollout.JUDGING
    assert ctl2.rollout_restarts == 1
    assert drive(ctl2, clock2) == rollout.COMMITTED
    # the canary's swap happened in the FIRST life only
    assert calls == [("a:1", NEW)]
    assert calls2 == [("b:1", NEW), ("c:1", NEW)]
    assert fleet_versions(router) == {a: NEW for a in router.fleet}


def test_resume_mid_wave_finishes_single_swap(tmp_path):
    ctl, router, clock, calls = make_controller(tmp_path, journal=True)
    assert ctl.begin(NEW)
    for _ in range(100):
        ctl.decide_once()
        if ctl.phase == rollout.WAVE and len(ctl.swapped) == 2:
            break
        clock.advance(1.0)
    ctl2, clock2, calls2 = resume(tmp_path, ctl, router)
    assert drive(ctl2, clock2) == rollout.COMMITTED
    both = calls + calls2
    # every replica reloaded exactly once across both lives
    assert sorted(both) == [("a:1", NEW), ("b:1", NEW), ("c:1", NEW)]
    assert fleet_versions(router) == {a: NEW for a in router.fleet}


def test_resume_mid_wave_recognizes_landed_swap(tmp_path):
    """The kill window between journaling swap_start and swap_done:
    the reload LANDED on the replica but the journal never heard. The
    resumed controller must reconcile against the replica's advertised
    version instead of reloading it a second time."""
    ctl, router, clock, calls = make_controller(tmp_path, journal=True)
    assert ctl.begin(NEW)
    for _ in range(100):
        ctl.decide_once()
        if ctl.phase == rollout.WAVE and len(ctl.swapped) == 2:
            break
        clock.advance(1.0)
    # simulate the torn swap: b's reload landed, journal says otherwise
    router.fleet["c:1"].model_version = NEW
    ctl2, clock2, calls2 = resume(tmp_path, ctl, router)
    assert drive(ctl2, clock2) == rollout.COMMITTED
    assert calls2 == []  # recognized via the heartbeat, not re-issued
    assert ctl2.swapped == ["a:1", "b:1", "c:1"]


def test_resume_mid_rollback_finishes_rollback(tmp_path):
    ctl, router, clock, calls = make_controller(
        tmp_path, journal=True, poisoned=True
    )
    assert ctl.begin(NEW)
    for _ in range(100):
        ctl.decide_once()
        if ctl.phase == rollout.ROLLING_BACK:
            break
        clock.advance(1.0)
    # judged parity_fail, rollback journaled but not yet executed —
    # the canary still serves the poisoned version at the kill
    assert fleet_versions(router)["a:1"] == NEW
    ctl2, clock2, calls2 = resume(tmp_path, ctl, router, poisoned=True)
    assert ctl2.phase == rollout.ROLLING_BACK
    assert drive(ctl2, clock2) == rollout.ROLLED_BACK
    assert calls2 == [("a:1", OLD)]
    assert fleet_versions(router) == {a: OLD for a in router.fleet}
    assert ctl2.verdict == "parity_fail"
    assert ctl2.rollbacks == 1


def test_resume_terminal_rollout_stays_terminal(tmp_path):
    ctl, router, clock, calls = make_controller(tmp_path, journal=True)
    assert ctl.begin(NEW)
    assert drive(ctl, clock) == rollout.COMMITTED
    ctl2, clock2, calls2 = resume(tmp_path, ctl, router)
    assert ctl2.phase == rollout.COMMITTED
    # a restart re-passing the same --rollout over a committed journal
    ctl2.request(NEW)
    ctl2.decide_once()
    assert ctl2.phase == rollout.COMMITTED
    assert calls2 == []


def test_deferred_request_waits_for_fleet(tmp_path):
    ctl, router, clock, calls = make_controller(tmp_path)
    fleet = dict(router.fleet)
    router.fleet.clear()
    ctl.request(NEW)
    ctl.decide_once()
    assert ctl.phase == rollout.IDLE  # nothing registered yet
    router.fleet.update(fleet)
    assert drive(ctl, clock) == rollout.COMMITTED
    assert fleet_versions(router) == {a: NEW for a in router.fleet}
