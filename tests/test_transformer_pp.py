"""Pipeline-parallel transformer family: pp>1 training matches the
single-device model exactly, stage params shard over pp (moments too),
and the family trains through the standard Trainer."""

import numpy as np

import jax
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.common.constants import MeshAxis
from elasticdl_tpu.common.model_utils import (
    format_params_str,
    load_model_spec_from_module,
)
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.training.trainer import Trainer

import pytest

# CI drills shard (make test-drills): the sub-5-min per-commit gate excludes this file.
pytestmark = pytest.mark.slow

CFG = dict(vocab_size=64, seq_len=16, embed_dim=32, num_heads=4,
           num_layers=4, num_microbatches=2)


def _trainer(mesh, extra=None):
    from model_zoo.transformer_pp import transformer_pp as zoo

    cfg = dict(CFG)
    if extra:
        cfg.update(extra)
    return Trainer(
        load_model_spec_from_module(zoo),
        mesh=mesh,
        model_params=format_params_str(cfg),
    )


def _batch(batch=8, seed=0):
    rng = np.random.RandomState(seed)
    tokens = rng.randint(
        0, CFG["vocab_size"], size=(batch, CFG["seq_len"] + 1)
    ).astype(np.int32)
    return ({"tokens": tokens[:, :-1]}, tokens[:, 1:])


def test_stage_params_sharded_over_pp():
    mesh = mesh_lib.build_mesh({"pp": 4, "dp": 2})
    trainer = _trainer(mesh)
    state = trainer.init_state(_batch())
    qkv = state.params["blk_qkv_w"]
    assert qkv.sharding.spec == P(MeshAxis.PP, None, None)
    # each device holds its contiguous layer chunk (4 layers / 4 stages)
    assert qkv.sharding.shard_shape(qkv.shape)[0] == 1

    # optimizer moments co-shard (annotation suffix matching)
    specs = []

    def check(path, leaf):
        keys = tuple(
            str(getattr(k, "key", getattr(k, "name", k))) for k in path
        )
        if keys[-1:] == ("blk_qkv_w",) and hasattr(leaf, "sharding"):
            specs.append(leaf.sharding.spec)

    jax.tree_util.tree_map_with_path(check, state.opt_state)
    assert len(specs) >= 2
    assert all(s == P(MeshAxis.PP, None, None) for s in specs)


def test_pp_loss_matches_single_device():
    batch = _batch()
    single = _trainer(
        mesh_lib.build_mesh({"dp": 1}, devices=jax.devices()[:1])
    )
    s_state = single.init_state(batch)

    pp = _trainer(mesh_lib.build_mesh({"pp": 4, "dp": 2}))
    p_state = pp.init_state(batch)

    for a, b in zip(jax.tree.leaves(s_state.params),
                    jax.tree.leaves(p_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6)

    losses_s, losses_p = [], []
    for _ in range(3):
        s_state, ls = single.train_step(s_state, batch)
        p_state, lp = pp.train_step(p_state, batch)
        losses_s.append(float(ls))
        losses_p.append(float(lp))
    np.testing.assert_allclose(losses_p, losses_s, rtol=1e-5, atol=1e-6)


def test_pp_interleaved_schedule_matches_gpipe():
    """Same params (converted to ring layout), same batch -> identical
    loss under both schedules; training stays finite and in lockstep."""
    from elasticdl_tpu.parallel.pipeline import (
        convert_params_to_interleaved,
    )

    cfg = {"num_layers": 8, "num_microbatches": 4}
    batch = _batch()  # batch 8 over dp=2 -> per-device 4 = M
    mesh = mesh_lib.build_mesh({"pp": 4, "dp": 2})
    g = _trainer(mesh, extra=cfg)
    g_state = g.init_state(batch)

    i = _trainer(mesh, extra={
        **cfg, "pp_schedule": "interleaved", "pp_interleave": 2,
    })
    i_state = i.init_state(batch)
    i_state = i_state.replace(params=convert_params_to_interleaved(
        g_state.params, 4, 2, like=i_state.params))

    losses_g, losses_i = [], []
    for _ in range(3):
        g_state, lg = g.train_step(g_state, batch)
        i_state, li = i.train_step(i_state, batch)
        losses_g.append(float(lg))
        losses_i.append(float(li))
    np.testing.assert_allclose(losses_i, losses_g, rtol=1e-5, atol=1e-6)


def test_pp_remat_matches_plain():
    """pp_remat (per-microbatch activation staging) is numerics-neutral."""
    batch = _batch()
    mesh = mesh_lib.build_mesh({"pp": 4, "dp": 2})
    plain = _trainer(mesh)
    r = _trainer(mesh, extra={"pp_remat": True})
    p_state = plain.init_state(batch)
    r_state = r.init_state(batch)
    _, lp = plain.train_step(p_state, batch)
    _, lr = r.train_step(r_state, batch)
    np.testing.assert_allclose(float(lr), float(lp), rtol=1e-6)


def test_pp_composes_with_microbatch_counts():
    batch = _batch(batch=16)  # dp=4 -> per-device 4, divisible by all m
    ref = None
    for m in (1, 2, 4):
        trainer = _trainer(
            mesh_lib.build_mesh({"pp": 2, "dp": 4}),
            extra={"num_microbatches": m},
        )
        state = trainer.init_state(batch)
        state, loss = trainer.train_step(state, batch)
        if ref is None:
            ref = float(loss)
        else:
            np.testing.assert_allclose(float(loss), ref, rtol=1e-5)
