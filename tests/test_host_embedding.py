"""Host-DRAM embedding store + host-spill engine: native C++ store vs
the numpy fallback vs hand-computed updates (the reference tests its
Eigen kernels the same way, go/pkg/kernel/kernel_test.go)."""

import numpy as np
import pytest

from elasticdl_tpu.embedding.host_spill import HostSpillEmbeddingEngine
from elasticdl_tpu.native import host_embedding
from elasticdl_tpu.native.host_embedding import HostEmbeddingStore

DIM = 8

BACKENDS = [True]  # force_python
if host_embedding.available():
    BACKENDS.append(False)


@pytest.fixture(params=BACKENDS, ids=lambda p: "py" if p else "native")
def force_python(request):
    return request.param


def test_native_library_built():
    """Informational gate: skip (not fail) when the .so is absent — the
    numpy-fallback parametrization still covers the semantics (precedent:
    tests/test_native_recordio.py). Build with
    `make -C elasticdl_tpu/native`."""
    if not host_embedding.available():
        pytest.skip("libhostembedding.so not built")


def test_lazy_init_bounds_and_determinism(force_python):
    store = HostEmbeddingStore(DIM, seed=3, force_python=force_python)
    rows = store.lookup([5, 9, 5])
    assert rows.shape == (3, DIM)
    assert np.all(rows >= -0.05) and np.all(rows <= 0.05)
    # same id -> same row; repeat lookup stable
    np.testing.assert_array_equal(rows[0], rows[2])
    np.testing.assert_array_equal(store.lookup([5])[0], rows[0])
    assert len(store) == 2
    # a fresh store with the same seed initializes identically
    store2 = HostEmbeddingStore(DIM, seed=3, force_python=force_python)
    np.testing.assert_array_equal(store2.lookup([9])[0], rows[1])


def test_native_and_python_agree_on_updates():
    """Both backends produce identical SGD math given identical rows."""
    if not host_embedding.available():
        pytest.skip("native lib not built")
    ids = [1, 2, 3]
    grads = np.random.RandomState(0).rand(3, DIM).astype(np.float32)
    stores = []
    for force in (False, True):
        store = HostEmbeddingStore(DIM, seed=1, force_python=force)
        rows = np.arange(3 * DIM, dtype=np.float32).reshape(3, DIM)
        store.set_rows(ids, rows)
        store.sgd(ids, grads, lr=0.5)
        stores.append(store.lookup(ids))
    np.testing.assert_allclose(stores[0], stores[1], rtol=1e-6)


def test_sgd_update(force_python):
    store = HostEmbeddingStore(DIM, force_python=force_python)
    base = store.lookup([7]).copy()
    g = np.ones((1, DIM), np.float32)
    store.sgd([7], g, lr=0.1)
    np.testing.assert_allclose(
        store.lookup([7]), base - 0.1, rtol=1e-6
    )


def test_adam_update_matches_reference(force_python):
    store = HostEmbeddingStore(DIM, force_python=force_python)
    m = HostEmbeddingStore(DIM, init_low=0, init_high=0,
                           force_python=force_python)
    v = HostEmbeddingStore(DIM, init_low=0, init_high=0,
                           force_python=force_python)
    p0 = store.lookup([4]).copy()
    g = np.full((1, DIM), 0.5, np.float32)
    store.adam(m, v, [4], g, lr=0.01, step=1)
    exp_m = 0.1 * g
    exp_v = 0.001 * g * g
    alpha = 0.01 * np.sqrt(1 - 0.999) / (1 - 0.9)
    exp_p = p0 - alpha * exp_m / (np.sqrt(exp_v) + 1e-8)
    np.testing.assert_allclose(store.lookup([4]), exp_p, rtol=1e-4)
    np.testing.assert_allclose(m.lookup([4]), exp_m, rtol=1e-4)
    np.testing.assert_allclose(v.lookup([4]), exp_v, rtol=1e-4)


def test_momentum_and_adagrad(force_python):
    p = HostEmbeddingStore(DIM, force_python=force_python)
    vel = HostEmbeddingStore(DIM, init_low=0, init_high=0,
                             force_python=force_python)
    p0 = p.lookup([1]).copy()
    g = np.full((1, DIM), 2.0, np.float32)
    p.momentum(vel, [1], g, lr=0.1, mu=0.9)
    np.testing.assert_allclose(p.lookup([1]), p0 - 0.2, rtol=1e-5)
    np.testing.assert_allclose(vel.lookup([1]), g, rtol=1e-6)

    pa = HostEmbeddingStore(DIM, force_python=force_python)
    accum = HostEmbeddingStore(DIM, init_low=0, init_high=0,
                               force_python=force_python)
    pa0 = pa.lookup([2]).copy()
    pa.adagrad(accum, [2], g, lr=0.1)
    exp = pa0 - 0.1 * g / (np.sqrt(g * g) + 1e-10)
    np.testing.assert_allclose(pa.lookup([2]), exp, rtol=1e-5)


def test_export_set_roundtrip(force_python):
    store = HostEmbeddingStore(DIM, force_python=force_python)
    store.lookup([10, 20, 30])
    ids, values = store.export_rows()
    assert sorted(ids.tolist()) == [10, 20, 30]
    store2 = HostEmbeddingStore(DIM, seed=99, force_python=force_python)
    store2.set_rows(ids, values)
    np.testing.assert_array_equal(
        store2.lookup(sorted(ids)), store.lookup(sorted(ids))
    )


# ------------------------------------------------------------- engine


def test_engine_pull_dedups(force_python):
    engine = HostSpillEmbeddingEngine(
        DIM, optimizer="sgd", force_python=force_python
    )
    ids = np.array([[3, 5], [5, 3]])
    unique_ids, rows, inverse = engine.pull(ids)
    assert unique_ids.tolist() == [3, 5]
    assert rows.shape == (2, DIM)
    assert inverse.shape == ids.shape
    np.testing.assert_array_equal(unique_ids[inverse], ids)


def test_engine_training_moves_only_touched_rows(force_python):
    engine = HostSpillEmbeddingEngine(
        DIM, optimizer="adam", lr=0.01, force_python=force_python
    )
    before = engine.param.lookup([1, 2, 3]).copy()
    unique_ids, rows, _ = engine.pull([1, 3])
    engine.apply_gradients(
        unique_ids, np.ones((2, DIM), np.float32)
    )
    after = engine.param.lookup([1, 2, 3])
    assert not np.allclose(after[0], before[0])
    np.testing.assert_array_equal(after[1], before[1])  # untouched
    assert not np.allclose(after[2], before[2])


def test_engine_checkpoint_roundtrip(force_python):
    engine = HostSpillEmbeddingEngine(
        DIM, optimizer="adam", lr=0.01, force_python=force_python
    )
    unique_ids, _, _ = engine.pull([1, 2])
    engine.apply_gradients(unique_ids, np.ones((2, DIM), np.float32))
    state = engine.state_dict()

    restored = HostSpillEmbeddingEngine(
        DIM, optimizer="adam", lr=0.01, force_python=force_python
    )
    restored.load_state_dict(state)
    np.testing.assert_array_equal(
        restored.param.lookup([1, 2]), engine.param.lookup([1, 2])
    )
    np.testing.assert_array_equal(
        restored.slots["m"].lookup([1, 2]),
        engine.slots["m"].lookup([1, 2]),
    )
    # continued training stays in lockstep
    engine.apply_gradients(unique_ids, np.ones((2, DIM), np.float32))
    restored.apply_gradients(unique_ids, np.ones((2, DIM), np.float32))
    np.testing.assert_allclose(
        restored.param.lookup([1, 2]), engine.param.lookup([1, 2]),
        rtol=1e-6,
    )


def test_engine_rejects_unknown_optimizer(force_python):
    with pytest.raises(ValueError, match="Unknown optimizer"):
        HostSpillEmbeddingEngine(DIM, optimizer="ftrl")


def test_lazy_init_identical_across_backends():
    """splitmix64 init must agree bit-for-bit between C++ and numpy
    (divergent lazy init would silently fork replica models)."""
    if not host_embedding.available():
        pytest.skip("libhostembedding.so not built")
    native = HostEmbeddingStore(DIM, seed=42, force_python=False)
    python = HostEmbeddingStore(DIM, seed=42, force_python=True)
    ids = [0, 1, 7, 123456789, 2**40]
    np.testing.assert_array_equal(native.lookup(ids), python.lookup(ids))
