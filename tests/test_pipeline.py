"""Pipeline parallelism (parallel/pipeline.py): numerical equality with
the sequential oracle, gradient flow through the pipeline, and
composition with data parallelism on the 8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.parallel.pipeline import (
    pipeline_apply,
    sequential_apply,
)


def _stage_fn(params, x):
    """One stage = its chunk of layers, applied in order: y = gelu(x W + b)
    per layer."""

    def layer(x, wb):
        w, b = wb
        return jax.nn.gelu(x @ w + b)

    def body(carry, wb):
        return layer(carry, wb), None

    out, _ = jax.lax.scan(body, x, (params["w"], params["b"]))
    return out


def _params(n_layers, dim, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w": jnp.asarray(
            rng.standard_normal((n_layers, dim, dim)) / np.sqrt(dim),
            jnp.float32,
        ),
        "b": jnp.asarray(rng.standard_normal((n_layers, dim)) * 0.01,
                         jnp.float32),
    }


@pytest.mark.parametrize("pp,m", [(4, 4), (4, 8), (2, 2), (8, 8)])
def test_matches_sequential(pp, m):
    mesh = mesh_lib.build_mesh({"pp": pp, "dp": 8 // pp})
    n_layers, dim, batch = 8, 16, 16
    params = _params(n_layers, dim)
    x = jnp.asarray(
        np.random.default_rng(1).standard_normal((batch, dim)), jnp.float32
    )
    with mesh:
        got = jax.jit(
            lambda p, xv: pipeline_apply(_stage_fn, p, xv, mesh, m)
        )(params, x)
    want = sequential_apply(_stage_fn, params, x, pp)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_gradients_match_sequential():
    pp, m = 4, 4
    mesh = mesh_lib.build_mesh({"pp": pp, "dp": 2})
    n_layers, dim, batch = 4, 8, 8
    params = _params(n_layers, dim, seed=2)
    x = jnp.asarray(
        np.random.default_rng(3).standard_normal((batch, dim)), jnp.float32
    )

    def loss_pp(p):
        with mesh:
            y = pipeline_apply(_stage_fn, p, x, mesh, m)
        return jnp.mean(y ** 2)

    def loss_seq(p):
        return jnp.mean(sequential_apply(_stage_fn, p, x, pp) ** 2)

    with mesh:
        g_pp = jax.jit(jax.grad(loss_pp))(params)
    g_seq = jax.grad(loss_seq)(params)
    for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-4)


def test_rejects_bad_shapes():
    mesh = mesh_lib.build_mesh({"pp": 4, "dp": 2})
    params = _params(6, 8)  # 6 layers not divisible by 4 stages
    x = jnp.zeros((8, 8))
    with pytest.raises(ValueError, match="not divisible by pp"):
        pipeline_apply(_stage_fn, params, x, mesh, 2)
    with pytest.raises(ValueError, match="num_microbatches"):
        pipeline_apply(_stage_fn, _params(4, 8), x, mesh, 0)
