"""Pipeline parallelism (parallel/pipeline.py): numerical equality with
the sequential oracle, gradient flow through the pipeline, and
composition with data parallelism on the 8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.parallel.pipeline import (
    deinterleave_layers,
    interleave_layers,
    pipeline_apply,
    sequential_apply,
)


def _stage_fn(params, x):
    """One stage = its chunk of layers, applied in order: y = gelu(x W + b)
    per layer."""

    def layer(x, wb):
        w, b = wb
        return jax.nn.gelu(x @ w + b)

    def body(carry, wb):
        return layer(carry, wb), None

    out, _ = jax.lax.scan(body, x, (params["w"], params["b"]))
    return out


def _params(n_layers, dim, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w": jnp.asarray(
            rng.standard_normal((n_layers, dim, dim)) / np.sqrt(dim),
            jnp.float32,
        ),
        "b": jnp.asarray(rng.standard_normal((n_layers, dim)) * 0.01,
                         jnp.float32),
    }


@pytest.mark.parametrize("pp,m", [(4, 4), (4, 8), (2, 2), (8, 8)])
def test_matches_sequential(pp, m):
    mesh = mesh_lib.build_mesh({"pp": pp, "dp": 8 // pp})
    n_layers, dim, batch = 8, 16, 16
    params = _params(n_layers, dim)
    x = jnp.asarray(
        np.random.default_rng(1).standard_normal((batch, dim)), jnp.float32
    )
    with mesh:
        got = jax.jit(
            lambda p, xv: pipeline_apply(_stage_fn, p, xv, mesh, m)
        )(params, x)
    want = sequential_apply(_stage_fn, params, x, pp)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_gradients_match_sequential():
    pp, m = 4, 4
    mesh = mesh_lib.build_mesh({"pp": pp, "dp": 2})
    n_layers, dim, batch = 4, 8, 8
    params = _params(n_layers, dim, seed=2)
    x = jnp.asarray(
        np.random.default_rng(3).standard_normal((batch, dim)), jnp.float32
    )

    def loss_pp(p):
        with mesh:
            y = pipeline_apply(_stage_fn, p, x, mesh, m)
        return jnp.mean(y ** 2)

    def loss_seq(p):
        return jnp.mean(sequential_apply(_stage_fn, p, x, pp) ** 2)

    with mesh:
        g_pp = jax.jit(jax.grad(loss_pp))(params)
    g_seq = jax.grad(loss_seq)(params)
    for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-4)


@pytest.mark.parametrize("pp,m,v", [(4, 4, 2), (4, 8, 2), (2, 4, 4),
                                    (2, 2, 1), (8, 8, 2)])
def test_interleaved_matches_sequential(pp, m, v):
    """Interleaved (circular) schedule == sequential oracle: the params
    stack converts to ring-ordered layout, the pipeline streams vM+P-1
    chunk ticks, and the banked outputs must equal running the semantic
    layer order straight through."""
    mesh = mesh_lib.build_mesh({"pp": pp, "dp": 8 // pp})
    n_layers, dim, batch = 16, 16, 16
    params = _params(n_layers, dim)
    x = jnp.asarray(
        np.random.default_rng(1).standard_normal((batch, dim)), jnp.float32
    )
    ring = interleave_layers(params, pp, v)
    with mesh:
        got = jax.jit(
            lambda p, xv: pipeline_apply(
                _stage_fn, p, xv, mesh, m,
                schedule="interleaved", interleave=v,
            )
        )(ring, x)
    want = sequential_apply(_stage_fn, params, x, pp)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_interleave_layers_roundtrip():
    params = _params(12, 4)
    ring = interleave_layers(params, 2, 3)
    back = deinterleave_layers(ring, 2, 3)
    for a, b in zip(jax.tree.leaves(back), jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # device-major layout: device 0's first chunk is virtual stage 0 =
    # semantic layers [0, 2), its second chunk virtual stage 2 = [4, 6)
    np.testing.assert_array_equal(
        np.asarray(ring["w"][:4]),
        np.asarray(params["w"])[[0, 1, 4, 5]],
    )


@pytest.mark.parametrize("schedule,remat", [("interleaved", False),
                                            ("gpipe", True),
                                            ("interleaved", True)])
def test_gradients_match_sequential_schedules(schedule, remat):
    """AD through both schedules (and the remat/activation-staging
    path) equals the sequential oracle's gradients."""
    pp, m, v = 4, 4, 2
    mesh = mesh_lib.build_mesh({"pp": pp, "dp": 2})
    n_layers, dim, batch = 8, 8, 8
    params = _params(n_layers, dim, seed=2)
    x = jnp.asarray(
        np.random.default_rng(3).standard_normal((batch, dim)), jnp.float32
    )
    if schedule == "interleaved":
        run_p = interleave_layers(params, pp, v)
    else:
        run_p = params

    def loss_pp(p):
        with mesh:
            y = pipeline_apply(_stage_fn, p, x, mesh, m,
                               schedule=schedule, interleave=v,
                               remat=remat)
        return jnp.mean(y ** 2)

    def loss_seq(p):
        return jnp.mean(sequential_apply(_stage_fn, p, x, pp) ** 2)

    with mesh:
        g_pp = jax.jit(jax.grad(loss_pp))(run_p)
    if schedule == "interleaved":
        g_pp = deinterleave_layers(g_pp, pp, v)
    g_seq = jax.grad(loss_seq)(params)
    for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-4)


def test_interleaved_rejects_bad_config():
    mesh = mesh_lib.build_mesh({"pp": 4, "dp": 2})
    x = jnp.zeros((8, 8))
    with pytest.raises(ValueError, match="groups of"):
        pipeline_apply(_stage_fn, _params(8, 8), x, mesh, 2,
                       schedule="interleaved", interleave=2)
    with pytest.raises(ValueError, match="interleave"):
        pipeline_apply(_stage_fn, _params(4, 8), x, mesh, 4,
                       schedule="interleaved", interleave=2)
    with pytest.raises(ValueError, match="unknown schedule"):
        pipeline_apply(_stage_fn, _params(8, 8), x, mesh, 4,
                       schedule="zigzag")
    # converters must refuse truncation, not silently drop layers
    with pytest.raises(ValueError, match="not divisible"):
        interleave_layers(_params(6, 4), 2, 2)
    with pytest.raises(ValueError, match="not divisible"):
        deinterleave_layers(_params(6, 4), 2, 2)


def test_rejects_bad_shapes():
    mesh = mesh_lib.build_mesh({"pp": 4, "dp": 2})
    params = _params(6, 8)  # 6 layers not divisible by 4 stages
    x = jnp.zeros((8, 8))
    with pytest.raises(ValueError, match="not divisible by pp"):
        pipeline_apply(_stage_fn, params, x, mesh, 2)
    with pytest.raises(ValueError, match="num_microbatches"):
        pipeline_apply(_stage_fn, _params(4, 8), x, mesh, 0)
