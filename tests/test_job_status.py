"""Job-status file + validator (the CI drill's contract,
scripts/run_local_job_drill.sh): phases mirror pod phases, writes are
atomic, the validator exits 0/1/2 for Succeeded/Failed/timeout."""

import threading
import time

import pytest

from elasticdl_tpu.common import job_status


def test_write_read_roundtrip(tmp_path):
    path = str(tmp_path / "status.json")
    assert job_status.read_job_status(path) is None
    job_status.write_job_status(path, job_status.PENDING)
    assert job_status.read_job_status(path)["status"] == "Pending"
    job_status.write_job_status(path, job_status.RUNNING, step=3)
    got = job_status.read_job_status(path)
    assert got["status"] == "Running" and got["step"] == 3
    assert got["time"] <= time.time()


def test_write_rejects_unknown_phase(tmp_path):
    with pytest.raises(ValueError, match="unknown job status"):
        job_status.write_job_status(str(tmp_path / "s"), "Exploded")


def test_empty_path_is_noop():
    job_status.write_job_status("", job_status.RUNNING)  # no crash


def test_partial_file_reads_none(tmp_path):
    path = tmp_path / "s.json"
    path.write_text('{"status": "Run')  # torn write
    assert job_status.read_job_status(str(path)) is None


def _validator():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "validate_job_status",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "validate_job_status.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_validator_success_and_failure(tmp_path):
    v = _validator()
    path = str(tmp_path / "s.json")
    job_status.write_job_status(path, job_status.SUCCEEDED)
    assert v.validate_status_file(path, timeout=5, poll_interval=0.01) == 0
    job_status.write_job_status(path, job_status.FAILED)
    assert v.validate_status_file(path, timeout=5, poll_interval=0.01) == 1


def test_validator_polls_until_terminal(tmp_path):
    v = _validator()
    path = str(tmp_path / "s.json")
    job_status.write_job_status(path, job_status.RUNNING)

    def finish():
        time.sleep(0.3)
        job_status.write_job_status(path, job_status.SUCCEEDED)

    t = threading.Thread(target=finish)
    t.start()
    assert v.validate_status_file(path, timeout=10, poll_interval=0.05) == 0
    t.join()


def test_validator_fails_fast_on_dead_master(tmp_path):
    """A master pid that no longer exists -> rc 3 well before timeout."""
    import subprocess
    import sys as _sys

    v = _validator()
    path = str(tmp_path / "s.json")
    job_status.write_job_status(path, job_status.RUNNING)
    proc = subprocess.Popen([_sys.executable, "-c", "pass"])
    proc.wait()
    t0 = time.time()
    rc = v.validate_status_file(
        path, timeout=30, poll_interval=0.05, pid=proc.pid
    )
    assert rc == 3
    assert time.time() - t0 < 5

    # ...but a dead pid with a terminal status still validates normally
    job_status.write_job_status(path, job_status.SUCCEEDED)
    assert v.validate_status_file(
        path, timeout=5, poll_interval=0.05, pid=proc.pid
    ) == 0


def test_validator_timeout(tmp_path):
    v = _validator()
    path = str(tmp_path / "never.json")
    assert v.validate_status_file(
        path, timeout=0.3, poll_interval=0.05
    ) == 2


def test_master_main_writes_failed_on_bad_model(tmp_path):
    """master.main marks the job Failed when it dies before running."""
    from elasticdl_tpu.master.main import main

    path = str(tmp_path / "s.json")
    with pytest.raises(Exception):
        main([
            "--model_zoo", "model_zoo",
            "--model_def", "no_such.module.custom_model",
            "--job_status_file", path,
            "--training_data", str(tmp_path),
        ])
    assert job_status.read_job_status(path)["status"] == "Failed"
