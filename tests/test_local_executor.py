"""End-to-end: LocalExecutor trains the mnist zoo model on synthetic TRec
data (mirrors the reference's example_test.py in-process harness)."""


import numpy as np
import pytest

from elasticdl_tpu.api.local_executor import LocalExecutor
from elasticdl_tpu.common.model_utils import get_model_spec
from elasticdl_tpu.data import recordio_gen

MODEL_ZOO = "model_zoo"


@pytest.fixture(scope="module")
def mnist_data(tmp_path_factory):
    root = tmp_path_factory.mktemp("mnist")
    train_dir = str(root / "train")
    val_dir = str(root / "val")
    recordio_gen.gen_mnist_like(train_dir, num_files=2, records_per_file=64)
    recordio_gen.gen_mnist_like(val_dir, num_files=1, records_per_file=32,
                                seed=1)
    return train_dir, val_dir


def _spec():
    return get_model_spec(
        MODEL_ZOO, "mnist_functional_api.mnist_functional_api.custom_model"
    )


def test_get_model_spec_by_convention():
    spec = _spec()
    assert spec.model_fn is not None
    assert callable(spec.loss)
    assert callable(spec.optimizer)
    assert callable(spec.dataset_fn)
    metrics = spec.eval_metrics_fn()
    assert "accuracy" in metrics


@pytest.mark.slow
def test_train_and_evaluate(mnist_data):
    train_dir, val_dir = mnist_data
    executor = LocalExecutor(
        _spec(),
        training_data=train_dir,
        validation_data=val_dir,
        minibatch_size=16,
        num_epochs=2,
        records_per_task=32,
    )
    state, metrics = executor.run()
    assert int(state.step) == 2 * 128 // 16
    assert len(executor.losses) == int(state.step)
    assert np.isfinite(executor.losses).all()
    # random data, random labels: loss should move from ~ln(10)
    assert "accuracy" in metrics
    assert 0.0 <= metrics["accuracy"] <= 1.0


@pytest.mark.slow
def test_training_reduces_loss_on_learnable_data(tmp_path):
    # labels perfectly determined by the mean pixel bucket -> learnable
    from elasticdl_tpu.data.example_codec import encode_example
    from elasticdl_tpu.data.record_format import RecordWriter

    rng = np.random.RandomState(0)
    train_dir = tmp_path / "train"
    train_dir.mkdir()
    with RecordWriter(str(train_dir / "t.trec")) as w:
        for _ in range(256):
            label = rng.randint(2)
            image = np.full((28, 28), 0.9 * label + 0.05, np.float32)
            image += rng.randn(28, 28).astype(np.float32) * 0.01
            w.write(encode_example({
                "image": image,
                "label": np.array([label], np.int32),
            }))
    executor = LocalExecutor(
        _spec(),
        training_data=str(train_dir),
        minibatch_size=32,
        num_epochs=3,
    )
    executor.run()
    first, last = executor.losses[0], np.mean(executor.losses[-4:])
    assert last < first


def test_predict(mnist_data):
    train_dir, _ = mnist_data
    executor = LocalExecutor(
        _spec(),
        prediction_data=train_dir,
        minibatch_size=16,
    )
    preds = executor.run()
    assert preds.shape == (128, 10)


@pytest.mark.slow
def test_max_steps_stops_early(mnist_data):
    train_dir, _ = mnist_data
    executor = LocalExecutor(
        _spec(),
        training_data=train_dir,
        minibatch_size=16,
        num_epochs=10,
        max_steps=3,
    )
    state, _ = executor.run()
    assert int(state.step) == 3


def test_local_executor_crash_resume(tmp_path, mnist_data):
    """A local run killed mid-job (simulated via the fault injector at
    the dispatch boundary) resumes from its --job_state_dir journal:
    completed ranges are not re-trained, and the combined runs cover
    every batch exactly once."""
    from elasticdl_tpu.common.fault_injection import (
        FaultInjector,
        InjectedRpcError,
    )

    train_dir, _ = mnist_data  # 128 records
    state_dir = str(tmp_path / "job_state")

    run1 = LocalExecutor(
        _spec(), training_data=train_dir, minibatch_size=16,
        records_per_task=32, num_epochs=1, job_state_dir=state_dir,
        fault_injector=FaultInjector(spec="local_get_task:drop:1:skip=2"),
    )
    with pytest.raises(InjectedRpcError):
        run1.train()
    steps1 = len(run1.losses)
    assert steps1 == 2 * 32 // 16  # two tasks trained before the crash

    run2 = LocalExecutor(
        _spec(), training_data=train_dir, minibatch_size=16,
        records_per_task=32, num_epochs=1, job_state_dir=state_dir,
    )
    run2.train()
    # remaining two tasks only — no range re-trained
    assert len(run2.losses) == 128 // 16 - steps1
