"""Row-sparse embedding update engine (embedding/sparse_update.py).

Covers VERDICT round-1 item #4: the per-step cost of training a model with
a big embedding table must not scale with vocab (the reference's whole
point: only touched rows move, ps/optimizer_wrapper.py:70-351 /
go/pkg/ps/optimizer.go per-row kernels), while the numerics must match the
dense-update-then-mask oracle (embedding/sparse_optim.py) exactly.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from elasticdl_tpu.common.model_utils import ModelSpec
from elasticdl_tpu.embedding.layer import Embedding
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.training.trainer import Trainer


def _make_model(vocab, dim, sparse, combiner="sum"):
    class Rec(nn.Module):
        @nn.compact
        def __call__(self, features, training=False):
            emb = Embedding(
                input_dim=vocab, output_dim=dim, combiner=combiner,
                sparse_grads=sparse, name="cat",
            )(features["ids"])
            return nn.Dense(1, name="out")(emb)[:, 0]

    return Rec


def _loss(labels, predictions, weights=None):
    per = optax.sigmoid_binary_cross_entropy(
        predictions, labels.astype(jnp.float32)
    )
    if weights is None:
        return jnp.mean(per)
    return jnp.sum(per * weights) / jnp.maximum(jnp.sum(weights), 1.0)


def _spec(model_fn, optimizer):
    return ModelSpec(
        model_fn=model_fn,
        dataset_fn=lambda ds, mode, meta: ds,
        loss=_loss,
        optimizer=optimizer,
        eval_metrics_fn=lambda: {},
    )


def _batch(vocab, bsz=8, width=4, seed=0):
    rng = np.random.RandomState(seed)
    # only ids < vocab // 4: plenty of untouched rows
    ids = rng.randint(0, max(vocab // 4, 2), size=(bsz, width))
    ids = ids.astype(np.int32)
    labels = rng.randint(0, 2, size=(bsz,)).astype(np.int32)
    return ({"ids": ids}, labels)


def _train(sparse, optimizer, vocab=64, dim=8, steps=3):
    trainer = Trainer(
        _spec(_make_model(vocab, dim, sparse), optimizer),
        mesh=mesh_lib.local_mesh(),
    )
    batches = [_batch(vocab, seed=s) for s in range(steps)]
    state = trainer.init_state(batches[0])
    losses = []
    for b in batches:
        state, loss = trainer.train_step(state, b)
        losses.append(float(loss))
    return trainer, state, losses


@pytest.mark.parametrize(
    "optimizer",
    [
        lambda: optax.sgd(0.1),
        lambda: optax.adam(1e-2),
        lambda: optax.adamw(1e-2, weight_decay=0.01),
        lambda: optax.adagrad(0.1),
    ],
    ids=["sgd", "adam", "adamw", "adagrad"],
)
def test_matches_dense_masked_oracle(optimizer):
    """The tapped path takes the exact same trajectory as the dense
    update + row mask (make_row_sparse) on every optimizer family the
    reference's Go PS ships kernels for."""
    _, s_sparse, l_sparse = _train(True, optimizer)
    _, s_dense, l_dense = _train(False, optimizer)
    np.testing.assert_allclose(l_sparse, l_dense, rtol=1e-5)
    t_sparse = s_sparse.params["cat"]["embedding_table"]
    t_dense = s_dense.params["cat"]["embedding_table"]
    np.testing.assert_allclose(
        np.asarray(t_sparse), np.asarray(t_dense), rtol=1e-5, atol=1e-6
    )
    # dense layers identical too
    np.testing.assert_allclose(
        np.asarray(s_sparse.params["out"]["kernel"]),
        np.asarray(s_dense.params["out"]["kernel"]),
        rtol=1e-5, atol=1e-6,
    )


def test_untouched_rows_and_slots_frozen():
    """Adam must not move rows (or their moments) the batch never
    touched — the OptimizerWrapper contract."""
    trainer, state, _ = _train(True, lambda: optax.adam(1e-2), vocab=64)
    init_trainer = Trainer(
        _spec(_make_model(64, 8, True), lambda: optax.adam(1e-2)),
        mesh=mesh_lib.local_mesh(),
    )
    state0 = init_trainer.init_state(_batch(64))
    table0 = np.asarray(state0.params["cat"]["embedding_table"])
    table = np.asarray(state.params["cat"]["embedding_table"])
    # ids were all < 16; rows 16+ must be bit-identical
    np.testing.assert_array_equal(table[16:], table0[16:])
    assert not np.allclose(table[:16], table0[:16])
    (slots,) = [
        v for k, v in state.embed_opt_state.items()
        if k.endswith("embedding_table")
    ]
    mu = np.asarray(jax.tree.leaves(slots)[1])  # (count, mu, nu)
    assert mu.shape[0] == 64
    np.testing.assert_array_equal(mu[16:], np.zeros_like(mu[16:]))


def test_eval_path_unaffected():
    """forward() (no perturbations passed) must produce the same
    predictions as a dense-path model with the same params."""
    trainer, state, _ = _train(True, lambda: optax.adam(1e-2))
    batch = _batch(64, seed=9)
    preds = trainer.forward(state, batch[0])
    dense_model = _make_model(64, 8, False)()
    manual = dense_model.apply(
        {"params": state.params, **state.model_state},
        batch[0], training=False,
    )
    np.testing.assert_allclose(
        np.asarray(preds), np.asarray(manual), rtol=1e-5
    )


def _compiled_hlo(vocab, sparse):
    trainer = Trainer(
        _spec(_make_model(vocab, 16, sparse), lambda: optax.adam(1e-3)),
        mesh=mesh_lib.local_mesh(),
    )
    batch = _batch(vocab)
    state = trainer.init_state(batch)
    trainer._train_step = trainer._build_train_step()
    features, labels = batch
    weights = trainer.make_weights(8, None)
    with trainer.mesh:
        lowered = trainer._train_step.lower(
            state, features, labels, weights
        )
    return lowered.compile().as_text()


def _vocab_sized_compute_ops(hlo, vocab, dim=16):
    """HLO ops producing a [vocab, dim] result, excluding parameters,
    tuples/get-tuple-element plumbing, and in-place row updates.
    Depending on the XLA version the sparse row writes lower either to
    named `scatter` ops or to `dynamic-update-slice` (and
    `select_dynamic-update-slice` fusions); both touch only the updated
    rows at runtime when the destination buffer is donated, so both are
    O(touched rows), not O(vocab). Anything else vocab-sized (adds,
    selects, multiplies, zeros broadcasts) is real O(vocab) per-step
    traffic."""
    import re

    pat = re.compile(r"= f32\[%d,%d\]\{[0-9,]*\} ([\w-]+)" % (vocab, dim))
    ops = []
    for line in hlo.splitlines():
        m = pat.search(line)
        if not m:
            continue
        kind = m.group(1)
        if kind in ("parameter", "tuple", "get-tuple-element"):
            continue
        if "scatter" in line or "dynamic-update-slice" in line:
            continue
        ops.append(line.strip()[:120])
    return ops


def test_cost_does_not_scale_with_vocab():
    """The whole point (VERDICT #4): the compiled step's only
    vocab-sized operations are the in-place row scatters into the
    donated table + slot buffers — every other op is O(touched rows).
    The dense-masked oracle by contrast runs vocab-sized compute every
    step (Adam over the full table, then the mask)."""
    vocab = 16 * 1024
    hlo = _compiled_hlo(vocab, True)
    assert "input_output_alias" in hlo  # donation: scatters are in-place
    leftovers = _vocab_sized_compute_ops(hlo, vocab)
    assert not leftovers, (
        "O(vocab) compute survived in the sparse path:\n%s"
        % "\n".join(leftovers)
    )
    dense_hlo = _compiled_hlo(vocab, False)
    dense_big = _vocab_sized_compute_ops(dense_hlo, vocab)
    assert len(dense_big) >= 3, (
        "dense-masked oracle should run vocab-sized compute (got %d big "
        "ops) — if it stopped, the assertion above is vacuous"
        % len(dense_big)
    )


def test_auto_threshold_taps_big_tables(monkeypatch):
    """sparse_grads=None: tables over the partition threshold tap
    automatically (model_handler.py:98-102's 2 MB rule)."""
    from elasticdl_tpu.common import constants

    monkeypatch.setattr(
        constants, "EMBEDDING_PARTITION_THRESHOLD_BYTES", 1024
    )
    trainer = Trainer(
        _spec(_make_model(64, 8, None), lambda: optax.sgd(0.1)),
        mesh=mesh_lib.local_mesh(),
    )
    state = trainer.init_state(_batch(64))
    assert trainer._sparse_paths, "64*8*4B > 1KiB: tap expected"
    state, loss = trainer.train_step(state, _batch(64))
    assert np.isfinite(float(loss))


def test_double_call_raises():
    class DoubleCall(nn.Module):
        @nn.compact
        def __call__(self, features, training=False):
            layer = Embedding(
                input_dim=32, output_dim=4, combiner="sum",
                sparse_grads=True, name="shared",
            )
            return nn.Dense(1)(
                layer(features["ids"]) + layer(features["ids"])
            )[:, 0]

    trainer = Trainer(
        _spec(DoubleCall, lambda: optax.sgd(0.1)),
        mesh=mesh_lib.local_mesh(),
    )
    with pytest.raises(ValueError, match="more than once"):
        trainer.init_state(_batch(32))
