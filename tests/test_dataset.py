import numpy as np

from elasticdl_tpu.data.dataset import Dataset, pad_batch


def test_map_batch():
    ds = Dataset.from_list(range(10)).map(lambda x: x * 2).batch(4)
    batches = list(ds)
    assert len(batches) == 3
    np.testing.assert_array_equal(batches[0], [0, 2, 4, 6])
    np.testing.assert_array_equal(batches[2], [16, 18])


def test_batch_drop_remainder():
    ds = Dataset.from_list(range(10)).batch(4, drop_remainder=True)
    assert len(list(ds)) == 2


def test_batch_dicts():
    items = [{"x": np.ones(3) * i, "y": np.array([i])} for i in range(4)]
    (b,) = list(Dataset.from_list(items).batch(4))
    assert b["x"].shape == (4, 3)
    assert b["y"].shape == (4, 1)


def test_batch_tuples():
    items = [({"x": np.float32(i)}, np.int32(i)) for i in range(6)]
    batches = list(Dataset.from_list(items).batch(3))
    feats, labels = batches[0]
    assert feats["x"].shape == (3,)
    assert labels.shape == (3,)


def test_shuffle_is_permutation():
    out = list(Dataset.from_list(range(100)).shuffle(16, seed=0))
    assert sorted(out) == list(range(100))
    assert out != list(range(100))


def test_prefetch_preserves_order_and_errors():
    ds = Dataset.from_list(range(50)).prefetch(4)
    assert list(ds) == list(range(50))

    def bad_gen():
        yield 1
        raise ValueError("boom")

    import pytest

    with pytest.raises(ValueError):
        list(Dataset.from_generator(bad_gen).prefetch(2))


def test_repeat_take():
    assert list(Dataset.from_list([1, 2]).repeat(3)) == [1, 2] * 3
    assert list(Dataset.from_list(range(10)).take(3)) == [0, 1, 2]


def test_pad_batch_dict():
    batch = {"x": np.arange(6).reshape(3, 2), "y": np.arange(3)}
    padded, n = pad_batch(batch, 5)
    assert n == 3
    assert padded["x"].shape == (5, 2)
    np.testing.assert_array_equal(padded["x"][3], padded["x"][2])


def test_pad_batch_tuple():
    batch = ({"x": np.zeros((2, 4))}, np.zeros(2))
    (feats, labels), n = pad_batch(batch, 8)
    assert n == 2
    assert feats["x"].shape == (8, 4)
    assert labels.shape == (8,)
