"""Pallas kernel tests: every kernel vs a numpy/jnp reference.

Runs the real kernel code under the Pallas interpreter on CPU (the same
source path that compiles on TPU), mirroring how the reference unit-tests
its Eigen kernels against hand-computed updates (go/pkg/kernel/
kernel_test.go:25-182).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from elasticdl_tpu.ops import (
    adagrad_update,
    adam_update,
    dedup_indexed_slices,
    embedding_gather,
    momentum_update,
    sgd_update,
    sparse_adagrad_update,
    sparse_adam_update,
    sparse_momentum_update,
    sparse_sgd_update,
)


@pytest.fixture(autouse=True)
def _opt_into_interpreted_kernels(monkeypatch):
    """use_pallas() routes to the jnp reference paths off-TPU; these
    tests exist to exercise the kernel code itself, so they opt into
    Pallas interpreter mode explicitly."""
    monkeypatch.setenv("ELASTICDL_TPU_FORCE_INTERPRET", "1")


DIM = 16
VOCAB = 32


def _rand(*shape, seed=0):
    return np.random.RandomState(seed).rand(*shape).astype(np.float32)


# ------------------------------------------------------------------ dense


def test_sgd_dense():
    p, g = _rand(7, 33, seed=1), _rand(7, 33, seed=2)
    out = sgd_update(p, g, lr=0.1)
    np.testing.assert_allclose(out, p - 0.1 * g, rtol=1e-5, atol=1e-6)


def test_momentum_dense():
    p, v, g = _rand(50, seed=1), _rand(50, seed=2), _rand(50, seed=3)
    new_p, new_v = momentum_update(p, v, g, lr=0.1, momentum=0.9)
    exp_v = 0.9 * v + g
    np.testing.assert_allclose(new_v, exp_v, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(new_p, p - 0.1 * exp_v, rtol=1e-5, atol=1e-6)


def test_momentum_dense_nesterov():
    p, v, g = _rand(9, seed=1), _rand(9, seed=2), _rand(9, seed=3)
    new_p, new_v = momentum_update(
        p, v, g, lr=0.1, momentum=0.9, nesterov=True
    )
    exp_v = 0.9 * v + g
    np.testing.assert_allclose(new_p, p - 0.1 * (0.9 * exp_v + g), rtol=1e-5, atol=1e-6)


def test_adam_dense():
    p, m, v, g = (_rand(40, seed=i) for i in range(4))
    t = 3
    new_p, new_m, new_v = adam_update(
        p, m, v, g, step=t, lr=0.01, beta1=0.9, beta2=0.999, eps=1e-8
    )
    exp_m = 0.9 * m + 0.1 * g
    exp_v = 0.999 * v + 0.001 * g * g
    alpha = 0.01 * np.sqrt(1 - 0.999**t) / (1 - 0.9**t)
    np.testing.assert_allclose(new_m, exp_m, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(new_v, exp_v, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(
        new_p, p - alpha * exp_m / (np.sqrt(exp_v) + 1e-8), rtol=1e-4, atol=1e-6
    )


def test_adam_dense_amsgrad():
    p, m, v, ms, g = (_rand(12, seed=i) for i in range(5))
    new_p, new_m, new_v, new_ms = adam_update(
        p, m, v, g, step=1, lr=0.01, max_square=ms
    )
    exp_v = 0.999 * v + 0.001 * g * g
    np.testing.assert_allclose(new_ms, np.maximum(ms, exp_v), rtol=1e-4, atol=1e-6)


def test_adagrad_dense():
    p, a, g = _rand(25, seed=1), _rand(25, seed=2), _rand(25, seed=3)
    new_p, new_a = adagrad_update(p, a, g, lr=0.1, eps=1e-10)
    exp_a = a + g * g
    np.testing.assert_allclose(new_a, exp_a, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        new_p, p - 0.1 * g / (np.sqrt(exp_a) + 1e-10), rtol=1e-5, atol=1e-6
    )


# ----------------------------------------------------------------- gather


def test_embedding_gather():
    table = _rand(VOCAB, DIM, seed=5)
    ids = np.array([3, 0, 31, 7, 7, 12], np.int32)
    out = embedding_gather(table, ids)
    np.testing.assert_allclose(out, table[ids], rtol=1e-5, atol=1e-6)


def test_embedding_gather_2d_ids():
    table = _rand(VOCAB, DIM, seed=5)
    ids = np.array([[3, 1], [30, 2], [9, 9]], np.int32)
    out = embedding_gather(table, ids)
    assert out.shape == (3, 2, DIM)
    np.testing.assert_allclose(out, table[ids], rtol=1e-5, atol=1e-6)


# ------------------------------------------------------------ sparse rows


def test_sparse_sgd_rows():
    table = _rand(VOCAB, DIM, seed=1)
    ids = np.array([2, 9, 30], np.int32)
    grads = _rand(3, DIM, seed=2)
    out = np.asarray(sparse_sgd_update(jnp.array(table), ids, grads, lr=0.5))
    exp = table.copy()
    exp[ids] -= 0.5 * grads
    np.testing.assert_allclose(out, exp, rtol=1e-5, atol=1e-6)


def test_sparse_sgd_skips_padding():
    table = _rand(VOCAB, DIM, seed=1)
    ids = np.array([4, -1, 6], np.int32)
    grads = _rand(3, DIM, seed=2)
    out = np.asarray(sparse_sgd_update(jnp.array(table), ids, grads, lr=0.5))
    exp = table.copy()
    exp[4] -= 0.5 * grads[0]
    exp[6] -= 0.5 * grads[2]
    np.testing.assert_allclose(out, exp, rtol=1e-5, atol=1e-6)


def test_sparse_momentum_rows():
    table, vel = _rand(VOCAB, DIM, seed=1), _rand(VOCAB, DIM, seed=2)
    ids = np.array([1, 5], np.int32)
    grads = _rand(2, DIM, seed=3)
    new_t, new_v = sparse_momentum_update(
        jnp.array(table), jnp.array(vel), ids, grads, lr=0.1, momentum=0.9
    )
    exp_t, exp_v = table.copy(), vel.copy()
    exp_v[ids] = 0.9 * vel[ids] + grads
    exp_t[ids] -= 0.1 * exp_v[ids]
    np.testing.assert_allclose(np.asarray(new_v), exp_v, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(new_t), exp_t, rtol=1e-5, atol=1e-6)


def test_sparse_adam_rows():
    table, m, v = (_rand(VOCAB, DIM, seed=i) for i in range(3))
    ids = np.array([0, 17, 31], np.int32)
    grads = _rand(3, DIM, seed=4)
    t = 2
    new_t, new_m, new_v = sparse_adam_update(
        jnp.array(table), jnp.array(m), jnp.array(v), ids, grads,
        step=t, lr=0.01,
    )
    exp_m, exp_v, exp_t = m.copy(), v.copy(), table.copy()
    exp_m[ids] = 0.9 * m[ids] + 0.1 * grads
    exp_v[ids] = 0.999 * v[ids] + 0.001 * grads * grads
    alpha = 0.01 * np.sqrt(1 - 0.999**t) / (1 - 0.9**t)
    exp_t[ids] -= alpha * exp_m[ids] / (np.sqrt(exp_v[ids]) + 1e-8)
    np.testing.assert_allclose(np.asarray(new_m), exp_m, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(new_v), exp_v, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(new_t), exp_t, rtol=1e-4, atol=1e-6)
    # untouched rows identical
    untouched = np.setdiff1d(np.arange(VOCAB), ids)
    np.testing.assert_array_equal(
        np.asarray(new_t)[untouched], table[untouched]
    )


def test_sparse_adagrad_rows():
    table, accum = _rand(VOCAB, DIM, seed=1), _rand(VOCAB, DIM, seed=2)
    ids = np.array([8], np.int32)
    grads = _rand(1, DIM, seed=3)
    new_t, new_a = sparse_adagrad_update(
        jnp.array(table), jnp.array(accum), ids, grads, lr=0.1
    )
    exp_a, exp_t = accum.copy(), table.copy()
    exp_a[8] = accum[8] + grads[0] ** 2
    exp_t[8] -= 0.1 * grads[0] / (np.sqrt(exp_a[8]) + 1e-10)
    np.testing.assert_allclose(np.asarray(new_a), exp_a, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(new_t), exp_t, rtol=1e-5, atol=1e-6)


# ------------------------------------------------------------------ dedup


def test_dedup_indexed_slices():
    ids = np.array([5, 3, 5, 3, 9], np.int32)
    vals = _rand(5, DIM, seed=1)
    uniq, summed = dedup_indexed_slices(ids, vals)
    uniq, summed = np.asarray(uniq), np.asarray(summed)
    assert uniq.shape == (5,)
    for want in (3, 5, 9):
        (k,) = np.where(uniq == want)[0]
        np.testing.assert_allclose(
            summed[k], vals[ids == want].sum(0), rtol=1e-5, atol=1e-6
        )
    # padding slots zeroed
    pad = uniq == -1
    assert pad.sum() == 2
    np.testing.assert_array_equal(summed[pad], 0)


def test_dedup_then_sparse_sgd_matches_dense_scatter():
    table = _rand(VOCAB, DIM, seed=1)
    ids = np.array([2, 2, 7, 2], np.int32)
    grads = _rand(4, DIM, seed=2)
    uniq, summed = dedup_indexed_slices(ids, grads)
    out = np.asarray(
        sparse_sgd_update(jnp.array(table), uniq, summed, lr=0.1)
    )
    exp = table.copy()
    np.add.at(exp, ids, -0.1 * grads)
    np.testing.assert_allclose(out, exp, rtol=1e-4, atol=1e-6)


# ------------------------------------------------- fallbacks & guards


def test_jnp_fallback_paths(monkeypatch):
    monkeypatch.setenv("ELASTICDL_TPU_DISABLE_PALLAS", "1")
    table = _rand(VOCAB, DIM, seed=1)
    ids = np.array([2, -1, 9], np.int32)
    grads = _rand(3, DIM, seed=2)
    out = np.asarray(sparse_sgd_update(jnp.array(table), ids, grads, lr=0.5))
    exp = table.copy()
    exp[2] -= 0.5 * grads[0]
    exp[9] -= 0.5 * grads[2]
    np.testing.assert_allclose(out, exp, rtol=1e-5, atol=1e-6)
    g = np.asarray(embedding_gather(jnp.array(table), np.array([1, 5])))
    np.testing.assert_allclose(g, table[[1, 5]], rtol=1e-6)
    p = _rand(10, seed=3)
    np.testing.assert_allclose(
        np.asarray(sgd_update(p, p, lr=1.0)), 0, atol=1e-6
    )


def test_oob_ids_are_safe():
    table = _rand(VOCAB, DIM, seed=1)
    # gather: OOB clamps into range (never reads foreign memory)
    out = np.asarray(
        embedding_gather(jnp.array(table), np.array([VOCAB + 5], np.int32))
    )
    np.testing.assert_allclose(out[0], table[VOCAB - 1], rtol=1e-5)
    # update: OOB rows are skipped like padding
    grads = _rand(2, DIM, seed=2)
    new_t = np.asarray(sparse_sgd_update(
        jnp.array(table), np.array([3, VOCAB + 5], np.int32), grads, lr=0.5
    ))
    exp = table.copy()
    exp[3] -= 0.5 * grads[0]
    np.testing.assert_allclose(new_t, exp, rtol=1e-5, atol=1e-6)


def test_adam_traced_step():
    import jax

    p, m, v, g = (_rand(8, DIM, seed=i) for i in range(4))

    @jax.jit
    def step_fn(step):
        return adam_update(p, m, v, g, step=step, lr=0.01)

    out1 = np.asarray(step_fn(jnp.asarray(1, jnp.int32))[0])
    ref1 = np.asarray(adam_update(p, m, v, g, step=1, lr=0.01)[0])
    np.testing.assert_allclose(out1, ref1, rtol=1e-5, atol=1e-6)


def test_dedup_rejects_truncation():
    ids = np.array([1, 2, 3, 4], np.int32)
    vals = _rand(4, DIM, seed=0)
    with pytest.raises(ValueError, match="distinct ids"):
        dedup_indexed_slices(ids, vals, num_unique=2)
