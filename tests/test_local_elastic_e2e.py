"""End-to-end local elasticity: a real master gRPC server + subprocess
workers launched by LocalInstanceManager, with fault injection — the
TPU-native analogue of the reference's PS-restart fault test
(worker_ps_interaction_test.py:350-402) and the minikube job drills
(scripts/travis/run_job.sh), run without a cluster."""

import os
import time

import pytest

from elasticdl_tpu.common.model_utils import load_model_spec_from_module
from elasticdl_tpu.data import recordio_gen
from elasticdl_tpu.master.instance_manager import LocalInstanceManager
from elasticdl_tpu.master.master import Master


def _spec():
    from model_zoo.mnist_functional_api import mnist_functional_api as zoo

    return load_model_spec_from_module(zoo)


def _worker_args(train_dir):
    return [
        "--model_zoo", os.path.join(os.path.dirname(__file__), "..",
                                    "model_zoo"),
        "--model_def",
        "mnist_functional_api.mnist_functional_api.custom_model",
        "--training_data", train_dir,
        "--minibatch_size", "16",
        "--records_per_task", "24",
        "--job_type", "training_only",
    ]


def _env():
    env = dict(os.environ)
    # subprocess workers run on CPU; keep jax quiet and single-device
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = (
        os.path.join(os.path.dirname(__file__), "..")
        + os.pathsep
        + env.get("PYTHONPATH", "")
    )
    return env


@pytest.mark.integration
def test_subprocess_workers_complete_job(tmp_path):
    train_dir = str(tmp_path / "train")
    recordio_gen.gen_mnist_like(train_dir, num_files=2,
                                records_per_file=48)
    master = Master(
        _spec(),
        training_data=train_dir,
        minibatch_size=16,
        records_per_task=24,
        num_epochs=1,
    )
    master.prepare()
    manager = LocalInstanceManager(
        master.task_d,
        num_workers=2,
        worker_args=_worker_args(train_dir)
        + ["--master_addr", "localhost:%d" % master.port],
        env=_env(),
    )
    master.instance_manager = manager
    manager.start_workers()
    try:
        deadline = time.time() + 300
        while not master.task_d.finished() and time.time() < deadline:
            time.sleep(0.5)
        assert master.task_d.finished(), "job did not finish"
    finally:
        master.stop()


@pytest.mark.integration
def test_worker_killed_mid_job_is_relaunched_and_job_completes(tmp_path):
    train_dir = str(tmp_path / "train")
    recordio_gen.gen_mnist_like(train_dir, num_files=4,
                                records_per_file=48)
    master = Master(
        _spec(),
        training_data=train_dir,
        minibatch_size=16,
        records_per_task=24,
        num_epochs=2,
    )
    master.prepare()
    manager = LocalInstanceManager(
        master.task_d,
        num_workers=1,
        worker_args=_worker_args(train_dir)
        + ["--master_addr", "localhost:%d" % master.port],
        env=_env(),
    )
    master.instance_manager = manager
    manager.start_workers()
    try:
        # wait for the worker to start doing real work, then kill it
        deadline = time.time() + 120
        while not master.task_d.doing_tasks() and time.time() < deadline:
            time.sleep(0.2)
        assert master.task_d.doing_tasks(), "worker never took a task"
        manager.remove_worker(0)  # SIGKILL -> exit -9 -> preemption path

        deadline = time.time() + 300
        while not master.task_d.finished() and time.time() < deadline:
            if manager.all_workers_failed():
                pytest.fail("all workers failed instead of relaunching")
            time.sleep(0.5)
        assert master.task_d.finished(), "job did not finish after kill"
        # the kill triggered a relaunch with a new worker id
        assert manager.worker_phase(0) in ("Failed", "Deleted")
        assert manager.worker_phase(1) is not None
    finally:
        master.stop()
