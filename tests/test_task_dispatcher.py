"""Dispatcher lifecycle tests, modeled on the reference's
elasticdl/python/tests/task_dispatcher_test.py coverage."""

from elasticdl_tpu.master.task_dispatcher import (
    TaskDispatcher,
    TaskType,
)


def make_dispatcher(train=None, evaluation=None, prediction=None,
                    records_per_task=10, num_epochs=1):
    return TaskDispatcher(
        train or {}, evaluation or {}, prediction or {},
        records_per_task, num_epochs,
    )


def test_create_tasks_partitions_ranges():
    d = make_dispatcher(train={"f1": (0, 95), "f2": (10, 20)})
    # 95/10 -> 10 tasks; 20/10 starting at 10 -> 2 tasks
    got = []
    while True:
        tid, task = d.get("w0")
        if task is None:
            break
        got.append(task)
    f1 = sorted((t.start, t.end) for t in got if t.shard_name == "f1")
    assert f1 == [(i * 10, min(i * 10 + 10, 95)) for i in range(10)]
    f2 = sorted((t.start, t.end) for t in got if t.shard_name == "f2")
    assert f2 == [(10, 20), (20, 30)]


def test_epoch_rollover():
    d = make_dispatcher(train={"f": (0, 10)}, records_per_task=5,
                        num_epochs=3)
    seen = 0
    while True:
        tid, task = d.get("w0")
        if task is None:
            break
        seen += 1
        d.report(tid, True)
    assert seen == 2 * 3
    assert d.finished()


def test_failed_task_requeued_max_3_times():
    d = make_dispatcher(train={"f": (0, 5)}, records_per_task=5)
    fails = 0
    while True:
        tid, task = d.get("w0")
        if task is None:
            break
        fails += 1
        d.report(tid, False)
    # reference counter semantics (task_dispatcher.py:350-359): the counter
    # starts at 1 and increments per failure, task dropped when it exceeds
    # MAX_TASK_RETRIES=3 -> exactly 3 total attempts
    assert fails == 3
    assert d.finished()


def test_recover_tasks_requeues_doing():
    d = make_dispatcher(train={"f": (0, 30)}, records_per_task=10)
    t1, _ = d.get("w0")
    t2, _ = d.get("w1")
    assert len(d.doing_tasks()) == 2
    d.recover_tasks("w0")
    assert len(d.doing_tasks()) == 1
    # the recovered task is back in todo: drain everything
    remaining = 0
    while True:
        tid, task = d.get("w2")
        if task is None:
            break
        remaining += 1
        d.report(tid, True)
    assert remaining == 2  # one never-started + one recovered
    d.report(t2, True)
    assert d.finished()


def test_eval_tasks_separate_queue():
    d = make_dispatcher(evaluation={"e": (0, 20)}, records_per_task=10)
    tid, task = d.get("w0")
    assert task is None  # no training tasks
    tid, task = d.get_eval_task("w0")
    assert task.type == TaskType.EVALUATION
    d.report(tid, True)
    tid2, _ = d.get_eval_task("w0")
    d.report(tid2, True)
    assert d.finished()


def test_train_end_callback_task_deferred():
    d = make_dispatcher(train={"f": (0, 10)}, records_per_task=10)
    d.add_deferred_callback_create_train_end_task()
    tid, task = d.get("w0")
    d.report(tid, True)
    assert d.finished()
    assert d.invoke_deferred_callback()
    tid, task = d.get("w0")
    assert task.type == TaskType.TRAIN_END_CALLBACK
    d.report(tid, True)
    assert d.finished()
    assert not d.invoke_deferred_callback()


def test_stop_training_clears_todo():
    d = make_dispatcher(train={"f": (0, 100)}, records_per_task=10,
                        num_epochs=5)
    tid, task = d.get("w0")
    d.stop_training = True
    d.report(tid, True)
    tid, task = d.get("w0")
    assert task is None
    assert d.finished()


def test_prediction_tasks():
    d = make_dispatcher(prediction={"p": (0, 25)}, records_per_task=10)
    types = set()
    while True:
        tid, task = d.get("w0")
        if task is None:
            break
        types.add(task.type)
        d.report(tid, True)
    assert types == {TaskType.PREDICTION}
    assert d.finished()


def test_retry_count_evicted_on_success():
    """A task that failed (but not fatally) and then succeeds must drop
    its retry-count entry — otherwise later same-range failures (e.g.
    the next epoch) inherit stale strikes toward the poison cap."""
    d = make_dispatcher(train={"f": (0, 5)}, records_per_task=5)
    tid, task = d.get("w0")
    d.report(tid, False)
    assert d._task_retry_count  # one strike recorded
    tid, task = d.get("w0")
    d.report(tid, True)
    assert not d._task_retry_count  # evicted on success
    assert d.finished()


def test_max_retries_cap_permanently_fails_poisoned_task():
    """A poisoned task is dropped after MAX_TASK_RETRIES total attempts,
    never redispatched, and its bookkeeping entry is cleaned up."""
    from elasticdl_tpu.common.constants import MAX_TASK_RETRIES

    d = make_dispatcher(train={"f": (0, 5)}, records_per_task=5)
    attempts = 0
    while True:
        tid, task = d.get("w0")
        if task is None:
            break
        attempts += 1
        d.report(tid, False)
    assert attempts == MAX_TASK_RETRIES
    assert d.finished()
    assert not d._task_retry_count  # no leak for the dead task
    # permanently failed: nothing left to dispatch
    tid, task = d.get("w0")
    assert task is None


def test_epoch_rollover_under_concurrent_requeue_preserves_coverage():
    """w0 holds an epoch-0 task across the rollover into epoch 1, then
    fails it; the requeued copy must land in the mixed todo queue and
    total successful completions must cover every range exactly
    num_epochs times."""
    from collections import Counter

    d = make_dispatcher(train={"f": (0, 40)}, records_per_task=10,
                        num_epochs=2)
    held_tid, held_task = d.get("w0")  # epoch-0 task, stays in flight
    completed = Counter()

    def drain(worker, limit):
        for _ in range(limit):
            tid, task = d.get(worker)
            if task is None:
                return
            d.report(tid, True)
            completed[(task.start, task.end)] += 1

    drain("w1", 3)  # rest of epoch 0
    # next get rolls into epoch 1 while held_task is still doing
    tid, task = d.get("w1")
    assert d.epoch == 1
    d.report(tid, True)
    completed[(task.start, task.end)] += 1
    # the held epoch-0 task fails AFTER the rollover: must requeue
    d.report(held_tid, False)
    drain("w1", 100)
    assert d.finished()
    expected = {(s, s + 10): 2 for s in range(0, 40, 10)}
    assert dict(completed) == expected
