"""The reference's published benchmark, reproduced by machinery: elastic
scheduling lets a second job start on leftover slots instead of waiting
for gang capacity (docs/benchmark/report_cn.md:70-91 — the only
performance numbers the reference ever published). The script runs real
masters + subprocess workers; this test asserts the STRUCTURAL
properties (which are load-independent), not wall-clock speedup (which
needs a quiet machine — scripts/bench_elasticity.py reports it)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_elastic_scheduling_beats_gang_on_wait_time():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "bench_elasticity.py"),
         "--records", "64", "--records2", "1280", "--job2-delay", "2",
         "--timeout", "350"],
        # outer timeout > 2 modes x inner 350s + overhead: the script's
        # own TimeoutError must fire first so its finally-cleanup runs
        # and its diagnostics (worker log tails) surface
        capture_output=True, text=True, timeout=880, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-3000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    gang, elastic = out["gang"], out["elastic"]
    # elastic job2 starts (nearly) immediately on the leftover slot;
    # gang job2 must wait for job1 to release its full worker count
    assert elastic["job2_wait_s"] <= 2.0, out
    assert gang["job2_wait_s"] > elastic["job2_wait_s"], out
    # both jobs complete under both policies (no lost work)
    for mode in (gang, elastic):
        assert mode["makespan_s"] > 0
    # job2 has 40 tasks (20x job1's work), so undispatched tasks remain
    # when job1's slots free: elastic must have scaled it up mid-job.
    # Assert the LAUNCH (the scheduler's structural decision): since
    # job2 started on 1 leftover slot (wait ~0 above), >= 2 launches
    # means a mid-job scale-up. Peak CONCURRENT workers also depends on
    # how fast the late worker process boots, which is load-dependent —
    # scripts/bench_elasticity.py reports it for quiet-machine runs.
    assert elastic["job2_workers_launched"] >= 2, out


@pytest.mark.slow
def test_mixed_deployment_training_survives_preemption():
    """report_cn.md:94-106: a low-priority elastic training job rides
    leftover capacity under an autoscaling service — it must get
    PREEMPTED on service scale-up (SIGKILL + task recovery), still
    complete, and keep the cluster busy.

    The whole scenario is wall-clock-scheduled (service scale-up
    timers racing worker task pulls), so under heavily parallel pytest
    runs the overlap can slip — same load-sensitive class as the
    two-process SPMD drill (tests/test_spmd_multiprocess.py). One full
    retry absorbs that; a real regression fails both attempts."""
    import warnings

    def attempt():
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts",
                                          "bench_elasticity.py"),
             "--mixed", "--records2", "1280", "--timeout", "350"],
            capture_output=True, text=True, timeout=880, cwd=REPO,
        )
        assert r.returncode == 0, r.stderr[-3000:]
        out = json.loads(r.stdout.strip().splitlines()[-1])
        assert out["training_completed"], out
        assert out["preemptions"] >= 1, out
        assert out["utilization"] > 0.85, out

    try:
        attempt()
    except (AssertionError, subprocess.TimeoutExpired, ValueError,
            IndexError) as e:
        # TimeoutExpired: the drill outlasted its subprocess bound
        # under load; ValueError/IndexError: a killed/garbled child
        # produced unparseable stdout — all the same infra class
        warnings.warn(
            "mixed-deployment drill retried after load-sensitive "
            "failure: %s" % (str(e)[:500],)
        )
        attempt()
